#!/usr/bin/env python
"""Parse training logs — or telemetry JSON dumps — into a summary table.
reference: tools/parse_log.py — extracts train/val accuracy and epoch time
from the logging output of fit()/Speedometer (`Epoch[3] Batch [100] Speed:
... accuracy=0.9`, `Epoch[3] Validation-accuracy=0.91`, `Epoch[3] Time
cost=12.3`).

Telemetry mode (--telemetry, or auto-detected when the file is a JSON
object): flattens a `mx.telemetry.dump()` snapshot — or a
`mx.profiler.dump()` file embedding one under its "telemetry" key — into
the same markdown/csv table shape the log mode produces."""
from __future__ import annotations

import argparse
import json
import re
import sys


def parse(lines, metric="accuracy"):
    train_re = re.compile(
        r"Epoch\[(\d+)\].*?Train-" + metric + r"=([\d.eE+-]+)")
    batch_re = re.compile(
        r"Epoch\[(\d+)\].*?" + metric + r"=([\d.eE+-]+)")
    val_re = re.compile(
        r"Epoch\[(\d+)\].*?Validation-" + metric + r"=([\d.eE+-]+)")
    time_re = re.compile(r"Epoch\[(\d+)\].*?Time cost=([\d.eE+-]+)")
    rows = {}

    def row(e):
        return rows.setdefault(int(e), {"train": None, "val": None,
                                        "time": None})

    for line in lines:
        m = val_re.search(line)
        if m:
            row(m.group(1))["val"] = float(m.group(2))
            continue
        m = time_re.search(line)
        if m:
            row(m.group(1))["time"] = float(m.group(2))
            continue
        m = train_re.search(line) or batch_re.search(line)
        if m:
            row(m.group(1))["train"] = float(m.group(2))  # last batch wins
    return rows


def parse_telemetry(obj):
    """Flatten a telemetry snapshot into [(metric, kind, count, value, max)]
    rows. Accepts either a raw `telemetry.dump()` object or a
    `profiler.dump()` object with the snapshot under "telemetry"."""
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    rows = []
    for name, value in sorted(obj.get("counters", {}).items()):
        rows.append((name, "counter", "", value, ""))
    for name, g in sorted(obj.get("gauges", {}).items()):
        rows.append((name, "gauge", "", g.get("value"), g.get("max")))
    for name, h in sorted(obj.get("histograms", {}).items()):
        avg = h.get("avg")
        rows.append((name, "histogram", h.get("count"),
                     round(avg, 3) if avg is not None else "",
                     h.get("max")))
    return rows


def _print_telemetry(rows, fmt):
    if fmt == "markdown":
        print("| metric | kind | count | value | max |")
        print("| --- | --- | --- | --- | --- |")
        line = "| %s | %s | %s | %s | %s |"
    else:
        print("metric,kind,count,value,max")
        line = "%s,%s,%s,%s,%s"
    for r in rows:
        print(line % r)


# the headline resilience events, in narrative order; per-site counters
# (resilience.retries.kvstore.push, ...) list after their total. The v2
# events tell the elastic/commit/preempt story: shrink and grow-back,
# commit elections (+ rank_ahead = mid-commit-crash recoveries), and
# proactive (notice-triggered) checkpoints.
_RESILIENCE_EVENTS = ("faults_injected", "retries", "retry_exhausted",
                      "stalls", "restores", "checkpoints",
                      "proactive_checkpoints", "mesh_shrinks", "mesh_grows",
                      "commit.elections", "commit.rank_ahead",
                      "preempt.notices", "rollbacks", "skipped_batches")

# integrity-plane counters living OUTSIDE the resilience.* namespace — the
# divergence sentinel (integrity.*) and checksum-verified restores
# (checkpoint.corrupt*) are part of the same recovery narrative, so the
# --resilience table lists them explicitly rather than losing them to the
# unknown-prefix scan.
_INTEGRITY_PREFIXES = ("integrity.", "checkpoint.corrupt", "comm.checksum.")


def parse_resilience(obj):
    """Extract the resilience story from a telemetry snapshot: one row per
    `resilience.*` counter — was the run clean, noisy-but-recovered, or
    restart-heavy? Returns [(event, site, count)]."""
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    counters = obj.get("counters", {})
    rows = []
    for event in _RESILIENCE_EVENTS:
        total_key = "resilience.%s" % event
        if total_key in counters:
            rows.append((event, "total", counters[total_key]))
        prefix = total_key + "."
        for name in sorted(counters):
            if name.startswith(prefix):
                rows.append((event, name[len(prefix):], counters[name]))
    # the commit-elected step rides a gauge (it is a frontier, not a count)
    elected = obj.get("gauges", {}).get("resilience.commit.elected_step")
    if elected is not None:
        rows.append(("commit.elected_step", "latest", elected.get("value")))
    # unknown resilience.* counters (future events) still surface
    known = {"resilience.%s" % e for e in _RESILIENCE_EVENTS}
    for name in sorted(counters):
        if name.startswith("resilience.") and name not in known and \
                not any(name.startswith("resilience.%s." % e)
                        for e in _RESILIENCE_EVENTS):
            rows.append((name[len("resilience."):], "total", counters[name]))
    # the integrity plane: sentinel trips (integrity.divergences.<site>),
    # AMP overflow skips, corrupt-checkpoint fallbacks, wire checksums
    for name in sorted(counters):
        if any(name.startswith(p) for p in _INTEGRITY_PREFIXES):
            rows.append((name, "total", counters[name]))
    return rows


def _print_resilience(rows, fmt):
    if not rows:
        # nothing on stdout: a header with zero rows reads as data to
        # downstream CSV consumers
        print("no resilience.* counters in this dump (clean run or "
              "telemetry disabled)", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| event | site | count |")
        print("| --- | --- | --- |")
        line = "| %s | %s | %s |"
    else:
        print("event,site,count")
        line = "%s,%s,%s"
    for r in rows:
        print(line % r)


def parse_comm(obj):
    """Extract the gradient-comm story from a telemetry snapshot: bucket
    counters (`comm.bucket.*`), launched collectives (`comm.collectives`),
    kvstore payload counters, and derived ratios — was the sync bucketed
    (few big launches) or per-param (many small ones)?
    Returns [(metric, value)] rows."""
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    counters = obj.get("counters", {})
    rows = []
    ordered = ("comm.collectives", "comm.reduce_scatter", "comm.all_gather",
               "comm.bucket.count", "comm.bucket.bytes",
               "comm.bucket.skipped", "comm.ready.rounds",
               "comm.ready.flush_during_backward",
               "comm.ready.first_flush_before_backward_end",
               "comm.ready.aborted", "comm.zero.pipelined",
               "comm.autotune.sweeps", "kvstore.push_calls",
               "kvstore.push_bytes", "kvstore.pull_calls",
               "kvstore.pull_bytes")
    for name in ordered:
        if name in counters:
            rows.append((name, counters[name]))
    for name in sorted(counters):
        if name.startswith("comm.bucket.flush_reason."):
            rows.append((name, counters[name]))
    # ZeRO weight-update sharding: sharded-state footprint + fused-update
    # latency ride the same table (the --comm story is the whole sync)
    state_gauge = obj.get("gauges", {}).get("opt.state_bytes_per_rank")
    if isinstance(state_gauge, dict) and state_gauge.get("value"):
        rows.append(("opt.state_bytes_per_rank", state_gauge["value"]))
    fused = obj.get("histograms", {}).get("opt.fused_update_ms")
    if isinstance(fused, dict) and fused.get("count"):
        rows.append(("opt.fused_updates", fused["count"]))
        rows.append(("opt.fused_update_ms_avg",
                     round(fused.get("sum", 0.0) / fused["count"], 3)))
    # the chosen comm schedule (autotuner winner or checkpoint-restored):
    # bucket cap + flush policy as one human row (ISSUE 19)
    gauges = obj.get("gauges", {})
    cap_g = gauges.get("comm.schedule.bucket_mb")
    if isinstance(cap_g, dict) and cap_g.get("value") is not None:
        ready_g = gauges.get("comm.schedule.ready", {})
        policy = "ready" if (isinstance(ready_g, dict)
                             and ready_g.get("value")) else "registration"
        rows.append(("comm.schedule",
                     "%gMB/%s" % (cap_g["value"], policy)))
    sweep_g = gauges.get("comm.autotune.sweep_steps")
    if isinstance(sweep_g, dict) and sweep_g.get("value") is not None:
        rows.append(("comm.autotune.sweep_steps", int(sweep_g["value"])))
    buckets = counters.get("comm.bucket.count", 0)
    if buckets:
        rows.append(("avg_bucket_kb",
                     round(counters.get("comm.bucket.bytes", 0)
                           / buckets / 1024.0, 1)))
    pushes = counters.get("kvstore.push_calls", 0)
    if pushes:
        rows.append(("collectives_per_push",
                     round(counters.get("comm.collectives", 0)
                           / float(pushes), 2)))
    return rows


def _print_comm(rows, fmt):
    if not rows:
        print("no comm.*/kvstore.* counters in this dump (no gradient "
              "sync ran, or telemetry disabled)", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| metric | value |")
        print("| --- | --- |")
        line = "| %s | %s |"
    else:
        print("metric,value")
        line = "%s,%s"
    for r in rows:
        print(line % r)


def parse_flight(obj):
    """Flatten a flight-recorder dump (`telemetry.flight.dump()` JSON, or a
    dict with a "records" list) into per-step rows:
    [(seq, site, step_ms, anomalies, compiles, events, notes)]."""
    records = obj.get("records", [])
    rows = []
    for r in records:
        deltas = r.get("deltas", {})
        notes = []
        for key, label in (("comm.collectives", "coll"),
                           ("comm.bucket.bytes", "comm_B"),
                           ("resilience.restores", "restores"),
                           ("resilience.retries", "retries")):
            if key in deltas:
                notes.append("%s=%s" % (label, deltas[key]))
        if r.get("retrace_reasons"):
            notes.append("retrace: " + "; ".join(r["retrace_reasons"]))
        rows.append((r.get("seq", ""), r.get("site", "?"),
                     r.get("step_ms", ""),
                     ",".join(r.get("anomalies", [])),
                     ",".join(r.get("compiles", [])),
                     "; ".join(r.get("events", [])),
                     " ".join(notes)))
    return rows


def _print_flight(rows, fmt):
    if not rows:
        print("no flight-recorder records in this dump", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| step | site | step_ms | anomalies | compiles | events |"
              " notes |")
        print("| --- | --- | --- | --- | --- | --- | --- |")
        line = "| %s | %s | %s | %s | %s | %s | %s |"
    else:
        print("step,site,step_ms,anomalies,compiles,events,notes")
        line = "%s,%s,%s,%s,%s,%s,%s"
    for r in rows:
        if fmt == "csv":
            r = tuple(str(c).replace(",", ";") for c in r)
        print(line % r)


def parse_anomalies(obj):
    """Extract the anomaly story from a telemetry snapshot: every
    `telemetry.anomaly.*` counter plus the step-time histograms the spikes
    were judged against. Returns [(metric, kind, value)]."""
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    rows = []
    for name, v in sorted(obj.get("counters", {}).items()):
        if name.startswith("telemetry.anomaly."):
            rows.append((name[len("telemetry.anomaly."):], "count", v))
    for name, h in sorted(obj.get("histograms", {}).items()):
        if name.endswith(".step_ms"):
            avg = h.get("avg")
            rows.append((name, "avg_ms",
                         round(avg, 3) if avg is not None else ""))
            rows.append((name, "max_ms", h.get("max")))
    return rows


def _print_anomalies(rows, fmt):
    if not rows:
        print("no telemetry.anomaly.* counters in this dump (clean run, "
              "no steps, or telemetry disabled)", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| metric | kind | value |")
        print("| --- | --- | --- |")
        line = "| %s | %s | %s |"
    else:
        print("metric,kind,value")
        line = "%s,%s,%s"
    for r in rows:
        print(line % r)


def _hist_quantile(h, q):
    """Quantile estimate from a snapshot histogram's sparse PER-BUCKET
    counts (non-cumulative — `Histogram.snapshot()` format, not the
    cumulative `le` series of a Prometheus scrape). Stdlib re-derivation
    of telemetry.export.histogram_quantiles — this tool must run without
    mxnet_tpu importable."""
    count = h.get("count") or 0
    if not count:
        return None
    buckets = h.get("buckets", {})
    bounds = h.get("bounds")
    if bounds:
        # densify: an empty (omitted) bucket's bound can be the true
        # lower edge of the rank-holding bucket
        items = [(float(b), buckets.get("le_%g" % b, 0)) for b in bounds]
        items.append((float("inf"), buckets.get("le_inf", 0)))
    else:  # legacy dump without bounds
        items = []
        for key, n in buckets.items():
            raw = key[len("le_"):]
            items.append((float("inf") if raw == "inf" else float(raw), n))
        items.sort()
    target = q * count
    cum = 0
    lower = 0.0
    val = None
    for bound, n in items:
        if cum + n >= target:
            val = (h.get("max") if bound == float("inf")
                   else lower + (bound - lower) * (target - cum) / n)
            break
        cum += n
        if bound != float("inf"):
            lower = bound
    if val is None:
        val = h.get("max")
    if val is None:
        return None
    if h.get("min") is not None:
        val = max(val, h["min"])
    if h.get("max") is not None:
        val = min(val, h["max"])
    return round(val, 3)


# the serving headline, in client-experience order: traffic in, prompt
# work (chunked prefill + prefix reuse), decode (incl. speculation),
# latency felt, pressure and shedding, recovery churn
_SERVE_COUNTERS = ("requests", "admitted", "completed", "tokens",
                   "prefills", "prefill_chunks", "prefill_chunk_tokens",
                   "prefix", "decode_steps", "spec", "shed", "failed",
                   "recoveries", "requeued_streams", "compile", "retrace")


def parse_serve(obj):
    """Extract the serving story from a telemetry snapshot: serve.*
    counters (chunked-prefill, prefix-sharing, and speculative-decoding
    columns included), derived prefix_hit_rate / spec_accept_rate,
    TTFT/TPOT quantiles from the latency histograms, and the pressure
    gauges (queue depth, batch occupancy, KV-pool blocks).
    Returns [(metric, value)] rows."""
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    counters = obj.get("counters", {})
    gauges = obj.get("gauges", {})
    hists = obj.get("histograms", {})
    rows = []
    tps = gauges.get("serve.tokens_per_s")
    if tps is not None:
        rows.append(("tokens_per_s", tps.get("value")))
    for name in _SERVE_COUNTERS:
        key = "serve.%s" % name
        if key in counters:
            rows.append((name, counters[key]))
        prefix = key + "."
        for sub in sorted(counters):
            if sub.startswith(prefix):
                rows.append((sub[len("serve."):], counters[sub]))
    lookups = counters.get("serve.prefix.lookups", 0)
    if lookups:
        rows.append(("prefix_hit_rate",
                     round(counters.get("serve.prefix.hits", 0)
                           / lookups, 4)))
    drafted = counters.get("serve.spec.drafted", 0)
    if drafted:
        rows.append(("spec_accept_rate",
                     round(counters.get("serve.spec.accepted", 0)
                           / drafted, 4)))
    for hname, label in (("serve.ttft_ms", "ttft_ms"),
                         ("serve.tpot_ms", "tpot_ms"),
                         ("serve.step_ms", "step_ms"),
                         ("serve.prefill_ms", "prefill_ms")):
        h = hists.get(hname)
        if h:
            rows.append((label + "_p50", _hist_quantile(h, 0.50)))
            rows.append((label + "_p99", _hist_quantile(h, 0.99)))
    for gname, label in (("serve.queue_depth", "queue_depth"),
                         ("serve.batch_occupancy", "batch_occupancy"),
                         ("serve.kv.blocks_in_use", "kv_blocks_in_use"),
                         ("serve.prefix.blocks", "prefix_cache_blocks"),
                         ("serve.replicas_alive", "replicas_alive")):
        g = gauges.get(gname)
        if g is not None:
            rows.append((label, g.get("value")))
            rows.append((label + "_peak", g.get("max")))
    return rows


def _print_serve(rows, fmt):
    if not rows:
        print("no serve.* metrics in this dump (no serving ran, or "
              "telemetry disabled)", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| metric | value |")
        print("| --- | --- |")
        line = "| %s | %s |"
    else:
        print("metric,value")
        line = "%s,%s"
    for r in rows:
        print(line % r)


# the sparse-embedding headline, in data-path order: training pushes
# (dedup ratio), the sparse wire (unique-rows comm vs the densified
# equivalent), the scatter-add kernel, and the served lookup path
_SPARSE_COUNTERS = ("embedding.push", "embedding.push.rows",
                    "embedding.push.unique_rows", "embedding.lookup",
                    "embedding.lookup.rows", "embedding.serve.lookup",
                    "embedding.serve.rows", "comm.sparse.push",
                    "comm.sparse.rows", "comm.sparse.unique_rows",
                    "comm.sparse.sync", "comm.sparse.bytes",
                    "comm.sparse.bytes_dense_equiv",
                    "comm.sparse.all_gather_rows",
                    "comm.sparse.psum_unique_rows",
                    "comm.sparse.bucket.count", "comm.sparse.bucket.bytes",
                    "comm.sparse.bucket.skipped")


def parse_sparse(obj):
    """Extract the sparse-embedding story (ISSUE 17) from a telemetry
    snapshot: embedding.* / comm.sparse.* counters, the derived
    unique-rows ratio (what fraction of pushed rows survived dedup),
    modeled wire savings vs the densified-allreduce equivalent,
    segment-sum kernel dispatch/fallback counts, served-lookup latency
    quantiles, and the table's HBM-ledger bytes.
    Returns [(metric, value)] rows."""
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    counters = obj.get("counters", {})
    rows = []
    for name in _SPARSE_COUNTERS:
        if name in counters:
            rows.append((name, counters[name]))
    for name in sorted(counters):
        if name.startswith("comm.sparse.bucket.flush_reason."):
            rows.append((name, counters[name]))
    pushed = counters.get("comm.sparse.rows",
                          counters.get("embedding.push.rows", 0))
    unique = counters.get("comm.sparse.unique_rows",
                          counters.get("embedding.push.unique_rows", 0))
    if pushed:
        rows.append(("unique_rows_ratio", round(unique / pushed, 4)))
    dense_eq = counters.get("comm.sparse.bytes_dense_equiv", 0)
    sparse_b = counters.get("comm.sparse.bytes", 0)
    if dense_eq:
        rows.append(("comm_bytes_saved", dense_eq - sparse_b))
    disp = counters.get("ops.pallas.dispatch.segment_sum", 0)
    fall = sum(v for k, v in counters.items()
               if k.startswith("ops.pallas.fallback.segment_sum."))
    if disp or fall:
        rows.append(("segment_sum_dispatch", disp))
        rows.append(("segment_sum_fallback", fall))
    h = obj.get("histograms", {}).get("embedding.serve.lookup_ms")
    if isinstance(h, dict) and h.get("count"):
        rows.append(("serve_lookup_ms_p50", _hist_quantile(h, 0.50)))
        rows.append(("serve_lookup_ms_p99", _hist_quantile(h, 0.99)))
    g = obj.get("gauges", {}).get("memory.scope.embedding.bytes")
    if isinstance(g, dict) and g.get("value") is not None:
        rows.append(("table_bytes", g["value"]))
    return rows


def _print_sparse(rows, fmt):
    if not rows:
        print("no embedding.*/comm.sparse.* counters in this dump (no "
              "sparse embedding ran, or telemetry disabled)",
              file=sys.stderr)
        return
    if fmt == "markdown":
        print("| metric | value |")
        print("| --- | --- |")
        line = "| %s | %s |"
    else:
        print("metric,value")
        line = "%s,%s"
    for r in rows:
        print(line % r)


def parse_kernels(obj):
    """Extract the Pallas kernel-layer story (ISSUE 10): which stages ran
    fused (`ops.pallas.dispatch.<kernel>`), which calls fell back and WHY
    (`ops.pallas.fallback.<reason>` / `.<kernel>.<reason>`), how many
    kernels each compiled step program carries (`*.pallas_kernels`
    gauges), and the fused-update latency histogram. Also accepts a
    `BENCH=fused_bwd` / `BENCH=fused_opt` row (a dict with
    bytes_fused/bytes_composed) and derives the traffic ratio.
    Returns [(kind, name, value)] rows."""
    rows = []
    if "bytes_fused" in obj or "bytes_composed" in obj:
        bf, bc = obj.get("bytes_fused"), obj.get("bytes_composed")
        rows.append(("bench", obj.get("metric", "?"), obj.get("value")))
        rows.append(("bench", "vs_baseline", obj.get("vs_baseline")))
        if bf is not None:
            rows.append(("bench", "bytes_fused", bf))
        if bc is not None:
            rows.append(("bench", "bytes_composed", bc))
        if bf and bc:
            rows.append(("bench", "bytes_ratio", round(bf / bc, 4)))
        return rows
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    counters = obj.get("counters", {})
    for total in ("ops.pallas.dispatch", "ops.pallas.fallback"):
        kind = total.rsplit(".", 1)[-1]
        if total in counters:
            rows.append((kind, "total", counters[total]))
        prefix = total + "."
        for name in sorted(counters):
            if name.startswith(prefix):
                rows.append((kind, name[len(prefix):], counters[name]))
    for gname in ("fused_step.pallas_kernels", "train_step.pallas_kernels"):
        g = obj.get("gauges", {}).get(gname)
        if isinstance(g, dict) and g.get("value") is not None:
            rows.append(("program", gname, g["value"]))
    fused = obj.get("histograms", {}).get("opt.fused_update_ms")
    if isinstance(fused, dict) and fused.get("count"):
        rows.append(("latency", "fused_updates", fused["count"]))
        rows.append(("latency", "fused_update_ms_avg",
                     round(fused.get("sum", 0.0) / fused["count"], 3)))
        rows.append(("latency", "fused_update_ms_max", fused.get("max")))
    return rows


def _print_kernels(rows, fmt):
    if not rows:
        print("no ops.pallas.* counters in this dump (no Pallas dispatch "
              "ran, or telemetry disabled)", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| kind | name | value |")
        print("| --- | --- | --- |")
        line = "| %s | %s | %s |"
    else:
        print("kind,name,value")
        line = "%s,%s,%s"
    for r in rows:
        print(line % r)


def parse_compile(obj):
    """Extract the whole-graph-compiler / AOT-cache story (ISSUE 11):
    how many graphs lowered and compiled, what the graph passes removed,
    cache hits/misses/writes/corruption, which executors fell back to
    op-by-op dispatch and WHY, plus per-site compile counters and the
    lower/compile latency histograms. Accepts a telemetry JSON dump, a
    `telemetry.compile_report()` dict (adds the recent-compiles ring
    rows), or a `BENCH=startup` row. Returns [(kind, name, value)]."""
    rows = []
    if "startup_cold_s" in obj or obj.get("metric") == "startup_warm_s":
        for k in ("metric", "value", "startup_cold_s", "startup_warm_s",
                  "compile_count_cold", "compile_count_warm",
                  "cache_hits_warm", "vs_baseline"):
            if k in obj:
                rows.append(("bench", k, obj[k]))
        return rows
    ring = obj.get("recent_compiles")
    if "telemetry" in obj and isinstance(obj["telemetry"], dict):
        obj = obj["telemetry"]
    counters = obj.get("counters", {})
    for name in ("compiler.lower", "compiler.compile",
                 "compiler.program_runs"):
        if name in counters:
            rows.append(("compiler", name.split(".", 1)[1], counters[name]))
    for name in sorted(counters):
        if name.startswith("compiler.pass."):
            rows.append(("pass", name[len("compiler.pass."):],
                         counters[name]))
    for name in ("hits", "misses", "writes", "corrupt", "evictions",
                 "serialize_error", "write_error", "unusable",
                 "skipped_donated"):
        full = "compiler.cache." + name
        if full in counters:
            rows.append(("cache", name, counters[full]))
    if "compiler.fallback" in counters:
        rows.append(("fallback", "total", counters["compiler.fallback"]))
    for name in sorted(counters):
        if name.startswith("compiler.fallback."):
            rows.append(("fallback", name[len("compiler.fallback."):],
                         counters[name]))
    for site in ("cachedop.compile", "fused_step.compile",
                 "train_step.compile", "serve.compile", "cachedop.retrace",
                 "fused_step.retrace", "train_step.retrace", "serve.retrace",
                 "train_step.aot_restored", "fused_step.aot_restored"):
        if site in counters:
            rows.append(("site", site, counters[site]))
    for hname in ("compiler.lower_ms", "compiler.compile_ms",
                  "compiler.cache.load_ms", "compiler.cache.store_ms"):
        h = obj.get("histograms", {}).get(hname)
        if isinstance(h, dict) and h.get("count"):
            rows.append(("latency", hname + "_avg",
                         round(h.get("sum", 0.0) / h["count"], 3)))
            rows.append(("latency", hname + "_max", h.get("max")))
    if ring:
        for name, ts in ring:
            rows.append(("ring", name, ts))
    return rows


def _print_compile(rows, fmt):
    if not rows:
        print("no compiler.* counters in this dump (whole-graph compiler "
              "never ran, or telemetry disabled)", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| kind | name | value |")
        print("| --- | --- | --- |")
        line = "| %s | %s | %s |"
    else:
        print("kind,name,value")
        line = "%s,%s,%s"
    for r in rows:
        print(line % r)


def parse_requests(obj):
    """Flatten a per-request trace dump — the `/requests` endpoint body
    ({"requests": [...]}) or a bare `telemetry.request_traces()` list —
    into [(request, outcome, wall_ms, queue_ms, prefill_ms, decode_ms,
    recovery_ms, ttft_ms, tokens, requeues, acct_pct)] rows."""
    if isinstance(obj, dict):
        reqs = obj.get("requests", [])
    else:
        reqs = obj or []
    rows = []
    for r in reqs:
        phases = r.get("phases_ms", {})
        wall = r.get("wall_ms") or 0.0
        acct = r.get("accounted_ms")
        acct_pct = (round(100.0 * acct / wall, 1)
                    if acct is not None and wall else "")
        rows.append((r.get("request_id", "?"), r.get("outcome", "?"),
                     wall, phases.get("queue", 0.0),
                     phases.get("prefill", 0.0), phases.get("decode", 0.0),
                     phases.get("recovery", 0.0),
                     r.get("ttft_ms") if r.get("ttft_ms") is not None
                     else "",
                     r.get("tokens", ""), r.get("requeues", 0), acct_pct))
    return rows


def _print_requests(rows, fmt):
    if not rows:
        print("no request traces in this dump (nothing served, or "
              "telemetry disabled)", file=sys.stderr)
        return
    header = ("request", "outcome", "wall_ms", "queue_ms", "prefill_ms",
              "decode_ms", "recovery_ms", "ttft_ms", "tokens", "requeues",
              "acct_pct")
    if fmt == "markdown":
        print("| " + " | ".join(header) + " |")
        print("|" + " --- |" * len(header))
        line = "| " + " | ".join(["%s"] * len(header)) + " |"
    else:
        print(",".join(header))
        line = ",".join(["%s"] * len(header))
    for r in rows:
        print(line % r)


# span categories for the --overlap decomposition (stdlib re-derivation of
# mxnet_tpu.telemetry.attribution — this tool must run without mxnet_tpu
# importable; keep the category sets in sync)
_OVL_COMM = ("comm",)
_OVL_HOST = ("host", "resilience", "fault", "user")
_OVL_IDLE = ("idle",)


def _ovl_union(iv):
    if not iv:
        return 0.0, []
    iv = sorted(iv)
    merged = [list(iv[0])]
    for s, e in iv[1:]:
        if s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return sum(e - s for s, e in merged), [(s, e) for s, e in merged]


def _ovl_subtract(iv, cover):
    out = []
    for s, e in iv:
        cur = s
        for cs, ce in cover:
            if ce <= cur:
                continue
            if cs >= e:
                break
            if cs > cur:
                out.append((cur, cs))
            cur = max(cur, ce)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


def _trace_events(obj):
    """Span events as (name, cat, ts_s, dur_s) from either a chrome trace
    dump (`telemetry.dump_trace()`: traceEvents, µs) or a raw
    `local_trace_dump()` object (events, s)."""
    if "traceEvents" in obj:
        out = []
        for e in obj["traceEvents"]:
            if e.get("ph") != "X":
                continue
            out.append((e.get("name", "?"), e.get("cat", ""),
                        e.get("ts", 0.0) / 1e6, e.get("dur", 0.0) / 1e6))
        return out
    return [(n, c, ts, dur)
            for n, c, ts, dur, *_ in obj.get("events", [])]


def parse_overlap(obj, site=None):
    """Per-step compute/collective/host/idle decomposition + comm overlap
    fraction from a trace dump: one row per cat-``step`` span, plus a
    TOTAL row. Returns [(step, site, step_ms, compute_ms, collective_ms,
    host_ms, idle_ms, comm_n, overlap_frac)]."""
    events = _trace_events(obj)
    steps = [(n, ts, dur) for n, c, ts, dur in events
             if c == "step" and (site is None or n == site)]
    rows = []
    totals = {"step": 0.0, "compute": 0.0, "coll": 0.0, "host": 0.0,
              "idle": 0.0, "n_comm": 0}
    phase_total = overlap_weighted = 0.0
    for i, (name, t0, dur) in enumerate(steps):
        t1 = t0 + dur

        def clip(cats):
            out = []
            for _n, c, ts, d in events:
                if c not in cats:
                    continue
                s, e = max(ts, t0), min(ts + d, t1)
                if e > s:
                    out.append((s, e))
            return out

        comm_iv = clip(_OVL_COMM)
        coll, comm_cover = _ovl_union(comm_iv)
        host, host_cover = _ovl_union(
            _ovl_subtract(clip(_OVL_HOST), comm_cover))
        idle, _ = _ovl_union(_ovl_subtract(
            _ovl_subtract(clip(_OVL_IDLE), comm_cover), host_cover))
        compute = max(0.0, (t1 - t0) - coll - host - idle)
        ovl = ""
        if comm_iv:
            phase0 = min(s for s, _e in comm_iv)
            phase = t1 - phase0
            in_phase, _ = _ovl_union([(max(s, phase0), e)
                                      for s, e in comm_iv])
            if phase > 0:
                ovl = round(max(0.0, phase - in_phase) / phase, 4)
                phase_total += phase
                overlap_weighted += ovl * phase
        rows.append((i, name, round(dur * 1e3, 3),
                     round(compute * 1e3, 3), round(coll * 1e3, 3),
                     round(host * 1e3, 3), round(idle * 1e3, 3),
                     len(comm_iv), ovl))
        totals["step"] += dur
        totals["compute"] += compute
        totals["coll"] += coll
        totals["host"] += host
        totals["idle"] += idle
        totals["n_comm"] += len(comm_iv)
    if rows:
        rows.append(("TOTAL", site or "*", round(totals["step"] * 1e3, 3),
                     round(totals["compute"] * 1e3, 3),
                     round(totals["coll"] * 1e3, 3),
                     round(totals["host"] * 1e3, 3),
                     round(totals["idle"] * 1e3, 3), totals["n_comm"],
                     round(overlap_weighted / phase_total, 4)
                     if phase_total else ""))
    return rows


def _print_overlap(rows, fmt):
    if not rows:
        print("no step spans in this trace dump (record steps — trainer/"
              "fused_step/serve.step — or pass a merged dump)",
              file=sys.stderr)
        return
    header = ("step", "site", "step_ms", "compute_ms", "collective_ms",
              "host_ms", "idle_ms", "comm_n", "overlap_frac")
    if fmt == "markdown":
        print("| " + " | ".join(header) + " |")
        print("|" + " --- |" * len(header))
        line = "| " + " | ".join(["%s"] * len(header)) + " |"
    else:
        print(",".join(header))
        line = ",".join(["%s"] * len(header))
    for r in rows:
        print(line % r)


# severity ordering for the lint table: errors first, then by location
_LINT_SEV_ORDER = {"error": 0, "warning": 1, "info": 2}

# rule id -> short name for the rollup (static mirror of
# `mxnet_tpu.analysis.rule_table()` — this tool parses dumps offline and
# must not import the package)
_LINT_RULE_NAMES = {
    "TPU001": "host-sync-under-trace",
    "TPU002": "side-effect-under-trace",
    "TPU003": "data-dependent-control-flow",
    "TPU004": "retrace-hazard",
    "TPU005": "host-rng-under-trace",
    "TPU006": "thread-shared-state",
    "TPU007": "sharding-annotation",
    "TPU008": "collective-safety",
    "TPU009": "lock-order-inversion",
    "TPU010": "blocking-under-lock",
}


def parse_lint(obj):
    """Flatten tracelint JSON (`python -m mxnet_tpu.analysis --format
    json`) into [(severity, code, location, symbol, message)] rows,
    errors first."""
    findings = obj.get("findings", [])
    keyed = []
    for f in findings:
        fname = f.get("file", "?")
        try:
            line = int(f.get("line", 0))
        except (TypeError, ValueError):
            line = 0
        row = (f.get("severity", "?"), f.get("code", "?"),
               "%s:%d" % (fname, line), f.get("symbol", ""),
               f.get("message", ""))
        keyed.append(((_LINT_SEV_ORDER.get(row[0], 3), fname, line,
                       row[1]), row))
    keyed.sort(key=lambda kr: kr[0])
    return [row for _, row in keyed]


def _print_lint(rows, fmt):
    if not rows:
        print("no tracelint findings in this dump (clean tree)",
              file=sys.stderr)
        return
    if fmt == "markdown":
        print("| severity | code | location | symbol | message |")
        print("| --- | --- | --- | --- | --- |")
        line = "| %s | %s | %s | %s | %s |"
    else:
        print("severity,code,location,symbol,message")
        line = "%s,%s,%s,%s,%s"
    for r in rows:
        sev, code, loc, sym, msg = r
        if fmt == "csv":
            msg = msg.replace(",", ";")
        print(line % (sev, code, loc, sym, msg))
    if fmt != "markdown":
        return  # csv consumers want ONE table; the rollup is human-facing
    # per-rule rollup: which rule dominates the findings?
    by_rule = {}
    for sev, code, _loc, _sym, _msg in rows:
        key = (code, sev)
        by_rule[key] = by_rule.get(key, 0) + 1
    print()
    print("| rule | name | severity | count |")
    print("| --- | --- | --- | --- |")
    for code, sev in sorted(by_rule):
        print("| %s | %s | %s | %d |"
              % (code, _LINT_RULE_NAMES.get(code, "?"), sev,
                 by_rule[(code, sev)]))


_OVERLAY_SCOPES = ("prefix_cache",)   # bytes shared with another scope


def _mem_fmt_bytes(n):
    n = float(n)
    sign = "-" if n < 0 else ""
    n = abs(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return ("%s%.1f%s" % (sign, n, unit) if unit != "B"
                    else "%s%d%s" % (sign, int(n), unit))
        n /= 1024.0


def parse_mem(obj):
    """Extract the HBM-ledger story from a `/snapshot` payload (its
    ``memory`` block: per-scope bytes, per-program static footprints, the
    last reconcile) or from a bare telemetry snapshot (the
    ``memory.scope.<name>.bytes`` gauges). Returns
    ``(scope_rows, program_rows, reconcile_dict_or_None)`` where
    scope_rows = [(scope, bytes, note)] largest first and program_rows =
    [(label, origin, bytes, temp, code, args, out)]."""
    mem = obj.get("memory") if isinstance(obj.get("memory"), dict) else None
    scopes, programs, reconcile = {}, [], None
    if mem:
        scopes = {k: v for k, v in (mem.get("scopes") or {}).items()
                  if isinstance(v, (int, float))}
        programs = [p for p in (mem.get("programs") or [])
                    if isinstance(p, dict)]
        reconcile = mem.get("reconcile") or None
    else:
        tel = obj.get("telemetry") if isinstance(obj.get("telemetry"),
                                                 dict) else obj
        gauges = tel.get("gauges", {}) if isinstance(tel, dict) else {}
        for name, g in gauges.items():
            if (name.startswith("memory.scope.")
                    and name.endswith(".bytes")):
                scope = name[len("memory.scope."):-len(".bytes")]
                val = g.get("value") if isinstance(g, dict) else g
                if isinstance(val, (int, float)):
                    scopes[scope] = val
    scope_rows = []
    for name, val in sorted(scopes.items(), key=lambda kv: -abs(kv[1])):
        note = ""
        if name in _OVERLAY_SCOPES:
            note = "overlay (inside kv_pool)"
        elif name == "unattributed":
            note = "reconcile residual"
        scope_rows.append((name, int(val), note))
    program_rows = []
    for p in programs:
        program_rows.append((p.get("label", "?"),
                             "cache" if p.get("cached") else "compile",
                             int(p.get("bytes", 0)),
                             int(p.get("temp_bytes", 0)),
                             int(p.get("code_bytes", 0)),
                             int(p.get("argument_bytes", 0)),
                             int(p.get("output_bytes", 0))))
    program_rows.sort(key=lambda r: -r[2])
    return scope_rows, program_rows, reconcile


def _print_mem(parsed, fmt):
    scope_rows, program_rows, reconcile = parsed
    if not scope_rows and not program_rows:
        print("no memory-ledger data in this dump (ledger disabled, or "
              "not a /snapshot payload)", file=sys.stderr)
        return
    if fmt == "markdown":
        print("| scope | bytes | size | note |")
        print("| --- | --- | --- | --- |")
        line = "| %s | %d | %s | %s |"
    else:
        print("scope,bytes,size,note")
        line = "%s,%d,%s,%s"
    for name, val, note in scope_rows:
        print(line % (name, val, _mem_fmt_bytes(val), note))
    if reconcile and fmt == "markdown":
        print()
        print("reconcile: device=%s scoped=%s residual=%s (source: %s, "
              "%s device(s))"
              % (_mem_fmt_bytes(reconcile.get("device_bytes", 0)),
                 _mem_fmt_bytes(reconcile.get("scoped_bytes", 0)),
                 _mem_fmt_bytes(reconcile.get("residual_bytes", 0)),
                 reconcile.get("source", "?"),
                 reconcile.get("device_count", "?")))
    if not program_rows:
        return
    if fmt == "markdown":
        print()
        print("| program | origin | bytes | temp | code | args | out |")
        print("| --- | --- | --- | --- | --- | --- | --- |")
        pline = "| %s | %s | %s | %s | %s | %s | %s |"
    else:
        print("program,origin,bytes,temp,code,args,out")
        pline = "%s,%s,%s,%s,%s,%s,%s"
    for label, origin, total, temp, code, argb, outb in program_rows:
        print(pline % (label, origin, _mem_fmt_bytes(total),
                       _mem_fmt_bytes(temp), _mem_fmt_bytes(code),
                       _mem_fmt_bytes(argb), _mem_fmt_bytes(outb)))


def _load_json(path):
    try:
        with open(path) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else None
    except (ValueError, OSError):
        return None


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("logfile")
    parser.add_argument("--format", choices=["markdown", "csv"],
                        default="markdown")
    parser.add_argument("--metric", default="accuracy")
    parser.add_argument("--telemetry", action="store_true",
                        help="treat the input as a telemetry/profiler JSON "
                             "dump (auto-detected for JSON files)")
    parser.add_argument("--resilience", action="store_true",
                        help="resilience-events mode: table of retries/"
                             "stalls/restores/faults plus the integrity "
                             "plane (rollbacks, skipped batches, sentinel "
                             "divergences, corrupt-checkpoint fallbacks) "
                             "from a telemetry JSON dump — distinguishes a "
                             "noisy-but-recovered run from a clean one")
    parser.add_argument("--lint", action="store_true",
                        help="tracelint mode: table of findings from "
                             "`python -m mxnet_tpu.analysis --format json` "
                             "output, errors first")
    parser.add_argument("--comm", action="store_true",
                        help="gradient-comm mode: table of bucket/collective"
                             " counters from a telemetry JSON dump — was the"
                             " sync bucketed (few big launches) or per-param"
                             " (many small ones)?")
    parser.add_argument("--flight", action="store_true",
                        help="flight-recorder mode: per-step table from a "
                             "telemetry.flight.dump() JSON file — the last "
                             "N steps before a crash")
    parser.add_argument("--serve", action="store_true",
                        help="serving mode: tokens/s, ttft/tpot quantiles, "
                             "queue/batch/KV pressure, shed and recovery "
                             "counts from a telemetry JSON dump")
    parser.add_argument("--sparse", action="store_true",
                        help="sparse-embedding mode: embedding.*/"
                             "comm.sparse.* counters, unique-rows ratio, "
                             "modeled wire savings vs densified allreduce, "
                             "segment-sum dispatch/fallback counts, and "
                             "served-lookup latency quantiles from a "
                             "telemetry JSON dump")
    parser.add_argument("--kernels", action="store_true",
                        help="Pallas kernel-layer mode: dispatch/fallback "
                             "counts by kernel/reason, per-program fused-"
                             "kernel gauges, fused-update latency, and "
                             "bytes ratios from BENCH=fused_* rows")
    parser.add_argument("--compile", dest="compile_mode",
                        action="store_true",
                        help="compiler mode: whole-graph lower/compile "
                             "counters, graph-pass stats, AOT-cache "
                             "hits/misses/corruption, op-by-op fallbacks "
                             "by reason, and the recent-compiles ring "
                             "from a telemetry JSON dump / "
                             "telemetry.compile_report() / BENCH=startup "
                             "row")
    parser.add_argument("--requests", dest="requests_mode",
                        action="store_true",
                        help="per-request trace mode: one row per served "
                             "request (ttft/queue-wait/prefill/decode/"
                             "recovery, outcome, requeues) from a "
                             "/requests endpoint dump or a "
                             "telemetry.request_traces() JSON list")
    parser.add_argument("--overlap", action="store_true",
                        help="comm-overlap attribution mode: per-step "
                             "compute/collective/host/idle decomposition "
                             "and the comm overlap fraction from a chrome "
                             "trace dump (telemetry.dump_trace output) — "
                             "the schedule autotuner's evidence table")
    parser.add_argument("--site", default=None,
                        help="with --overlap: only decompose step spans "
                             "with this name (e.g. serve.step)")
    parser.add_argument("--mem", action="store_true",
                        help="memory-ledger mode: per-scope HBM bytes, "
                             "per-program static footprints (compile vs "
                             "AOT-cache restore), and the device/scoped "
                             "reconcile from a /snapshot payload or a "
                             "telemetry JSON dump's memory.scope.* gauges")
    parser.add_argument("--anomalies", action="store_true",
                        help="anomaly mode: telemetry.anomaly.* counters + "
                             "step-time histograms from a telemetry JSON "
                             "dump — did any step blow its rolling median "
                             "or SLO?")
    args = parser.parse_args()
    obj = _load_json(args.logfile)
    if args.requests_mode:
        # a bare telemetry.request_traces() list is a valid input here,
        # which _load_json (dict-only) rejects — load it directly
        raw = None
        try:
            with open(args.logfile) as f:
                raw = json.load(f)
        except (ValueError, OSError):
            pass
        if not isinstance(raw, (dict, list)):
            sys.exit("--requests input is not JSON: %s" % args.logfile)
        _print_requests(parse_requests(raw), args.format)
        return
    if args.overlap:
        if obj is None:
            sys.exit("--overlap input is not a JSON object: %s"
                     % args.logfile)
        _print_overlap(parse_overlap(obj, site=args.site), args.format)
        return
    if args.compile_mode:
        if obj is None:
            sys.exit("--compile input is not a JSON object: %s"
                     % args.logfile)
        _print_compile(parse_compile(obj), args.format)
        return
    if args.serve:
        if obj is None:
            sys.exit("--serve input is not a JSON object: %s" % args.logfile)
        _print_serve(parse_serve(obj), args.format)
        return
    if args.mem:
        if obj is None:
            sys.exit("--mem input is not a JSON object: %s" % args.logfile)
        _print_mem(parse_mem(obj), args.format)
        return
    if args.flight:
        if obj is None:
            sys.exit("--flight input is not a JSON object: %s"
                     % args.logfile)
        _print_flight(parse_flight(obj), args.format)
        return
    if args.anomalies:
        if obj is None:
            sys.exit("--anomalies input is not a JSON object: %s"
                     % args.logfile)
        _print_anomalies(parse_anomalies(obj), args.format)
        return
    if args.kernels:
        if obj is None:
            sys.exit("--kernels input is not a JSON object: %s"
                     % args.logfile)
        _print_kernels(parse_kernels(obj), args.format)
        return
    if args.sparse:
        if obj is None:
            sys.exit("--sparse input is not a JSON object: %s"
                     % args.logfile)
        _print_sparse(parse_sparse(obj), args.format)
        return
    if args.comm:
        if obj is None:
            sys.exit("--comm input is not a JSON object: %s" % args.logfile)
        _print_comm(parse_comm(obj), args.format)
        return
    if args.lint:
        if obj is None:
            sys.exit("--lint input is not a JSON object: %s" % args.logfile)
        _print_lint(parse_lint(obj), args.format)
        return
    if args.resilience:
        if obj is None:
            sys.exit("--resilience input is not a JSON object: %s"
                     % args.logfile)
        _print_resilience(parse_resilience(obj), args.format)
        return
    if args.telemetry or obj is not None:
        if obj is None:
            sys.exit("--telemetry input is not a JSON object: %s"
                     % args.logfile)
        _print_telemetry(parse_telemetry(obj), args.format)
        return
    with open(args.logfile) as f:
        rows = parse(f, args.metric)
    if args.format == "markdown":
        print("| epoch | train-%s | val-%s | time(s) |" % (args.metric,
                                                           args.metric))
        print("| --- | --- | --- | --- |")
        fmt = "| %d | %s | %s | %s |"
    else:
        print("epoch,train-%s,val-%s,time" % (args.metric, args.metric))
        fmt = "%d,%s,%s,%s"
    for e in sorted(rows):
        r = rows[e]
        print(fmt % (e, r["train"], r["val"], r["time"]))


if __name__ == "__main__":
    main()
