#!/usr/bin/env python
"""Cluster launcher. reference: tools/launch.py +
3rdparty/dmlc-core/tracker/dmlc_tracker/{local.py,ssh.py}.

Spawns N worker processes with the DMLC_* rendezvous env protocol the
reference uses; under the TPU build the coordinator is JAX's multi-controller
service instead of a ps-lite scheduler, so there are no server/scheduler
processes — `-s` is accepted and ignored with a note (SPMD has no servers).

Launchers:
  local  — all workers as subprocesses of this host (the reference's
           `--launcher local`, used by tests/nightly dist tests).
  ssh    — one worker per host from --hostfile via ssh (reference ssh.py).
  tpu    — emit the per-host env and command for TPU pods (one process per
           host; the operator's pod runner executes it on each host).

Usage:
  python tools/launch.py -n 4 --launcher local python train.py --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def build_env(rank, args):
    env = dict(os.environ)
    env.update({
        "DMLC_ROLE": "worker",
        "DMLC_PS_ROOT_URI": args.root_uri,
        "DMLC_PS_ROOT_PORT": str(args.root_port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_WORKER_ID": str(rank),
    })
    return env


def launch_local(args, command):
    import time
    procs = []
    try:
        for rank in range(args.num_workers):
            procs.append(subprocess.Popen(command,
                                          env=build_env(rank, args)))
        # poll the whole group: first nonzero exit kills the rest — a dead
        # worker leaves peers blocked in a collective forever (reference:
        # dmlc_tracker local.py behavior)
        while True:
            codes = [p.poll() for p in procs]
            if any(c not in (None, 0) for c in codes):
                return next(c for c in codes if c not in (None, 0))
            if all(c == 0 for c in codes):
                return 0
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


def launch_ssh(args, command):
    if not args.hostfile:
        raise SystemExit("--launcher ssh requires --hostfile")
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < args.num_workers:
        raise SystemExit("hostfile has %d hosts; need %d"
                         % (len(hosts), args.num_workers))
    procs = []
    try:
        for rank in range(args.num_workers):
            env = build_env(rank, args)
            exports = " ".join("export %s=%s;" % (k, v)
                               for k, v in env.items()
                               if k.startswith("DMLC_"))
            remote = "%s cd %s; %s" % (exports, os.getcwd(),
                                       " ".join(command))
            procs.append(subprocess.Popen(["ssh", "-o",
                                           "StrictHostKeyChecking=no",
                                           hosts[rank], remote]))
        code = 0
        for p in procs:
            code = p.wait() or code
        return code
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


def launch_tpu(args, command):
    """Print the per-host launch spec (TPU pod runners execute a single
    command on every host; rendezvous envs differ only in worker id)."""
    for rank in range(args.num_workers):
        env = {k: v for k, v in build_env(rank, args).items()
               if k.startswith("DMLC_")}
        exports = " ".join("%s=%s" % (k, v) for k, v in sorted(env.items()))
        print("host%d: %s %s" % (rank, exports, " ".join(command)))
    return 0


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for reference CLI parity; SPMD has "
                             "no server processes")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh", "tpu"])
    parser.add_argument("--hostfile", "-H", default=None)
    parser.add_argument("--root-uri", default="127.0.0.1")
    parser.add_argument("--root-port", type=int, default=9091)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")
    if args.num_servers:
        print("note: -s ignored — SPMD collectives replace parameter "
              "servers (see SURVEY.md §5.8)", file=sys.stderr)
    fn = {"local": launch_local, "ssh": launch_ssh, "tpu": launch_tpu}
    sys.exit(fn[args.launcher](args, args.command))


if __name__ == "__main__":
    main()
