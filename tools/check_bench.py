"""Continuous perf-regression gate over the bench history.

`tools/run_tracelint.sh --ci` keeps the *code* from regressing;
`python tools/check_bench.py --ci` keeps the *numbers* from regressing.
Together they are the CI gate:

    tools/run_tracelint.sh --ci && python tools/check_bench.py --ci

For every (metric, fingerprint) series in ``bench_history.jsonl`` the gate
compares the NEWEST row against a rolling baseline — the median of up to
``--window`` (default 5) immediately-preceding rows with the *same*
fingerprint. Rows from a different environment (other backend, other
device count, a run that fell back to CPU) are never compared against
each other: those comparisons are skipped and counted, not failed —
a laptop checkout must not fail CI because the committed history came
from an accelerator fleet.

Direction is inferred from the metric name (`*_per_s`/`*_tok_s`/
`*img_per_sec` → higher is better; `*_ms`/`*_us`/`*_s` → lower is
better; unknown units are checked both ways against a symmetric band).
Tolerance defaults to 10% and can be tuned per metric prefix with
``--tolerance metric_prefix=0.25`` (repeatable). Series with fewer than
``--min-rows`` (default 2) rows have no baseline yet: skipped+counted.

Exit codes: 0 = no regressions (skips allowed), 1 = at least one
regression, 2 = usage / unreadable history with --ci.
Stdlib-only, like every tools/ script — CI runs it from a bare checkout.
"""
import argparse
import json
import statistics
import sys

try:
    import benchdb
except ImportError:  # invoked as tools/check_bench.py from the repo root
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import benchdb

__all__ = ["direction_for", "check", "main"]

# metric-name suffix → which way "good" points. Checked in order; first hit
# wins. Throughput names in this repo end in per_sec/per_s/tok_s/img_s;
# latency names end in _ms/_us/_ns/_s.
_HIGHER_BETTER = ("per_sec", "per_s", "_tok_s", "_img_s", "_qps",
                  "throughput", "hits")
_LOWER_BETTER = ("_ms", "_us", "_ns", "_s", "latency", "overhead_pct",
                 "_bytes")

# names the suffix heuristics get WRONG or can't classify, pinned by
# longest-prefix match (BENCH=sparse, ISSUE 17): `comm_bytes_saved` is a
# savings (higher better, despite "bytes" in the name); the lookup
# latency percentiles end in _p50/_p99, not a latency suffix;
# `sparse_rows_pct` is a traffic property, not a perf axis — movement
# either way means the workload changed, so keep the symmetric band.
_DIRECTION_OVERRIDES = {
    "comm_bytes_saved": "up",
    "sparse_rows_pct": "both",
    "lookup_ms_p50": "down",
    "lookup_ms_p99": "down",
    # BENCH=comm readiness legs (ISSUE 19): overlap fraction is a share
    # (no throughput/latency suffix) and the collective_ms_* rows end in
    # the leg name, not _ms — pin both directions explicitly
    "overlap_frac": "up",
    "collective_ms": "down",
}

# built-in per-metric tolerance floors, longest-prefix match (CLI
# --tolerance still overrides). Sub-millisecond CPU comm timings swing
# far past the 10% default from scheduler jitter alone — an interleaved
# same-code A/B shows ±20-50% run-to-run — so gating them at 10% flags
# pure noise. The readiness A/B's load-bearing signal (overlap_frac,
# a ratio of spans from the SAME run) keeps the tight default.
_TOLERANCE_OVERRIDES = {
    "collective_ms_comm": 0.75,
    "comm_grad_sync_cpu": 0.30,
}


def direction_for(metric):
    """'up' (higher better), 'down' (lower better), or 'both' (unknown —
    regress on movement past the band in either direction)."""
    name = metric.lower()
    best, best_len = None, -1
    for prefix, d in _DIRECTION_OVERRIDES.items():
        if name.startswith(prefix) and len(prefix) > best_len:
            best, best_len = d, len(prefix)
    if best is not None:
        return best
    for suf in _HIGHER_BETTER:
        if name.endswith(suf) or suf in name.split(".")[-1]:
            return "up"
    for suf in _LOWER_BETTER:
        if name.endswith(suf):
            return "down"
    return "both"


def _tolerance_for(metric, tolerances, default):
    """Longest matching prefix wins: `--tolerance serve=0.2` covers every
    serve_* metric unless a longer prefix is also given. CLI overrides
    shadow the built-in `_TOLERANCE_OVERRIDES` at equal prefix length."""
    best, best_len = default, -1
    for prefix, tol in _TOLERANCE_OVERRIDES.items():
        if metric.startswith(prefix) and len(prefix) > best_len:
            best, best_len = tol, len(prefix)
    for prefix, tol in tolerances.items():
        if metric.startswith(prefix) and len(prefix) >= best_len:
            best, best_len = tol, len(prefix)
    return best


def check(rows, window=5, min_rows=2, default_tolerance=0.10,
          tolerances=None):
    """Evaluate the history. Returns a report dict:
    {ok, checked, regressions: [...], skipped: {reason: count}, series: N}.
    Never raises on malformed rows — rows without metric/value/fingerprint
    are counted under skipped."""
    tolerances = tolerances or {}
    series = {}
    skipped = {"no_fingerprint": 0, "no_value": 0, "insufficient_history": 0,
               "fingerprint_mismatch": 0}
    fingerprints_seen = set()
    for row in rows:
        metric = row.get("metric")
        value = row.get("value")
        fpid = row.get("fingerprint_id")
        if not metric or not isinstance(value, (int, float)):
            skipped["no_value"] += 1
            continue
        if not fpid:
            skipped["no_fingerprint"] += 1
            continue
        fingerprints_seen.add(fpid)
        series.setdefault((metric, fpid), []).append(float(value))
    # a metric measured under several fingerprints: the cross-environment
    # pairs we deliberately refuse to compare
    metrics_by_name = {}
    for metric, fpid in series:
        metrics_by_name.setdefault(metric, set()).add(fpid)
    skipped["fingerprint_mismatch"] = sum(
        len(fps) - 1 for fps in metrics_by_name.values() if len(fps) > 1)

    regressions, checked = [], []
    for (metric, fpid), values in sorted(series.items()):
        if len(values) < min_rows:
            skipped["insufficient_history"] += 1
            continue
        newest = values[-1]
        baseline = statistics.median(values[-(window + 1):-1])
        tol = _tolerance_for(metric, tolerances, default_tolerance)
        direction = direction_for(metric)
        if baseline == 0:
            delta = 0.0 if newest == 0 else float("inf")
        else:
            delta = (newest - baseline) / abs(baseline)
        if direction == "up":
            bad = delta < -tol
        elif direction == "down":
            bad = delta > tol
        else:
            bad = abs(delta) > tol
        entry = {"metric": metric, "fingerprint_id": fpid,
                 "newest": newest, "baseline": baseline,
                 "delta_pct": round(delta * 100.0, 2),
                 "tolerance_pct": round(tol * 100.0, 2),
                 "direction": direction, "n": len(values)}
        checked.append(entry)
        if bad:
            regressions.append(entry)
    return {"ok": not regressions, "series": len(series),
            "checked": checked, "regressions": regressions,
            "skipped": skipped,
            "fingerprints": len(fingerprints_seen)}


def _parse_tolerances(pairs):
    out = {}
    for pair in pairs or []:
        if "=" not in pair:
            raise ValueError("--tolerance wants metric_prefix=FRACTION, "
                             "got %r" % pair)
        prefix, _, frac = pair.partition("=")
        out[prefix] = float(frac)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="perf-regression gate over bench_history.jsonl")
    ap.add_argument("history", nargs="?", default=None,
                    help="history file (default: repo bench_history.jsonl "
                         "or $MXNET_TPU_BENCH_HISTORY)")
    ap.add_argument("--ci", action="store_true",
                    help="gate mode: exit 1 on any regression, 2 if the "
                         "history is unreadable/empty")
    ap.add_argument("--window", type=int, default=5,
                    help="rolling-baseline width (median of up to N prior "
                         "rows; default 5)")
    ap.add_argument("--min-rows", type=int, default=2,
                    help="rows a series needs before it is gated "
                         "(default 2)")
    ap.add_argument("--default-tolerance", type=float, default=0.10,
                    help="allowed regression fraction (default 0.10)")
    ap.add_argument("--tolerance", action="append", default=[],
                    metavar="PREFIX=FRAC",
                    help="per-metric-prefix tolerance override "
                         "(repeatable, longest prefix wins)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON object")
    args = ap.parse_args(argv)

    try:
        tolerances = _parse_tolerances(args.tolerance)
    except ValueError as e:
        print("check_bench: %s" % e, file=sys.stderr)
        return 2
    path = args.history or benchdb.history_path()
    rows = benchdb.load(path)
    if not rows:
        print("check_bench: no usable rows in %s" % path, file=sys.stderr)
        return 2 if args.ci else 0

    report = check(rows, window=args.window, min_rows=args.min_rows,
                   default_tolerance=args.default_tolerance,
                   tolerances=tolerances)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        skips = ", ".join("%s=%d" % kv
                          for kv in sorted(report["skipped"].items())
                          if kv[1])
        print("check_bench: %d series, %d gated, %d regression(s)%s"
              % (report["series"], len(report["checked"]),
                 len(report["regressions"]),
                 (" [skipped: %s]" % skips) if skips else ""))
        for entry in report["checked"]:
            flag = "REGRESSION" if entry in report["regressions"] else "ok"
            print("  %-10s %-40s fp=%s %+.2f%% (tol %.0f%%, %s, n=%d)"
                  % (flag, entry["metric"], entry["fingerprint_id"],
                     entry["delta_pct"], entry["tolerance_pct"],
                     entry["direction"], entry["n"]))
    if report["regressions"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
