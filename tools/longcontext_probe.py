"""Long-context measurement: Pallas flash attention vs XLA fallback.

BASELINE.md chip-queue item 6 / round-4 VERDICT weak #4: the long-context
story (O(S) HBM flash forward+backward) is claimed but unmeasured. This
probe runs a Llama causal-LM train step at seq >= 4096 twice — once with
the Pallas flash kernels (default on TPU) and once with the plain-XLA
rematerialized fallback (MXNET_FLASH_DISABLE=1) — same model, same data,
hard-sync protocol, and reports tok/s plus compiled-program cost_analysis
bytes for both arms.

Each arm runs in its own subprocess so the env gate is read fresh by
`flash_attention._use_pallas` and so an arm that OOMs (the S^2 fallback at
long seq can) doesn't take the other arm down.

Usage:
  python tools/longcontext_probe.py               # both arms, seq from env
  MXNET_LC_SEQ=8192 python tools/longcontext_probe.py
  python tools/longcontext_probe.py --arm flash   # (internal) one arm

Output: one JSON line per arm, e.g.
  {"arm": "flash", "seq": 4096, "tok_per_sec": N, "bytes_accessed": N}
and a final summary line {"metric": "longcontext_flash_speedup", ...}.

reference: the contrast is SURVEY §5.7 — upstream's
src/operator/contrib/transformer.cc keeps the full S^2 prob matrix in HBM.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_arm(arm, seq, on_accel):
    """One measurement arm in-process. Returns the result dict."""
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from mxnet_tpu.models.llama import CONFIGS, llama_loss, llama_init

    # Llama-110M geometry (768 x 12L x 12H) — big enough that attention is
    # a real fraction of the step, small enough that the S^2 fallback arm
    # still fits one v5e chip at seq 4k.
    cfg = CONFIGS["llama_110m" if on_accel else "llama_tiny"]
    batch = 1
    steps, warmup = (20, 5) if on_accel else (3, 1)
    lr = 1e-3

    params = llama_init(jax.random.PRNGKey(0), cfg)
    if on_accel:
        params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16) if p.dtype == jnp.float32
            else p, params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                                cfg.vocab_size)

    @jax.jit
    def step(params, tokens):
        loss, grads = jax.value_and_grad(llama_loss)(
            params, {"tokens": tokens}, cfg)
        params = jax.tree_util.tree_map(
            lambda p, g: (p - lr * g.astype(jnp.float32)).astype(p.dtype),
            params, grads)
        return params, loss

    bytes_accessed = None
    try:
        cost = step.lower(params, tokens).compile().cost_analysis()
        cost = cost[0] if isinstance(cost, list) else cost
        bytes_accessed = cost.get("bytes accessed")
    except Exception as e:                       # best-effort
        print("# %s cost_analysis unavailable: %s" % (arm, e),
              file=sys.stderr)

    # the hard-barrier sync (block_until_ready can ack early on the axon
    # tunnel) lives in bench.py with its rationale — reuse, don't fork
    from bench import _sync

    for _ in range(warmup):
        params, loss = step(params, tokens)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, loss = step(params, tokens)
    _sync(loss)
    dt = time.perf_counter() - t0
    return {
        "arm": arm,
        "seq": seq,
        "batch": batch,
        "tok_per_sec": round(batch * seq * steps / dt, 2),
        "bytes_accessed": bytes_accessed,
        "loss": float(loss),
        "platform": jax.default_backend(),
    }


def main():
    # CPU smoke runs: the axon sitecustomize re-registers the TPU backend
    # and resets jax_platforms after env vars are read, so the env var
    # alone hangs in make_c_api_client — force the config too
    # (tests/conftest.py recipe).
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--arm", choices=["flash", "fallback"])
    ap.add_argument("--seq", type=int,
                    default=int(os.environ.get("MXNET_LC_SEQ", "4096")))
    args = ap.parse_args()

    if args.arm:                                 # child: measure one arm
        import jax
        on_accel = jax.default_backend() not in ("cpu",)
        if not on_accel:
            args.seq = min(args.seq, 256)
            os.environ.setdefault("MXNET_FLASH_INTERPRET", "1")
        print(json.dumps(run_arm(args.arm, args.seq, on_accel)), flush=True)
        return

    results = {}
    for arm in ("flash", "fallback"):
        env = dict(os.environ)
        env["MXNET_FLASH_DISABLE"] = "1" if arm == "fallback" else "0"
        # own process group + killpg: a hung arm (tunnel drop mid-run, or
        # a tunnel-helper grandchild holding the pipe) must not take the
        # other arm or the summary down. The kill recipe lives in
        # chip_capture.run_killable — reuse, don't fork a third copy.
        import tempfile
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from chip_capture import run_killable
        with tempfile.NamedTemporaryFile("w+", suffix=".out") as out, \
                tempfile.NamedTemporaryFile("w+", suffix=".err") as err:
            rc, timed_out = run_killable(
                [sys.executable, os.path.abspath(__file__),
                 "--arm", arm, "--seq", str(args.seq)],
                {"MXNET_FLASH_DISABLE": env["MXNET_FLASH_DISABLE"]},
                1800, out.name, err.name)
            if timed_out:
                rc = None
            with open(out.name) as f:
                stdout = f.read()
            with open(err.name) as f:
                stderr = f.read()
        sys.stderr.write(stderr)
        line = None
        for ln in stdout.splitlines():
            if ln.startswith("{"):
                line = ln
        if rc != 0 or line is None:
            results[arm] = {"arm": arm,
                            "error": ("timeout" if rc is None
                                      else "rc=%s" % rc),
                            "stderr_tail": stderr[-500:]}
        else:
            results[arm] = json.loads(line)
        print(json.dumps(results[arm]), flush=True)

    f, b = results.get("flash", {}), results.get("fallback", {})
    if not ("tok_per_sec" in f and "tok_per_sec" in b):
        sys.exit(1)                 # chip_capture must mark this failed
    print(json.dumps({
        "metric": "longcontext_flash_speedup",
        "value": round(f["tok_per_sec"] / b["tok_per_sec"], 4),
        "unit": "x vs XLA fallback",
        "seq": f["seq"],
        "platform": f.get("platform"),
        "flash_tok_per_sec": f["tok_per_sec"],
        "fallback_tok_per_sec": b["tok_per_sec"],
        "flash_bytes": f.get("bytes_accessed"),
        "fallback_bytes": b.get("bytes_accessed"),
    }), flush=True)


if __name__ == "__main__":
    main()
