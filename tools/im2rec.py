#!/usr/bin/env python
"""Pack an image dataset into RecordIO. reference: tools/im2rec.py — same
two-phase CLI: `--list` walks an image root and writes a .lst
(index\tlabel\tpath per line), then the default mode packs the listed
images into .rec/.idx shards readable by ImageRecordIter /
ImageRecordDataset.

No OpenCV in this environment: PIL is used when available for decode/resize
and JPEG re-encode; otherwise images are stored as raw .npy payloads
(readable by mxnet_tpu.image.imdecode). Files already in JPEG/PNG form can
be passed through unrecoded with --pass-through, which needs no codec at
all.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive):
    cat = {}
    items = []
    i = 0
    if recursive:
        for path, _, files in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(files):
                if fname.lower().endswith(EXTS):
                    label_dir = os.path.relpath(path, root).split(os.sep)[0]
                    if label_dir not in cat:
                        cat[label_dir] = len(cat)
                    items.append((i, os.path.relpath(
                        os.path.join(path, fname), root), cat[label_dir]))
                    i += 1
        for k in sorted(cat):
            print("%s %d" % (k, cat[k]))
    else:
        for fname in sorted(os.listdir(root)):
            if fname.lower().endswith(EXTS):
                items.append((i, fname, 0))
                i += 1
    return items


def write_list(path_out, items):
    with open(path_out, "w") as fout:
        for idx, rel, label in items:
            fout.write("%d\t%f\t%s\n" % (idx, label, rel))


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            # reference format: idx \t label(s)... \t relpath
            yield (int(float(parts[0])),
                   [float(x) for x in parts[1:-1]], parts[-1])


def _encode_image(path, args):
    if args.pass_through:
        with open(path, "rb") as f:
            return f.read()
    try:
        from PIL import Image
        import io
        img = Image.open(path).convert("RGB")
        if args.resize:
            w, h = img.size
            scale = args.resize / min(w, h)
            img = img.resize((max(1, int(w * scale)),
                              max(1, int(h * scale))))
        buf = io.BytesIO()
        img.save(buf, format="JPEG", quality=args.quality)
        return buf.getvalue()
    except ImportError:
        import io
        import numpy as np
        with open(path, "rb") as f:
            raw = f.read()
        # no codec: store raw bytes if already jpg/png, else fail clearly
        if path.lower().endswith(EXTS):
            return raw
        raise SystemExit("no PIL available and %s is not a supported "
                         "pass-through format" % path)


def make_record(args, lst_path):
    prefix = os.path.splitext(lst_path)[0]
    record = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                        "w")
    count = 0
    for idx, labels, rel in read_list(lst_path):
        fullpath = os.path.join(args.root, rel)
        try:
            payload = _encode_image(fullpath, args)
        except (OSError, SystemExit) as e:
            print("imread error, skipping %s: %s" % (rel, e),
                  file=sys.stderr)
            continue
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        record.write_idx(idx, recordio.pack(header, payload))
        count += 1
        if count % 1000 == 0:
            print("processed %d images" % count)
    record.close()
    print("wrote %d records to %s.rec" % (count, prefix))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("prefix", help=".lst path prefix (or output prefix "
                                       "with --list)")
    parser.add_argument("root", help="image root directory")
    parser.add_argument("--list", action="store_true",
                        help="create a .lst instead of packing")
    parser.add_argument("--recursive", action="store_true",
                        help="walk subdirectories; dir names become labels")
    parser.add_argument("--shuffle", type=int, default=1)
    parser.add_argument("--resize", type=int, default=0,
                        help="resize shorter edge")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--pass-through", action="store_true",
                        help="pack original file bytes, no re-encode")
    args = parser.parse_args()

    if args.list:
        items = list_images(args.root, args.recursive)
        if args.shuffle:
            random.seed(100)
            random.shuffle(items)
            items = [(i, rel, lab) for i, (_, rel, lab) in enumerate(items)]
        write_list(args.prefix + ".lst", items)
        print("wrote %d entries to %s.lst" % (len(items), args.prefix))
        return

    lst = args.prefix if args.prefix.endswith(".lst") else \
        args.prefix + ".lst"
    if not os.path.isfile(lst):
        raise SystemExit("list file %s not found; run with --list first"
                         % lst)
    make_record(args, lst)


if __name__ == "__main__":
    main()
