#!/usr/bin/env python
"""mxtop — live terminal dashboard for the mxnet_tpu telemetry plane.

Polls a running training/serving process through either live transport the
telemetry exporter provides and renders a top-style view: step latency
(rolling p50/p99 + steps/s), comm and kvstore throughput, compile/retrace
activity, device-memory watermarks, resilience events, and anomalies.

Sources (pick one):
  --port N [--host H]   poll http://H:N/snapshot (the endpoint started by
                        MXNET_TPU_METRICS_PORT; /metrics also works for
                        Prometheus, but mxtop wants the richer JSON)
  --url URL             full /snapshot URL
  --stream FILE         tail the JSONL file written by
                        MXNET_TPU_METRICS_STREAM (no network needed)

Options:
  --serve               serving view: tokens/s, queue depth, batch
                        occupancy, shed counts, chunked-prefill windows,
                        prefix-cache hit rate, speculative accept rate,
                        TTFT/TPOT p50/p99 — from a single replica's
                        /snapshot OR rank 0's /fleet/snapshot (one row
                        per rank + fleet totals)
  --mem                 memory view: HBM-ledger per-scope bytes (with
                        per-poll deltas), per-program static footprints
                        (compile vs AOT-cache restore), the reconcile
                        residual, and recent profile captures
  --interval S          refresh period (default 2 s)
  --once                render a single frame and exit (scripting / tests)

Keys (live HTTP mode): `p` + Enter triggers an on-device profile capture
via the endpoint's rate-limited /profile route; the result path (or the
rate-limit notice) shows in the next frame's footer.

Examples:
  MXNET_TPU_METRICS_PORT=9100 python train.py &
  python tools/mxtop.py --port 9100

  MXNET_TPU_METRICS_STREAM=/tmp/run.jsonl python train.py &
  python tools/mxtop.py --stream /tmp/run.jsonl
"""
from __future__ import annotations

import argparse
import json
import sys
import time

CLEAR = "\x1b[2J\x1b[H"
BOLD = "\x1b[1m"
DIM = "\x1b[2m"
RED = "\x1b[31m"
RESET = "\x1b[0m"


def fetch_url(url, timeout=3.0):
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def fetch_stream(path, block=1 << 16):
    """Last complete JSON line of the stream file (the newest snapshot).
    Reads only a tail block from EOF (doubling while no newline-delimited
    line fits) — a week-long stream is hundreds of MB and re-scanning it
    every poll would eventually take longer than the poll interval."""
    with open(path, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        while True:
            span = min(size, block)
            f.seek(size - span)
            chunk = f.read(span)
            parts = chunk.split(b"\n")
            if not chunk.endswith(b"\n"):
                parts = parts[:-1]    # streamer mid-append: partial tail
            if span < size:
                parts = parts[1:]     # seek landed mid-line: partial head
            lines = [l for l in parts if l.strip()]
            if lines:
                return json.loads(lines[-1].decode("utf-8"))
            if span == size:
                raise ValueError(
                    "stream file %s has no snapshot lines yet" % path)
            block *= 2


def _fmt_bytes(n):
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return "%.1f %s" % (n, unit)
        n /= 1024.0


def _fmt_num(n):
    if n is None:
        return "-"
    if isinstance(n, float):
        return "%.2f" % n
    return str(n)


def _rate(cur, prev, name, dt):
    if prev is None or dt <= 0:
        return None
    d = cur.get(name, 0) - prev.get(name, 0)
    return d / dt if d >= 0 else None


def render(payload, prev_payload=None, dt=None, source=""):
    """One dashboard frame as a string. `prev_payload` (the previous poll)
    turns monotonic counters into rates."""
    snap = payload.get("snapshot", {})
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    prev = (prev_payload or {}).get("snapshot", {}).get("counters") \
        if prev_payload else None
    quant = payload.get("step_quantiles", {}) or {}
    lines = []
    lines.append("%smxtop%s — rank %s  trace %s  %s  %s" % (
        BOLD, RESET, payload.get("rank", "?"),
        payload.get("trace_id", "?"),
        time.strftime("%H:%M:%S", time.localtime(payload.get("ts",
                                                             time.time()))),
        DIM + source + RESET))
    lines.append("")

    # --- step latency ---------------------------------------------------
    lines.append(BOLD + "step latency (rolling window)" + RESET)
    lines.append("  %-12s %10s %10s %10s %8s %9s"
                 % ("site", "p50 ms", "p99 ms", "last ms", "n", "steps/s"))
    hists = snap.get("histograms", {})
    for site, q in sorted(quant.items()):
        hist = hists.get("%s.step_ms" % site, {})
        rate = None
        if prev_payload is not None and dt:
            prev_hists = prev_payload.get("snapshot", {}).get(
                "histograms", {})
            d = hist.get("count", 0) - prev_hists.get(
                "%s.step_ms" % site, {}).get("count", 0)
            rate = d / dt if d >= 0 else None
        lines.append("  %-12s %10s %10s %10s %8s %9s"
                     % (site, _fmt_num(q.get("p50")), _fmt_num(q.get("p99")),
                        _fmt_num(q.get("last_ms")), q.get("n", "-"),
                        _fmt_num(rate)))
    if not quant:
        lines.append(DIM + "  (no steps observed yet)" + RESET)
    lines.append("")

    # --- throughput -----------------------------------------------------
    lines.append(BOLD + "throughput" + RESET)
    for name, label in (("comm.bucket.bytes", "comm bucket"),
                        ("kvstore.push_bytes", "kvstore push"),
                        ("kvstore.pull_bytes", "kvstore pull")):
        total = counters.get(name)
        if total is None:
            continue
        rate = _rate(counters, prev, name, dt or 0)
        lines.append("  %-14s %14s total %14s"
                     % (label, _fmt_bytes(total),
                        (_fmt_bytes(rate) + "/s") if rate is not None
                        else ""))
    coll = counters.get("comm.collectives")
    if coll is not None:
        rate = _rate(counters, prev, "comm.collectives", dt or 0)
        lines.append("  %-14s %14s total %14s"
                     % ("collectives", coll,
                        ("%.1f/s" % rate) if rate is not None else ""))
    # comm schedule + measured overlap (ISSUE 19): which (cap, policy) is
    # live and how much of the comm phase the step windows hide
    gauges = snap.get("gauges", {})
    row = []
    cap_g = gauges.get("comm.schedule.bucket_mb", {})
    if isinstance(cap_g, dict) and cap_g.get("value") is not None:
        ready_g = gauges.get("comm.schedule.ready", {})
        policy = "ready" if (isinstance(ready_g, dict)
                             and ready_g.get("value")) else "registration"
        row.append("schedule=%gMB/%s" % (cap_g["value"], policy))
    fracs = [(n[len("attrib."):-len(".overlap_frac")], g.get("value"))
             for n, g in sorted(gauges.items())
             if n.startswith("attrib.") and n.endswith(".overlap_frac")
             and isinstance(g, dict) and g.get("value") is not None]
    row.extend("overlap_frac[%s]=%.2f" % (site, v) for site, v in fracs)
    rounds = counters.get("comm.ready.rounds")
    if rounds:
        row.append("ready_rounds=%d" % rounds)
    if row:
        lines.append("  " + "  ".join(row))
    lines.append("")

    # --- compiles -------------------------------------------------------
    lines.append(BOLD + "compiles / retraces" + RESET)
    row = []
    for name in ("cachedop.compile", "fused_step.compile",
                 "train_step.compile", "cachedop.retrace",
                 "fused_step.retrace", "train_step.retrace"):
        v = counters.get(name)
        if v:
            row.append("%s=%d" % (name, v))
    lines.append("  " + ("  ".join(row) if row else DIM + "(none)" + RESET))
    lines.append("")

    # --- sparse embeddings ---------------------------------------------
    if any(n.startswith(("embedding.", "comm.sparse.")) for n in counters):
        lines.append(BOLD + "sparse embeddings" + RESET)
        pushed = counters.get("comm.sparse.rows",
                              counters.get("embedding.push.rows", 0))
        unique = counters.get("comm.sparse.unique_rows",
                              counters.get("embedding.push.unique_rows", 0))
        row = ["pushes=%d" % counters.get("embedding.push", 0)]
        if pushed:
            row.append("unique_rows=%d/%d (%.0f%%)"
                       % (unique, pushed, 100.0 * unique / pushed))
        disp = counters.get("ops.pallas.dispatch.segment_sum", 0)
        fall = sum(v for n, v in counters.items()
                   if n.startswith("ops.pallas.fallback.segment_sum."))
        if disp or fall:
            row.append("segsum=%d pallas/%d xla" % (disp, fall))
        sp_bytes = counters.get("comm.sparse.bytes")
        if sp_bytes is not None:
            rate = _rate(counters, prev, "comm.sparse.bytes", dt or 0)
            row.append("wire=%s%s"
                       % (_fmt_bytes(sp_bytes),
                          (" (%s/s)" % _fmt_bytes(rate))
                          if rate is not None else ""))
        dense_eq = counters.get("comm.sparse.bytes_dense_equiv")
        if dense_eq:
            row.append("saved=%s" % _fmt_bytes(dense_eq - (sp_bytes or 0)))
        lines.append("  " + "  ".join(row))
        row2 = []
        lookups = counters.get("embedding.serve.lookup")
        if lookups:
            look_rate = _rate(counters, prev, "embedding.serve.lookup",
                              dt or 0)
            row2.append("serve_lookups=%d%s"
                        % (lookups, (" (%.1f/s)" % look_rate)
                           if look_rate is not None else ""))
            h = snap.get("histograms", {}).get("embedding.serve.lookup_ms")
            if h:
                row2.append("lookup_ms p50/p99=%s/%s"
                            % (_fmt_num(_hist_quantile(h, 0.5)),
                               _fmt_num(_hist_quantile(h, 0.99))))
        table_g = gauges.get("memory.scope.embedding.bytes") or {}
        if table_g.get("value"):
            row2.append("table=%s" % _fmt_bytes(table_g["value"]))
        if row2:
            lines.append("  " + "  ".join(row2))
        lines.append("")

    # --- memory ---------------------------------------------------------
    mem_rows = [(n, g) for n, g in sorted(gauges.items())
                if n.startswith("memory.") and n.endswith(".bytes_in_use")]
    if mem_rows:
        lines.append(BOLD + "device memory" + RESET)
        for name, g in mem_rows:
            dev = name[len("memory."):-len(".bytes_in_use")]
            lines.append("  %-10s %14s in use   %14s peak"
                         % (dev, _fmt_bytes(g.get("value", 0)),
                            _fmt_bytes(g.get("max", 0))))
        lines.append("")

    # --- resilience + anomalies ----------------------------------------
    res = {n: v for n, v in sorted(counters.items())
           if n.startswith("resilience.") and v}
    if res:
        lines.append(BOLD + "resilience" + RESET)
        lines.append("  " + "  ".join("%s=%d" % (n[len("resilience."):], v)
                                      for n, v in res.items()))
        lines.append("")
    anom = {n: v for n, v in sorted(counters.items())
            if n.startswith("telemetry.anomaly.") and v}
    if anom:
        lines.append(BOLD + RED + "anomalies" + RESET)
        lines.append("  " + "  ".join(
            "%s=%d" % (n[len("telemetry.anomaly."):], v)
            for n, v in anom.items()))
        lines.append("")
    flight_n = payload.get("flight_steps")
    if flight_n is not None:
        lines.append(DIM + "flight recorder: %s steps buffered" % flight_n
                     + RESET)
    return "\n".join(lines)


def render_mem(payload, prev_payload=None, dt=None, source=""):
    """The --mem frame: the HBM ledger's per-scope bytes (with per-poll
    deltas), the per-program static footprints (compile vs AOT-cache
    restore), the device/scoped reconcile, and recent profile captures."""
    mem = payload.get("memory") or {}
    scopes = mem.get("scopes") or {}
    programs = mem.get("programs") or []
    reconcile = mem.get("reconcile") or {}
    prev_scopes = ((prev_payload or {}).get("memory") or {}).get(
        "scopes") or {}
    lines = ["%smxtop --mem%s  %s  %s" % (
        BOLD, RESET,
        time.strftime("%H:%M:%S", time.localtime(payload.get("ts",
                                                             time.time()))),
        DIM + source + RESET), ""]
    lines.append(BOLD + "HBM ledger (per-scope bytes)" + RESET)
    if scopes:
        lines.append("  %-14s %14s %12s  %s"
                     % ("scope", "bytes", "delta", "note"))
        for name, val in sorted(scopes.items(), key=lambda kv: -abs(kv[1])):
            delta = val - prev_scopes.get(name, val)
            note = ""
            if name == "prefix_cache":
                note = DIM + "overlay (inside kv_pool)" + RESET
            elif name == "unattributed":
                note = DIM + "reconcile residual" + RESET
            lines.append("  %-14s %14s %12s  %s"
                         % (name, _fmt_bytes(val),
                            ("%+d" % delta) if delta else "", note))
    else:
        lines.append(DIM + "  (no scopes yet — ledger disabled or idle)"
                     + RESET)
    if reconcile:
        lines.append("")
        lines.append("  reconcile: device %s  scoped %s  residual %s  (%s)"
                     % (_fmt_bytes(reconcile.get("device_bytes", 0)),
                        _fmt_bytes(reconcile.get("scoped_bytes", 0)),
                        _fmt_bytes(reconcile.get("residual_bytes", 0)),
                        reconcile.get("source", "?")))
    lines.append("")
    lines.append(BOLD + "program footprints (static, per executable)"
                 + RESET)
    if programs:
        lines.append("  %-28s %8s %12s %12s"
                     % ("program", "origin", "temp+code", "args"))
        ranked = sorted(programs, key=lambda p: -p.get("bytes", 0))
        for p in ranked[:12]:
            lines.append("  %-28s %8s %12s %12s"
                         % (p.get("label", "?")[:28],
                            "cache" if p.get("cached") else "compile",
                            _fmt_bytes(p.get("bytes", 0)),
                            _fmt_bytes(p.get("argument_bytes", 0))))
        if len(ranked) > 12:
            lines.append(DIM + "  ... %d more" % (len(ranked) - 12) + RESET)
    else:
        lines.append(DIM + "  (none recorded yet)" + RESET)
    profiles = payload.get("profiles") or []
    if profiles:
        lines.append("")
        lines.append(BOLD + "profile captures" + RESET)
        for rec in profiles[-4:]:
            lines.append("  %s  %s (%sms)"
                         % (rec.get("path", "?"), rec.get("kind", "?"),
                            rec.get("window_ms", "?")))
    lines.append("")
    lines.append(DIM + "press p+Enter to capture an on-device profile"
                 + RESET)
    return "\n".join(lines)


def _trigger_profile(base_url):
    """GET /profile on the polled endpoint (the `p` key). Returns a
    one-line status for the frame footer."""
    import urllib.error
    import urllib.request
    url = base_url.rsplit("/", 1)[0] + "/profile"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            body = json.loads(resp.read().decode("utf-8"))
        return "profile captured: %s" % body.get("path")
    except urllib.error.HTTPError as exc:
        if exc.code == 429:
            return "profile rate-limited (min interval not elapsed)"
        return "profile failed: HTTP %d" % exc.code
    except Exception as exc:  # noqa: BLE001 — footer status, not control flow
        return "profile failed: %s" % exc


def _wait_for_key(interval):
    """Sleep `interval` seconds, returning early with the line if the user
    typed one (the profile trigger). Falls back to a plain sleep when
    stdin is not selectable (tests piping /dev/null, Windows files)."""
    import select
    try:
        ready, _, _ = select.select([sys.stdin], [], [], interval)
    except (OSError, ValueError):
        time.sleep(interval)
        return None
    if ready:
        return sys.stdin.readline().strip().lower()
    return None


# the sparse-bucket quantile math lives in parse_log (same directory, so
# it resolves both run-as-script and with tools/ on sys.path): ONE stdlib
# re-derivation of telemetry.export.histogram_quantiles, not two copies
# that drift
from parse_log import _hist_quantile  # noqa: E402


def _serve_row(label, snap, quants):
    """One serving table row from a snapshot dict + hist_quantiles."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})

    def g(name):
        v = gauges.get(name) or {}
        return v.get("value"), v.get("max")

    def qfmt(name):
        q = quants.get(name)
        if not q:
            h = snap.get("histograms", {}).get(name)
            if h:
                q = {"p50": _hist_quantile(h, 0.5),
                     "p99": _hist_quantile(h, 0.99)}
        if not q:
            return "-"
        return "%s/%s" % (_fmt_num(q.get("p50")), _fmt_num(q.get("p99")))

    def rate(num, den):
        d = counters.get(den, 0)
        if not d:
            return "-"
        return "%d%%" % round(100.0 * counters.get(num, 0) / d)

    tok_s, _ = g("serve.tokens_per_s")
    qd, qd_peak = g("serve.queue_depth")
    occ, occ_peak = g("serve.batch_occupancy")
    return "  %-6s %9s %7s %7s %6s %6s %6s %6s %7s %5s %5s %15s %15s" % (
        label, _fmt_num(tok_s),
        "%s/%s" % (_fmt_num(qd), _fmt_num(qd_peak)),
        "%s/%s" % (_fmt_num(occ), _fmt_num(occ_peak)),
        counters.get("serve.requests", 0),
        counters.get("serve.completed", 0),
        counters.get("serve.shed", 0),
        counters.get("serve.requeued_streams", 0),
        counters.get("serve.prefill_chunks", 0),
        # prefix-cache hit rate (admissions reusing cached prompt blocks)
        # and speculative accept rate (drafts the target agreed with)
        rate("serve.prefix.hits", "serve.prefix.lookups"),
        rate("serve.spec.accepted", "serve.spec.drafted"),
        qfmt("serve.ttft_ms"), qfmt("serve.tpot_ms"))


def render_serve(payload, prev_payload=None, dt=None, source=""):
    """The --serve frame: one row per rank (fleet payloads) or one row
    (single endpoint), plus shed-reason and replica-health detail."""
    fleet = "ranks" in payload and "merged" in payload
    lines = ["%smxtop --serve%s  %s  %s" % (
        BOLD, RESET,
        time.strftime("%H:%M:%S", time.localtime(payload.get("ts",
                                                             time.time()))),
        DIM + source + RESET)]
    if fleet:
        stale = payload.get("stale_ranks") or []
        missing = payload.get("missing") or []
        health = "%d rank(s)" % payload.get("workers", 0)
        if stale:
            health += ", %s%d stale%s" % (RED, len(stale), RESET)
        if missing:
            health += ", %s%d missing%s" % (RED, len(missing), RESET)
        lines.append("  fleet: " + health)
    lines.append("")
    header = "  %-6s %9s %7s %7s %6s %6s %6s %6s %7s %5s %5s %15s %15s" \
        % ("rank", "tok/s", "queue", "batch", "reqs", "done", "shed",
           "requeue", "chunks", "pfx%", "acc%", "ttft p50/p99",
           "tpot p50/p99")
    lines.append(BOLD + header + RESET)
    if fleet:
        merged_counters = payload["merged"].get("counters", {})
        for rank, p in sorted(payload["ranks"].items(),
                              key=lambda kv: int(kv[0])):
            label = str(rank) + ("*" if p.get("stale") else "")
            lines.append(_serve_row(label, p.get("snapshot", {}),
                                    p.get("hist_quantiles", {}) or {}))
        lines.append(_serve_row("fleet", payload["merged"], {}))
        counters = merged_counters
    else:
        snap = payload.get("snapshot", {})
        counters = snap.get("counters", {})
        lines.append(_serve_row(str(payload.get("rank", 0)), snap,
                                payload.get("hist_quantiles", {}) or {}))
    sheds = {n: v for n, v in sorted(counters.items())
             if n.startswith("serve.shed.") and v}
    if sheds:
        lines.append("")
        lines.append(BOLD + "shed by reason" + RESET)
        lines.append("  " + "  ".join(
            "%s=%d" % (n[len("serve.shed."):], v)
            for n, v in sheds.items()))
    deaths = counters.get("serve.replica_deaths")
    if deaths:
        lines.append("")
        lines.append("%sreplica deaths: %d%s" % (RED, deaths, RESET))
    if not fleet and not any(n.startswith("serve.")
                             for n in counters):
        lines.append(DIM + "  (no serve.* metrics yet)" + RESET)
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--port", type=int, help="poll localhost /snapshot")
    src.add_argument("--url", help="full /snapshot URL")
    src.add_argument("--stream", help="tail a MXNET_TPU_METRICS_STREAM file")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--serve", action="store_true",
                        help="serving view (tokens/s, queue, batch, shed, "
                             "TTFT/TPOT); understands both /snapshot and "
                             "/fleet/snapshot payloads")
    parser.add_argument("--mem", action="store_true",
                        help="memory view: HBM-ledger scope bytes with "
                             "per-poll deltas, per-program static "
                             "footprints, reconcile residual, recent "
                             "profile captures")
    parser.add_argument("--interval", type=float, default=2.0)
    parser.add_argument("--once", action="store_true",
                        help="render one frame and exit")
    args = parser.parse_args(argv)

    if args.stream:
        fetch = lambda: fetch_stream(args.stream)  # noqa: E731
        source = args.stream
    else:
        url = args.url or ("http://%s:%d/snapshot" % (args.host, args.port))
        fetch = lambda: fetch_url(url)  # noqa: E731
        source = url

    prev = None
    prev_t = None
    status = None
    while True:
        try:
            payload = fetch()
        except Exception as exc:  # noqa: BLE001 — poll target flakiness is
            # the normal case for a dashboard; report and keep trying
            if args.once:
                sys.exit("mxtop: cannot read %s: %s" % (source, exc))
            sys.stdout.write(CLEAR + "mxtop: waiting for %s (%s)\n"
                             % (source, exc))
            sys.stdout.flush()
            time.sleep(args.interval)
            continue
        now = time.monotonic()
        dt = (now - prev_t) if prev_t is not None else None
        renderer = (render_mem if args.mem
                    else render_serve if args.serve else render)
        frame = renderer(payload, prev, dt, source=source)
        if status:
            frame += "\n" + BOLD + status + RESET
            status = None
        if args.once:
            print(frame)
            return 0
        sys.stdout.write(CLEAR + frame + "\n")
        sys.stdout.flush()
        prev, prev_t = payload, now
        # p+Enter during the poll wait triggers an on-device profile
        # capture on the polled endpoint (HTTP sources only)
        key = _wait_for_key(args.interval)
        if key == "p" and not args.stream:
            status = _trigger_profile(source)


if __name__ == "__main__":
    sys.exit(main())
