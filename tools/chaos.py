"""Chaos soak harness: randomized multi-fault schedules, exact invariants.

Single-shot fault tests prove each recovery path works alone; fleets die
to *combinations* — a preemption landing mid-replay of a rollback, a
corrupt checkpoint discovered only because a later hang forced a restore.
This harness closes that gap: a seeded `random.Random` draws a multi-fault
schedule over the registered fault sites (`resilience.faults`), installs
it as a fault plan, soaks a real training loop (and, separately, the
serving engine) through it, and then checks invariants that are EXACT,
not statistical:

training soak (`train_soak`)
    * the run completes (no fault combination may wedge or kill it);
    * final params are bit-identical to an equivalent clean run over the
      post-skip batch trajectory (`ResilientRunner.data_index`) — replay
      and rollback must be deterministic to the last mantissa bit;
    * every committed data index is unique (no batch trained twice, none
      silently dropped);
    * all params finite (a poisoned batch that escaped the sentinel would
      leave NaN footprints).

serving soak (`serve_soak`)
    * every stream's tokens byte-identical to the unfaulted run (no token
      lost or duplicated across kills/requeues/hangs);
    * `pool.reconcile() == 0` and zero leaked KV blocks after drain.

Deterministic by construction: same seed → same schedule → same report.
Run standalone (``python tools/chaos.py --mode both --seed 0``) or from
CI via the ``chaos``-marked pytest wrappers (``pytest -m chaos``).
Unlike the log-side tools/ scripts this one imports the framework — it
IS the workload.

Exit codes: 0 = all invariants green, 1 = an invariant failed,
2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as tools/chaos.py from anywhere
    sys.path.insert(0, _REPO)

# ---------------------------------------------------------------------------
# schedule generation
# ---------------------------------------------------------------------------
# site -> kinds a soak may draw there. Deliberately narrower than what the
# site accepts: the soak must always be *survivable* (an `error` inside the
# restore path would fail the run by design, so only latency goes there).
TRAIN_SITE_KINDS = {
    "run.step": ("error", "preempt", "latency", "hang"),
    "train.step": ("error", "latency"),
    "train.batch": ("corrupt",),
    "checkpoint.save": ("error", "latency"),
    "checkpoint.restore": ("latency",),
    "checkpoint.corrupt": ("corrupt",),
}
SERVE_SITE_KINDS = {
    "serve.step": ("error", "latency", "hang"),
    "serve.admit": ("latency",),
}
# kinds the acceptance bar demands at least once per schedule
_MANDATORY = (("train.batch", "corrupt"), ("checkpoint.corrupt", "corrupt"),
              ("run.step", "preempt"), ("run.step", "hang"))
_SERVE_MANDATORY = (("serve.step", "error"), ("serve.step", "hang"))

_HANG_ARG = 3.0  # seconds; the watchdog deadline converts it to a StallError


def _nth_range(site, steps, ckpt_every):
    """1-based call-count window in which a fault at `site` is guaranteed
    to fire during a `steps`-step soak (replays only add calls)."""
    if site in ("run.step", "train.step", "train.batch"):
        return 2, max(2, steps)
    if site in ("checkpoint.save", "checkpoint.corrupt"):
        return 1, max(1, steps // ckpt_every - 1)
    if site == "checkpoint.restore":
        return 1, 2  # only recoveries restore; keep it early
    if site == "serve.admit":
        return 1, 4
    return 2, 8  # serve.step: scheduler ticks, many per request


def _draw_schedule(rng, site_kinds, n_faults, steps=32, ckpt_every=2,
                   mandatory=()):
    """Seeded schedule: `n_faults` deduped (site, nth) entries in fault-plan
    grammar, mandatory (site, kind) pairs first so the acceptance kinds
    (corrupt / preempt / hang) always appear."""
    entries = {}

    def add(site, kind):
        lo, hi = _nth_range(site, steps, ckpt_every)
        for _ in range(8):  # dedup (site, nth) by redraw
            nth = rng.randint(lo, hi)
            if (site, nth) not in entries:
                break
        else:
            return
        arg = None
        if kind == "latency":
            arg = round(rng.uniform(0.01, 0.04), 3)
        elif kind == "hang":
            arg = _HANG_ARG
        entries[(site, nth)] = (site, kind, nth, arg)

    for site, kind in mandatory:
        if site in site_kinds:
            add(site, kind)
    sites = sorted(site_kinds)
    while len(entries) < n_faults:
        site = rng.choice(sites)
        add(site, rng.choice(site_kinds[site]))
    plan = []
    for site, kind, nth, arg in sorted(entries.values(),
                                       key=lambda e: (e[0], e[2])):
        plan.append("%s:%s:%d" % (site, kind, nth)
                    + ("" if arg is None else ":%g" % arg))
    return ";".join(plan)


def _fired_specs(plan):
    """Which plan entries actually fired: a one-shot spec fired iff its
    site's call counter reached its nth."""
    return [s for s in plan.specs
            if (not s.every) and plan.count(s.site) >= s.nth]


# ---------------------------------------------------------------------------
# training soak
# ---------------------------------------------------------------------------
def _build_mlp():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(42)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    return net, trainer


def _batches(n, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 32, 8).astype(np.float32)
    Y = rng.randint(0, 3, (n, 32)).astype(np.float32)
    return X, Y


def train_soak(seed=0, steps=30, n_faults=12, verbose=False):
    """Seeded multi-fault training soak; returns the invariant report."""
    import numpy as np
    from mxnet_tpu import gluon, nd, telemetry
    from mxnet_tpu import resilience as rz
    from mxnet_tpu.resilience import faults

    rng = random.Random(seed)
    ckpt_every = 2
    plan_text = _draw_schedule(rng, TRAIN_SITE_KINDS, n_faults, steps=steps,
                               ckpt_every=ckpt_every, mandatory=_MANDATORY)
    if verbose:
        print("train plan:", plan_text)
    # enough spare batches to absorb every possible skipped window
    X, Y = _batches(steps + n_faults + 4)

    def batch_fn(i):
        return nd.array(X[i]), nd.array(Y[i])

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    overrides = {"MXNET_TPU_INTEGRITY": "1",
                 "MXNET_TPU_ROLLBACK_BUDGET": "10"}
    saved_env = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    telemetry.enable()
    telemetry.reset()
    try:
        net, trainer = _build_mlp()
        fused = gluon.FusedTrainStep(net, loss_fn, trainer)
        with tempfile.TemporaryDirectory(prefix="chaos_ckpt_") as ckpt_dir:
            runner = rz.ResilientRunner.for_fused_step(
                fused, batch_fn, ckpt_dir=ckpt_dir, ckpt_every=ckpt_every,
                keep=4, max_restarts=n_faults + 8, step_deadline_s=0.75)
            with faults.inject(plan_text) as plan:
                report = runner.run(steps)
                fired = _fired_specs(plan)
            final_idx = [runner.data_index(s) for s in range(steps)]
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    counters = telemetry.snapshot()["counters"]

    # the equivalent clean run: same init, the post-skip batch trajectory
    net_clean, trainer_clean = _build_mlp()
    fused_clean = gluon.FusedTrainStep(net_clean, loss_fn, trainer_clean)
    for i in final_idx:
        fused_clean(*batch_fn(i))

    mismatched, nonfinite = [], []
    chaos_params = sorted(net.collect_params().items())
    clean_params = sorted(net_clean.collect_params().items())
    for (name, p_chaos), (_, p_clean) in zip(chaos_params, clean_params):
        a = np.asarray(p_chaos.data().asnumpy())
        b = np.asarray(p_clean.data().asnumpy())
        if not np.isfinite(a).all():
            nonfinite.append(name)
        if a.tobytes() != b.tobytes():
            mismatched.append(name)

    result = {
        "mode": "train",
        "seed": seed,
        "steps": steps,
        "plan": plan_text,
        "faults_scheduled": len(plan.specs),
        "faults_fired": len(fired),
        "sites_hit": sorted({s.site for s in fired}),
        "kinds_hit": sorted({s.kind for s in fired}),
        "rollbacks": report.rollbacks,
        "skipped_batches": report.skipped_batches,
        "restarts": report.restarts,
        "replayed": report.replayed_steps,
        "corrupt_snapshots": int(counters.get("checkpoint.corrupt", 0)),
        "corrupt_fallbacks": int(
            counters.get("checkpoint.corrupt_fallbacks", 0)),
        "divergences": int(counters.get("integrity.divergences", 0)),
        "final_indices_unique": len(set(final_idx)) == steps,
        "params_bit_identical": not mismatched,
        "params_finite": not nonfinite,
        "mismatched_params": mismatched,
        "nonfinite_params": nonfinite,
    }
    result["ok"] = (result["params_bit_identical"]
                    and result["params_finite"]
                    and result["final_indices_unique"])
    return result


# ---------------------------------------------------------------------------
# serving soak
# ---------------------------------------------------------------------------
def serve_soak(seed=0, requests=6, n_faults=6, verbose=False):
    """Seeded multi-fault serving soak; returns the invariant report."""
    import jax
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.models.llama import LlamaConfig, llama_init
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.serve import InferenceServer, Request

    rng = random.Random(seed)
    plan_text = _draw_schedule(rng, SERVE_SITE_KINDS, n_faults,
                               mandatory=_SERVE_MANDATORY)
    if verbose:
        print("serve plan:", plan_text)

    import jax.numpy as jnp
    cfg = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=128, rope_theta=10000.0,
                      max_seq_len=64, dtype=jnp.float32)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    prng = np.random.RandomState(seed)
    prompts = [prng.randint(1, cfg.vocab_size - 1,
                            size=prng.randint(3, 10)).tolist()
               for _ in range(requests)]
    budgets = [3 + i % 4 for i in range(requests)]

    def run_all(server):
        handles = [server.submit(Request(p, max_new_tokens=b))
                   for p, b in zip(prompts, budgets)]
        server.run()
        return [h.result(timeout=60) for h in handles]

    def make_server():
        return InferenceServer(params, cfg, kv_blocks=48, block_size=8,
                               max_batch=4, max_context=32,
                               step_deadline_s=0.5).warmup()

    baseline = run_all(make_server())
    telemetry.enable()
    telemetry.reset()
    server = make_server()
    with faults.inject(plan_text) as plan:
        chaos = run_all(server)
        fired = _fired_specs(plan)
    counters = telemetry.snapshot()["counters"]

    leaked = server.pool.blocks_in_use - server.pool.prefix_blocks
    result = {
        "mode": "serve",
        "seed": seed,
        "requests": requests,
        "plan": plan_text,
        "faults_scheduled": len(plan.specs),
        "faults_fired": len(fired),
        "sites_hit": sorted({s.site for s in fired}),
        "kinds_hit": sorted({s.kind for s in fired}),
        "recoveries": int(counters.get("serve.recoveries", 0)),
        "requeued_streams": int(counters.get("serve.requeued_streams", 0)),
        "tokens_byte_identical": chaos == baseline,
        "reconcile_exact": server.pool.reconcile() == 0,
        "leaked_kv_blocks": int(leaked),
    }
    result["ok"] = (result["tokens_byte_identical"]
                    and result["reconcile_exact"]
                    and result["leaked_kv_blocks"] == 0)
    return result


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Seeded chaos soak over the resilience fault sites.")
    ap.add_argument("--mode", choices=("train", "serve", "both"),
                    default="both")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=30,
                    help="training soak steps")
    ap.add_argument("--requests", type=int, default=6,
                    help="serving soak request count")
    ap.add_argument("--faults", type=int, default=12,
                    help="faults per training schedule (serve draws half)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    reports = []
    if args.mode in ("train", "both"):
        reports.append(train_soak(args.seed, steps=args.steps,
                                  n_faults=args.faults,
                                  verbose=args.verbose))
    if args.mode in ("serve", "both"):
        reports.append(serve_soak(args.seed, requests=args.requests,
                                  n_faults=max(2, args.faults // 2),
                                  verbose=args.verbose))
    print(json.dumps(reports, indent=2))
    return 0 if all(r["ok"] for r in reports) else 1


if __name__ == "__main__":
    sys.exit(main())
