#!/usr/bin/env python
"""prebake_cache — populate a shared AOT-executable cache before the
fleet boots.

The persistent AOT cache (``MXNET_TPU_AOT_CACHE``) makes the SECOND
process cheap; this tool makes the FIRST one cheap too, by paying every
serve-program compile once, centrally, from a **program manifest** — so a
thousand replicas cold-start restoring serialized executables instead of
racing XLA. Executables are value-independent (the cache key is model
geometry + pool geometry + param avals), so the tool compiles against
freshly-initialized parameters of the right shapes; the weights the fleet
loads later restore the same binaries.

Manifest (JSON)::

    {"programs": [
      {"model": "llama_tiny",                   # models.llama.CONFIGS key
       "overrides": {"dtype": "float32"},       # LlamaConfig replacements
       "serve": {"max_batch": 8, "kv_blocks": 64, "block_size": 8,
                 "max_context": 48, "chunk_size": 16, "prefill_rows": 4,
                 "spec_k": 4,                   # with draft_model: spec
                 "draft_model": "llama_tiny",   # draft programs prebaked
                 "draft_overrides": {"n_layers": 1}}}
    ]}

Every entry warms one `InferenceServer` geometry: the chunk-prefill,
decode, and CoW-copy executables — plus the draft-chunk / draft-k /
verify executables when a draft model is named. Run it twice and the
second pass reports 0 fresh compiles (the fleet's boot experience).

Usage::

    python tools/prebake_cache.py manifest.json --cache /shared/aot
    python tools/prebake_cache.py manifest.json          # env cache dir
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# runnable from anywhere: an installed mxnet_tpu wins, otherwise the
# checkout this script lives in (tools/..) provides it
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _build_cfg(entry_model, overrides):
    import dataclasses

    import jax.numpy as jnp

    from mxnet_tpu.models.llama import CONFIGS
    if entry_model not in CONFIGS:
        raise SystemExit("prebake: unknown model %r (have: %s)"
                         % (entry_model, ", ".join(sorted(CONFIGS))))
    cfg = CONFIGS[entry_model]
    overrides = dict(overrides or {})
    if "dtype" in overrides:
        overrides["dtype"] = jnp.dtype(overrides["dtype"]).type
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def prebake(manifest, cache_dir=None):
    """Warm every manifest entry; returns the per-entry report rows."""
    if cache_dir:
        os.environ["MXNET_TPU_AOT_CACHE"] = cache_dir
    if not os.environ.get("MXNET_TPU_AOT_CACHE"):
        raise SystemExit(
            "prebake: no cache directory — pass --cache DIR or set "
            "MXNET_TPU_AOT_CACHE (without it this tool only measures "
            "compile times and bakes nothing)")
    import jax

    from mxnet_tpu import telemetry
    from mxnet_tpu.models.llama import llama_init
    from mxnet_tpu.serve import InferenceServer

    rows = []
    for i, entry in enumerate(manifest.get("programs", [])):
        serve_kw = dict(entry.get("serve", {}))
        draft_model = serve_kw.pop("draft_model", None)
        draft_overrides = serve_kw.pop("draft_overrides", None)
        cfg = _build_cfg(entry.get("model", "llama_tiny"),
                         entry.get("overrides"))
        params = llama_init(jax.random.PRNGKey(0), cfg)
        if draft_model is not None:
            dcfg = _build_cfg(draft_model, draft_overrides)
            serve_kw["draft_cfg"] = dcfg
            serve_kw["draft_params"] = llama_init(jax.random.PRNGKey(1),
                                                  dcfg)

        def counters():
            return telemetry.snapshot().get("counters", {})

        before = counters()
        server = InferenceServer(params, cfg, **serve_kw)
        server.warmup()
        after = counters()

        def delta(name):
            return after.get(name, 0) - before.get(name, 0)

        rows.append({
            "entry": i,
            "model": entry.get("model", "llama_tiny"),
            "programs": len(server.programs.program_names),
            "compiled": delta("serve.compile"),
            "restored": delta("compiler.cache.hits"),
            "written": delta("compiler.cache.writes"),
            "errors": (delta("compiler.cache.serialize_error")
                       + delta("compiler.cache.write_error")),
        })
    return rows


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("manifest", help="program manifest JSON")
    parser.add_argument("--cache", help="cache directory "
                        "(default: $MXNET_TPU_AOT_CACHE)")
    parser.add_argument("--format", choices=("table", "json"),
                        default="table")
    args = parser.parse_args(argv)
    with open(args.manifest) as f:
        manifest = json.load(f)
    rows = prebake(manifest, cache_dir=args.cache)
    if args.format == "json":
        print(json.dumps({"entries": rows}))
    else:
        print("entry  model            programs  compiled  restored  "
              "written  errors")
        for r in rows:
            print("%-6s %-16s %8d  %8d  %8d  %7d  %6d"
                  % (r["entry"], r["model"][:16], r["programs"],
                     r["compiled"], r["restored"], r["written"],
                     r["errors"]))
        total_c = sum(r["compiled"] for r in rows)
        total_r = sum(r["restored"] for r in rows)
        print("total: %d compiled, %d restored -> %s"
              % (total_c, total_r, os.environ.get("MXNET_TPU_AOT_CACHE")))
    # a serialize/write error means the NEXT boot will recompile — that
    # is the condition a pre-bake pipeline must fail loudly on
    return 1 if any(r["errors"] for r in rows) else 0


if __name__ == "__main__":
    sys.exit(main())
