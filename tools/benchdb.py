"""Bench-history store: every bench.py row, appended forever, fingerprinted.

A bench number is only comparable to another bench number taken on the
SAME hardware and software stack — a CPU smoke row regressing against an
accelerator row is noise, not signal. So every row appended here carries
an *environment fingerprint*: platform, device count, jax/jaxlib/python
versions, and whether the run silently fell back to CPU. The regression
gate (`tools/check_bench.py`) only ever compares rows whose fingerprints
match.

Storage is one JSON object per line (`bench_history.jsonl`, next to this
repo's bench.py, overridable via ``MXNET_TPU_BENCH_HISTORY``) — append-only
so concurrent bench runs cannot corrupt each other, greppable, diffable,
and trivially committed to git so CI has a rolling baseline to gate on.

Stdlib-only: bench.py imports this *after* the backend probe, and CI
imports it from a bare checkout — it must never pull in jax or mxnet_tpu
(the fingerprint's jax versions come from the caller or from
importlib.metadata, never from importing jax).

Used two ways:
  - bench.py calls `append(row)` after printing its BENCH line;
  - `python tools/benchdb.py` pretty-prints the history grouped by
    (metric, fingerprint) for a human.
"""
import hashlib
import json
import os
import platform as _platform
import sys

__all__ = ["fingerprint", "fingerprint_id", "history_path", "append",
           "load"]


def _dist_version(name):
    """Installed-distribution version without importing the package (an
    `import jax` here would initialize the backend bench.py so carefully
    probes around)."""
    try:
        from importlib import metadata
        return metadata.version(name)
    except Exception:  # noqa: BLE001 — absent dist, py<3.8, broken metadata
        return None


def fingerprint(backend=None, device_count=None, cpu_fallback=None):
    """The environment identity a bench row is only comparable within.

    The caller (bench.py) passes what it already knows — the probed
    backend platform, the device count, whether the accelerator probe
    fell back to CPU — so this module never has to import jax itself.
    """
    return {
        "backend": backend or "unknown",
        "device_count": int(device_count) if device_count else 0,
        "cpu_fallback": bool(cpu_fallback),
        "jax": _dist_version("jax"),
        "jaxlib": _dist_version("jaxlib"),
        "python": "%d.%d" % sys.version_info[:2],
        "machine": _platform.machine(),
        "system": _platform.system(),
    }


def fingerprint_id(fp):
    """Short stable id of a fingerprint dict — the grouping key the
    regression gate buckets history rows by."""
    canon = json.dumps(fp, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:12]


def history_path():
    env = os.environ.get("MXNET_TPU_BENCH_HISTORY")
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_history.jsonl")


def append(row, path=None):
    """Append one bench row (a dict) as a JSON line. Best-effort: a full
    disk or read-only checkout must not fail the bench itself. Returns
    the path written, or None."""
    path = path or history_path()
    try:
        with open(path, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
        return path
    except OSError as e:
        print("# benchdb: could not append to %s: %s" % (path, e),
              file=sys.stderr)
        return None


def load(path=None):
    """All rows, oldest first. Unparseable lines are skipped (a truncated
    tail from a killed run must not poison the whole history)."""
    path = path or history_path()
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict):
                    rows.append(obj)
    except OSError:
        pass
    return rows


def _main(argv):
    path = argv[1] if len(argv) > 1 else history_path()
    rows = load(path)
    if not rows:
        print("no history at %s" % path)
        return 0
    groups = {}
    for row in rows:
        key = (row.get("metric", "?"), row.get("fingerprint_id", "?"))
        groups.setdefault(key, []).append(row)
    print("%s: %d rows, %d (metric, fingerprint) series"
          % (path, len(rows), len(groups)))
    for (metric, fpid), series in sorted(groups.items()):
        vals = [r.get("value") for r in series if r.get("value") is not None]
        tail = ", ".join("%g" % v for v in vals[-5:])
        print("  %-40s fp=%s n=%-3d last: %s"
              % (metric, fpid, len(series), tail))
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv))
