#!/bin/sh
# tracelint self-check: lint mxnet_tpu/ for trace-safety hazards, failing
# on error-severity findings. Part of the tier-1 gate (also run from
# tests/test_analysis.py under the `lint` pytest marker).
#
# The per-file mtime cache keeps repeat runs well under the 10 s budget —
# only files that changed since the last run are re-parsed.
#
# Usage: tools/run_tracelint.sh [extra tracelint args...]
#        (e.g. tools/run_tracelint.sh --format json)
set -e
cd "$(dirname "$0")/.."
# --cache uses the CLI's uid-scoped default path under $TMPDIR;
# MXNET_TPU_TRACELINT_CACHE overrides it explicitly
if [ -n "${MXNET_TPU_TRACELINT_CACHE:-}" ]; then
    set -- --cache-file "$MXNET_TPU_TRACELINT_CACHE" "$@"
else
    set -- --cache "$@"
fi
# tools/mxtop.py rides along: the dashboard spawns no traces itself but
# shares the telemetry thread model the TPU006 rule audits
exec python -m mxnet_tpu.analysis mxnet_tpu tools/mxtop.py --fail-on=error "$@"
