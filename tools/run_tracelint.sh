#!/bin/sh
# tracelint self-check: lint mxnet_tpu/ for trace-safety hazards, failing
# on error-severity findings. Part of the tier-1 gate (also run from
# tests/test_analysis.py under the `lint` pytest marker).
#
# The per-file mtime cache keeps repeat runs well under the 10 s budget —
# only files that changed since the last run are re-parsed (the
# whole-program project digest folds every file's mtime in, so editing a
# helper re-lints its callers too).
#
# Usage: tools/run_tracelint.sh [extra tracelint args...]
#        tools/run_tracelint.sh --ci
#
# --ci is the findings gate: any NEW warning-or-worse finding fails; the
# findings fingerprinted in tools/tracelint_baseline.json pass. Refresh
# the baseline after a reviewed change with:
#   python -m mxnet_tpu.analysis mxnet_tpu tools/mxtop.py \
#       --baseline tools/tracelint_baseline.json --update-baseline
set -e
cd "$(dirname "$0")/.."
# rewrite a --ci token into the baseline-gate argument set (plain-flag
# word splitting is fine here: tracelint args carry no spaces)
ci=0
rest=""
for a in "$@"; do
    if [ "$a" = "--ci" ]; then
        ci=1
    else
        rest="$rest $a"
    fi
done
# shellcheck disable=SC2086
set -- $rest
if [ "$ci" = 1 ]; then
    set -- --baseline tools/tracelint_baseline.json --fail-on warning "$@"
fi
# --cache uses the CLI's uid-scoped default path under $TMPDIR;
# MXNET_TPU_TRACELINT_CACHE overrides it explicitly
if [ -n "${MXNET_TPU_TRACELINT_CACHE:-}" ]; then
    set -- --cache-file "$MXNET_TPU_TRACELINT_CACHE" "$@"
else
    set -- --cache "$@"
fi
# tools/mxtop.py and tools/prebake_cache.py ride along: the dashboard
# spawns no traces itself but shares the telemetry thread model the
# TPU006 rule audits, and the pre-bake tool drives the serve warmup
# path. tools/benchdb.py and tools/check_bench.py (the bench-history
# store and the perf-regression gate) ride along too — stdlib-only, but
# bench.py imports benchdb in-process so it must hold the same bar. The
# package root covers mxnet_tpu/serve/ AND mxnet_tpu/compiler/
# — the serving scheduler/replica threads are TPU006-clean with zero
# suppressions (tests/test_serve.py asserts it under the lint marker),
# and the whole-graph compiler package is tracelint-clean with zero
# suppressions (tests/test_compiler.py asserts it the same way). The
# linter also lints its own runtime guards: mxnet_tpu/analysis/guard.py
# and lockguard.py sit under the package root, so the lock-order guard
# must itself pass TPU009/TPU010 (its _GRAPH_LOCK is the one lock the
# guard holds while checking, and nothing blocking happens under it).
exec python -m mxnet_tpu.analysis mxnet_tpu tools/mxtop.py \
    tools/prebake_cache.py tools/benchdb.py tools/check_bench.py \
    --fail-on=error "$@"
