"""Window-resilient chip-evidence capture (round-5 VERDICT task 1).

The axon tunnel has been up twice in four rounds; live windows are a
scarce resource. This watcher probes the accelerator cheaply (subprocess
with its own process group, hard-killed on timeout — the tunnel hangs
`jax.devices()` in make_c_api_client when the relay is down, BASELINE.md
round-3 notes) and, the moment a window opens, runs the BASELINE.md chip
queue IN ORDER with per-item timeouts and incremental artifact writes, so
a mid-queue drop still leaves everything captured up to that point.

Queue (BASELINE.md "chip queue", round-4 ordering):
  1. bench_gluon        python bench.py                (headline)
  2. bench_gluon_nhwc   BENCH=gluon_nhwc python bench.py
                        -> writes chip_artifacts/NHWC_PROMOTE if the NHWC
                           row clears the 2,250 bar and beats NCHW
  3. bench_bert         BENCH=bert python bench.py
  4. bench_bert_gluon   BENCH=bert_gluon python bench.py
  5. bench_functional   BENCH=functional python bench.py
  6. bench_fused        BENCH=fused python bench.py    (cost bytes on stderr)
     + bench_fused_train / bench_fused_bwd / bench_fused_opt — the
       training-form fusion, the fused CBR backward, and the Pallas flat
       optimizer kernel (ISSUE 10), all logging cost_analysis bytes
  7. longcontext        python tools/longcontext_probe.py   (seq 4096 A/B)
  8. tpu_suite          MXNET_TEST_DEVICE=tpu pytest tests/ -q
                        -> summary recorded to TESTS_r05_tpu.json

Artifacts: CHIP_CAPTURE_r05.json (incremental, one entry per item) plus
full stdout/stderr per item under chip_artifacts/. Items that fail or
time out are retried on the next live window; completed items are not
re-run (delete CHIP_CAPTURE_r05.json to start over).

Usage:
  python tools/chip_capture.py [--hours 11] [--probe-interval 180]
  python tools/chip_capture.py --once        # single probe+queue attempt
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART_DIR = os.path.join(REPO, "chip_artifacts")
STATE = os.path.join(REPO, "CHIP_CAPTURE_r05.json")
BAR_IMG_S = 2250.0

QUEUE = [
    # MXNET_HEADLINE_LAYOUT=NCHW: the baseline row must stay NCHW even
    # after a prior window wrote the NHWC_PROMOTE marker, or the
    # promotion comparison becomes NHWC-vs-NHWC and can never be
    # re-falsified
    ("bench_gluon", [sys.executable, "bench.py"],
     {"MXNET_HEADLINE_LAYOUT": "NCHW"}, 2400),
    ("bench_gluon_nhwc", [sys.executable, "bench.py"],
     {"BENCH": "gluon_nhwc"}, 2400),
    ("bench_bert", [sys.executable, "bench.py"], {"BENCH": "bert"}, 2400),
    ("bench_bert_gluon", [sys.executable, "bench.py"],
     {"BENCH": "bert_gluon"}, 2400),
    ("bench_functional", [sys.executable, "bench.py"],
     {"BENCH": "functional"}, 1800),
    ("bench_fused", [sys.executable, "bench.py"], {"BENCH": "fused"}, 1800),
    ("bench_fused_train", [sys.executable, "bench.py"],
     {"BENCH": "fused_train"}, 1800),
    ("bench_fused_bwd", [sys.executable, "bench.py"],
     {"BENCH": "fused_bwd"}, 1800),
    ("bench_fused_opt", [sys.executable, "bench.py"],
     {"BENCH": "fused_opt"}, 1800),
    ("bench_gluon_fused", [sys.executable, "bench.py"],
     {"BENCH": "gluon_fused"}, 2400),
    ("longcontext", [sys.executable, "tools/longcontext_probe.py"], {},
     3900),
    ("tpu_suite", [sys.executable, "-m", "pytest", "tests/", "-q"],
     {"MXNET_TEST_DEVICE": "tpu"}, 9000),
]


def log(msg):
    print("[chip_capture %s] %s"
          % (time.strftime("%H:%M:%S"), msg), flush=True)


def load_state():
    if os.path.exists(STATE):
        with open(STATE) as f:
            return json.load(f)
    return {"items": {}, "started": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime())}


def save_state(state):
    tmp = STATE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1, sort_keys=True)
    os.replace(tmp, STATE)


def run_killable(cmd, env_extra, timeout, out_path, err_path):
    """Run cmd in its own process group; SIGKILL the whole group on
    timeout (a tunnel-helper grandchild holding the pipe would otherwise
    hang the reader — bench.py f476311 lesson)."""
    env = dict(os.environ)
    env.update(env_extra)
    with open(out_path, "w") as out, open(err_path, "w") as err:
        proc = subprocess.Popen(cmd, cwd=REPO, env=env, stdout=out,
                                stderr=err, start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout)
            timed_out = False
        except subprocess.TimeoutExpired:
            rc, timed_out = None, True
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
    return rc, timed_out


def probe(timeout=150):
    """True if the accelerator backend EXECUTES within `timeout`.

    `jax.devices()` alone is not enough: the tunnel has a failure mode
    where the control plane answers but the data plane hangs (observed
    2026-07-31: devices() returned in 3s, then the first real dispatch
    blocked >35 min with zero CPU).  The probe therefore runs a tiny
    computation and forces a D2H readback — MXNet `.asnumpy()`
    semantics, the same hard barrier bench.py syncs through."""
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax, jax.numpy as jnp, numpy as np;"
         " d = jax.devices()[0];"
         " v = float(np.asarray(jnp.arange(8.0) + 1.0).sum());"
         " print('LIVE', d.platform, v)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True, cwd=REPO)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.communicate()
        return False
    return proc.returncode == 0 and "LIVE" in (out or "") \
        and "cpu" not in (out or "")


def last_json_line(path):
    try:
        with open(path) as f:
            lines = [ln for ln in f if ln.strip().startswith("{")]
        return json.loads(lines[-1]) if lines else None
    except Exception:
        return None


def maybe_promote_nhwc(state):
    """(Re-)evaluate the NHWC headline promotion whenever both layout
    measurements exist — also demotes a stale marker if NCHW now wins."""
    items = state["items"]
    gi = items.get("bench_gluon", {})
    ni = items.get("bench_gluon_nhwc", {})
    # both rows must be REAL chip captures — a cpu-fallback NCHW baseline
    # vs an on-chip NHWC row would promote on a bogus comparison
    if not (gi.get("status") == "ok" and ni.get("status") == "ok"):
        return
    g, n = gi.get("json") or {}, ni.get("json") or {}
    if not (g.get("value") and n.get("value")):
        return
    marker = os.path.join(ART_DIR, "NHWC_PROMOTE")
    if n["value"] >= BAR_IMG_S and n["value"] >= g["value"]:
        with open(marker, "w") as f:
            json.dump({"nhwc": n["value"], "nchw": g["value"],
                       "bar": BAR_IMG_S}, f)
        log("NHWC PROMOTED: %.1f img/s (NCHW %.1f, bar %.0f)"
            % (n["value"], g["value"], BAR_IMG_S))
    else:
        if os.path.exists(marker):
            os.remove(marker)
            log("stale NHWC_PROMOTE removed")
        log("NHWC not promoted: nhwc=%.1f nchw=%.1f bar=%.0f"
            % (n["value"], g["value"], BAR_IMG_S))


DONE = ("ok", "completed_with_failures")


def write_suite_artifact(state):
    item = state["items"].get("tpu_suite")
    if not item or item.get("status") not in DONE:
        return
    tail, backend = "", None
    try:
        with open(os.path.join(ART_DIR, "tpu_suite.out")) as f:
            lines = f.readlines()
        tail = "".join(lines[-30:])
        for ln in lines:
            # conftest prints this at session start on accel runs and
            # hard-fails if the backend silently fell back to cpu
            if "on-chip suite backend:" in ln:
                backend = ln.split("on-chip suite backend:")[1].strip()
                break
    except OSError:
        pass
    with open(os.path.join(REPO, "TESTS_r05_tpu.json"), "w") as f:
        json.dump({"device": os.environ.get("MXNET_TEST_DEVICE", "tpu"),
                   "backend": backend, "rc": item["rc"],
                   "seconds": item["seconds"],
                   "captured_at": item["captured_at"],
                   "summary_tail": tail}, f, indent=1)


def run_queue(state):
    """Run every incomplete queue item; returns True when all are done."""
    os.makedirs(ART_DIR, exist_ok=True)
    for name, cmd, env_extra, timeout in QUEUE:
        if state["items"].get(name, {}).get("status") in DONE:
            continue
        log("running %s (timeout %ds)" % (name, timeout))
        out_path = os.path.join(ART_DIR, name + ".out")
        err_path = os.path.join(ART_DIR, name + ".err")
        t0 = time.time()
        rc, timed_out = run_killable(cmd, env_extra, timeout, out_path,
                                     err_path)
        if timed_out:
            status = "timeout"
        elif rc == 0:
            status = "ok"
        elif name == "tpu_suite" and rc == 1:
            # pytest rc 1 = suite ran to completion with some failures —
            # that IS capture-worthy on-chip evidence; re-running it every
            # window would burn 2.5h on a deterministic failure
            status = "completed_with_failures"
        else:
            status = "failed"
        entry = {
            "rc": rc,
            "seconds": round(time.time() - t0, 1),
            "status": status,
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
            "json": last_json_line(out_path),
        }
        # a run that fell back to cpu is NOT chip evidence — mark it so
        # it re-runs next window (bench metrics carry 'cpu' in the name;
        # the longcontext summary carries a platform field)
        j = entry["json"] or {}
        if entry["status"] == "ok" and ("cpu" in str(j.get("metric", ""))
                                        or j.get("platform") == "cpu"):
            entry["status"] = "cpu_fallback"
        state["items"][name] = entry
        save_state(state)
        log("%s -> %s (%.0fs) %s"
            % (name, entry["status"], entry["seconds"],
               json.dumps(j) if j else ""))
        if name in ("bench_gluon", "bench_gluon_nhwc"):
            maybe_promote_nhwc(state)
        if name == "tpu_suite":
            write_suite_artifact(state)
        if entry["status"] in ("timeout", "cpu_fallback"):
            # tunnel likely dropped mid-queue: verify before burning the
            # next item's timeout on a dead backend
            if not probe():
                log("backend dropped mid-queue — back to watching")
                return False
    return all(state["items"].get(n, {}).get("status") in DONE
               for n, *_ in QUEUE)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hours", type=float, default=11.0)
    ap.add_argument("--probe-interval", type=int, default=180)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()

    deadline = time.time() + args.hours * 3600
    state = load_state()
    log("watching for a chip window (deadline in %.1fh; %d/%d items done)"
        % (args.hours, sum(1 for n, *_ in QUEUE
                           if state["items"].get(n, {}).get("status")
                           in DONE), len(QUEUE)))
    while time.time() < deadline:
        if probe():
            log("chip window OPEN — running queue")
            if run_queue(state):
                log("queue COMPLETE — all items captured")
                return 0
        elif args.once:
            log("probe: backend unreachable")
            return 1
        if args.once:
            return 1
        time.sleep(args.probe_interval)
    log("deadline reached; %d/%d items captured"
        % (sum(1 for n, *_ in QUEUE
               if state["items"].get(n, {}).get("status") in DONE),
           len(QUEUE)))
    return 0 if all(state["items"].get(n, {}).get("status") in DONE
                    for n, *_ in QUEUE) else 1


if __name__ == "__main__":
    sys.exit(main())
