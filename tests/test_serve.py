"""mx.serve — fault-tolerant continuous-batching inference runtime.

Acceptance (ISSUE 8): a llama-family LM serves >= 8 concurrent streams
under continuous batching on the CPU backend with NO new prefill/decode
compiles after warm-up (asserted via telemetry.note_compile), and a
MXNET_TPU_FAULT_PLAN kill at serve.step mid-stream recovers every
in-flight stream with no lost or duplicated tokens (byte-identical
output). Paged-KV edge cases: pool exhaustion -> structured Overloaded,
block reuse after stream completion, fragmentation across many short
streams.

Serving v2 (ISSUE 13): chunked multi-stream prefill byte-matches the
monolithic reference; shared-prefix admission reuses cached prompt blocks
(refcount-exact through kill-recovery, copy-on-write at the divergence
block); speculative greedy decode is byte-identical to the
non-speculative path; sampled streams replay the same draws after a
drain; all at zero post-warm-up compiles.
"""
import functools
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.models.llama import (LlamaConfig, llama_init, llama_forward,
                                    init_kv_cache, llama_decode_step)
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.errors import RetryExhausted, is_retriable
from mxnet_tpu.serve import (DeadlineExceeded, InferenceServer, KVBlockPool,
                             Overloaded, ReplicaGroup, Request)

pytestmark = pytest.mark.serve

CFG = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                  n_kv_heads=2, hidden_dim=128, rope_theta=10000.0,
                  max_seq_len=64, dtype=jnp.float32)
PARAMS = llama_init(jax.random.PRNGKey(0), CFG)
# a deliberately different tiny draft model: near-zero accept rate, which
# is exactly what the byte-identical parity bar must survive
DRAFT_CFG = LlamaConfig(vocab_size=256, dim=32, n_layers=1, n_heads=2,
                        n_kv_heads=1, hidden_dim=64, rope_theta=10000.0,
                        max_seq_len=64, dtype=jnp.float32)
DRAFT_PARAMS = llama_init(jax.random.PRNGKey(7), DRAFT_CFG)


@pytest.fixture(autouse=True)
def _clean_planes():
    telemetry.enable()
    telemetry.reset()
    faults.deactivate()
    yield
    faults.deactivate()
    telemetry.reset()


def make_server(**kw):
    kw.setdefault("kv_blocks", 48)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_context", 32)
    return InferenceServer(PARAMS, CFG, **kw)


def prompts_for(n, lo=3, hi=12, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, CFG.vocab_size - 1,
                        size=rng.randint(lo, hi)).tolist() for _ in range(n)]


@functools.lru_cache(maxsize=1)
def _ref_decode():
    return jax.jit(functools.partial(llama_decode_step, cfg=CFG))


def reference_generate(prompt, n_new):
    """Unpaged single-stream greedy reference: llama_forward prefill +
    contiguous-cache decode loop."""
    logits = llama_forward(PARAMS, jnp.asarray([prompt], jnp.int32), CFG)
    out = [int(jnp.argmax(logits[0, -1]))]
    cache = init_kv_cache(CFG, 1, max_len=CFG.max_seq_len)
    step = _ref_decode()
    for p, t in enumerate(prompt):
        _, cache = step(PARAMS, cache, jnp.asarray([t], jnp.int32),
                        jnp.asarray(p, jnp.int32))
    while len(out) < n_new:
        pos = len(prompt) + len(out) - 1
        lg, cache = step(PARAMS, cache, jnp.asarray([out[-1]], jnp.int32),
                        jnp.asarray(pos, jnp.int32))
        out.append(int(jnp.argmax(lg[0])))
    return out


# ---------------------------------------------------------------------------
# paged KV allocator
# ---------------------------------------------------------------------------
def test_kv_pool_alloc_free_reuse():
    pool = KVBlockPool(CFG, num_blocks=8, block_size=4)
    t1 = pool.alloc("a", 10)            # 3 blocks
    assert len(t1) == 3 and pool.blocks_in_use == 3
    t2 = pool.alloc("a", 12)            # grows by 0 (3 blocks cover 12)
    assert t2 == t1
    assert pool.alloc("b", 4) and pool.blocks_in_use == 4
    assert pool.free("a") == 3
    assert pool.free("a") == 0          # idempotent
    assert pool.blocks_in_use == 1
    # freed blocks recycle (LIFO): the new stream reuses a's ids
    t3 = pool.alloc("c", 12)
    assert set(t3) <= set(t1)
    snap = telemetry.snapshot()
    assert snap["gauges"]["serve.kv.blocks_in_use"]["max"] >= 4
    assert snap["counters"]["serve.kv.freed_blocks"] == 3


def test_kv_pool_exhaustion_structured_overloaded():
    pool = KVBlockPool(CFG, num_blocks=4, block_size=4)
    pool.alloc("a", 12)                 # 3 of 4 blocks
    with pytest.raises(Overloaded) as ei:
        pool.alloc("b", 8)              # needs 2, only 1 free
    err = ei.value
    assert err.reason == "kv_exhausted"
    assert err.kv_free_blocks == 1 and err.kv_needed_blocks == 2
    assert not is_retriable(err)        # a verdict, not a transport fault
    # all-or-nothing: the failed alloc reserved NOTHING — not even an
    # empty table entry (uuid stream ids never return; entries would leak)
    assert pool.blocks_in_use == 3
    assert pool.owned_blocks("b") == []
    assert "b" not in pool._tables
    assert telemetry.snapshot()["counters"]["serve.kv.exhausted"] == 1


def test_kv_pool_fragmentation_across_short_streams():
    """Interleaved alloc/free of many short streams scatters the free-list;
    a later long stream must still get its blocks (any block serves any
    position — fragmentation cannot exist by construction)."""
    pool = KVBlockPool(CFG, num_blocks=10, block_size=4)
    for wave in range(5):
        ids = ["s%d_%d" % (wave, i) for i in range(5)]
        for sid in ids:
            pool.alloc(sid, 5)          # 2 blocks each
        for sid in ids[::2]:            # free a non-contiguous subset
            pool.free(sid)
        for sid in ids[1::2]:
            pool.free(sid)
    assert pool.blocks_in_use == 0 and pool.free_blocks == 10
    table = pool.alloc("long", 40)      # the WHOLE pool, post-churn
    assert sorted(table) == list(range(10))
    # the table is not contiguous in allocation order (churned free-list)
    assert table != sorted(table)


def test_chunk_geometry_defaults(monkeypatch):
    from mxnet_tpu.serve import (default_chunk_size, default_prefill_rows,
                                 default_spec_k)
    monkeypatch.setenv("MXNET_TPU_SERVE_CHUNK", "24")
    monkeypatch.setenv("MXNET_TPU_SERVE_PREFILL_ROWS", "6")
    monkeypatch.setenv("MXNET_TPU_SERVE_SPEC_K", "2")
    assert default_chunk_size() == 24
    assert default_prefill_rows() == 6
    assert default_spec_k() == 2
    monkeypatch.setenv("MXNET_TPU_SERVE_CHUNK", "bogus")
    assert default_chunk_size() == 16
    server = make_server(chunk_size=4, prefill_rows=3)
    assert server.programs.chunk_size == 4
    assert server.programs.prefill_rows == 3
    assert server.prefill_budget == 12          # rows x chunk by default
    assert "chunk" in server.programs.program_names
    assert "draft_k" not in server.programs.program_names  # no draft model


# ---------------------------------------------------------------------------
# correctness: paged continuous batching vs the unpaged reference
# ---------------------------------------------------------------------------
def test_single_stream_matches_reference():
    server = make_server().warmup()
    prompt = [3, 17, 42, 99, 7]
    h = server.submit(Request(prompt, max_new_tokens=6))
    server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 6)
    assert h.ttft_ms is not None and h.ttft_ms > 0
    assert len(h.tpot_ms) == 5


def test_eight_concurrent_streams_no_retrace():
    """THE acceptance test: >= 8 concurrent streams under continuous
    batching, every output matching its single-stream reference, and zero
    new prefill/decode compiles after warm-up."""
    server = make_server(max_batch=8, kv_blocks=64).warmup()
    warm = len(telemetry.recent_compiles())
    prompts = prompts_for(10)
    budgets = [5 + i % 4 for i in range(10)]
    handles = [server.submit(Request(p, max_new_tokens=b))
               for p, b in zip(prompts, budgets)]
    server.run()
    for h, p, b in zip(handles, prompts, budgets):
        assert h.result(timeout=10) == reference_generate(p, b)
    # continuous batching actually batched (streams shared decode steps)
    snap = telemetry.snapshot()
    assert snap["gauges"]["serve.batch_occupancy"]["max"] >= 8
    assert snap["counters"]["serve.decode_steps"] < sum(budgets)
    # no mid-traffic compiles: the compile ring did not grow after warmup
    new = [n for n, _ in telemetry.recent_compiles()][warm:]
    assert new == [], "post-warmup compiles: %s" % new
    assert "serve.retrace" not in snap["counters"]


def test_fragmented_pool_end_to_end():
    """Many short streams churn the free-list, then a long stream spans
    non-contiguous blocks — its output must still match the reference."""
    server = make_server(max_batch=2, kv_blocks=6, block_size=4,
                         max_context=32).warmup()
    for p in prompts_for(6, lo=3, hi=8, seed=1):
        server.submit(Request(p, max_new_tokens=3))
    server.run()
    long_prompt = prompts_for(1, lo=14, hi=15, seed=2)[0]
    h = server.submit(Request(long_prompt, max_new_tokens=8))
    server.run()
    blocks = server.pool.owned_blocks(h.id)
    assert blocks == []                 # retired: blocks recycled
    assert h.result(timeout=10) == reference_generate(long_prompt, 8)


# ---------------------------------------------------------------------------
# admission control / load shedding
# ---------------------------------------------------------------------------
def test_queue_full_sheds_with_overloaded():
    server = make_server(queue_cap=2).warmup()
    server.submit(Request([1, 2], max_new_tokens=2))
    server.submit(Request([1, 2], max_new_tokens=2))
    with pytest.raises(Overloaded) as ei:
        server.submit(Request([1, 2], max_new_tokens=2))
    assert ei.value.reason == "queue_full"
    assert ei.value.queue_depth == 2
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.shed"] == 1 and snap["serve.shed.queue_full"] == 1
    server.run()                        # the two admitted still finish


def test_oversized_request_shed_at_submit():
    server = make_server()              # pool: 48x8; max_context 32
    with pytest.raises(Overloaded) as ei:
        server.submit(Request([1] * 8, max_new_tokens=1000))
    assert ei.value.reason == "too_large"
    assert telemetry.snapshot()["counters"]["serve.shed.too_large"] == 1
    # the max_context bound holds independently of the pool: a request
    # whose worst-case re-prefill exceeds the model context sheds even
    # when the blocks would fit
    tight = make_server(max_context=20)
    with pytest.raises(Overloaded) as ei:
        tight.submit(Request([1] * 5, max_new_tokens=18))   # 22 > 20
    assert ei.value.reason == "too_large"


def test_kv_backpressure_defers_not_sheds():
    """Two requests whose worst-case contexts cannot coexist in the pool:
    the second WAITS (backpressure) and completes after the first frees
    its blocks — no shed, no OOM."""
    server = make_server(kv_blocks=5, block_size=8, max_batch=2,
                         max_context=32).warmup()
    p1, p2 = prompts_for(2, lo=8, hi=9, seed=3)
    h1 = server.submit(Request(p1, max_new_tokens=16))   # 3 blocks
    h2 = server.submit(Request(p2, max_new_tokens=16))   # 3 blocks: waits
    server.run()
    assert h1.result(timeout=10) == reference_generate(p1, 16)
    assert h2.result(timeout=10) == reference_generate(p2, 16)
    snap = telemetry.snapshot()["counters"]
    assert "serve.shed" not in snap
    assert snap["serve.completed"] == 2


def test_deadline_expires_in_queue():
    server = make_server(max_batch=1).warmup()
    slow = server.submit(Request([1, 2, 3], max_new_tokens=4))
    h = server.submit(Request([4, 5], max_new_tokens=2, deadline_s=0.001))
    time.sleep(0.01)
    server.run()
    slow.result(timeout=10)
    with pytest.raises(DeadlineExceeded) as ei:
        h.result(timeout=10)
    assert ei.value.tokens == []
    assert telemetry.snapshot()["counters"]["serve.shed.deadline"] == 1


def test_deadline_mid_stream_carries_partial_output():
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=24,
                              deadline_s=0.08))
    # slow every step so the deadline lands mid-stream
    with faults.inject("serve.step:latency:*:0.02"):
        server.run()
    with pytest.raises(DeadlineExceeded) as ei:
        h.result(timeout=10)
    assert 0 < len(ei.value.tokens) < 24
    assert ei.value.tokens == h.tokens


# ---------------------------------------------------------------------------
# fault tolerance: the robustness headline
# ---------------------------------------------------------------------------
def _serve_all(server, prompts, budgets):
    handles = [server.submit(Request(p, max_new_tokens=b))
               for p, b in zip(prompts, budgets)]
    server.run()
    return [h.result(timeout=30) for h in handles], handles


def test_kill_serve_step_mid_stream_byte_identical():
    """THE chaos acceptance test: MXNET_TPU_FAULT_PLAN kills serve.step
    twice mid-stream; every in-flight stream drains, requeues, resumes by
    re-prefill — and the full output is byte-identical to the unfaulted
    run (no token lost, none duplicated)."""
    prompts = prompts_for(8, seed=4)
    budgets = [5 + i % 4 for i in range(8)]
    baseline, _ = _serve_all(make_server(max_batch=4, kv_blocks=64).warmup(),
                             prompts, budgets)
    telemetry.reset()
    server = make_server(max_batch=4, kv_blocks=64).warmup()
    os.environ["MXNET_TPU_FAULT_PLAN"] = \
        "serve.step:error:3;serve.step:error:6"
    try:
        faults.activate()
        chaos, handles = _serve_all(server, prompts, budgets)
    finally:
        del os.environ["MXNET_TPU_FAULT_PLAN"]
        faults.deactivate()
    assert chaos == baseline
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.recoveries"] == 2
    assert snap["serve.requeued_streams"] >= 1
    assert snap["resilience.faults_injected"] >= 2
    assert sum(h.requeues for h in handles) == snap["serve.requeued_streams"]


def test_retry_budget_exhausted_fails_stream():
    server = make_server().warmup()
    doomed = server.submit(Request([1, 2, 3], max_new_tokens=8, retries=0))
    survivor = server.submit(Request([4, 5, 6], max_new_tokens=4))
    with faults.inject("serve.step:error:2"):
        server.run()
    with pytest.raises(RetryExhausted):
        doomed.result(timeout=10)
    assert survivor.result(timeout=10) == reference_generate([4, 5, 6], 4)
    assert telemetry.snapshot()["counters"]["serve.failed"] == 1


def test_watchdog_converts_hang_to_recovery():
    """An injected hang inside serve.step becomes a StallError (not a
    frozen replica) and the scheduler recovers the stream."""
    server = make_server(step_deadline_s=0.25).warmup()
    prompt = [7, 8, 9]
    h = server.submit(Request(prompt, max_new_tokens=4))
    with faults.inject("serve.step:hang:2:30"):
        server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 4)
    snap = telemetry.snapshot()["counters"]
    assert snap["resilience.stalls.serve.step"] == 1
    assert snap["serve.recoveries"] == 1


def test_replica_group_survives_replica_death():
    """ResilientRunner semantics at group level: a replica killed with a
    spent restart budget drains its streams to the shared queue; the
    survivor finishes them — byte-identical, group still healthy."""
    prompts = prompts_for(8, seed=5)
    budgets = [6] * 8
    baseline, _ = _serve_all(make_server(max_batch=4, kv_blocks=64).warmup(),
                             prompts, budgets)
    telemetry.reset()
    group = ReplicaGroup(PARAMS, CFG, replicas=2, kv_blocks=48,
                         block_size=8, max_batch=4, max_context=32,
                         max_restarts=0).warmup()
    with faults.inject("serve.step:preempt:3"):
        group.start()
        handles = [group.submit(Request(p, max_new_tokens=b))
                   for p, b in zip(prompts, budgets)]
        out = [h.result(timeout=30) for h in handles]
        assert group.drain(timeout=10)
    group.stop()
    assert out == baseline
    assert group.alive_replicas == 1
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.replica_deaths"] == 1
    assert snap["serve.recoveries"] == 1


def test_fault_mid_admission_loses_no_stream():
    """A fault landing INSIDE _admit (after the queue pop, during the KV
    reservation — where an async watchdog stall would land) must drain
    the half-admitted stream back to the queue, not lose it."""
    from mxnet_tpu.resilience.errors import InjectedFault
    server = make_server().warmup()
    real_admit = server.pool.admit
    state = {"fired": False}

    def flaky_admit(stream_id, n_tokens, context=None):
        if not state["fired"]:
            state["fired"] = True
            raise InjectedFault("mid-admission fault", site="serve.step")
        return real_admit(stream_id, n_tokens, context=context)

    server.pool.admit = flaky_admit
    prompt = [5, 6, 7]
    h = server.submit(Request(prompt, max_new_tokens=4))
    server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 4)
    assert h.requeues == 1
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.requeued_streams"] == 1
    assert server.pool.blocks_in_use == server.pool.prefix_blocks
    assert server.pool.reconcile() == 0     # nothing leaked or torn


def test_nonretriable_death_drains_streams():
    """A NON-retriable escape from the step (a bug, a device loss) kills
    the replica but still drains its in-flight streams to the shared
    queue — a fresh replica on the same queue finishes them."""
    server = make_server().warmup()
    prompt = [9, 8, 7]
    h = server.submit(Request(prompt, max_new_tokens=4))
    boom = {"armed": True}
    real_decode = server.programs.decode

    def bad_decode(*args):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("simulated device loss")
        return real_decode(*args)

    server.programs.decode = bad_decode
    with pytest.raises(RuntimeError):
        server.run()
    assert server.dead
    assert not h.done()                     # not lost, not failed: queued
    survivor = make_server(queue=server.queue).warmup()
    survivor.run()
    assert h.result(timeout=10) == reference_generate(prompt, 4)
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.replica_deaths"] == 1
    assert snap["serve.requeued_streams"] == 1


def test_recovery_async_windows():
    """White-box regression for the async-StallError windows: (1) a stream
    caught in BOTH _admitting and a slot drains once, not twice; (2) a
    requeued stream that already emitted its full budget retires without
    re-prefilling an extra token; (3) pool buffers deleted by a fault
    between a donating program call and update() are re-materialized."""
    from mxnet_tpu.resilience.errors import InjectedFault
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=4))
    server.step()                       # admit + first decode
    stream = server._slots[0]
    assert stream is not None
    # (1) fault landed between slot assignment and the _admitting clear
    server._admitting = stream
    server._recover(InjectedFault("window", site="serve.step"))
    assert len(server.queue) == 1       # requeued ONCE
    assert h.requeues == 1
    # (1b) fault landed AFTER a requeue had already handed ownership to
    # the queue (or a sibling replica): recovery must not requeue again —
    # the ownership check is atomic under the queue lock
    server._admitting = stream          # still queue-owned
    server._recover(InjectedFault("window1b", site="serve.step"))
    assert len(server.queue) == 1
    assert h.requeues == 1
    # (2) pretend the fault also landed after the final token but before
    # _finish_check: the stream comes back already complete
    h.tokens.extend([0] * (4 - len(h.tokens)))
    # (3) and the donating call's outputs never reached pool.update
    for leaf in jax.tree_util.tree_leaves(server.pool.pools):
        leaf.delete()
    # (4) and an alloc was torn mid-flight: blocks popped off the
    # free-list that never reached any table
    torn = [server.pool._free.pop() for _ in range(3)]
    assert torn and server.pool.free_blocks < server.pool.num_blocks
    server._recover(InjectedFault("window2", site="serve.step"))
    assert not any(x.is_deleted()
                   for x in jax.tree_util.tree_leaves(server.pool.pools))
    assert server.pool.free_blocks == server.pool.num_blocks  # reconciled
    server.run()
    assert h.result(timeout=10) == h.tokens and len(h.tokens) == 4
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.kv.storage_resets"] == 1
    assert snap["serve.completed"] == 1


# ---------------------------------------------------------------------------
# per-request tracing (ISSUE 12 tentpole)
# ---------------------------------------------------------------------------
def test_request_trace_accounts_wall_clock_single_replica():
    """Every served request leaves a timeline in the last-N ring whose
    spans tile its wall clock (queue + prefill + per-token decode)."""
    server = make_server(max_batch=2).warmup()
    prompts = prompts_for(3, seed=9)
    handles = [server.submit(Request(p, max_new_tokens=4))
               for p in prompts]
    server.run()
    for h in handles:
        h.result(timeout=10)
    traces = {t["request_id"]: t for t in telemetry.request_traces()}
    assert set(traces) == {h.id for h in handles}
    for h in handles:
        t = traces[h.id]
        assert t["outcome"] == "completed"
        assert t["tokens"] == 4
        # payload ttft is rounded to 3 decimals for the JSON dump
        assert t["ttft_ms"] == pytest.approx(h.ttft_ms, abs=5e-4)
        assert t["accounted_ms"] >= 0.95 * t["wall_ms"]
        assert set(t["phases_ms"]) == {"queue", "prefill", "decode"}
        # decode emitted one span per token after the prefill's first
        decodes = [s for s in t["spans"] if s["name"] == "decode"]
        assert len(decodes) == 3
        assert t["replicas"] == ["replica0"]


def test_request_trace_survives_replica_kill_95pct_accounted():
    """THE ISSUE 12 acceptance test: a deliberately delayed request whose
    replica is killed mid-stream still yields ONE RequestTrace — continued
    on the surviving replica — whose spans (queue-wait + prefill + decode
    + recovery) account for >= 95% of its wall clock."""
    group = ReplicaGroup(PARAMS, CFG, replicas=2, kv_blocks=48,
                         block_size=8, max_batch=4, max_context=32,
                         max_restarts=0).warmup()
    prompts = prompts_for(6, seed=10)
    # every step delayed (the "deliberately delayed request"), and the 4th
    # step check is a kill — preempt FIRST: the plan fires the first
    # matching entry, so the wildcard latency must come after it
    with faults.inject("serve.step:preempt:4;serve.step:latency:*:0.01"):
        group.start()
        handles = [group.submit(Request(p, max_new_tokens=6))
                   for p in prompts]
        for h in handles:
            h.result(timeout=30)
        assert group.drain(timeout=10)
    group.stop()
    assert group.alive_replicas == 1
    traces = {t["request_id"]: t for t in telemetry.request_traces()}
    for h in handles:
        t = traces[h.id]
        assert t["outcome"] == "completed"
        assert t["accounted_ms"] >= 0.95 * t["wall_ms"], t
    recovered = [traces[h.id] for h in handles if h.requeues > 0]
    assert recovered, "the kill drained no in-flight stream"
    # the killed replica's streams resumed elsewhere: recovery spans are
    # on the timeline and the trace names BOTH replicas it crossed
    assert any("recovery" in t["phases_ms"] for t in recovered)
    assert any(len(set(t["replicas"])) == 2 for t in recovered)


def test_deadline_exceeded_embeds_request_trace():
    """A shed request carries its own timeline: DeadlineExceeded's
    request_trace names where the time went."""
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=24,
                              deadline_s=0.08))
    with faults.inject("serve.step:latency:*:0.02"):
        server.run()
    with pytest.raises(DeadlineExceeded) as ei:
        h.result(timeout=10)
    tr = ei.value.request_trace
    assert tr is not None and tr["outcome"] == "deadline"
    assert tr["request_id"] == h.id
    assert tr["tokens"] == len(ei.value.tokens)
    assert tr["accounted_ms"] >= 0.95 * tr["wall_ms"]
    # the same payload is queryable from the ring (the /requests body)
    ring = {t["request_id"]: t for t in telemetry.request_traces()}
    assert ring[h.id]["outcome"] == "deadline"


def test_shed_requests_land_in_ring():
    server = make_server(queue_cap=1).warmup()
    with pytest.raises(Overloaded):
        server.submit(Request([1] * 8, max_new_tokens=1000))  # too_large
    server.submit(Request([1, 2], max_new_tokens=2))
    with pytest.raises(Overloaded):
        server.submit(Request([3, 4], max_new_tokens=2))      # queue_full
    outcomes = [t["outcome"] for t in telemetry.request_traces()]
    assert "shed.too_large" in outcomes
    assert "shed.queue_full" in outcomes
    server.run()


def test_request_rows_in_chrome_dump(tmp_path):
    """Completed requests replay into the chrome dump as their own rows:
    spans named req[<id>].<phase> under a per-request tid."""
    import json
    server = make_server(max_batch=2).warmup()
    handles = [server.submit(Request(p, max_new_tokens=3))
               for p in prompts_for(2, seed=12)]
    server.run()
    path = telemetry.dump_trace(str(tmp_path / "serve_trace.json"))
    obj = json.load(open(path))
    rows = [e for e in obj["traceEvents"]
            if e.get("ph") == "X" and e.get("cat") == "request"]
    assert rows
    assert {e["tid"] for e in rows} == {
        __import__("zlib").crc32(h.id.encode()) & 0x3fffffff
        for h in handles}
    names = {e["name"] for e in rows}
    for h in handles:
        assert "req[%s].prefill" % h.id in names
        assert "req[%s].decode" % h.id in names


def test_flight_records_name_in_flight_requests():
    """ISSUE 12 satellite: every serve.step flight record carries the
    active/completed request ids, so a stall post-mortem names the
    in-flight requests instead of just counters."""
    from mxnet_tpu.telemetry import flight as _flight
    server = make_server(max_batch=2).warmup()
    handles = [server.submit(Request(p, max_new_tokens=4))
               for p in prompts_for(2, seed=13)]
    server.run()
    recs = [r for r in telemetry.flight_records()
            if r["site"] == "serve.step"]
    assert recs and all("active_requests" in r for r in recs)
    seen = {i for r in recs for i in (r["active_requests"]
                                      + r.get("completed_requests", []))}
    assert {h.id for h in handles} <= seen
    rendered = _flight.format_records(recs)
    assert any(h.id in rendered for h in handles)


def test_request_tracing_knob_inert(monkeypatch):
    """MXNET_TPU_SERVE_TRACE=0 (the bench's A/B lever): NULL traces, an
    empty ring, no request spans — while the rest of telemetry stays on."""
    from mxnet_tpu.telemetry import request_trace as _reqtrace
    monkeypatch.setenv("MXNET_TPU_SERVE_TRACE", "0")
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=3))
    assert h.trace is _reqtrace.NULL_TRACE
    server.run()
    h.result(timeout=10)
    assert telemetry.request_traces() == []
    assert not any(n.startswith("req[")
                   for n, *_ in telemetry.span_events())
    # aggregate serving telemetry is unaffected
    assert telemetry.snapshot()["counters"]["serve.completed"] == 1


# ---------------------------------------------------------------------------
# telemetry / no-retrace plumbing
# ---------------------------------------------------------------------------
def test_serving_telemetry_and_flight_records():
    server = make_server().warmup()
    for p in prompts_for(3, seed=6):
        server.submit(Request(p, max_new_tokens=4))
    server.run()
    snap = telemetry.snapshot()
    hists = snap["histograms"]
    assert hists["serve.ttft_ms"]["count"] == 3
    assert hists["serve.tpot_ms"]["count"] > 0
    assert hists["serve.step_ms"]["count"] > 0
    assert snap["gauges"]["serve.tokens_per_s"]["value"] > 0
    # the flight recorder saw the serving path (step_event wiring)
    sites = {r["site"] for r in telemetry.flight_records()}
    assert "serve.step" in sites
    # and the rolling quantile tracker covers serve.step
    assert telemetry.step_quantiles("serve.step")["n"] > 0


def test_post_warmup_signature_miss_counts_as_retrace():
    """White-box: an executable that escaped warm-up is handled (the
    request still completes) but counted and reported like a CachedOp
    retrace."""
    server = make_server().warmup()
    del server.programs._exec["chunk"]          # simulate the escape
    prompt = [1, 2, 3]
    h = server.submit(Request(prompt, max_new_tokens=3))
    server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 3)
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.retrace"] == 1
    names = [n for n, _ in telemetry.recent_compiles()]
    assert "serve.chunk(retrace)" in names


def test_duplicate_request_ids_do_not_share_kv():
    """Two in-flight requests reusing one caller-supplied request_id must
    not share a block table (the pool is keyed per stream, not per id)."""
    server = make_server(max_batch=2).warmup()
    p1, p2 = prompts_for(2, seed=7)
    h1 = server.submit(Request(p1, max_new_tokens=5, request_id="dup"))
    h2 = server.submit(Request(p2, max_new_tokens=5, request_id="dup"))
    server.run()
    assert h1.result(timeout=10) == reference_generate(p1, 5)
    assert h2.result(timeout=10) == reference_generate(p2, 5)
    # only the prefix index may still hold blocks (cached full prompt
    # blocks outlive their stream by design)
    assert server.pool.blocks_in_use == server.pool.prefix_blocks


def test_zero_deadline_means_expired_not_disabled():
    server = make_server().warmup()
    h = server.submit(Request([1, 2], max_new_tokens=2, deadline_s=0))
    server.run()
    with pytest.raises(DeadlineExceeded):
        h.result(timeout=10)


def test_admit_fault_site_wired():
    server = make_server().warmup()
    with faults.inject("serve.admit:error:1"):
        with pytest.raises(Exception) as ei:
            server.submit(Request([1, 2], max_new_tokens=2))
    assert "serve.admit" in str(ei.value)


# ---------------------------------------------------------------------------
# serving v2 (ISSUE 13): chunked prefill, prefix sharing, spec, sampling
# ---------------------------------------------------------------------------
def test_chunked_prefill_matches_reference_across_geometries():
    """A long prompt split over many chunk windows — and several window
    geometries — always byte-matches the monolithic reference."""
    prompt = prompts_for(1, lo=20, hi=21, seed=20)[0]
    ref = reference_generate(prompt, 5)
    for chunk, rows in ((4, 1), (4, 3), (8, 2), (32, 4)):
        server = make_server(chunk_size=chunk, prefill_rows=rows).warmup()
        h = server.submit(Request(prompt, max_new_tokens=5))
        server.run()
        assert h.result(timeout=10) == ref, (chunk, rows)
        snap = telemetry.snapshot()["counters"]
        assert snap["serve.prefill_chunks"] >= -(-len(prompt) // chunk)
        telemetry.reset()


def test_burst_prefill_batches_windows():
    """THE chunked-prefill win: a burst of arrivals prefills together —
    fewer prefill program dispatches than streams — instead of
    serializing TTFT behind batch-1 programs."""
    server = make_server(max_batch=8, kv_blocks=64, prefill_rows=4,
                         chunk_size=16).warmup()
    prompts = prompts_for(8, lo=6, hi=12, seed=21)
    handles = [server.submit(Request(p, max_new_tokens=4))
               for p in prompts]
    server.run()
    for h, p in zip(handles, prompts):
        assert h.result(timeout=10) == reference_generate(p, 4)
    snap = telemetry.snapshot()
    windows = snap["histograms"]["serve.prefill_ms"]["count"]
    assert windows < len(prompts), \
        "burst prefills did not batch (%d windows)" % windows
    assert snap["counters"]["serve.prefill_chunks"] >= len(prompts)


def test_prefix_sharing_reuses_system_prompt_blocks():
    """N users of one system prompt: the first stream pays the prefill,
    later streams share its cached blocks (refcounted) and skip those
    positions — outputs still byte-match the unshared reference."""
    server = make_server(max_batch=2, kv_blocks=64).warmup()
    sysp = prompts_for(1, lo=16, hi=17, seed=22)[0]     # 2 full blocks
    tails = prompts_for(4, lo=2, hi=5, seed=23)
    first = server.submit(Request(sysp + tails[0], max_new_tokens=4))
    server.run()                        # prefix now cached
    handles = [server.submit(Request(sysp + t, max_new_tokens=4))
               for t in tails[1:]]
    server.run()
    assert first.result(timeout=10) == reference_generate(sysp + tails[0], 4)
    for h, t in zip(handles, tails[1:]):
        assert h.result(timeout=10) == reference_generate(sysp + t, 4)
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.prefix.hits"] >= 3
    assert snap["serve.prefix.blocks_shared"] >= 6      # 2 blocks x 3
    # every stream retired: only the index holds blocks, refcounts exact
    assert server.pool.blocks_in_use == server.pool.prefix_blocks
    assert server.pool.reconcile() == 0


def test_prefix_cow_at_divergence_block():
    """Two prompts diverging INSIDE a block: the divergence block is
    copied-on-write (counted) and only the true tail re-prefills."""
    base = prompts_for(1, lo=16, hi=17, seed=24)[0]
    p1 = base + [1, 2]
    p2 = base[:12] + [9, 9, 9]          # diverges inside block 1
    server = make_server(max_batch=1, kv_blocks=64).warmup()
    h1 = server.submit(Request(p1, max_new_tokens=4))
    server.run()
    h2 = server.submit(Request(p2, max_new_tokens=4))
    server.run()
    assert h1.result(timeout=10) == reference_generate(p1, 4)
    assert h2.result(timeout=10) == reference_generate(p2, 4)
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.prefix.cow"] >= 1
    assert snap["serve.prefix.hits"] >= 1


def test_prefix_eviction_under_pool_pressure():
    """Cached prefixes are best-effort: when a fresh allocation would
    fail, least-recently-matched index entries are evicted and the
    request still completes."""
    server = make_server(max_batch=1, kv_blocks=6, block_size=8,
                         max_context=48).warmup()
    p1 = prompts_for(1, lo=16, hi=17, seed=25)[0]
    h1 = server.submit(Request(p1, max_new_tokens=4))
    server.run()
    assert server.pool.prefix_blocks >= 1
    big = prompts_for(1, lo=30, hi=31, seed=26)[0]      # needs ~the pool
    h2 = server.submit(Request(big, max_new_tokens=8))
    server.run()
    assert h2.result(timeout=10) == reference_generate(big, 8)
    assert telemetry.snapshot()["counters"]["serve.prefix.evictions"] >= 1
    h1.result(timeout=10)


def test_prefix_eviction_never_recycles_own_match():
    """Regression: under pressure, admission's eviction pass must not
    reclaim the very blocks it just matched as this stream's shared
    prefix — the freed block would be popped right back as a 'fresh'
    block, the table holding the same id twice and the stream clobbering
    its own shared KV. Protecting the match costs nothing (sharing s
    blocks shrinks demand by the same s an eviction would free), so a
    shortfall here is a true Overloaded — with NOTHING reserved."""
    pool = KVBlockPool(CFG, num_blocks=5, block_size=4)
    base = list(range(100, 108))        # 2 full blocks
    ta, _, _ = pool.admit("a", 8, context=base)
    pool.register_prefix("a", base)
    pool.free("a")                      # index-only refs on ta[0], ta[1]
    pool.admit("live", 8)               # 2 blocks held by a live stream
    assert pool.free_blocks == 1
    with pytest.raises(Overloaded):     # 4 blocks can never fit: 2 are
        pool.admit("b", 16, context=base + [1] * 8)  # live, match kept
    assert pool.owned_blocks("b") == []          # nothing reserved
    assert pool.prefix_blocks == 2               # match NOT evicted
    assert pool.reconcile() == 0                 # refcounts exact
    # backpressure resolves it: the live stream frees, admission then
    # shares the (still-cached) prefix with no duplicate block ids
    pool.free("live")
    tb, fs, cow = pool.admit("b", 16, context=base + [1] * 8)
    assert len(tb) == len(set(tb)) == 4, tb
    assert fs == 8 and tb[:2] == ta[:2]
    pool.free("b")
    assert pool.blocks_in_use == pool.prefix_blocks


def test_prefix_sharing_knob_inert(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SERVE_PREFIX", "0")
    server = make_server().warmup()
    p = prompts_for(1, lo=10, hi=11, seed=27)[0]
    for _ in range(2):
        h = server.submit(Request(p, max_new_tokens=3))
        server.run()
        h.result(timeout=10)
    snap = telemetry.snapshot()["counters"]
    assert "serve.prefix.lookups" not in snap
    assert server.pool.prefix_blocks == 0


def make_spec_server(identity=False, **kw):
    kw.setdefault("kv_blocks", 64)
    kw.setdefault("max_batch", 4)
    if identity:
        kw.update(draft_params=PARAMS, draft_cfg=CFG)
    else:
        kw.update(draft_params=DRAFT_PARAMS, draft_cfg=DRAFT_CFG)
    kw.setdefault("spec_k", 3)
    return make_server(**kw)


def test_spec_decode_byte_identical_to_plain_greedy():
    """THE spec acceptance bar: draft-k/verify greedy decode emits the
    exact token streams of the non-speculative path — with a random
    draft (accept ~0) AND an identity draft (accept 1.0) — at zero
    post-warm-up compiles."""
    prompts = prompts_for(6, seed=28)
    budgets = [5 + i % 3 for i in range(6)]
    baseline, _ = _serve_all(make_server(max_batch=4,
                                         kv_blocks=64).warmup(),
                             prompts, budgets)
    for identity in (False, True):
        telemetry.reset()
        server = make_spec_server(identity=identity).warmup()
        warm = len(telemetry.recent_compiles())
        out, _ = _serve_all(server, prompts, budgets)
        assert out == baseline, "spec output diverged (identity=%s)" \
            % identity
        new = [n for n, _ in telemetry.recent_compiles()][warm:]
        assert new == [], new
        snap = telemetry.snapshot()["counters"]
        assert snap["serve.spec.rounds"] >= 1
        assert snap["serve.spec.drafted"] == (snap["serve.spec.accepted"]
                                              + snap["serve.spec.rejected"])
        rate = snap["serve.spec.accepted"] / snap["serve.spec.drafted"]
        if identity:
            # the draft IS the target: every draft must verify (this is
            # the no-stale-KV invariant, not a modeling claim)
            assert rate == 1.0, rate
    telemetry.reset()


def test_spec_mixed_with_sampled_streams():
    """Sampled streams bypass the draft/verify loop (spec stays
    greedy-verify) but decode alongside spec streams — and their draws
    match a spec-free server's draws exactly."""
    prompts = prompts_for(4, seed=29)
    plain = make_server(max_batch=4, kv_blocks=64).warmup()
    ph = [plain.submit(Request(p, max_new_tokens=5, request_id="r%d" % i,
                               temperature=0.8 if i % 2 else 0.0))
          for i, p in enumerate(prompts)]
    plain.run()
    expected = [h.result(timeout=10) for h in ph]
    telemetry.reset()
    server = make_spec_server(identity=True).warmup()
    sh = [server.submit(Request(p, max_new_tokens=5, request_id="r%d" % i,
                                temperature=0.8 if i % 2 else 0.0))
          for i, p in enumerate(prompts)]
    server.run()
    assert [h.result(timeout=10) for h in sh] == expected
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.spec.rounds"] >= 1   # greedy streams rode spec


def test_sampling_deterministic_and_filtered():
    """Per-stream draws are a pure function of (seed, position): reruns
    replay them; top_k=1 collapses to greedy; a different seed moves."""
    prompt = prompts_for(1, seed=30)[0]

    def run_once(**kw):
        server = make_server(max_batch=1).warmup()
        h = server.submit(Request(prompt, max_new_tokens=6,
                                  request_id="fixed", **kw))
        server.run()
        return h.result(timeout=10)

    greedy = run_once()
    a = run_once(temperature=0.9, seed=11)
    assert a == run_once(temperature=0.9, seed=11)
    assert a != run_once(temperature=0.9, seed=12)
    assert run_once(temperature=0.9, top_k=1, seed=11) == greedy
    # id-derived default seed: same request_id -> same draws
    assert run_once(temperature=0.9) == run_once(temperature=0.9)
    with pytest.raises(ValueError):
        Request([1], top_p=0.0)
    with pytest.raises(ValueError):
        Request([1], top_k=-1)


def test_sampled_stream_kill_recovery_byte_identical():
    """Kill-recovery replay for SAMPLED streams: the position-keyed draws
    make the resumed stream emit the same tokens the unfaulted run
    would."""
    prompts = prompts_for(4, seed=31)
    kw = dict(max_new_tokens=6, temperature=0.7, top_p=0.9)
    server = make_server(max_batch=2, kv_blocks=64).warmup()
    handles = [server.submit(Request(p, seed=40 + i, **kw))
               for i, p in enumerate(prompts)]
    server.run()
    baseline = [h.result(timeout=10) for h in handles]
    telemetry.reset()
    server = make_server(max_batch=2, kv_blocks=64).warmup()
    with faults.inject("serve.step:error:3"):
        handles = [server.submit(Request(p, seed=40 + i, **kw))
                   for i, p in enumerate(prompts)]
        server.run()
    assert [h.result(timeout=10) for h in handles] == baseline
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.recoveries"] == 1
    assert snap["serve.requeued_streams"] >= 1


def test_spec_and_prefix_survive_replica_kill_exact_refcounts():
    """THE ISSUE 13 recovery acceptance: replicas killed mid-stream under
    spec decoding + shared prefixes resume byte-identical, with the
    shared-prefix refcounts reconciled exactly (no leaked or double-freed
    blocks) and zero post-warm-up compiles."""
    sysp = prompts_for(1, lo=16, hi=17, seed=32)[0]
    tails = prompts_for(6, lo=2, hi=5, seed=33)
    prompts = [sysp + t for t in tails]
    budgets = [6] * 6
    baseline, _ = _serve_all(
        make_spec_server(identity=True, max_batch=4).warmup(),
        prompts, budgets)
    telemetry.reset()
    server = make_spec_server(identity=True, max_batch=4).warmup()
    warm = len(telemetry.recent_compiles())
    os.environ["MXNET_TPU_FAULT_PLAN"] = \
        "serve.step:error:3;serve.step:error:6"
    try:
        faults.activate()
        chaos, handles = _serve_all(server, prompts, budgets)
    finally:
        del os.environ["MXNET_TPU_FAULT_PLAN"]
        faults.deactivate()
    assert chaos == baseline
    new = [n for n, _ in telemetry.recent_compiles()][warm:]
    assert new == [], new
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.recoveries"] == 2
    # refcounts exact: a reconcile finds NOTHING to fix, and the only
    # live blocks are the index's
    assert server.pool.reconcile() == 0
    assert server.pool.blocks_in_use == server.pool.prefix_blocks
    assert snap.get("serve.prefix.hits", 0) >= 1


def test_recovery_storage_reset_clears_prefix_cache():
    """White-box: when recovery re-materializes donated-away pool storage
    (fresh zeros), every cached prefix must be dropped with it — a later
    match would hand out garbage KV."""
    from mxnet_tpu.resilience.errors import InjectedFault
    server = make_server(max_batch=1).warmup()
    p = prompts_for(1, lo=12, hi=13, seed=34)[0]
    h = server.submit(Request(p, max_new_tokens=3))
    server.run()
    h.result(timeout=10)
    assert server.pool.prefix_blocks >= 1
    for leaf in jax.tree_util.tree_leaves(server.pool.pools):
        leaf.delete()
    server._recover(InjectedFault("window", site="serve.step"))
    assert server.pool.prefix_blocks == 0
    assert server.pool.blocks_in_use == 0
    # and the cache rebuilds from the next completed prefill
    h2 = server.submit(Request(p, max_new_tokens=3))
    server.run()
    assert h2.result(timeout=10) == h.result()
    assert server.pool.prefix_blocks >= 1


def test_spec_verify_window_respects_reserved_range():
    """Regression: near the end of a stream's budget the verify window
    p..p+k would overrun the stream's reserved positions; the gather
    clamp then redirects those writes into its LAST real block,
    overwriting valid KV rows the same round still reads. Overflow
    columns must ride position -1 (dropped), capped at the remaining
    budget — asserted by byte-parity on a stream whose worst-case
    context exactly fills max_context."""
    # geometry chosen so the LAST spec round starts at p = 29 with spec_k
    # = 3: its unmasked window reaches position 32 == max_context, one
    # past the reserved range (a 12-token prompt aligns the rounds so
    # the window never overruns — 13 breaks the alignment)
    prompt = prompts_for(1, lo=13, hi=14, seed=35)[0]
    budget = 32 - len(prompt) + 1       # prompt + budget - 1 == 32
    server = make_server(max_batch=1, kv_blocks=64).warmup()
    h = server.submit(Request(prompt, max_new_tokens=budget))
    server.run()
    baseline = h.result(timeout=20)
    spec = make_spec_server(identity=True, max_batch=1).warmup()
    h2 = spec.submit(Request(prompt, max_new_tokens=budget))
    spec.run()
    assert h2.result(timeout=20) == baseline


def test_chunk_writes_drop_past_table_range():
    """White-box program-level guard: a chunk/verify position past the
    block table must DROP its KV write — a clamped gather index would
    silently land it in the stream's last real block, overwriting live
    rows (caught building the spec verify window)."""
    server = make_spec_server(identity=True, max_batch=1).warmup()
    pool = server.pool
    table, _, _ = pool.admit("s", 32)           # all 4 blocks of a 32-ctx
    nb = server.programs.blocks_per_stream
    tables = np.full((1, nb), pool.num_blocks, np.int32)
    tables[0, :len(table)] = table
    before = jax.tree_util.tree_map(np.asarray, pool.pools)
    k = server.programs.spec_k
    vt = np.full((1, k + 1), 5, np.int32)
    vp = np.full((1, k + 1), -1, np.int32)
    vp[0, 0] = 32                               # one past the table range
    server.programs.verify(vt, vp, tables)
    after = jax.tree_util.tree_map(np.asarray, pool.pools)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(a, b)
    pool.free("s")


def test_prebake_cache_tool_warms_fleet_boot(tmp_path, monkeypatch):
    """tools/prebake_cache.py (the PR 11 follow-on): a manifest-driven
    pre-bake pays every serve compile once; a replica booting with the
    same geometry then warms up at ZERO fresh compiles."""
    import json
    import subprocess
    import sys
    manifest = {"programs": [{
        "model": "llama_tiny",
        "overrides": {"dtype": "float32", "max_seq_len": 64},
        "serve": {"max_batch": 2, "kv_blocks": 16, "block_size": 8,
                  "max_context": 16, "chunk_size": 8, "prefill_rows": 2,
                  "spec_k": 2, "draft_model": "llama_tiny",
                  "draft_overrides": {"dtype": "float32", "n_layers": 1,
                                      "max_seq_len": 64}}}]}
    mpath = tmp_path / "manifest.json"
    mpath.write_text(json.dumps(manifest))
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "prebake_cache.py")
    cache = str(tmp_path / "aot")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MXNET_TPU_AOT_CACHE", None)
    proc = subprocess.run(
        [sys.executable, tool, str(mpath), "--cache", cache,
         "--format", "json"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout)["entries"][0]
    assert row["programs"] == 7         # chunk/decode/copy + 4 spec
    assert row["compiled"] == 7 and row["written"] == 7
    assert row["errors"] == 0
    # the fleet-boot experience: same geometry, fresh process-equivalent
    # params -> every executable restores, zero fresh compiles
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", cache)
    import dataclasses

    from mxnet_tpu.models.llama import CONFIGS
    cfg = dataclasses.replace(CONFIGS["llama_tiny"], dtype=jnp.float32,
                              max_seq_len=64)
    dcfg = dataclasses.replace(cfg, n_layers=1)
    telemetry.reset()
    InferenceServer(llama_init(jax.random.PRNGKey(9), cfg), cfg,
                    max_batch=2, kv_blocks=16, block_size=8,
                    max_context=16, chunk_size=8, prefill_rows=2,
                    spec_k=2, draft_cfg=dcfg,
                    draft_params=llama_init(jax.random.PRNGKey(8),
                                            dcfg)).warmup()
    snap = telemetry.snapshot()["counters"]
    assert snap.get("serve.compile", 0) == 0, snap
    assert snap.get("compiler.cache.hits") == 7


@pytest.mark.lint
def test_serve_package_lint_clean_zero_suppressions():
    """The scheduler/replica threads must be TPU006-clean with ZERO
    suppression comments (ISSUE 8 CI satellite)."""
    import mxnet_tpu.analysis as analysis
    serve_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "serve")
    findings = analysis.check(serve_dir)
    assert findings == [], "\n".join(str(f) for f in findings)
    for name in os.listdir(serve_dir):
        if name.endswith(".py"):
            with open(os.path.join(serve_dir, name)) as f:
                assert "tpu-lint" not in f.read(), (
                    "suppression found in %s" % name)
