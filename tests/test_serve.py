"""mx.serve — fault-tolerant continuous-batching inference runtime.

Acceptance (ISSUE 8): a llama-family LM serves >= 8 concurrent streams
under continuous batching on the CPU backend with NO new prefill/decode
compiles after warm-up (asserted via telemetry.note_compile), and a
MXNET_TPU_FAULT_PLAN kill at serve.step mid-stream recovers every
in-flight stream with no lost or duplicated tokens (byte-identical
output). Paged-KV edge cases: pool exhaustion -> structured Overloaded,
block reuse after stream completion, fragmentation across many short
streams.
"""
import functools
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.models.llama import (LlamaConfig, llama_init, llama_forward,
                                    init_kv_cache, llama_decode_step)
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.errors import RetryExhausted, is_retriable
from mxnet_tpu.serve import (DeadlineExceeded, InferenceServer, KVBlockPool,
                             Overloaded, ReplicaGroup, Request,
                             default_buckets)

pytestmark = pytest.mark.serve

CFG = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                  n_kv_heads=2, hidden_dim=128, rope_theta=10000.0,
                  max_seq_len=64, dtype=jnp.float32)
PARAMS = llama_init(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _clean_planes():
    telemetry.enable()
    telemetry.reset()
    faults.deactivate()
    yield
    faults.deactivate()
    telemetry.reset()


def make_server(**kw):
    kw.setdefault("kv_blocks", 48)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_context", 32)
    return InferenceServer(PARAMS, CFG, **kw)


def prompts_for(n, lo=3, hi=12, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, CFG.vocab_size - 1,
                        size=rng.randint(lo, hi)).tolist() for _ in range(n)]


@functools.lru_cache(maxsize=1)
def _ref_decode():
    return jax.jit(functools.partial(llama_decode_step, cfg=CFG))


def reference_generate(prompt, n_new):
    """Unpaged single-stream greedy reference: llama_forward prefill +
    contiguous-cache decode loop."""
    logits = llama_forward(PARAMS, jnp.asarray([prompt], jnp.int32), CFG)
    out = [int(jnp.argmax(logits[0, -1]))]
    cache = init_kv_cache(CFG, 1, max_len=CFG.max_seq_len)
    step = _ref_decode()
    for p, t in enumerate(prompt):
        _, cache = step(PARAMS, cache, jnp.asarray([t], jnp.int32),
                        jnp.asarray(p, jnp.int32))
    while len(out) < n_new:
        pos = len(prompt) + len(out) - 1
        lg, cache = step(PARAMS, cache, jnp.asarray([out[-1]], jnp.int32),
                        jnp.asarray(pos, jnp.int32))
        out.append(int(jnp.argmax(lg[0])))
    return out


# ---------------------------------------------------------------------------
# paged KV allocator
# ---------------------------------------------------------------------------
def test_kv_pool_alloc_free_reuse():
    pool = KVBlockPool(CFG, num_blocks=8, block_size=4)
    t1 = pool.alloc("a", 10)            # 3 blocks
    assert len(t1) == 3 and pool.blocks_in_use == 3
    t2 = pool.alloc("a", 12)            # grows by 0 (3 blocks cover 12)
    assert t2 == t1
    assert pool.alloc("b", 4) and pool.blocks_in_use == 4
    assert pool.free("a") == 3
    assert pool.free("a") == 0          # idempotent
    assert pool.blocks_in_use == 1
    # freed blocks recycle (LIFO): the new stream reuses a's ids
    t3 = pool.alloc("c", 12)
    assert set(t3) <= set(t1)
    snap = telemetry.snapshot()
    assert snap["gauges"]["serve.kv.blocks_in_use"]["max"] >= 4
    assert snap["counters"]["serve.kv.freed_blocks"] == 3


def test_kv_pool_exhaustion_structured_overloaded():
    pool = KVBlockPool(CFG, num_blocks=4, block_size=4)
    pool.alloc("a", 12)                 # 3 of 4 blocks
    with pytest.raises(Overloaded) as ei:
        pool.alloc("b", 8)              # needs 2, only 1 free
    err = ei.value
    assert err.reason == "kv_exhausted"
    assert err.kv_free_blocks == 1 and err.kv_needed_blocks == 2
    assert not is_retriable(err)        # a verdict, not a transport fault
    # all-or-nothing: the failed alloc reserved NOTHING — not even an
    # empty table entry (uuid stream ids never return; entries would leak)
    assert pool.blocks_in_use == 3
    assert pool.owned_blocks("b") == []
    assert "b" not in pool._tables
    assert telemetry.snapshot()["counters"]["serve.kv.exhausted"] == 1


def test_kv_pool_fragmentation_across_short_streams():
    """Interleaved alloc/free of many short streams scatters the free-list;
    a later long stream must still get its blocks (any block serves any
    position — fragmentation cannot exist by construction)."""
    pool = KVBlockPool(CFG, num_blocks=10, block_size=4)
    for wave in range(5):
        ids = ["s%d_%d" % (wave, i) for i in range(5)]
        for sid in ids:
            pool.alloc(sid, 5)          # 2 blocks each
        for sid in ids[::2]:            # free a non-contiguous subset
            pool.free(sid)
        for sid in ids[1::2]:
            pool.free(sid)
    assert pool.blocks_in_use == 0 and pool.free_blocks == 10
    table = pool.alloc("long", 40)      # the WHOLE pool, post-churn
    assert sorted(table) == list(range(10))
    # the table is not contiguous in allocation order (churned free-list)
    assert table != sorted(table)


def test_default_buckets_block_aligned():
    assert default_buckets(8, 64) == (8, 16, 32, 64)
    assert default_buckets(16, 100) == (16, 32, 64, 112)
    assert all(b % 16 == 0 for b in default_buckets(16, 100))


# ---------------------------------------------------------------------------
# correctness: paged continuous batching vs the unpaged reference
# ---------------------------------------------------------------------------
def test_single_stream_matches_reference():
    server = make_server().warmup()
    prompt = [3, 17, 42, 99, 7]
    h = server.submit(Request(prompt, max_new_tokens=6))
    server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 6)
    assert h.ttft_ms is not None and h.ttft_ms > 0
    assert len(h.tpot_ms) == 5


def test_eight_concurrent_streams_no_retrace():
    """THE acceptance test: >= 8 concurrent streams under continuous
    batching, every output matching its single-stream reference, and zero
    new prefill/decode compiles after warm-up."""
    server = make_server(max_batch=8, kv_blocks=64).warmup()
    warm = len(telemetry.recent_compiles())
    prompts = prompts_for(10)
    budgets = [5 + i % 4 for i in range(10)]
    handles = [server.submit(Request(p, max_new_tokens=b))
               for p, b in zip(prompts, budgets)]
    server.run()
    for h, p, b in zip(handles, prompts, budgets):
        assert h.result(timeout=10) == reference_generate(p, b)
    # continuous batching actually batched (streams shared decode steps)
    snap = telemetry.snapshot()
    assert snap["gauges"]["serve.batch_occupancy"]["max"] >= 8
    assert snap["counters"]["serve.decode_steps"] < sum(budgets)
    # no mid-traffic compiles: the compile ring did not grow after warmup
    new = [n for n, _ in telemetry.recent_compiles()][warm:]
    assert new == [], "post-warmup compiles: %s" % new
    assert "serve.retrace" not in snap["counters"]


def test_fragmented_pool_end_to_end():
    """Many short streams churn the free-list, then a long stream spans
    non-contiguous blocks — its output must still match the reference."""
    server = make_server(max_batch=2, kv_blocks=6, block_size=4,
                         max_context=32).warmup()
    for p in prompts_for(6, lo=3, hi=8, seed=1):
        server.submit(Request(p, max_new_tokens=3))
    server.run()
    long_prompt = prompts_for(1, lo=14, hi=15, seed=2)[0]
    h = server.submit(Request(long_prompt, max_new_tokens=8))
    server.run()
    blocks = server.pool.owned_blocks(h.id)
    assert blocks == []                 # retired: blocks recycled
    assert h.result(timeout=10) == reference_generate(long_prompt, 8)


# ---------------------------------------------------------------------------
# admission control / load shedding
# ---------------------------------------------------------------------------
def test_queue_full_sheds_with_overloaded():
    server = make_server(queue_cap=2).warmup()
    server.submit(Request([1, 2], max_new_tokens=2))
    server.submit(Request([1, 2], max_new_tokens=2))
    with pytest.raises(Overloaded) as ei:
        server.submit(Request([1, 2], max_new_tokens=2))
    assert ei.value.reason == "queue_full"
    assert ei.value.queue_depth == 2
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.shed"] == 1 and snap["serve.shed.queue_full"] == 1
    server.run()                        # the two admitted still finish


def test_oversized_request_shed_at_submit():
    server = make_server()              # pool: 48x8; max_context 32
    with pytest.raises(Overloaded) as ei:
        server.submit(Request([1] * 8, max_new_tokens=1000))
    assert ei.value.reason == "too_large"
    assert telemetry.snapshot()["counters"]["serve.shed.too_large"] == 1
    # the max_context bound holds even when the last bucket rounded UP
    # past it (block alignment): buckets (8, 16, 24) for max_context 20
    tight = make_server(max_context=20)
    assert tight.programs.buckets[-1] > 20
    with pytest.raises(Overloaded) as ei:
        tight.submit(Request([1] * 5, max_new_tokens=18))   # 22 > 20
    assert ei.value.reason == "too_large"


def test_kv_backpressure_defers_not_sheds():
    """Two requests whose worst-case contexts cannot coexist in the pool:
    the second WAITS (backpressure) and completes after the first frees
    its blocks — no shed, no OOM."""
    server = make_server(kv_blocks=5, block_size=8, max_batch=2,
                         max_context=32).warmup()
    p1, p2 = prompts_for(2, lo=8, hi=9, seed=3)
    h1 = server.submit(Request(p1, max_new_tokens=16))   # 3 blocks
    h2 = server.submit(Request(p2, max_new_tokens=16))   # 3 blocks: waits
    server.run()
    assert h1.result(timeout=10) == reference_generate(p1, 16)
    assert h2.result(timeout=10) == reference_generate(p2, 16)
    snap = telemetry.snapshot()["counters"]
    assert "serve.shed" not in snap
    assert snap["serve.completed"] == 2


def test_deadline_expires_in_queue():
    server = make_server(max_batch=1).warmup()
    slow = server.submit(Request([1, 2, 3], max_new_tokens=4))
    h = server.submit(Request([4, 5], max_new_tokens=2, deadline_s=0.001))
    time.sleep(0.01)
    server.run()
    slow.result(timeout=10)
    with pytest.raises(DeadlineExceeded) as ei:
        h.result(timeout=10)
    assert ei.value.tokens == []
    assert telemetry.snapshot()["counters"]["serve.shed.deadline"] == 1


def test_deadline_mid_stream_carries_partial_output():
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=24,
                              deadline_s=0.08))
    # slow every step so the deadline lands mid-stream
    with faults.inject("serve.step:latency:*:0.02"):
        server.run()
    with pytest.raises(DeadlineExceeded) as ei:
        h.result(timeout=10)
    assert 0 < len(ei.value.tokens) < 24
    assert ei.value.tokens == h.tokens


# ---------------------------------------------------------------------------
# fault tolerance: the robustness headline
# ---------------------------------------------------------------------------
def _serve_all(server, prompts, budgets):
    handles = [server.submit(Request(p, max_new_tokens=b))
               for p, b in zip(prompts, budgets)]
    server.run()
    return [h.result(timeout=30) for h in handles], handles


def test_kill_serve_step_mid_stream_byte_identical():
    """THE chaos acceptance test: MXNET_TPU_FAULT_PLAN kills serve.step
    twice mid-stream; every in-flight stream drains, requeues, resumes by
    re-prefill — and the full output is byte-identical to the unfaulted
    run (no token lost, none duplicated)."""
    prompts = prompts_for(8, seed=4)
    budgets = [5 + i % 4 for i in range(8)]
    baseline, _ = _serve_all(make_server(max_batch=4, kv_blocks=64).warmup(),
                             prompts, budgets)
    telemetry.reset()
    server = make_server(max_batch=4, kv_blocks=64).warmup()
    os.environ["MXNET_TPU_FAULT_PLAN"] = \
        "serve.step:error:3;serve.step:error:6"
    try:
        faults.activate()
        chaos, handles = _serve_all(server, prompts, budgets)
    finally:
        del os.environ["MXNET_TPU_FAULT_PLAN"]
        faults.deactivate()
    assert chaos == baseline
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.recoveries"] == 2
    assert snap["serve.requeued_streams"] >= 1
    assert snap["resilience.faults_injected"] >= 2
    assert sum(h.requeues for h in handles) == snap["serve.requeued_streams"]


def test_retry_budget_exhausted_fails_stream():
    server = make_server().warmup()
    doomed = server.submit(Request([1, 2, 3], max_new_tokens=8, retries=0))
    survivor = server.submit(Request([4, 5, 6], max_new_tokens=4))
    with faults.inject("serve.step:error:2"):
        server.run()
    with pytest.raises(RetryExhausted):
        doomed.result(timeout=10)
    assert survivor.result(timeout=10) == reference_generate([4, 5, 6], 4)
    assert telemetry.snapshot()["counters"]["serve.failed"] == 1


def test_watchdog_converts_hang_to_recovery():
    """An injected hang inside serve.step becomes a StallError (not a
    frozen replica) and the scheduler recovers the stream."""
    server = make_server(step_deadline_s=0.25).warmup()
    prompt = [7, 8, 9]
    h = server.submit(Request(prompt, max_new_tokens=4))
    with faults.inject("serve.step:hang:2:30"):
        server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 4)
    snap = telemetry.snapshot()["counters"]
    assert snap["resilience.stalls.serve.step"] == 1
    assert snap["serve.recoveries"] == 1


def test_replica_group_survives_replica_death():
    """ResilientRunner semantics at group level: a replica killed with a
    spent restart budget drains its streams to the shared queue; the
    survivor finishes them — byte-identical, group still healthy."""
    prompts = prompts_for(8, seed=5)
    budgets = [6] * 8
    baseline, _ = _serve_all(make_server(max_batch=4, kv_blocks=64).warmup(),
                             prompts, budgets)
    telemetry.reset()
    group = ReplicaGroup(PARAMS, CFG, replicas=2, kv_blocks=48,
                         block_size=8, max_batch=4, max_context=32,
                         max_restarts=0).warmup()
    with faults.inject("serve.step:preempt:3"):
        group.start()
        handles = [group.submit(Request(p, max_new_tokens=b))
                   for p, b in zip(prompts, budgets)]
        out = [h.result(timeout=30) for h in handles]
        assert group.drain(timeout=10)
    group.stop()
    assert out == baseline
    assert group.alive_replicas == 1
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.replica_deaths"] == 1
    assert snap["serve.recoveries"] == 1


def test_fault_mid_admission_loses_no_stream():
    """A fault landing INSIDE _admit (after the queue pop, during the
    prefill — where an async watchdog stall would land) must drain the
    half-admitted stream back to the queue, not lose it."""
    from mxnet_tpu.resilience.errors import InjectedFault
    server = make_server().warmup()
    real_prefill = server.programs.prefill
    state = {"fired": False}

    def flaky_prefill(tokens, table):
        if not state["fired"]:
            state["fired"] = True
            raise InjectedFault("mid-admission fault", site="serve.step")
        return real_prefill(tokens, table)

    server.programs.prefill = flaky_prefill
    prompt = [5, 6, 7]
    h = server.submit(Request(prompt, max_new_tokens=4))
    server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 4)
    assert h.requeues == 1
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.requeued_streams"] == 1
    assert server.pool.blocks_in_use == 0   # nothing leaked


def test_nonretriable_death_drains_streams():
    """A NON-retriable escape from the step (a bug, a device loss) kills
    the replica but still drains its in-flight streams to the shared
    queue — a fresh replica on the same queue finishes them."""
    server = make_server().warmup()
    prompt = [9, 8, 7]
    h = server.submit(Request(prompt, max_new_tokens=4))
    boom = {"armed": True}
    real_decode = server.programs.decode

    def bad_decode(tokens, positions, tables):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("simulated device loss")
        return real_decode(tokens, positions, tables)

    server.programs.decode = bad_decode
    with pytest.raises(RuntimeError):
        server.run()
    assert server.dead
    assert not h.done()                     # not lost, not failed: queued
    survivor = make_server(queue=server.queue).warmup()
    survivor.run()
    assert h.result(timeout=10) == reference_generate(prompt, 4)
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.replica_deaths"] == 1
    assert snap["serve.requeued_streams"] == 1


def test_recovery_async_windows():
    """White-box regression for the async-StallError windows: (1) a stream
    caught in BOTH _admitting and a slot drains once, not twice; (2) a
    requeued stream that already emitted its full budget retires without
    re-prefilling an extra token; (3) pool buffers deleted by a fault
    between a donating program call and update() are re-materialized."""
    from mxnet_tpu.resilience.errors import InjectedFault
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=4))
    server.step()                       # admit + first decode
    stream = server._slots[0]
    assert stream is not None
    # (1) fault landed between slot assignment and the _admitting clear
    server._admitting = stream
    server._recover(InjectedFault("window", site="serve.step"))
    assert len(server.queue) == 1       # requeued ONCE
    assert h.requeues == 1
    # (1b) fault landed AFTER a requeue had already handed ownership to
    # the queue (or a sibling replica): recovery must not requeue again —
    # the ownership check is atomic under the queue lock
    server._admitting = stream          # still queue-owned
    server._recover(InjectedFault("window1b", site="serve.step"))
    assert len(server.queue) == 1
    assert h.requeues == 1
    # (2) pretend the fault also landed after the final token but before
    # _finish_check: the stream comes back already complete
    h.tokens.extend([0] * (4 - len(h.tokens)))
    # (3) and the donating call's outputs never reached pool.update
    for leaf in jax.tree_util.tree_leaves(server.pool.pools):
        leaf.delete()
    # (4) and an alloc was torn mid-flight: blocks popped off the
    # free-list that never reached any table
    torn = [server.pool._free.pop() for _ in range(3)]
    assert torn and server.pool.free_blocks < server.pool.num_blocks
    server._recover(InjectedFault("window2", site="serve.step"))
    assert not any(x.is_deleted()
                   for x in jax.tree_util.tree_leaves(server.pool.pools))
    assert server.pool.free_blocks == server.pool.num_blocks  # reconciled
    server.run()
    assert h.result(timeout=10) == h.tokens and len(h.tokens) == 4
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.kv.storage_resets"] == 1
    assert snap["serve.completed"] == 1


# ---------------------------------------------------------------------------
# per-request tracing (ISSUE 12 tentpole)
# ---------------------------------------------------------------------------
def test_request_trace_accounts_wall_clock_single_replica():
    """Every served request leaves a timeline in the last-N ring whose
    spans tile its wall clock (queue + prefill + per-token decode)."""
    server = make_server(max_batch=2).warmup()
    prompts = prompts_for(3, seed=9)
    handles = [server.submit(Request(p, max_new_tokens=4))
               for p in prompts]
    server.run()
    for h in handles:
        h.result(timeout=10)
    traces = {t["request_id"]: t for t in telemetry.request_traces()}
    assert set(traces) == {h.id for h in handles}
    for h in handles:
        t = traces[h.id]
        assert t["outcome"] == "completed"
        assert t["tokens"] == 4
        # payload ttft is rounded to 3 decimals for the JSON dump
        assert t["ttft_ms"] == pytest.approx(h.ttft_ms, abs=5e-4)
        assert t["accounted_ms"] >= 0.95 * t["wall_ms"]
        assert set(t["phases_ms"]) == {"queue", "prefill", "decode"}
        # decode emitted one span per token after the prefill's first
        decodes = [s for s in t["spans"] if s["name"] == "decode"]
        assert len(decodes) == 3
        assert t["replicas"] == ["replica0"]


def test_request_trace_survives_replica_kill_95pct_accounted():
    """THE ISSUE 12 acceptance test: a deliberately delayed request whose
    replica is killed mid-stream still yields ONE RequestTrace — continued
    on the surviving replica — whose spans (queue-wait + prefill + decode
    + recovery) account for >= 95% of its wall clock."""
    group = ReplicaGroup(PARAMS, CFG, replicas=2, kv_blocks=48,
                         block_size=8, max_batch=4, max_context=32,
                         max_restarts=0).warmup()
    prompts = prompts_for(6, seed=10)
    # every step delayed (the "deliberately delayed request"), and the 4th
    # step check is a kill — preempt FIRST: the plan fires the first
    # matching entry, so the wildcard latency must come after it
    with faults.inject("serve.step:preempt:4;serve.step:latency:*:0.01"):
        group.start()
        handles = [group.submit(Request(p, max_new_tokens=6))
                   for p in prompts]
        for h in handles:
            h.result(timeout=30)
        assert group.drain(timeout=10)
    group.stop()
    assert group.alive_replicas == 1
    traces = {t["request_id"]: t for t in telemetry.request_traces()}
    for h in handles:
        t = traces[h.id]
        assert t["outcome"] == "completed"
        assert t["accounted_ms"] >= 0.95 * t["wall_ms"], t
    recovered = [traces[h.id] for h in handles if h.requeues > 0]
    assert recovered, "the kill drained no in-flight stream"
    # the killed replica's streams resumed elsewhere: recovery spans are
    # on the timeline and the trace names BOTH replicas it crossed
    assert any("recovery" in t["phases_ms"] for t in recovered)
    assert any(len(set(t["replicas"])) == 2 for t in recovered)


def test_deadline_exceeded_embeds_request_trace():
    """A shed request carries its own timeline: DeadlineExceeded's
    request_trace names where the time went."""
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=24,
                              deadline_s=0.08))
    with faults.inject("serve.step:latency:*:0.02"):
        server.run()
    with pytest.raises(DeadlineExceeded) as ei:
        h.result(timeout=10)
    tr = ei.value.request_trace
    assert tr is not None and tr["outcome"] == "deadline"
    assert tr["request_id"] == h.id
    assert tr["tokens"] == len(ei.value.tokens)
    assert tr["accounted_ms"] >= 0.95 * tr["wall_ms"]
    # the same payload is queryable from the ring (the /requests body)
    ring = {t["request_id"]: t for t in telemetry.request_traces()}
    assert ring[h.id]["outcome"] == "deadline"


def test_shed_requests_land_in_ring():
    server = make_server(queue_cap=1).warmup()
    with pytest.raises(Overloaded):
        server.submit(Request([1] * 8, max_new_tokens=1000))  # too_large
    server.submit(Request([1, 2], max_new_tokens=2))
    with pytest.raises(Overloaded):
        server.submit(Request([3, 4], max_new_tokens=2))      # queue_full
    outcomes = [t["outcome"] for t in telemetry.request_traces()]
    assert "shed.too_large" in outcomes
    assert "shed.queue_full" in outcomes
    server.run()


def test_request_rows_in_chrome_dump(tmp_path):
    """Completed requests replay into the chrome dump as their own rows:
    spans named req[<id>].<phase> under a per-request tid."""
    import json
    server = make_server(max_batch=2).warmup()
    handles = [server.submit(Request(p, max_new_tokens=3))
               for p in prompts_for(2, seed=12)]
    server.run()
    path = telemetry.dump_trace(str(tmp_path / "serve_trace.json"))
    obj = json.load(open(path))
    rows = [e for e in obj["traceEvents"]
            if e.get("ph") == "X" and e.get("cat") == "request"]
    assert rows
    assert {e["tid"] for e in rows} == {
        __import__("zlib").crc32(h.id.encode()) & 0x3fffffff
        for h in handles}
    names = {e["name"] for e in rows}
    for h in handles:
        assert "req[%s].prefill" % h.id in names
        assert "req[%s].decode" % h.id in names


def test_flight_records_name_in_flight_requests():
    """ISSUE 12 satellite: every serve.step flight record carries the
    active/completed request ids, so a stall post-mortem names the
    in-flight requests instead of just counters."""
    from mxnet_tpu.telemetry import flight as _flight
    server = make_server(max_batch=2).warmup()
    handles = [server.submit(Request(p, max_new_tokens=4))
               for p in prompts_for(2, seed=13)]
    server.run()
    recs = [r for r in telemetry.flight_records()
            if r["site"] == "serve.step"]
    assert recs and all("active_requests" in r for r in recs)
    seen = {i for r in recs for i in (r["active_requests"]
                                      + r.get("completed_requests", []))}
    assert {h.id for h in handles} <= seen
    rendered = _flight.format_records(recs)
    assert any(h.id in rendered for h in handles)


def test_request_tracing_knob_inert(monkeypatch):
    """MXNET_TPU_SERVE_TRACE=0 (the bench's A/B lever): NULL traces, an
    empty ring, no request spans — while the rest of telemetry stays on."""
    from mxnet_tpu.telemetry import request_trace as _reqtrace
    monkeypatch.setenv("MXNET_TPU_SERVE_TRACE", "0")
    server = make_server().warmup()
    h = server.submit(Request([1, 2, 3], max_new_tokens=3))
    assert h.trace is _reqtrace.NULL_TRACE
    server.run()
    h.result(timeout=10)
    assert telemetry.request_traces() == []
    assert not any(n.startswith("req[")
                   for n, *_ in telemetry.span_events())
    # aggregate serving telemetry is unaffected
    assert telemetry.snapshot()["counters"]["serve.completed"] == 1


# ---------------------------------------------------------------------------
# telemetry / no-retrace plumbing
# ---------------------------------------------------------------------------
def test_serving_telemetry_and_flight_records():
    server = make_server().warmup()
    for p in prompts_for(3, seed=6):
        server.submit(Request(p, max_new_tokens=4))
    server.run()
    snap = telemetry.snapshot()
    hists = snap["histograms"]
    assert hists["serve.ttft_ms"]["count"] == 3
    assert hists["serve.tpot_ms"]["count"] > 0
    assert hists["serve.step_ms"]["count"] > 0
    assert snap["gauges"]["serve.tokens_per_s"]["value"] > 0
    # the flight recorder saw the serving path (step_event wiring)
    sites = {r["site"] for r in telemetry.flight_records()}
    assert "serve.step" in sites
    # and the rolling quantile tracker covers serve.step
    assert telemetry.step_quantiles("serve.step")["n"] > 0


def test_post_warmup_signature_miss_counts_as_retrace():
    """White-box: a prefill signature that escaped warm-up is handled (the
    request still completes) but counted and reported like a CachedOp
    retrace."""
    server = make_server().warmup()
    bucket = server.programs.buckets[0]
    del server.programs._prefill_exec[bucket]   # simulate the escape
    prompt = [1, 2, 3]                          # rides the smallest bucket
    h = server.submit(Request(prompt, max_new_tokens=3))
    server.run()
    assert h.result(timeout=10) == reference_generate(prompt, 3)
    snap = telemetry.snapshot()["counters"]
    assert snap["serve.retrace"] == 1
    names = [n for n, _ in telemetry.recent_compiles()]
    assert "serve.prefill(retrace)" in names


def test_duplicate_request_ids_do_not_share_kv():
    """Two in-flight requests reusing one caller-supplied request_id must
    not share a block table (the pool is keyed per stream, not per id)."""
    server = make_server(max_batch=2).warmup()
    p1, p2 = prompts_for(2, seed=7)
    h1 = server.submit(Request(p1, max_new_tokens=5, request_id="dup"))
    h2 = server.submit(Request(p2, max_new_tokens=5, request_id="dup"))
    server.run()
    assert h1.result(timeout=10) == reference_generate(p1, 5)
    assert h2.result(timeout=10) == reference_generate(p2, 5)
    assert server.pool.blocks_in_use == 0


def test_zero_deadline_means_expired_not_disabled():
    server = make_server().warmup()
    h = server.submit(Request([1, 2], max_new_tokens=2, deadline_s=0))
    server.run()
    with pytest.raises(DeadlineExceeded):
        h.result(timeout=10)


def test_admit_fault_site_wired():
    server = make_server().warmup()
    with faults.inject("serve.admit:error:1"):
        with pytest.raises(Exception) as ei:
            server.submit(Request([1, 2], max_new_tokens=2))
    assert "serve.admit" in str(ei.value)


@pytest.mark.lint
def test_serve_package_lint_clean_zero_suppressions():
    """The scheduler/replica threads must be TPU006-clean with ZERO
    suppression comments (ISSUE 8 CI satellite)."""
    import mxnet_tpu.analysis as analysis
    serve_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "serve")
    findings = analysis.check(serve_dir)
    assert findings == [], "\n".join(str(f) for f in findings)
    for name in os.listdir(serve_dir):
        if name.endswith(".py"):
            with open(os.path.join(serve_dir, name)) as f:
                assert "tpu-lint" not in f.read(), (
                    "suppression found in %s" % name)
