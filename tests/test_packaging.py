"""Packaging: `pip install -e .` from a clean venv (round-4 VERDICT
missing #2 — reference ships python/setup.py; here pyproject.toml).

The venv gets the baked environment's site-packages via a .pth file
(jax/numpy are image-provided, never pip-installed — Environment rule),
and the install runs --no-deps --no-build-isolation so it is fully
offline.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_pip_install_editable_smoke(tmp_path):
    venv = tmp_path / "venv"
    subprocess.run([sys.executable, "-m", "venv", str(venv)], check=True,
                   timeout=120)
    # expose the baked site-packages (jax, numpy, setuptools) to the venv
    baked = [p for p in sys.path if p.endswith("site-packages")]
    assert baked, "no baked site-packages on sys.path"
    sp = subprocess.run(
        [str(venv / "bin" / "python"), "-c",
         "import sysconfig; print(sysconfig.get_paths()['purelib'])"],
        capture_output=True, text=True, check=True, timeout=60)
    (tmp_path / "baked.pth").write_text("\n".join(baked))
    import shutil
    shutil.copy(str(tmp_path / "baked.pth"),
                os.path.join(sp.stdout.strip(), "_baked.pth"))

    proc = subprocess.run(
        [str(venv / "bin" / "pip"), "install", "-e", REPO, "--no-deps",
         "--no-build-isolation", "-q"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    check = subprocess.run(
        [str(venv / "bin" / "python"), "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu')\n"
         "import mxnet_tpu as mx\n"
         "from mxnet_tpu import nd, gluon, numpy as mnp\n"
         "import numpy as np\n"
         "x = nd.array(np.ones((2, 3), np.float32))\n"
         "assert float((x + x).asnumpy().sum()) == 12.0\n"
         "print('ok')"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(tmp_path))  # NOT the repo root: the install must stand alone
    assert check.returncode == 0, check.stderr[-2000:]
    assert "ok" in check.stdout
