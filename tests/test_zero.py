"""ZeRO-1 weight-update sharding (ISSUE 9) tests.

Coverage: persistent BucketLayout freeze/checkpoint/re-partition semantics,
pack/unpack padding round-trips, bit-exact ZeRO-vs-replicated final params
on a resnet18-sized set via single-process injectable collectives (the
CommitCoordinator fake-gather pattern — CPU tier-1 cannot run multiprocess
collectives), SGD/momentum + Adam + multi-precision fp16, one fused update
dispatch per dtype-bucket, `opt.state_bytes_per_rank` = replicated total /
world, elastic shrink/grow state migration, SnapshotCheckpointer + orbax
round-trips (incl. restore onto a different world size), Trainer(zero=)
end-to-end, the dist store's per-bucket 2-bit compression residuals parity,
the in-mesh reduce_scatter_multi/all_gather_multi collectives, fault-site
retry, and the `parse_log --comm` rows.
"""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, nd, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.optimizer import (Updater, ZeroComm, ZeroUpdater,
                                 create as opt_create)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counters():
    return dict(telemetry.snapshot()["counters"])


def _delta(before, after, key):
    return after.get(key, 0) - before.get(key, 0)


def _hist_count(name):
    return telemetry.snapshot()["histograms"].get(name, {}).get("count", 0)


# ===========================================================================
# injectable single-process fleet (the CommitCoordinator fake-gather
# pattern): each simulated rank runs its ZeroUpdater on its own thread; the
# fleet object is the collective fabric — a barrier'd mailbox that sums
# contributions in rank order (the fixed order keeps fp32 runs bit-exact
# against a baseline summed the same way)
# ===========================================================================
class FakeFleet:
    def __init__(self, world):
        self.world = world
        self.lock = threading.Lock()
        self.barrier = threading.Barrier(world)
        self.box = {}

    def comm(self, rank):
        return _FakeComm(self, rank)


class _FakeComm:
    def __init__(self, fleet, rank):
        self._fleet = fleet
        self.rank = rank

    @property
    def world(self):
        return self._fleet.world

    def _exchange(self, tag, spec, value):
        fleet = self._fleet
        with fleet.lock:
            fleet.box.setdefault((tag, spec.index), {})[self.rank] = \
                np.asarray(value)
        fleet.barrier.wait()
        parts = fleet.box[(tag, spec.index)]
        fleet.barrier.wait()
        return parts

    def reduce_scatter(self, spec, flat):
        parts = self._exchange("rs", spec, flat)
        total = parts[0].copy()
        for r in range(1, self.world):
            total = total + parts[r]   # rank order, matching the baseline
        lo = self.rank * spec.shard
        return jnp.asarray(total[lo:lo + spec.shard])

    def all_gather(self, spec, shard):
        parts = self._exchange("ag", spec, shard)
        return jnp.asarray(np.concatenate(
            [parts[r] for r in range(self.world)]))

    def all_reduce(self, spec, value):
        # LAMB per-segment norm completion (ISSUE 10): sum in rank order,
        # matching the replicated baseline's accumulation
        parts = self._exchange("ar", spec, value)
        total = parts[0].copy()
        for r in range(1, self.world):
            total = total + parts[r]
        return jnp.asarray(total)


def _run_fleet(world, fn):
    """Run fn(rank, comm) on `world` threads; re-raise the first error."""
    fleet = FakeFleet(world)
    errs = [None] * world

    def wrap(rank):
        try:
            fn(rank, fleet.comm(rank))
        except BaseException as e:  # noqa: BLE001 - test harness
            errs[rank] = e
            fleet.barrier.abort()

    threads = [threading.Thread(target=wrap, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errs:
        if e is not None:
            raise e


# ===========================================================================
# BucketLayout
# ===========================================================================

def test_bucket_layout_freeze_pad_and_segments():
    entries = [(str(i), jnp.ones((n,), jnp.float32))
               for i, n in enumerate((5, 3, 7))]
    layout = engine.BucketLayout.from_entries(entries, world=4,
                                              cap_bytes=1 << 20)
    assert len(layout) == 1
    spec = layout.buckets[0]
    assert spec.size == 15 and spec.padded == 16 and spec.shard == 4
    assert spec.keys == ["0", "1", "2"]
    # rank 1 owns flat [4, 8): tail of key 0 (1 elem) + all of key 1 (3)
    assert spec.shard_segments(1) == [("0", 0, 1, 4), ("1", 1, 3, 0)]
    # rank 3 owns [12, 16): 3 real elements of key 2, 1 padding elem
    assert spec.shard_segments(3) == [("2", 0, 3, 4)]


def test_bucket_layout_payload_roundtrip_and_reworld():
    entries = [(str(i), jnp.ones((6,), jnp.float32)) for i in range(4)]
    layout = engine.BucketLayout.from_entries(entries, world=4,
                                              cap_bytes=2 * 6 * 4)
    payload = layout.to_payload()
    back = engine.BucketLayout.from_payload(payload)
    assert back.world == 4
    assert [b.keys for b in back] == [b.keys for b in layout]
    assert [b.shard for b in back] == [b.shard for b in layout]
    # elastic re-partition: same buckets, new shard boundaries
    two = layout.rebuild_for_world(2)
    assert two.world == 2
    assert [b.keys for b in two] == [b.keys for b in layout]
    assert all(b2.shard == b4.shard * 2
               for b2, b4 in zip(two, layout))


def test_bucket_layout_frozen_guard():
    entries = [(str(i), jnp.ones((4,), jnp.float32)) for i in range(3)]
    layout = engine.BucketLayout.from_entries(entries, 2, 1 << 20)
    layout.assert_matches(["0", "1", "2"])
    with pytest.raises(ValueError, match="frozen"):
        layout.assert_matches(["0", "1"])
    with pytest.raises(ValueError, match="frozen"):
        layout.assert_matches(["0", "2", "1"])


def test_pack_unpack_flat_padded_roundtrip():
    rng = np.random.RandomState(0)
    raws = [jnp.asarray(rng.randn(*s).astype(np.float32))
            for s in [(3, 4), (7,)]]
    layout = engine.BucketLayout.from_entries(enumerate(raws), world=4,
                                              cap_bytes=1 << 20)
    spec = layout.buckets[0]
    flat = engine.pack_flat(spec, raws)
    assert flat.shape == (spec.padded,) == (20,)
    np.testing.assert_array_equal(np.asarray(flat[19:]), [0.0])
    parts = engine.unpack_flat(spec, flat)
    for r, p in zip(raws, parts):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(p))


# ===========================================================================
# acceptance: bit-exact ZeRO vs replicated on a resnet18-sized param set,
# through injectable single-process collectives
# ===========================================================================

def _resnet18_grad_shapes():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from bench import resnet18_grad_shapes
    return resnet18_grad_shapes()


def _replicated_phases(optname, shapes, init_w, phases, **opt_kw):
    """Replicated baseline over a SEQUENCE of (grads_per_rank, steps)
    phases with ONE continuously-carried updater (momentum/moments survive
    phase changes — the elastic baseline needs this)."""
    opt = opt_create(optname, **opt_kw)
    upd = Updater(opt)
    ws = [nd.array(w, dtype=w.dtype) for w in init_w]
    for grads_per_rank, steps in phases:
        world = len(grads_per_rank)
        for _ in range(steps):
            for i in range(len(shapes)):
                total = grads_per_rank[0][i].copy()
                for r in range(1, world):   # rank order, like the fleet
                    total = total + grads_per_rank[r][i]
                upd(i, nd.array(total, dtype=total.dtype), ws[i])
    return [w.asnumpy() for w in ws]


def _replicated_final(optname, shapes, init_w, grads_per_rank, steps,
                      **opt_kw):
    return _replicated_phases(optname, shapes, init_w,
                              [(grads_per_rank, steps)], **opt_kw)


def _zero_final(optname, shapes, init_w, grads_per_rank, steps, world,
                **opt_kw):
    keys = [str(i) for i in range(len(shapes))]
    outs = [None] * world

    def run(rank, comm):
        opt = opt_create(optname, **opt_kw)
        zu = ZeroUpdater(opt, comm=comm)
        ws = [nd.array(w, dtype=w.dtype) for w in init_w]
        for _ in range(steps):
            zu.step(keys, [jnp.asarray(g) for g in grads_per_rank[rank]],
                    ws)
        outs[rank] = [w.asnumpy() for w in ws]

    _run_fleet(world, run)
    return outs


# dyadic hyperparameters (the PR 5 exactness trick): power-of-two lr /
# momentum / betas make every scalar·tensor product exact in fp32, so the
# fused flat kernel (where XLA may contract mul+add into FMA) and the
# eager per-op path round identically on ARBITRARY data — bit parity
# without constraining the gradients
_SGD_DYADIC = {"learning_rate": 0.125, "momentum": 0.5, "rescale_grad": 1.0}
_ADAM_DYADIC = {"learning_rate": 0.125, "beta1": 0.5, "beta2": 0.5,
                "epsilon": 2.0 ** -8, "rescale_grad": 1.0}


@pytest.mark.parametrize("optname,opt_kw", [
    ("sgd", _SGD_DYADIC),
    ("adam", _ADAM_DYADIC),
])
def test_zero_resnet18_sized_parity_injectable_fleet(optname, opt_kw):
    """ISSUE 9 acceptance: final params bit-identical to the replicated
    update on the resnet18-sized 62-tensor param set, world=2, simulated
    on one process (dyadic lr keeps every fp32 step exactly representable;
    the fake fleet and the baseline sum ranks in the same order)."""
    shapes = _resnet18_grad_shapes()
    assert len(shapes) == 62
    world, steps = 2, 2
    rng = np.random.RandomState(0)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[rng.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(world)]
    ref = _replicated_final(optname, shapes, init_w, grads, steps, **opt_kw)
    zouts = _zero_final(optname, shapes, init_w, grads, steps, world,
                        **opt_kw)
    for rank in range(world):
        for a, b in zip(zouts[rank], ref):
            np.testing.assert_array_equal(a, b)


def test_zero_world4_with_padding_parity():
    """Sizes that do NOT divide the world exercise the zero-padded shard
    tail on every rank."""
    shapes = [(5, 3), (7,), (4, 4), (3,)]   # 15+7+16+3 = 41, world 4
    world, steps = 4, 3
    rng = np.random.RandomState(1)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[rng.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(world)]
    kw = {"learning_rate": 0.125, "momentum": 0.5, "rescale_grad": 1.0}
    ref = _replicated_final("sgd", shapes, init_w, grads, steps, **kw)
    zouts = _zero_final("sgd", shapes, init_w, grads, steps, world, **kw)
    for rank in range(world):
        for a, b in zip(zouts[rank], ref):
            np.testing.assert_array_equal(a, b)


def test_zero_multi_precision_fp16_parity():
    """fp16 weights with multi_precision: the fused flat kernel carries an
    fp32 master shard and stays bit-identical to mp_sgd_mom_update."""
    shapes = [(6, 2), (10,)]
    rng = np.random.RandomState(2)
    init_w = [(rng.randn(*s) * 0.1).astype(np.float16) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float16) for s in shapes]]
    kw = {"learning_rate": 0.125, "momentum": 0.5, "rescale_grad": 1.0,
          "multi_precision": True}
    ref = _replicated_final("sgd", shapes, init_w, grads, 3, **kw)
    zouts = _zero_final("sgd", shapes, init_w, grads, 3, 1, **kw)
    for a, b in zip(zouts[0], ref):
        assert a.dtype == np.float16
        np.testing.assert_array_equal(a, b)


def test_zero_multi_precision_restore_keeps_master_bits():
    """A restored fp32 master must NOT be re-derived from the rounded
    fp16 store weights: resume + 1 step == uninterrupted 3 steps,
    bitwise."""
    shapes = [(6, 2), (10,)]
    rng = np.random.RandomState(8)
    init_w = [(rng.randn(*s) * 0.1).astype(np.float16) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float16) for s in shapes]
             for _ in range(3)]
    kw = {"learning_rate": 0.125, "momentum": 0.5, "rescale_grad": 1.0,
          "multi_precision": True}
    keys = ["0", "1"]

    def steps(zu, ws, grad_steps):
        for gs in grad_steps:
            zu.step(keys, [jnp.asarray(g) for g in gs], ws)

    zu = ZeroUpdater(opt_create("sgd", **kw))
    ws = [nd.array(w, dtype=w.dtype) for w in init_w]
    steps(zu, ws, grads)
    ref = [w.asnumpy() for w in ws]

    zu2 = ZeroUpdater(opt_create("sgd", **kw))
    ws2 = [nd.array(w, dtype=w.dtype) for w in init_w]
    steps(zu2, ws2, grads[:2])
    payload = zu2.state_payload()
    saved_w = [w.asnumpy() for w in ws2]
    zu3 = ZeroUpdater(opt_create("sgd", **kw))
    zu3.optimizer._index_update_count = dict(
        zu2.optimizer._index_update_count)
    zu3.optimizer.num_update = zu2.optimizer.num_update
    zu3.load_state_payload(payload)
    ws3 = [nd.array(w, dtype=w.dtype) for w in saved_w]
    steps(zu3, ws3, grads[2:])
    for a, b in zip((w.asnumpy() for w in ws3), ref):
        np.testing.assert_array_equal(a, b)


# ===========================================================================
# LAMB through the ZeroUpdater (ISSUE 10: closes the PR 9 "fused flat
# kernels for more optimizers" follow-on — the per-segment norm kernel)
# ===========================================================================
_LAMB_KW = {"learning_rate": 0.01, "beta1": 0.9, "beta2": 0.999,
            "epsilon": 1e-6, "rescale_grad": 1.0}


def test_zero_lamb_resnet18_sized_parity_vs_eager():
    """ISSUE 10 satellite: ZeRO LAMB (two-pass flat update with
    per-segment norms completed by ONE tiny all-reduce) vs the eager
    per-param LAMB updater on the resnet18-sized 62-tensor key set,
    world=2. The flat path accumulates each parameter's ‖w‖/‖g‖ in shard
    segments rather than `jnp.linalg.norm`'s single reduce, so parity is
    fp32-round-off (documented tolerance), not bitwise."""
    shapes = _resnet18_grad_shapes()
    assert len(shapes) == 62
    world, steps = 2, 2
    rng = np.random.RandomState(5)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float32) for s in shapes]
             for _ in range(world)]
    ref = _replicated_final("lamb", shapes, init_w, grads, steps,
                            **_LAMB_KW)
    zouts = _zero_final("lamb", shapes, init_w, grads, steps, world,
                        **_LAMB_KW)
    for rank in range(world):
        for a, b in zip(zouts[rank], ref):
            np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6)


def test_zero_lamb_world4_cross_boundary_segments():
    """Shapes that straddle shard boundaries at world=4: each rank sees
    only a PARTIAL slice of most parameters, so the trust-ratio norms are
    only correct if the per-segment partials really complete across ranks
    through comm.all_reduce."""
    shapes = [(7, 3), (11,), (6, 5), (9,)]   # 21+11+30+9 = 71, world 4
    world, steps = 4, 3
    rng = np.random.RandomState(6)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float32) for s in shapes]
             for _ in range(world)]
    ref = _replicated_final("lamb", shapes, init_w, grads, steps,
                            **_LAMB_KW)
    zouts = _zero_final("lamb", shapes, init_w, grads, steps, world,
                        **_LAMB_KW)
    for rank in range(world):
        for a, b in zip(zouts[rank], ref):
            np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6)
    # the norm exchange is visible in telemetry
    assert _counters().get("comm.all_reduce", 0) > 0


def test_zero_lamb_bounds_and_wd():
    """lower/upper trust-ratio bounds and weight decay follow the eager
    lamb_update_phase1/phase2 semantics through the flat path."""
    shapes = [(16,), (4, 4)]
    rng = np.random.RandomState(7)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float32) for s in shapes]]
    kw = dict(_LAMB_KW, wd=0.01, lower_bound=0.5, upper_bound=2.0)
    ref = _replicated_final("lamb", shapes, init_w, grads, 2, **kw)
    zouts = _zero_final("lamb", shapes, init_w, grads, 2, 1, **kw)
    for a, b in zip(zouts[0], ref):
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6)


def test_zero_lamb_state_roundtrip_resume_parity():
    """save/restore mid-run: resume + 1 step == uninterrupted 2 steps
    (the lamb mean/var slots ride the generic world-portable payload)."""
    shapes = [(6, 2), (10,)]
    rng = np.random.RandomState(9)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float32) for s in shapes]
             for _ in range(2)]
    keys = ["0", "1"]

    zu = ZeroUpdater(opt_create("lamb", **_LAMB_KW))
    ws = [nd.array(w) for w in init_w]
    for gs in grads:
        zu.step(keys, [jnp.asarray(g) for g in gs], ws)
    ref = [w.asnumpy() for w in ws]

    zu2 = ZeroUpdater(opt_create("lamb", **_LAMB_KW))
    ws2 = [nd.array(w) for w in init_w]
    zu2.step(keys, [jnp.asarray(g) for g in grads[0]], ws2)
    payload = zu2.state_payload()
    zu3 = ZeroUpdater(opt_create("lamb", **_LAMB_KW))
    zu3.optimizer._index_update_count = dict(
        zu2.optimizer._index_update_count)
    zu3.optimizer.num_update = zu2.optimizer.num_update
    zu3.load_state_payload(payload)
    ws3 = [nd.array(w.asnumpy()) for w in ws2]
    zu3.step(keys, [jnp.asarray(g) for g in grads[1]], ws3)
    for a, b in zip((w.asnumpy() for w in ws3), ref):
        np.testing.assert_array_equal(a, b)


# ===========================================================================
# Pallas flat kernels through the ZeroUpdater (ISSUE 10 tentpole): the
# interpreter runs the REAL kernels on the CPU backend — parity evidence
# only, never perf evidence
# ===========================================================================
@pytest.mark.pallas
@pytest.mark.parametrize("optname,opt_kw", [
    ("sgd", _SGD_DYADIC),
    ("adam", _ADAM_DYADIC),
])
def test_zero_pallas_flat_kernels_world2_bit_parity(optname, opt_kw):
    """With the Pallas gate on, ZeroUpdater dispatches the flat-segment
    kernels (counted in ops.pallas.dispatch.*) and the world=2 sharded run
    stays BIT-identical to the replicated eager baseline (dyadic
    hyperparameters, the FMA-immunity trick above)."""
    from mxnet_tpu.ops import fused_optimizer as fo
    assert fo.use_pallas_flat()
    shapes = [(5, 3), (17,), (4, 4), (3,)]
    world, steps = 2, 2
    rng = np.random.RandomState(12)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[rng.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(world)]
    before = _counters()
    ref = _replicated_final(optname, shapes, init_w, grads, steps, **opt_kw)
    zouts = _zero_final(optname, shapes, init_w, grads, steps, world,
                        **opt_kw)
    after = _counters()
    for rank in range(world):
        for a, b in zip(zouts[rank], ref):
            np.testing.assert_array_equal(a, b)
    key = "ops.pallas.dispatch.flat_%s" % optname
    assert after.get(key, 0) > before.get(key, 0)


@pytest.mark.pallas
def test_zero_pallas_multi_precision_fp16_bit_parity():
    """fp16 + fp32-master through the Pallas flat kernel: bit-identical
    to the replicated mp_sgd_mom_update baseline."""
    shapes = [(6, 2), (10,)]
    rng = np.random.RandomState(13)
    init_w = [(rng.randn(*s) * 0.1).astype(np.float16) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float16) for s in shapes]]
    kw = {"learning_rate": 0.125, "momentum": 0.5, "rescale_grad": 1.0,
          "multi_precision": True}
    ref = _replicated_final("sgd", shapes, init_w, grads, 3, **kw)
    zouts = _zero_final("sgd", shapes, init_w, grads, 3, 1, **kw)
    for a, b in zip(zouts[0], ref):
        assert a.dtype == np.float16
        np.testing.assert_array_equal(a, b)


@pytest.mark.pallas
def test_zero_pallas_lamb_world2_parity():
    """LAMB's Pallas two-pass (phase1+norm partials, trust-ratio apply)
    through the sharded updater, world=2 — fp32-round-off parity vs the
    eager per-param baseline (norm association differs; see module
    docstring of ops/fused_optimizer.py)."""
    shapes = [(7, 3), (11,), (5, 5)]
    world, steps = 2, 2
    rng = np.random.RandomState(14)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads = [[(rng.randn(*s) * 0.1).astype(np.float32) for s in shapes]
             for _ in range(world)]
    before = _counters()
    ref = _replicated_final("lamb", shapes, init_w, grads, steps,
                            **_LAMB_KW)
    zouts = _zero_final("lamb", shapes, init_w, grads, steps, world,
                        **_LAMB_KW)
    after = _counters()
    for rank in range(world):
        for a, b in zip(zouts[rank], ref):
            np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6)
    assert after.get("ops.pallas.dispatch.flat_lamb1", 0) > \
        before.get("ops.pallas.dispatch.flat_lamb1", 0)
    assert after.get("ops.pallas.dispatch.flat_lamb2", 0) > \
        before.get("ops.pallas.dispatch.flat_lamb2", 0)


def test_zero_and_compression_are_mutually_exclusive():
    from mxnet_tpu.base import MXNetError
    kv = _dist_store()
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    with pytest.raises(MXNetError, match="mutually exclusive"):
        kv.set_optimizer(opt_create("sgd", learning_rate=0.1), zero=True)
    kv2 = _dist_store()
    kv2.set_optimizer(opt_create("sgd", learning_rate=0.1), zero=True)
    with pytest.raises(MXNetError, match="compression"):
        kv2.set_gradient_compression({"type": "2bit", "threshold": 0.5})


def test_zero_skips_zero_size_grads_consistently():
    """Zero-size grads never enter a bucket — both the freeze and every
    later step must filter them the same way (a desync here broke the
    frozen-layout guard on step 2)."""
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5))
    ws = [nd.array(np.ones(4, np.float32)), nd.zeros((0,)),
          nd.array(np.ones(2, np.float32))]
    gs = [jnp.ones((4,), jnp.float32), jnp.zeros((0,), jnp.float32),
          jnp.ones((2,), jnp.float32)]
    for _ in range(2):
        zu.step(["0", "1", "2"], gs, ws)
    assert zu.layout.keys() == ["0", "2"]
    np.testing.assert_array_equal(ws[0].asnumpy(), np.zeros(4))
    assert ws[1].asnumpy().size == 0


def test_zero_rejects_unsupported_optimizer():
    with pytest.raises(ValueError, match="SGD, Adam and LAMB"):
        ZeroUpdater(opt_create("rmsprop"))


def test_zero_frozen_layout_rejects_changed_key_set():
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5))
    ws = [nd.array(np.ones(4, np.float32)) for _ in range(2)]
    gs = [jnp.ones((4,), jnp.float32)] * 2
    zu.step(["0", "1"], gs, ws)
    with pytest.raises(ValueError, match="frozen"):
        zu.step(["0"], gs[:1], ws[:1])


# ===========================================================================
# telemetry contract: one fused dispatch per dtype-bucket, sharded-state
# gauge = replicated total / world
# ===========================================================================

def test_one_fused_dispatch_per_bucket_not_per_param():
    shapes = [(64,)] * 6   # 256 B each
    rng = np.random.RandomState(3)
    ws = [nd.array(rng.randn(*s).astype(np.float32)) for s in shapes]
    gs = [jnp.asarray(rng.randn(*s).astype(np.float32)) for s in shapes]
    keys = [str(i) for i in range(len(shapes))]
    # cap of two grads per bucket -> 3 buckets for 6 params
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5, momentum=0.5),
                     cap_bytes=2 * 256)
    before, h0 = _counters(), _hist_count("opt.fused_update_ms")
    zu.step(keys, gs, ws)
    after, h1 = _counters(), _hist_count("opt.fused_update_ms")
    assert len(zu.layout) == 3
    assert h1 - h0 == 3                       # per bucket, not per param
    assert _delta(before, after, "comm.reduce_scatter") == 3
    assert _delta(before, after, "comm.all_gather") == 3


def test_state_bytes_per_rank_is_total_over_world():
    # bucket sizes divisible by world -> zero padding, exact division
    shapes = [(8, 4), (16,), (4, 4)]   # 64 elements total
    world = 4
    rng = np.random.RandomState(4)
    grads = [[rng.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(world)]
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    per_rank = [None] * world

    def run(rank, comm):
        zu = ZeroUpdater(opt_create("adam", learning_rate=0.125),
                         comm=comm)
        ws = [nd.array(w) for w in init_w]
        zu.step([str(i) for i in range(len(shapes))],
                [jnp.asarray(g) for g in grads[rank]], ws)
        per_rank[rank] = zu.state_bytes_per_rank()

    _run_fleet(world, run)
    replicated_total = 64 * 4 * 2              # mean+var, fp32
    assert all(b == replicated_total // world for b in per_rank)
    gauge = telemetry.snapshot()["gauges"].get("opt.state_bytes_per_rank")
    assert gauge and gauge["value"] == replicated_total // world


# ===========================================================================
# elastic shrink/grow: owned-shard state migrates bit-preserving across a
# world-size change
# ===========================================================================

def test_elastic_world_change_migrates_state_bit_preserving():
    shapes = [(5, 3), (7,), (4, 4)]
    rng = np.random.RandomState(5)
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]
    g4 = [[rng.randn(*s).astype(np.float32) for s in shapes]
          for _ in range(4)]
    g2 = [[rng.randn(*s).astype(np.float32) for s in shapes]
          for _ in range(2)]
    kw = {"learning_rate": 0.125, "momentum": 0.5, "rescale_grad": 1.0}
    keys = [str(i) for i in range(len(shapes))]

    # uninterrupted baseline: ONE carried updater — 2 steps with the
    # 4-rank sums, then 2 with the 2-rank sums (replicated math never
    # cares about world size, but momentum must survive the transition)
    ref = _replicated_phases("sgd", shapes, init_w, [(g4, 2), (g2, 2)],
                             **kw)

    # phase 1: world=4 fleet runs 2 steps, checkpoints (full-state payload)
    payload_box, w_box = {}, {}

    def phase1(rank, comm):
        zu = ZeroUpdater(opt_create("sgd", **kw), comm=comm)
        ws = [nd.array(w) for w in init_w]
        for _ in range(2):
            zu.step(keys, [jnp.asarray(g) for g in g4[rank]], ws)
        payload = zu.state_payload()   # collective: every rank gathers
        if rank == 0:   # payload is identical on every rank
            payload_box[0] = payload
            w_box[0] = [w.asnumpy() for w in ws]

    _run_fleet(4, phase1)

    # phase 2: SHRUNK world=2 fleet restores the payload and continues
    outs = [None, None]

    def phase2(rank, comm):
        zu = ZeroUpdater(opt_create("sgd", **kw), comm=comm)
        zu.load_state_payload(payload_box[0])
        assert zu.layout.world == 2     # re-partitioned shard boundaries
        ws = [nd.array(w) for w in w_box[0]]
        for _ in range(2):
            zu.step(keys, [jnp.asarray(g) for g in g2[rank]], ws)
        outs[rank] = [w.asnumpy() for w in ws]

    _run_fleet(2, phase2)
    for rank in range(2):
        for a, b in zip(outs[rank], ref):
            np.testing.assert_array_equal(a, b)


# ===========================================================================
# checkpoint round-trips: SnapshotCheckpointer (pickle) and orbax, incl.
# restore onto a different world size
# ===========================================================================

def _seed_updater(steps=2):
    rng = np.random.RandomState(6)
    shapes = [(5, 3), (7,)]
    zu = ZeroUpdater(opt_create("adam", learning_rate=0.125))
    ws = [nd.array(rng.randn(*s).astype(np.float32)) for s in shapes]
    for _ in range(steps):
        zu.step(["0", "1"],
                [jnp.asarray(rng.randn(*s).astype(np.float32))
                 for s in shapes], ws)
    return zu, ws


def test_snapshot_checkpointer_roundtrip(tmp_path):
    from mxnet_tpu.resilience import SnapshotCheckpointer
    zu, _ = _seed_updater()
    ck = SnapshotCheckpointer(str(tmp_path), keep=None)
    ck.save(1, {"zero": zu.state_payload()})
    step, tree = ck.restore(1)
    zu2 = ZeroUpdater(opt_create("adam", learning_rate=0.125))
    zu2.load_state_payload(tree["zero"])
    assert [b.keys for b in zu2.layout] == [b.keys for b in zu.layout]
    for spec in zu.layout:
        for slot in ("mean", "var"):
            np.testing.assert_array_equal(
                np.asarray(zu._states[spec.index][slot]),
                np.asarray(zu2._states[spec.index][slot]))


def test_orbax_zero_roundtrip_onto_different_world(tmp_path):
    from mxnet_tpu.parallel.checkpoint import (restore_zero_state,
                                               save_zero_state)
    zu, _ = _seed_updater()
    save_zero_state(str(tmp_path), zu, step=2)

    class TwoRankComm(ZeroComm):
        world = 2

        def __init__(self, rank):
            self.rank = rank

    restored = {}
    for rank in range(2):
        zu_r = ZeroUpdater(opt_create("adam", learning_rate=0.125),
                           comm=TwoRankComm(rank))
        restore_zero_state(str(tmp_path), zu_r)
        assert zu_r.layout.world == 2
        restored[rank] = zu_r
    # the two half-shards concatenate back to the saved full state
    for spec in zu.layout:
        spec2 = restored[0].layout.buckets[spec.index]
        for slot in ("mean", "var"):
            full = np.concatenate([
                np.asarray(restored[r]._states[spec.index][slot])
                for r in range(2)])[:spec.size]
            np.testing.assert_array_equal(
                full, np.asarray(zu._states[spec.index][slot])[:spec.size])
        assert spec2.shard * 2 == spec2.padded


# ===========================================================================
# Trainer / kvstore end-to-end
# ===========================================================================

def _train_gluon(zero, optname="sgd", steps=4, opt_kw=None, env_cap=None):
    mx.random.seed(0)
    np.random.seed(0)
    scope = engine.bucket_mb_scope(env_cap) if env_cap is not None else \
        engine.bucket_mb_scope(None)
    with scope:
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(8),
                    nn.Dense(2))
        net.initialize(mx.init.Xavier())
        kw = opt_kw or {"learning_rate": 0.125, "momentum": 0.5}
        tr = gluon.Trainer(net.collect_params(), optname, dict(kw),
                           update_on_kvstore=True, zero=zero)
        x = nd.array(np.random.RandomState(1).randn(8, 10)
                     .astype(np.float32))
        y = nd.array(np.ones((8,), np.float32))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        for _ in range(steps):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(8)
        return net, tr, [p.data().asnumpy()
                         for _, p in sorted(net.collect_params().items())]


@pytest.mark.parametrize("optname,opt_kw", [
    ("sgd", {"learning_rate": 0.125, "momentum": 0.5}),
    ("adam", {"learning_rate": 0.125, "beta1": 0.5, "beta2": 0.5,
              "epsilon": 2.0 ** -8}),
])
def test_trainer_zero_parity_end_to_end(optname, opt_kw):
    _, _, a = _train_gluon(True, optname, opt_kw=opt_kw)
    _, _, b = _train_gluon(False, optname, opt_kw=opt_kw)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)


def test_trainer_zero_env_optin(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_ZERO", "1")
    net = nn.Dense(3, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    with autograd.record():
        loss = net(nd.ones((2, 4))).sum()
    loss.backward()
    tr.step(2)
    assert isinstance(tr._kvstore._updater, ZeroUpdater)
    assert tr._update_on_kvstore


def test_trainer_zero_bucket_escape_hatch_still_shards():
    """MXNET_TPU_COMM_BUCKET_MB=0 cannot disable ZeRO — the layout
    degrades to one bucket per dtype and the sharded update still runs."""
    net, tr, a = _train_gluon(True, env_cap=0)
    assert isinstance(tr._kvstore._updater, ZeroUpdater)
    assert len(tr._kvstore._updater.layout) == 1
    _, _, b = _train_gluon(False, env_cap=None)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)


def test_trainer_zero_rejects_update_on_kvstore_false():
    net = nn.Dense(3, in_units=4)
    net.initialize()
    with pytest.raises(ValueError, match="update_on_kvstore"):
        gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                      update_on_kvstore=False, zero=True)


def test_trainer_zero_save_load_states_resumes_bit_exact(tmp_path):
    """Trainer.save_states/load_states (the SnapshotCheckpointer payload
    of the Gluon path) round-trips the sharded state: resume + 2 steps ==
    uninterrupted 4 steps."""
    fname = str(tmp_path / "trainer.states")
    net, tr, _ = _train_gluon(True, steps=2)
    tr.save_states(fname)
    saved = [p.data().asnumpy()
             for _, p in sorted(net.collect_params().items())]
    _, _, ref = _train_gluon(True, steps=4)

    # fresh net+trainer, params rewound to step 2, states reloaded
    # (match params by sorted position — the fresh net gets new name
    # prefixes from the global name scope)
    mx.random.seed(0)
    np.random.seed(0)
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(16, activation="relu"), nn.Dense(8), nn.Dense(2))
    net2.initialize(mx.init.Xavier())
    for (_, p), arr in zip(sorted(net2.collect_params().items()), saved):
        p.set_data(nd.array(arr))
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.125, "momentum": 0.5},
                        update_on_kvstore=True, zero=True)
    tr2.load_states(fname)
    x = nd.array(np.random.RandomState(1).randn(8, 10).astype(np.float32))
    y = nd.array(np.ones((8,), np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(2):
        with autograd.record():
            loss = loss_fn(net2(x), y)
        loss.backward()
        tr2.step(8)
    resumed = [p.data().asnumpy()
               for _, p in sorted(net2.collect_params().items())]
    for a, b in zip(resumed, ref):
        np.testing.assert_array_equal(a, b)


def test_kvstore_zero_rejects_sparse():
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.ndarray import sparse as sp
    kv = mx.kv.create("device")
    kv.set_optimizer(opt_create("sgd", learning_rate=0.1), zero=True)
    kv.init(0, nd.zeros((4, 2)))
    g = sp.row_sparse_array((np.ones((1, 2), np.float32), [0]),
                            shape=(4, 2))
    with pytest.raises(MXNetError, match="dense"):
        kv.push(0, g)


def test_zero_reduce_scatter_fault_site_retries():
    from mxnet_tpu.resilience import faults
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5))
    ws = [nd.array(np.ones(4, np.float32))]
    before = _counters()
    with faults.inject("collective.reduce_scatter:error:1"):
        zu.step(["0"], [jnp.ones((4,), jnp.float32)], ws)
    after = _counters()
    assert _delta(before, after,
                  "resilience.retries.collective.reduce_scatter") >= 1
    np.testing.assert_array_equal(ws[0].asnumpy(), np.full(4, 0.5))


# ===========================================================================
# dist kvstore: ZeRO routing + per-bucket 2-bit compression residuals
# ===========================================================================

def _dist_store():
    from mxnet_tpu.kvstore.kvstore_dist import KVStoreDist
    return KVStoreDist("dist_sync")


def test_dist_zero_parity_single_worker():
    def run(zero):
        kv = _dist_store()
        kv.set_optimizer(opt_create("sgd", learning_rate=0.5, momentum=0.5,
                                    rescale_grad=1.0), zero=zero)
        rng = np.random.RandomState(0)
        keys = list(range(5))
        for k in keys:
            kv.init(k, nd.array(rng.randn(4).astype(np.float32)))
        for _ in range(3):
            kv.push(keys, [nd.array(rng.randn(4).astype(np.float32))
                           for _ in keys])
        outs = [nd.zeros((4,)) for _ in keys]
        kv.pull(keys, out=outs)
        return [o.asnumpy() for o in outs]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_dist_compression_bucketed_residuals_parity():
    """ISSUE 9 satellite: 2-bit residuals keyed per persistent bucket are
    bit-identical to the per-key path across multiple steps (residual
    state must track identically), through BOTH push and pushpull."""
    def run(mb, via_pushpull):
        with engine.bucket_mb_scope(mb):
            kv = _dist_store()
            kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
            rng = np.random.RandomState(1)
            for k in range(4):
                kv.init(k, nd.zeros((6,)))
            for _ in range(3):
                vals = [nd.array(rng.randn(6).astype(np.float32))
                        for _ in range(4)]
                if via_pushpull:
                    outs = [nd.zeros((6,)) for _ in range(4)]
                    kv.pushpull(list(range(4)), vals, out=outs)
                else:
                    kv.push(list(range(4)), vals)
            outs = [nd.zeros((6,)) for _ in range(4)]
            kv.pull(list(range(4)), out=outs)
            return [o.asnumpy() for o in outs]

    for via_pushpull in (False, True):
        ref = run(0, via_pushpull)          # per-key escape hatch
        for a, b in zip(run(25, via_pushpull), ref):
            np.testing.assert_array_equal(a, b)


def test_dist_compression_bucketed_residual_keys_on_bucket():
    with engine.bucket_mb_scope(25):
        kv = _dist_store()
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        for k in range(3):
            kv.init(k, nd.zeros((4,)))
        kv.push(list(range(3)),
                [nd.array(np.full(4, 0.3, np.float32)) for _ in range(3)])
        assert kv._gc_layout is not None and len(kv._gc_layout) == 1
        # ONE residual entry for the whole bucket, not one per key
        assert list(kv._gc._residual.keys()) == ["__bucket__0"]


def test_dist_compression_changed_key_set_refreezes_with_warning():
    with engine.bucket_mb_scope(25):
        kv = _dist_store()
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        for k in range(4):
            kv.init(k, nd.zeros((4,)))
        kv.push([0, 1, 2], [nd.array(np.ones(4, np.float32))] * 3)
        old_layout = kv._gc_layout
        assert old_layout is not None
        # a different key set after the freeze RE-freezes the layout
        # (warned — the old buckets' residuals are dropped) and stays on
        # the bucketed path for the new stable set
        with pytest.warns(UserWarning, match="re-frozen"):
            kv.push([0, 1, 2, 3], [nd.array(np.ones(4, np.float32))] * 4)
        assert kv._gc_layout is not None
        assert kv._gc_layout.keys() == ["0", "1", "2", "3"]
        kv.push([0, 1, 2, 3], [nd.array(np.ones(4, np.float32))] * 4)
        outs = [nd.zeros((4,)) for _ in range(4)]
        kv.pull(list(range(4)), out=outs)
        assert np.isfinite(outs[3].asnumpy()).all()


def test_dist_compression_bucketed_counts_buckets_per_step():
    with engine.bucket_mb_scope(25):
        kv = _dist_store()
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        for k in range(3):
            kv.init(k, nd.zeros((8,)))
        vals = [nd.array(np.ones(8, np.float32)) for _ in range(3)]
        kv.push(list(range(3)), vals)   # freeze step (counted by bucketize)
        before = _counters()
        kv.push(list(range(3)), vals)
        kv.push(list(range(3)), vals)
        after = _counters()
        # steady state: one bucket counted per push, like _push_bucketed
        assert _delta(before, after, "comm.bucket.count") == 2
        assert _delta(before, after, "comm.bucket.bytes") == 2 * 3 * 8 * 4


def test_reduce_scatter_multi_rejects_zero_size_arrays():
    from mxnet_tpu.parallel import collectives
    with pytest.raises(ValueError, match="zero-size"):
        collectives.reduce_scatter_multi(
            [jnp.ones((4,)), jnp.zeros((0,))], "data", axis_size=2)


# ===========================================================================
# in-mesh fused collectives
# ===========================================================================

def test_reduce_scatter_all_gather_multi_roundtrip():
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel.mesh import local_mesh
    mesh = local_mesh()
    n = mesh.devices.size
    ax = mesh.axis_names[0]
    rng = np.random.RandomState(0)
    shapes = [(5, 3), (7,), (4, 4)]
    xs = [jnp.asarray(rng.randn(n, *s).astype(np.float32)) for s in shapes]
    box = {}

    def f(*per_dev):
        # in_specs P(ax) keeps a leading length-1 block dim; drop it so
        # each device contributes its own (shape,) array
        shards, layout = collectives.reduce_scatter_multi(
            [x[0] for x in per_dev], ax, axis_size=n)
        box["layout"] = layout
        return tuple(collectives.all_gather_multi(shards, layout, ax))

    before = _counters()
    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P(ax), out_specs=P(),
                            check_rep=False))(*xs)
    after = _counters()
    for x, o in zip(xs, out):
        np.testing.assert_allclose(np.asarray(o), np.asarray(x).sum(0),
                                   rtol=1e-5)
    layout = box["layout"]
    assert len(layout) == 1          # 38 elems -> one bucket
    assert layout.buckets[0].padded % n == 0
    # trace-time counters: one per bucket per trace
    assert _delta(before, after, "comm.reduce_scatter") == 1
    assert _delta(before, after, "comm.all_gather") == 1


def test_reduce_scatter_multi_requires_axis_size_or_layout():
    from mxnet_tpu.parallel import collectives
    with pytest.raises(ValueError, match="axis_size"):
        collectives.reduce_scatter_multi([jnp.ones((4,))], "data")


# ===========================================================================
# ShardedTrainStep zero composition
# ===========================================================================

def test_sharded_train_step_zero_parity_and_state_sharding():
    from mxnet_tpu.parallel import ShardedTrainStep
    from mxnet_tpu.parallel.mesh import local_mesh
    mesh = local_mesh()
    n = mesh.devices.size
    if "data" not in mesh.axis_names or n == 1:
        pytest.skip("needs a data-axis mesh")

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    def run(zero):
        params = {"w": jnp.ones((n, 4)), "b": jnp.zeros((4,))}
        st = ShardedTrainStep(loss_fn, params, mesh, optimizer="adam",
                              lr=0.125, zero=zero)
        p, s = st.init()
        if zero:
            # state leading dims shard over 'data' where divisible;
            # indivisible leaves keep the rules' (replicated) spec
            assert "data" in tuple(s["m"]["w"].sharding.spec)
            assert tuple(s["m"]["b"].sharding.spec) in ((), (None,))
        batch = {"x": jnp.asarray(
                     np.arange(n * 16 * n).reshape(16 * n, n) % 7,
                     jnp.float32),
                 "y": jnp.ones((16 * n, 4))}
        for i in range(3):
            p, s, loss = st(p, s, batch, i)
        return np.asarray(p["w"]), float(loss)

    (wa, la), (wb, lb) = run(True), run(False)
    np.testing.assert_array_equal(wa, wb)
    assert la == lb


# ===========================================================================
# tooling: parse_log --comm carries the ZeRO rows
# ===========================================================================

def test_parse_log_comm_zero_rows(tmp_path):
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5, momentum=0.5))
    ws = [nd.array(np.ones(64, np.float32)) for _ in range(3)]
    zu.step(["0", "1", "2"], [jnp.ones((64,), jnp.float32)] * 3, ws)
    dump = str(tmp_path / "telemetry.json")
    telemetry.dump(dump)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--comm"],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert "comm.reduce_scatter" in proc.stdout
    assert "comm.all_gather" in proc.stdout
    assert "opt.state_bytes_per_rank" in proc.stdout
    assert "opt.fused_update_ms_avg" in proc.stdout
