"""Gluon core tests — modeled on reference tests/python/unittest/test_gluon.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier", ctx=[mx.cpu(0)])
    assert len(p.list_data()) == 1
    assert len(p.list_grad()) == 1
    assert p.data(mx.cpu(0)).context == mx.cpu(0)
    assert p.data(mx.cpu(0)).shape == (10, 10)
    assert p.var().name == "weight"
    assert p.grad(mx.cpu(0)).stype == "default"


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()
    with pytest.raises(RuntimeError):
        p.list_data()


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu())


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False,
                     prefix="test_")
    inputs = mx.nd.zeros((2, 3, 10))
    model.initialize()
    outputs = model(inputs)
    assert {p.name for p in model.collect_params().values()} == \
        {"test_weight", "test_bias"}
    assert outputs.shape == (2, 3, 128)

    model = nn.Dense(128, activation="relu", in_units=30, flatten=True,
                     prefix="test2_")
    inputs = mx.nd.zeros((17, 2, 5, 3))
    model.initialize()
    outputs = model(inputs)
    assert outputs.shape == (17, 128)


def test_dense_deferred_shape():
    model = nn.Dense(4)
    model.initialize()
    out = model(mx.nd.ones((3, 7)))
    assert out.shape == (3, 4)
    assert model.weight.shape == (4, 7)


def test_sequential_training():
    """MLP trains end-to-end: loss decreases (the SURVEY §7 config-1 slice)."""
    np.random.seed(0)
    x = np.random.normal(size=(64, 10)).astype("float32")
    w = np.random.normal(size=(10, 1)).astype("float32")
    y = (x @ w > 0).astype("float32").reshape(-1)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"),
                nn.Dense(2))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    data, label = mx.nd.array(x), mx.nd.array(y)

    losses = []
    for _ in range(30):
        with autograd.record():
            out = net(data)
            loss = loss_fn(out, label)
        loss.backward()
        trainer.step(64)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = mx.nd.array(np.random.normal(size=(4, 5)).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)
    # second call goes through compiled cache
    hybrid2 = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid2, rtol=1e-5, atol=1e-6)


def test_hybridize_grad_matches_eager():
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh"), nn.Dense(1))
        return net

    x = mx.nd.array(np.random.normal(size=(4, 5)).astype("float32"))

    net = build()
    net.initialize()
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    eager_grads = {k: v.grad().asnumpy()
                   for k, v in net.collect_params().items()}

    net.hybridize()
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    for k, v in net.collect_params().items():
        np.testing.assert_allclose(eager_grads[k], v.grad().asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_batchnorm_moving_stats():
    layer = nn.BatchNorm(in_channels=4)
    layer.initialize()
    x = mx.nd.array(np.random.normal(2.0, 3.0, size=(8, 4, 3, 3))
                    .astype("float32"))
    with autograd.record():
        layer(x)
    rm = layer.running_mean.data().asnumpy()
    assert not np.allclose(rm, np.zeros(4)), "moving mean should update"
    # predict mode uses moving stats, output differs from train mode
    out_pred = layer(x).asnumpy()
    assert out_pred.shape == x.shape


def test_batchnorm_moving_stats_hybridized():
    layer = nn.BatchNorm(in_channels=4)
    layer.initialize()
    layer.hybridize()
    x = mx.nd.array(np.random.normal(1.0, 2.0, size=(8, 4))
                    .astype("float32"))
    with autograd.record():
        layer(x)
    rm = layer.running_mean.data().asnumpy()
    assert not np.allclose(rm, np.zeros(4)), \
        "moving mean should update through the jit trace"


def test_conv_layers():
    layer = nn.Conv2D(16, (3, 3), in_channels=4)
    layer.initialize()
    x = mx.nd.ones((2, 4, 10, 10))
    assert layer(x).shape == (2, 16, 8, 8)

    layer = nn.Conv2D(16, (3, 3), padding=(1, 1), strides=(2, 2))
    layer.initialize()
    assert layer(x).shape == (2, 16, 5, 5)

    layer = nn.MaxPool2D((2, 2), strides=(2, 2))
    assert layer(x).shape == (2, 4, 5, 5)

    layer = nn.GlobalAvgPool2D()
    assert layer(x).shape == (2, 4, 1, 1)

    layer = nn.Conv2DTranspose(8, (2, 2), strides=(2, 2), in_channels=4)
    layer.initialize()
    assert layer(x).shape == (2, 8, 20, 20)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, in_units=5), nn.Dense(3, in_units=8))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(8, in_units=5), nn.Dense(3, in_units=8))
    net2.load_parameters(f)
    x = mx.nd.ones((2, 5))
    np.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(),
                               rtol=1e-6)


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(3, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.1})
    x = mx.nd.ones((2, 4))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.step(2)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr2 = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.1})
    tr2.load_states(f)
    assert tr2._optimizer.num_update == tr._optimizer.num_update


def test_optimizers_decrease_loss():
    for name in ["sgd", "adam", "rmsprop", "adagrad", "nag", "adadelta",
                 "adamax", "nadam", "ftrl", "signum", "ftml", "lamb",
                 "adamw"]:
        net = nn.Dense(1, in_units=3)
        net.initialize(mx.init.Normal(0.5))
        if name == "adadelta":
            opt_params = {}
        elif name in ("adamax", "nadam", "signum"):
            opt_params = {"learning_rate": 0.01}
        else:
            opt_params = {"learning_rate": 0.05}
        tr = gluon.Trainer(net.collect_params(), name, opt_params)
        x = mx.nd.array(np.random.normal(size=(16, 3)).astype("float32"))
        y = mx.nd.array(np.ones((16, 1), dtype="float32"))
        l2 = gluon.loss.L2Loss()
        first = None
        for _ in range(10):
            with autograd.record():
                loss = l2(net(x), y)
            loss.backward()
            tr.step(16)
            cur = float(loss.mean().asscalar())
            if first is None:
                first = cur
        assert cur < first, "optimizer %s did not reduce loss" % name


def test_block_repr_and_summary(capsys):
    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize()
    repr(net)
    net.summary(mx.nd.ones((2, 3)))
    out = capsys.readouterr().out
    assert "Dense" in out and "Total params" in out


def test_constant_param():
    class Net(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.const = self.params.get_constant(
                "const", np.ones((2, 2), dtype="float32"))

        def hybrid_forward(self, F, x, const):
            return x + const

    net = Net()
    net.initialize()
    out = net(mx.nd.zeros((2, 2)))
    np.testing.assert_allclose(out.asnumpy(), np.ones((2, 2)))
    # constants take no gradient
    assert net.const.grad_req == "null"


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import (FactorScheduler, PolyScheduler,
                                        CosineScheduler,
                                        MultiFactorScheduler)
    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    s = MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert abs(s(12) - 0.01) < 1e-9
    s = PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert abs(s(50) - 0.5) < 1e-6
    s = CosineScheduler(max_update=100, base_lr=1.0)
    assert abs(s(100)) < 1e-6
    # warmup
    s = FactorScheduler(step=100, base_lr=1.0, warmup_steps=10,
                        warmup_begin_lr=0.0)
    assert s(5) == 0.5


def test_lbsgd_warmup_and_lars():
    """LBSGD (reference: optimizer.py LBSGD): warmup multiplier ramps to
    batch_scale; warmup_strategy='lars' computes the layer-adaptive rate."""
    from mxnet_tpu.optimizer import create as opt_create
    o = opt_create("lbsgd", learning_rate=0.1, momentum=0.9, batch_scale=4,
                   warmup_epochs=1, updates_per_epoch=2)
    w = mx.nd.array(np.full((4,), 2.0, np.float32))
    g = mx.nd.array(np.full((4,), 0.5, np.float32))
    st = o.create_state(0, w)
    for _ in range(4):
        o.update(0, w, g, st)
    assert o.lbmult == 4.0, o.lbmult

    o = opt_create("lbsgd", learning_rate=0.1, warmup_strategy="lars")
    w = mx.nd.array(np.full((4,), 2.0, np.float32))
    o.update(0, w, g, None)
    # lars = sqrt(|w|^2 / (|g|^2 + wd|w|^2 + eps)) = sqrt(16/1) = 4
    assert abs(o.lbmult - 4.0) < 1e-5, o.lbmult


def test_conv_pooling_nhwc_layout():
    """Channels-last convolution/pooling (reference: NHWC conv support,
    GPU-only there; first-class here) match the NCHW math."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype("float32")
    w = rng.randn(4, 3, 3, 3).astype("float32")     # OIHW
    b = rng.randn(4).astype("float32")
    out1 = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                             kernel=(3, 3), num_filter=4,
                             pad=(1, 1)).asnumpy()
    xl = np.transpose(x, (0, 2, 3, 1))
    wl = np.transpose(w, (0, 2, 3, 1))              # OHWI
    out2 = mx.nd.Convolution(mx.nd.array(xl), mx.nd.array(wl),
                             mx.nd.array(b), kernel=(3, 3), num_filter=4,
                             pad=(1, 1), layout="NHWC").asnumpy()
    np.testing.assert_allclose(np.transpose(out2, (0, 3, 1, 2)), out1,
                               rtol=1e-4, atol=1e-5)
    p1 = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                       pool_type="max").asnumpy()
    p2 = mx.nd.Pooling(mx.nd.array(xl), kernel=(2, 2), stride=(2, 2),
                       pool_type="max", layout="NHWC").asnumpy()
    np.testing.assert_allclose(np.transpose(p2, (0, 3, 1, 2)), p1)

    # gluon layer: deferred init infers channels from the LAST axis
    net = nn.Conv2D(4, 3, padding=1, layout="NHWC")
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    y = net(mx.nd.array(xl))
    assert y.shape == (2, 8, 8, 4)
    assert net.weight.shape == (4, 3, 3, 3)   # (O, kh, kw, I)
