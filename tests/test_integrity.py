"""Training integrity plane (ISSUE 20): divergence sentinel, rollback-to-
last-good, checksummed checkpoints, AMP overflow bridge, chaos soak.

The parity bar everywhere is BIT-identical, not allclose: rollback must
restore the exact snapshot and the skip-adjusted replay must follow the
exact clean trajectory, or silent drift hides behind tolerances.
"""
import math
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, nd, resilience as rz, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faults, integrity
from mxnet_tpu.resilience.errors import (CheckpointCorruptError,
                                         DivergenceError, FatalTrainingError)
from mxnet_tpu.resilience.run import SnapshotCheckpointer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter(name):
    return telemetry.snapshot()["counters"].get(name, 0)


@pytest.fixture(autouse=True)
def _clean_integrity():
    telemetry.enable()
    integrity.reset()
    faults.deactivate()
    yield
    integrity.reset()
    faults.deactivate()


def _build_mlp():
    mx.random.seed(42)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    return net, tr


def _batches(n=8, poison=None):
    rng = np.random.RandomState(0)
    X = rng.rand(n, 32, 8).astype(np.float32)
    Y = rng.randint(0, 3, (n, 32)).astype(np.float32)
    if poison is not None:
        X[poison, 0, 0] = np.nan
    return X, Y


def _params_of(net):
    return [(k, p.data().asnumpy())
            for k, p in sorted(net.collect_params().items())]


def _assert_bit_identical(net_a, net_b):
    for (ka, a), (_, b) in zip(_params_of(net_a), _params_of(net_b)):
        assert a.tobytes() == b.tobytes(), "param %s drifted" % ka


# ---------------------------------------------------------------------------
# sentinel unit behavior
# ---------------------------------------------------------------------------
def test_divergence_error_carries_context():
    integrity.set_step(17)
    with pytest.raises(DivergenceError) as ei:
        integrity.check_finite([np.array([1.0, np.nan])],
                               site="kvstore.bucket", keys=["3", "4"])
    err = ei.value
    assert err.step == 17 and err.site == "kvstore.bucket"
    assert err.keys == ["3", "4"]
    assert "kvstore.bucket" in err.format_report()
    assert _counter("integrity.divergences.kvstore.bucket") == 1


def test_loss_sentinel_nonfinite_always_trips():
    with pytest.raises(DivergenceError):
        integrity.observe_loss(float("nan"), step=3)
    with pytest.raises(DivergenceError):
        integrity.observe_loss(float("inf"), step=4)


def test_loss_spike_factor_trips_after_warmup(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_LOSS_SPIKE_FACTOR", "10")
    for i in range(9):
        integrity.observe_loss(1.0 + 0.01 * i, step=i)
    before = _counter("integrity.loss_spikes")
    with pytest.raises(DivergenceError, match="rolling median"):
        integrity.observe_loss(500.0, step=9)
    assert _counter("integrity.loss_spikes") == before + 1
    # the spike did not join the window: the baseline survives
    with pytest.raises(DivergenceError):
        integrity.observe_loss(400.0, step=10)


def test_loss_spike_within_factor_passes(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_LOSS_SPIKE_FACTOR", "10")
    for i in range(12):
        integrity.observe_loss(1.0, step=i)
    integrity.observe_loss(5.0, step=12)  # 5x median: under the bar


def test_sentinel_off_by_default_lets_nan_through(tmp_path):
    """Gating: without MXNET_TPU_INTEGRITY the fused step must not pay for
    (or raise) the check — the NaN lands in the params."""
    X, Y = _batches(poison=2)
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    for i in range(4):
        fused(nd.array(X[i]), nd.array(Y[i]))
    finite = all(np.isfinite(a).all() for _, a in _params_of(net))
    assert not finite


# ---------------------------------------------------------------------------
# rollback parity: FusedTrainStep / Trainer / Trainer(zero=True)
# ---------------------------------------------------------------------------
def test_fused_step_nan_rollback_bit_identical(tmp_path, monkeypatch):
    """Corrupt-kind fault poisons batch 3; the in-program sentinel raises,
    the runner rolls back to the last committed snapshot and skips the
    poisoned index — final params bit-identical to the clean run that
    never saw that batch."""
    monkeypatch.setenv("MXNET_TPU_INTEGRITY", "1")
    X, Y = _batches()
    batch_fn = lambda i: (nd.array(X[i]), nd.array(Y[i]))  # noqa: E731
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    with faults.inject("train.batch:corrupt:4"):
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2,
            max_restarts=3)
        report = runner.run(6)
    assert report.rollbacks == 1 and report.skipped_batches == 1
    assert report.restarts == 0  # rollback has its own budget
    assert _counter("resilience.rollbacks") >= 1
    assert _counter("resilience.skipped_batches") >= 1
    final_idx = [runner.data_index(s) for s in range(6)]
    assert final_idx == [0, 1, 2, 4, 5, 6]

    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    for i in final_idx:
        fused_a(*batch_fn(i))
    _assert_bit_identical(net_a, net_b)


def _trainer_state_io(net, tr, tmp_path):
    sfile = str(tmp_path / "trainer.states")

    def state_get():
        tr.save_states(sfile)
        with open(sfile, "rb") as f:
            blob = f.read()
        return {"params": {k: p.data().asnumpy()
                           for k, p in net.collect_params().items()},
                "opt": blob}

    def state_set(tree):
        for k, p in net.collect_params().items():
            p.set_data(nd.array(tree["params"][k]))
        # weights live ON the store under update_on_kvstore: re-init the
        # kvstore from the restored params, then reload optimizer state
        tr._reset_kvstore()
        with open(sfile, "wb") as f:
            f.write(tree["opt"])
        tr.load_states(sfile)

    return state_get, state_set


def _trainer_rollback_parity(tmp_path, monkeypatch, zero):
    """Shared body: poisoned batch 3 trips the bucket sentinel inside
    tr.step (kvstore.bucket for the local bucketed path, zero.bucket for
    the ZeRO reduce-scatter guard); rollback + skip must reproduce the
    clean trajectory bit-exactly."""
    monkeypatch.setenv("MXNET_TPU_INTEGRITY", "1")
    steps, poison = 6, 3
    X, Y = _batches(poison=poison)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def build():
        mx.random.seed(42)
        net = nn.HybridSequential()
        with net.name_scope():
            # explicit in_units: the runner snapshots state BEFORE the first
            # forward, so shapes cannot stay deferred
            net.add(nn.Dense(16, in_units=8, activation="relu"),
                    nn.Dense(3, in_units=16))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore="device", update_on_kvstore=True,
                           zero=zero)
        return net, tr

    def one_step(net, tr, i):
        x, y = nd.array(X[i]), nd.array(Y[i])
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(x.shape[0])
        return float(loss.mean().asnumpy())

    with engine.bucket_mb_scope(0.001):  # several buckets, not one
        net_b, tr_b = build()
        state_get, state_set = _trainer_state_io(net_b, tr_b, tmp_path)
        runner = rz.ResilientRunner(
            lambda i: one_step(net_b, tr_b, i),
            state_get=state_get, state_set=state_set,
            ckpt_dir=str(tmp_path / "ck"), ckpt_every=2, max_restarts=3)
        report = runner.run(steps)
        assert report.rollbacks == 1 and report.skipped_batches == 1
        final_idx = [runner.data_index(s) for s in range(steps)]
        assert poison not in final_idx

        net_a, tr_a = build()
        for i in final_idx:
            one_step(net_a, tr_a, i)
    _assert_bit_identical(net_a, net_b)
    assert all(np.isfinite(a).all() for _, a in _params_of(net_b))


def test_trainer_bucketed_nan_rollback_bit_identical(tmp_path, monkeypatch):
    _trainer_rollback_parity(tmp_path, monkeypatch, zero=None)


def test_trainer_zero_nan_rollback_bit_identical(tmp_path, monkeypatch):
    _trainer_rollback_parity(tmp_path, monkeypatch, zero=True)


def test_resume_after_rollback_roundtrip(tmp_path, monkeypatch):
    """Skip windows ride the checkpoint: a process kill after a rollback
    resumes with the poisoned index still skipped, and the 10-step result
    is bit-identical to the clean run over the final trajectory."""
    monkeypatch.setenv("MXNET_TPU_INTEGRITY", "1")
    X, Y = _batches(n=12)
    batch_fn = lambda i: (nd.array(X[i]), nd.array(Y[i]))  # noqa: E731
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    with faults.inject("train.batch:corrupt:4"):
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=3)
        runner.run(6)
    assert runner.data_index(5) == 6

    # "process kill": perturb live state, fresh runner, resume from disk
    for _, p in net_b.collect_params().items():
        p.set_data(p.data() * 0.0)
    fused_b2 = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    runner2 = rz.ResilientRunner.for_fused_step(
        fused_b2, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
        max_restarts=3)
    runner2.run(10, resume=True)
    final_idx = [runner2.data_index(s) for s in range(10)]
    assert final_idx == [0, 1, 2, 4, 5, 6, 7, 8, 9, 10]

    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    for i in final_idx:
        fused_a(*batch_fn(i))
    _assert_bit_identical(net_a, net_b)


def test_rollback_budget_escalates_fatal(tmp_path, monkeypatch):
    """Every batch poisoned from call 4 on: rollback+skip can never make
    progress, so the consecutive-rollback budget must escalate to
    FatalTrainingError instead of looping forever."""
    monkeypatch.setenv("MXNET_TPU_INTEGRITY", "1")
    monkeypatch.setenv("MXNET_TPU_ROLLBACK_BUDGET", "2")
    X, Y = _batches(n=16)
    batch_fn = lambda i: (nd.array(X[i]), nd.array(Y[i]))  # noqa: E731
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    with faults.inject("train.batch:corrupt:4+"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=10)
        with pytest.raises(FatalTrainingError, match="rollback"):
            runner.run(8)


def test_divergence_without_checkpointer_surfaces(tmp_path, monkeypatch):
    """No checkpointer configured: nothing to roll back to — the
    DivergenceError itself must surface, not a secondary failure."""
    monkeypatch.setenv("MXNET_TPU_INTEGRITY", "1")
    X, Y = _batches()
    batch_fn = lambda i: (nd.array(X[i]), nd.array(Y[i]))  # noqa: E731
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    with faults.inject("train.batch:corrupt:2"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=None, max_restarts=3)
        with pytest.raises(DivergenceError):
            runner.run(6)


def test_skip_policy_pluggable(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_INTEGRITY", "1")
    X, Y = _batches(n=10)
    batch_fn = lambda i: (nd.array(X[i]), nd.array(Y[i]))  # noqa: E731
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    with faults.inject("train.batch:corrupt:4"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2,
            max_restarts=3, skip_policy=lambda step, exc: 3)
        report = runner.run(6)
    assert report.skipped_batches == 3
    assert [runner.data_index(s) for s in range(6)] == [0, 1, 2, 6, 7, 8]


# ---------------------------------------------------------------------------
# checksummed snapshots: the corruption matrix
# ---------------------------------------------------------------------------
def _ck_with_two_steps(tmp_path):
    ck = SnapshotCheckpointer(str(tmp_path / "ck"), keep=4)
    ck.save(1, {"w": np.arange(4.0), "step": 1})
    ck.save(2, {"w": np.arange(4.0) * 2, "step": 2})
    return ck


def test_snapshot_truncated_payload_falls_back(tmp_path):
    ck = _ck_with_two_steps(tmp_path)
    with open(ck._file(2), "r+b") as f:
        f.truncate(10)
    before = _counter("checkpoint.corrupt")
    step, tree = ck.restore()
    assert step == 1 and tree["step"] == 1
    assert _counter("checkpoint.corrupt") == before + 1
    assert _counter("checkpoint.corrupt_fallbacks") >= 1


def test_snapshot_flipped_bytes_falls_back(tmp_path):
    ck = _ck_with_two_steps(tmp_path)
    with open(ck._file(2), "r+b") as f:
        blob = bytearray(f.read())
        blob[len(blob) // 2] ^= 0xFF
        f.seek(0)
        f.write(bytes(blob))
    step, tree = ck.restore()
    assert step == 1 and tree["step"] == 1


def test_snapshot_stale_latest_marker_never_crashes(tmp_path):
    """LATEST flipped to garbage bytes: the scan fallback restores the
    newest durable step — counted nowhere, crashed never."""
    ck = _ck_with_two_steps(tmp_path)
    with open(os.path.join(ck.path, "LATEST"), "wb") as f:
        f.write(b"\xff\x13garbage")
    assert ck.latest_step() == 2
    step, tree = ck.restore()
    assert step == 2 and tree["step"] == 2


def test_snapshot_marker_names_missing_step_falls_back(tmp_path):
    from mxnet_tpu.util import write_latest_marker
    ck = _ck_with_two_steps(tmp_path)
    write_latest_marker(ck.path, 9)  # stale: step 9 was retained away
    assert ck.latest_step() == 2
    step, _ = ck.restore()
    assert step == 2


def test_snapshot_injected_corruption_between_prepare_and_commit(tmp_path):
    """The checkpoint.corrupt transform flips bytes ON DISK between pickle
    and atomic write, while the sidecar keeps the true digest — restore
    must detect it and fall back even though commit() succeeded."""
    ck = SnapshotCheckpointer(str(tmp_path / "ck"), keep=4)
    ck.save(1, {"w": np.arange(4.0)})
    with faults.inject("checkpoint.corrupt:corrupt:1"):
        ck.prepare(2, {"w": np.arange(4.0) * 2})
        ck.commit(2)
    assert ck.latest_step() == 2  # committed: the marker moved
    before = _counter("checkpoint.corrupt")
    step, tree = ck.restore()
    assert step == 1
    assert _counter("checkpoint.corrupt") == before + 1


def test_snapshot_all_corrupt_raises(tmp_path):
    ck = _ck_with_two_steps(tmp_path)
    for s in (1, 2):
        with open(ck._file(s), "r+b") as f:
            f.truncate(8)
    with pytest.raises(CheckpointCorruptError) as ei:
        ck.restore()
    assert ei.value.steps_tried == [2, 1]


def test_snapshot_missing_sidecar_still_loads(tmp_path):
    """Pre-checksum snapshots (no .sha256 sidecar) must stay restorable."""
    ck = _ck_with_two_steps(tmp_path)
    os.remove(ck._digest_file(2))
    step, tree = ck.restore()
    assert step == 2


def test_runner_restores_past_corrupt_snapshot(tmp_path, monkeypatch):
    """End-to-end: newest snapshot corrupted on disk, then a preemption —
    the runner falls back to the older snapshot, replays, and still
    matches the clean trajectory bit-exactly."""
    X, Y = _batches()
    batch_fn = lambda i: (nd.array(X[i]), nd.array(Y[i]))  # noqa: E731
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    with faults.inject(
            "checkpoint.corrupt:corrupt:2;run.step:preempt:5"):
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2,
            max_restarts=3)
        report = runner.run(6)
    assert report.restarts == 1
    assert _counter("checkpoint.corrupt_fallbacks") >= 1

    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    for i in range(6):
        fused_a(*batch_fn(i))
    _assert_bit_identical(net_a, net_b)


# ---------------------------------------------------------------------------
# orbax (sharded) checksums
# ---------------------------------------------------------------------------
def _orbax_corrupt(root, step):
    """Flip a byte in every ocdbt data chunk of the step — the array
    payload lives in the d/ files (tensorstore may surface the damage as
    a read error or as silently different values; both must be caught)."""
    import glob
    victims = [p for p in glob.glob("%s/%d/**/*" % (root, step),
                                    recursive=True)
               if os.path.isfile(p) and os.sep + "d" + os.sep in p]
    assert victims, "no ocdbt data chunks under step %d" % step
    for victim in victims:
        with open(victim, "r+b") as f:
            blob = bytearray(f.read())
            blob[len(blob) // 2] ^= 0xFF
            f.seek(0)
            f.write(bytes(blob))


def test_sharded_checkpoint_flipped_bytes_falls_back(tmp_path):
    from mxnet_tpu.parallel import checkpoint as ckpt
    root = str(tmp_path / "sharded")
    ckpt.save_sharded(root, {"w": np.arange(8.0, dtype=np.float32)}, step=1)
    ckpt.save_sharded(root, {"w": np.arange(8.0, dtype=np.float32) * 3},
                      step=2)
    _orbax_corrupt(root, 2)
    before = _counter("checkpoint.corrupt")
    tree = ckpt.restore_sharded(root)
    np.testing.assert_array_equal(np.asarray(tree["w"]),
                                  np.arange(8.0, dtype=np.float32))
    assert _counter("checkpoint.corrupt") == before + 1
    assert _counter("checkpoint.corrupt_fallbacks") >= 1


def test_sharded_checkpoint_all_corrupt_raises(tmp_path):
    from mxnet_tpu.parallel import checkpoint as ckpt
    root = str(tmp_path / "sharded")
    ckpt.save_sharded(root, {"w": np.ones(4, np.float32)}, step=1)
    _orbax_corrupt(root, 1)
    with pytest.raises(CheckpointCorruptError):
        ckpt.restore_sharded(root)


def test_sharded_checkpoint_coordinated_commit_verified(tmp_path):
    """commit=True path (single-process election degenerates): the elected
    step's sidecar is stamped and a clean restore verifies against it."""
    from mxnet_tpu.parallel import checkpoint as ckpt
    root = str(tmp_path / "sharded")
    ckpt.save_sharded(root, {"w": np.full(4, 7.0, np.float32)}, step=3,
                      coordinated=True)
    assert os.path.isfile(os.path.join(root, "3.sha256.json"))
    assert ckpt.latest_committed_step(root) == 3
    tree = ckpt.restore_sharded(root, coordinated=True)
    np.testing.assert_array_equal(np.asarray(tree["w"]),
                                  np.full(4, 7.0, np.float32))


# ---------------------------------------------------------------------------
# AMP bridge
# ---------------------------------------------------------------------------
def _net_with_grads(poison=False):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3), nn.Dense(2))
    net.initialize()
    x = nd.array(np.random.RandomState(3).rand(5, 3).astype(np.float32))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    params = [p for p in net.collect_params().values()
              if p.grad_req != "null"]
    if poison:
        g = params[1].list_grad()[0]
        g[:] = nd.array(np.full(g.shape, np.nan, np.float32))
    return params


def _reference_has_overflow(params):
    # the pre-fusion per-grad host-sync loop, kept as the decision oracle
    for p in params:
        if p.grad_req == "null":
            continue
        for g in p.list_grad():
            if not np.isfinite(np.asarray(g.asnumpy(),
                                          dtype=np.float64)).all():
                return True
    return False


def test_amp_has_overflow_single_sync_bit_identical_decision():
    from mxnet_tpu.contrib.amp import amp
    scaler = amp.LossScaler()
    for poison in (False, True):
        params = _net_with_grads(poison=poison)
        n_grads = sum(len(p.list_grad()) for p in params)
        saved0 = _counter("amp.syncs_saved")
        got = scaler.has_overflow(params)
        assert got == _reference_has_overflow(params) == poison
        assert _counter("amp.syncs_saved") - saved0 == n_grads - 1
    assert _counter("integrity.amp_overflow") == 1


def test_amp_overflow_skip_routes_through_sentinel_counters():
    from mxnet_tpu.contrib.amp import amp
    net = nn.Dense(2, in_units=3)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    amp.init_trainer(tr)
    tr._amp_loss_scaler.loss_scale = 1.0
    x = nd.array(np.ones((4, 3), np.float32))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    for p in net.collect_params().values():
        g = p.list_grad()[0]
        g[:] = nd.array(np.full(g.shape, np.nan, np.float32))
    before_w = _params_of(net)
    skipped0 = _counter("integrity.amp_skipped_steps")
    # force the fp16-style decision path regardless of global amp state
    import mxnet_tpu.contrib.amp.amp as amp_mod
    old = amp_mod._target_dtype
    amp_mod._target_dtype = "float16"
    try:
        tr._update()
    finally:
        amp_mod._target_dtype = old
    assert _counter("integrity.amp_skipped_steps") == skipped0 + 1
    for (k, a), (_, b) in zip(before_w, _params_of(net)):
        assert a.tobytes() == b.tobytes(), "skip-step mutated %s" % k


# ---------------------------------------------------------------------------
# comm checksum lever (dist push buckets)
# ---------------------------------------------------------------------------
def test_comm_checksum_counts_and_trips_on_nonfinite(monkeypatch):
    from mxnet_tpu.kvstore.kvstore_dist import KVStoreDist
    monkeypatch.setenv("MXNET_TPU_COMM_CHECKSUM", "1")
    with engine.bucket_mb_scope(25):
        kv = KVStoreDist("dist_sync")
        for k in range(4):
            kv.init(k, nd.zeros((3,)))
        before = _counter("comm.checksum.buckets")
        kv.push(list(range(4)),
                [nd.array(np.full(3, float(k + 1), np.float32))
                 for k in range(4)])
        assert _counter("comm.checksum.buckets") > before
        bad = [nd.array(np.full(3, float(k), np.float32)) for k in range(4)]
        bad[2] = nd.array(np.array([1.0, np.nan, 2.0], np.float32))
        with pytest.raises(DivergenceError):
            kv.push(list(range(4)), bad)


# ---------------------------------------------------------------------------
# chaos soak (pytest -m chaos; rides slow CI)
# ---------------------------------------------------------------------------
def _chaos_mod():
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import chaos
    return chaos


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_train_soak_invariants():
    chaos = _chaos_mod()
    report = chaos.train_soak(seed=0, steps=30, n_faults=14)
    assert report["ok"], report
    assert report["faults_fired"] >= 12
    assert len(report["sites_hit"]) >= 5
    for kind in ("corrupt", "preempt", "hang"):
        assert kind in report["kinds_hit"], report["kinds_hit"]
    assert report["params_bit_identical"] and report["params_finite"]
    assert report["final_indices_unique"]


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_serve_soak_invariants():
    chaos = _chaos_mod()
    report = chaos.serve_soak(seed=0, requests=6, n_faults=6)
    assert report["ok"], report
    assert report["faults_fired"] >= 5
    assert report["tokens_byte_identical"]
    assert report["reconcile_exact"] and report["leaked_kv_blocks"] == 0
