"""mx.np / mx.npx namespace tests. reference idiom:
tests/python/unittest/test_numpy_op.py / test_numpy_ndarray.py."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_np_creation_and_elementwise():
    a = mx.np.arange(6).reshape((2, 3))
    b = mx.np.ones((2, 3))
    out = mx.np.add(a, b)
    onp.testing.assert_allclose(out.asnumpy(),
                                onp.arange(6).reshape(2, 3) + 1)
    assert mx.np.sum(out).asnumpy() == 21
    onp.testing.assert_allclose(
        mx.np.exp(mx.np.zeros((3,))).asnumpy(), onp.ones(3))


def test_np_matmul_and_reductions():
    a = mx.np.array(onp.random.rand(3, 4).astype("float32"))
    b = mx.np.array(onp.random.rand(4, 2).astype("float32"))
    out = mx.np.matmul(a, b)
    onp.testing.assert_allclose(out.asnumpy(), a.asnumpy() @ b.asnumpy(),
                                rtol=1e-5)
    m = mx.np.mean(a, axis=0)
    onp.testing.assert_allclose(m.asnumpy(), a.asnumpy().mean(axis=0),
                                rtol=1e-6)
    assert int(mx.np.argmax(a).asnumpy()) == int(a.asnumpy().argmax())


def test_np_autograd_flows():
    x = mx.np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = mx.np.sum(mx.np.square(mx.np.sin(x)))
    y.backward()
    expect = 2 * onp.sin([1, 2, 3]) * onp.cos([1, 2, 3])
    onp.testing.assert_allclose(x.grad.asnumpy(), expect, rtol=1e-5)


def test_np_manipulation():
    a = mx.np.arange(12).reshape((3, 4))
    st = mx.np.stack([a, a])
    assert st.shape == (2, 3, 4)
    sp = mx.np.split(a, 2, axis=1)
    assert len(sp) == 2 and sp[0].shape == (3, 2)
    w = mx.np.where(a > 5, a, mx.np.zeros_like(a))
    assert float(mx.np.sum(w).asnumpy()) == sum(range(6, 12))
    t = mx.np.transpose(a)
    assert t.shape == (4, 3)


def test_np_random_seeded():
    mx.np.random.seed(3)
    a = mx.np.random.uniform(size=(5,)).asnumpy()
    mx.np.random.seed(3)
    b = mx.np.random.uniform(size=(5,)).asnumpy()
    onp.testing.assert_array_equal(a, b)
    r = mx.np.random.randint(0, 10, size=(100,)).asnumpy()
    assert r.min() >= 0 and r.max() < 10
    n = mx.np.random.normal(2.0, 0.1, size=(2000,)).asnumpy()
    assert abs(n.mean() - 2.0) < 0.05


def test_npx_ops_and_np_mode():
    x = mx.np.array([[1.0, 2.0, 3.0]])
    s = mx.npx.softmax(x)
    onp.testing.assert_allclose(s.asnumpy().sum(), 1.0, rtol=1e-6)
    assert not mx.npx.is_np_array()
    mx.npx.set_np()
    assert mx.npx.is_np_array() and mx.npx.is_np_shape()
    mx.npx.reset_np()
    assert not mx.npx.is_np_shape()
    r = mx.npx.relu(mx.np.array([-1.0, 2.0]))
    onp.testing.assert_array_equal(r.asnumpy(), [0.0, 2.0])


# ---------------------------------------------------------------------------
# dedicated mx.np.ndarray type (reference: python/mxnet/numpy/multiarray.py)
# ---------------------------------------------------------------------------

def test_np_ndarray_is_distinct_type():
    import mxnet_tpu as mx
    x = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(x, mx.np.ndarray)
    assert isinstance(x, mx.nd.NDArray)
    assert type(x) is not mx.nd.NDArray
    # operations stay in the np type
    y = (x + 1) * 2
    assert type(y) is mx.np.ndarray
    assert type(x.sum()) is mx.np.ndarray
    assert type(x.T) is mx.np.ndarray
    assert type(mx.np.exp(x)) is mx.np.ndarray
    assert type(mx.np.random.normal(size=(2,))) is mx.np.ndarray


def test_np_ndarray_numpy_semantics():
    import numpy as np
    import mxnet_tpu as mx
    x = mx.np.array([1.0, -2.0, 3.0, -4.0])
    # boolean-mask indexing
    pos = x[x > 0]
    assert type(pos) is mx.np.ndarray
    np.testing.assert_array_equal(pos.asnumpy(), [1.0, 3.0])
    # fancy indexing
    np.testing.assert_array_equal(x[mx.np.array([0, 3]).astype("int32")]
                                  .asnumpy(), [1.0, -4.0])
    # zero-dim
    s = x.sum()
    assert s.shape == ()
    assert abs(s.item() - (-2.0)) < 1e-6
    assert x.tolist() == [1.0, -2.0, 3.0, -4.0]
    # numpy-style repr
    assert repr(x).startswith("array(")
    # iteration yields np arrays
    rows = list(mx.np.array([[1, 2], [3, 4]]).astype("float32"))
    assert len(rows) == 2 and type(rows[0]) is mx.np.ndarray


def test_np_nd_interop_and_autograd():
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    x = mx.np.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = mx.np.sum(x * x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0, 6.0])
    # legacy view shares payload
    legacy = x.as_nd_ndarray()
    assert type(legacy) is mx.nd.NDArray
    np.testing.assert_array_equal(legacy.asnumpy(), x.asnumpy())


def test_np_linalg_namespace():
    """mx.np.linalg (reference: python/mxnet/numpy/linalg.py) — factor
    routines roundtrip and the ops ride the autograd tape."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    a = mx.np.array(rng.randn(4, 4).astype(np.float32))
    spd = mx.np.matmul(a, a.T) + 4 * mx.np.eye(4)

    assert float(mx.np.linalg.norm(a).asnumpy()) > 0
    L = mx.np.linalg.cholesky(spd)
    np.testing.assert_allclose(mx.np.matmul(L, L.T).asnumpy(),
                               spd.asnumpy(), rtol=1e-4)
    u, s, vt = mx.np.linalg.svd(a)
    np.testing.assert_allclose((u.asnumpy() * s.asnumpy()) @ vt.asnumpy(),
                               a.asnumpy(), atol=1e-4)
    x = mx.np.linalg.solve(spd, mx.np.ones((4,)))
    np.testing.assert_allclose(mx.np.matmul(spd, x).asnumpy(),
                               np.ones(4), atol=1e-4)
    inv = mx.np.linalg.inv(spd)
    np.testing.assert_allclose(mx.np.matmul(spd, inv).asnumpy(),
                               np.eye(4), atol=1e-4)
    sign, logdet = mx.np.linalg.slogdet(spd)
    assert float(sign.asnumpy()) == 1.0
    qq, rr = mx.np.linalg.qr(a)
    np.testing.assert_allclose(mx.np.matmul(qq, rr).asnumpy(),
                               a.asnumpy(), atol=1e-4)
    assert type(L) is mx.np.ndarray and type(u) is mx.np.ndarray

    # differentiable: d(det)/dA = det(A) * inv(A).T
    w = mx.np.array(np.eye(3, dtype=np.float32) * 2.0)
    w.attach_grad()
    with autograd.record():
        y = mx.np.linalg.det(w)
    y.backward()
    np.testing.assert_allclose(w.grad.asnumpy(), np.eye(3) * 4.0, atol=1e-4)


def test_np_linalg_multioutput_backward():
    """NamedTuple-output linalg ops must differentiate: slogdet, svd
    (reduced — also the reference's convention), eigh, qr backward."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    rng = np.random.RandomState(1)
    w = mx.np.array((rng.randn(3, 3) @ rng.randn(3, 3).T +
                     3 * np.eye(3)).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        sign, ld = mx.np.linalg.slogdet(w)
    ld.backward()
    np.testing.assert_allclose(w.grad.asnumpy(),
                               np.linalg.inv(w.asnumpy()).T,
                               rtol=1e-4, atol=1e-5)

    # reduced SVD on a non-square matrix, forward + backward under record
    a = mx.np.array(rng.randn(3, 5).astype(np.float32))
    a.attach_grad()
    with autograd.record():
        u, s, vt = mx.np.linalg.svd(a)
        y = mx.np.sum(s)
    assert u.shape == (3, 3) and s.shape == (3,) and vt.shape == (3, 5)
    y.backward()
    assert np.isfinite(a.grad.asnumpy()).all()

    spd = w.asnumpy()
    h = mx.np.array(spd)
    h.attach_grad()
    with autograd.record():
        vals, vecs = mx.np.linalg.eigh(h)
        z = mx.np.sum(vals)
    z.backward()
    # d(sum eigvals)/dA = d(trace)/dA = I for symmetric A
    np.testing.assert_allclose(h.grad.asnumpy(), np.eye(3), atol=1e-4)


def test_gluon_np_mode():
    """npx.set_np(): Gluon blocks return mx.np.ndarray and Parameter.data
    hands back an np-typed zero-copy view (reference: GluonNLP's np-mode
    Gluon flow)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.Dense(4, in_units=3)
    net.initialize()
    x_np = mx.np.array(np.ones((2, 3), np.float32))
    try:
        mx.npx.set_np()
        out = net(x_np)
        assert type(out) is mx.np.ndarray
        w = net.weight.data()
        assert type(w) is mx.np.ndarray
        # np view aliases the parameter payload (writes go through)
        before = float(out.asnumpy().sum())
        w[:] = w * 2.0
        after = float(net(x_np).asnumpy().sum())
        assert abs(after - 2.0 * before) < 1e-4
        # hybridized path too
        net2 = nn.Dense(4, in_units=3)
        net2.initialize()
        net2.hybridize()
        assert type(net2(x_np)) is mx.np.ndarray
    finally:
        mx.npx.reset_np()
    # legacy mode restored
    out = net(mx.nd.ones((2, 3)))
    assert type(out) is mx.nd.NDArray


def test_gluon_np_mode_training_updates_params():
    """np-mode gradients reach Parameter.grad and Trainer really moves
    parameters (regression: the np view must share the grad buffer)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    try:
        mx.npx.set_np()
        net = nn.Dense(3, in_units=4)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.5})
        x = mx.np.array(np.ones((2, 4), np.float32))
        w_before = net.weight.data().asnumpy().copy()
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        g = net.weight.grad()
        assert float(np.abs(g.asnumpy()).sum()) > 0, \
            "np-mode backward dropped parameter gradients"
        tr.step(2)
        w_after = net.weight.data().asnumpy()
        assert not np.allclose(w_after, w_before), \
            "np-mode Trainer.step did not move parameters"
    finally:
        mx.npx.reset_np()


def test_gluon_np_mode_passthrough_does_not_mutate_caller():
    """An identity-style forward must not retag the caller's legacy array
    in place."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import Block

    class Identity(Block):
        def forward(self, x):
            return x

    try:
        mx.npx.set_np()
        x = mx.nd.ones((2, 2))
        out = Identity()(x)
        assert type(x) is mx.nd.NDArray        # caller untouched
        assert type(out) is mx.np.ndarray      # output np-typed view
        out[0, 0] = 5.0                        # aliasing goes through
        assert float(x.asnumpy()[0, 0]) == 5.0
    finally:
        mx.npx.reset_np()


def test_dataloader_np_mode():
    """np-mode DataLoader yields mx.np batches (reference: np-mode data
    pipeline)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    ds = gluon.data.ArrayDataset(
        np.arange(12, dtype=np.float32).reshape(6, 2),
        np.arange(6, dtype=np.float32))
    try:
        mx.npx.set_np()
        loader = gluon.data.DataLoader(ds, batch_size=3)
        xb, yb = next(iter(loader))
        assert type(xb) is mx.np.ndarray and type(yb) is mx.np.ndarray
        assert xb.shape == (3, 2)
    finally:
        mx.npx.reset_np()
    loader = gluon.data.DataLoader(ds, batch_size=3)
    xb, _ = next(iter(loader))
    assert type(xb) is mx.nd.NDArray


def test_np_mode_shared_param_two_sites_accumulates():
    """A parameter used at two sites in one recorded graph must see the
    SUM of both cotangents in np mode (regression: per-call views made
    two leaves whose writes overwrote each other)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn

    try:
        mx.npx.set_np()
        net = nn.Dense(1, in_units=2, use_bias=False)
        net.initialize()
        a = mx.np.array(np.array([[1.0, 0.0]], np.float32))
        b = mx.np.array(np.array([[0.0, 1.0]], np.float32))
        with autograd.record():
            loss = (net(a) + net(b)).sum()
        loss.backward()
        g = net.weight.grad().asnumpy()
        # d loss/dW = a + b = [1, 1] — both use sites must contribute
        np.testing.assert_allclose(g, [[1.0, 1.0]], atol=1e-6)
    finally:
        mx.npx.reset_np()


def test_np_mode_container_passthrough_not_mutated():
    """Passthrough of an element of a container argument must not retag
    the caller's array either."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import Block

    class First(Block):
        def forward(self, pair):
            return pair[0]

    try:
        mx.npx.set_np()
        a = mx.nd.ones((2,))
        b = mx.nd.zeros((2,))
        out = First()([a, b])
        assert type(a) is mx.nd.NDArray
        assert type(out) is mx.np.ndarray
    finally:
        mx.npx.reset_np()


def test_dataloader_np_mode_multiworker():
    """np typing holds on the worker path too (shm/pickle delivery)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    ds = gluon.data.ArrayDataset(
        np.arange(12, dtype=np.float32).reshape(6, 2),
        np.arange(6, dtype=np.float32))
    try:
        mx.npx.set_np()
        loader = gluon.data.DataLoader(ds, batch_size=3, num_workers=1)
        xb, yb = next(iter(loader))
        assert type(xb) is mx.np.ndarray and type(yb) is mx.np.ndarray
    finally:
        mx.npx.reset_np()
