"""Shape/dtype matrix over the op library (round-4 VERDICT task #3).

reference: tests/python/unittest/test_operator.py is 8K+ lines largely
because shape/dtype edges are where op bugs live (the round-4 int64
truncation find proves the point here too). The registry sweep
(test_registry_grad_sweep.py) pins one (3,4) fp32 spec per op; this file
adds the edge matrix for the ~100 most-used ops:

  shapes: {0-size, 1-element, odd-rank, high-rank-with-degenerate-dim}
  dtypes: {float32 (+gradient FD), bfloat16, float16} for elementwise,
          {int32, int64} forwards for index ops, ints vs numpy for the
          np bit ops.

Checks per cell: forward runs, output shape/dtype is right, values match
the fp32 reference (low-precision) or real numpy (int/bit ops), and for
fp32 cells the tape gradient passes the same directional finite-difference
check the sweep uses — including through 0-size tensors and
broadcast-degenerate operands (the classic sum-reduction backward bug).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.ndarray import invoke

from test_registry_grad_sweep import _run_check

RNG = onp.random.RandomState(7)

# shape cases (VERDICT: 0-size, 1-element, odd/high-rank,
# broadcast-degenerate)
SHAPES = {
    "zero_size": (0, 4),
    "one_elem": (1,),
    "odd_rank": (7, 5, 3),
    "high_rank_degenerate": (2, 3, 1, 4, 5),
}

# unary elementwise ops: name -> sampling domain (lo, hi); None = (0.6, 1.4)
UNARY = {
    "abs": None, "arccos": (-0.8, -0.2), "arccosh": (1.5, 3.0),
    "arcsin": (-0.8, -0.2), "arcsinh": None, "arctan": None,
    "arctanh": (-0.8, -0.2), "cbrt": None, "cos": None, "cosh": None,
    "degrees": None, "erf": None, "erfinv": (0.1, 0.7), "exp": None,
    "expm1": None, "gamma": (1.5, 3.0), "gammaln": (1.5, 3.0),
    "hard_sigmoid": None, "identity": None, "log": (0.5, 2.0),
    "log10": (0.5, 2.0), "log1p": None, "log2": (0.5, 2.0),
    "negative": None, "radians": None, "rcbrt": (0.5, 2.0),
    "reciprocal": (0.5, 2.0), "relu": None, "rsqrt": (0.5, 2.0),
    "sigmoid": None, "sin": None, "sinh": None, "softsign": None,
    "sqrt": (0.5, 2.0), "square": None, "tan": (0.1, 0.9), "tanh": None,
}
# step/round-like: forward-only (derivative zero a.e., FD meaningless)
UNARY_FWD_ONLY = {
    "ceil": None, "fix": None, "floor": None, "isfinite": None,
    "isinf": None, "isnan": None, "logical_not": None, "rint": None,
    "round": None, "sign": None, "trunc": None,
}

# binary ops that broadcast: checked with degenerate operand pairs
BINARY = {
    "broadcast_add": None, "broadcast_sub": None, "broadcast_mul": None,
    "broadcast_div": (0.5, 1.5), "broadcast_maximum": None,
    "broadcast_minimum": None, "broadcast_power": (0.6, 1.4),
    "broadcast_hypot": None, "arctan2": None,
}
BINARY_FWD_ONLY = {
    "broadcast_equal": None, "broadcast_not_equal": None,
    "broadcast_greater": None, "broadcast_greater_equal": None,
    "broadcast_lesser": None, "broadcast_lesser_equal": None,
    "broadcast_logical_and": None, "broadcast_logical_or": None,
    "broadcast_logical_xor": None,
}
# broadcast-degenerate operand shape pairs and the broadcast result
BINARY_SHAPES = {
    "deg_2d": ((3, 1), (1, 4), (3, 4)),
    "deg_rank_mix": ((2, 1, 4), (5, 1), (2, 5, 4)),
    "zero_size": ((0, 1), (1, 4), (0, 4)),
    "one_elem": ((1,), (1,), (1,)),
}

# reductions: axis-kwarg'd; zero-size only where the identity exists
REDUCE = {
    "sum": {},
    "mean": {},
    "nansum": {},
    "prod": {},
    "nanprod": {},
    "max": {"axis": 0},
    "min": {"axis": 0},
    "norm": {},
    "logsumexp": {},
}
REDUCE_ZERO_OK = {"sum", "nansum", "prod", "nanprod"}

LOW_PRECISION = ["bfloat16", "float16"]
# |x|<=3 domains above => absolute error of bf16 elementwise ~2^-8*|f|;
# fp16 ~2^-11*|f|. gamma at 3.0 reaches ~2.0; tol is on the output.
LP_TOL = {"bfloat16": dict(rtol=3e-2, atol=3e-2),
          "float16": dict(rtol=5e-3, atol=5e-3)}


def _arr(shape, domain, dtype="float32", seed=None):
    rng = RNG if seed is None else onp.random.RandomState(seed)
    lo, hi = domain or (0.6, 1.4)
    return rng.uniform(lo, hi, size=shape).astype(dtype)


# ---------------------------------------------------------------------------
# unary: shape matrix (fp32, with gradient where differentiable)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("case", sorted(SHAPES))
@pytest.mark.parametrize("name", sorted(UNARY) + sorted(UNARY_FWD_ONLY))
def test_unary_shape_matrix(name, case):
    domain = UNARY.get(name, UNARY_FWD_ONLY.get(name))
    shape = SHAPES[case]
    x = _arr(shape, domain, seed=3)
    out = invoke(name, nd.array(x))
    assert tuple(out.shape) == shape, (
        "%s(%s): shape %s" % (name, shape, out.shape))
    got = out.asnumpy()
    if got.dtype.kind == "f":
        assert onp.isfinite(got).all(), "%s(%s): non-finite" % (name, shape)
    if name in UNARY:
        # full tape + directional-FD gradient at this shape (0-size
        # included: backward must run and produce a 0-size grad)
        _run_check(name, [x], {})


# ---------------------------------------------------------------------------
# unary: low-precision forward vs the fp32 reference
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", LOW_PRECISION)
@pytest.mark.parametrize("name", sorted(UNARY))
def test_unary_low_precision(name, dtype):
    x32 = _arr((7, 5, 3), UNARY[name], seed=5)
    xlp = nd.array(x32).astype(dtype)
    out = invoke(name, xlp)
    ref = invoke(name, nd.array(x32)).asnumpy()
    got = out.asnumpy().astype("float32")
    if str(out.dtype) not in ("bool",):
        assert str(out.dtype) == dtype, (
            "%s: %s input produced %s output" % (name, dtype, out.dtype))
    onp.testing.assert_allclose(got, ref, err_msg="%s/%s" % (name, dtype),
                                **LP_TOL[dtype])


# ---------------------------------------------------------------------------
# binary broadcast: degenerate operands, gradient through the reduction
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("case", sorted(BINARY_SHAPES))
@pytest.mark.parametrize("name", sorted(BINARY) + sorted(BINARY_FWD_ONLY))
def test_binary_broadcast_matrix(name, case):
    domain = BINARY.get(name, BINARY_FWD_ONLY.get(name))
    sa, sb, sout = BINARY_SHAPES[case]
    a = _arr(sa, domain, seed=11)
    b = _arr(sb, domain, seed=13)
    out = invoke(name, nd.array(a), nd.array(b))
    assert tuple(out.shape) == sout, (
        "%s(%s,%s): shape %s != %s" % (name, sa, sb, out.shape, sout))
    if name in BINARY:
        # FD through BOTH inputs: the backward must sum-reduce the
        # cotangent back to each degenerate operand shape
        _run_check(name, [a, b], {})


@pytest.mark.parametrize("dtype", LOW_PRECISION)
@pytest.mark.parametrize("name", sorted(BINARY))
def test_binary_low_precision(name, dtype):
    domain = BINARY[name]
    a32 = _arr((3, 1), domain, seed=17)
    b32 = _arr((1, 4), domain, seed=19)
    out = invoke(name, nd.array(a32).astype(dtype),
                 nd.array(b32).astype(dtype))
    ref = invoke(name, nd.array(a32), nd.array(b32)).asnumpy()
    assert str(out.dtype) == dtype
    onp.testing.assert_allclose(out.asnumpy().astype("float32"), ref,
                                err_msg="%s/%s" % (name, dtype),
                                **LP_TOL[dtype])


# ---------------------------------------------------------------------------
# reductions: shape matrix + keepdims + zero-size identities
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("case", ["one_elem", "odd_rank",
                                  "high_rank_degenerate", "zero_size"])
@pytest.mark.parametrize("name", sorted(REDUCE))
def test_reduce_shape_matrix(name, case):
    if case == "zero_size" and name not in REDUCE_ZERO_OK:
        pytest.skip("%s has no identity over an empty axis" % name)
    shape = SHAPES[case]
    x = _arr(shape, (0.6, 1.4), seed=23)
    kwargs = dict(REDUCE[name])
    out = invoke(name, nd.array(x), **kwargs)
    ref_fn = {"sum": onp.sum, "mean": onp.mean, "nansum": onp.nansum,
              "prod": onp.prod, "nanprod": onp.nanprod, "max": onp.max,
              "min": onp.min, "logsumexp": None, "norm": None}[name]
    if ref_fn is not None:
        axis = kwargs.get("axis")
        want = ref_fn(x.astype("float64"), axis=axis)
        onp.testing.assert_allclose(
            onp.asarray(out.asnumpy(), "float64"), want,
            rtol=1e-5, atol=1e-6, err_msg="%s(%s)" % (name, shape))
    if case != "zero_size" or name in ("sum", "nansum"):
        _run_check(name, [x], kwargs)


@pytest.mark.parametrize("name", ["sum", "mean", "max"])
def test_reduce_keepdims_axis(name):
    x = _arr((2, 3, 4), None, seed=29)
    out = invoke(name, nd.array(x), axis=1, keepdims=True)
    assert tuple(out.shape) == (2, 1, 4)
    out2 = invoke(name, nd.array(x), axis=(0, 2))
    assert tuple(out2.shape) == (3,)


# ---------------------------------------------------------------------------
# matmul family: degenerate dims and bf16
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("sa,sb,sout", [
    ((0, 4), (4, 2), (0, 2)),        # zero-row lhs
    ((3, 0), (0, 2), (3, 2)),        # empty contraction (result = zeros)
    ((1, 1), (1, 1), (1, 1)),
])
def test_dot_degenerate(sa, sb, sout):
    a = _arr(sa, None, seed=31)
    b = _arr(sb, None, seed=37)
    out = invoke("dot", nd.array(a), nd.array(b))
    assert tuple(out.shape) == sout
    onp.testing.assert_allclose(out.asnumpy(), a @ b, rtol=1e-5, atol=1e-6)
    _run_check("dot", [a, b], {})


def test_dot_bf16_accumulates_reasonably():
    a32 = _arr((16, 32), (-1.0, 1.0), seed=41)
    b32 = _arr((32, 8), (-1.0, 1.0), seed=43)
    out = invoke("dot", nd.array(a32).astype("bfloat16"),
                 nd.array(b32).astype("bfloat16"))
    assert str(out.dtype) == "bfloat16"
    onp.testing.assert_allclose(out.asnumpy().astype("float32"),
                                a32 @ b32, rtol=6e-2, atol=6e-2)


def test_batch_dot_degenerate_batch():
    a = _arr((0, 3, 4), None, seed=47)
    b = _arr((0, 4, 2), None, seed=53)
    out = invoke("batch_dot", nd.array(a), nd.array(b))
    assert tuple(out.shape) == (0, 3, 2)


# ---------------------------------------------------------------------------
# shape ops at the edges
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("case", sorted(SHAPES))
def test_transpose_flip_expand(case):
    shape = SHAPES[case]
    x = _arr(shape, None, seed=59)
    t = invoke("transpose", nd.array(x))
    assert tuple(t.shape) == tuple(reversed(shape))
    onp.testing.assert_allclose(t.asnumpy(), x.T, rtol=0, atol=0)
    f = invoke("flip", nd.array(x), axis=0)
    onp.testing.assert_allclose(f.asnumpy(), onp.flip(x, 0), rtol=0, atol=0)
    e = invoke("expand_dims", nd.array(x), axis=0)
    assert tuple(e.shape) == (1,) + shape
    _run_check("transpose", [x], {})


def test_concat_zero_size_piece():
    a = _arr((0, 4), None, seed=61)
    b = _arr((3, 4), None, seed=67)
    out = invoke("Concat", nd.array(a), nd.array(b), dim=0)
    assert tuple(out.shape) == (3, 4)
    onp.testing.assert_allclose(out.asnumpy(), b, rtol=0, atol=0)


# ---------------------------------------------------------------------------
# index ops: integer dtypes (int32 AND int64 indices)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("idtype", ["int32", "int64"])
def test_take_int_indices(idtype):
    data = _arr((5, 3), None, seed=71)
    idx = onp.array([0, 4, 2], idtype)
    out = invoke("take", nd.array(data), nd.array(idx, dtype=idtype))
    onp.testing.assert_allclose(out.asnumpy(), onp.take(data, idx, axis=0),
                                rtol=0, atol=0)


@pytest.mark.parametrize("idtype", ["int32", "int64"])
def test_gather_scatter_int_indices(idtype):
    data = _arr((4, 3), None, seed=73)
    idx = onp.array([[0, 2], [1, 0]], idtype).T
    out = invoke("gather_nd", nd.array(data), nd.array(idx.T, dtype=idtype))
    want = data[onp.array([0, 2]), onp.array([1, 0])]
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=0, atol=0)


@pytest.mark.parametrize("idtype", ["int32", "int64"])
def test_one_hot_int(idtype):
    idx = onp.array([0, 3, 1], idtype)
    out = invoke("one_hot", nd.array(idx, dtype=idtype), depth=4)
    assert tuple(out.shape) == (3, 4)
    onp.testing.assert_allclose(out.asnumpy(), onp.eye(4)[idx], rtol=0,
                                atol=0)


def test_argmax_argsort_topk_int_outputs():
    x = _arr((4, 5), None, seed=79)
    am = invoke("argmax", nd.array(x), axis=1)
    onp.testing.assert_allclose(am.asnumpy(), onp.argmax(x, 1), rtol=0,
                                atol=0)
    asrt = invoke("argsort", nd.array(x), axis=1)
    onp.testing.assert_allclose(asrt.asnumpy(), onp.argsort(x, 1,
                                                            kind="stable"),
                                rtol=0, atol=0)
    tk = invoke("topk", nd.array(x), k=2, axis=1)
    want = onp.argsort(-x, 1, kind="stable")[:, :2]
    onp.testing.assert_allclose(tk.asnumpy(), want, rtol=0, atol=0)


# ---------------------------------------------------------------------------
# np bit ops vs real numpy (VERDICT: integer forward checks for bit ops)
# ---------------------------------------------------------------------------
_BITS = {
    "_np_bitwise_and": onp.bitwise_and,
    "_np_bitwise_or": onp.bitwise_or,
    "_np_bitwise_xor": onp.bitwise_xor,
    "_np_left_shift": onp.left_shift,
    "_np_right_shift": onp.right_shift,
    "_np_gcd": onp.gcd,
    "_np_lcm": onp.lcm,
    "_np_floor_divide": onp.floor_divide,
}


@pytest.mark.parametrize("idtype", ["int32", "int64"])
@pytest.mark.parametrize("name", sorted(_BITS))
def test_np_int_ops_vs_numpy(name, idtype):
    import contextlib
    rng = onp.random.RandomState(83)
    a = rng.randint(1, 17, (3, 4)).astype(idtype)
    b = rng.randint(1, 5, (3, 4)).astype(idtype)
    # true int64 storage is opt-in (mx.util.large_tensor_scope — the
    # analog of upstream's MXNET_INT64_TENSOR_SIZE build flag); default
    # mode stores int32
    scope = (mx.util.large_tensor_scope() if idtype == "int64"
             else contextlib.nullcontext())
    with scope:
        out = invoke(name, nd.array(a, dtype=idtype),
                     nd.array(b, dtype=idtype))
        want = _BITS[name](a, b)
        got = out.asnumpy()
        assert got.dtype == want.dtype, (
            "%s/%s: dtype %s != numpy %s" % (name, idtype, got.dtype,
                                             want.dtype))
        onp.testing.assert_allclose(got, want, rtol=0, atol=0,
                                    err_msg="%s/%s" % (name, idtype))


@pytest.mark.parametrize("name,npf", [("_np_bitwise_not", onp.bitwise_not),
                                      ("_np_invert", onp.invert)])
def test_np_bitwise_unary_vs_numpy(name, npf):
    a = onp.random.RandomState(89).randint(0, 64, (3, 4)).astype("int32")
    out = invoke(name, nd.array(a, dtype="int32"))
    onp.testing.assert_allclose(out.asnumpy(), npf(a), rtol=0, atol=0)
