"""RNN layers/cells + gluon.data tests — modeled on reference
tests/python/unittest/test_gluon_rnn.py and test_gluon_data.py."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn


def test_rnn_cells_forward():
    for cell_cls, n_states in [(rnn.RNNCell, 1), (rnn.LSTMCell, 2),
                               (rnn.GRUCell, 1)]:
        cell = cell_cls(10, input_size=6, prefix="%s_" %
                        cell_cls.__name__.lower())
        cell.initialize()
        x = mx.nd.ones((4, 6))
        states = cell.begin_state(batch_size=4)
        out, new_states = cell(x, states)
        assert out.shape == (4, 10)
        assert len(new_states) == n_states


def test_rnn_cell_unroll():
    cell = rnn.LSTMCell(8, input_size=5)
    cell.initialize()
    x = mx.nd.ones((2, 3, 5))  # NTC
    outputs, states = cell.unroll(3, x, layout="NTC", merge_outputs=False)
    assert len(outputs) == 3
    assert outputs[0].shape == (2, 8)
    assert len(states) == 2


def test_sequential_rnn_cell():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=5))
    stack.add(rnn.LSTMCell(8, input_size=8))
    stack.initialize()
    x = mx.nd.ones((2, 5))
    states = stack.begin_state(batch_size=2)
    assert len(states) == 4
    out, new_states = stack(x, states)
    assert out.shape == (2, 8)


def test_residual_bidirectional_cells():
    cell = rnn.ResidualCell(rnn.GRUCell(5, input_size=5))
    cell.initialize()
    x = mx.nd.ones((2, 3, 5))
    outputs, _ = cell.unroll(3, x, merge_outputs=False)
    assert outputs[0].shape == (2, 5)

    bi = rnn.BidirectionalCell(rnn.LSTMCell(4, input_size=5),
                               rnn.LSTMCell(4, input_size=5))
    bi.initialize()
    outputs, states = bi.unroll(3, x, merge_outputs=False)
    assert outputs[0].shape == (2, 8)


@pytest.mark.parametrize("layer_cls,mode_states",
                         [(rnn.LSTM, 2), (rnn.GRU, 1), (rnn.RNN, 1)])
def test_fused_rnn_layer(layer_cls, mode_states):
    layer = layer_cls(hidden_size=8, num_layers=2, layout="TNC")
    layer.initialize()
    x = mx.nd.ones((5, 3, 6))  # T, N, C
    out = layer(x)
    assert out.shape == (5, 3, 8)
    states = layer.begin_state(batch_size=3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 8)
    assert len(new_states) == mode_states
    assert new_states[0].shape == (2, 3, 8)


def test_fused_rnn_bidirectional_ntc():
    layer = rnn.LSTM(hidden_size=4, num_layers=1, layout="NTC",
                     bidirectional=True)
    layer.initialize()
    x = mx.nd.ones((3, 5, 6))
    out = layer(x)
    assert out.shape == (3, 5, 8)


def test_fused_lstm_matches_cell():
    """Fused lax.scan LSTM must agree with the unfused cell math."""
    T, N, C, H = 4, 2, 3, 5
    layer = rnn.LSTM(hidden_size=H, num_layers=1, input_size=C)
    layer.initialize()
    cell = rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    # copy fused params into the cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    x = mx.nd.array(np.random.normal(size=(T, N, C)).astype("float32"))
    fused_out = layer(x).asnumpy()
    cell_out, _ = cell.unroll(T, x, layout="TNC", merge_outputs=False)
    for t in range(T):
        np.testing.assert_allclose(fused_out[t], cell_out[t].asnumpy(),
                                   rtol=1e-4, atol=1e-5)


def test_rnn_gradient_flows():
    layer = rnn.LSTM(hidden_size=8, num_layers=1)
    layer.initialize()
    x = mx.nd.ones((5, 3, 6))
    with autograd.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad()
    assert float(g.abs().sum().asscalar()) > 0


def test_dataset_and_dataloader():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    x = np.random.uniform(size=(40, 3)).astype("float32")
    y = np.arange(40).astype("float32")
    ds = ArrayDataset(x, y)
    assert len(ds) == 40
    loader = DataLoader(ds, batch_size=8, shuffle=True)
    batches = list(loader)
    assert len(batches) == 5
    assert batches[0][0].shape == (8, 3)
    # last_batch handling
    loader = DataLoader(ds, batch_size=16, last_batch="discard")
    assert len(list(loader)) == 2
    loader = DataLoader(ds, batch_size=16, last_batch="keep")
    batches = list(loader)
    assert batches[-1][0].shape[0] == 8


def test_dataloader_multiworker():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    x = np.random.uniform(size=(32, 4)).astype("float32")
    y = np.arange(32).astype("float32")
    loader = DataLoader(ArrayDataset(x, y), batch_size=8, num_workers=2)
    seen = []
    for data, label in loader:
        assert data.shape == (8, 4)
        seen.extend(label.asnumpy().tolist())
    assert sorted(seen) == list(range(32))


def test_dataset_transform_shard():
    from mxnet_tpu.gluon.data import SimpleDataset
    ds = SimpleDataset(list(range(10)))
    t = ds.transform(lambda x: x * 2)
    assert t[3] == 6
    s = ds.shard(3, 0)
    assert len(s) == 4  # 10 = 4+3+3
    assert s[0] == 0


def test_mnist_synthetic_and_training():
    """Config-1 milestone: MLP on MNIST via gluon.data pipeline."""
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision import MNIST, transforms
    ds = MNIST(root="/tmp/mxtpu_mnist", train=True)
    img, label = ds[0]
    assert img.shape == (28, 28, 1)
    tfm = transforms.Compose([transforms.ToTensor()])
    ds_t = ds.transform_first(tfm)
    loader = DataLoader(ds_t.take(512), batch_size=64, shuffle=True)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    first = last = None
    for epoch in range(2):
        for data, label in loader:
            data = data.reshape((data.shape[0], -1))
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(data.shape[0])
            cur = float(loss.mean().asscalar())
            if first is None:
                first = cur
            last = cur
    assert last < first


def test_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    payloads = []
    for i in range(5):
        header = recordio.IRHeader(0, float(i), i, 0)
        data = recordio.pack(header, bytes([i]) * (i * 7 + 1))
        payloads.append(data)
        writer.write_idx(i, data)
    writer.close()

    reader = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    for i in [3, 0, 4]:
        rec = reader.read_idx(i)
        header, content = recordio.unpack(rec)
        assert header.label == float(i)
        assert content == bytes([i]) * (i * 7 + 1)
    reader.close()


def test_image_record_dataset(tmp_path):
    from mxnet_tpu import recordio
    from mxnet_tpu.gluon.data.vision import ImageRecordDataset
    rec_path = str(tmp_path / "imgs.rec")
    idx_path = str(tmp_path / "imgs.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(4):
        img = np.random.randint(0, 255, size=(8, 8, 3)).astype("uint8")
        header = recordio.IRHeader(0, float(i % 2), i, 0)
        writer.write_idx(i, recordio.pack_img(header, img))
    writer.close()
    ds = ImageRecordDataset(rec_path)
    img, label = ds[2]
    assert img.shape == (8, 8, 3)
    assert label == 0.0


def test_image_ops():
    from mxnet_tpu import image
    img = mx.nd.array(np.random.randint(0, 255, size=(20, 30, 3)),
                      dtype="uint8")
    resized = image.imresize(img, 15, 10)
    assert resized.shape == (10, 15, 3)
    short = image.resize_short(img, 10)
    assert min(short.shape[:2]) == 10
    crop, _ = image.center_crop(img, (8, 8))
    assert crop.shape == (8, 8, 3)
    augs = image.CreateAugmenter((3, 8, 8), rand_mirror=True, mean=True,
                                 std=True)
    out = img
    for aug in augs:
        out = aug(out)
    assert out.shape == (8, 8, 3)


def test_dataloader_shm_transport():
    """Spawn workers return batches through POSIX shared memory (reference:
    cpu_shared storage manager) — the pickled payload is just descriptors."""
    from mxnet_tpu.gluon.data.dataloader import (_batch_to_shm,
                                                 _batch_from_shm, _ShmBatch)
    rng = np.random.RandomState(0)
    batch = [rng.randn(8, 4).astype(np.float32),
             rng.randint(0, 5, (8,)).astype(np.float32)]
    sb = _batch_to_shm(batch)
    assert isinstance(sb, _ShmBatch)
    import pickle
    assert len(pickle.dumps(sb)) < 512  # descriptors only, not the data
    out = _batch_from_shm(sb, mx.cpu())
    np.testing.assert_array_equal(out[0].asnumpy(), batch[0])
    np.testing.assert_array_equal(out[1].asnumpy(), batch[1])


def test_dataloader_multiworker_uses_shm():
    ds = gluon.data.ArrayDataset(
        np.arange(64, dtype=np.float32).reshape(16, 4),
        np.arange(16, dtype=np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=2)
    seen = 0
    for x, y in loader:
        assert x.shape == (4, 4)
        seen += 1
    assert seen == 4
