"""mx.analysis (tracelint) tests: per-rule positive/negative fixtures,
suppression comments, the programmatic check() API, CLI exit codes &
formats, the runtime trace guard (host-sync + retrace under
JAX_PLATFORMS=cpu), and the meta-test that mxnet_tpu/ itself is clean at
error severity."""
import json
import logging
import os
import subprocess
import sys
import textwrap

import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import (Severity, TraceGuardError, check,
                                check_source, set_guard_mode)
from mxnet_tpu.analysis.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src, rules=None):
    return check_source(textwrap.dedent(src), filename="fixture.py",
                        rules=rules)


def codes(findings):
    return sorted({f.code for f in findings})


def only(findings, code):
    return [f for f in findings if f.code == code]


@pytest.fixture
def guard_raise():
    prev = set_guard_mode("raise")
    yield
    set_guard_mode(prev)


@pytest.fixture
def guard_warn():
    prev = set_guard_mode("warn")
    yield
    set_guard_mode(prev)


def _counter(name):
    return mx.telemetry.snapshot()["counters"].get(name, 0)


# ===========================================================================
# TPU001 — host syncs under trace
# ===========================================================================
def test_tpu001_flags_asnumpy_and_item():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()
            b = x.sum().item()
            return F.relu(x)
    """)
    hits = only(f, "TPU001")
    assert len(hits) == 2
    assert all(h.severity == Severity.ERROR for h in hits)
    assert hits[0].line == 4 and hits[1].line == 5
    assert "hybrid_forward" in hits[0].symbol


def test_tpu001_flags_float_and_np_call():
    f = lint("""
    import numpy as np
    class Net:
        def hybrid_forward(self, F, x):
            s = float(x.sum())
            e = np.exp(x)
            return x * s + e
    """)
    assert len(only(f, "TPU001")) == 2


def test_tpu001_passes_static_shape_reads():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            n = x.shape[0]
            d = float(n)
            return F.reshape(x, (n, -1))
    """)
    assert not only(f, "TPU001")


def test_tpu001_passes_untraced_function():
    # a plain function is not a traced region — eager asnumpy is fine
    f = lint("""
    def evaluate(net, x):
        return net(x).asnumpy()
    """)
    assert not only(f, "TPU001")


def test_tpu001_passes_np_on_host_values():
    f = lint("""
    import numpy as np
    class Net:
        def hybrid_forward(self, F, x):
            scale = np.sqrt(2.0)
            return x * scale
    """)
    assert not only(f, "TPU001")


# ===========================================================================
# TPU002 — side effects under trace
# ===========================================================================
def test_tpu002_flags_print_and_self_mutation():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            print("forward", x.shape)
            self.last_input = x
            return x
    """)
    hits = only(f, "TPU002")
    assert len(hits) == 2
    assert all(h.severity == Severity.WARNING for h in hits)


def test_tpu002_flags_tracer_leak_into_closure():
    f = lint("""
    captured = []
    class Net:
        def hybrid_forward(self, F, x):
            y = F.relu(x)
            captured.append(y)
            return y
    """)
    assert len(only(f, "TPU002")) == 1


def test_tpu002_passes_local_container_use():
    # appending tracers to a LOCAL list (concat pattern) is idiomatic
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            outs = []
            for i in range(3):
                outs.append(F.relu(x))
            return F.concat(*outs, dim=0)
    """)
    assert not only(f, "TPU002")


def test_tpu002_passes_side_effect_free_body():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x, weight):
            return F.dot(x, weight)
    """)
    assert not only(f, "TPU002")


# ===========================================================================
# TPU003 — data-dependent control flow
# ===========================================================================
def test_tpu003_flags_if_and_while_on_traced():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            if x.sum() > 0:
                return x
            while x.max() > 1:
                x = x * 0.5
            return x
    """)
    hits = only(f, "TPU003")
    assert len(hits) == 2
    assert all(h.severity == Severity.ERROR for h in hits)
    assert "early return" in hits[0].message


def test_tpu003_flags_assert_and_ifexp():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            assert x.min() >= 0
            y = x if x.sum() > 0 else -x
            return y
    """)
    assert len(only(f, "TPU003")) == 2


def test_tpu003_passes_none_shape_isinstance_checks():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x, bias=None):
            if bias is not None:
                x = x + bias
            if x.shape[0] > 2:
                x = x * 2
            if isinstance(x, tuple):
                x = x[0]
            return x
    """)
    assert not only(f, "TPU003")


def test_tpu003_passes_while_on_python_counter():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            n = 3
            while n > 0:
                x = F.relu(x)
                n -= 1
            return x
    """)
    assert not only(f, "TPU003")


# ===========================================================================
# TPU004 — retrace hazards
# ===========================================================================
def test_tpu004_flags_loop_scalar_in_signature():
    f = lint("""
    def train(net, batches):
        for i in range(100):
            out = net(batches, i)
        return out
    """)
    hits = only(f, "TPU004")
    assert len(hits) == 1 and hits[0].severity == Severity.WARNING
    assert "'i'" in hits[0].message


def test_tpu004_flags_dict_literal_and_nonliteral_static():
    f = lint("""
    import jax
    def select():
        return (0, 1)
    def build(fn, xs):
        for x in xs:
            fn(x, {"mode": "train"})
        return jax.jit(fn, static_argnums=select())
    """)
    hits = only(f, "TPU004")
    assert len(hits) == 2
    assert any("dict/list literal" in h.message for h in hits)
    assert any("static_argnums" in h.message for h in hits)


def test_tpu004_passes_stable_signatures():
    f = lint("""
    import jax
    def train(net, batches):
        for batch in batches:
            out = net(batch)
        return out
    step = jax.jit(train, static_argnums=(0,))
    """)
    assert not only(f, "TPU004")


def test_tpu004_passes_scalar_hoisted_out_of_loop():
    f = lint("""
    def train(net, x, n_layers):
        y = net(x, n_layers)
        for _ in range(10):
            y = net(y)
        return y
    """)
    assert not only(f, "TPU004")


# ===========================================================================
# TPU005 — host RNG under trace
# ===========================================================================
def test_tpu005_flags_stdlib_and_numpy_rng():
    f = lint("""
    import random
    import numpy as np
    class Net:
        def hybrid_forward(self, F, x):
            if random.random() < 0.5:
                x = -x
            noise = np.random.normal(size=(3,))
            return x + noise
    """)
    hits = only(f, "TPU005")
    assert len(hits) == 2
    assert all(h.severity == Severity.ERROR for h in hits)
    assert "trace-time constant" in hits[0].message


def test_tpu005_flags_aliased_numpy_rng():
    f = lint("""
    import numpy as onp
    class Net:
        def hybrid_forward(self, F, x):
            return x * onp.random.rand()
    """)
    assert len(only(f, "TPU005")) == 1


def test_tpu005_flags_indirect_rng_imports():
    # every spelling of "host RNG" import must be caught, not just np.*
    f = lint("""
    import numpy.random as npr
    from numpy import random as nprand
    from numpy.random import uniform
    from random import randint
    class Net:
        def hybrid_forward(self, F, x):
            a = npr.uniform()
            b = nprand.normal()
            c = uniform(0, 1)
            d = randint(0, 9)
            return x * (a + b + c + d)
    """)
    assert len(only(f, "TPU005")) == 4


def test_tpu005_passes_keyed_device_rng():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            mask = F.uniform(0, 1, shape=(1,)) < 0.5
            noise = F.random.normal(0, 1, shape=(3,))
            return x + noise * mask
    """)
    assert not only(f, "TPU005")


def test_tpu005_passes_rng_outside_trace():
    f = lint("""
    import random
    def make_batch(n):
        return [random.random() for _ in range(n)]
    """)
    assert not only(f, "TPU005")


# ===========================================================================
# TPU006 — thread-shared module state
# ===========================================================================
def test_tpu006_flags_lockfree_thread_mutation():
    f = lint("""
    import threading
    _STATE = {}
    _EVENTS = []
    def worker():
        _STATE["k"] = 1
        _EVENTS.append("seen")
    def start():
        threading.Thread(target=worker, daemon=True).start()
    """)
    hits = only(f, "TPU006")
    assert len(hits) == 2
    assert all(h.severity == Severity.WARNING for h in hits)
    assert "_STATE" in hits[0].message


def test_tpu006_flags_transitively_reached_mutation():
    f = lint("""
    import threading
    _STATE = {}
    def helper():
        _STATE["deep"] = 2
    def worker():
        helper()
    def start():
        threading.Thread(target=worker).start()
    """)
    assert len(only(f, "TPU006")) == 1


def test_tpu006_passes_mutation_under_lock():
    f = lint("""
    import threading
    _STATE = {}
    _LOCK = threading.Lock()
    def worker():
        with _LOCK:
            _STATE["k"] = 1
    def start():
        threading.Thread(target=worker).start()
    """)
    assert not only(f, "TPU006")


def test_tpu006_passes_without_threads():
    f = lint("""
    _STATE = {}
    def main():
        _STATE["k"] = 1
    """)
    assert not only(f, "TPU006")


# ===========================================================================
# suppression comments
# ===========================================================================
def test_suppression_same_line_and_bare():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()  # tpu-lint: disable=TPU001
            b = x.asscalar()  # tpu-lint: disable
            c = x.item()
            return x
    """)
    hits = only(f, "TPU001")
    assert len(hits) == 1 and hits[0].line == 6


def test_suppression_comment_above_line():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            # tpu-lint: disable=TPU001
            a = x.asnumpy()
            return x
    """)
    assert not only(f, "TPU001")


def test_suppression_wrong_code_does_not_hide():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()  # tpu-lint: disable=TPU003
            return x
    """)
    assert len(only(f, "TPU001")) == 1


def test_suppression_disable_file():
    f = lint("""
    # tpu-lint: disable-file=TPU001
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()
            if x.sum() > 0:
                return -x
            return x
    """)
    assert not only(f, "TPU001")
    assert len(only(f, "TPU003")) == 1  # other rules unaffected


# ===========================================================================
# programmatic check() API
# ===========================================================================
class _BadBlock(mx.gluon.HybridBlock):
    # intentionally trace-hostile — fixture for check() on live objects
    def hybrid_forward(self, F, x):
        peek = x.asnumpy()  # noqa — the finding under test
        return F.relu(x) * peek.sum()


class _GoodBlock(mx.gluon.HybridBlock):
    def hybrid_forward(self, F, x):
        return F.relu(x)


def test_check_live_block_class_and_instance():
    for target in (_BadBlock, _BadBlock()):
        f = check(target)
        assert "TPU001" in codes(f), f
    assert check(_GoodBlock()) == []


def test_check_live_function_is_traced_by_definition():
    def step(params, batch):
        loss = float(batch.sum())
        return loss

    f = check(step)
    assert "TPU001" in codes(f)
    assert all(x.line for x in f)


def test_check_path_and_rule_selection():
    path = os.path.join(REPO, "mxnet_tpu", "gluon", "loss.py")
    f = check(path)
    assert [x for x in f if x.severity == Severity.ERROR] == []
    sel = analysis.lint_file(path, rules=["TPU006"])
    assert all(x.code == "TPU006" for x in sel)


def test_rule_registry_complete():
    table = analysis.rule_table()
    got = [row[0] for row in table]
    assert got == ["TPU001", "TPU002", "TPU003", "TPU004", "TPU005",
                   "TPU006"]
    assert all(row[4] for row in table)  # every rule documented


# ===========================================================================
# CLI
# ===========================================================================
_BAD_SRC = """
class Net:
    def hybrid_forward(self, F, x):
        return x.asnumpy()
"""
_CLEAN_SRC = """
class Net:
    def hybrid_forward(self, F, x):
        return F.relu(x)
"""


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(_BAD_SRC)
    clean = tmp_path / "clean.py"
    clean.write_text(_CLEAN_SRC)

    assert cli_main([str(clean), "--fail-on=error"]) == 0
    capsys.readouterr()
    assert cli_main([str(bad), "--fail-on=error"]) == 1
    capsys.readouterr()
    assert cli_main([str(bad), "--fail-on=never"]) == 0
    capsys.readouterr()

    rc = cli_main([str(bad), "--format", "json", "--fail-on=never"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["counts"]["error"] == 1
    assert out["findings"][0]["code"] == "TPU001"
    assert out["findings"][0]["line"] == 4

    assert cli_main([]) == 2                       # no targets
    capsys.readouterr()
    assert cli_main([str(bad), "--rules", "TPU999"]) == 2
    capsys.readouterr()
    assert cli_main(["--list-rules"]) == 0
    assert "TPU006" in capsys.readouterr().out


def test_cli_module_name_target(capsys):
    rc = cli_main(["mxnet_tpu.analysis", "--fail-on=error"])
    assert rc == 0


def test_cli_cache_reuses_and_invalidates(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text(_CLEAN_SRC)
    cache = tmp_path / "cache.json"
    assert cli_main([str(target), "--cache-file", str(cache),
                     "--fail-on=error"]) == 0
    capsys.readouterr()
    assert cache.exists()
    # cached rerun stays clean; rewriting the file invalidates by mtime
    assert cli_main([str(target), "--cache-file", str(cache),
                     "--fail-on=error"]) == 0
    capsys.readouterr()
    os.utime(target, (1, 1))
    target.write_text(_BAD_SRC)
    assert cli_main([str(target), "--cache-file", str(cache),
                     "--fail-on=error"]) == 1
    capsys.readouterr()


def test_cli_end_to_end_subprocess(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_BAD_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", str(bad),
         "--fail-on=error", "--format", "json"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert r.returncode == 1, r.stderr
    out = json.loads(r.stdout)
    assert out["counts"]["error"] == 1


def test_parse_log_lint_mode(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_BAD_SRC)
    findings = analysis.lint_file(str(bad))
    dump = tmp_path / "lint.json"
    dump.write_text(json.dumps(
        {"version": 1, "counts": {"error": len(findings)},
         "findings": [f.to_dict() for f in findings]}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         str(dump), "--lint"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| severity | code | location | symbol | message |" in r.stdout
    assert "TPU001" in r.stdout


# ===========================================================================
# runtime trace guard
# ===========================================================================
def test_guard_off_by_default():
    assert not analysis.guard_active() or \
        os.environ.get("MXNET_TPU_TRACE_GUARD")


def test_guard_host_sync_raises_inside_jitted_step(guard_raise):
    class Bad(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.relu(x) * float(x.sum().asnumpy())

    net = Bad()
    net.initialize()
    net.hybridize()
    before = _counter("analysis.guard.host_sync")
    with pytest.raises(TraceGuardError) as exc_info:
        net(mx.nd.ones((2, 3)))
    assert exc_info.value.kind == "host_sync"
    assert exc_info.value.site == "asnumpy"
    assert _counter("analysis.guard.host_sync") == before + 1
    # eager (unhybridized) host reads stay allowed
    net2 = Bad()
    net2.initialize()
    out = net2(mx.nd.ones((2, 3)))
    assert out.shape == (2, 3)


def test_guard_warn_mode_warns_before_jax_error(guard_warn):
    class Bad(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return x * x.asnumpy().sum()

    net = Bad()
    net.initialize()
    net.hybridize()
    with pytest.warns(RuntimeWarning, match="trace guard"):
        with pytest.raises(Exception):  # jax concretization error follows
            net(mx.nd.ones((2, 2)))


def test_guard_retrace_limit_and_reason(guard_raise, monkeypatch, caplog):
    monkeypatch.setenv("MXNET_TPU_TRACE_GUARD_RETRACE_LIMIT", "2")

    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.relu(x)

    net = Net()
    net.initialize()
    net.hybridize()
    before = _counter("analysis.guard.retrace")
    with caplog.at_level(logging.DEBUG, logger="mxnet_tpu.gluon.cachedop"):
        with pytest.raises(TraceGuardError) as exc_info:
            for n in range(1, 8):
                net(mx.nd.ones((n, 2)))
    assert exc_info.value.kind == "retrace"
    assert "shape" in str(exc_info.value)
    assert _counter("analysis.guard.retrace") > before
    # the debug channel carries the per-retrace reason (which arg moved)
    assert any("arg0 shape" in rec.message for rec in caplog.records)


def test_guard_allows_stable_hybrid_calls(guard_raise):
    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.relu(x)

    net = Net()
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 3))
    for _ in range(5):
        out = net(x)
    assert out.shape == (2, 3)


def test_guard_env_var_subprocess(tmp_path):
    """Acceptance: MXNET_TPU_TRACE_GUARD=1 catches a runtime .asnumpy()
    inside a jitted step (env wiring, not just set_guard_mode)."""
    script = tmp_path / "guarded.py"
    script.write_text(textwrap.dedent("""
        import mxnet_tpu as mx
        from mxnet_tpu.analysis import TraceGuardError

        class Bad(mx.gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                return F.relu(x) * x.asnumpy().sum()

        net = Bad(); net.initialize(); net.hybridize()
        try:
            net(mx.nd.ones((2, 3)))
        except TraceGuardError as e:
            assert e.site == "asnumpy", e.site
            n = mx.telemetry.snapshot()["counters"][
                "analysis.guard.host_sync"]
            assert n == 1, n
            print("GUARD_OK")
        else:
            raise SystemExit("guard did not fire")
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_TRACE_GUARD="1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    r = subprocess.run([sys.executable, str(script)], capture_output=True,
                       text=True, timeout=180, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "GUARD_OK" in r.stdout


def test_retrace_reason_formatting():
    from mxnet_tpu.gluon.block import _retrace_reason
    old = (False, (((2, 3), "float32"), "repr:7"))
    new_shape = (False, (((4, 3), "float32"), "repr:7"))
    assert "arg0 shape (2, 3)->(4, 3)" in _retrace_reason(new_shape, old)
    new_dtype = (False, (((2, 3), "float16"), "repr:7"))
    assert "dtype" in _retrace_reason(new_dtype, old)
    new_train = (True, (((2, 3), "float32"), "repr:7"))
    assert "train mode" in _retrace_reason(new_train, old)
    new_val = (False, (((2, 3), "float32"), "repr:9"))
    assert "value" in _retrace_reason(new_val, old)
    assert _retrace_reason(new_val, None) == "first trace"


# ===========================================================================
# meta: the tree lints itself clean (tier-1 self-check, `lint` marker)
# ===========================================================================
@pytest.mark.lint
def test_mxnet_tpu_is_error_clean():
    findings = analysis.lint_paths([os.path.join(REPO, "mxnet_tpu")])
    errors = [f for f in findings if f.severity == Severity.ERROR]
    assert not errors, "tracelint errors in mxnet_tpu/:\n" + \
        "\n".join(f.format() for f in errors)


@pytest.mark.lint
def test_run_tracelint_script():
    r = subprocess.run(
        ["sh", os.path.join(REPO, "tools", "run_tracelint.sh")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 error(s)" in r.stdout
