"""mx.analysis (tracelint) tests: per-rule positive/negative fixtures,
suppression comments, the programmatic check() API, CLI exit codes &
formats, the runtime trace guard (host-sync + retrace under
JAX_PLATFORMS=cpu), and the meta-test that mxnet_tpu/ itself is clean at
error severity."""
import json
import logging
import os
import subprocess
import sys
import textwrap

import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import (Severity, TraceGuardError, check,
                                check_source, set_guard_mode)
from mxnet_tpu.analysis.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src, rules=None):
    return check_source(textwrap.dedent(src), filename="fixture.py",
                        rules=rules)


def codes(findings):
    return sorted({f.code for f in findings})


def only(findings, code):
    return [f for f in findings if f.code == code]


@pytest.fixture
def guard_raise():
    prev = set_guard_mode("raise")
    yield
    set_guard_mode(prev)


@pytest.fixture
def guard_warn():
    prev = set_guard_mode("warn")
    yield
    set_guard_mode(prev)


def _counter(name):
    return mx.telemetry.snapshot()["counters"].get(name, 0)


# ===========================================================================
# TPU001 — host syncs under trace
# ===========================================================================
def test_tpu001_flags_asnumpy_and_item():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()
            b = x.sum().item()
            return F.relu(x)
    """)
    hits = only(f, "TPU001")
    assert len(hits) == 2
    assert all(h.severity == Severity.ERROR for h in hits)
    assert hits[0].line == 4 and hits[1].line == 5
    assert "hybrid_forward" in hits[0].symbol


def test_tpu001_flags_float_and_np_call():
    f = lint("""
    import numpy as np
    class Net:
        def hybrid_forward(self, F, x):
            s = float(x.sum())
            e = np.exp(x)
            return x * s + e
    """)
    assert len(only(f, "TPU001")) == 2


def test_tpu001_passes_static_shape_reads():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            n = x.shape[0]
            d = float(n)
            return F.reshape(x, (n, -1))
    """)
    assert not only(f, "TPU001")


def test_tpu001_passes_untraced_function():
    # a plain function is not a traced region — eager asnumpy is fine
    f = lint("""
    def evaluate(net, x):
        return net(x).asnumpy()
    """)
    assert not only(f, "TPU001")


def test_tpu001_passes_np_on_host_values():
    f = lint("""
    import numpy as np
    class Net:
        def hybrid_forward(self, F, x):
            scale = np.sqrt(2.0)
            return x * scale
    """)
    assert not only(f, "TPU001")


# ===========================================================================
# TPU002 — side effects under trace
# ===========================================================================
def test_tpu002_flags_print_and_self_mutation():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            print("forward", x.shape)
            self.last_input = x
            return x
    """)
    hits = only(f, "TPU002")
    assert len(hits) == 2
    assert all(h.severity == Severity.WARNING for h in hits)


def test_tpu002_flags_tracer_leak_into_closure():
    f = lint("""
    captured = []
    class Net:
        def hybrid_forward(self, F, x):
            y = F.relu(x)
            captured.append(y)
            return y
    """)
    assert len(only(f, "TPU002")) == 1


def test_tpu002_passes_local_container_use():
    # appending tracers to a LOCAL list (concat pattern) is idiomatic
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            outs = []
            for i in range(3):
                outs.append(F.relu(x))
            return F.concat(*outs, dim=0)
    """)
    assert not only(f, "TPU002")


def test_tpu002_passes_side_effect_free_body():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x, weight):
            return F.dot(x, weight)
    """)
    assert not only(f, "TPU002")


# ===========================================================================
# TPU003 — data-dependent control flow
# ===========================================================================
def test_tpu003_flags_if_and_while_on_traced():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            if x.sum() > 0:
                return x
            while x.max() > 1:
                x = x * 0.5
            return x
    """)
    hits = only(f, "TPU003")
    assert len(hits) == 2
    assert all(h.severity == Severity.ERROR for h in hits)
    assert "early return" in hits[0].message


def test_tpu003_flags_assert_and_ifexp():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            assert x.min() >= 0
            y = x if x.sum() > 0 else -x
            return y
    """)
    assert len(only(f, "TPU003")) == 2


def test_tpu003_passes_none_shape_isinstance_checks():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x, bias=None):
            if bias is not None:
                x = x + bias
            if x.shape[0] > 2:
                x = x * 2
            if isinstance(x, tuple):
                x = x[0]
            return x
    """)
    assert not only(f, "TPU003")


def test_tpu003_passes_while_on_python_counter():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            n = 3
            while n > 0:
                x = F.relu(x)
                n -= 1
            return x
    """)
    assert not only(f, "TPU003")


# ===========================================================================
# TPU004 — retrace hazards
# ===========================================================================
def test_tpu004_flags_loop_scalar_in_signature():
    f = lint("""
    def train(net, batches):
        for i in range(100):
            out = net(batches, i)
        return out
    """)
    hits = only(f, "TPU004")
    assert len(hits) == 1 and hits[0].severity == Severity.WARNING
    assert "'i'" in hits[0].message


def test_tpu004_flags_dict_literal_and_nonliteral_static():
    f = lint("""
    import jax
    def select():
        return (0, 1)
    def build(fn, xs):
        for x in xs:
            fn(x, {"mode": "train"})
        return jax.jit(fn, static_argnums=select())
    """)
    hits = only(f, "TPU004")
    assert len(hits) == 2
    assert any("dict/list literal" in h.message for h in hits)
    assert any("static_argnums" in h.message for h in hits)


def test_tpu004_passes_stable_signatures():
    f = lint("""
    import jax
    def train(net, batches):
        for batch in batches:
            out = net(batch)
        return out
    step = jax.jit(train, static_argnums=(0,))
    """)
    assert not only(f, "TPU004")


def test_tpu004_passes_scalar_hoisted_out_of_loop():
    f = lint("""
    def train(net, x, n_layers):
        y = net(x, n_layers)
        for _ in range(10):
            y = net(y)
        return y
    """)
    assert not only(f, "TPU004")


# ===========================================================================
# TPU005 — host RNG under trace
# ===========================================================================
def test_tpu005_flags_stdlib_and_numpy_rng():
    f = lint("""
    import random
    import numpy as np
    class Net:
        def hybrid_forward(self, F, x):
            if random.random() < 0.5:
                x = -x
            noise = np.random.normal(size=(3,))
            return x + noise
    """)
    hits = only(f, "TPU005")
    assert len(hits) == 2
    assert all(h.severity == Severity.ERROR for h in hits)
    assert "trace-time constant" in hits[0].message


def test_tpu005_flags_aliased_numpy_rng():
    f = lint("""
    import numpy as onp
    class Net:
        def hybrid_forward(self, F, x):
            return x * onp.random.rand()
    """)
    assert len(only(f, "TPU005")) == 1


def test_tpu005_flags_indirect_rng_imports():
    # every spelling of "host RNG" import must be caught, not just np.*
    f = lint("""
    import numpy.random as npr
    from numpy import random as nprand
    from numpy.random import uniform
    from random import randint
    class Net:
        def hybrid_forward(self, F, x):
            a = npr.uniform()
            b = nprand.normal()
            c = uniform(0, 1)
            d = randint(0, 9)
            return x * (a + b + c + d)
    """)
    assert len(only(f, "TPU005")) == 4


def test_tpu005_passes_keyed_device_rng():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            mask = F.uniform(0, 1, shape=(1,)) < 0.5
            noise = F.random.normal(0, 1, shape=(3,))
            return x + noise * mask
    """)
    assert not only(f, "TPU005")


def test_tpu005_passes_rng_outside_trace():
    f = lint("""
    import random
    def make_batch(n):
        return [random.random() for _ in range(n)]
    """)
    assert not only(f, "TPU005")


# ===========================================================================
# TPU006 — thread-shared module state
# ===========================================================================
def test_tpu006_flags_lockfree_thread_mutation():
    f = lint("""
    import threading
    _STATE = {}
    _EVENTS = []
    def worker():
        _STATE["k"] = 1
        _EVENTS.append("seen")
    def start():
        threading.Thread(target=worker, daemon=True).start()
    """)
    hits = only(f, "TPU006")
    assert len(hits) == 2
    assert all(h.severity == Severity.WARNING for h in hits)
    assert "_STATE" in hits[0].message


def test_tpu006_flags_transitively_reached_mutation():
    f = lint("""
    import threading
    _STATE = {}
    def helper():
        _STATE["deep"] = 2
    def worker():
        helper()
    def start():
        threading.Thread(target=worker).start()
    """)
    assert len(only(f, "TPU006")) == 1


def test_tpu006_passes_mutation_under_lock():
    f = lint("""
    import threading
    _STATE = {}
    _LOCK = threading.Lock()
    def worker():
        with _LOCK:
            _STATE["k"] = 1
    def start():
        threading.Thread(target=worker).start()
    """)
    assert not only(f, "TPU006")


def test_tpu006_passes_without_threads():
    f = lint("""
    _STATE = {}
    def main():
        _STATE["k"] = 1
    """)
    assert not only(f, "TPU006")


# ===========================================================================
# suppression comments
# ===========================================================================
def test_suppression_same_line_and_bare():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()  # tpu-lint: disable=TPU001
            b = x.asscalar()  # tpu-lint: disable
            c = x.item()
            return x
    """)
    hits = only(f, "TPU001")
    assert len(hits) == 1 and hits[0].line == 6


def test_suppression_comment_above_line():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            # tpu-lint: disable=TPU001
            a = x.asnumpy()
            return x
    """)
    assert not only(f, "TPU001")


def test_suppression_wrong_code_does_not_hide():
    f = lint("""
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()  # tpu-lint: disable=TPU003
            return x
    """)
    assert len(only(f, "TPU001")) == 1


def test_suppression_disable_file():
    f = lint("""
    # tpu-lint: disable-file=TPU001
    class Net:
        def hybrid_forward(self, F, x):
            a = x.asnumpy()
            if x.sum() > 0:
                return -x
            return x
    """)
    assert not only(f, "TPU001")
    assert len(only(f, "TPU003")) == 1  # other rules unaffected


# ===========================================================================
# programmatic check() API
# ===========================================================================
class _BadBlock(mx.gluon.HybridBlock):
    # intentionally trace-hostile — fixture for check() on live objects
    def hybrid_forward(self, F, x):
        peek = x.asnumpy()  # noqa — the finding under test
        return F.relu(x) * peek.sum()


class _GoodBlock(mx.gluon.HybridBlock):
    def hybrid_forward(self, F, x):
        return F.relu(x)


def test_check_live_block_class_and_instance():
    for target in (_BadBlock, _BadBlock()):
        f = check(target)
        assert "TPU001" in codes(f), f
    assert check(_GoodBlock()) == []


def test_check_live_function_is_traced_by_definition():
    def step(params, batch):
        loss = float(batch.sum())
        return loss

    f = check(step)
    assert "TPU001" in codes(f)
    assert all(x.line for x in f)


def test_check_path_and_rule_selection():
    path = os.path.join(REPO, "mxnet_tpu", "gluon", "loss.py")
    f = check(path)
    assert [x for x in f if x.severity == Severity.ERROR] == []
    sel = analysis.lint_file(path, rules=["TPU006"])
    assert all(x.code == "TPU006" for x in sel)


def test_rule_registry_complete():
    table = analysis.rule_table()
    got = [row[0] for row in table]
    assert got == ["TPU001", "TPU002", "TPU003", "TPU004", "TPU005",
                   "TPU006", "TPU007", "TPU008", "TPU009", "TPU010"]
    assert all(row[4] for row in table)  # every rule documented


# ===========================================================================
# CLI
# ===========================================================================
_BAD_SRC = """
class Net:
    def hybrid_forward(self, F, x):
        return x.asnumpy()
"""
_CLEAN_SRC = """
class Net:
    def hybrid_forward(self, F, x):
        return F.relu(x)
"""


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(_BAD_SRC)
    clean = tmp_path / "clean.py"
    clean.write_text(_CLEAN_SRC)

    assert cli_main([str(clean), "--fail-on=error"]) == 0
    capsys.readouterr()
    assert cli_main([str(bad), "--fail-on=error"]) == 1
    capsys.readouterr()
    assert cli_main([str(bad), "--fail-on=never"]) == 0
    capsys.readouterr()

    rc = cli_main([str(bad), "--format", "json", "--fail-on=never"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["counts"]["error"] == 1
    assert out["findings"][0]["code"] == "TPU001"
    assert out["findings"][0]["line"] == 4

    assert cli_main([]) == 2                       # no targets
    capsys.readouterr()
    assert cli_main([str(bad), "--rules", "TPU999"]) == 2
    capsys.readouterr()
    assert cli_main(["--list-rules"]) == 0
    assert "TPU006" in capsys.readouterr().out


def test_cli_module_name_target(capsys):
    rc = cli_main(["mxnet_tpu.analysis", "--fail-on=error"])
    assert rc == 0


def test_cli_cache_reuses_and_invalidates(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text(_CLEAN_SRC)
    cache = tmp_path / "cache.json"
    assert cli_main([str(target), "--cache-file", str(cache),
                     "--fail-on=error"]) == 0
    capsys.readouterr()
    assert cache.exists()
    # cached rerun stays clean; rewriting the file invalidates by mtime
    assert cli_main([str(target), "--cache-file", str(cache),
                     "--fail-on=error"]) == 0
    capsys.readouterr()
    os.utime(target, (1, 1))
    target.write_text(_BAD_SRC)
    assert cli_main([str(target), "--cache-file", str(cache),
                     "--fail-on=error"]) == 1
    capsys.readouterr()


def test_cli_end_to_end_subprocess(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_BAD_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", str(bad),
         "--fail-on=error", "--format", "json"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert r.returncode == 1, r.stderr
    out = json.loads(r.stdout)
    assert out["counts"]["error"] == 1


def test_parse_log_lint_mode(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_BAD_SRC)
    findings = analysis.lint_file(str(bad))
    dump = tmp_path / "lint.json"
    dump.write_text(json.dumps(
        {"version": 1, "counts": {"error": len(findings)},
         "findings": [f.to_dict() for f in findings]}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         str(dump), "--lint"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| severity | code | location | symbol | message |" in r.stdout
    assert "TPU001" in r.stdout
    # per-rule rollup table rides along, naming the rule
    assert "| rule | name | severity | count |" in r.stdout
    assert "| TPU001 | host-sync-under-trace | error | 1 |" in r.stdout


# ===========================================================================
# runtime trace guard
# ===========================================================================
def test_guard_off_by_default():
    assert not analysis.guard_active() or \
        os.environ.get("MXNET_TPU_TRACE_GUARD")


def test_guard_host_sync_raises_inside_jitted_step(guard_raise):
    class Bad(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.relu(x) * float(x.sum().asnumpy())

    net = Bad()
    net.initialize()
    net.hybridize()
    before = _counter("analysis.guard.host_sync")
    with pytest.raises(TraceGuardError) as exc_info:
        net(mx.nd.ones((2, 3)))
    assert exc_info.value.kind == "host_sync"
    assert exc_info.value.site == "asnumpy"
    assert _counter("analysis.guard.host_sync") == before + 1
    # eager (unhybridized) host reads stay allowed
    net2 = Bad()
    net2.initialize()
    out = net2(mx.nd.ones((2, 3)))
    assert out.shape == (2, 3)


def test_guard_warn_mode_warns_before_jax_error(guard_warn):
    class Bad(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return x * x.asnumpy().sum()

    net = Bad()
    net.initialize()
    net.hybridize()
    with pytest.warns(RuntimeWarning, match="trace guard"):
        with pytest.raises(Exception):  # jax concretization error follows
            net(mx.nd.ones((2, 2)))


def test_guard_retrace_limit_and_reason(guard_raise, monkeypatch, caplog):
    monkeypatch.setenv("MXNET_TPU_TRACE_GUARD_RETRACE_LIMIT", "2")

    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.relu(x)

    net = Net()
    net.initialize()
    net.hybridize()
    before = _counter("analysis.guard.retrace")
    with caplog.at_level(logging.DEBUG, logger="mxnet_tpu.gluon.cachedop"):
        with pytest.raises(TraceGuardError) as exc_info:
            for n in range(1, 8):
                net(mx.nd.ones((n, 2)))
    assert exc_info.value.kind == "retrace"
    assert "shape" in str(exc_info.value)
    assert _counter("analysis.guard.retrace") > before
    # the debug channel carries the per-retrace reason (which arg moved)
    assert any("arg0 shape" in rec.message for rec in caplog.records)


def test_guard_allows_stable_hybrid_calls(guard_raise):
    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.relu(x)

    net = Net()
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 3))
    for _ in range(5):
        out = net(x)
    assert out.shape == (2, 3)


def test_guard_env_var_subprocess(tmp_path):
    """Acceptance: MXNET_TPU_TRACE_GUARD=1 catches a runtime .asnumpy()
    inside a jitted step (env wiring, not just set_guard_mode)."""
    script = tmp_path / "guarded.py"
    script.write_text(textwrap.dedent("""
        import mxnet_tpu as mx
        from mxnet_tpu.analysis import TraceGuardError

        class Bad(mx.gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                return F.relu(x) * x.asnumpy().sum()

        net = Bad(); net.initialize(); net.hybridize()
        try:
            net(mx.nd.ones((2, 3)))
        except TraceGuardError as e:
            assert e.site == "asnumpy", e.site
            n = mx.telemetry.snapshot()["counters"][
                "analysis.guard.host_sync"]
            assert n == 1, n
            print("GUARD_OK")
        else:
            raise SystemExit("guard did not fire")
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_TRACE_GUARD="1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    r = subprocess.run([sys.executable, str(script)], capture_output=True,
                       text=True, timeout=180, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "GUARD_OK" in r.stdout


def test_retrace_reason_formatting():
    from mxnet_tpu.gluon.block import _retrace_reason
    old = (False, (((2, 3), "float32"), "repr:7"))
    new_shape = (False, (((4, 3), "float32"), "repr:7"))
    assert "arg0 shape (2, 3)->(4, 3)" in _retrace_reason(new_shape, old)
    new_dtype = (False, (((2, 3), "float16"), "repr:7"))
    assert "dtype" in _retrace_reason(new_dtype, old)
    new_train = (True, (((2, 3), "float32"), "repr:7"))
    assert "train mode" in _retrace_reason(new_train, old)
    new_val = (False, (((2, 3), "float32"), "repr:9"))
    assert "value" in _retrace_reason(new_val, old)
    assert _retrace_reason(new_val, None) == "first trace"


# ===========================================================================
# TPU007 — sharding annotations
# ===========================================================================
def test_tpu007_flags_undeclared_axis_in_partition_spec():
    f = lint("""
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(None, ("data", "model"))
    SPEC = P("dat", None)
    GOOD = P("model", "data")
    """)
    hits = only(f, "TPU007")
    assert len(hits) == 1 and hits[0].severity == Severity.ERROR
    assert "'dat'" in hits[0].message and "data, model" in hits[0].message


def test_tpu007_flags_dead_partition_rule_and_duplicate():
    f = lint("""
    from jax.sharding import PartitionSpec as P
    RULES = ShardingRules([
        (r"attn", P("model")),
        (r"attn/wo", P(None, "model")),
        (r"mlp/(w1|w3)", P("fsdp")),
        (r"attn", P()),
    ])
    """)
    hits = only(f, "TPU007")
    assert len(hits) == 2
    assert all("dead partition rule" in h.message for h in hits)
    assert all(h.severity == Severity.WARNING for h in hits)


def test_tpu007_flags_in_shardings_arity_mismatch():
    f = lint("""
    import jax
    def step(params, batch):
        return params
    f = jax.jit(step, in_shardings=(1, 2, 3))
    """)
    hits = only(f, "TPU007")
    assert len(hits) == 1
    assert "3 entries" in hits[0].message and "2 traced" in hits[0].message


def test_tpu007_flags_invalid_partition_rule_regex():
    f = lint("""
    RULES = ShardingRules([(r"attn/(wq", 1)])
    """)
    hits = only(f, "TPU007")
    assert len(hits) == 1 and "invalid regex" in hits[0].message


def test_tpu007_passes_declared_axes_and_specific_first_rules():
    f = lint("""
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(None, ("data", "model", "fsdp"))
    RULES = ShardingRules([
        (r"attn/wo", P("model")),
        (r"attn", P("fsdp")),
        (r"norm|bias", P()),
    ])
    SPEC = P(("model",), ("fsdp",))
    """)
    assert not only(f, "TPU007")


def test_tpu007_passes_without_any_mesh_declaration():
    # no declaration anywhere -> the axis universe is unknown, stay silent
    f = lint("""
    from jax.sharding import PartitionSpec as P
    SPEC = P("whatever")
    """)
    assert not only(f, "TPU007")


def test_tpu007_passes_matching_arity_and_static_argnums():
    f = lint("""
    import jax
    def step(params, batch):
        return params
    def stepn(n, params, batch):
        return params
    a = jax.jit(step, in_shardings=(1, 2))
    b = jax.jit(stepn, static_argnums=(0,), in_shardings=(1, 2))
    """)
    assert not only(f, "TPU007")


def test_tpu007_static_argnames_of_kwonly_param_keeps_arity():
    # static_argnames naming a KEYWORD-ONLY param never occupied an
    # in_shardings slot — the 2-entry spec is correct, not a mismatch
    f = lint("""
    import jax
    def step(x, y, *, training):
        return x
    a = jax.jit(step, static_argnames=("training",), in_shardings=(1, 2))
    """)
    assert not only(f, "TPU007")


def test_tpu007_anchored_earlier_pattern_keeps_rule_alive():
    # "embedding$" does NOT shadow "embedding": "embedding_table" only
    # matches the later rule — anchored patterns never prove deadness
    f = lint("""
    from jax.sharding import PartitionSpec as P
    RULES = ShardingRules([
        (r"embedding$", P("model")),
        (r"embedding", P("fsdp")),
    ])
    """)
    assert not only(f, "TPU007")


def test_tpu007_nonliteral_branches_keep_rules_alive():
    # "attn/(wq|wk)" after "q_proj" is NOT provably dead (regex branch)
    f = lint("""
    from jax.sharding import PartitionSpec as P
    RULES = ShardingRules([
        (r"q_proj", P("model")),
        (r"attn/(wq|wk)", P("model")),
    ])
    """)
    assert not only(f, "TPU007")


def test_tpu007_out_shardings_ignores_nested_function_returns():
    # the closure's 2-tuple return is NOT step's return arity
    f = lint("""
    import jax
    def step(x):
        def parts():
            return x, x
        return parts
    a = jax.jit(step, out_shardings=(1,))
    """)
    assert not only(f, "TPU007")


def test_tpu007_meshconfig_nonaxis_kwargs_still_declare_defaults():
    f = lint("""
    import jax
    from jax.sharding import PartitionSpec as P
    cfg = MeshConfig(devices=jax.devices())
    GOOD = P("data")
    BAD = P("dat")
    """)
    hits = only(f, "TPU007")
    assert len(hits) == 1 and "'dat'" in hits[0].message


def test_tpu007_cross_file_jit_arity_via_summary(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "steps.py").write_text(
        "def step(params, batch):\n    return params\n")
    (pkg / "main.py").write_text(
        "import jax\nfrom pkg import steps\n"
        "good = jax.jit(steps.step, in_shardings=(1, 2))\n"
        "bad = jax.jit(steps.step, in_shardings=(1, 2, 3))\n")
    hits = [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU007"]
    assert len(hits) == 1, [h.format() for h in hits]
    assert "pkg.steps.step" in hits[0].message and \
        "3 entries" in hits[0].message


def test_tpu007_self_rules_tables_are_alive():
    # LLAMA_RULES/BERT_RULES in parallel/sharding.py must never regress
    # into shadowed entries
    path = os.path.join(REPO, "mxnet_tpu", "parallel", "sharding.py")
    f = [x for x in analysis.lint_file(path, rules=["TPU007"])]
    assert f == [], [x.format() for x in f]


# ===========================================================================
# TPU008 — collective safety
# ===========================================================================
def test_tpu008_flags_collective_under_data_dependent_if():
    f = lint("""
    import jax
    from jax import lax
    @jax.jit
    def step(x):
        if x.sum() > 0:
            x = lax.psum(x, "data")
        return x
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 1 and hits[0].severity == Severity.ERROR
    assert "deadlock" in hits[0].message
    assert "step" in hits[0].symbol


def test_tpu008_flags_collective_in_cond_branch_with_traced_pred():
    f = lint("""
    import jax
    from jax import lax
    @jax.jit
    def step(x):
        return lax.cond(x.sum() > 0,
                        lambda v: lax.psum(v, "data"),
                        lambda v: v, x)
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 1
    assert "lax.cond" in hits[0].message


def test_tpu008_flags_unbound_axis_name():
    f = lint("""
    import jax
    from jax import lax
    from jax.sharding import Mesh
    mesh = Mesh(None, ("data", "model"))
    @jax.jit
    def step(x):
        return lax.all_gather(x, "batch")
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 1
    assert "'batch'" in hits[0].message and "data, model" in hits[0].message


def test_tpu008_flags_undivisible_static_leading_dim():
    f = lint("""
    import jax.numpy as jnp
    def sync():
        mesh = local_mesh(4)
        g0 = jnp.ones((6, 2))
        g1 = jnp.ones((8, 2))
        return all_reduce_multi([g0, g1], mesh=mesh)
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 1 and hits[0].severity == Severity.WARNING
    assert "'g0'" in hits[0].message and "zero-pads" in hits[0].message


def test_tpu008_flags_axis_index_divergent_collective():
    """`lax.axis_index()` is per-rank by construction — branching on it
    and meeting in a collective is the canonical mesh deadlock."""
    f = lint("""
    import jax
    from jax import lax
    @jax.jit
    def step(x):
        if lax.axis_index("data") == 0:
            x = lax.psum(x, "data")
        return x
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 1
    assert "deadlock" in hits[0].message
    # the branch itself is also untraceable — TPU003 fires alongside
    assert len(only(f, "TPU003")) == 1


def test_tpu008_passes_unconditional_and_none_guarded_collectives():
    f = lint("""
    import jax
    from jax import lax
    @jax.jit
    def step(x, bias=None):
        y = lax.psum(x, "data")
        if bias is not None:
            y = y + lax.psum(bias, "data")
        return y
    """)
    assert not only(f, "TPU008")


def test_tpu008_passes_bound_axis_and_divisible_dims():
    f = lint("""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh
    mesh2 = Mesh(None, ("data",))
    @jax.jit
    def step(x):
        return lax.psum(x, "data")
    def sync():
        mesh = local_mesh(4)
        g = jnp.ones((8, 2))
        return all_reduce_multi([g], mesh=mesh)
    """)
    assert not only(f, "TPU008")


def test_tpu008_knows_zero_sharding_collectives():
    """ISSUE 9 satellite: the ZeRO weight-update collectives
    (`reduce_scatter_multi` / `all_gather_multi`) are rendezvous ops —
    divergent-branch placement and unbound axis names must flag exactly
    like psum."""
    f = lint("""
    import jax
    from mxnet_tpu.parallel.collectives import (reduce_scatter_multi,
                                                all_gather_multi)
    @jax.jit
    def step(xs, layout):
        if xs[0].sum() > 0:
            shards, layout = reduce_scatter_multi(xs, "data", axis_size=4)
            xs = all_gather_multi(shards, layout, "data")
        return xs
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 2
    assert all("deadlock" in h.message for h in hits)


def test_tpu008_zero_collectives_axis_binding_checked():
    f = lint("""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.parallel.collectives import all_gather_multi
    mesh = Mesh(None, ("data",))
    @jax.jit
    def step(shards, layout):
        return all_gather_multi(shards, layout, "worker")
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 1
    assert "worker" in hits[0].message


def test_tpu008_knows_sparse_row_collectives():
    """ISSUE 17 satellite: the unique-rows sparse collectives
    (`all_gather_rows` / `psum_unique_rows`) rendezvous like psum —
    divergent-branch placement flags, and the axis argument (positional
    slot 2, after the ids/vals slabs) is checked against the declared
    axes."""
    f = lint("""
    import jax
    from mxnet_tpu.parallel.collectives import (all_gather_rows,
                                                psum_unique_rows)
    @jax.jit
    def step(ids, vals):
        if vals.sum() > 0:
            ids, vals = psum_unique_rows(ids, vals, "data")
        return all_gather_rows(ids, vals, "data")
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 1
    assert "deadlock" in hits[0].message


def test_tpu008_sparse_row_collectives_axis_binding_checked():
    f = lint("""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.parallel.collectives import (all_gather_rows,
                                                psum_unique_rows)
    mesh = Mesh(None, ("data",))
    @jax.jit
    def gather(ids, vals):
        return all_gather_rows(ids, vals, "rows")
    @jax.jit
    def merge(ids, vals):
        return psum_unique_rows(ids, vals, "rows", pad_id=-1)
    """)
    hits = only(f, "TPU008")
    assert len(hits) == 2
    assert all("'rows'" in h.message for h in hits)


def test_tpu008_passes_cond_with_collective_free_branches():
    f = lint("""
    import jax
    from jax import lax
    @jax.jit
    def step(x):
        return lax.cond(x.sum() > 0, lambda v: v * 2, lambda v: v, x)
    """)
    assert not only(f, "TPU008")


def test_tpu008_axis_index_is_not_a_rendezvous():
    # axis_index reads the local coordinate — no cross-rank rendezvous,
    # legal inside divergent branches (only its axis_name is checked)
    f = lint("""
    import jax
    from jax import lax
    from jax.sharding import Mesh
    mesh = Mesh(None, ("data",))
    @jax.jit
    def step(x):
        return lax.cond(x.sum() > 0,
                        lambda v: v * lax.axis_index("data"),
                        lambda v: v, x)
    """)
    assert not only(f, "TPU008")


def test_tpu008_function_defined_in_branch_is_not_executed():
    # a lambda/def CREATED inside a divergent branch executes nothing
    # there — only calls in the branch body itself diverge
    f = lint("""
    import jax
    from jax import lax
    @jax.jit
    def step(x):
        cb = lambda g: g
        if x.sum() > 0:
            cb = lambda g: lax.psum(g, "data")
        return cb(x)
    """)
    assert not only(f, "TPU008")


def test_tpu008_nested_tainted_ifs_report_once():
    f = lint("""
    import jax
    from jax import lax
    @jax.jit
    def step(x):
        if x.sum() > 0:
            if x.min() < 0:
                x = lax.psum(x, "data")
        return x
    """)
    assert len(only(f, "TPU008")) == 1


def test_tpu008_divisibility_is_function_scoped():
    # `g` in other() must not alias sync()'s parameter of unknown shape
    f = lint("""
    import jax.numpy as jnp
    def other():
        g = jnp.ones((6, 2))
        return g
    def sync(g):
        mesh = local_mesh(4)
        return all_reduce_multi([g], mesh=mesh)
    """)
    assert not only(f, "TPU008")


def test_tpu007_axes_from_fully_dotted_mesh_ctor():
    # jax.sharding.Mesh(...) at full attribute depth still declares axes
    f = lint("""
    import jax
    from jax.sharding import PartitionSpec as P
    mesh = jax.sharding.Mesh(None, ("data", "model"))
    BAD = P("dat")
    GOOD = P("model")
    """)
    hits = only(f, "TPU007")
    assert len(hits) == 1 and "'dat'" in hits[0].message


# ===========================================================================
# cross-file taint (one level over project imports)
# ===========================================================================
_XF_HELPER_BAD = """
import numpy as np

def probe(x):
    return float(x.sum().item())

def clean(x):
    return x * 2

def noisy():
    return np.random.rand()
"""
_XF_HELPER_FIXED = """
def probe(x):
    return x.sum()

def clean(x):
    return x * 2

def noisy():
    return 4  # chosen by fair dice roll ahead of time, on the host
"""
_XF_MODEL = """
import jax
from pkg.helpers import probe, clean, noisy
from . import helpers

@jax.jit
def step(x):
    a = probe(x)
    b = clean(x)
    c = helpers.probe(2)
    d = noisy()
    return b * a * c * d
"""


def _write_pkg(tmp_path, helper_src):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "helpers.py").write_text(helper_src)
    (pkg / "model.py").write_text(_XF_MODEL)
    return pkg


def test_cross_file_taint_flags_helper_sync_at_traced_call_site(tmp_path):
    pkg = _write_pkg(tmp_path, _XF_HELPER_BAD)
    findings = analysis.lint_paths([str(pkg)])
    sync = [f for f in findings if f.code == "TPU001"]
    assert len(sync) == 1, [f.format() for f in findings]
    # flagged at the CALLER (model.py), pointing at the helper's line
    assert sync[0].file.endswith("model.py")
    assert "pkg.helpers.probe" in sync[0].message
    assert "helpers.py:" in sync[0].message
    assert "step" in sync[0].symbol


def test_cross_file_taint_flags_helper_rng(tmp_path):
    pkg = _write_pkg(tmp_path, _XF_HELPER_BAD)
    findings = analysis.lint_paths([str(pkg)])
    rng = [f for f in findings if f.code == "TPU005"]
    assert len(rng) == 1
    assert rng[0].file.endswith("model.py")
    assert "pkg.helpers.noisy" in rng[0].message


def test_cross_file_taint_passes_when_helper_cleaned(tmp_path):
    # acceptance: the SAME caller passes once the helper is device-pure
    pkg = _write_pkg(tmp_path, _XF_HELPER_FIXED)
    findings = analysis.lint_paths([str(pkg)])
    assert codes(findings) == [], [f.format() for f in findings]


def test_cross_file_taint_ignores_untainted_args(tmp_path):
    # helpers.probe(2) in the fixture carries no tracer: only the
    # tainted call is flagged (one TPU001, not two)
    pkg = _write_pkg(tmp_path, _XF_HELPER_BAD)
    findings = [f for f in analysis.lint_paths([str(pkg)])
                if f.code == "TPU001"]
    assert len(findings) == 1


def test_cross_file_taint_relative_import_from_package_init(tmp_path):
    """`from . import helpers` / `from .helpers import probe` inside a
    package __init__.py anchor at the package ITSELF, not its parent."""
    pkg = tmp_path / "pkg"
    sub = pkg / "sub"
    sub.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (sub / "helpers.py").write_text(_XF_HELPER_BAD)
    (sub / "__init__.py").write_text(
        "import jax\n"
        "from .helpers import probe\n"
        "from . import helpers\n\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return probe(x) * helpers.noisy()\n")
    findings = analysis.lint_paths([str(pkg)])
    assert "TPU001" in codes(findings) and "TPU005" in codes(findings)
    assert all(f.file.endswith("__init__.py") for f in findings
               if f.code in ("TPU001", "TPU005"))


def test_cross_file_taint_disabled_without_project(tmp_path):
    pkg = _write_pkg(tmp_path, _XF_HELPER_BAD)
    findings = analysis.lint_paths([str(pkg)], project=None)
    assert [f for f in findings if f.code in ("TPU001", "TPU005")] == []


def test_cross_file_cache_invalidates_when_helper_changes(tmp_path):
    """The findings cache keys on the project digest: fixing the HELPER
    must invalidate the CALLER's cached findings."""
    from mxnet_tpu.analysis.cli import FileCache
    pkg = _write_pkg(tmp_path, _XF_HELPER_BAD)
    cache = FileCache(str(tmp_path / "cache.json"))
    first = analysis.lint_paths([str(pkg)], cache=cache)
    assert any(f.code == "TPU001" for f in first)
    os.utime(str(pkg / "helpers.py"), (1, 1))
    (pkg / "helpers.py").write_text(_XF_HELPER_FIXED)
    second = analysis.lint_paths([str(pkg)], cache=cache)
    assert [f for f in second if f.code in ("TPU001", "TPU005")] == []


_XF_BRANCHY = """
def route(x, flag):
    if flag > 0:
        return x * 2
    return x
"""


def _write_ctl_pkg(tmp_path, call):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "helpers.py").write_text(_XF_BRANCHY)
    (pkg / "model.py").write_text(
        "import jax\n"
        "from .helpers import route\n\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return %s\n" % call)
    return pkg


def test_cross_file_ctl_flags_helper_branch_at_call_site(tmp_path):
    pkg = _write_ctl_pkg(tmp_path, "route(x, x.sum())")
    hits = [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU003"]
    assert len(hits) == 1
    assert hits[0].file.endswith("model.py")
    assert "pkg.helpers.route" in hits[0].message
    assert "if on parameter 'flag'" in hits[0].message
    assert "helpers.py:3" in hits[0].message


def test_cross_file_ctl_keyword_argument_maps_to_parameter(tmp_path):
    pkg = _write_ctl_pkg(tmp_path, "route(2, flag=x.sum())")
    hits = [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU003"]
    assert len(hits) == 1 and "flag" in hits[0].message


def test_cross_file_ctl_clean_when_branch_param_is_static(tmp_path):
    # the traced value flows into `x`, the branch is on static `flag`
    pkg = _write_ctl_pkg(tmp_path, "route(x, 3)")
    assert [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU003"] == []


def _write_depth_pkg(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "deep.py").write_text(
        "def pull(v):\n"
        "    return float(v.sum())\n")
    (pkg / "mid.py").write_text(
        "from .deep import pull\n\n"
        "def stage(y):\n"
        "    return pull(y)\n")
    (pkg / "model.py").write_text(
        "import jax\n"
        "from .mid import stage\n\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return stage(x)\n")
    return pkg


def test_import_depth_two_reaches_second_hop(tmp_path):
    pkg = _write_depth_pkg(tmp_path)
    hits = [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU001"]
    assert len(hits) == 1
    assert hits[0].file.endswith("model.py")
    assert "pkg.mid.stage" in hits[0].message
    assert "deep.py" in hits[0].message  # names the second-hop sync


def test_import_depth_env_knob_limits_folding(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_TRACELINT_IMPORT_DEPTH", "1")
    pkg = _write_depth_pkg(tmp_path)
    hits = [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU001"]
    assert hits == []


def test_depth_changes_project_digest(tmp_path):
    from mxnet_tpu.analysis.engine import build_project
    pkg = _write_depth_pkg(tmp_path)
    from mxnet_tpu.analysis.project import ProjectContext
    from mxnet_tpu.analysis.rules import LINT_VERSION
    d2 = ProjectContext([str(pkg)], lint_version=LINT_VERSION, depth=2)
    d1 = ProjectContext([str(pkg)], lint_version=LINT_VERSION, depth=1)
    assert d2.digest() != d1.digest()


def test_summary_cache_round_trip(tmp_path):
    from mxnet_tpu.analysis.project import SummaryCache
    from mxnet_tpu.analysis.engine import build_project
    pkg = _write_pkg(tmp_path, _XF_HELPER_BAD)
    cache_path = str(tmp_path / "summaries.json")
    proj = build_project([str(pkg)], summary_cache=cache_path)
    s = proj.summary("pkg.helpers")
    assert s is not None and "probe" in s.functions
    assert any(h[0] == "sync" for h in s.functions["probe"].hazards)
    proj.save_cache()
    assert os.path.exists(cache_path)
    # a fresh context reads the summary back from disk
    from mxnet_tpu.analysis.rules import LINT_VERSION
    sc = SummaryCache(cache_path, LINT_VERSION)
    cached = sc.get(str(pkg / "helpers.py"))
    assert cached is not None and "noisy" in cached.functions
    assert any(h[0] == "rng" for h in cached.functions["noisy"].hazards)


# ===========================================================================
# baseline gate (CI findings gate)
# ===========================================================================
_BASE_BAD_TWO = """
class Net:
    def hybrid_forward(self, F, x):
        a = x.asnumpy()
        return x
"""
_BASE_BAD_THREE = """
class Net:
    def hybrid_forward(self, F, x):
        a = x.asnumpy()
        b = x.item()
        return x
"""


def test_baseline_gate_semantics(tmp_path, capsys):
    target = tmp_path / "mod.py"
    baseline = tmp_path / "baseline.json"
    target.write_text(_BASE_BAD_TWO)

    # no baseline file: everything is new -> gate fails
    assert cli_main([str(target), "--baseline", str(baseline),
                     "--fail-on=error"]) == 1
    capsys.readouterr()

    # record the baseline: the same finding now passes
    assert cli_main([str(target), "--baseline", str(baseline),
                     "--update-baseline"]) == 0
    capsys.readouterr()
    data = json.loads(baseline.read_text())
    assert data["entries"] and all(
        v == 1 for v in data["entries"].values())
    assert cli_main([str(target), "--baseline", str(baseline),
                     "--fail-on=error"]) == 0
    capsys.readouterr()

    # a NEW finding fails even though the old one is baselined
    os.utime(str(target), (1, 1))
    target.write_text(_BASE_BAD_THREE)
    rc = cli_main([str(target), "--baseline", str(baseline),
                   "--format", "json", "--fail-on=error"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["baseline"]["suppressed"] == 1
    assert out["baseline"]["new"] == 1
    assert out["findings"][0]["message"].startswith(".item()")

    # fixing everything leaves a stale entry; --update-baseline prunes it
    os.utime(str(target), (2, 2))
    target.write_text(_CLEAN_SRC)
    rc = cli_main([str(target), "--baseline", str(baseline),
                   "--format", "json", "--fail-on=error"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["baseline"]["stale"] == 1
    assert cli_main([str(target), "--baseline", str(baseline),
                     "--update-baseline"]) == 0
    capsys.readouterr()
    assert json.loads(baseline.read_text())["entries"] == {}


def test_baseline_fingerprints_survive_line_shifts(tmp_path, capsys):
    target = tmp_path / "mod.py"
    baseline = tmp_path / "baseline.json"
    target.write_text(_BASE_BAD_TWO)
    assert cli_main([str(target), "--baseline", str(baseline),
                     "--update-baseline"]) == 0
    capsys.readouterr()
    # unrelated code above moves the finding down two lines — still
    # baselined (fingerprints carry no line numbers)
    os.utime(str(target), (1, 1))
    target.write_text("import os\nimport sys\n" + _BASE_BAD_TWO)
    assert cli_main([str(target), "--baseline", str(baseline),
                     "--fail-on=error"]) == 0
    capsys.readouterr()


def test_baseline_matches_from_any_cwd(tmp_path, capsys, monkeypatch):
    """The gate must keep matching when invoked from OUTSIDE the tree
    with absolute targets: absolute finding paths fall back to
    path-suffix fingerprint matching against the repo-relative
    baseline."""
    tree = tmp_path / "tree"
    tree.mkdir()
    (tree / "mod.py").write_text(_BASE_BAD_TWO)
    baseline = tree / "baseline.json"
    monkeypatch.chdir(str(tree))
    assert cli_main(["mod.py", "--baseline", str(baseline),
                     "--update-baseline"]) == 0
    capsys.readouterr()
    elsewhere = tmp_path / "elsewhere"
    elsewhere.mkdir()
    monkeypatch.chdir(str(elsewhere))
    assert cli_main([str(tree / "mod.py"), "--baseline", str(baseline),
                     "--fail-on=error"]) == 0
    capsys.readouterr()


def test_sarif_output(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text(_BASE_BAD_TWO)
    rc = cli_main([str(target), "--format", "sarif", "--fail-on=never"])
    assert rc == 0
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "tracelint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"TPU001", "TPU007", "TPU008"} <= rule_ids
    res = run["results"][0]
    assert res["ruleId"] == "TPU001" and res["level"] == "error"
    assert res["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 4
    assert "tracelint/v1" in res["partialFingerprints"]


# ===========================================================================
# meta: the tree lints itself clean (tier-1 self-check, `lint` marker)
# ===========================================================================
@pytest.mark.lint
def test_mxnet_tpu_is_error_clean():
    findings = analysis.lint_paths([os.path.join(REPO, "mxnet_tpu")])
    errors = [f for f in findings if f.severity == Severity.ERROR]
    assert not errors, "tracelint errors in mxnet_tpu/:\n" + \
        "\n".join(f.format() for f in errors)


@pytest.mark.lint
def test_run_tracelint_script():
    r = subprocess.run(
        ["sh", os.path.join(REPO, "tools", "run_tracelint.sh")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 error(s)" in r.stdout


@pytest.mark.lint
def test_run_tracelint_ci_gate_passes_committed_baseline(tmp_path):
    """Acceptance: --ci exits 0 against the committed baseline, and
    non-zero when a new finding is introduced (an extra target file
    stands in for an edit to the tree)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_TRACELINT_CACHE=str(tmp_path / "cache.json"))
    r = subprocess.run(
        ["sh", os.path.join(REPO, "tools", "run_tracelint.sh"), "--ci"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in r.stdout

    bad = tmp_path / "newly_introduced.py"
    bad.write_text(_BASE_BAD_TWO)
    r = subprocess.run(
        ["sh", os.path.join(REPO, "tools", "run_tracelint.sh"), "--ci",
         str(bad)],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "1 new" in r.stdout


@pytest.mark.lint
def test_committed_baseline_matches_tree():
    """The checked-in baseline must stay in sync: no finding outside it
    (a new hazard must be fixed or reviewed into the baseline) and no
    stale entries (fixed findings must be pruned)."""
    from mxnet_tpu.analysis.cli import apply_baseline, load_baseline
    cwd = os.getcwd()
    os.chdir(REPO)
    try:
        findings = analysis.lint_paths(
            [os.path.join(REPO, "mxnet_tpu"),
             os.path.join(REPO, "tools", "mxtop.py")])
        baseline = load_baseline(
            os.path.join(REPO, "tools", "tracelint_baseline.json"))
        assert baseline, "committed baseline missing or empty"
        new, _baselined, stale = apply_baseline(findings, baseline)
    finally:
        os.chdir(cwd)
    assert new == [], "unbaselined findings:\n" + "\n".join(
        f.format() for f in new)
    assert stale == [], "stale baseline entries (prune them):\n" + \
        "\n".join(stale)
