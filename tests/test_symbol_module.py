"""Symbol + Module tests — modeled on reference tests/python/unittest/
test_symbol.py and test_module.py."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.io.io import NDArrayIter


def _mlp_symbol():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=16)
    act1 = sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = sym.FullyConnected(act1, name="fc2", num_hidden=3)
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_symbol_compose_and_lists():
    data = sym.var("data")
    net1 = sym.FullyConnected(data, name="fc1", num_hidden=10)
    net1 = sym.FullyConnected(net1, name="fc2", num_hidden=100)
    assert net1.list_arguments() == \
        ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"]

    net2 = sym.FullyConnected(sym.var("data2"), name="fc3", num_hidden=10)
    net2 = sym.Activation(net2, act_type="relu")
    net2 = sym.FullyConnected(net2, name="fc4", num_hidden=20)
    composed = net2(data2=net1, name="composed")
    args = composed.list_arguments()
    assert "fc1_weight" in args and "fc4_weight" in args
    assert "data2" not in args


def test_symbol_infer_shape():
    data = sym.var("data")
    out = sym.FullyConnected(data, name="fc1", num_hidden=10)
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(4, 7))
    assert arg_shapes == [(4, 7), (10, 7), (10,)]
    assert out_shapes == [(4, 10)]
    assert aux_shapes == []


def test_symbol_json_roundtrip(tmp_path):
    net = _mlp_symbol()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    f = str(tmp_path / "net-symbol.json")
    net.save(f)
    net3 = sym.load(f)
    assert net3.tojson() == js


def test_symbol_eval_matches_nd():
    a = sym.var("a")
    b = sym.var("b")
    c = a + b * 2.0
    x = mx.nd.ones((2, 3))
    y = mx.nd.full((2, 3), 3.0)
    out = c.eval(a=x, b=y)[0]
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 7.0))


def test_simple_bind_forward_backward():
    net = _mlp_symbol()
    ex = net.simple_bind(mx.cpu(), data=(4, 5), softmax_label=(4,))
    ex.arg_dict["data"][:] = np.random.normal(size=(4, 5)).astype("float32")
    ex.arg_dict["fc1_weight"][:] = \
        np.random.normal(size=(16, 5)).astype("float32") * 0.1
    ex.arg_dict["fc2_weight"][:] = \
        np.random.normal(size=(3, 16)).astype("float32") * 0.1
    ex.arg_dict["softmax_label"][:] = np.array([0, 1, 2, 1], dtype="float32")
    out = ex.forward(is_train=True)[0]
    assert out.shape == (4, 3)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(4),
                               rtol=1e-5)
    ex.backward()
    g = ex.grad_dict["fc1_weight"].asnumpy()
    assert np.abs(g).sum() > 0


def test_module_fit():
    mx.random.seed(0)
    np.random.seed(0)
    x = np.random.normal(size=(96, 8)).astype("float32")
    w = np.random.normal(size=(8, 3)).astype("float32")
    y = np.argmax(x @ w, axis=1).astype("float32")
    train_iter = NDArrayIter(x, y, batch_size=16, shuffle=True,
                             label_name="softmax_label")

    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.fit(train_iter, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    score = mod.score(train_iter, "acc")
    assert score[0][1] > 0.6, score

    # predict
    out = mod.predict(train_iter)
    assert out.shape[0] == 96


def test_module_save_load_checkpoint(tmp_path):
    x = np.random.normal(size=(32, 8)).astype("float32")
    y = np.zeros(32, dtype="float32")
    it = NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3)

    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu(),
                              label_names=("softmax_label",))
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_module_multi_device():
    """Batch sliced over several (virtual) devices — SURVEY §2.3 DP row."""
    n_dev = 2
    ctxs = [mx.cpu(i) for i in range(n_dev)]
    x = np.random.normal(size=(32, 8)).astype("float32")
    w = np.random.normal(size=(8, 3)).astype("float32")
    y = np.argmax(x @ w, axis=1).astype("float32")
    it = NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=ctxs,
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=3, kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    out = mod.predict(it)
    assert out.shape == (32, 3)


def test_bucketing_module():
    def sym_gen(seq_len):
        # params must be bucket-independent (as with the reference's RNN
        # buckets): FC applied per-step with flatten=False
        data = sym.var("data")
        fc = sym.FullyConnected(data, name="fc", num_hidden=4,
                                flatten=False)
        pooled = sym.mean(fc, axis=1)
        out = sym.SoftmaxOutput(pooled, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    from mxnet_tpu.io.io import DataBatch, DataDesc
    x10 = mx.nd.ones((8, 10, 6))
    y = mx.nd.zeros((8,))
    batch10 = DataBatch([x10], [y],
                        provide_data=[DataDesc("data", (8, 10, 6))],
                        provide_label=[DataDesc("softmax_label", (8,))])
    batch10.bucket_key = 10
    mod.bind(data_shapes=batch10.provide_data,
             label_shapes=batch10.provide_label)
    mod.init_params()
    mod.init_optimizer(kvstore=None)
    mod.forward(batch10, is_train=True)
    mod.backward()
    mod.update()
    assert mod.get_outputs()[0].shape == (8, 4)

    x5 = mx.nd.ones((8, 5, 6))
    batch5 = DataBatch([x5], [y],
                       provide_data=[DataDesc("data", (8, 5, 6))],
                       provide_label=[DataDesc("softmax_label", (8,))])
    batch5.bucket_key = 5
    mod.forward(batch5, is_train=True)
    assert mod.get_outputs()[0].shape == (8, 4)


def test_sym_contrib_namespace():
    """mx.sym.contrib (reference: python/mxnet/symbol/contrib.py): contrib
    ops compose into graphs and bind like core ops."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym, nd
    data = sym.Variable("feat")
    anchors = sym.contrib.MultiBoxPrior(data, sizes=(0.4,),
                                        ratios=(1.0, 2.0))
    assert anchors.list_arguments() == ["feat"]
    ex = anchors.bind(mx.cpu(), {"feat": nd.zeros((1, 8, 2, 2))})
    out = ex.forward()[0]
    assert out.shape == (1, 2 * 2 * 2, 4)
    assert hasattr(sym.contrib, "interleaved_matmul_selfatt_qk")
    assert hasattr(sym.contrib, "box_nms")


def test_bucketing_update_on_new_bucket_after_init_optimizer():
    """A bucket created AFTER init_optimizer must inherit the shared
    optimizer (regression: its update() asserted optimizer_initialized)."""
    import mxnet_tpu as mx
    from mxnet_tpu.io.io import DataBatch, DataDesc

    def sym_gen(T):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        # bucket-independent parameter shapes (params are shared): pool
        # over the time axis before the shared classifier
        pooled = mx.sym.mean(data, axis=1, name="pool")
        fc = mx.sym.FullyConnected(pooled, num_hidden=3, name="fcw")
        return mx.sym.SoftmaxOutput(fc, label, name="softmax"), \
            ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=6,
                                 context=mx.cpu())
    y = mx.nd.zeros((4,))

    def batch(T):
        b = DataBatch([mx.nd.ones((4, T, 4))], [y],
                      provide_data=[DataDesc("data", (4, T, 4))],
                      provide_label=[DataDesc("softmax_label", (4,))])
        b.bucket_key = T
        return b

    mod.bind(data_shapes=batch(6).provide_data,
             label_shapes=batch(6).provide_label)
    mod.init_params()
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    mod.forward(batch(6), is_train=True)
    mod.backward()
    mod.update()
    # NEW bucket after init_optimizer: forward/backward/update must work
    mod.forward(batch(3), is_train=True)
    mod.backward()
    mod.update()
    assert mod.get_outputs()[0].shape == (4, 3)
