"""Sparse storage + ops + kvstore + FM end-to-end.

reference idioms: tests/python/unittest/test_sparse_ndarray.py,
test_sparse_operator.py, test_kvstore.py (rowsparse), and the FM training
config (BASELINE config #4, example/sparse/factorization_machine).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.ndarray import sparse as sp


def _rand_csr(m, n, density=0.3):
    dense = np.random.rand(m, n) * (np.random.rand(m, n) < density)
    return sp.csr_matrix(dense.astype(np.float32)), dense.astype(np.float32)


def test_rsp_roundtrip_and_retain():
    dense = np.zeros((6, 3), np.float32)
    dense[1] = 1.0
    dense[4] = 2.0
    rsp = sp.row_sparse_array(dense)
    assert rsp.stype == "row_sparse"
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(rsp.tostype("default").asnumpy(), dense)
    kept = sp.retain(rsp, np.array([0, 4]))
    out = kept.tostype("default").asnumpy()
    np.testing.assert_allclose(out[4], dense[4])
    assert out[1].sum() == 0


def test_csr_roundtrip():
    csr, dense = _rand_csr(5, 7)
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.tostype("default").asnumpy(), dense,
                               rtol=1e-6)
    back = csr.tostype("default").tostype("csr")
    np.testing.assert_allclose(back.tostype("default").asnumpy(), dense,
                               rtol=1e-6)


def test_csr_dot_forward_backward():
    csr, dense = _rand_csr(4, 6)
    w = nd.array(np.random.rand(6, 3).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        out = sp.dot(csr, w)
        loss = nd.sum(out)
    loss.backward()
    np.testing.assert_allclose(out.asnumpy(), dense @ w.asnumpy(),
                               rtol=1e-5, atol=1e-5)
    # d(sum(X@W))/dW = X^T @ ones
    expect = dense.T @ np.ones((4, 3), np.float32)
    np.testing.assert_allclose(w.grad.asnumpy(), expect, rtol=1e-5,
                               atol=1e-5)


def test_csr_dot_transpose():
    csr, dense = _rand_csr(4, 6)
    rhs = nd.array(np.random.rand(4, 2).astype(np.float32))
    out = sp.dot(csr, rhs, transpose_a=True)
    np.testing.assert_allclose(out.asnumpy(), dense.T @ rhs.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_rsp_elemwise_add():
    a = sp.row_sparse_array(np.diag([1., 0, 2, 0]).astype(np.float32))
    b = sp.row_sparse_array(np.diag([0., 3, 4, 0]).astype(np.float32))
    out = sp.elemwise_add(a, b)
    np.testing.assert_allclose(out.tostype("default").asnumpy(),
                               np.diag([1., 3, 6, 0]))


def test_lazy_sgd_momentum_untouched_rows():
    """reference rowsparse sgd_mom semantics: momentum of rows absent from
    the grad must NOT decay."""
    opt = mx.optimizer.create("sgd", learning_rate=1.0, momentum=0.5,
                              rescale_grad=1.0)
    w = nd.array(np.ones((4, 2), np.float32))
    mom = opt.create_state(0, w)
    mom[:] = nd.array(np.full((4, 2), 10.0, np.float32))
    grad = sp.RowSparseNDArray(
        sp.jnp.asarray(np.full((1, 2), 1.0, np.float32)),
        sp.jnp.asarray(np.array([2], np.int32)), (4, 2))
    before = w.asnumpy().copy()
    opt.update(0, w, grad, mom)
    after = w.asnumpy()
    momn = mom.asnumpy()
    # untouched rows: no weight change, momentum untouched
    np.testing.assert_array_equal(after[0], before[0])
    np.testing.assert_array_equal(momn[0], 10.0 * np.ones(2))
    # touched row moved and its momentum decayed
    assert not np.allclose(after[2], before[2])
    assert not np.allclose(momn[2], 10.0)


def test_kvstore_rowsparse_push_pull():
    kv = mx.kv.create("local")
    weight = nd.array(np.arange(12, dtype=np.float32).reshape(6, 2))
    kv.init(3, weight)
    # server-side optimizer (reference: set_optimizer → updater on server)
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5,
                                         rescale_grad=1.0))
    g1 = sp.row_sparse_array((np.ones((1, 2), np.float32), [1]), shape=(6, 2))
    g2 = sp.row_sparse_array((np.ones((1, 2), np.float32), [1]), shape=(6, 2))
    kv.push(3, [g1, g2])   # two "devices" push the same sparse row
    out = sp.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull(3, out=out, row_ids=nd.array(np.array([1, 4])))
    dense = out.tostype("default").asnumpy()
    # row1 got -0.5*(1+1) = -1 applied: 2,3 -> 1,2
    np.testing.assert_allclose(dense[1], [1.0, 2.0])
    np.testing.assert_allclose(dense[4], [8.0, 9.0])  # untouched
    assert dense[0].sum() == 0  # not pulled


def test_libsvm_iter(tmp_path):
    f = tmp_path / "train.libsvm"
    f.write_text("1 0:1.5 3:2.0\n0 1:1.0\n1 2:0.5 3:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(f), data_shape=4, batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    b0 = batches[0]
    assert b0.data[0].stype == "csr"
    dense = b0.data[0].tostype("default").asnumpy()
    np.testing.assert_allclose(dense[0], [1.5, 0, 0, 2.0])
    np.testing.assert_allclose(b0.label[0].asnumpy(), [1.0, 0.0])
    # last batch wraps one row; pad reports it (NDArrayIter.getpad contract)
    assert b0.pad == 0 and batches[1].pad == 1
    # separate label file overrides column-0 labels
    lf = tmp_path / "labels.libsvm"
    lf.write_text("5\n6\n7\n")
    it2 = mx.io.LibSVMIter(data_libsvm=str(f), label_libsvm=str(lf),
                           data_shape=4, batch_size=3)
    np.testing.assert_allclose(next(iter(it2)).label[0].asnumpy(),
                               [5.0, 6.0, 7.0])


def test_factorization_machine_end_to_end(tmp_path):
    """FM on synthetic libsvm data: csr batches, autograd through sparse
    dot, rowsparse grads pushed through a kvstore with server-side
    optimizer, lazy updates. BASELINE config #4 in miniature."""
    rng = np.random.RandomState(0)
    dim, n_samples = 30, 200
    w_true = rng.randn(dim).astype(np.float32)
    lines = []
    for _ in range(n_samples):
        nnz = rng.randint(2, 6)
        idx = sorted(rng.choice(dim, size=nnz, replace=False))
        vals = rng.rand(nnz).astype(np.float32)
        y = 1 if sum(w_true[i] * v for i, v in zip(idx, vals)) > 0 else 0
        lines.append(str(y) + " " +
                     " ".join("%d:%.4f" % (i, v) for i, v in zip(idx, vals)))
    f = tmp_path / "fm.libsvm"
    f.write_text("\n".join(lines) + "\n")

    batch_size, k = 50, 4
    w = nd.array(np.zeros((dim, 1), np.float32))
    v = nd.array((rng.randn(dim, k) * 0.05).astype(np.float32))
    b = nd.array(np.zeros((1,), np.float32))
    for p in (w, v, b):
        p.attach_grad()

    kv = mx.kv.create("local")
    kv.init(0, w)
    kv.init(1, v)
    kv.set_optimizer(mx.optimizer.create("adagrad", learning_rate=0.5,
                                         rescale_grad=1.0 / batch_size))

    def forward(csr, csr_sq):
        lin = sp.dot(csr, w)                              # (B,1)
        xv = sp.dot(csr, v)                               # (B,k)
        x2v2 = sp.dot(csr_sq, nd.square(v))               # (B,k)
        pair = 0.5 * nd.sum(nd.square(xv) - x2v2, axis=1, keepdims=True)
        return lin + pair + b

    losses = []
    for epoch in range(10):
        it = mx.io.LibSVMIter(data_libsvm=str(f), data_shape=dim,
                              batch_size=batch_size)
        total, count = 0.0, 0
        for batch in it:
            csr = batch.data[0]
            sq = sp.CSRNDArray(csr._sp_data * csr._sp_data,
                               csr._sp_indices, csr._indptr, csr.shape)
            y = batch.label[0].reshape((-1, 1))
            with autograd.record():
                out = forward(csr, sq)
                # logistic loss
                loss = nd.mean(nd.log(1 + nd.exp(-(2 * y - 1) * out)))
            loss.backward()
            # communicate sparse: only rows this batch touched
            touched = np.unique(np.asarray(csr._sp_indices))
            rows = sp.jnp.asarray(touched.astype(np.int32))
            gw = sp.RowSparseNDArray(w.grad._read()[rows] * batch_size,
                                     rows, w.shape)
            gv = sp.RowSparseNDArray(v.grad._read()[rows] * batch_size,
                                     rows, v.shape)
            kv.push(0, gw)
            kv.push(1, gv)
            # pull only touched rows back into the local dense replicas
            # (reference: Parameter.row_sparse_data path)
            for key, param in ((0, w), (1, v)):
                tmp = sp.zeros("row_sparse", param.shape)
                kv.row_sparse_pull(key, out=tmp, row_ids=nd.array(touched))
                param._write(param._read().at[tmp._indices].set(tmp._values))
            b -= 0.1 * b.grad
            for p in (w, v, b):
                p.grad[:] = 0
            total += float(loss.asnumpy())
            count += 1
        losses.append(total / count)
    assert losses[-1] < 0.55 * losses[0], losses

# ---------------------------------------------------------------------------
# Embedding(sparse_grad=True): the gradient is row_sparse end to end
# (reference: indexing_op.cc EmbeddingOpBackward rowsparse kernel;
#  python/mxnet/gluon/nn/basic_layers.py Embedding(sparse_grad))
# ---------------------------------------------------------------------------
def _make_emb(sparse, rows=12, dim=3):
    from mxnet_tpu.gluon import nn
    emb = nn.Embedding(rows, dim, sparse_grad=sparse)
    emb.initialize(mx.init.Constant(0.5))
    return emb


def test_embedding_sparse_grad_rows_and_values():
    emb_s, emb_d = _make_emb(True), _make_emb(False)
    idx = nd.array(np.array([3, 7, 3, 1]), dtype="int32")
    head = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    for emb in (emb_s, emb_d):
        with autograd.record():
            out = (emb(idx) * head).sum()
        out.backward()
    gs, gd = emb_s.weight.grad(), emb_d.weight.grad()
    assert gs.stype == "row_sparse" and gd.stype == "default"
    # touched rows only, sorted unique; duplicate lookups (row 3) summed
    np.testing.assert_array_equal(np.asarray(gs._indices), [1, 3, 7])
    np.testing.assert_allclose(gs.asnumpy(), gd.asnumpy(), rtol=1e-6)


def test_embedding_sparse_grad_add_accumulates_rows():
    emb = _make_emb(True)
    emb.weight.grad_req = "add"
    for sel in ([0, 1], [1, 2]):
        idx = nd.array(np.array(sel), dtype="int32")
        with autograd.record():
            loss = emb(idx).sum()
        loss.backward()
    g = emb.weight.grad()
    assert g.stype == "row_sparse"
    np.testing.assert_array_equal(np.asarray(g._indices), [0, 1, 2])
    dense = g.asnumpy()
    np.testing.assert_allclose(dense[1], 2.0 * np.ones(3), rtol=1e-6)
    np.testing.assert_allclose(dense[0], np.ones(3), rtol=1e-6)
    emb.weight.zero_grad()
    assert emb.weight.grad()._indices.shape[0] == 0


def test_embedding_sparse_grad_autograd_grad():
    emb = _make_emb(True)
    idx = nd.array(np.array([2, 5]), dtype="int32")
    w = emb.weight.data()
    with autograd.record():
        loss = emb(idx).sum()
    g = autograd.grad([loss], [w])[0]
    assert g.stype == "row_sparse"
    np.testing.assert_array_equal(np.asarray(g._indices), [2, 5])


def test_embedding_sparse_grad_lazy_momentum_untouched_rows():
    """lazy_update: momentum/weight of rows ABSENT from a batch must not
    move — including rows with nonzero momentum from an earlier step,
    which a dense sgd_mom_update would keep decaying (reference:
    rowsparse sgd_mom_update kernels)."""
    from mxnet_tpu import gluon
    emb = _make_emb(True)
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.9,
                             "lazy_update": True})

    def step(rows):
        idx = nd.array(np.array(rows), dtype="int32")
        with autograd.record():
            loss = emb(idx).sum()
        loss.backward()
        trainer.step(1)

    # step 1 builds nonzero momentum on rows {4, 9}
    step([4, 9])
    after1 = emb.weight.data().asnumpy().copy()
    changed1 = np.where(np.abs(after1 - 0.5).sum(axis=1) > 0)[0]
    np.testing.assert_array_equal(changed1, [4, 9])
    # step 2 touches only row 1: rows 4/9 must NOT move even though their
    # momentum is nonzero (the dense path would apply momentum decay)
    step([1])
    after2 = emb.weight.data().asnumpy()
    moved = np.where(np.abs(after2 - after1).sum(axis=1) > 0)[0]
    np.testing.assert_array_equal(moved, [1])
    np.testing.assert_allclose(after2[4], after1[4])
    np.testing.assert_allclose(after2[9], after1[9])


def test_embedding_sparse_grad_hybridized_falls_back_dense_values():
    """Under hybridize the whole block is one traced program; the grad
    buffer stays row_sparse but is filled via the dense path — values must
    still match the eager dense reference."""
    emb_h, emb_d = _make_emb(True), _make_emb(False)
    emb_h.hybridize()
    idx = nd.array(np.array([0, 6, 6]), dtype="int32")
    for emb in (emb_h, emb_d):
        with autograd.record():
            out = emb(idx).sum()
        out.backward()
    np.testing.assert_allclose(emb_h.weight.grad().asnumpy(),
                               emb_d.weight.grad().asnumpy(), rtol=1e-6)


def test_embedding_sparse_grad_clips_oob_like_dense():
    """Out-of-range / negative lookups: jnp.take wraps negatives
    python-style and drops the cotangent of OOB-high ones — the sparse
    grad must land on exactly the same rows as the dense path."""
    emb_s, emb_d = _make_emb(True), _make_emb(False)
    idx = nd.array(np.array([-1, 3, 99]), dtype="int32")
    for emb in (emb_s, emb_d):
        with autograd.record():
            loss = emb(idx).sum()
        loss.backward()
    gs = emb_s.weight.grad()
    assert gs.stype == "row_sparse"
    assert int(np.asarray(gs._indices).min()) >= 0
    assert int(np.asarray(gs._indices).max()) < 12
    np.testing.assert_allclose(gs.asnumpy(), emb_d.weight.grad().asnumpy(),
                               rtol=1e-6)
