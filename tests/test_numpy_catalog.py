"""mx.np surface catalog — the np analog of test_op_parity.py.

reference: python/mxnet/numpy/multiarray.py + function_base.py export
~600 public names; this catalog pins the subset this build guarantees
(>=400 names across mx.np / mx.np.linalg / mx.np.random / mx.npx) so a
regression that drops a name fails loudly.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx

np = mx.np

# Functions expected to exist AND be callable on mx.np
NP_FUNCS = """
add subtract multiply divide true_divide mod remainder fmod power pow
float_power maximum minimum fmax fmin hypot negative positive reciprocal
abs absolute fabs sign heaviside copysign ldexp nextafter spacing signbit
exp exp2 expm1 log log2 log10 log1p logaddexp logaddexp2 sqrt cbrt square
sin cos tan arcsin arccos arctan arctan2 asin acos atan atan2
sinh cosh tanh arcsinh arccosh arctanh asinh acosh atanh
sinc i0 angle unwrap degrees radians deg2rad rad2deg
rint fix floor ceil trunc round around clip nan_to_num
dot matmul inner outer tensordot einsum vdot vecdot kron cross trace
matrix_transpose
sum prod mean std var cumsum cumprod max min amax amin ptp median quantile
percentile average nansum nanprod nanmean nanstd nanvar nanmedian
nanquantile nanpercentile nanmax nanmin nancumsum nancumprod nanargmax
nanargmin trapezoid corrcoef cov
reshape ravel transpose permute_dims swapaxes moveaxis rollaxis
expand_dims squeeze broadcast_to concatenate concat stack vstack hstack
dstack column_stack split array_split vsplit hsplit dsplit tile repeat
roll flip fliplr flipud rot90 pad append delete insert resize trim_zeros
broadcast_arrays atleast_1d atleast_2d atleast_3d astype copy
take take_along_axis where select compress choose extract diag diagflat
diagonal tril triu meshgrid ix_
sort partition argpartition argmax argmin argsort argwhere searchsorted
flatnonzero count_nonzero nonzero lexsort sort_complex digitize
floor_divide equal not_equal greater greater_equal less less_equal
logical_and logical_or logical_not logical_xor isnan isinf isfinite
isposinf isneginf isreal iscomplex all any allclose isclose array_equal
array_equiv isin
unique union1d intersect1d setdiff1d setxor1d unique_all unique_counts
unique_inverse unique_values
lcm gcd bincount bitwise_and bitwise_or bitwise_xor bitwise_not
bitwise_invert bitwise_count invert left_shift right_shift
bitwise_left_shift bitwise_right_shift packbits unpackbits
interp diff ediff1d gradient convolve correlate real imag conj conjugate
histogram histogram2d histogramdd histogram_bin_edges
frexp modf divmod unravel_index ravel_multi_index
polyval polyadd polysub polymul polyder polyint polydiv polyfit poly
roots vander
apply_along_axis apply_over_axes piecewise vectorize
array asarray asnumpy zeros ones empty full arange linspace logspace
geomspace eye identity tri indices zeros_like ones_like full_like
empty_like frombuffer fromiter fromfunction fromstring fromfile block
bartlett blackman hamming hanning kaiser
tril_indices triu_indices diag_indices mask_indices tril_indices_from
triu_indices_from diag_indices_from
fill_diagonal place put put_along_axis copyto
result_type can_cast promote_types issubdtype isscalar iterable
broadcast_shapes isdtype iscomplexobj isrealobj
shape ndim size array_repr array_str shares_memory may_share_memory
save savez load loadtxt savetxt
ascontiguousarray asfortranarray
""".split()

NP_CONSTANTS = """pi e euler_gamma inf nan newaxis""".split()

NP_DTYPES = """
float16 float32 float64 half single double bfloat16
int8 int16 int32 int64 intc intp int_ uint8 uint16 uint32 uint64 uint
byte ubyte short ushort longlong ulonglong
complex64 complex128 csingle cdouble bool_ float_ generic number integer
signedinteger unsignedinteger inexact floating complexfloating dtype
finfo iinfo
""".split()

LINALG_FUNCS = """
norm svd cholesky qr pinv solve lstsq eig eigvals eigh eigvalsh
matrix_rank matrix_power multi_dot tensorinv tensorsolve det slogdet inv
""".split()

RANDOM_FUNCS = """
seed uniform normal randn rand randint choice shuffle permutation gamma
beta exponential multinomial lognormal laplace logistic gumbel pareto
power rayleigh weibull chisquare f poisson standard_normal
standard_exponential standard_gamma standard_cauchy multivariate_normal
bernoulli binomial negative_binomial
""".split()

NPX_FUNCS = """
set_np reset_np is_np_array is_np_shape softmax log_softmax
masked_softmax relu sigmoid one_hot pick topk batch_dot embedding gamma
activation fully_connected convolution deconvolution pooling batch_norm
layer_norm group_norm dropout leaky_relu rnn reshape_like arange_like
broadcast_like gather_nd scatter_nd smooth_l1 sequence_mask erf erfinv
seed waitall save load cast interleaved_matmul_selfatt_qk
interleaved_matmul_selfatt_valatt
""".split()


def test_np_function_catalog_resolves():
    missing = [n for n in NP_FUNCS if not callable(getattr(np, n, None))]
    assert not missing, f"mx.np missing/uncallable: {missing}"


def test_np_constants_and_dtypes():
    for n in NP_CONSTANTS:
        assert hasattr(np, n), n
    missing = [n for n in NP_DTYPES if not hasattr(np, n)]
    assert not missing, f"mx.np missing dtypes: {missing}"
    assert np.float32 is onp.float32
    assert np.dtype("int64") == onp.int64


def test_linalg_random_npx_catalogs():
    missing = [n for n in LINALG_FUNCS
               if not callable(getattr(np.linalg, n, None))]
    assert not missing, f"mx.np.linalg missing: {missing}"
    missing = [n for n in RANDOM_FUNCS
               if not callable(getattr(np.random, n, None))]
    assert not missing, f"mx.np.random missing: {missing}"
    missing = [n for n in NPX_FUNCS
               if not callable(getattr(mx.npx, n, None))]
    assert not missing, f"mx.npx missing: {missing}"


def test_total_surface_size():
    total = (len(set(NP_FUNCS)) + len(set(NP_CONSTANTS)) +
             len(set(NP_DTYPES)) + len(set(LINALG_FUNCS)) +
             len(set(RANDOM_FUNCS)) + len(set(NPX_FUNCS)))
    assert total >= 400, total
    # and the live module actually exposes at least that many names
    live = [n for n in dir(np) if not n.startswith("_")]
    assert len(live) >= 380, len(live)


def test_ndarray_method_surface():
    methods = """
    item tolist tobytes astype copy all any argsort argmax argmin cumsum
    cumprod std var dot diagonal trace nonzero searchsorted ptp conj
    conjugate compress repeat take clip round mean sum prod max min sort
    fill flatten ravel reshape transpose squeeze expand_dims swapaxes
    broadcast_to tile as_nd_ndarray attach_grad backward detach asnumpy
    """.split()
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    missing = [m for m in methods if not callable(getattr(x, m, None))]
    assert not missing, f"mx.np.ndarray missing methods: {missing}"
    props = ["T", "shape", "dtype", "size", "ndim", "itemsize", "nbytes",
             "real", "imag", "flat", "context", "grad"]
    missing = [p for p in props if not hasattr(type(x), p)]
    assert not missing, f"mx.np.ndarray missing properties: {missing}"
