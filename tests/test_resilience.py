"""mxnet_tpu.resilience — fault injection, retry, watchdog, auto-resume.

Every scenario runs on one chip: the fault harness makes preemptions,
transport faults, and hangs deterministic, so the recovery paths
(in-place retry, StallError-instead-of-hang, restore-and-replay) are
ordinary unit tests. The kill-and-resume parity tests reuse the 6-step
trajectory pattern from test_fused_step.py.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, resilience as rz, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faults, retry, watchdog
from mxnet_tpu.resilience.errors import (FatalTrainingError, InjectedFault,
                                         PreemptionError, RetryExhausted,
                                         StallError, TransportError,
                                         classify)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter(name):
    return telemetry.snapshot()["counters"].get(name, 0)


# ---------------------------------------------------------------------------
# faults: plan grammar + injection
# ---------------------------------------------------------------------------
def test_fault_plan_parse():
    plan = faults.FaultPlan.parse(
        "kvstore.push:error:1; collective.all_reduce:latency:2:0.01;"
        "run.step:preempt:3+;train.step:hang:*:0.1")
    kinds = [(s.site, s.kind) for s in plan.specs]
    assert kinds == [("kvstore.push", "error"),
                     ("collective.all_reduce", "latency"),
                     ("run.step", "preempt"), ("train.step", "hang")]
    assert plan.specs[1].arg == pytest.approx(0.01)
    assert plan.specs[2].from_nth_on and plan.specs[2].nth == 3
    assert plan.specs[3].every
    # nth matching
    assert not plan.specs[0].matches(2)
    assert plan.specs[2].matches(3) and plan.specs[2].matches(7)
    assert plan.specs[3].matches(1)


def test_fault_plan_parse_rejects_garbage():
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("justonefield")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("a:explode:1")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("a:error:0")


def test_inject_scoping_and_counts():
    before = faults.active_plan()
    with faults.inject("s:error:2") as plan:
        faults.check("s")              # call 1: clean
        with pytest.raises(InjectedFault):
            faults.check("s")          # call 2: fires
        faults.check("s")              # call 3: clean again
        assert plan.count("s") == 3
    assert faults.active_plan() is before


def test_env_fault_plan(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FAULT_PLAN", "e.site:preempt:1")
    try:
        faults.activate()
        with pytest.raises(PreemptionError):
            faults.check("e.site")
    finally:
        faults.deactivate()


def test_latency_injection_sleeps():
    with faults.inject("l.site:latency:1:0.05"):
        t0 = time.monotonic()
        faults.check("l.site")
        assert time.monotonic() - t0 >= 0.04


# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------
def test_classify_taxonomy():
    assert classify(TransportError("x")) == "retriable"
    assert classify(PreemptionError("x")) == "retriable"
    assert classify(StallError("x")) == "retriable"
    assert classify(FatalTrainingError("x")) == "fatal"
    assert classify(ValueError("anything")) == "fatal"
    assert classify(ConnectionResetError("peer")) == "retriable"
    # message-based: grpc-ish runtime errors
    assert classify(RuntimeError("UNAVAILABLE: connection reset")) \
        == "retriable"
    assert classify(RuntimeError("DEADLINE_EXCEEDED while waiting")) \
        == "retriable"
    # fatal markers beat transient markers
    assert classify(RuntimeError(
        "INVALID_ARGUMENT: shape mismatch on connection")) == "fatal"
    assert classify(RuntimeError("no idea what happened")) == "fatal"


# ---------------------------------------------------------------------------
# retry engine
# ---------------------------------------------------------------------------
def test_retry_succeeds_after_injected_fault():
    base = _counter("resilience.retries")
    calls = {"n": 0}

    def flaky():
        faults.check("r.site")
        calls["n"] += 1
        return "ok"

    with faults.inject("r.site:error:1"):
        out = retry.call_with_retry(
            flaky, site="r.site",
            policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0.001))
    assert out == "ok" and calls["n"] == 1
    assert _counter("resilience.retries") == base + 1
    assert _counter("resilience.retries.r.site") >= 1


def test_retry_fatal_propagates_first_attempt():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ValueError("dtype mismatch")

    with pytest.raises(ValueError):
        retry.call_with_retry(fatal, site="f.site",
                              policy=retry.RetryPolicy(max_attempts=5,
                                                       base_delay_s=0.001))
    assert calls["n"] == 1


def test_retry_exhausted_carries_context():
    def always_down():
        raise TransportError("endpoint down")

    with pytest.raises(RetryExhausted) as ei:
        retry.call_with_retry(
            always_down, site="kvstore.push", context="key=7 shard=(4, 4)",
            policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0.001))
    err = ei.value
    assert err.attempts == 3 and err.site == "kvstore.push"
    assert isinstance(err.last_error, TransportError)
    assert "key=7" in str(err) and "3 attempt" in str(err)
    # RetryExhausted is itself retriable at a coarser granularity
    assert classify(err) == "retriable"


def test_retry_on_filter():
    """A runner narrows in-place retry to TransportError: preemptions must
    reach its restore path un-retried."""
    calls = {"n": 0}

    def preempted():
        calls["n"] += 1
        raise PreemptionError("going away")

    with pytest.raises(PreemptionError):
        retry.call_with_retry(
            preempted, site="p",
            retry_on=lambda e: isinstance(e, TransportError),
            policy=retry.RetryPolicy(max_attempts=5, base_delay_s=0.001))
    assert calls["n"] == 1


def test_retriable_decorator_passes_kwargs_through():
    """site/policy bind at decoration; the wrapped function's own kwargs —
    even ones named like call_with_retry parameters — arrive untouched."""
    seen = {}

    @retry.retriable("deco.site",
                     policy=retry.RetryPolicy(max_attempts=2,
                                              base_delay_s=0.001))
    def fn(x, context=None, policy="user-policy"):
        seen.update(x=x, context=context, policy=policy)
        return x + 1

    assert fn(1, context="user-context") == 2
    assert seen == {"x": 1, "context": "user-context",
                    "policy": "user-policy"}


def test_retry_env_knob(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_RETRIES", "7")
    assert retry.RetryPolicy().max_attempts == 7
    monkeypatch.setenv("MXNET_TPU_RETRIES", "1")
    calls = {"n": 0}

    def down():
        calls["n"] += 1
        raise TransportError("down")

    with pytest.raises(RetryExhausted):
        retry.call_with_retry(down, site="k")
    assert calls["n"] == 1  # max_attempts=1 == no retry


def test_backoff_is_exponential_with_ceiling():
    pol = retry.RetryPolicy(max_attempts=10, base_delay_s=0.1,
                            max_delay_s=0.5, jitter=0.0)
    assert pol.delay(1) == pytest.approx(0.1)
    assert pol.delay(2) == pytest.approx(0.2)
    assert pol.delay(3) == pytest.approx(0.4)
    assert pol.delay(4) == pytest.approx(0.5)  # ceiling
    jittered = retry.RetryPolicy(base_delay_s=0.1, jitter=0.25)
    assert 0.074 <= jittered.delay(1) <= 0.126


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_turns_hang_into_stall_error():
    base = _counter("resilience.stalls")
    telemetry.span("warmup", "test").__enter__()  # ensure some span exists
    t0 = time.monotonic()
    with pytest.raises(StallError) as ei:
        with faults.inject("w.site:hang:1:30"):
            with watchdog.guard("w.site", deadline_s=0.25):
                faults.check("w.site")  # cooperative hang, 30s
    took = time.monotonic() - t0
    assert took < 5.0, "watchdog did not interrupt the hang (took %.1fs)" % took
    err = ei.value
    assert err.site == "w.site" and err.deadline_s == pytest.approx(0.25)
    assert err.span_dump, "StallError must carry the telemetry span dump"
    assert "recent spans" in err.format_spans()
    assert _counter("resilience.stalls") == base + 1
    assert _counter("resilience.stalls.w.site") >= 1


def test_watchdog_quiet_when_fast():
    base = _counter("resilience.stalls")
    with watchdog.guard("q.site", deadline_s=5.0):
        x = sum(range(1000))
    assert x == 499500
    assert _counter("resilience.stalls") == base


def test_watchdog_heartbeat_extends_deadline():
    base = _counter("resilience.stalls")
    with watchdog.guard("h.site", deadline_s=0.3):
        for _ in range(5):
            time.sleep(0.15)
            watchdog.heartbeat()  # 0.75s total but never 0.3s silent
    assert _counter("resilience.stalls") == base


def test_watchdog_no_deadline_is_transparent():
    with watchdog.guard("n.site", deadline_s=None):
        pass


# ---------------------------------------------------------------------------
# kvstore wiring
# ---------------------------------------------------------------------------
def test_kvstore_dist_push_retries_injected_fault():
    kv = mx.kv.create("dist_sync")
    shape = (4, 3)
    kv.init("w", nd.zeros(shape))
    base = _counter("resilience.retries")
    with faults.inject("kvstore.push:error:1"):
        kv.push("w", nd.ones(shape))
    out = nd.zeros(shape)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(shape))
    assert _counter("resilience.retries") > base


def test_kvstore_pull_retries_injected_fault():
    kv = mx.kv.create("local")
    kv.init("p", nd.full((2, 2), 3.0))
    out = nd.zeros((2, 2))
    with faults.inject("kvstore.pull:error:1"):
        kv.pull("p", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0 * np.ones((2, 2)))


def test_kvstore_dist_exhaustion_reports_key_and_attempts(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_RETRIES", "2")
    monkeypatch.setenv("MXNET_TPU_RETRY_BASE_S", "0.001")
    kv = mx.kv.create("dist_sync")
    kv.init("conv0_weight", nd.zeros((4,)))
    with faults.inject("kvstore.push:error:*"):
        with pytest.raises(RetryExhausted) as ei:
            kv.push("conv0_weight", nd.ones((4,)))
    msg = str(ei.value)
    assert "key=conv0_weight" in msg and "shard=(4,)" in msg
    assert "2 attempt" in msg
    assert ei.value.site == "kvstore.push"


def test_kvstore_dist_wraps_foreign_errors_with_context():
    kv = mx.kv.create("dist_sync")
    kv.init("3", nd.zeros((2,)))
    kv._updater = lambda *a: (_ for _ in ()).throw(
        RuntimeError("UNAVAILABLE: endpoint lost"))
    with pytest.raises(TransportError) as ei:
        kv.push("3", nd.ones((2,)))
    assert "key=3" in str(ei.value) and "UNAVAILABLE" in str(ei.value)


def test_collective_barrier_retries_injected_fault():
    from mxnet_tpu.parallel import collectives
    base = _counter("resilience.retries")
    with faults.inject("collective.barrier:error:1"):
        collectives.barrier()
    assert _counter("resilience.retries") > base


# ---------------------------------------------------------------------------
# snapshot checkpointer
# ---------------------------------------------------------------------------
def test_snapshot_checkpointer_roundtrip_retention_atomicity(tmp_path):
    ck = rz.SnapshotCheckpointer(str(tmp_path / "ck"), keep=2)
    for step in range(5):
        ck.save(step, {"w": np.full((3,), step), "step": step})
    assert ck.steps() == [3, 4], "keep=2 must prune older steps"
    assert ck.latest_step() == 4
    step, tree = ck.restore()
    assert step == 4 and tree["step"] == 4
    np.testing.assert_array_equal(tree["w"], np.full((3,), 4))
    # torn write simulation: a stray .tmp and a corrupt LATEST marker must
    # not lose the committed checkpoints
    (tmp_path / "ck" / "step_9.ckpt.tmp").write_bytes(b"torn")
    (tmp_path / "ck" / "LATEST").write_text("not a number")
    assert ck.latest_step() == 4
    step, tree = ck.restore()
    assert step == 4


def test_sharded_checkpoint_keep_and_latest_marker(tmp_path):
    """parallel.checkpoint satellite: keep=N retention + atomic LATEST."""
    from mxnet_tpu.parallel import checkpoint as ckpt
    path = str(tmp_path / "ck")
    for step in (1, 2, 3, 4):
        ckpt.save_sharded(path, {"w": np.ones((2,)) * step}, step=step,
                          keep=2)
    assert ckpt.latest_step(path) == 4
    committed = [d for d in os.listdir(path) if d.isdigit()]
    assert sorted(int(d) for d in committed) == [3, 4], \
        "keep=2 must retain exactly the newest two steps"
    assert (tmp_path / "ck" / "LATEST").read_text().strip() == "4"
    # corrupt marker: scan fallback still finds the newest step
    (tmp_path / "ck" / "LATEST").write_text("garbage")
    assert ckpt.latest_step(path) == 4
    restored = ckpt.restore_sharded(path)
    np.testing.assert_allclose(np.asarray(restored["w"]), 4 * np.ones((2,)))


# ---------------------------------------------------------------------------
# resilient runner: the acceptance scenario
# ---------------------------------------------------------------------------
def _build_mlp():
    mx.random.seed(42)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    return net, tr


def _six_batches():
    rng = np.random.RandomState(0)
    X = rng.rand(6, 32, 8).astype(np.float32)
    Y = rng.randint(0, 3, (6, 32)).astype(np.float32)
    return lambda i: (nd.array(X[i]), nd.array(Y[i]))


def test_kill_and_resume_matches_fault_free_run(tmp_path, monkeypatch):
    """ISSUE acceptance: MXNET_TPU_FAULT_PLAN injects a transport fault AND
    a mid-run kill; the 6-step resilient run must reproduce the fault-free
    trajectory and final params within fp32 tolerance, with nonzero
    resilience.retries and resilience.restores."""
    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    retries0 = _counter("resilience.retries")
    restores0 = _counter("resilience.restores")
    monkeypatch.setenv("MXNET_TPU_FAULT_PLAN",
                       "run.step:error:2;run.step:preempt:5")
    try:
        faults.activate()
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2,
            max_restarts=3,
            retry_policy=retry.RetryPolicy(max_attempts=3,
                                           base_delay_s=0.001))
        report = runner.run(6)
    finally:
        faults.deactivate()

    assert report.restarts >= 1 and report.retries >= 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-5, atol=1e-6)
    for (ka, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                 sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=ka)
    assert _counter("resilience.retries") > retries0
    assert _counter("resilience.restores") > restores0


def test_kill_and_resume_with_dropout_rng_state(tmp_path):
    """RNG key table is checkpointed: even a net that CONSUMES randomness
    every step (dropout) replays the uninterrupted trajectory."""
    def build():
        mx.random.seed(9)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.4),
                    nn.Dense(3))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        return net, tr

    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net_a, tr_a = build()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    net_b, tr_b = build()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    with faults.inject("run.step:preempt:3"):
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=2)
        report = runner.run(6)
    assert report.restarts == 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-5, atol=1e-6)


def test_runner_fault_before_first_checkpoint_surfaces_cause(tmp_path):
    """A fault with an EMPTY checkpoint dir must surface the fault itself,
    not a FileNotFoundError about the missing snapshot."""
    def step_fn(i):
        faults.check("bare.step")
        return 0.0

    state = {"w": 1.0}
    with faults.inject("bare.step:preempt:1"):
        runner = rz.ResilientRunner(
            step_fn, state_get=lambda: dict(state),
            state_set=lambda t: state.update(t),
            ckpt_dir=str(tmp_path / "ck"), ckpt_every=5, max_restarts=3)
        # start_step=2 is off the ckpt cadence: nothing saved before the hit
        with pytest.raises(PreemptionError):
            runner.run(6, start_step=2)


def test_runner_restart_budget_exhausts():
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    batch_fn = _six_batches()
    with faults.inject("run.step:preempt:1+"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=None, max_restarts=2)
        # no checkpointer: first preemption must surface immediately
        with pytest.raises(PreemptionError):
            runner.run(6)


def test_runner_recovers_from_stall(tmp_path):
    """A hang inside the step (dead collective) → watchdog StallError →
    restore-and-replay, run completes."""
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    batch_fn = _six_batches()
    stalls0 = _counter("resilience.stalls")
    with faults.inject("train.step:hang:3:30"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=2, step_deadline_s=0.5)
        report = runner.run(4)
    assert report.restarts == 1
    assert _counter("resilience.stalls") > stalls0
    assert all(l is not None for l in report.losses)


def test_runner_step_deadline_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_STEP_DEADLINE_S", "0.4")
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    runner = rz.ResilientRunner.for_fused_step(
        fused, _six_batches(), ckpt_dir=str(tmp_path / "ck"))
    assert runner.step_deadline_s == pytest.approx(0.4)


def test_runner_auto_resume_after_process_kill(tmp_path):
    """resume=True restores the newest checkpoint — the relaunch-after-kill
    path (same ckpt_dir, fresh process state)."""
    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    # "first boot": dies by preemption with the restart budget at 0
    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    runner = rz.ResilientRunner.for_fused_step(
        fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
        max_restarts=0)
    with faults.inject("run.step:preempt:4"):
        with pytest.raises(PreemptionError):
            runner.run(6)

    # "relaunch": perturb live state to prove restore really happens
    for _, p in net_b.collect_params().items():
        p.set_data(p.data() * 0.0)
    runner2 = rz.ResilientRunner.for_fused_step(
        fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1)
    report = runner2.run(6, resume=True)
    assert report.restarts == 0  # a requested resume is not a failure
    for (ka, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                 sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=ka)
    # the tail of the trajectory (post-resume steps) matches the clean run
    resumed_tail = [l for l in report.losses if l is not None]
    np.testing.assert_allclose(clean[-len(resumed_tail):], resumed_tail,
                               rtol=1e-5, atol=1e-6)


def test_runner_mesh_shrink_degrades_gracefully(tmp_path):
    """Device set shrinks across a restore → on_shrink rebuilds the step
    for the smaller mesh and the run continues (degraded, not dead)."""
    class FakeDevices:
        def __init__(self, size):
            self.size = size

    class FakeMesh:
        def __init__(self, size):
            self.devices = FakeDevices(size)

    sizes = {"n": 8}
    meshes = []

    def mesh_factory():
        m = FakeMesh(sizes["n"])
        meshes.append(m)
        return m

    state = {"w": 0.0, "rebuilt_for": None}

    def step_fn(i):
        faults.check("fake.step")
        state["w"] += 1.0
        return state["w"]

    def on_shrink(mesh):
        state["rebuilt_for"] = mesh.devices.size
        return step_fn  # rebuilt step for the smaller mesh

    shrinks0 = _counter("resilience.mesh_shrinks")
    with faults.inject("fake.step:preempt:3"):
        runner = rz.ResilientRunner(
            step_fn, state_get=lambda: dict(state),
            state_set=lambda t: state.update(t),
            ckpt_dir=str(tmp_path / "ck"), ckpt_every=1, max_restarts=2,
            mesh_factory=mesh_factory, on_shrink=on_shrink)
        sizes["n"] = 4  # preemption takes half the fleet
        report = runner.run(5)
    assert report.restarts == 1 and report.mesh_shrinks == 1
    assert state["rebuilt_for"] == 4
    assert _counter("resilience.mesh_shrinks") == shrinks0 + 1


def test_sharded_train_step_resilient_run(tmp_path):
    """Functional path: ShardedTrainStep under the runner reproduces the
    uninterrupted trajectory through a preemption."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import ShardedTrainStep, create_mesh

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    rng = np.random.RandomState(3)
    X = rng.rand(6, 16, 4).astype(np.float32)
    Y = rng.rand(6, 16, 2).astype(np.float32)

    def batch_fn(i):
        return {"x": jnp.asarray(X[i]), "y": jnp.asarray(Y[i])}

    def make():
        mesh = create_mesh(data=2)
        params = {"w": jnp.zeros((4, 2))}
        step = ShardedTrainStep(loss_fn, params, mesh, optimizer="sgd",
                                lr=0.1, momentum=0.9, donate=False)
        return step, step.init()

    step_a, (pa, oa) = make()
    clean = []
    for i in range(6):
        pa, oa, l = step_a(pa, oa, batch_fn(i), i)
        clean.append(float(l))

    step_b, (pb, ob) = make()
    with faults.inject("run.step:preempt:4"):
        runner = rz.ResilientRunner.for_sharded_step(
            step_b, pb, ob, batch_fn, ckpt_dir=str(tmp_path / "ck"),
            ckpt_every=2, max_restarts=2)
        report = runner.run(6)
    assert report.restarts == 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(pa["w"]),
                               np.asarray(runner.holder["params"]["w"]),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# telemetry aggregation (satellite)
# ---------------------------------------------------------------------------
def test_merge_snapshots_fleet_semantics():
    a = {"counters": {"kvstore.push_calls": 3, "resilience.retries": 1},
         "gauges": {"memory.dev0.bytes_in_use": {"value": 10, "max": 40}},
         "histograms": {"step_ms": {"count": 2, "sum": 10.0, "min": 4.0,
                                    "max": 6.0, "avg": 5.0,
                                    "buckets": {"le_10": 2}}}}
    b = {"counters": {"kvstore.push_calls": 5, "cachedop.compile": 2},
         "gauges": {"memory.dev0.bytes_in_use": {"value": 30, "max": 35}},
         "histograms": {"step_ms": {"count": 1, "sum": 8.0, "min": 8.0,
                                    "max": 8.0, "avg": 8.0,
                                    "buckets": {"le_10": 1}}}}
    m = telemetry.merge_snapshots([a, b])
    assert m["workers"] == 2
    assert m["counters"]["kvstore.push_calls"] == 8      # extensive: sum
    assert m["counters"]["cachedop.compile"] == 2        # union of keys
    g = m["gauges"]["memory.dev0.bytes_in_use"]
    assert g["value"] == 30 and g["max"] == 40           # fleet watermark
    h = m["histograms"]["step_ms"]
    assert h["count"] == 3 and h["sum"] == pytest.approx(18.0)
    assert h["min"] == 4.0 and h["max"] == 8.0
    assert h["avg"] == pytest.approx(6.0)
    assert h["buckets"]["le_10"] == 3


def test_aggregate_snapshot_single_process():
    telemetry.inc("agg.test.counter", 4)
    merged = telemetry.aggregate_snapshot()
    assert merged["workers"] == 1
    assert merged["counters"]["agg.test.counter"] >= 4


# ---------------------------------------------------------------------------
# tooling (satellite)
# ---------------------------------------------------------------------------
def test_parse_log_resilience_mode(tmp_path):
    telemetry.reset()  # counters are process-global; start this dump clean
    telemetry.inc("resilience.retries")
    telemetry.inc("resilience.retries.kvstore.push")
    telemetry.inc("resilience.restores", 2)
    dump = str(tmp_path / "telemetry.json")
    telemetry.dump(dump)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--resilience"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| retries | total |" in r.stdout
    assert "| retries | kvstore.push | 1 |" in r.stdout
    assert "| restores | total |" in r.stdout
    # csv shape too
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--resilience", "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "event,site,count" in r.stdout


def test_parse_log_resilience_v2_event_rows(tmp_path):
    """Satellite: elastic/commit/preempt events surface as table rows —
    shrink, grow-back, commit elections (+ elected-step gauge), proactive
    checkpoints, preemption notices."""
    telemetry.reset()
    telemetry.inc("resilience.mesh_shrinks")
    telemetry.inc("resilience.mesh_grows")
    telemetry.inc("resilience.commit.elections", 3)
    telemetry.inc("resilience.commit.elections.save", 2)
    telemetry.inc("resilience.commit.elections.restore")
    telemetry.inc("resilience.commit.rank_ahead")
    telemetry.inc("resilience.proactive_checkpoints")
    telemetry.inc("resilience.preempt.notices")
    telemetry.inc("resilience.preempt.notices.poll")
    telemetry.set_gauge("resilience.commit.elected_step", 42)
    dump = str(tmp_path / "telemetry.json")
    telemetry.dump(dump)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--resilience"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    for row in ("| mesh_shrinks | total | 1 |",
                "| mesh_grows | total | 1 |",
                "| commit.elections | total | 3 |",
                "| commit.elections | save | 2 |",
                "| commit.elections | restore | 1 |",
                "| commit.rank_ahead | total | 1 |",
                "| commit.elected_step | latest | 42 |",
                "| proactive_checkpoints | total | 1 |",
                "| preempt.notices | total | 1 |",
                "| preempt.notices | poll | 1 |"):
        assert row in r.stdout, "missing row %r in:\n%s" % (row, r.stdout)


# ---------------------------------------------------------------------------
# coordinated commit (resilience v2)
# ---------------------------------------------------------------------------
def test_commit_election_min_and_rank_ahead_counter():
    from mxnet_tpu.resilience import commit
    ahead0 = _counter("resilience.commit.rank_ahead")
    coord = commit.CommitCoordinator(gather=lambda step, rnd: [step, step - 1])
    assert coord.elect(5) == 4
    assert _counter("resilience.commit.rank_ahead") == ahead0 + 1
    assert _counter("resilience.commit.elections") >= 1
    snap = telemetry.snapshot()["gauges"]
    assert snap["resilience.commit.elected_step"]["value"] == 4


def test_commit_election_single_process_identity_and_none():
    from mxnet_tpu.resilience import commit
    assert commit.elect_step(7) == 7
    assert commit.CommitCoordinator().elect(None) is None
    # a rank with nothing durable does not drag the fleet to None
    coord = commit.CommitCoordinator(gather=lambda step, rnd: [step, None, 3])
    assert coord.elect(5) == 3


def test_checkpointer_two_phase_prepare_commit(tmp_path):
    """prepare makes the payload durable without moving a committed marker;
    commit refuses a step whose payload is missing."""
    ck = rz.SnapshotCheckpointer(str(tmp_path / "ck"), keep=None)
    ck.save(2, {"w": 2})               # committed baseline
    ck.prepare(3, {"w": 3})            # durable, NOT committed
    assert ck.latest_step() == 2, \
        "an uncommitted payload must not win over the committed marker"
    assert 3 in ck.prepared_steps()
    assert ck.commit(9) is False       # no payload -> marker unchanged
    assert ck.latest_step() == 2
    assert ck.commit(3) is True
    assert ck.latest_step() == 3


def test_mid_commit_crash_resumes_at_committed_step(tmp_path):
    """checkpoint.save fault site (satellite): a crash AFTER the payload is
    durable but BEFORE the marker moves (the rank-ahead shape) resumes at
    the last COMMITTED step; the stray newer payload is invisible."""
    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    # saves land at steps 0, 2, 4: the 3rd save (step 4) dies mid-commit
    with faults.inject("checkpoint.save:preempt:3"):
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2)
        with pytest.raises(PreemptionError):
            runner.run(6)
    ck = rz.SnapshotCheckpointer(str(tmp_path / "ck"))
    assert 4 in ck.prepared_steps(), "step-4 payload must be durable"
    assert ck.latest_step() == 2, "marker must still name the committed step"

    # relaunch: resumes from the committed step and reproduces the clean run
    runner2 = rz.ResilientRunner.for_fused_step(
        fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2)
    report = runner2.run(6, resume=True)
    tail = [l for l in report.losses if l is not None]
    np.testing.assert_allclose(clean[-len(tail):], tail,
                               rtol=1e-5, atol=1e-6)
    for (ka, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                 sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=ka)


def test_sharded_checkpoint_coordinated_mid_commit_crash(tmp_path):
    """Orbax path: `coordinated=True` + the checkpoint.save fault site —
    a crash between payload-durable and marker-flip leaves the committed
    view at the previous step, and `restore_sharded(coordinated=True)`
    restores it (the stray newer payload stays invisible)."""
    from mxnet_tpu.parallel import checkpoint as ckpt
    path = str(tmp_path / "ck")
    ckpt.save_sharded(path, {"w": np.ones((2,))}, step=1, coordinated=True)
    assert ckpt.latest_committed_step(path) == 1
    with faults.inject("checkpoint.save:preempt:1"):
        with pytest.raises(PreemptionError):
            ckpt.save_sharded(path, {"w": np.ones((2,)) * 2}, step=2,
                              coordinated=True)
    # the step-2 payload is durable (scan sees it) but NOT committed
    assert ckpt.latest_step(path) == 2
    assert ckpt.latest_committed_step(path) == 1
    restored = ckpt.restore_sharded(path, coordinated=True)
    np.testing.assert_allclose(np.asarray(restored["w"]), np.ones((2,)))


def test_commit_restore_election_agrees_across_simulated_ranks(tmp_path):
    """Two simulated ranks, rank1 a prepared step ahead (crashed
    mid-commit): the restore election lands every rank on the elected min
    step."""
    from mxnet_tpu.resilience import commit
    cks = [rz.SnapshotCheckpointer(str(tmp_path / ("rank%d" % r)))
           for r in range(2)]
    for step in (1, 2, 3, 4):
        for ck in cks:
            ck.save(step, {"w": np.full((2,), float(step)), "step": step})
    cks[1].prepare(5, {"w": np.full((2,), 5.0), "step": 5})  # rank1 ahead

    # the fleet exchange: every rank reports its newest DURABLE step
    durable = [max(ck.prepared_steps()) for ck in cks]
    assert durable == [4, 5]
    fleet = {}

    def gather_for(rank):
        def gather(step, rnd):
            fleet[rank] = step
            return [durable[0], durable[1]]
        return gather

    restored = []
    for rank, ck in enumerate(cks):
        coord = commit.CommitCoordinator(gather=gather_for(rank))
        elected = coord.elect(durable[rank], kind="restore")
        step, tree = ck.restore(elected)
        restored.append((step, tree["step"]))
    assert restored == [(4, 4), (4, 4)], restored


def test_runner_coordinated_save_commits_elected_step(tmp_path):
    """_save under a CommitCoordinator: the marker names the fleet-elected
    min, not this rank's (newer) prepared step."""
    from mxnet_tpu.resilience import commit
    state = {"w": 0.0}
    runner = rz.ResilientRunner(
        lambda i: 0.0, state_get=lambda: dict(state),
        state_set=lambda t: state.update(t),
        ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
        commit=commit.CommitCoordinator(
            gather=lambda step, rnd: [step, max(0, step - 1)]))
    report = runner.run(3)
    ck = runner.ckpt
    # last save prepared step 2; the fleet's laggard was at 1 -> marker 1
    assert 2 in ck.prepared_steps()
    assert ck.latest_step() == 1
    assert report.checkpoints == 3


# ---------------------------------------------------------------------------
# proactive preemption (resilience v2)
# ---------------------------------------------------------------------------
def test_preempt_listener_poll_notice_via_fault_plan():
    from mxnet_tpu.resilience.preempt import PreemptionListener
    notices0 = _counter("resilience.preempt.notices")
    with faults.inject("preempt.poll:preempt:1"):
        listener = PreemptionListener(poll_interval_s=0.01)
        listener.start()
        try:
            deadline = time.monotonic() + 5.0
            while listener.pending() is None and time.monotonic() < deadline:
                time.sleep(0.01)
            notice = listener.pending()
        finally:
            listener.stop()
    assert notice is not None, "poller never observed the planned event"
    assert notice.source == "poll"
    assert "preemption" in notice.reason
    assert _counter("resilience.preempt.notices") == notices0 + 1
    assert _counter("resilience.preempt.notices.poll") >= 1


def test_preempt_listener_sigterm_notice():
    import signal
    from mxnet_tpu.resilience.preempt import PreemptionListener
    seen = []
    listener = PreemptionListener(poll_fn=False,
                                  on_notice=lambda n: seen.append(n))
    listener.start()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while listener.pending() is None and time.monotonic() < deadline:
            time.sleep(0.01)
        notice = listener.pending()
    finally:
        listener.stop()
    assert notice is not None and notice.source == "sigterm"
    assert seen and seen[0] is notice
    # handler restored: a second listener can install again
    assert signal.getsignal(signal.SIGTERM) not in (listener._handle_sigterm,)


def test_runner_proactive_checkpoint_zero_replay(tmp_path):
    """ISSUE acceptance: a simulated preemption notice produces a proactive
    checkpoint — resume replays ZERO steps (vs up to ckpt_every-1 for a
    periodic-snapshot-only recovery) and the trajectory still matches the
    fault-free run."""
    from mxnet_tpu.resilience.preempt import PreemptionListener
    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    proactive0 = _counter("resilience.proactive_checkpoints")
    with faults.inject("preempt.poll:preempt:1"):
        listener = PreemptionListener(poll_interval_s=0.01).start()
        try:
            # deterministic: the notice is pending BEFORE the run begins
            deadline = time.monotonic() + 5.0
            while listener.pending() is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert listener.pending() is not None
            # ckpt_every=5: without the proactive save, recovery would
            # rewind to step 0 and replay
            runner = rz.ResilientRunner.for_fused_step(
                fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"),
                ckpt_every=5, max_restarts=2, preempt_listener=listener)
            report = runner.run(6)
        finally:
            listener.stop()
    assert report.proactive_ckpts == 1
    assert report.replayed_steps == 0, \
        "proactive checkpoint must make the preemption replay-free"
    assert report.restarts == 1
    assert _counter("resilience.proactive_checkpoints") == proactive0 + 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-5, atol=1e-6)


def test_runner_reactive_preemption_replays_for_contrast(tmp_path):
    """The ledger distinguishes reactive from proactive: a hard preemption
    off the checkpoint cadence replays completed steps."""
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    with faults.inject("run.step:preempt:5"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, _six_batches(), ckpt_dir=str(tmp_path / "ck"),
            ckpt_every=3, max_restarts=2)
        report = runner.run(6)
    # preempt at step 4; last snapshot at step 3 -> step 3 replays? no:
    # steps 0..3 completed, preempt at step 4, restore to 3, steps 3,4
    # re-run — step 3 was completed before, so exactly 1 replay
    assert report.replayed_steps == 1
    assert report.recovery_time_s > 0.0


# ---------------------------------------------------------------------------
# device-aware stall post-mortems (resilience v2)
# ---------------------------------------------------------------------------
def test_stall_post_mortem_includes_device_state():
    """ISSUE acceptance: StallError carries per-device PjRt state (live
    buffer counts/bytes) and the last-compiled executables next to the
    host span dump — one structured report."""
    import jax.numpy as jnp
    keep_alive = jnp.ones((16, 16))  # a live buffer the report must see
    telemetry.note_compile("test_executable")
    with pytest.raises(StallError) as ei:
        with faults.inject("pm.site:hang:1:30"):
            with watchdog.guard("pm.site", deadline_s=0.25):
                faults.check("pm.site")
    err = ei.value
    assert err.device_dump, "StallError must carry the device dump"
    entry = err.device_dump[0]
    assert "device" in entry and "platform" in entry
    assert any("live_buffers" in e for e in err.device_dump), \
        "at least one device must report live buffers: %r" % err.device_dump
    total_bufs = sum(e.get("live_buffers", 0) for e in err.device_dump)
    assert total_bufs >= 1
    assert any(name == "test_executable" for name, _ in err.compile_dump)
    report = err.format_report()
    assert "recent spans" in report
    assert "device state:" in report
    assert "live_buffers=" in report
    assert "test_executable" in report
    del keep_alive


def test_telemetry_device_report_shape():
    report = telemetry.device_report()
    assert isinstance(report, list) and report
    for entry in report:
        assert "device" in entry and "platform" in entry


def test_telemetry_recent_compiles_ring():
    telemetry.reset()
    for i in range(40):
        telemetry.note_compile("exe_%d" % i)
    events = telemetry.recent_compiles()
    assert len(events) <= 32
    assert events[-1][0] == "exe_39"
    assert telemetry.recent_compiles(limit=3)[0][0] == "exe_37"


# ---------------------------------------------------------------------------
# elastic re-sharding (resilience v2 tentpole)
# ---------------------------------------------------------------------------
def _exact_sharded_fixture(steps=6):
    """Binary data + dyadic hyperparameters: every sum in the train step is
    exactly representable in fp32, so ANY reduction order — any mesh —
    produces bit-identical results. That turns cross-mesh parity into an
    equality assertion instead of a tolerance."""
    import jax.numpy as jnp

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    rng = np.random.RandomState(3)
    X = rng.randint(0, 2, (steps, 16, 4)).astype(np.float32)
    Y = rng.randint(0, 2, (steps, 16, 2)).astype(np.float32)

    def batch_fn(i):
        return {"x": jnp.asarray(X[i]), "y": jnp.asarray(Y[i])}

    def make(n):
        from mxnet_tpu.parallel import ShardedTrainStep, create_mesh
        import jax.numpy as jnp
        mesh = create_mesh(data=n)
        params = {"w": jnp.zeros((4, 2))}
        step = ShardedTrainStep(loss_fn, params, mesh, optimizer="sgd",
                                lr=0.5, momentum=0.5, donate=False)
        return step, step.init()

    return batch_fn, make


def test_runner_elastic_reshard_on_mesh_shrink(tmp_path):
    """ISSUE acceptance: mesh-shrink fault -> the runner re-shards the
    restored snapshot onto the smaller mesh automatically (NO on_shrink
    callback) and the final params are bit-identical to an uninterrupted
    run on that mesh."""
    from mxnet_tpu.parallel import create_mesh
    batch_fn, make = _exact_sharded_fixture()

    # the acceptance reference: uninterrupted run entirely on the small mesh
    step_a, (pa, oa) = make(1)
    clean = []
    for i in range(6):
        pa, oa, l = step_a(pa, oa, batch_fn(i), i)
        clean.append(float(l))

    sizes = {"n": 2}

    def mesh_factory():
        return create_mesh(data=sizes["n"])

    shrinks0 = _counter("resilience.mesh_shrinks")
    step_b, (pb, ob) = make(2)
    with faults.inject("run.step:preempt:4"):
        runner = rz.ResilientRunner.for_sharded_step(
            step_b, pb, ob, batch_fn, ckpt_dir=str(tmp_path / "ck"),
            ckpt_every=1, max_restarts=2, mesh_factory=mesh_factory)
        sizes["n"] = 1  # the preemption takes half the fleet
        report = runner.run(6)
    assert report.restarts == 1 and report.mesh_shrinks == 1
    assert _counter("resilience.mesh_shrinks") == shrinks0 + 1
    # params update through LINEAR gradient math (exact on binary data);
    # the loss itself squares the residual, which can round differently
    # per mesh — so params get the bit-equality assertion, losses a tight
    # tolerance
    np.testing.assert_allclose(clean, report.losses, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(runner.holder["params"]["w"]),
                                  np.asarray(pa["w"]))
    # the state really lives on the smaller mesh now
    assert len(runner.holder["params"]["w"].sharding.device_set) == 1
    # and the rebuilt step targets it
    assert runner.active["step"].mesh.devices.size == 1


def test_runner_elastic_grow_back(tmp_path):
    """Capacity returns mid-run: the checkpoint-boundary poll re-lays the
    LIVE state back onto the larger mesh (no fault, no restore) and the
    trajectory is unchanged."""
    from mxnet_tpu.parallel import create_mesh
    batch_fn, make = _exact_sharded_fixture()

    step_a, (pa, oa) = make(1)
    clean = []
    for i in range(6):
        pa, oa, l = step_a(pa, oa, batch_fn(i), i)
        clean.append(float(l))

    sizes = {"n": 1}

    def mesh_factory():
        return create_mesh(data=sizes["n"])

    grows0 = _counter("resilience.mesh_grows")
    step_b, (pb, ob) = make(1)
    runner = rz.ResilientRunner.for_sharded_step(
        step_b, pb, ob, batch_fn, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=2, mesh_factory=mesh_factory)
    sizes["n"] = 2  # capacity comes back; the step-2 boundary poll sees it
    report = runner.run(6)
    assert report.mesh_grows == 1 and report.restarts == 0
    assert _counter("resilience.mesh_grows") == grows0 + 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(runner.holder["params"]["w"]),
                                  np.asarray(pa["w"]))
    assert len(runner.holder["params"]["w"].sharding.device_set) == 2
    assert runner.active["step"].mesh.devices.size == 2


def test_on_shrink_hook_still_overrides_auto_reshard(tmp_path):
    """Back-compat: a user on_shrink hook wins over the automatic
    relayout."""
    from mxnet_tpu.parallel import create_mesh
    batch_fn, make = _exact_sharded_fixture()
    sizes = {"n": 2}

    def mesh_factory():
        return create_mesh(data=sizes["n"])

    called = []
    step_b, (pb, ob) = make(2)
    with faults.inject("run.step:preempt:3"):
        runner = rz.ResilientRunner.for_sharded_step(
            step_b, pb, ob, batch_fn, ckpt_dir=str(tmp_path / "ck"),
            ckpt_every=1, max_restarts=2, mesh_factory=mesh_factory,
            on_shrink=lambda mesh: called.append(mesh.devices.size) or None)
        sizes["n"] = 1
        report = runner.run(4)
    assert called == [1]
    assert report.mesh_shrinks == 1


def test_fused_step_elastic_rebuild_on_shrink(tmp_path):
    """Gluon path: a mesh-aware FusedTrainStep is rebuilt for the smaller
    mesh automatically, optimizer state carried across; the run completes
    and matches the fault-free trajectory."""
    from mxnet_tpu.parallel import create_mesh
    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a,
                                   mesh=create_mesh(data=2))
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    sizes = {"n": 2}

    def mesh_factory():
        return create_mesh(data=sizes["n"])

    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b,
                                   mesh=create_mesh(data=2))
    with faults.inject("run.step:preempt:4"):
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=2, mesh_factory=mesh_factory)
        sizes["n"] = 1
        report = runner.run(6)
    assert report.restarts == 1 and report.mesh_shrinks == 1
    assert runner.active["fused"] is not fused_b, "step must be rebuilt"
    assert runner.active["fused"]._mesh.devices.size == 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-4, atol=1e-5)
    for (ka, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                 sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=ka)


def test_sharded_step_place_and_rebuild_unit():
    """ShardedTrainStep.place re-lays host trees onto the step's mesh with
    rules-derived shardings; rebuild_for_mesh preserves knobs."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel import ShardedTrainStep, create_mesh

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"]) ** 2)

    mesh2 = create_mesh(data=2)
    step = ShardedTrainStep(loss_fn, {"w": jnp.zeros((4, 2))}, mesh2,
                            optimizer="sgd", lr=0.5, momentum=0.5,
                            donate=False, grad_accum=1)
    params, opt = step.init()
    mesh1 = create_mesh(data=1)
    rebuilt = step.rebuild_for_mesh(mesh1)
    assert rebuilt.mesh is mesh1
    assert rebuilt.lr == step.lr and rebuilt.donate == step.donate
    assert rebuilt.opt_kwargs == step.opt_kwargs
    p2, o2 = rebuilt.place({"w": np.ones((4, 2), np.float32)},
                           {"mom": {"w": np.zeros((4, 2), np.float32)}})
    assert len(p2["w"].sharding.device_set) == 1
    np.testing.assert_array_equal(np.asarray(p2["w"]), np.ones((4, 2)))
    assert len(o2["mom"]["w"].sharding.device_set) == 1
