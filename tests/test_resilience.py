"""mxnet_tpu.resilience — fault injection, retry, watchdog, auto-resume.

Every scenario runs on one chip: the fault harness makes preemptions,
transport faults, and hangs deterministic, so the recovery paths
(in-place retry, StallError-instead-of-hang, restore-and-replay) are
ordinary unit tests. The kill-and-resume parity tests reuse the 6-step
trajectory pattern from test_fused_step.py.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, resilience as rz, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faults, retry, watchdog
from mxnet_tpu.resilience.errors import (FatalTrainingError, InjectedFault,
                                         PreemptionError, RetryExhausted,
                                         StallError, TransportError,
                                         classify)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter(name):
    return telemetry.snapshot()["counters"].get(name, 0)


# ---------------------------------------------------------------------------
# faults: plan grammar + injection
# ---------------------------------------------------------------------------
def test_fault_plan_parse():
    plan = faults.FaultPlan.parse(
        "kvstore.push:error:1; collective.all_reduce:latency:2:0.01;"
        "run.step:preempt:3+;train.step:hang:*:0.1")
    kinds = [(s.site, s.kind) for s in plan.specs]
    assert kinds == [("kvstore.push", "error"),
                     ("collective.all_reduce", "latency"),
                     ("run.step", "preempt"), ("train.step", "hang")]
    assert plan.specs[1].arg == pytest.approx(0.01)
    assert plan.specs[2].from_nth_on and plan.specs[2].nth == 3
    assert plan.specs[3].every
    # nth matching
    assert not plan.specs[0].matches(2)
    assert plan.specs[2].matches(3) and plan.specs[2].matches(7)
    assert plan.specs[3].matches(1)


def test_fault_plan_parse_rejects_garbage():
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("justonefield")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("a:explode:1")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("a:error:0")


def test_inject_scoping_and_counts():
    before = faults.active_plan()
    with faults.inject("s:error:2") as plan:
        faults.check("s")              # call 1: clean
        with pytest.raises(InjectedFault):
            faults.check("s")          # call 2: fires
        faults.check("s")              # call 3: clean again
        assert plan.count("s") == 3
    assert faults.active_plan() is before


def test_env_fault_plan(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FAULT_PLAN", "e.site:preempt:1")
    try:
        faults.activate()
        with pytest.raises(PreemptionError):
            faults.check("e.site")
    finally:
        faults.deactivate()


def test_latency_injection_sleeps():
    with faults.inject("l.site:latency:1:0.05"):
        t0 = time.monotonic()
        faults.check("l.site")
        assert time.monotonic() - t0 >= 0.04


# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------
def test_classify_taxonomy():
    assert classify(TransportError("x")) == "retriable"
    assert classify(PreemptionError("x")) == "retriable"
    assert classify(StallError("x")) == "retriable"
    assert classify(FatalTrainingError("x")) == "fatal"
    assert classify(ValueError("anything")) == "fatal"
    assert classify(ConnectionResetError("peer")) == "retriable"
    # message-based: grpc-ish runtime errors
    assert classify(RuntimeError("UNAVAILABLE: connection reset")) \
        == "retriable"
    assert classify(RuntimeError("DEADLINE_EXCEEDED while waiting")) \
        == "retriable"
    # fatal markers beat transient markers
    assert classify(RuntimeError(
        "INVALID_ARGUMENT: shape mismatch on connection")) == "fatal"
    assert classify(RuntimeError("no idea what happened")) == "fatal"


# ---------------------------------------------------------------------------
# retry engine
# ---------------------------------------------------------------------------
def test_retry_succeeds_after_injected_fault():
    base = _counter("resilience.retries")
    calls = {"n": 0}

    def flaky():
        faults.check("r.site")
        calls["n"] += 1
        return "ok"

    with faults.inject("r.site:error:1"):
        out = retry.call_with_retry(
            flaky, site="r.site",
            policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0.001))
    assert out == "ok" and calls["n"] == 1
    assert _counter("resilience.retries") == base + 1
    assert _counter("resilience.retries.r.site") >= 1


def test_retry_fatal_propagates_first_attempt():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ValueError("dtype mismatch")

    with pytest.raises(ValueError):
        retry.call_with_retry(fatal, site="f.site",
                              policy=retry.RetryPolicy(max_attempts=5,
                                                       base_delay_s=0.001))
    assert calls["n"] == 1


def test_retry_exhausted_carries_context():
    def always_down():
        raise TransportError("endpoint down")

    with pytest.raises(RetryExhausted) as ei:
        retry.call_with_retry(
            always_down, site="kvstore.push", context="key=7 shard=(4, 4)",
            policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0.001))
    err = ei.value
    assert err.attempts == 3 and err.site == "kvstore.push"
    assert isinstance(err.last_error, TransportError)
    assert "key=7" in str(err) and "3 attempt" in str(err)
    # RetryExhausted is itself retriable at a coarser granularity
    assert classify(err) == "retriable"


def test_retry_on_filter():
    """A runner narrows in-place retry to TransportError: preemptions must
    reach its restore path un-retried."""
    calls = {"n": 0}

    def preempted():
        calls["n"] += 1
        raise PreemptionError("going away")

    with pytest.raises(PreemptionError):
        retry.call_with_retry(
            preempted, site="p",
            retry_on=lambda e: isinstance(e, TransportError),
            policy=retry.RetryPolicy(max_attempts=5, base_delay_s=0.001))
    assert calls["n"] == 1


def test_retriable_decorator_passes_kwargs_through():
    """site/policy bind at decoration; the wrapped function's own kwargs —
    even ones named like call_with_retry parameters — arrive untouched."""
    seen = {}

    @retry.retriable("deco.site",
                     policy=retry.RetryPolicy(max_attempts=2,
                                              base_delay_s=0.001))
    def fn(x, context=None, policy="user-policy"):
        seen.update(x=x, context=context, policy=policy)
        return x + 1

    assert fn(1, context="user-context") == 2
    assert seen == {"x": 1, "context": "user-context",
                    "policy": "user-policy"}


def test_retry_env_knob(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_RETRIES", "7")
    assert retry.RetryPolicy().max_attempts == 7
    monkeypatch.setenv("MXNET_TPU_RETRIES", "1")
    calls = {"n": 0}

    def down():
        calls["n"] += 1
        raise TransportError("down")

    with pytest.raises(RetryExhausted):
        retry.call_with_retry(down, site="k")
    assert calls["n"] == 1  # max_attempts=1 == no retry


def test_backoff_is_exponential_with_ceiling():
    pol = retry.RetryPolicy(max_attempts=10, base_delay_s=0.1,
                            max_delay_s=0.5, jitter=0.0)
    assert pol.delay(1) == pytest.approx(0.1)
    assert pol.delay(2) == pytest.approx(0.2)
    assert pol.delay(3) == pytest.approx(0.4)
    assert pol.delay(4) == pytest.approx(0.5)  # ceiling
    jittered = retry.RetryPolicy(base_delay_s=0.1, jitter=0.25)
    assert 0.074 <= jittered.delay(1) <= 0.126


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_turns_hang_into_stall_error():
    base = _counter("resilience.stalls")
    telemetry.span("warmup", "test").__enter__()  # ensure some span exists
    t0 = time.monotonic()
    with pytest.raises(StallError) as ei:
        with faults.inject("w.site:hang:1:30"):
            with watchdog.guard("w.site", deadline_s=0.25):
                faults.check("w.site")  # cooperative hang, 30s
    took = time.monotonic() - t0
    assert took < 5.0, "watchdog did not interrupt the hang (took %.1fs)" % took
    err = ei.value
    assert err.site == "w.site" and err.deadline_s == pytest.approx(0.25)
    assert err.span_dump, "StallError must carry the telemetry span dump"
    assert "recent spans" in err.format_spans()
    assert _counter("resilience.stalls") == base + 1
    assert _counter("resilience.stalls.w.site") >= 1


def test_watchdog_quiet_when_fast():
    base = _counter("resilience.stalls")
    with watchdog.guard("q.site", deadline_s=5.0):
        x = sum(range(1000))
    assert x == 499500
    assert _counter("resilience.stalls") == base


def test_watchdog_heartbeat_extends_deadline():
    base = _counter("resilience.stalls")
    with watchdog.guard("h.site", deadline_s=0.3):
        for _ in range(5):
            time.sleep(0.15)
            watchdog.heartbeat()  # 0.75s total but never 0.3s silent
    assert _counter("resilience.stalls") == base


def test_watchdog_no_deadline_is_transparent():
    with watchdog.guard("n.site", deadline_s=None):
        pass


# ---------------------------------------------------------------------------
# kvstore wiring
# ---------------------------------------------------------------------------
def test_kvstore_dist_push_retries_injected_fault():
    kv = mx.kv.create("dist_sync")
    shape = (4, 3)
    kv.init("w", nd.zeros(shape))
    base = _counter("resilience.retries")
    with faults.inject("kvstore.push:error:1"):
        kv.push("w", nd.ones(shape))
    out = nd.zeros(shape)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(shape))
    assert _counter("resilience.retries") > base


def test_kvstore_pull_retries_injected_fault():
    kv = mx.kv.create("local")
    kv.init("p", nd.full((2, 2), 3.0))
    out = nd.zeros((2, 2))
    with faults.inject("kvstore.pull:error:1"):
        kv.pull("p", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0 * np.ones((2, 2)))


def test_kvstore_dist_exhaustion_reports_key_and_attempts(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_RETRIES", "2")
    monkeypatch.setenv("MXNET_TPU_RETRY_BASE_S", "0.001")
    kv = mx.kv.create("dist_sync")
    kv.init("conv0_weight", nd.zeros((4,)))
    with faults.inject("kvstore.push:error:*"):
        with pytest.raises(RetryExhausted) as ei:
            kv.push("conv0_weight", nd.ones((4,)))
    msg = str(ei.value)
    assert "key=conv0_weight" in msg and "shard=(4,)" in msg
    assert "2 attempt" in msg
    assert ei.value.site == "kvstore.push"


def test_kvstore_dist_wraps_foreign_errors_with_context():
    kv = mx.kv.create("dist_sync")
    kv.init("3", nd.zeros((2,)))
    kv._updater = lambda *a: (_ for _ in ()).throw(
        RuntimeError("UNAVAILABLE: endpoint lost"))
    with pytest.raises(TransportError) as ei:
        kv.push("3", nd.ones((2,)))
    assert "key=3" in str(ei.value) and "UNAVAILABLE" in str(ei.value)


def test_collective_barrier_retries_injected_fault():
    from mxnet_tpu.parallel import collectives
    base = _counter("resilience.retries")
    with faults.inject("collective.barrier:error:1"):
        collectives.barrier()
    assert _counter("resilience.retries") > base


# ---------------------------------------------------------------------------
# snapshot checkpointer
# ---------------------------------------------------------------------------
def test_snapshot_checkpointer_roundtrip_retention_atomicity(tmp_path):
    ck = rz.SnapshotCheckpointer(str(tmp_path / "ck"), keep=2)
    for step in range(5):
        ck.save(step, {"w": np.full((3,), step), "step": step})
    assert ck.steps() == [3, 4], "keep=2 must prune older steps"
    assert ck.latest_step() == 4
    step, tree = ck.restore()
    assert step == 4 and tree["step"] == 4
    np.testing.assert_array_equal(tree["w"], np.full((3,), 4))
    # torn write simulation: a stray .tmp and a corrupt LATEST marker must
    # not lose the committed checkpoints
    (tmp_path / "ck" / "step_9.ckpt.tmp").write_bytes(b"torn")
    (tmp_path / "ck" / "LATEST").write_text("not a number")
    assert ck.latest_step() == 4
    step, tree = ck.restore()
    assert step == 4


def test_sharded_checkpoint_keep_and_latest_marker(tmp_path):
    """parallel.checkpoint satellite: keep=N retention + atomic LATEST."""
    from mxnet_tpu.parallel import checkpoint as ckpt
    path = str(tmp_path / "ck")
    for step in (1, 2, 3, 4):
        ckpt.save_sharded(path, {"w": np.ones((2,)) * step}, step=step,
                          keep=2)
    assert ckpt.latest_step(path) == 4
    committed = [d for d in os.listdir(path) if d.isdigit()]
    assert sorted(int(d) for d in committed) == [3, 4], \
        "keep=2 must retain exactly the newest two steps"
    assert (tmp_path / "ck" / "LATEST").read_text().strip() == "4"
    # corrupt marker: scan fallback still finds the newest step
    (tmp_path / "ck" / "LATEST").write_text("garbage")
    assert ckpt.latest_step(path) == 4
    restored = ckpt.restore_sharded(path)
    np.testing.assert_allclose(np.asarray(restored["w"]), 4 * np.ones((2,)))


# ---------------------------------------------------------------------------
# resilient runner: the acceptance scenario
# ---------------------------------------------------------------------------
def _build_mlp():
    mx.random.seed(42)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    return net, tr


def _six_batches():
    rng = np.random.RandomState(0)
    X = rng.rand(6, 32, 8).astype(np.float32)
    Y = rng.randint(0, 3, (6, 32)).astype(np.float32)
    return lambda i: (nd.array(X[i]), nd.array(Y[i]))


def test_kill_and_resume_matches_fault_free_run(tmp_path, monkeypatch):
    """ISSUE acceptance: MXNET_TPU_FAULT_PLAN injects a transport fault AND
    a mid-run kill; the 6-step resilient run must reproduce the fault-free
    trajectory and final params within fp32 tolerance, with nonzero
    resilience.retries and resilience.restores."""
    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    retries0 = _counter("resilience.retries")
    restores0 = _counter("resilience.restores")
    monkeypatch.setenv("MXNET_TPU_FAULT_PLAN",
                       "run.step:error:2;run.step:preempt:5")
    try:
        faults.activate()
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=2,
            max_restarts=3,
            retry_policy=retry.RetryPolicy(max_attempts=3,
                                           base_delay_s=0.001))
        report = runner.run(6)
    finally:
        faults.deactivate()

    assert report.restarts >= 1 and report.retries >= 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-5, atol=1e-6)
    for (ka, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                 sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=ka)
    assert _counter("resilience.retries") > retries0
    assert _counter("resilience.restores") > restores0


def test_kill_and_resume_with_dropout_rng_state(tmp_path):
    """RNG key table is checkpointed: even a net that CONSUMES randomness
    every step (dropout) replays the uninterrupted trajectory."""
    def build():
        mx.random.seed(9)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.4),
                    nn.Dense(3))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        return net, tr

    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net_a, tr_a = build()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    net_b, tr_b = build()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    with faults.inject("run.step:preempt:3"):
        runner = rz.ResilientRunner.for_fused_step(
            fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=2)
        report = runner.run(6)
    assert report.restarts == 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-5, atol=1e-6)


def test_runner_fault_before_first_checkpoint_surfaces_cause(tmp_path):
    """A fault with an EMPTY checkpoint dir must surface the fault itself,
    not a FileNotFoundError about the missing snapshot."""
    def step_fn(i):
        faults.check("bare.step")
        return 0.0

    state = {"w": 1.0}
    with faults.inject("bare.step:preempt:1"):
        runner = rz.ResilientRunner(
            step_fn, state_get=lambda: dict(state),
            state_set=lambda t: state.update(t),
            ckpt_dir=str(tmp_path / "ck"), ckpt_every=5, max_restarts=3)
        # start_step=2 is off the ckpt cadence: nothing saved before the hit
        with pytest.raises(PreemptionError):
            runner.run(6, start_step=2)


def test_runner_restart_budget_exhausts():
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    batch_fn = _six_batches()
    with faults.inject("run.step:preempt:1+"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=None, max_restarts=2)
        # no checkpointer: first preemption must surface immediately
        with pytest.raises(PreemptionError):
            runner.run(6)


def test_runner_recovers_from_stall(tmp_path):
    """A hang inside the step (dead collective) → watchdog StallError →
    restore-and-replay, run completes."""
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    batch_fn = _six_batches()
    stalls0 = _counter("resilience.stalls")
    with faults.inject("train.step:hang:3:30"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=2, step_deadline_s=0.5)
        report = runner.run(4)
    assert report.restarts == 1
    assert _counter("resilience.stalls") > stalls0
    assert all(l is not None for l in report.losses)


def test_runner_step_deadline_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_STEP_DEADLINE_S", "0.4")
    net, tr = _build_mlp()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    runner = rz.ResilientRunner.for_fused_step(
        fused, _six_batches(), ckpt_dir=str(tmp_path / "ck"))
    assert runner.step_deadline_s == pytest.approx(0.4)


def test_runner_auto_resume_after_process_kill(tmp_path):
    """resume=True restores the newest checkpoint — the relaunch-after-kill
    path (same ckpt_dir, fresh process state)."""
    batch_fn = _six_batches()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net_a, tr_a = _build_mlp()
    fused_a = gluon.FusedTrainStep(net_a, loss_fn, tr_a)
    clean = [float(fused_a(*batch_fn(i)).asnumpy()) for i in range(6)]

    # "first boot": dies by preemption with the restart budget at 0
    net_b, tr_b = _build_mlp()
    fused_b = gluon.FusedTrainStep(net_b, loss_fn, tr_b)
    runner = rz.ResilientRunner.for_fused_step(
        fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
        max_restarts=0)
    with faults.inject("run.step:preempt:4"):
        with pytest.raises(PreemptionError):
            runner.run(6)

    # "relaunch": perturb live state to prove restore really happens
    for _, p in net_b.collect_params().items():
        p.set_data(p.data() * 0.0)
    runner2 = rz.ResilientRunner.for_fused_step(
        fused_b, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1)
    report = runner2.run(6, resume=True)
    assert report.restarts == 0  # a requested resume is not a failure
    for (ka, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                 sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=ka)
    # the tail of the trajectory (post-resume steps) matches the clean run
    resumed_tail = [l for l in report.losses if l is not None]
    np.testing.assert_allclose(clean[-len(resumed_tail):], resumed_tail,
                               rtol=1e-5, atol=1e-6)


def test_runner_mesh_shrink_degrades_gracefully(tmp_path):
    """Device set shrinks across a restore → on_shrink rebuilds the step
    for the smaller mesh and the run continues (degraded, not dead)."""
    class FakeDevices:
        def __init__(self, size):
            self.size = size

    class FakeMesh:
        def __init__(self, size):
            self.devices = FakeDevices(size)

    sizes = {"n": 8}
    meshes = []

    def mesh_factory():
        m = FakeMesh(sizes["n"])
        meshes.append(m)
        return m

    state = {"w": 0.0, "rebuilt_for": None}

    def step_fn(i):
        faults.check("fake.step")
        state["w"] += 1.0
        return state["w"]

    def on_shrink(mesh):
        state["rebuilt_for"] = mesh.devices.size
        return step_fn  # rebuilt step for the smaller mesh

    shrinks0 = _counter("resilience.mesh_shrinks")
    with faults.inject("fake.step:preempt:3"):
        runner = rz.ResilientRunner(
            step_fn, state_get=lambda: dict(state),
            state_set=lambda t: state.update(t),
            ckpt_dir=str(tmp_path / "ck"), ckpt_every=1, max_restarts=2,
            mesh_factory=mesh_factory, on_shrink=on_shrink)
        sizes["n"] = 4  # preemption takes half the fleet
        report = runner.run(5)
    assert report.restarts == 1 and report.mesh_shrinks == 1
    assert state["rebuilt_for"] == 4
    assert _counter("resilience.mesh_shrinks") == shrinks0 + 1


def test_sharded_train_step_resilient_run(tmp_path):
    """Functional path: ShardedTrainStep under the runner reproduces the
    uninterrupted trajectory through a preemption."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import ShardedTrainStep, create_mesh

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    rng = np.random.RandomState(3)
    X = rng.rand(6, 16, 4).astype(np.float32)
    Y = rng.rand(6, 16, 2).astype(np.float32)

    def batch_fn(i):
        return {"x": jnp.asarray(X[i]), "y": jnp.asarray(Y[i])}

    def make():
        mesh = create_mesh(data=2)
        params = {"w": jnp.zeros((4, 2))}
        step = ShardedTrainStep(loss_fn, params, mesh, optimizer="sgd",
                                lr=0.1, momentum=0.9, donate=False)
        return step, step.init()

    step_a, (pa, oa) = make()
    clean = []
    for i in range(6):
        pa, oa, l = step_a(pa, oa, batch_fn(i), i)
        clean.append(float(l))

    step_b, (pb, ob) = make()
    with faults.inject("run.step:preempt:4"):
        runner = rz.ResilientRunner.for_sharded_step(
            step_b, pb, ob, batch_fn, ckpt_dir=str(tmp_path / "ck"),
            ckpt_every=2, max_restarts=2)
        report = runner.run(6)
    assert report.restarts == 1
    np.testing.assert_allclose(clean, report.losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(pa["w"]),
                               np.asarray(runner.holder["params"]["w"]),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# telemetry aggregation (satellite)
# ---------------------------------------------------------------------------
def test_merge_snapshots_fleet_semantics():
    a = {"counters": {"kvstore.push_calls": 3, "resilience.retries": 1},
         "gauges": {"memory.dev0.bytes_in_use": {"value": 10, "max": 40}},
         "histograms": {"step_ms": {"count": 2, "sum": 10.0, "min": 4.0,
                                    "max": 6.0, "avg": 5.0,
                                    "buckets": {"le_10": 2}}}}
    b = {"counters": {"kvstore.push_calls": 5, "cachedop.compile": 2},
         "gauges": {"memory.dev0.bytes_in_use": {"value": 30, "max": 35}},
         "histograms": {"step_ms": {"count": 1, "sum": 8.0, "min": 8.0,
                                    "max": 8.0, "avg": 8.0,
                                    "buckets": {"le_10": 1}}}}
    m = telemetry.merge_snapshots([a, b])
    assert m["workers"] == 2
    assert m["counters"]["kvstore.push_calls"] == 8      # extensive: sum
    assert m["counters"]["cachedop.compile"] == 2        # union of keys
    g = m["gauges"]["memory.dev0.bytes_in_use"]
    assert g["value"] == 30 and g["max"] == 40           # fleet watermark
    h = m["histograms"]["step_ms"]
    assert h["count"] == 3 and h["sum"] == pytest.approx(18.0)
    assert h["min"] == 4.0 and h["max"] == 8.0
    assert h["avg"] == pytest.approx(6.0)
    assert h["buckets"]["le_10"] == 3


def test_aggregate_snapshot_single_process():
    telemetry.inc("agg.test.counter", 4)
    merged = telemetry.aggregate_snapshot()
    assert merged["workers"] == 1
    assert merged["counters"]["agg.test.counter"] >= 4


# ---------------------------------------------------------------------------
# tooling (satellite)
# ---------------------------------------------------------------------------
def test_parse_log_resilience_mode(tmp_path):
    telemetry.reset()  # counters are process-global; start this dump clean
    telemetry.inc("resilience.retries")
    telemetry.inc("resilience.retries.kvstore.push")
    telemetry.inc("resilience.restores", 2)
    dump = str(tmp_path / "telemetry.json")
    telemetry.dump(dump)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--resilience"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| retries | total |" in r.stdout
    assert "| retries | kvstore.push | 1 |" in r.stdout
    assert "| restores | total |" in r.stdout
    # csv shape too
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--resilience", "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "event,site,count" in r.stdout
