"""gluon.contrib.estimator fit loop + event handlers.

reference: python/mxnet/gluon/contrib/estimator/ +
tests/python/unittest/test_gluon_estimator.py."""
import logging

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib.estimator import (
    Estimator, EarlyStoppingHandler, CheckpointHandler, EpochEnd)


_W_TRUE = onp.random.RandomState(99).randn(8, 3).astype("float32")


def _data(n=64, d=8, classes=3, seed=0):
    rng = onp.random.RandomState(seed)
    x = rng.randn(n, d).astype("float32")
    y = (x @ _W_TRUE).argmax(axis=1).astype("float32")
    ds = gluon.data.ArrayDataset(x, y)
    return gluon.data.DataLoader(ds, batch_size=16)


def _estimator(lr=0.05):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": lr})
    return Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     metrics=mx.metric.Accuracy(), trainer=tr,
                     logger=logging.getLogger("test-est"))


def test_fit_improves_and_validates():
    est = _estimator()
    train, val = _data(seed=0), _data(seed=1)
    est.fit(train, val_data=val, epochs=4)
    name, acc = est.train_metrics[0].get()
    assert name == "accuracy" and acc > 0.5, (name, acc)
    scores = est.evaluate(val)
    assert "accuracy" in scores and "val_loss" in scores
    assert scores["accuracy"] > 0.4


def test_loss_only_estimator_requires_gluon_loss():
    net = nn.Dense(2)
    with pytest.raises(ValueError):
        Estimator(net, loss="not-a-loss")


def test_early_stopping_stops():
    class ConstantMetric(mx.metric.EvalMetric):
        def __init__(self):
            super().__init__("const")

        def update(self, labels, preds):
            self.sum_metric += 1.0
            self.num_inst += 1

    est = _estimator()
    monitor = ConstantMetric()

    class FeedMonitor(EpochEnd):
        def epoch_end(self, estimator, *args, **kwargs):
            monitor.update(None, None)

    stopper = EarlyStoppingHandler(monitor, mode="min", patience=2)
    est.fit(_data(), epochs=50,
            event_handlers=est._default_handlers(None, 50) +
            [FeedMonitor(), stopper])
    # constant metric never improves after the first epoch: 1 + patience
    assert stopper.stop_training
    assert stopper.stopped_epoch <= 4


def test_checkpoint_handler_saves(tmp_path):
    est = _estimator()
    ck = CheckpointHandler(str(tmp_path), model_prefix="m",
                           monitor=est.train_loss_metric, save_best=True)
    est.fit(_data(), epochs=2,
            event_handlers=est._default_handlers(None, 2) + [ck])
    import os
    files = sorted(os.listdir(tmp_path))
    assert "m-epoch1.params" in files and "m-epoch2.params" in files
    assert "m-best.params" in files
    # best checkpoint loads back
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net2.load_parameters(str(tmp_path / "m-best.params"))


def test_fit_twice_with_reused_handlers_and_loss_metric_correct():
    """Regressions: StoppingHandler resets across fit() calls, and the
    train_loss metric really averages the LOSS (not predictions)."""
    est = _estimator()
    data = _data()
    handlers = est._default_handlers(None, 1)
    est.fit(data, event_handlers=handlers)
    n_first = est.train_loss_metric.num_inst
    assert n_first > 0
    est.fit(data, event_handlers=handlers)      # must actually run again
    assert est.train_loss_metric.num_inst > 0
    # loss metric tracks the real loss: positive CE, matches a manual pass
    name, val = est.train_loss_metric.get()
    manual = 0.0
    count = 0
    for x, y in data:
        l = est.loss(est.net(x), y).asnumpy()
        manual += float(l.sum()); count += l.size
    assert abs(val - manual / count) < 0.25 * max(1.0, manual / count), \
        (val, manual / count)
