"""mx.embedding tests (ISSUE 17).

Coverage per the issue: bit-exact sharded-vs-dense lookup/grad/update
parity on a FakeFleet at world 2 and 4 with a non-divisible vocab (the
padded tail rows), kernel-vs-XLA scatter-add bit parity through the
Pallas interpreter, elastic world-4 -> world-2 checkpoint restore,
fault-injected per-bucket retry on the sparse bucketed push, and the
serve contract on the kvstore lookup path (zero post-warm-up retraces).

The fleet fake mirrors `test_zero.FakeFleet` — a barrier'd mailbox that
sums/concats contributions in rank order, so fp32 runs stay bit-exact
against a dense reference that accumulates in the same order.
"""
import threading

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import engine, nd, telemetry
from mxnet_tpu.embedding import ShardedEmbedding
from mxnet_tpu.ndarray import sparse
from mxnet_tpu.parallel.collectives import merge_unique_rows


def _counters():
    return dict(telemetry.snapshot()["counters"])


def _delta(before, after, key):
    return after.get(key, 0) - before.get(key, 0)


# ===========================================================================
# injectable single-process fleet: the embedding comm contract is the
# simple one (`all_reduce(x)` dense sum, `all_gather(x)` rank-order
# axis-0 concat) — each simulated rank drives its ShardedEmbedding on its
# own thread through a barrier'd mailbox
# ===========================================================================
class FakeFleet:
    def __init__(self, world):
        self.world = world
        self.lock = threading.Lock()
        self.barrier = threading.Barrier(world)
        self.box = {}

    def comm(self, rank):
        return _FleetComm(self, rank)


class _FleetComm:
    def __init__(self, fleet, rank):
        self._fleet = fleet
        self.rank = rank
        self.world = fleet.world
        self._calls = 0

    def _exchange(self, value):
        # collective calls happen in lockstep on every rank, so the local
        # call index is a globally-consistent tag
        fleet = self._fleet
        tag = self._calls
        self._calls += 1
        with fleet.lock:
            fleet.box.setdefault(tag, {})[self.rank] = np.asarray(value)
        fleet.barrier.wait()
        with fleet.lock:
            parts = [fleet.box[tag][r] for r in range(self.world)]
        fleet.barrier.wait()
        return parts

    def all_reduce(self, x):
        parts = self._exchange(x)
        total = parts[0].copy()
        for p in parts[1:]:
            total = total + p   # rank order, matching the dense baseline
        return jnp.asarray(total)

    def all_gather(self, x):
        return jnp.asarray(np.concatenate(self._exchange(x), axis=0))


def _run_fleet(world, fn):
    """Run fn(rank, comm) on `world` threads; re-raise the first error."""
    fleet = FakeFleet(world)
    errs = [None] * world

    def wrap(rank):
        try:
            fn(rank, fleet.comm(rank))
        except BaseException as e:  # noqa: BLE001 - test harness
            errs[rank] = e
            fleet.barrier.abort()

    threads = [threading.Thread(target=wrap, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errs:
        if e is not None:
            raise e


# a vocab no tested world size divides: both world 2 and 4 pad to 12 rows
VOCAB, DIM = 11, 4


def _batches(world, steps, batch=6, seed=0):
    """[(step, rank) -> (ids, grads)] with repeated ids across and within
    ranks, so dedup paths actually merge."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        per_rank = []
        for _r in range(world):
            ids = rng.randint(0, VOCAB, size=batch).astype(np.int32)
            grads = rng.randn(batch, DIM).astype(np.float32)
            per_rank.append((ids, grads))
        out.append(per_rank)
    return out


def _dense_step(dense, per_rank):
    """Apply one global step to the world-1 reference table through the
    SAME merge sequence the sharded path sees: per-rank local dedup, then
    the rank-order concat slab (the fleet all_gather), re-merged inside
    apply_grads."""
    slabs = [merge_unique_rows(jnp.asarray(ids), jnp.asarray(grads))
             for ids, grads in per_rank]
    cat_ids = jnp.concatenate([s[0] for s in slabs])
    cat_vals = jnp.concatenate([s[1] for s in slabs])
    dense.apply_grads(cat_ids, cat_vals)


_OPTS = {
    "sgd": dict(optimizer="sgd", learning_rate=0.1, momentum=0.9, wd=0.01),
    "adam": dict(optimizer="adam", learning_rate=0.05),
}


# ===========================================================================
# sharded vs dense bit-exact parity (lookup + grad + update)
# ===========================================================================
@pytest.mark.parametrize("world", [2, 4])
@pytest.mark.parametrize("opt", sorted(_OPTS))
def test_sharded_matches_dense_reference_bit_exact(world, opt):
    steps = _batches(world, steps=3, seed=17 + world)
    probe = jnp.asarray([3, 7, 3, -1, 10, 0], jnp.int32)

    dense = ShardedEmbedding(VOCAB, DIM, seed=0, **_OPTS[opt])
    for per_rank in steps:
        _dense_step(dense, per_rank)
    want_w = np.asarray(dense.gathered_weight())
    want_rows = np.asarray(dense.lookup(probe))

    gathered = [None] * world
    looked = [None] * world

    def run(rank, comm):
        table = ShardedEmbedding(VOCAB, DIM, comm=comm, seed=0, **_OPTS[opt])
        for per_rank in steps:
            ids, grads = per_rank[rank]
            table.apply_grads(ids, grads)
        gathered[rank] = np.asarray(table.gathered_weight())
        looked[rank] = np.asarray(table.lookup(probe))

    _run_fleet(world, run)
    for rank in range(world):
        np.testing.assert_array_equal(gathered[rank], want_w)
        np.testing.assert_array_equal(looked[rank], want_rows)
    # the -1 probe slot is padding: exactly zero rows back
    assert not looked[0][3].any()


def test_lookup_masks_padded_tail_rows():
    # padded vocab is 12 at world 4; ids never reach the pad rows, and a
    # full-vocab lookup round-trips the init bytes exactly
    table = ShardedEmbedding(VOCAB, DIM, seed=2)
    out = [None]

    def run(rank, comm):
        t = ShardedEmbedding(VOCAB, DIM, comm=comm, seed=2)
        if rank == 0:
            out[0] = np.asarray(t.lookup(jnp.arange(VOCAB)))
        else:
            t.lookup(jnp.arange(VOCAB))

    _run_fleet(4, run)
    np.testing.assert_array_equal(out[0], np.asarray(table.gathered_weight()))


# ===========================================================================
# Pallas segment-sum: kernel vs XLA bit parity (interpreter on CPU)
# ===========================================================================
@pytest.mark.pallas
def test_segment_sum_kernel_bit_identical_to_xla():
    from mxnet_tpu.ops import sparse_ops
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 24, size=50), jnp.int32)
    vals = jnp.asarray(rng.randn(50, 16), jnp.float32)
    before = _counters()
    out = sparse_ops.segment_sum(vals, ids, 24)
    after = _counters()
    ref = jnp.zeros((24, 16), jnp.float32).at[ids].add(vals)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert _delta(before, after, "ops.pallas.dispatch.segment_sum") == 1


@pytest.mark.pallas
def test_segment_sum_kernel_drops_negative_ids():
    from mxnet_tpu.ops import sparse_ops
    ids = jnp.asarray([-1, 3, -1, 3, 0], jnp.int32)
    vals = jnp.ones((5, 4), jnp.float32)
    out = sparse_ops.segment_sum(vals, ids, 6)
    expect = np.zeros((6, 4), np.float32)
    expect[3] = 2.0
    expect[0] = 1.0
    np.testing.assert_array_equal(np.asarray(out), expect)


@pytest.mark.pallas
def test_segment_sum_vmem_gate_falls_back_counted():
    # a destination slab past the VMEM budget routes to XLA and is
    # counted — never an error
    from mxnet_tpu.ops import sparse_ops
    ids = jnp.asarray([0, 1, 2, 3], jnp.int32)
    vals = jnp.ones((4, 1), jnp.float32)
    before = _counters()
    out = sparse_ops.segment_sum(vals, ids, 20000)
    after = _counters()
    assert _delta(before, after,
                  "ops.pallas.fallback.segment_sum.vmem") == 1
    assert _delta(before, after, "ops.pallas.dispatch.segment_sum") == 0
    assert float(np.asarray(out).sum()) == 4.0


@pytest.mark.pallas
def test_segment_sum_dtype_gate_falls_back_counted():
    from mxnet_tpu.ops import sparse_ops
    ids = jnp.asarray([0, 1], jnp.int32)
    vals = jnp.ones((2, 4), jnp.int32)   # integer grads: XLA path
    before = _counters()
    out = sparse_ops.segment_sum(vals, ids, 4)
    after = _counters()
    assert _delta(before, after,
                  "ops.pallas.fallback.segment_sum.dtype") == 1
    assert int(np.asarray(out).sum()) == 8


def test_merge_unique_rows_dedups_and_pads():
    ids = jnp.asarray([5, 2, 5, -1, 2, 9], jnp.int32)
    vals = jnp.arange(24, dtype=jnp.float32).reshape(6, 4)
    uids, uvals = merge_unique_rows(ids, vals)
    assert uids.shape == ids.shape and uvals.shape == vals.shape
    np.testing.assert_array_equal(np.asarray(uids),
                                  [2, 5, 9, -1, -1, -1])
    want = np.asarray(vals)
    np.testing.assert_array_equal(np.asarray(uvals[0]), want[1] + want[4])
    np.testing.assert_array_equal(np.asarray(uvals[1]), want[0] + want[2])
    np.testing.assert_array_equal(np.asarray(uvals[2]), want[5])
    assert not np.asarray(uvals[3:]).any()


# ===========================================================================
# elastic checkpoints: world 4 -> world 2
# ===========================================================================
def test_elastic_checkpoint_world4_restores_onto_world2():
    steps4 = _batches(4, steps=2, seed=31)
    payloads = [None] * 4

    def train4(rank, comm):
        t = ShardedEmbedding(VOCAB, DIM, comm=comm, seed=0, **_OPTS["adam"])
        for per_rank in steps4:
            t.apply_grads(*per_rank[rank])
        payloads[rank] = t.state_payload()   # collective: lockstep on all

    _run_fleet(4, train4)
    payload = payloads[0]
    assert payload["layout"]["world"] == 4
    assert payload["step"] == 2
    assert set(payload["state"]) == {"mean", "var"}

    # reference: a world-1 table restored from the same payload, stepped
    # once with the world-2 merge structure
    steps2 = _batches(2, steps=1, seed=77)
    dense = ShardedEmbedding(VOCAB, DIM, seed=9, **_OPTS["adam"])
    dense.load_state_payload(payload)
    _dense_step(dense, steps2[0])
    want = np.asarray(dense.gathered_weight())

    gathered = [None] * 2

    def resume2(rank, comm):
        # seed differs on purpose: the payload must fully overwrite
        t = ShardedEmbedding(VOCAB, DIM, comm=comm, seed=9, **_OPTS["adam"])
        t.load_state_payload(payload)
        t.apply_grads(*steps2[0][rank])
        gathered[rank] = np.asarray(t.gathered_weight())

    _run_fleet(2, resume2)
    np.testing.assert_array_equal(gathered[0], want)
    np.testing.assert_array_equal(gathered[1], want)


def test_checkpoint_payload_geometry_is_validated():
    table = ShardedEmbedding(VOCAB, DIM, seed=0)
    payload = table.state_payload()
    other = ShardedEmbedding(VOCAB + 1, DIM, seed=0)
    with pytest.raises(ValueError):
        other.load_state_payload(payload)
    with pytest.raises(ValueError):
        table.load_state_payload({"embed_format": 0})


# ===========================================================================
# sparse bucketed push: per-bucket retry under fault injection
# ===========================================================================
def test_sparse_bucketed_push_retries_per_bucket():
    from mxnet_tpu.resilience import faults
    vocab = 20
    with engine.bucket_mb_scope(25):
        kv = mx.kv.create("local")
        keys = list(range(3))
        for k in keys:
            kv.init(k, nd.zeros((vocab, DIM)))
        vals = []
        for k in keys:
            rows = jnp.asarray([1, 4, 7 + k], jnp.int32)
            vals.append(sparse.RowSparseNDArray(
                jnp.full((3, DIM), float(k + 1), jnp.float32),
                rows, (vocab, DIM)))
        before = _counters()
        with faults.inject("kvstore.push:error:1"):
            kv.push(keys, vals)
        after = _counters()
        assert _delta(before, after,
                      "resilience.retries.kvstore.push") >= 1
        assert _delta(before, after, "comm.sparse.push") == 3
        assert _delta(before, after, "comm.sparse.bucket.count") >= 1
        # the retry replayed the bucket: every key holds its push
        for k in keys:
            out = nd.zeros((vocab, DIM))
            kv.pull(k, out=out)
            expect = np.zeros((vocab, DIM), np.float32)
            expect[[1, 4, 7 + k]] = float(k + 1)
            np.testing.assert_array_equal(out.asnumpy(), expect)


# ===========================================================================
# kvstore-served lookups: the serve no-retrace contract
# ===========================================================================
def test_row_sparse_pull_zero_retraces_after_warmup():
    kv = mx.kv.create("local")
    table = ShardedEmbedding(37, 8, seed=3)
    svc = kv.init_embedding("emb", table, max_batch=64)
    full = np.asarray(table.gathered_weight())
    before = _counters()
    for n in (3, 17, 64, 5, 17):
        rows = np.sort(np.random.RandomState(n).choice(
            37, size=min(n, 37), replace=False)).astype(np.int32)
        out = sparse.RowSparseNDArray(
            jnp.zeros((len(rows), 8), jnp.float32),
            jnp.asarray(rows), (37, 8))
        kv.row_sparse_pull("emb", out=out, row_ids=jnp.asarray(rows))
        np.testing.assert_array_equal(np.asarray(out._values), full[rows])
    after = _counters()
    assert _delta(before, after, "serve.retrace") == 0
    assert _delta(before, after, "embedding.serve.lookup") == 5
    # ...and an UN-warmed bucket after warm-up IS a retrace
    svc._fns.pop(32)
    svc.lookup(jnp.arange(20, dtype=jnp.int32))
    final = _counters()
    assert _delta(after, final, "serve.retrace") == 1


def test_embedding_push_updates_table_and_serving_snapshot():
    kv = mx.kv.create("local")
    table = ShardedEmbedding(19, DIM, optimizer="sgd", learning_rate=1.0,
                             seed=5)
    kv.init_embedding(7, table, max_batch=16)
    w0 = np.asarray(table.gathered_weight()).copy()
    rows = jnp.asarray([2, 5, 11], jnp.int32)
    kv.push(7, sparse.RowSparseNDArray(
        jnp.ones((3, DIM), jnp.float32), rows, (19, DIM)))
    w1 = np.asarray(table.gathered_weight())
    expect = w0.copy()
    expect[[2, 5, 11]] -= 1.0   # lr=1.0 sgd: exact fp32 subtraction
    np.testing.assert_array_equal(w1, expect)
    untouched = np.delete(np.arange(19), [2, 5, 11])
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    # the serve snapshot refreshed with the push
    out = sparse.RowSparseNDArray(jnp.zeros((3, DIM), jnp.float32),
                                  rows, (19, DIM))
    kv.row_sparse_pull(7, out=out, row_ids=rows)
    np.testing.assert_array_equal(np.asarray(out._values), w1[[2, 5, 11]])


def test_table_bytes_land_in_embedding_ledger_scope():
    from mxnet_tpu.telemetry import ledger
    table = ShardedEmbedding(64, 8, optimizer="adam", seed=1)
    # weight + mean + var for at least this table
    assert ledger.scopes().get("embedding", 0) >= \
        3 * table.weight.size * 4
