"""CustomOp bridge (reference suite: tests/python/unittest/test_operator.py
(test_custom_op) — forward/backward through mx.operator.CustomOp with
autograd, shapes inferred by the Prop)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


@mx.operator.register("softsign_t")
class SoftsignProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softsign()


class Softsign(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        self.assign(out_data[0], req[0], x / (1 + abs(x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        x = in_data[0]
        g = out_grad[0] / ((1 + abs(x)) * (1 + abs(x)))
        self.assign(in_grad[0], req[0], g)


def test_custom_forward():
    x = nd.array(np.array([-2.0, 0.0, 3.0], np.float32))
    y = nd.Custom(x, op_type="softsign_t")
    np.testing.assert_allclose(y.asnumpy(),
                               [-2 / 3, 0.0, 3 / 4], rtol=1e-6)


def test_custom_backward_through_autograd():
    xv = np.array([-1.5, 0.5, 2.0], np.float32)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="softsign_t")
        loss = (y * nd.array([1.0, 2.0, 3.0])).sum()
    loss.backward()
    expect = np.array([1.0, 2.0, 3.0]) / (1 + np.abs(xv)) ** 2
    np.testing.assert_allclose(x.grad.asnumpy(), expect, rtol=1e-6)


@mx.operator.register("twin_out")
class TwinProp(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["a", "b"]

    def list_outputs(self):
        return ["sum", "diff"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return TwinOp()


class TwinOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        a, b = in_data
        self.assign(out_data[0], req[0], a + b)
        self.assign(out_data[1], req[1], a - b)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        gs, gd = out_grad
        self.assign(in_grad[0], req[0], gs + gd)
        self.assign(in_grad[1], req[1], gs - gd)


def test_custom_multi_input_output():
    a = nd.array(np.array([1.0, 2.0], np.float32))
    b = nd.array(np.array([0.5, 1.0], np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        s, d = nd.Custom(a, b, op_type="twin_out")
        loss = (s * 2).sum() + d.sum()
    loss.backward()
    np.testing.assert_allclose(s.asnumpy(), [1.5, 3.0])
    np.testing.assert_allclose(d.asnumpy(), [0.5, 1.0])
    np.testing.assert_allclose(a.grad.asnumpy(), [3.0, 3.0])  # 2 + 1
    np.testing.assert_allclose(b.grad.asnumpy(), [1.0, 1.0])  # 2 - 1


def test_custom_unregistered_raises():
    with pytest.raises(mx.MXNetError, match="not registered"):
        nd.Custom(nd.ones((2,)), op_type="no_such_op")
