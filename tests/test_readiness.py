"""Readiness-ordered comm overlap + schedule autotuning (ISSUE 19) tests.

Coverage: autograd grad-ready hooks (firing order = reverse tape order,
`in_backward`/`backward_round` bookkeeping, hook-free path unchanged),
`engine.ready.ReadyScheduler` free/frozen assembly + flush reasons + the
bucket_mb=0 per-key escape hatch, bit-exact Trainer parity readiness-vs-
registration (local kvstore both update_on_kvstore modes, dist kvstore,
ZeRO) across bucket caps, ZeRO world=2/4 readiness parity on the
injectable FakeFleet fabric with out-of-order bucket completion, frozen
BucketLayout stability across reordered steps, fault-injected per-bucket
retry under out-of-order flush, per-key span launch order at cap=0,
gradient-accumulation abort + fallback, the schedule autotuner sweep →
pin → gauges, and checkpoint round-trips (ZeRO payload + ResilientRunner
tree) that restart with ZERO re-sweep steps.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, nd, telemetry
from mxnet_tpu.engine import ready as engine_ready
from mxnet_tpu.gluon import nn
from mxnet_tpu.optimizer import ZeroUpdater, create as opt_create

from test_zero import FakeFleet, _run_fleet  # noqa: F401 (fleet fabric)


def _counters():
    return dict(telemetry.snapshot()["counters"])


def _delta(before, after, key):
    return after.get(key, 0) - before.get(key, 0)


@pytest.fixture(autouse=True)
def _no_pinned_schedule():
    """Every test starts and ends without a process-wide pinned comm
    schedule (the autotuner tests pin one)."""
    engine.set_schedule(None)
    yield
    engine.set_schedule(None)


# ===========================================================================
# autograd grad-ready hooks
# ===========================================================================

def test_grad_ready_hook_fires_in_reverse_tape_order():
    order = []
    hook = autograd.add_grad_ready_hook(lambda leaf: order.append(id(leaf)))
    try:
        w1 = nd.array(np.ones((3,), np.float32))
        w2 = nd.array(np.full((3,), 2.0, np.float32))
        autograd.mark_variable(w1, grad_req="write")
        autograd.mark_variable(w2, grad_req="write")
        with autograd.record():
            h = w1 * 3.0          # w1's last use: early tape position
            y = (h + w2).sum()    # w2's last use: later position
        y.backward()
    finally:
        autograd.remove_grad_ready_hook(hook)
    # reverse replay finalizes w2 (later position) BEFORE w1
    assert order == [id(w2), id(w1)]
    np.testing.assert_array_equal(w1.grad.asnumpy(), np.full(3, 3.0))
    np.testing.assert_array_equal(w2.grad.asnumpy(), np.ones(3))


def test_grad_ready_hook_sees_in_backward_and_rounds():
    flags, rounds0 = [], autograd.backward_round()
    hook = autograd.add_grad_ready_hook(
        lambda leaf: flags.append(autograd.in_backward()))
    try:
        x = nd.array(np.ones((2,), np.float32))
        autograd.mark_variable(x, grad_req="write")
        for _ in range(2):
            with autograd.record():
                y = (x * x).sum()
            y.backward()
    finally:
        autograd.remove_grad_ready_hook(hook)
    assert flags == [True, True]
    assert autograd.backward_round() == rounds0 + 2
    assert not autograd.in_backward()


def test_grad_ready_hook_free_path_bit_identical():
    def grads(with_hook):
        mx.random.seed(3)
        np.random.seed(3)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
        net.initialize(mx.init.Xavier())
        hook = (autograd.add_grad_ready_hook(lambda leaf: None)
                if with_hook else None)
        try:
            x = nd.array(np.random.RandomState(0).randn(4, 6)
                         .astype(np.float32))
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
        finally:
            if hook is not None:
                autograd.remove_grad_ready_hook(hook)
        return [p.list_grad()[0].asnumpy()
                for p in net.collect_params().values()]

    for a, b in zip(grads(False), grads(True)):
        np.testing.assert_array_equal(a, b)


def test_remove_grad_ready_hook_absent_is_noop():
    autograd.remove_grad_ready_hook(lambda leaf: None)


# ===========================================================================
# ReadyScheduler: event-driven bucket assembly
# ===========================================================================

def _raw(n, fill=1.0):
    return jnp.full((n,), fill, jnp.float32)


def test_ready_scheduler_free_mode_reasons_and_boundaries():
    got = []
    sched = engine_ready.ReadyScheduler(
        lambda bucket, spec=None: got.append(bucket), cap_bytes=40)
    sched.add("a", _raw(5))       # 20B, open
    sched.add("b", _raw(5))       # 40B, still open (== cap is full next add)
    sched.add("c", _raw(5))       # overflow: [a,b] flush as "ready"
    sched.add("big", _raw(64))    # oversize alone
    sched.drain()                 # tail [c] flushes as "final"
    assert [b.reason for b in got] == ["ready", "oversize", "final"]
    assert [list(b.keys) for b in got] == [["a", "b"], ["big"], ["c"]]


def test_ready_scheduler_cap0_dispatches_per_key_immediately():
    got = []
    sched = engine_ready.ReadyScheduler(
        lambda bucket, spec=None: got.append(bucket), cap_bytes=0)
    sched.add("x", _raw(2))
    assert [list(b.keys) for b in got] == [["x"]]   # BEFORE drain
    sched.add("y", _raw(2))
    sched.drain()
    assert [list(b.keys) for b in got] == [["x"], ["y"]]
    assert all(b.reason == "ready" for b in got)


def test_ready_scheduler_frozen_mode_canonical_order():
    entries = [(k, _raw(4, float(i))) for i, k in enumerate("abcd")]
    layout = engine.BucketLayout.from_entries(entries, world=1,
                                              cap_bytes=32)
    assert len(layout) == 2       # [a,b] and [c,d]
    got = []
    sched = engine_ready.ReadyScheduler(
        lambda bucket, spec: got.append((spec.index, list(bucket.keys))),
        layout=layout)
    # arrival order is fully reversed: buckets still assemble in each
    # spec's canonical key order, completing out of bucket-index order
    for k, r in reversed(entries):
        sched.add(k, r)
    sched.drain()
    assert got == [(1, ["c", "d"]), (0, ["a", "b"])]


def test_ready_scheduler_frozen_mode_guards():
    entries = [("a", _raw(4)), ("b", _raw(4))]
    layout = engine.BucketLayout.from_entries(entries, world=1,
                                              cap_bytes=1 << 20)
    sched = engine_ready.ReadyScheduler(lambda b, s: None, layout=layout)
    with pytest.raises(ValueError, match="not in the frozen bucket layout"):
        sched.add("zz", _raw(4))
    sched.add("a", _raw(4))
    with pytest.raises(ValueError, match="b"):
        sched.drain()             # bucket incomplete: missing key named


# ===========================================================================
# Trainer parity: readiness vs registration, bit-exact
# ===========================================================================

def _train(comm_ready, cap, steps=4, uok=True, zero=None, kvstore="device",
           opt_kw=None):
    mx.random.seed(0)
    np.random.seed(0)
    with engine.bucket_mb_scope(cap):
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(8),
                    nn.Dense(2))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           dict(opt_kw or {"learning_rate": 0.125,
                                           "momentum": 0.5}),
                           kvstore=kvstore, update_on_kvstore=uok,
                           zero=zero, comm_ready=comm_ready)
        x = nd.array(np.random.RandomState(1).randn(8, 10)
                     .astype(np.float32))
        y = nd.array(np.ones((8,), np.float32))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        for _ in range(steps):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(8)
        return tr, [p.data().asnumpy()
                    for p in net.collect_params().values()]


@pytest.mark.parametrize("cap", [None, 0.0001, 0])
def test_trainer_readiness_parity_local(cap):
    before = _counters()
    _, a = _train(True, cap)
    after = _counters()
    _, b = _train(False, cap)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)
    # the first step goes registration (kv uninitialized during its
    # backward); every later round is readiness-ordered
    assert _delta(before, after, "comm.ready.rounds") == 3
    assert _delta(before, after, "comm.ready.aborted") == 0


def test_trainer_readiness_first_flush_before_backward_end():
    """The acceptance counter: with buckets smaller than the grad set the
    FIRST collective launches while backward is still running."""
    before = _counters()
    _train(True, 0.0001)
    after = _counters()
    assert _delta(before, after,
                  "comm.ready.first_flush_before_backward_end") >= 1
    assert _delta(before, after, "comm.ready.flush_during_backward") >= 1
    reason_ready = sum(
        _delta(before, after, k) for k in after
        if k.startswith("comm.bucket.flush_reason.ready"))
    assert reason_ready >= 1 or _delta(
        before, after, "comm.bucket.flush_reason.oversize") >= 1


def test_trainer_readiness_parity_pushpull():
    """update_on_kvstore=False: readiness launches feed the SAME grads
    back through the deferred out-broadcast at finish()."""
    _, a = _train(True, 0.0001, uok=False, kvstore=mx.kv.create("device"))
    _, b = _train(False, 0.0001, uok=False, kvstore=mx.kv.create("device"))
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)


@pytest.mark.parametrize("cap", [None, 0.0001, 0])
def test_trainer_readiness_parity_dist_single_worker(cap):
    from mxnet_tpu.kvstore.kvstore_dist import KVStoreDist
    _, a = _train(True, cap, kvstore=KVStoreDist("dist_sync"))
    _, b = _train(False, cap, kvstore=KVStoreDist("dist_sync"))
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)


@pytest.mark.parametrize("cap", [None, 0.0001])
def test_trainer_readiness_parity_zero(cap):
    before = _counters()
    _, a = _train(True, cap, zero=True)
    after = _counters()
    _, b = _train(False, cap, zero=True)
    _, c = _train(False, cap, zero=None)
    for pa, pb, pc in zip(a, b, c):
        np.testing.assert_array_equal(pa, pb)
        np.testing.assert_array_equal(pa, pc)   # and vs non-ZeRO baseline
    assert _delta(before, after, "comm.ready.rounds") == 3
    if cap == 0.0001:
        # multi-bucket layout: update(N) pipelines against ag(N-1)
        assert _delta(before, after, "comm.zero.pipelined") >= 1


def test_trainer_readiness_env_optin(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COMM_READY", "1")
    before = _counters()
    _, a = _train(None, 0.0001)
    after = _counters()
    assert _delta(before, after, "comm.ready.rounds") == 3
    monkeypatch.delenv("MXNET_TPU_COMM_READY")
    _, b = _train(None, 0.0001)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)


def test_trainer_readiness_grad_accumulation_aborts():
    """A second backward before step() means gradient accumulation: the
    armed session must be discarded (its launches are pure — nothing was
    mutated) and the step must fall back to the final grad buffers."""
    mx.random.seed(0)
    np.random.seed(0)
    with engine.bucket_mb_scope(0.0001):
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(4, in_units=3))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, update_on_kvstore=True,
                           comm_ready=True)
        x = nd.array(np.ones((2, 3), np.float32))

        def backward():
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()

        backward()
        tr.step(2)                 # round 1: registration (kv init)
        before = _counters()
        backward()                 # round 2 arms a session...
        backward()                 # ...round 3 must abort it
        tr.step(2)
        after = _counters()
        assert _delta(before, after, "comm.ready.aborted") >= 1
        # grads from the LAST backward applied (write semantics)
        expected = [p.data().asnumpy() for p in
                    net.collect_params().values()]
        assert all(np.isfinite(p).all() for p in expected)


def test_trainer_readiness_fault_injected_bucket_retry():
    """Per-bucket retry fires under out-of-order readiness flush with the
    bucket keys in the error context — and the step still lands the same
    parameters as the registration path under the same plan. Store-replace
    mode (update_on_kvstore=False): readiness launches are immutable, so
    the bucket replays as a unit."""
    from mxnet_tpu.resilience import faults

    def run(comm_ready):
        mx.random.seed(0)
        np.random.seed(0)
        with engine.bucket_mb_scope(0.0001):
            net = nn.HybridSequential()
            with net.name_scope():
                net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
            net.initialize(mx.init.Xavier())
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.125},
                               kvstore=mx.kv.create("device"),
                               update_on_kvstore=False,
                               comm_ready=comm_ready)
            x = nd.array(np.random.RandomState(1).randn(4, 6)
                         .astype(np.float32))
            y = nd.array(np.ones((4,), np.float32))
            loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
            for step in range(3):
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                if step == 2:
                    with faults.inject("kvstore.push:error:1"):
                        tr.step(4)
                else:
                    tr.step(4)
            return [p.data().asnumpy()
                    for p in net.collect_params().values()]

    before = _counters()
    a = run(True)
    after = _counters()
    assert _delta(before, after, "resilience.retries.kvstore.push") >= 1
    assert _delta(before, after, "comm.ready.rounds") >= 1
    b = run(False)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)


def test_bucket0_escape_hatch_per_key_spans_in_ready_order():
    """ISSUE 19 satellite: at bucket_mb=0 the per-key pushes route through
    the ready callback, so `comm.key[...]` spans appear in LAUNCH
    (readiness) order — reverse registration order for a chain net."""
    telemetry.reset()
    _train(True, 0, steps=2)
    names = [ev[0] for ev in telemetry.span_events()
             if ev[0].startswith("comm.key[")]
    assert names, "no per-key comm spans recorded"
    # steps feed 6 params (3 layers x weight+bias); readiness order within
    # a round is last-registered-first — the same set, key order reversed
    per_round = len(set(names))
    first_round = names[-per_round:]
    keys = [n[len("comm.key["):-1] for n in first_round]
    assert keys == sorted(keys, key=int, reverse=True)


# ===========================================================================
# ZeRO readiness at world=2/4 on the injectable fleet
# ===========================================================================

@pytest.mark.parametrize("world", [2, 4])
def test_zero_readiness_worldN_out_of_order_parity(world):
    rng = np.random.RandomState(7)
    shapes = [(24,), (17,), (33,), (8,)]
    keys = [str(i) for i in range(len(shapes))]
    steps = [[rng.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(3)]
    init_w = [rng.randn(*s).astype(np.float32) for s in shapes]

    def run(ready):
        out = {}

        def worker(rank, comm):
            zu = ZeroUpdater(opt_create("sgd", learning_rate=0.25,
                                        momentum=0.5, rescale_grad=1.0),
                             comm=comm)
            ws = [nd.array(w.copy()) for w in init_w]
            by_key = dict(zip(keys, ws))
            with engine.bucket_mb_scope(0.0001):
                # first step always registration: freezes the layout
                zu.step(keys, [jnp.asarray(g) for g in steps[0]], ws)
                for grads in steps[1:]:
                    if not ready:
                        zu.step(keys, [jnp.asarray(g) for g in grads], ws)
                        continue
                    graw = dict(zip(keys, [jnp.asarray(g) for g in grads]))
                    arrivals = []
                    # buckets complete in REVERSED layout order on every
                    # rank (same SPMD readiness order), exercising
                    # finish_ready's any-permutation contract
                    for spec in reversed(list(zu.layout)):
                        flat = engine.pack_flat(
                            spec, [graw[k] for k in spec.keys])
                        arrivals.append(
                            (spec, zu.scatter_ready(spec, flat, by_key)))
                    zu.finish_ready(arrivals, by_key)
            if rank == 0:
                out["w"] = [w.asnumpy() for w in ws]

        _run_fleet(world, worker)
        return out["w"]

    before = _counters()
    a = run(True)
    after = _counters()
    b = run(False)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)
    assert _delta(before, after, "comm.zero.pipelined") >= 1


def test_zero_frozen_layout_stable_across_reordered_steps():
    """Readiness rounds with shuffled completion order must not disturb
    the frozen layout (same payload every step, same as registration)."""
    rng = np.random.RandomState(1)
    shapes = [(10,), (6,), (14,)]
    keys = [str(i) for i in range(len(shapes))]
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5))
    ws = [nd.array(rng.randn(*s).astype(np.float32)) for s in shapes]
    by_key = dict(zip(keys, ws))
    with engine.bucket_mb_scope(0.0001):
        zu.step(keys, [jnp.asarray(rng.randn(*s).astype(np.float32))
                       for s in shapes], ws)
        frozen = zu.layout.to_payload()
        orders = [list(zu.layout), list(reversed(list(zu.layout)))]
        for order in orders:
            graw = {k: jnp.asarray(rng.randn(*s).astype(np.float32))
                    for k, s in zip(keys, shapes)}
            arrivals = [(spec, zu.scatter_ready(
                spec, engine.pack_flat(spec, [graw[k] for k in spec.keys]),
                by_key)) for spec in order]
            zu.finish_ready(arrivals, by_key)
            assert zu.layout.to_payload() == frozen


def test_zero_finish_ready_rejects_incomplete_round():
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5))
    ws = [nd.array(np.ones(4, np.float32)), nd.array(np.ones(6, np.float32))]
    by_key = {"0": ws[0], "1": ws[1]}
    with engine.bucket_mb_scope(0.00001):
        zu.step(["0", "1"], [jnp.ones((4,), jnp.float32),
                             jnp.ones((6,), jnp.float32)], ws)
        spec = list(zu.layout)[0]
        g = zu.scatter_ready(spec, engine.pack_flat(
            spec, [jnp.ones((4,), jnp.float32)]), by_key)
        with pytest.raises(ValueError):
            zu.finish_ready([(spec, g)], by_key)


# ===========================================================================
# schedule autotuner
# ===========================================================================

def test_comm_schedule_payload_roundtrip():
    sched = engine.CommSchedule(4.0, "ready", score=1.5, source="autotune")
    back = engine.CommSchedule.from_payload(sched.to_payload())
    assert back == sched and back.score == 1.5
    with pytest.raises(ValueError):
        engine.CommSchedule(4.0, "nonsense")
    with pytest.raises(ValueError):
        engine.CommSchedule.from_payload({"schedule_format": 99})


def test_autotuner_scores_and_pins_winner(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COMM_AUTOTUNE", "1")
    monkeypatch.setenv("MXNET_TPU_COMM_AUTOTUNE_STEPS", "1")
    monkeypatch.setenv("MXNET_TPU_COMM_AUTOTUNE_CAPS", "0,25")
    before = _counters()
    tr, a = _train(None, None, steps=6)
    after = _counters()
    tuner = tr._autotune
    assert tuner is not None and tuner.done
    assert len(tuner.results) == 4          # 2 caps x 2 policies
    chosen = engine.current_schedule()
    assert chosen is not None and chosen.source == "autotune"
    assert chosen.score == min(c.score for c, _ in tuner.results)
    assert _delta(before, after, "comm.autotune.sweeps") == 1
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["comm.schedule.bucket_mb"]["value"] == chosen.bucket_mb
    # every swept schedule stayed bit-identical: the sweep run's final
    # params match a plain registration run of the same traffic
    _, b = _train(False, None, steps=6)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(pa, pb)


def test_autotuner_restored_runs_zero_sweep_steps():
    sched = engine.CommSchedule(25.0, "ready", source="checkpoint")
    tuner = engine.ScheduleAutotuner.restored(sched)
    assert tuner.done and tuner.sweep_steps == 0
    assert tuner.on_step_end() is sched
    assert tuner.sweep_steps == 0


def test_zero_state_payload_carries_schedule_and_restores():
    engine.set_schedule(engine.CommSchedule(4.0, "ready", score=0.5,
                                            source="autotune"))
    zu = ZeroUpdater(opt_create("sgd", learning_rate=0.5))
    ws = [nd.array(np.ones(4, np.float32))]
    zu.step(["0"], [jnp.ones((4,), jnp.float32)], ws)
    payload = zu.state_payload()
    assert payload["comm_schedule"]["bucket_mb"] == 4.0
    engine.set_schedule(None)
    zu.load_state_payload(payload)
    restored = engine.current_schedule()
    assert restored is not None and restored.policy == "ready"
    assert restored.source == "checkpoint"
    assert engine.bucket_bytes() == int(4.0 * 1024 * 1024)


def test_trainer_restart_after_restore_skips_sweep(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COMM_AUTOTUNE", "1")
    monkeypatch.setenv("MXNET_TPU_COMM_AUTOTUNE_STEPS", "1")
    monkeypatch.setenv("MXNET_TPU_COMM_AUTOTUNE_CAPS", "0,25")
    tr, _ = _train(None, None, steps=6, zero=True)
    assert tr._autotune.done
    payload = tr._kvstore._updater.state_payload()
    chosen = engine.current_schedule()
    assert payload["comm_schedule"] == chosen.to_payload()
    engine.set_schedule(None)
    # "relaunch": fresh trainer, restore, then train — no sweeping
    tr2, _ = _train(None, None, steps=1, zero=True)
    tr2._kvstore._updater.load_state_payload(payload)
    mx.random.seed(0)
    x = nd.array(np.ones((2, 10), np.float32))
    net = nn.Dense(2, in_units=10)
    net.initialize()
    tr3 = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1}, update_on_kvstore=True)
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    tr3.step(2)
    assert tr3._autotune is not None and tr3._autotune.done
    assert tr3._autotune.sweep_steps == 0
    assert tr3._autotune.current() == chosen


def test_resilient_runner_checkpoint_carries_schedule(tmp_path):
    from mxnet_tpu.resilience.errors import PreemptionError
    from mxnet_tpu.resilience.run import ResilientRunner
    engine.set_schedule(engine.CommSchedule(25.0, "ready",
                                            source="autotune"))
    state = {"step": 0}
    seen = {}

    def step_fn(step):
        state["step"] = step
        if step == 2 and "crashed" not in seen:
            seen["crashed"] = True
            raise PreemptionError("host reclaimed")
        return 0.0

    def state_set(tree):
        seen["restored_tree"] = dict(tree)
        state.update(tree)
        # the schedule was consumed by the runner BEFORE state_set
        seen["sched_at_restore"] = engine.current_schedule()

    runner = ResilientRunner(
        step_fn, state_get=lambda: dict(state), state_set=state_set,
        ckpt_dir=str(tmp_path), ckpt_every=1, max_restarts=2)
    # pin cleared mid-run simulates the relaunched process
    orig_restore = runner._restore

    def clearing_restore(report, cause):
        engine.set_schedule(None)
        return orig_restore(report, cause)

    runner._restore = clearing_restore
    runner.run(4)
    assert "comm_schedule" not in seen["restored_tree"]
    restored = seen["sched_at_restore"]
    assert restored is not None and restored.bucket_mb == 25.0
    assert restored.policy == "ready" and restored.source == "checkpoint"
