"""cpu-vs-accelerator op consistency (round-3 VERDICT task #5 tail).

reference: tests/python/gpu/test_operator_gpu.py re-runs the op suite on
gpu(0) and `test_utils.check_consistency` compares context outputs. Here:
when MXNET_TEST_DEVICE=tpu (the on-chip suite run), every op in the
gradient sweep's spec catalog is executed on BOTH the accelerator and the
host CPU backend from identical inputs and compared. On the CPU-only
suite these tests skip — the harness is exercised the first time the
driver's on-chip run happens.
"""
import os

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu.ndarray.ndarray import invoke
from mxnet_tpu.ops import registry

from test_registry_grad_sweep import SPECS, SKIP, ALL_OPS, _auto_inputs

_ON_ACCEL = os.environ.get("MXNET_TEST_DEVICE", "cpu") in ("tpu", "gpu")

pytestmark = pytest.mark.skipif(
    not _ON_ACCEL,
    reason="cpu-vs-accelerator consistency needs MXNET_TEST_DEVICE=tpu")


def _run_on(ctx, name, inputs, kwargs):
    from mxnet_tpu import nd
    with mx.Context(ctx):
        xs = [nd.array(a, dtype=str(a.dtype))
              if isinstance(a, onp.ndarray) else a for a in inputs]
        out = invoke(name, *xs, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o.asnumpy() for o in outs]


# training-output ops are in the gradient sweep's SKIP only because their
# backward is deliberately not the forward vjp — the FORWARD consistency
# check here is still valid, so run them with explicit specs
_FWD_OK = {
    "LinearRegressionOutput": dict(
        inputs=[onp.random.RandomState(1).rand(3, 4).astype("float32"),
                onp.random.RandomState(2).rand(3, 4).astype("float32")],
        kwargs={}),
    "MAERegressionOutput": dict(
        inputs=[onp.random.RandomState(3).rand(3, 4).astype("float32"),
                onp.random.RandomState(4).rand(3, 4).astype("float32")],
        kwargs={}),
    "LogisticRegressionOutput": dict(
        inputs=[onp.random.RandomState(5).rand(3, 4).astype("float32"),
                onp.random.RandomState(6).rand(3, 4).astype("float32")],
        kwargs={}),
    "IdentityAttachKLSparseReg": dict(
        inputs=[onp.random.RandomState(7).uniform(
            0.1, 0.9, (3, 4)).astype("float32")], kwargs={}),
    "Softmax": dict(
        inputs=[onp.random.RandomState(8).rand(3, 4).astype("float32"),
                onp.array([0., 2., 1.], "float32")], kwargs={}),
}


@pytest.mark.parametrize("name", ALL_OPS)
def test_op_consistency_cpu_vs_accel(name):
    if name in _FWD_OK:
        spec = _FWD_OK[name]
        accel = _run_on(mx.tpu() if jax.default_backend() in ("tpu", "axon")
                        else mx.gpu(), name, spec["inputs"], spec["kwargs"])
        host = _run_on(mx.cpu(), name, spec["inputs"], spec["kwargs"])
        for a, h in zip(accel, host):
            onp.testing.assert_allclose(a, h, rtol=2e-2, atol=2e-3,
                                        err_msg=name)
        return
    if name in SKIP:
        pytest.skip(SKIP[name])
    spec = SPECS.get(name)
    if name in SPECS and spec is None:
        pytest.skip("covered elsewhere")
    if spec is None:
        inputs, kwargs = _auto_inputs(name)
        if inputs is None:
            pytest.skip("no auto inputs")
        spec = dict(inputs=inputs, kwargs=kwargs)
    accel = _run_on(mx.tpu() if jax.default_backend() in ("tpu", "axon")
                    else mx.gpu(), name, spec["inputs"],
                    spec.get("kwargs", {}))
    host = _run_on(mx.cpu(), name, spec["inputs"], spec.get("kwargs", {}))
    assert len(accel) == len(host)
    for a, h in zip(accel, host):
        onp.testing.assert_allclose(a, h, rtol=2e-2, atol=2e-3,
                                    err_msg=name)
