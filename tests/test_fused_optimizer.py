"""Pallas fused optimizer kernels (ISSUE 10) — parity + dispatch tests.

Every test here runs the REAL kernels through the Pallas interpreter on
the CPU backend (this container has no chip): interpreter results are
PARITY evidence only, never perf evidence (the interpreter serializes the
grid; perf evidence is `BENCH=fused_opt` on a live chip window).

Parity contracts:
* flat SGD/Adam vs `optimizer._fused_flat_xla` — BIT-identical (same
  elementwise ops in the same order, both jitted);
* LAMB phase1/apply Pallas vs XLA — fp32 round-off only (the per-segment
  norm reduction accumulates per-tile vs per-slice);
* per-parameter tpu_impls vs the eager base ops — bit-identical under
  FMA-immune dyadic hyperparameters (the test_zero.py trick: the jitted
  kernel path may contract mul+add into FMA, the un-jitted eager
  composite does not), fp32 round-off otherwise.
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx  # noqa: F401
from mxnet_tpu import telemetry
from mxnet_tpu.ops import fused_optimizer as fo
from mxnet_tpu.ops import optimizer_ops as oo
from mxnet_tpu.optimizer.optimizer import _fused_flat_fn, _fused_flat_xla

pytestmark = pytest.mark.pallas


def _counters():
    return dict(telemetry.snapshot()["counters"])


def _vecs(n, seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(n).astype(np.float32))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    s = jnp.asarray(rng.rand(n).astype(np.float32))
    lr = jnp.asarray((rng.rand(n) * 0.1).astype(np.float32))
    wd = jnp.asarray((rng.rand(n) * 0.01).astype(np.float32))
    return w, g, s, lr, wd


@pytest.mark.parametrize("momentum_on,clip_on,mp_on", [
    (False, False, False), (True, False, False), (True, True, False),
    (False, True, False), (True, False, True), (True, True, True),
])
@pytest.mark.parametrize("n", [50, 1024, 2000])
def test_flat_sgd_bit_identical_to_xla(momentum_on, clip_on, mp_on, n):
    """Pallas flat SGD == `_fused_flat_xla("sgd", ...)` BITWISE, including
    the non-128-multiple padding path and the fp32-master multi-precision
    contract."""
    w, g, mom, lr, wd = _vecs(n, seed=n)
    master = w.astype(jnp.float32) if mp_on else None
    ww = w.astype(jnp.float16) if mp_on else w
    args = (ww, g, mom if momentum_on else None, master, lr, wd,
            jnp.float32(0.9), jnp.float32(1.5), jnp.float32(0.25))
    ref = _fused_flat_xla("sgd", momentum_on, clip_on, mp_on)(*args)
    got = fo.flat_update_fn("sgd", momentum_on, clip_on, mp_on)(*args)
    for a, b, nm in zip(got, ref, ("w", "mom", "master")):
        if b is None:
            assert a is None, nm
            continue
        assert a.dtype == b.dtype, nm
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=nm)


@pytest.mark.parametrize("clip_on,mp_on", [(False, False), (True, False),
                                           (False, True)])
def test_flat_adam_bit_identical_to_xla(clip_on, mp_on):
    n = 777
    w, g, mean, lr, wd = _vecs(n, seed=7)
    var = jnp.abs(g) * 0.1
    master = w.astype(jnp.float32) if mp_on else None
    ww = w.astype(jnp.float16) if mp_on else w
    args = (ww, g, mean, var, master, lr, wd, jnp.float32(0.9),
            jnp.float32(1.0 - 0.9), jnp.float32(0.999),
            jnp.float32(1.0 - 0.999), jnp.float32(1e-8), jnp.float32(1.0),
            jnp.float32(0.5))
    ref = _fused_flat_xla("adam", True, clip_on, mp_on)(*args)
    got = fo.flat_update_fn("adam", True, clip_on, mp_on)(*args)
    for a, b, nm in zip(got, ref, ("w", "mean", "var", "master")):
        if b is None:
            assert a is None, nm
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=nm)


def test_fused_flat_fn_dispatches_pallas_under_gate():
    """`optimizer._fused_flat_fn` (the ZeroUpdater entry) returns the
    counted Pallas wrapper when the gate is on, the XLA jit otherwise."""
    assert fo.use_pallas_flat()   # pallas marker fixture set interpret mode
    n = 64
    w, g, mom, lr, wd = _vecs(n, seed=3)
    before = _counters()
    out = _fused_flat_fn("sgd", True, False, False)(
        w, g, mom, None, lr, wd, jnp.float32(0.5), jnp.float32(1.0),
        jnp.float32(0.0))
    after = _counters()
    assert after.get("ops.pallas.dispatch.flat_sgd", 0) == \
        before.get("ops.pallas.dispatch.flat_sgd", 0) + 1
    ref = _fused_flat_xla("sgd", True, False, False)(
        w, g, mom, None, lr, wd, jnp.float32(0.5), jnp.float32(1.0),
        jnp.float32(0.0))
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))
    # spans: the dispatch rides a pallas.<kernel> span for trace attribution
    assert any(ev[0] == "pallas.flat_sgd" and ev[1] == "kernel"
               for ev in telemetry.span_events())


def test_flat_fallback_counted_never_erroring():
    """Ineligible operands (integer weights) fall back to the XLA
    composite with a counted reason — never an exception."""
    n = 32
    w = jnp.arange(n, dtype=jnp.int32)
    g = jnp.ones((n,), jnp.int32)
    mom = jnp.zeros((n,), jnp.int32)
    lr = jnp.full((n,), 0.5, jnp.float32)
    wd = jnp.zeros((n,), jnp.float32)
    before = _counters()
    out = fo.flat_update_fn("sgd", True, False, False)(
        w, g, mom, None, lr, wd, jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(0.0))
    after = _counters()
    assert after.get("ops.pallas.fallback.dtype", 0) == \
        before.get("ops.pallas.fallback.dtype", 0) + 1
    ref = _fused_flat_xla("sgd", True, False, False)(
        w, g, mom, None, lr, wd, jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(0.0))
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(ref[0]))


def test_flat_multi_tile_grid():
    """A vector larger than one tile runs a >1 grid; results must be
    identical to the XLA path across the tile boundary."""
    n = fo._MAX_TILE_ROWS * fo._LANES + 4321   # forces grid == 2
    w, g, mom, lr, wd = _vecs(n, seed=11)
    args = (w, g, mom, None, lr, wd, jnp.float32(0.9), jnp.float32(1.0),
            jnp.float32(0.0))
    ref = _fused_flat_xla("sgd", True, False, False)(*args)
    got = fo.flat_update_fn("sgd", True, False, False)(*args)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(ref[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(ref[1]))


def test_lamb_phase1_and_apply_pallas_vs_xla():
    """LAMB two-pass: Pallas phase1 (direction + per-segment norm
    partials) and trust-ratio apply vs the XLA impls. Norm accumulation
    order differs per documented tolerance (fp32 round-off)."""
    n = 700
    segments = ((0, 0, 300), (1, 300, 300), (2, 600, 100))
    seg_ids = np.zeros((n,), np.int32)
    seg_ids[300:600] = 1
    seg_ids[600:] = 2
    seg_ids = jnp.asarray(seg_ids)
    w, g, mean, lr, wd = _vecs(n, seed=13)
    var = jnp.abs(g) * 0.1
    scal = (jnp.float32(0.9), jnp.float32(0.1), jnp.float32(0.999),
            jnp.float32(0.001), jnp.float32(1 - 0.9 ** 2),
            jnp.float32(1 - 0.999 ** 2), jnp.float32(1e-6),
            jnp.float32(1.0), jnp.float32(0.0))
    x_impl = fo._jitted(("t_lamb1x",),
                        lambda: fo._lamb1_xla_impl(False, False, True,
                                                   segments, 3))
    p_impl = fo._jitted(("t_lamb1p",),
                        lambda: fo._lamb1_pallas_impl(False, False, True, 3))
    ref = x_impl(w, g, mean, var, None, wd, seg_ids, *scal)
    got = p_impl(w, g, mean, var, None, wd, seg_ids, *scal)
    for a, b, nm in zip(got, ref, ("gdir", "mean", "var", "norms")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-6,
                                   atol=1e-6, err_msg=nm)
    # the norms really are per-key sums of squares
    w32 = np.asarray(w)
    want0 = np.array([np.sum(w32[np.asarray(seg_ids) == k] ** 2)
                      for k in range(3)])
    np.testing.assert_allclose(np.asarray(got[3])[0], want0, rtol=1e-5)
    # apply pass
    scale = lr * 0.7
    ra, ma = fo.lamb_flat_apply_fn(False)(w, None, ref[0], scale)
    np.testing.assert_allclose(
        np.asarray(ra), w32 - np.asarray(scale) * np.asarray(ref[0]),
        rtol=1e-6)
    assert ma is None


# FMA-immune dyadic hyperparameters (see tests/test_zero.py): the jitted
# kernel may contract mul+add into FMA, the eager base op does not —
# power-of-two scalars make both round identically on arbitrary data
_DY = dict(lr=0.125, momentum=0.5, wd=0.125)


def test_per_param_sgd_updates_bit_identical():
    rng = np.random.RandomState(21)
    w = jnp.asarray(rng.randn(9, 11).astype(np.float32))
    g = jnp.asarray(rng.randn(9, 11).astype(np.float32))
    mom = jnp.asarray(rng.randn(9, 11).astype(np.float32))
    ref = oo.sgd_update(w, g, _DY["lr"], wd=_DY["wd"], clip_gradient=0.5)
    got = fo._sgd_update_tpu(w, g, _DY["lr"], wd=_DY["wd"],
                             clip_gradient=0.5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    ref = oo.sgd_mom_update(w, g, mom, _DY["lr"], momentum=_DY["momentum"],
                            wd=_DY["wd"])
    got = fo._sgd_mom_update_tpu(w, g, mom, _DY["lr"],
                                 momentum=_DY["momentum"], wd=_DY["wd"])
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_per_param_adam_update_parity():
    """Dyadic betas -> bitwise; arbitrary betas -> <= 1-ulp FMA skew."""
    rng = np.random.RandomState(22)
    w = jnp.asarray(rng.randn(64).astype(np.float32))
    g = jnp.asarray(rng.randn(64).astype(np.float32))
    m = jnp.asarray(rng.randn(64).astype(np.float32))
    v = jnp.abs(g) * 0.5
    kw = dict(beta1=0.5, beta2=0.5, epsilon=2.0 ** -8, wd=0.125)
    ref = oo.adam_update(w, g, m, v, 0.125, **kw)
    got = fo._adam_update_tpu(w, g, m, v, 0.125, **kw)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    kw = dict(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.01)
    ref = oo.adam_update(w, g, m, v, 0.01, **kw)
    got = fo._adam_update_tpu(w, g, m, v, 0.01, **kw)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                                   atol=1e-7)


def test_per_param_lamb_phases_parity():
    rng = np.random.RandomState(23)
    w = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    g = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    m = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    v = jnp.abs(g) * 0.3
    ref = oo.lamb_update_phase1(w, g, m, v, t=3, wd=0.01)
    got = fo._lamb_phase1_tpu(w, g, m, v, t=3, wd=0.01)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                                   atol=1e-7)
    r1 = jnp.linalg.norm(w)
    r2 = jnp.linalg.norm(ref[0])
    refw = oo.lamb_update_phase2(w, ref[0], r1, r2, 0.125, lower_bound=0.1,
                                 upper_bound=10.0)
    gotw = fo._lamb_phase2_tpu(w, ref[0], r1, r2, 0.125, lower_bound=0.1,
                               upper_bound=10.0)
    np.testing.assert_allclose(np.asarray(gotw), np.asarray(refw),
                               rtol=1e-6, atol=1e-7)


def test_per_param_fp16_falls_back_counted():
    """The per-param kernels are f32-only (the base ops run native-dtype
    math): fp16 weights fall back to the base op, counted, identical."""
    rng = np.random.RandomState(24)
    w = jnp.asarray((rng.randn(32) * 0.1).astype(np.float16))
    g = jnp.asarray((rng.randn(32) * 0.1).astype(np.float16))
    before = _counters()
    got = fo._sgd_update_tpu(w, g, 0.125, wd=0.0)
    after = _counters()
    assert after.get("ops.pallas.fallback.sgd.dtype", 0) == \
        before.get("ops.pallas.fallback.sgd.dtype", 0) + 1
    ref = oo.sgd_update(w, g, 0.125, wd=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_registry_best_fn_gates_per_param_path(monkeypatch):
    """`optimizer._run_op` resolves through registry.best_fn: on a CPU
    context the base op runs (tier-1 behavior unchanged); the tpu_impl is
    registered and reachable for accelerator contexts."""
    from mxnet_tpu.ops import registry as reg
    op = reg.get("sgd_mom_update")
    assert op.tpu_fn is fo._sgd_mom_update_tpu
    monkeypatch.setenv("MXNET_TPU_USE_PALLAS", "1")
    assert op.best_fn(False) is op.fn
    assert op.best_fn(True) is fo._sgd_mom_update_tpu


def test_use_pallas_flat_gate(monkeypatch):
    monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
    assert fo.use_pallas_flat()
    monkeypatch.delenv("MXNET_FLASH_INTERPRET", raising=False)
    # CPU backend without interpret: never
    assert not fo.use_pallas_flat()


@pytest.mark.lint
def test_fused_optimizer_lint_clean_zero_suppressions():
    """The new kernel layer must be tracelint-clean with ZERO suppression
    comments (ISSUE 10 CI satellite)."""
    import mxnet_tpu.analysis as analysis
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "ops")
    for name in ("fused_optimizer.py", "pallas_stats.py"):
        path = os.path.join(root, name)
        findings = analysis.check(path)
        assert findings == [], "\n".join(str(f) for f in findings)
        with open(path) as f:
            assert "tpu-lint" not in f.read(), \
                "suppression found in %s" % name
