"""Bucketed gradient-communication engine (mx.engine) tests.

Coverage per ISSUE 4: bit-exact parity of bucketed vs. unbucketed gradients
(local + dist kvstore + eager collectives + both fused train-step paths),
bucket-boundary cases (grad > cap, dtype-mixed buckets split, empty grads
skipped), fault-injection retry per-bucket with key context, the
`MXNET_TPU_COMM_BUCKET_MB=0` escape hatch, the collectives-per-step drop for
a resnet18-sized gradient set, the retrace-guard routing for the functional
paths, the single-sync mp batchify, and the `parse_log.py --comm` table.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, nd, telemetry
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counters():
    return dict(telemetry.snapshot()["counters"])


def _delta(before, after, key):
    return after.get(key, 0) - before.get(key, 0)


# ===========================================================================
# GradBucketer unit behavior
# ===========================================================================

def test_bucketer_packs_in_order_and_caps():
    before = _counters()
    buckets = engine.bucketize(
        [(str(i), jnp.ones((1000,), jnp.float32)) for i in range(10)],
        cap_bytes=3 * 4000)
    assert [b.keys for b in buckets] == [
        ["0", "1", "2"], ["3", "4", "5"], ["6", "7", "8"], ["9"]]
    assert [b.reason for b in buckets] == ["full", "full", "full", "final"]
    assert all(b.nbytes <= 12000 for b in buckets)
    after = _counters()
    assert _delta(before, after, "comm.bucket.count") == 4
    assert _delta(before, after, "comm.bucket.bytes") == 40000
    assert _delta(before, after, "comm.bucket.flush_reason.full") == 3
    assert _delta(before, after, "comm.bucket.flush_reason.final") == 1


def test_bucketer_oversize_grad_travels_alone():
    buckets = engine.bucketize(
        [("small", jnp.ones((10,), jnp.float32)),
         ("big", jnp.ones((100000,), jnp.float32)),
         ("tail", jnp.ones((10,), jnp.float32))],
        cap_bytes=1000)
    assert [b.keys for b in buckets] == [["small"], ["big"], ["tail"]]
    assert buckets[1].reason == "oversize"


def test_bucketer_splits_mixed_dtypes():
    buckets = engine.bucketize(
        [("a", jnp.ones((10,), jnp.float32)),
         ("b", jnp.ones((10,), jnp.bfloat16)),
         ("c", jnp.ones((10,), jnp.bfloat16))],
        cap_bytes=1 << 20)
    assert [b.keys for b in buckets] == [["a"], ["b", "c"]]
    assert all(len({str(r.dtype) for r in b.raws}) == 1 for b in buckets)


def test_bucketer_skips_empty_grads():
    before = _counters()
    buckets = engine.bucketize(
        [("a", jnp.ones((4,), jnp.float32)),
         ("empty", jnp.zeros((0,), jnp.float32)),
         ("none", None),
         ("b", jnp.ones((4,), jnp.float32))],
        cap_bytes=1 << 20)
    assert [b.keys for b in buckets] == [["a", "b"]]
    assert _delta(before, _counters(), "comm.bucket.skipped") == 2


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(0)
    raws = [jnp.asarray(rng.randn(*s).astype(np.float32))
            for s in [(3, 4), (7,), (2, 2, 2)]]
    (bucket,) = engine.bucketize(enumerate(raws), cap_bytes=1 << 20)
    flat = engine.pack_bucket(bucket)
    assert flat.shape == (12 + 7 + 8,)
    parts = engine.unpack_bucket(bucket, flat)
    for r, p in zip(raws, parts):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(p))


def test_reassociate_bucketed_is_identity():
    rng = np.random.RandomState(1)
    raws = [jnp.asarray(rng.randn(*s).astype(np.float32))
            for s in [(5, 5), (100,), (3,), (17, 2)]]
    out = engine.reassociate_bucketed(raws, bucket_mb=0.0001)
    for r, o in zip(raws, out):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(o))
    # and under jit (the train-step usage)
    out2 = jax.jit(lambda xs: engine.reassociate_bucketed(xs, 25))(raws)
    for r, o in zip(raws, out2):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(o))


def test_bucket_cap_knob_precedence(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COMM_BUCKET_MB", "2")
    assert engine.bucket_bytes() == 2 * 1024 * 1024
    with engine.bucket_mb_scope(1):
        assert engine.bucket_bytes() == 1024 * 1024
        assert engine.bucket_bytes(4) == 4 * 1024 * 1024  # arg wins
    assert engine.bucket_bytes() == 2 * 1024 * 1024
    # the escape hatch: 0 disables bucketing entirely
    monkeypatch.setenv("MXNET_TPU_COMM_BUCKET_MB", "0")
    assert engine.bucket_bytes() == 0


# ===========================================================================
# local kvstore: bucketed vs per-key bit-exact parity
# ===========================================================================

def _local_pushpull(bucket_mb, nrep=1, n=7, shape=(5, 3), seed=0):
    with engine.bucket_mb_scope(bucket_mb):
        kv = mx.kv.create("device")
        rng = np.random.RandomState(seed)
        keys = list(range(n))
        for k in keys:
            kv.init(k, nd.zeros(shape))
        vals = [[nd.array(rng.randn(*shape).astype(np.float32))
                 for _ in range(nrep)] for _ in keys]
        outs = [[nd.zeros(shape) for _ in range(nrep)] for _ in keys]
        kv.pushpull(keys, vals, out=outs)
        return [o[0].asnumpy() for o in outs]


@pytest.mark.parametrize("nrep", [1, 3])
def test_local_kvstore_bucketed_parity(nrep):
    bucketed = _local_pushpull(25, nrep=nrep)
    flat = _local_pushpull(0, nrep=nrep)
    for a, b in zip(bucketed, flat):
        np.testing.assert_array_equal(a, b)


def test_local_kvstore_bucketed_push_with_updater_parity():
    def run(mb):
        with engine.bucket_mb_scope(mb):
            kv = mx.kv.create("device")
            kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.5,
                                                 rescale_grad=1.0))
            rng = np.random.RandomState(0)
            keys = list(range(5))
            for k in keys:
                kv.init(k, nd.array(rng.randn(4).astype(np.float32)))
            kv.push(keys, [nd.array(rng.randn(4).astype(np.float32))
                           for _ in keys])
            outs = [nd.zeros((4,)) for _ in keys]
            kv.pull(keys, out=outs)
            return [o.asnumpy() for o in outs]
    for a, b in zip(run(25), run(0)):
        np.testing.assert_array_equal(a, b)


def test_local_bucketed_launches_fewer_programs():
    before = _counters()
    _local_pushpull(25, n=10)
    mid = _counters()
    _local_pushpull(0, n=10)
    after = _counters()
    assert _delta(before, mid, "comm.collectives") == 1  # one small bucket
    assert _delta(mid, after, "comm.collectives") == 10  # one per key
    assert _delta(mid, after, "comm.bucket.count") == 0  # hatch = no buckets


def test_local_bucketed_pushpull_retry_with_aliased_outs():
    """A mid-bucket fault after some out-writes must replay on the
    ORIGINAL payloads: outs alias the pushed grads (the Trainer pushpull
    pattern), so the retry would otherwise re-merge already-merged
    values."""
    from mxnet_tpu.resilience import faults
    with engine.bucket_mb_scope(25):
        kv = mx.kv.create("device")
        keys = list(range(4))
        for k in keys:
            kv.init(k, nd.zeros((3,)))
        grads = [[nd.array(np.full(3, float(k + 1), np.float32))
                  for _ in range(2)] for k in keys]
        # error on the SECOND per-key fault check: key 0's outs (aliasing
        # its pushed replicas) are already overwritten when it fires
        with faults.inject("kvstore.push:error:2"):
            kv.pushpull(keys, grads, out=grads)
    for k in keys:
        for rep in grads[k]:
            np.testing.assert_array_equal(rep.asnumpy(),
                                          np.full(3, 2.0 * (k + 1)))


def test_trainer_step_bucketed_parity():
    """End-to-end Gluon training parity: bucketed vs per-param gradient
    sync produce bit-identical parameters after several steps."""
    def train(mb, steps=4):
        mx.random.seed(0)
        np.random.seed(0)
        with engine.bucket_mb_scope(mb):
            net = nn.HybridSequential()
            with net.name_scope():
                net.add(nn.Dense(16, activation="relu"), nn.Dense(8),
                        nn.Dense(2))
            net.initialize(mx.init.Xavier())
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               update_on_kvstore=True)
            x = nd.array(np.random.RandomState(1).randn(8, 10)
                         .astype(np.float32))
            y = nd.array(np.ones((8,), np.float32))
            loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
            for _ in range(steps):
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                tr.step(8)
            return [p.data().asnumpy()
                    for _, p in sorted(net.collect_params().items())]
    for a, b in zip(train(25), train(0)):
        np.testing.assert_array_equal(a, b)


def test_trainer_escape_hatch_env_restores_per_param(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COMM_BUCKET_MB", "0")
    before = _counters()
    net = nn.Dense(3, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       update_on_kvstore=True)
    with autograd.record():
        loss = net(nd.ones((2, 4))).sum()
    loss.backward()
    tr.step(2)
    after = _counters()
    assert _delta(before, after, "comm.bucket.count") == 0
    # per-key path: one launch per pushed parameter (weight + bias)
    assert _delta(before, after, "comm.collectives") == 2


# ===========================================================================
# dist kvstore (single-worker in-process; the allreduce path is identical,
# the cross-worker exchange short-circuits at num_workers == 1)
# ===========================================================================

def _dist_store():
    from mxnet_tpu.kvstore.kvstore_dist import KVStoreDist
    return KVStoreDist("dist_sync")


def _dist_pushpull(bucket_mb, n=6, shape=(4, 2), seed=0):
    with engine.bucket_mb_scope(bucket_mb):
        kv = _dist_store()
        rng = np.random.RandomState(seed)
        keys = list(range(n))
        for k in keys:
            kv.init(k, nd.zeros(shape))
        vals = [nd.array(rng.randn(*shape).astype(np.float32))
                for _ in keys]
        outs = [nd.zeros(shape) for _ in keys]
        kv.pushpull(keys, vals, out=outs)
        return [o.asnumpy() for o in outs]


def test_dist_kvstore_bucketed_parity():
    for a, b in zip(_dist_pushpull(25), _dist_pushpull(0)):
        np.testing.assert_array_equal(a, b)


def test_dist_bucketed_fewer_allreduces():
    before = _counters()
    _dist_pushpull(25, n=8)
    mid = _counters()
    _dist_pushpull(0, n=8)
    after = _counters()
    assert _delta(before, mid, "comm.collectives") == 1
    assert _delta(mid, after, "comm.collectives") == 8


def test_dist_bucketed_push_retries_per_bucket_with_key_context():
    """ISSUE 4 satellite: a failed bucketed push retries per-bucket and the
    error context names the member keys."""
    from mxnet_tpu.resilience import faults
    with engine.bucket_mb_scope(25):
        kv = _dist_store()
        keys = list(range(4))
        for k in keys:
            kv.init(k, nd.zeros((3,)))
        vals = [nd.array(np.full(3, float(k + 1), np.float32))
                for k in keys]
        before = _counters()
        with faults.inject("kvstore.push:error:1"):
            kv.push(keys, vals)
        after = _counters()
        assert _delta(before, after, "resilience.retries.kvstore.push") >= 1
        # the retry replayed the WHOLE bucket: every key holds its push
        for k in keys:
            out = nd.zeros((3,))
            kv.pull(k, out=out)
            np.testing.assert_array_equal(out.asnumpy(),
                                          np.full(3, float(k + 1)))


def test_dist_bucketed_push_exhaustion_names_bucket_keys():
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.errors import RetryExhausted
    with engine.bucket_mb_scope(25):
        kv = _dist_store()
        for k in range(3):
            kv.init(k, nd.zeros((2,)))
        with faults.inject("kvstore.push:error:*"):
            with pytest.raises(RetryExhausted) as ei:
                kv.push(list(range(3)),
                        [nd.array(np.ones(2, np.float32))] * 3)
        msg = str(ei.value)
        assert "keys=[0,1,2]" in msg  # bucket keys preserved in context


def test_dist_compression_stays_per_key():
    """2-bit compression keeps per-key residual state — it must bypass the
    bucketed path and stay bit-identical with bucketing on or off, through
    BOTH push+pull and the fused pushpull entry point."""
    def run(mb, via_pushpull):
        with engine.bucket_mb_scope(mb):
            kv = _dist_store()
            kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
            for k in range(3):
                kv.init(k, nd.zeros((4,)))
            vals = [nd.array(np.array([1.0, -1.0, 0.3, 0.0], np.float32))
                    for _ in range(3)]
            outs = [nd.zeros((4,)) for _ in range(3)]
            if via_pushpull:
                kv.pushpull(list(range(3)), vals, out=outs)
            else:
                kv.push(list(range(3)), vals)
                kv.pull(list(range(3)), out=outs)
            return [o.asnumpy() for o in outs]
    for via_pushpull in (False, True):
        ref = run(0, via_pushpull)
        for a, b in zip(run(25, via_pushpull), ref):
            np.testing.assert_array_equal(a, b)
        # quantized: 1.0 -> 0.5, -1.0 -> -0.5, 0.3 below threshold -> 0
        np.testing.assert_array_equal(ref[0], [0.5, -0.5, 0.0, 0.0])


def test_bucketed_pushpull_keeps_pull_fault_site():
    """The fused pushpull must not silently drop the kvstore.pull
    fault-injection site — a pull fault fires and is recovered."""
    from mxnet_tpu.resilience import faults
    with engine.bucket_mb_scope(25):
        kv = mx.kv.create("device")
        keys = list(range(3))
        for k in keys:
            kv.init(k, nd.zeros((4,)))
        vals = [nd.array(np.full(4, float(k + 1), np.float32))
                for k in keys]
        outs = [nd.zeros((4,)) for _ in keys]
        before = _counters()
        with faults.inject("kvstore.pull:error:1"):
            kv.pushpull(keys, vals, out=outs)
        after = _counters()
        assert _delta(before, after, "resilience.faults_injected") == 1
        for k in keys:
            np.testing.assert_array_equal(outs[k].asnumpy(),
                                          np.full(4, float(k + 1)))


# ===========================================================================
# acceptance: collectives_per_step drops below the parameter count for a
# resnet18-sized gradient set
# ===========================================================================

def _resnet18_grad_shapes():
    """The bench's 62-tensor gradient set — imported, not duplicated, so
    bench and acceptance test always sync the same model."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from bench import resnet18_grad_shapes
    return resnet18_grad_shapes()


def test_resnet18_sized_sync_collectives_below_param_count():
    shapes = _resnet18_grad_shapes()
    assert len(shapes) == 62

    def run(mb):
        with engine.bucket_mb_scope(mb):
            kv = mx.kv.create("device")
            keys = list(range(len(shapes)))
            for k, s in zip(keys, shapes):
                kv.init(k, nd.zeros(s))
            grads = [nd.array(np.ones(s, np.float32)) for s in shapes]
            outs = [nd.zeros(s) for s in shapes]
            before = _counters()
            kv.pushpull(keys, grads, out=outs)
            after = _counters()
            return (_delta(before, after, "comm.collectives"),
                    [o.asnumpy() for o in outs])

    n_bucketed, r_bucketed = run(25)
    n_flat, r_flat = run(0)
    assert n_bucketed < len(shapes), \
        "bucketed sync must launch fewer collectives than parameters"
    assert n_bucketed <= 4   # ~46.8 MB of grads / 25 MB cap
    assert n_flat == len(shapes)
    for a, b in zip(r_bucketed, r_flat):
        np.testing.assert_array_equal(a, b)


# ===========================================================================
# eager collectives
# ===========================================================================

def test_eager_all_reduce_multi_matches_per_tensor():
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel.mesh import local_mesh
    mesh = local_mesh()
    n = mesh.devices.size
    rng = np.random.RandomState(0)
    arrs = [jnp.asarray(rng.randn(n * k, 3).astype(np.float32))
            for k in (1, 2, 3)]
    before = _counters()
    fused = collectives.all_reduce_multi(arrs, mesh=mesh)
    mid = _counters()
    with engine.bucket_mb_scope(0):
        per_tensor = collectives.all_reduce_multi(arrs, mesh=mesh)
    after = _counters()
    for f, p in zip(fused, per_tensor):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(p))
    for a, r in zip(arrs, fused):
        np.testing.assert_allclose(
            np.asarray(r), np.asarray(a).reshape(n, -1).sum(0)
            .reshape(r.shape), rtol=1e-6)
    assert _delta(before, mid, "comm.collectives") == 1
    assert _delta(mid, after, "comm.collectives") == len(arrs)


def test_eager_all_reduce_multi_zero_size_array():
    """Zero-size arrays skip the bucketer but must still get a (empty)
    result slot, matching the per-tensor path's output shape."""
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel.mesh import local_mesh
    mesh = local_mesh()
    n = mesh.devices.size
    arrs = [jnp.zeros((0, 4), jnp.float32), jnp.ones((n * 2, 3))]
    out = collectives.all_reduce_multi(arrs, mesh=mesh)
    assert out[0] is not None and tuple(out[0].shape) == (0, 4)
    np.testing.assert_allclose(np.asarray(out[1]), np.full((2, 3), float(n)))


def test_eager_all_reduce_multi_pads_undivisible_dim():
    """Pad-and-slice: a leading dim that does not divide the axis size is
    zero-padded to the next multiple inside the fused program; the result
    has ceil(m/n) rows (the last sums fewer real contributions) instead
    of raising."""
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel.mesh import local_mesh
    mesh = local_mesh()
    n = mesh.devices.size
    if n == 1:
        pytest.skip("needs a >1-device mesh")
    m = n + 1
    x = jnp.asarray(np.arange(m * 3, dtype=np.float32).reshape(m, 3))
    (out,) = collectives.all_reduce_multi([x], mesh=mesh)
    k = -(-m // n)
    assert tuple(out.shape) == (k, 3)
    padded = np.zeros((k * n, 3), np.float32)
    padded[:m] = np.asarray(x)
    np.testing.assert_allclose(
        np.asarray(out), padded.reshape(n, -1).sum(0).reshape(k, 3))


def test_eager_all_reduce_multi_mixed_odd_even_parity():
    """Odd- and even-leading-dim arrays in one call agree between the
    bucketed fused path and the per-tensor escape hatch (which routes odd
    arrays through the same padded program)."""
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel.mesh import local_mesh
    mesh = local_mesh()
    n = mesh.devices.size
    if n == 1:
        pytest.skip("needs a >1-device mesh")
    rng = np.random.RandomState(3)
    arrs = [jnp.asarray(rng.randn(n + 1, 2).astype(np.float32)),
            jnp.asarray(rng.randn(2 * n, 3).astype(np.float32)),
            jnp.asarray(rng.randn(2 * n + 1).astype(np.float32))]
    fused = collectives.all_reduce_multi(arrs, mesh=mesh)
    with engine.bucket_mb_scope(0):
        per_tensor = collectives.all_reduce_multi(arrs, mesh=mesh)
    for f, p in zip(fused, per_tensor):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(p))
    for a, r in zip(arrs, fused):
        m = a.shape[0]
        k = -(-m // n)
        rest = tuple(a.shape[1:])
        padded = np.zeros((k * n,) + rest, np.float32)
        padded[:m] = np.asarray(a)
        np.testing.assert_allclose(
            np.asarray(r), padded.reshape(n, -1).sum(0).reshape(r.shape),
            rtol=1e-6)


def test_psum_bucketed_inside_shard_map():
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel.mesh import local_mesh
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    mesh = local_mesh()
    ax = mesh.axis_names[0]
    n = mesh.devices.size
    xs = [jnp.ones((n, 3)), jnp.ones((n, 5)), jnp.ones((n, 2))]

    def f(a, b, c):
        return tuple(collectives.psum_bucketed([a, b, c], ax))

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P(ax), out_specs=P()))(
        *xs)
    for x, o in zip(xs, out):
        np.testing.assert_allclose(np.asarray(o),
                                   np.full((1, x.shape[1]), float(n)))


# ===========================================================================
# fused train-step paths: bucket_mb knob parity + retrace guard routing
# ===========================================================================

def _fused_train(bucket_mb, steps=3):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    step = gluon.FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                tr, bucket_mb=bucket_mb)
    x = nd.array(np.random.RandomState(1).randn(8, 10).astype(np.float32))
    y = nd.array(np.ones((8,), np.float32))
    losses = [float(step(x, y).asnumpy()) for _ in range(steps)]
    return losses, [p.data().asnumpy()
                    for _, p in sorted(net.collect_params().items())]


def test_fused_step_bucket_knob_parity():
    (la, pa) = _fused_train(25)
    (lb, pb) = _fused_train(None)
    (lc, pc) = _fused_train(0)
    assert la == lb == lc
    for a, b, c in zip(pa, pb, pc):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)


def test_sharded_train_step_bucket_knob_parity():
    from mxnet_tpu.parallel import ShardedTrainStep
    from mxnet_tpu.parallel.mesh import local_mesh
    mesh = local_mesh()

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    def run(bucket_mb):
        params = {"w": jnp.ones((5, 3)), "b": jnp.zeros((3,))}
        st = ShardedTrainStep(loss_fn, params, mesh, optimizer="adamw",
                              lr=0.1, bucket_mb=bucket_mb)
        p, s = st.init()
        size = mesh.devices.size
        batch = {"x": jnp.arange(5.0 * 4 * size).reshape(4 * size, 5),
                 "y": jnp.ones((4 * size, 3))}
        for i in range(3):
            p, s, loss = st(p, s, batch, i)
        return np.asarray(p["w"]), float(loss)

    (wa, la), (wb, lb), (wc, lc) = run(25), run(None), run(0)
    np.testing.assert_array_equal(wa, wb)
    np.testing.assert_array_equal(wa, wc)
    assert la == lb == lc


def test_fused_step_retrace_routes_through_guard(monkeypatch):
    from mxnet_tpu.analysis import guard
    monkeypatch.setenv("MXNET_TPU_TRACE_GUARD_RETRACE_LIMIT", "1")
    prev = guard.set_mode("raise")
    try:
        mx.random.seed(0)
        net = nn.Dense(1, in_units=4)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        step = gluon.FusedTrainStep(net, gluon.loss.L2Loss(), tr)
        before = _counters()
        step(nd.ones((4, 4)), nd.ones((4, 1)))
        with pytest.raises(guard.TraceGuardError, match="FusedTrainStep"):
            step(nd.ones((6, 4)), nd.ones((6, 1)))
        after = _counters()
        assert _delta(before, after, "fused_step.retrace") == 1
        assert _delta(before, after, "analysis.guard.retrace") == 1
    finally:
        guard.set_mode(prev)


def test_sharded_train_step_retrace_routes_through_guard(monkeypatch):
    from mxnet_tpu.analysis import guard
    from mxnet_tpu.parallel import ShardedTrainStep
    from mxnet_tpu.parallel.mesh import local_mesh
    monkeypatch.setenv("MXNET_TPU_TRACE_GUARD_RETRACE_LIMIT", "1")
    mesh = local_mesh()
    size = mesh.devices.size

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"]) ** 2)

    params = {"w": jnp.ones((5, 3))}
    st = ShardedTrainStep(loss_fn, params, mesh, optimizer="sgd", lr=0.1)
    p, s = st.init()
    p, s, _ = st(p, s, {"x": jnp.ones((4 * size, 5))}, 0)
    prev = guard.set_mode("raise")
    try:
        before = _counters()
        with pytest.raises(guard.TraceGuardError, match="ShardedTrainStep"):
            st(p, s, {"x": jnp.ones((8 * size, 5))}, 1)
        after = _counters()
        assert _delta(before, after, "train_step.retrace") == 1
        assert _delta(before, after, "analysis.guard.retrace") == 1
    finally:
        guard.set_mode(prev)


# ===========================================================================
# dataloader satellite: batched device→host conversion
# ===========================================================================

def test_mp_batchify_single_sync():
    from mxnet_tpu.gluon.data.dataloader import default_mp_batchify_fn
    rng = np.random.RandomState(0)
    samples_np = [rng.randn(3, 4).astype(np.float32) for _ in range(8)]
    samples = [nd.array(a) for a in samples_np]
    before = _counters()
    out = default_mp_batchify_fn(samples)
    after = _counters()
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, np.stack(samples_np, axis=0))
    # ONE device→host sync for the whole batch, 7 saved
    assert _delta(before, after, "ndarray.sync.asnumpy") == 1
    assert _delta(before, after,
                  "dataloader.batchify.syncs_saved") == len(samples) - 1


def test_mp_batchify_nested_and_numpy_paths_unchanged():
    from mxnet_tpu.gluon.data.dataloader import default_mp_batchify_fn
    rng = np.random.RandomState(0)
    pairs = [(nd.array(rng.randn(2).astype(np.float32)), float(i))
             for i in range(4)]
    data, labels = default_mp_batchify_fn(pairs)
    assert data.shape == (4, 2)
    np.testing.assert_array_equal(labels, np.arange(4.0))


# ===========================================================================
# tooling: parse_log --comm
# ===========================================================================

def test_parse_log_comm_table(tmp_path):
    with engine.bucket_mb_scope(25):
        kv = mx.kv.create("device")
        keys = list(range(6))
        for k in keys:
            kv.init(k, nd.zeros((50,)))
        kv.pushpull(keys, [nd.array(np.ones(50, np.float32))
                           for _ in keys],
                    out=[nd.zeros((50,)) for _ in keys])
    dump = str(tmp_path / "telemetry.json")
    telemetry.dump(dump)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--comm"],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert "comm.collectives" in proc.stdout
    assert "comm.bucket.count" in proc.stdout
    assert "avg_bucket_kb" in proc.stdout
    # csv mode too
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--comm", "--format", "csv"],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.startswith("metric,value")


def test_bucket_spans_visible_in_trace_dump(tmp_path):
    """Per-bucket spans land in the chrome-trace dump — the overlap story
    is inspectable."""
    with engine.bucket_mb_scope(25):
        kv = mx.kv.create("device")
        for k in range(4):
            kv.init(k, nd.zeros((10,)))
        kv.pushpull(list(range(4)),
                    [nd.array(np.ones(10, np.float32)) for _ in range(4)],
                    out=[nd.zeros((10,)) for _ in range(4)])
    path = str(tmp_path / "trace.json")
    telemetry.dump_trace(path)
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    assert any(str(e.get("name", "")).startswith("comm.bucket[")
               for e in events)
