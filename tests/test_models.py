"""Transformer model-family tests (llama/bert) incl. sharded train step.

Mirrors the reference's test style (tests/python/unittest/test_gluon.py
forward-shape checks + tests/nightly numeric training smoke), extended with
mesh-sharded step validation the reference could not express.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.models import (LlamaConfig, llama_init, llama_forward,
                              llama_loss, BertConfig, bert_init,
                              bert_forward, bert_mlm_loss)
from mxnet_tpu.models.llama import (CONFIGS, init_kv_cache,
                                    llama_decode_step)
from mxnet_tpu.parallel.mesh import create_mesh
from mxnet_tpu.parallel.sharding import LLAMA_RULES, BERT_RULES
from mxnet_tpu.parallel.train_step import ShardedTrainStep


CFG = CONFIGS["llama_tiny"]


def test_llama_forward_shape_dtype():
    params = llama_init(jax.random.PRNGKey(0), CFG)
    toks = jnp.zeros((2, 16), jnp.int32)
    logits = llama_forward(params, toks, CFG)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32


def test_llama_embed_onehot_matches_gather():
    """embed_onehot (the sharded-table path, llama3_8b + dryrun) must be
    numerically identical to the default gather lookup."""
    import dataclasses
    params = llama_init(jax.random.PRNGKey(0), CFG)
    toks = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                              CFG.vocab_size)
    ref = llama_forward(params, toks, CFG)
    oh = llama_forward(params, toks,
                       dataclasses.replace(CFG, embed_onehot=True))
    # the two lookups are bit-exact (one-hot rows select single table rows)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(oh),
                               rtol=1e-6, atol=1e-6)


def test_llama_loss_decreases_training():
    params = llama_init(jax.random.PRNGKey(0), CFG)
    key = jax.random.PRNGKey(1)
    toks = jax.random.randint(key, (4, 33), 0, CFG.vocab_size)

    loss_fn = lambda p, b: llama_loss(p, b, CFG)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    first = None
    for _ in range(8):
        loss, g = grad_fn(params, {"tokens": toks})
        if first is None:
            first = float(loss)
        params = jax.tree_util.tree_map(lambda p, g_: p - 0.05 * g_.astype(p.dtype),
                                        params, g)
    assert float(loss) < first


def test_llama_decode_matches_forward():
    cfg = CFG
    params = llama_init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                              cfg.vocab_size)
    full = llama_forward(params, toks, cfg)        # (2, 8, V)
    cache = init_kv_cache(cfg, batch=2, max_len=8)
    step = jax.jit(lambda p, c, t, pos: llama_decode_step(p, c, t, pos, cfg))
    for i in range(8):
        logits, cache = step(params, cache, toks[:, i],
                             jnp.asarray(i, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, -1]),
                               rtol=0.15, atol=0.15)


def test_llama_sharded_train_step_tp_fsdp():
    mesh = create_mesh(data=2, fsdp=2, model=2)
    params = llama_init(jax.random.PRNGKey(0), CFG)
    step = ShardedTrainStep(lambda p, b: llama_loss(p, b, CFG), params, mesh,
                            rules=LLAMA_RULES, optimizer="adamw", lr=1e-2)
    p, s = step.init()
    # wq got a model-sharded output dim
    wq = p["layers"]["0"]["attn"]["wq"]
    assert "model" in str(wq.sharding.spec)
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                              CFG.vocab_size)
    losses = []
    for _ in range(4):
        p, s, loss = step(p, s, {"tokens": toks})
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_forward_and_mlm_loss():
    cfg = BertConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                     hidden_dim=128, max_seq_len=64)
    params = bert_init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                              cfg.vocab_size)
    h = bert_forward(params, toks, cfg)
    assert h.shape == (2, 32, cfg.dim)
    batch = {"tokens": toks, "targets": toks,
             "mask": jnp.ones_like(toks)}
    loss = bert_mlm_loss(params, batch, cfg)
    assert np.isfinite(float(loss))


def test_bert_sharded_step():
    cfg = BertConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                     hidden_dim=128, max_seq_len=64)
    mesh = create_mesh(data=2, model=2)
    params = bert_init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                              cfg.vocab_size)
    batch = {"tokens": toks, "targets": toks, "mask": jnp.ones_like(toks)}
    step = ShardedTrainStep(lambda p, b: bert_mlm_loss(p, b, cfg), params,
                            mesh, rules=BERT_RULES, optimizer="adam",
                            lr=1e-2)
    p, s = step.init()
    losses = []
    for _ in range(3):
        p, s, loss = step(p, s, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
