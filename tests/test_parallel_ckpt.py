"""Sharded checkpoint save/restore (reference analog: SURVEY §5.4 sharded
native format for pod-scale models)."""
import jax
import jax.numpy as jnp
import numpy as np

from mxnet_tpu.models.llama import CONFIGS, llama_init
from mxnet_tpu.parallel.mesh import create_mesh
from mxnet_tpu.parallel.sharding import LLAMA_RULES, shard_pytree
from mxnet_tpu.parallel import checkpoint as ckpt


def test_sharded_save_restore_roundtrip(tmp_path):
    cfg = CONFIGS["llama_tiny"]
    mesh = create_mesh(data=2, fsdp=2, model=2)
    params = shard_pytree(llama_init(jax.random.PRNGKey(0), cfg),
                          LLAMA_RULES, mesh)
    path = str(tmp_path / "ckpt")
    ckpt.save_sharded(path, params, step=3)
    assert ckpt.latest_step(path) == 3

    restored = ckpt.restore_sharded(path, mesh=mesh, rules=LLAMA_RULES)
    ref_wq = np.asarray(params["layers"]["0"]["attn"]["wq"])
    got_wq = restored["layers"]["0"]["attn"]["wq"]
    np.testing.assert_array_equal(np.asarray(got_wq), ref_wq)
    # restored with the requested sharding
    assert "model" in str(got_wq.sharding.spec)


def test_train_state_roundtrip(tmp_path):
    mesh = create_mesh(data=2)
    params = {"w": jnp.ones((4, 4))}
    opt = {"mom": jnp.zeros((4, 4))}
    path = str(tmp_path / "state")
    ckpt.save_train_state(path, params, opt, step=7)
    p, s, step = ckpt.restore_train_state(path, mesh=mesh)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(p["w"]), np.ones((4, 4)))
