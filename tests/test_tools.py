"""tools/ CLI tests (im2rec, parse_log, launch covered in test_dist).

reference idiom: the reference ships these as operator-facing tools; tests
drive the CLIs end-to-end on synthetic data.
"""
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_dataset(root, classes=2, per_class=3):
    from PIL import Image
    for c in range(classes):
        d = os.path.join(root, "class%d" % c)
        os.makedirs(d)
        for i in range(per_class):
            arr = np.random.randint(0, 255, (10, 12, 3), np.uint8)
            Image.fromarray(arr).save(os.path.join(d, "img%d.jpg" % i))


def test_im2rec_list_and_pack(tmp_path):
    root = tmp_path / "imgs"
    root.mkdir()
    _make_dataset(str(root))
    prefix = str(tmp_path / "data")
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "im2rec.py"),
                        prefix, str(root), "--list", "--recursive"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert os.path.isfile(prefix + ".lst")
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "im2rec.py"),
                        prefix, str(root), "--resize", "8"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert os.path.isfile(prefix + ".rec")
    assert os.path.isfile(prefix + ".idx")

    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(rec.keys) == 6
    header, payload = recordio.unpack(rec.read_idx(rec.keys[0]))
    assert payload[:2] == b"\xff\xd8"  # JPEG SOI
    assert float(np.asarray(header.label)) in (0.0, 1.0)
    # decodes back through the image module
    from mxnet_tpu import image
    img = image.imdecode(payload, to_ndarray=False)
    assert img.shape[2] == 3 and min(img.shape[:2]) == 8


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Batch [20] Speed: 100 samples/sec accuracy=0.5\n"
        "INFO Epoch[0] Train-accuracy=0.61\n"
        "INFO Epoch[0] Time cost=12.5\n"
        "INFO Epoch[0] Validation-accuracy=0.58\n"
        "INFO Epoch[1] Train-accuracy=0.75\n"
        "INFO Epoch[1] Time cost=11.0\n"
        "INFO Epoch[1] Validation-accuracy=0.71\n")
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "parse_log.py"),
                        str(log)], capture_output=True, text=True,
                       timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| 0 | 0.61 | 0.58 | 12.5 |" in r.stdout
    assert "| 1 | 0.75 | 0.71 | 11.0 |" in r.stdout


def test_parse_log_requests(tmp_path):
    """--requests: per-request ttft/queue/prefill/decode/recovery table
    from a /requests dump or a bare request_traces() list (ISSUE 12)."""
    import json
    payload = {
        "rank": 0, "trace_id": "t0",
        "requests": [
            {"request_id": "abc123", "outcome": "completed",
             "wall_ms": 100.0, "accounted_ms": 99.0, "ttft_ms": 40.5,
             "tokens": 8, "requeues": 0,
             "phases_ms": {"queue": 10.0, "prefill": 30.0,
                           "decode": 59.0}},
            {"request_id": "def456", "outcome": "deadline",
             "wall_ms": 50.0, "accounted_ms": 50.0, "tokens": 2,
             "requeues": 1,
             "phases_ms": {"queue": 5.0, "prefill": 20.0, "decode": 15.0,
                           "recovery": 10.0}},
        ],
    }
    dump = tmp_path / "requests.json"
    dump.write_text(json.dumps(payload))
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "parse_log.py"),
                        "--requests", str(dump), "--format", "csv"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0] == ("request,outcome,wall_ms,queue_ms,prefill_ms,"
                        "decode_ms,recovery_ms,ttft_ms,tokens,requeues,"
                        "acct_pct")
    assert "abc123,completed,100.0,10.0,30.0,59.0,0.0,40.5,8,0,99.0" \
        in lines
    assert "def456,deadline,50.0,5.0,20.0,15.0,10.0,,2,1,100.0" in lines
    # a bare telemetry.request_traces() list parses the same way
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(payload["requests"]))
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "parse_log.py"),
                        "--requests", str(bare), "--format", "csv"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "abc123" in r.stdout and "def456" in r.stdout


def test_parse_log_overlap(tmp_path):
    """--overlap: per-step compute/collective/host/idle decomposition +
    overlap fraction from a chrome trace dump (ISSUE 12). The partition
    must sum to the step time exactly."""
    import json
    us = 1e6
    dump = tmp_path / "trace.json"
    dump.write_text(json.dumps({"traceEvents": [
        {"name": "fused_step", "cat": "step", "ph": "X",
         "ts": 0.0, "dur": 1.0 * us, "pid": 0, "tid": 1},
        {"name": "comm.bucket[0..5]", "cat": "comm", "ph": "X",
         "ts": 0.1 * us, "dur": 0.2 * us, "pid": 0, "tid": 1},
        {"name": "checkpoint", "cat": "resilience", "ph": "X",
         "ts": 0.5 * us, "dur": 0.1 * us, "pid": 0, "tid": 1},
        {"name": "x", "cat": "counter", "ph": "C", "ts": 0, "pid": 0},
    ]}))
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "parse_log.py"),
                        "--overlap", str(dump), "--format", "csv"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0] == ("step,site,step_ms,compute_ms,collective_ms,"
                        "host_ms,idle_ms,comm_n,overlap_frac")
    row = lines[1].split(",")
    assert row[1] == "fused_step"
    step, comp, coll, host, idle = map(float, (row[2], row[3], row[4],
                                               row[5], row[6]))
    assert (step, coll, host, idle) == (1000.0, 200.0, 100.0, 0.0)
    assert comp + coll + host + idle == step
    # comm phase [0.1, 1.0]: 0.7 of 0.9 s off the collective path
    assert abs(float(row[8]) - 0.7 / 0.9) < 1e-3
    assert lines[-1].startswith("TOTAL,")
    # --site filters step spans by name
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "parse_log.py"),
                        "--overlap", "--site", "serve.step", str(dump),
                        "--format", "csv"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    assert "no step spans" in r.stderr


def test_parse_log_kernels(tmp_path):
    """--kernels: Pallas dispatch/fallback table from a telemetry dump,
    and the bytes ratio from a BENCH=fused_* row (ISSUE 10)."""
    import json
    dump = tmp_path / "telemetry.json"
    dump.write_text(json.dumps({
        "counters": {
            "ops.pallas.dispatch": 7,
            "ops.pallas.dispatch.cbr_train_bwd": 2,
            "ops.pallas.dispatch.flat_adam": 5,
            "ops.pallas.fallback": 1,
            "ops.pallas.fallback.shape": 1,
            "ops.pallas.fallback.cbr_train_bwd.shape": 1,
        },
        "gauges": {"fused_step.pallas_kernels": {"value": 32, "max": 32}},
        "histograms": {"opt.fused_update_ms":
                       {"count": 4, "sum": 8.0, "max": 3.0}},
    }))
    cmd = [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
           "--kernels", str(dump)]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| dispatch | flat_adam | 5 |" in r.stdout
    assert "| fallback | cbr_train_bwd.shape | 1 |" in r.stdout
    assert "| program | fused_step.pallas_kernels | 32 |" in r.stdout
    assert "| latency | fused_update_ms_avg | 2.0 |" in r.stdout

    row = tmp_path / "bench_row.json"
    row.write_text(json.dumps({
        "metric": "fused_cbr_bwd_cpu_img_per_sec", "value": 5489.0,
        "vs_baseline": 1.1, "bytes_fused": 438000.0,
        "bytes_composed": 497000.0}))
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "parse_log.py"),
                        "--kernels", str(row)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "| bench | bytes_ratio | 0.8813 |" in r.stdout
