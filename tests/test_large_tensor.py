"""Large-tensor (>2^31 elements) and int64-index coverage.

reference: tests/nightly/test_large_array.py — the guarantee that ops
survive tensors whose element count (or flat index) exceeds int32. The
reference needs a 64-bit build flag (MXNET_INT64_TENSOR_SIZE); here XLA
indexes with 64-bit arithmetic internally, and these tests pin that the
framework surface (creation, reduction, slicing, gather with int64
indices, argmax) stays correct past the 2^31 boundary. int8 payloads keep
the footprint at ~2.2 GB so the CPU suite can afford one such tensor;
marked slow. The >2^31 index paths run inside
`mx.util.large_tensor_scope()` — the analog of the reference's opt-in
MXNET_INT64_TENSOR_SIZE build (64-bit index arithmetic on demand,
without flipping jax's global default dtypes).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

INT32_MAX = 2 ** 31


@pytest.mark.slow
def test_over_int32_elements_reduce_slice_index():
    with mx.util.large_tensor_scope():
        _over_int32_body()


def _over_int32_body():
    n = INT32_MAX + 128               # 2,147,483,776 elements, int8
    x = nd.zeros((n,), dtype="int8")
    assert x.size == n and x.size > INT32_MAX
    # writes above the int32 boundary land where they should
    x[n - 1] = 7
    x[INT32_MAX + 5] = 3
    total = int(x.sum(axis=0).asnumpy())     # int8 accum would overflow; op
    assert total == 10                       # promotes internally
    # slice across the boundary
    tail = x[INT32_MAX:INT32_MAX + 8]
    assert tail.shape == (8,)
    assert int(tail.asnumpy()[5]) == 3
    # argmax must report a position > int32
    am = int(x.argmax(axis=0).asnumpy())
    assert am == n - 1, am          # 7 at n-1 is the unique maximum
    del x


@pytest.mark.slow
def test_int64_index_gather_roundtrip():
    with mx.util.large_tensor_scope():
        n = INT32_MAX + 64
        x = nd.zeros((n,), dtype="int8")
        x[n - 2] = 9
        idx = nd.array(onp.array([0, INT32_MAX + 1, n - 2], dtype="int64"),
                       dtype="int64")
        got = nd.take(x, idx).asnumpy()
        onp.testing.assert_array_equal(got, [0, 0, 9])
        del x
        # scatter_nd writes past the boundary land exactly
        snd = nd.scatter_nd(nd.array(onp.array([5], "int8"), dtype="int8"),
                            nd.array(onp.array([[n - 3]], "int64"),
                                     dtype="int64"), shape=(n,))
        assert int(snd[n - 3].asnumpy()) == 5
        assert int(snd[n - 4].asnumpy()) == 0


def test_int64_indices_small_scale():
    """int64 index dtype flows through take/gather_nd/one_hot at any
    scale (the nightly's cheap invariant)."""
    x = nd.array(onp.arange(12.0, dtype="float32").reshape(3, 4))
    idx = nd.array(onp.array([2, 0], dtype="int64"), dtype="int64")
    onp.testing.assert_array_equal(nd.take(x, idx, axis=0).asnumpy(),
                                   x.asnumpy()[[2, 0]])
    # mx gather_nd convention: indices[d, i] = coordinate in dim d of
    # point i -> points (0,1) and (2,3)
    gidx = nd.array(onp.array([[0, 2], [1, 3]], dtype="int64"),
                    dtype="int64")
    got = nd.gather_nd(x, gidx).asnumpy()
    onp.testing.assert_array_equal(got, [x.asnumpy()[0, 1],
                                         x.asnumpy()[2, 3]])
    oh = mx.npx.one_hot(nd.array(onp.array([1, 3], "int64"),
                                 dtype="int64"), 4)
    assert oh.shape == (2, 4)
