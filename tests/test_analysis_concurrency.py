"""tracelint concurrency analysis: the static lock model (analysis.locks),
the TPU009 lock-order-inversion / TPU010 blocking-under-lock /
TPU006-v2 guarded-state rules, the project-wide lock-order graph, and the
runtime lock-order guard (analysis.lockguard) with its env gating."""
import ast
import os
import subprocess
import sys
import textwrap
import threading
import warnings

import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import LockOrderError, Severity, check_source
from mxnet_tpu.analysis import locks as locksmod
from mxnet_tpu.analysis import lockguard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src, rules=None):
    return check_source(textwrap.dedent(src), filename="fixture.py",
                        rules=rules)


def only(findings, code):
    return [f for f in findings if f.code == code]


def _facts(src):
    tree = ast.parse(textwrap.dedent(src))
    return locksmod.module_lock_facts(tree)


# ===========================================================================
# static lock model
# ===========================================================================
def test_lock_model_discovers_module_and_class_locks():
    model, facts = _facts("""
    import threading
    _LOCK = threading.Lock()
    _COND = threading.Condition()
    class Pool:
        SHARED = threading.RLock()
        def __init__(self):
            self._lock = threading.Lock()
    """)
    assert set(model.module_locks) == {"_LOCK", "_COND"}
    assert model.class_locks["Pool"].keys() == {"_lock", "SHARED"}


def test_lock_model_sees_lockguard_factories():
    model, _ = _facts("""
    from mxnet_tpu.analysis import lockguard
    _L = lockguard.lock("telemetry.registry")
    class Q:
        def __init__(self):
            self._cond = lockguard.condition("serve.queue")
    """)
    assert "_L" in model.module_locks
    assert "_cond" in model.class_locks["Q"]


def test_fn_lock_facts_acquires_and_edges():
    _, facts = _facts("""
    import threading
    A = threading.Lock()
    B = threading.Lock()
    def f():
        with A:
            with B:
                pass
    """)
    f = facts["f"]
    assert [a[0] for a in f.acquires] == ["A", "B"]
    assert [(e[0], e[1]) for e in f.edges] == [("A", "B")]


def test_fn_lock_facts_sequential_withs_make_no_edge():
    _, facts = _facts("""
    import threading
    A = threading.Lock()
    B = threading.Lock()
    def f():
        with A:
            pass
        with B:
            pass
    """)
    assert facts["f"].edges == []


def test_fn_lock_facts_bare_acquire_release_tracks_held():
    _, facts = _facts("""
    import threading
    A = threading.Lock()
    B = threading.Lock()
    def f():
        A.acquire()
        with B:
            pass
        A.release()
        with B:
            pass
    """)
    # only the first `with B` runs under A
    assert [(e[0], e[1]) for e in facts["f"].edges] == [("A", "B")]


def test_find_cycles_reports_two_lock_inversion_once():
    edges = [("A", "B", {"f": 1}), ("B", "A", {"f": 2}),
             ("B", "A", {"f": 3})]
    cycles = locksmod.find_cycles(edges)
    assert len(cycles) == 1
    assert {(a, b) for a, b, _ in cycles[0]} == {("A", "B"), ("B", "A")}


def test_find_cycles_three_lock_ring_and_acyclic_clean():
    ring = [("A", "B", None), ("B", "C", None), ("C", "A", None)]
    assert len(locksmod.find_cycles(ring)) == 1
    dag = [("A", "B", None), ("A", "C", None), ("B", "C", None)]
    assert locksmod.find_cycles(dag) == []


def test_classify_blocking_kinds():
    def kind(expr):
        call = ast.parse(expr, mode="eval").body
        got = locksmod.classify_blocking(call)
        return got and got[0]
    assert kind("time.sleep(1)") == "sleep"
    assert kind("jax.lax.psum(x, 'dp')") == "collective"
    assert kind("x.asnumpy()") == "host_sync"
    assert kind("urllib.request.urlopen(u)") == "http"
    assert kind("subprocess.run(cmd)") == "subprocess"
    assert kind("self._queue.get()") == "queue"
    assert kind("self._queue.get(timeout=1)") is None
    assert kind("math.sqrt(2)") is None


# ===========================================================================
# TPU009 — lock-order inversion
# ===========================================================================
_INVERSION = """
import threading
A = threading.Lock()
B = threading.Lock()

def take_ab():
    with A:
        with B:
            pass

def take_ba():
    with B:
        with A:
            pass
"""


def test_tpu009_reports_both_chains_with_lines():
    hits = only(lint(_INVERSION), "TPU009")
    assert len(hits) == 1
    h = hits[0]
    assert h.severity == Severity.ERROR
    # both acquisition chains, each with file:line
    assert "take_ab() acquires B at fixture.py:8" in h.message
    assert "take_ba() acquires A at fixture.py:13" in h.message
    assert "holding A" in h.message and "holding B" in h.message


def test_tpu009_consistent_hierarchy_clean():
    f = lint("""
    import threading
    A = threading.Lock()
    B = threading.Lock()
    def f():
        with A:
            with B:
                pass
    def g():
        with A:
            with B:
                pass
    """)
    assert not only(f, "TPU009")


def test_tpu009_instance_lock_inversion_across_methods():
    f = lint("""
    import threading
    class Pool:
        def __init__(self):
            self._alloc = threading.Lock()
            self._index = threading.Lock()
        def grow(self):
            with self._alloc:
                with self._index:
                    pass
        def shrink(self):
            with self._index:
                with self._alloc:
                    pass
    """)
    hits = only(f, "TPU009")
    assert len(hits) == 1
    assert "Pool._alloc" in hits[0].message
    assert "Pool._index" in hits[0].message


def test_tpu009_suppressible():
    src = _INVERSION.replace("        with B:\n",
                             "        with B:  # tpu-lint: disable=TPU009\n",
                             1)
    assert not only(lint(src), "TPU009")


def test_tpu009_cross_module_inversion(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text(textwrap.dedent("""
        import threading
        from .b import grab_b
        LOCK_A = threading.Lock()
        def forward():
            with LOCK_A:
                grab_b()
    """))
    (pkg / "b.py").write_text(textwrap.dedent("""
        import threading
        from . import a
        LOCK_B = threading.Lock()
        def grab_b():
            with LOCK_B:
                pass
        def backward():
            with LOCK_B:
                with a.LOCK_A:
                    pass
    """))
    hits = [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU009"]
    assert len(hits) == 1
    assert "LOCK_A" in hits[0].message and "LOCK_B" in hits[0].message
    assert "grab_b" in hits[0].message  # the import-hop edge is named


# ===========================================================================
# TPU010 — blocking under lock
# ===========================================================================
def test_tpu010_flags_each_blocking_class():
    f = lint("""
    import queue
    import subprocess
    import threading
    import time
    from urllib.request import urlopen
    import jax
    L = threading.Lock()
    _Q = queue.Queue()
    def f(x):
        with L:
            time.sleep(0.5)
            y = x.asnumpy()
            jax.lax.psum(x, "dp")
            urlopen("http://example.com/cfg")
            subprocess.run(["ls"])
            item = _Q.get()
    """)
    hits = only(f, "TPU010")
    assert len(hits) == 6
    assert all(h.severity == Severity.WARNING for h in hits)
    assert all("holding L" in h.message for h in hits)


def test_tpu010_clean_when_blocking_is_outside_lock():
    f = lint("""
    import threading
    import time
    L = threading.Lock()
    def f():
        with L:
            n = 1
        time.sleep(0.5)
    """)
    assert not only(f, "TPU010")


def test_tpu010_queue_get_with_timeout_clean():
    f = lint("""
    import queue
    import threading
    L = threading.Lock()
    _Q = queue.Queue()
    def f():
        with L:
            return _Q.get(timeout=0.1)
    """)
    assert not only(f, "TPU010")


def test_tpu010_condition_wait_on_own_lock_exempt():
    # cond.wait() RELEASES the lock it guards — the canonical pattern
    f = lint("""
    import threading
    C = threading.Condition()
    def f():
        with C:
            C.wait()
    """)
    assert not only(f, "TPU010")


def test_tpu010_cross_function_same_module():
    f = lint("""
    import threading
    import time
    L = threading.Lock()
    def slow():
        time.sleep(1.0)
    def f():
        with L:
            slow()
    """)
    hits = only(f, "TPU010")
    assert len(hits) == 1
    assert "slow()" in hits[0].message and "holding L" in hits[0].message


def test_tpu010_cross_module_blocking(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "io_util.py").write_text(textwrap.dedent("""
        from urllib.request import urlopen
        def fetch(url):
            return urlopen(url).read()
    """))
    (pkg / "svc.py").write_text(textwrap.dedent("""
        import threading
        from .io_util import fetch
        _LOCK = threading.Lock()
        def refresh(url):
            with _LOCK:
                return fetch(url)
    """))
    hits = [f for f in analysis.lint_paths([str(pkg)])
            if f.code == "TPU010"]
    assert len(hits) == 1
    assert hits[0].file.endswith("svc.py")
    assert "fetch" in hits[0].message


# ===========================================================================
# TPU006 v2 — guarded-state inference
# ===========================================================================
def test_tpu006_infers_majority_lock_and_flags_minority():
    f = lint("""
    import threading
    L = threading.Lock()
    _STATE = {}
    def worker():
        with L:
            _STATE["a"] = 1
        with L:
            _STATE["b"] = 2
        _STATE["c"] = 3
    t = threading.Thread(target=worker)
    t.start()
    """)
    hits = only(f, "TPU006")
    assert len(hits) == 1
    assert "'L'" in hits[0].message
    assert "2 of 3" in hits[0].message


def test_tpu006_flags_wrong_lock_held():
    f = lint("""
    import threading
    L = threading.Lock()
    M = threading.Lock()
    _STATE = {}
    def worker():
        with L:
            _STATE["a"] = 1
        with L:
            _STATE["b"] = 2
        with M:
            _STATE["c"] = 3
    t = threading.Thread(target=worker)
    t.start()
    """)
    hits = only(f, "TPU006")
    assert len(hits) == 1
    assert "'L'" in hits[0].message and "M" in hits[0].message


def test_tpu006_instance_attr_inference():
    f = lint("""
    import threading
    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
        def put(self, x):
            with self._lock:
                self._items.append(x)
        def put2(self, x):
            with self._lock:
                self._items.append(x)
        def drop(self):
            self._items.clear()
        def run(self):
            self.put(1)
            self.drop()
    class Worker(threading.Thread):
        def __init__(self, pool):
            super().__init__()
            self.pool = pool
        def run(self):
            self.pool.run()
    """)
    hits = only(f, "TPU006")
    assert len(hits) == 1
    assert "_items" in hits[0].message and "_lock" in hits[0].message


def test_tpu006_all_sites_guarded_clean():
    f = lint("""
    import threading
    L = threading.Lock()
    _STATE = {}
    def worker():
        with L:
            _STATE["a"] = 1
        with L:
            _STATE["b"] = 2
    t = threading.Thread(target=worker)
    t.start()
    """)
    assert not only(f, "TPU006")


def test_tpu006_no_threads_clean():
    f = lint("""
    import threading
    L = threading.Lock()
    _STATE = {}
    def main():
        _STATE["a"] = 1
    """)
    assert not only(f, "TPU006")


# ===========================================================================
# runtime lock-order guard
# ===========================================================================
@pytest.fixture
def guard(request):
    mode = getattr(request, "param", "raise")
    prev = lockguard.set_mode(mode)
    lockguard.reset()
    yield lockguard
    lockguard.set_mode(prev)
    lockguard.reset()


def _counter(name):
    return mx.telemetry.snapshot()["counters"].get(name, 0)


def test_lockguard_two_thread_inversion_raises_with_both_stacks(guard):
    a = lockguard.lock("A")
    b = lockguard.lock("B")
    seeded = threading.Event()
    caught = []

    def t1():
        with a:
            with b:          # records edge A -> B
                pass
        seeded.set()

    def t2():
        seeded.wait(5)
        try:
            with b:
                with a:      # inverts it
                    pass
        except LockOrderError as e:
            caught.append(e)

    th1 = threading.Thread(target=t1, name="seeder")
    th2 = threading.Thread(target=t2, name="inverter")
    th1.start(); th1.join()
    th2.start(); th2.join()

    assert len(caught) == 1
    err = caught[0]
    assert err.edge == ("B", "A")
    assert err.this_thread == "inverter"
    assert err.this_chain == ["B"]
    assert err.other_thread == "seeder"
    assert err.other_chain == ["A"]
    assert err.this_stack and err.other_stack
    assert "--- this thread" in str(err)
    assert "--- first-observed order" in str(err)


def test_lockguard_counts_and_flight_event(guard):
    from mxnet_tpu.telemetry import flight
    before = _counter("analysis.guard.lock_order")
    a, b = lockguard.lock("ga"), lockguard.lock("gb")
    with a:
        with b:
            pass
    with pytest.raises(LockOrderError):
        with b:
            with a:
                pass
    assert _counter("analysis.guard.lock_order") == before + 1
    pending = list(flight._RECORDER._events)
    assert any(k == "lock_order_inversion" and "gb" in d
               for k, d, _ in pending)


@pytest.mark.parametrize("guard", ["warn"], indirect=True)
def test_lockguard_warn_mode_warns_once_per_edge(guard):
    a, b = lockguard.lock("wa"), lockguard.lock("wb")
    with a:
        with b:
            pass
    with warnings.catch_warnings(record=True) as seen:
        warnings.simplefilter("always")
        for _ in range(3):
            with b:
                with a:
                    pass
    msgs = [w for w in seen if "lock-order inversion" in str(w.message)]
    assert len(msgs) == 1


def test_lockguard_transitive_inversion(guard):
    a = lockguard.lock("ta")
    b = lockguard.lock("tb")
    c = lockguard.lock("tc")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    # c -> a closes the a -> b -> c ring
    with pytest.raises(LockOrderError):
        with c:
            with a:
                pass


def test_lockguard_rlock_reentrancy_is_not_an_inversion(guard):
    r = lockguard.rlock("rl")
    other = lockguard.lock("ol")
    with r:
        with other:
            with r:          # re-entry: no other -> rl edge learned
                pass
    with r:
        with other:          # would invert if re-entry had made an edge
            pass


def test_lockguard_condition_wait_notify_roundtrip(guard):
    cond = lockguard.condition("cv")
    ready = []

    def producer():
        with cond:
            ready.append(1)
            cond.notify()

    with cond:
        t = threading.Thread(target=producer)
        t.start()
        got = cond.wait_for(lambda: ready, timeout=5)
    t.join()
    assert got


def test_lockguard_factories_return_raw_primitives_when_off():
    prev = lockguard.set_mode("off")
    try:
        assert not lockguard.active()
        assert type(lockguard.lock("x")) is type(threading.Lock())
        assert isinstance(lockguard.condition("x"), threading.Condition)
    finally:
        lockguard.set_mode(prev)


_INERT_PROBE = """
import threading
from mxnet_tpu.analysis import lockguard
from mxnet_tpu.telemetry.metrics import Registry
from mxnet_tpu.serve.scheduler import RequestQueue
from mxnet_tpu.resilience.watchdog import Watchdog

assert not lockguard.active()
r = Registry()
q = RequestQueue(cap=4)
w = Watchdog()
# creation-time gating: raw threading primitives, no wrapper in the path
assert type(r._lock) is type(threading.Lock()), type(r._lock)
assert not isinstance(getattr(q._cond, "_lock", None), lockguard.GuardedLock)
assert not isinstance(getattr(w._cond, "_lock", None), lockguard.GuardedLock)
r.counter("c").inc()
print("INERT_OK")
"""


def test_lockguard_disabled_env_is_fully_inert():
    env = dict(os.environ,
               MXNET_TPU_LOCK_GUARD="0", MXNET_TPU_TELEMETRY="0")
    out = subprocess.run(
        [sys.executable, "-c", _INERT_PROBE], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "INERT_OK" in out.stdout


_ENV_RAISE_PROBE = """
from mxnet_tpu.analysis import lockguard, LockOrderError
assert lockguard.active() and lockguard.mode() == "raise"
a, b = lockguard.lock("A"), lockguard.lock("B")
with a:
    with b:
        pass
try:
    with b:
        with a:
            pass
except LockOrderError as e:
    assert e.edge == ("B", "A")
    print("RAISED_OK")
"""


def test_lockguard_env_one_arms_raise_mode():
    env = dict(os.environ, MXNET_TPU_LOCK_GUARD="1")
    out = subprocess.run(
        [sys.executable, "-c", _ENV_RAISE_PROBE], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "RAISED_OK" in out.stdout


# ===========================================================================
# adoption — guarded sites carry their order-class names
# ===========================================================================
def test_adopted_sites_use_guarded_locks_when_armed(guard):
    from mxnet_tpu.telemetry.metrics import Registry
    from mxnet_tpu.serve.scheduler import RequestQueue
    r = Registry()
    q = RequestQueue(cap=2)
    assert isinstance(r._lock, lockguard.GuardedLock)
    assert r._lock.name == "telemetry.registry"
    assert isinstance(q._cond._lock, lockguard.GuardedLock)
    assert q._cond._lock.name == "serve.queue"
    r.counter("x").inc()          # exercise the guarded paths
    import types
    s = types.SimpleNamespace(owner=None)
    q.push(s)
    assert q.pop() is s
