"""Async exception semantics (reference suite:
tests/python/unittest/test_exc_handling.py): a failing async op must NOT
raise at dispatch — the error is stored on the output and surfaces at the
next sync point (asnumpy / wait_to_read); dependent ops propagate the
poison; MXNET_ENGINE_TYPE=NaiveEngine raises in place for debugging."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_error_defers_to_asnumpy():
    # dispatch must succeed...
    bad = nd.random.normal(0, -1, shape=(2, 2))
    good = nd.random.normal(0, 1, shape=(2, 2))
    # ...and the error surface exactly at the sync point
    with pytest.raises(ValueError, match="sigma"):
        bad.asnumpy()
    assert good.asnumpy().shape == (2, 2)  # other work is unaffected


def test_error_defers_to_wait_to_read():
    bad = nd.random.normal(0, -2.0, shape=(3,))
    with pytest.raises(ValueError):
        bad.wait_to_read()


def test_poison_propagates_through_dependent_ops():
    bad = nd.random.normal(0, -1, shape=(4,))
    c = bad + 1          # dispatch of a dependent op must not raise
    d = c * 2
    e = nd.dot(d.reshape((2, 2)), nd.ones((2, 2)))
    with pytest.raises(ValueError, match="sigma"):
        e.asnumpy()


def test_caught_error_does_not_break_later_ops():
    bad = nd.random.normal(0, -1, shape=(2,))
    with pytest.raises(ValueError):
        bad.asnumpy()
    ok = nd.ones((2,)) + 1
    np.testing.assert_array_equal(ok.asnumpy(), [2, 2])


def test_naive_engine_raises_in_place(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    with pytest.raises(ValueError, match="sigma"):
        nd.random.normal(0, -1, shape=(2, 2))


def test_deferred_out_kwarg():
    dst = nd.zeros((2, 2))
    bad = nd.random.normal(0, -1, shape=(2, 2))
    out = bad + dst
    with pytest.raises(ValueError):
        out.asnumpy()
