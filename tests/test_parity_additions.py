"""Round-5 parity additions: conv RNN cells, LSTMP, FusedRNN initializer,
legacy FeedForward, kvstore_server role, contrib.tensorboard, download.

reference: gluon/contrib/rnn/conv_rnn_cell.py, contrib/rnn/rnn_cell.py
(LSTMPCell), initializer.py (FusedRNN), model.py (FeedForward),
kvstore_server.py, contrib/tensorboard.py, test_utils.py (download).
"""
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, sym
from mxnet_tpu.gluon import contrib


# ---------------------------------------------------------------------------
# conv RNN cells
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("cls,nstates", [
    (contrib.rnn.Conv2DLSTMCell, 2),
    (contrib.rnn.Conv2DGRUCell, 1),
    (contrib.rnn.Conv2DRNNCell, 1),
])
def test_conv2d_cells_unroll_and_grad(cls, nstates):
    cell = cls(input_shape=(3, 8, 8), hidden_channels=5,
               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).rand(2, 4, 3, 8, 8)
                 .astype(np.float32))
    with autograd.record():
        outs, states = cell.unroll(4, x, layout="NTC", merge_outputs=True)
        loss = outs.sum()
    loss.backward()
    assert outs.shape == (2, 4, 5, 8, 8)
    assert len(states) == nstates
    for s in states:
        assert s.shape == (2, 5, 8, 8)
    g = cell.i2h_weight.grad().asnumpy()
    assert np.abs(g).max() > 0


def test_conv_cells_1d_3d_state_shape():
    c1 = contrib.rnn.Conv1DLSTMCell(input_shape=(2, 10), hidden_channels=4,
                                    i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    c1.initialize()
    o, s = c1(nd.array(np.random.rand(2, 2, 10).astype(np.float32)),
              c1.begin_state(batch_size=2))
    assert o.shape == (2, 4, 10) and s[1].shape == (2, 4, 10)
    c3 = contrib.rnn.Conv3DGRUCell(input_shape=(1, 4, 4, 4),
                                   hidden_channels=2, i2h_kernel=3,
                                   h2h_kernel=3, i2h_pad=1)
    c3.initialize()
    o, _ = c3(nd.array(np.random.rand(2, 1, 4, 4, 4).astype(np.float32)),
              c3.begin_state(batch_size=2))
    assert o.shape == (2, 2, 4, 4, 4)


def test_conv_cell_even_h2h_kernel_rejected():
    with pytest.raises(ValueError):
        contrib.rnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=5,
                                   i2h_kernel=3, h2h_kernel=4)


def test_conv_cell_spatial_reduction_state():
    # no i2h padding: state spatial shrinks to the conv output size
    cell = contrib.rnn.Conv2DRNNCell(input_shape=(3, 8, 8),
                                     hidden_channels=2, i2h_kernel=3,
                                     h2h_kernel=3)
    info = cell.state_info(batch_size=4)
    assert info[0]["shape"] == (4, 2, 6, 6)


def test_lstmp_cell_projection():
    p = contrib.rnn.LSTMPCell(16, 6)
    p.initialize()
    x = nd.array(np.random.rand(3, 8).astype(np.float32))
    with autograd.record():
        o, s = p(x, p.begin_state(batch_size=3))
        loss = o.sum()
    loss.backward()
    assert o.shape == (3, 6)
    assert s[0].shape == (3, 6) and s[1].shape == (3, 16)
    assert p.h2r_weight.grad().shape == (6, 16)


# ---------------------------------------------------------------------------
# FusedRNN initializer + fused sym.RNN binding
# ---------------------------------------------------------------------------
def test_fused_rnn_initializer_layout():
    init = mx.init.FusedRNN(mx.init.Xavier(), num_hidden=4, num_layers=2,
                            mode="lstm", forget_bias=1.0)
    arr = nd.zeros((352,))  # in=6: 4*4*(6+4) + 4*4*(4+4) + 2*2*16
    init("lstm_parameters", arr)
    v = arr.asnumpy()
    assert np.abs(v[:288]).max() > 0
    b = v[288:].reshape(4, 16)
    np.testing.assert_allclose(b[:, 4:8], 1.0)   # forget gates [i,f,g,o]
    np.testing.assert_allclose(b[:, :4], 0.0)
    np.testing.assert_allclose(b[:, 8:], 0.0)


def test_fused_rnn_cell_simple_bind_runs():
    """The packed-parameter shape is inferred from the data shape (RNN
    shape hint) and the bound executor runs — this path was unbindable
    before round 5."""
    import mxnet_tpu.rnn as mrnn
    cell = mrnn.FusedRNNCell(4, num_layers=2, mode="lstm")
    out, _ = cell.unroll(5, sym.Variable("data"), layout="NTC")
    ex = out.simple_bind(mx.cpu(), data=(2, 5, 6))
    assert ex.arg_dict["lstm_parameters"].shape == (352,)
    mx.init.FusedRNN(mx.init.Xavier(), 4, 2, "lstm")(
        "lstm_parameters", ex.arg_dict["lstm_parameters"])
    ex.forward(data=np.random.rand(2, 5, 6).astype(np.float32))
    assert ex.outputs[0].shape == (2, 5, 4)


# ---------------------------------------------------------------------------
# legacy FeedForward
# ---------------------------------------------------------------------------
def _ff_symbol():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, sym.Variable("fc_w"), sym.Variable("fc_b"),
                            num_hidden=16)
    act = sym.Activation(fc, act_type="relu")
    return sym.SoftmaxOutput(
        sym.FullyConnected(act, sym.Variable("o_w"), sym.Variable("o_b"),
                           num_hidden=3), name="softmax")


def test_feedforward_fit_score_predict_save_load(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(256, 8).astype(np.float32)
    W = rng.randn(8, 3).astype(np.float32)
    y = (X @ W).argmax(-1).astype(np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = mx.model.FeedForward(_ff_symbol(), num_epoch=12,
                                     learning_rate=0.5, numpy_batch_size=64)
        model.fit(X, y)
        acc = model.score(mx.io.NDArrayIter(X, y, batch_size=64))
        assert acc > 0.8, acc
        pred = model.predict(X)
        assert pred.shape == (256, 3)
        prefix = str(tmp_path / "ff")
        model.save(prefix, 1)
        m2 = mx.model.FeedForward.load(prefix, 1)
    assert set(m2.arg_params) == set(model.arg_params)


def test_feedforward_warns_deprecated():
    with pytest.warns(DeprecationWarning):
        mx.model.FeedForward(_ff_symbol())


# ---------------------------------------------------------------------------
# kvstore_server role contract
# ---------------------------------------------------------------------------
def test_server_role_never_runs_user_code():
    env = dict(os.environ, DMLC_ROLE="server", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c",
         "from mxnet_tpu.runtime import honor_jax_platforms_env;"
         "honor_jax_platforms_env();"
         "import mxnet_tpu; print('REACHED_USER_CODE')"],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0
    assert "REACHED_USER_CODE" not in r.stdout


# ---------------------------------------------------------------------------
# contrib.tensorboard + download
# ---------------------------------------------------------------------------
def test_tensorboard_callback(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    cb = mx.contrib.tensorboard.LogMetricsCallback(str(tmp_path), "train")
    m = mx.metric.create("acc")
    m.update([nd.array(np.array([1.0, 0.0]))],
             [nd.array(np.array([[0.1, 0.9], [0.8, 0.2]]))])

    class P:
        eval_metric = m
    cb(P())
    files = os.listdir(str(tmp_path))
    assert any("tfevents" in f for f in files), files


def test_test_utils_download_local(tmp_path):
    src = tmp_path / "weights.bin"
    src.write_bytes(b"abc123")
    out = mx.test_utils.download("file://" + str(src),
                                 dirname=str(tmp_path / "dl"),
                                 fname="w.bin")
    assert open(out, "rb").read() == b"abc123"


def test_feedforward_defaults_and_load_score(tmp_path):
    """Default optimizer params must not crash; score() must work on a
    freshly loaded model; predict() resets a consumed iterator."""
    rng = np.random.RandomState(1)
    X = rng.randn(128, 8).astype(np.float32)
    W = rng.randn(8, 3).astype(np.float32)
    y = (X @ W).argmax(-1).astype(np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = mx.model.FeedForward(_ff_symbol(), num_epoch=2,
                                     numpy_batch_size=64)
        model.fit(X, y)   # no optimizer kwargs: default lr path
        prefix = str(tmp_path / "ffd")
        model.save(prefix, 2)
        loaded = mx.model.FeedForward.load(prefix, 2)
        it = mx.io.NDArrayIter(X, y, batch_size=64)
        acc1 = loaded.score(it)          # score directly after load
        preds = loaded.predict(it)       # consumed iter: reset=True re-reads
    assert preds.shape == (128, 3)
    assert 0.0 <= acc1 <= 1.0


def test_fused_rnn_init_none_uses_global_init():
    """FusedRNN(None, ...) delegates weight blocks to the net's global
    initializer instead of leaving zeros (reference pattern)."""
    from mxnet_tpu.initializer import InitDesc
    init = mx.init.FusedRNN(None, num_hidden=4, num_layers=2, mode="lstm")
    arr = nd.zeros((352,))
    desc = InitDesc("lstm_parameters", global_init=mx.init.Xavier())
    init(desc, arr)
    assert np.abs(arr.asnumpy()[:288]).max() > 0


def test_feedforward_eval_data_tuple_and_predict_guard():
    rng = np.random.RandomState(2)
    X = rng.randn(128, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        sym_out = _ff_symbol()
        with pytest.raises(RuntimeError):
            mx.model.FeedForward(sym_out).predict(X)
        m = mx.model.FeedForward(sym_out, num_epoch=1, numpy_batch_size=64)
        m.fit(X, y, eval_data=(X, y))  # tuple form, reference pattern


def test_image_op_namespace():
    """mx.nd.image / mx.sym.image / nd.linalg / sym.linalg / sym.sparse
    sub-namespaces (reference: python/mxnet/{ndarray,symbol}/{image,
    linalg,sparse}.py)."""
    rng = np.random.RandomState(0)
    img = nd.array((rng.rand(8, 6, 3) * 255).astype(np.uint8))
    t = mx.nd.image.to_tensor(img)
    assert t.shape == (3, 8, 6)
    np.testing.assert_allclose(t.asnumpy(),
                               img.asnumpy().transpose(2, 0, 1) / 255.0,
                               rtol=1e-6)
    nrm = mx.nd.image.normalize(t, mean=(0.5, 0.5, 0.5), std=(2, 2, 2))
    np.testing.assert_allclose(nrm.asnumpy(), (t.asnumpy() - 0.5) / 2.0,
                               rtol=1e-5)
    assert mx.nd.image.resize(img, size=(4, 5)).shape == (5, 4, 3)
    assert mx.nd.image.resize(img, size=4, keep_ratio=True).shape[1] == 4
    crop = mx.nd.image.crop(img, x=1, y=2, width=4, height=3)
    np.testing.assert_array_equal(crop.asnumpy(),
                                  img.asnumpy()[2:5, 1:5, :])
    # batched NHWC
    batch = nd.array((rng.rand(2, 8, 6, 3) * 255).astype(np.uint8))
    assert mx.nd.image.to_tensor(batch).shape == (2, 3, 8, 6)
    np.testing.assert_array_equal(
        mx.nd.image.flip_top_bottom(batch).asnumpy(),
        batch.asnumpy()[:, ::-1])
    # symbolic composition binds and runs
    s = mx.sym.image.to_tensor(mx.sym.Variable("img"))
    ex = s.simple_bind(mx.cpu(), img=(8, 6, 3))
    ex.forward(img=img.asnumpy())
    assert ex.outputs[0].shape == (3, 8, 6)
    out = mx.sym.linalg.gemm2(mx.sym.Variable("a"), mx.sym.Variable("b"))
    ex2 = out.simple_bind(mx.cpu(), a=(3, 4), b=(4, 2))
    ex2.forward(a=np.ones((3, 4), np.float32), b=np.ones((4, 2), np.float32))
    np.testing.assert_allclose(ex2.outputs[0].asnumpy(),
                               4.0 * np.ones((3, 2)))
    assert hasattr(mx.sym.sparse, "dot")


def test_conv_lstm_hybridize_parity_and_checkpoint(tmp_path):
    """Conv cells hybridize to the same numbers and roundtrip through
    save_parameters/load_parameters."""
    rng = np.random.RandomState(5)
    x = nd.array(rng.rand(2, 3, 3, 8, 8).astype(np.float32))

    def build():
        c = contrib.rnn.Conv2DLSTMCell(input_shape=(3, 8, 8),
                                       hidden_channels=4, i2h_kernel=3,
                                       h2h_kernel=3, i2h_pad=1,
                                       prefix="clstm_")
        return c
    cell = build()
    cell.initialize(mx.init.Xavier())
    out_e, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    cell.hybridize()
    out_h, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    np.testing.assert_allclose(out_e.asnumpy(), out_h.asnumpy(),
                               rtol=2e-5, atol=2e-6)
    f = str(tmp_path / "clstm.params")
    cell.save_parameters(f)
    cell2 = build()
    cell2.load_parameters(f)
    out_l, _ = cell2.unroll(3, x, layout="NTC", merge_outputs=True)
    np.testing.assert_allclose(out_l.asnumpy(), out_e.asnumpy(), rtol=2e-5,
                               atol=2e-6)


# ---------------------------------------------------------------------------
# legacy rnn: pack/unpack, checkpoints, zoneout, encode_sentences
# ---------------------------------------------------------------------------
def test_fused_unfused_weight_interchange(tmp_path):
    """Fused sym.RNN vs the unfused cell stack must agree numerically
    under exchanged (unpacked) weights — cross-validates the packed
    layout, the lax.scan kernel, and unfuse() in one assert (reference:
    FusedRNNCell.unpack_weights/unfuse)."""
    import mxnet_tpu.rnn as mrnn
    cell = mrnn.FusedRNNCell(4, num_layers=2, mode="lstm", prefix="lstm_")
    out, _ = cell.unroll(5, sym.Variable("data"), layout="NTC")
    ex = out.simple_bind(mx.cpu(), data=(2, 5, 6))
    mx.init.FusedRNN(mx.init.Xavier(), 4, 2, "lstm")(
        "lstm_parameters", ex.arg_dict["lstm_parameters"])
    x = np.random.RandomState(0).rand(2, 5, 6).astype(np.float32)
    ex.forward(data=x)
    fused_out = ex.outputs[0].asnumpy()

    args = {"lstm_parameters": ex.arg_dict["lstm_parameters"]}
    unpacked = cell.unpack_weights(args)
    stack = cell.unfuse()
    uout, _ = stack.unroll(5, sym.Variable("data"), layout="NTC",
                           merge_outputs=True)
    shapes = {"lstm_l%d_begin_state_%d" % (i, j): (2, 4)
              for i in range(2) for j in range(2)}
    ex2 = uout.simple_bind(mx.cpu(), data=(2, 5, 6), **shapes)
    for k, v in unpacked.items():
        if k in ex2.arg_dict:
            ex2.arg_dict[k][:] = v.asnumpy()
    ex2.forward(data=x)
    np.testing.assert_allclose(fused_out, ex2.outputs[0].asnumpy(),
                               rtol=2e-5, atol=2e-6)
    # pack is the exact inverse
    repacked = cell.pack_weights(unpacked)
    np.testing.assert_allclose(repacked["lstm_parameters"].asnumpy(),
                               args["lstm_parameters"].asnumpy(), rtol=1e-6)
    # checkpoint helpers roundtrip through the unpacked form
    mrnn.save_rnn_checkpoint(cell, str(tmp_path / "cp"), 3, out,
                             dict(args), {})
    _, arg2, _ = mrnn.load_rnn_checkpoint(cell, str(tmp_path / "cp"), 3)
    assert "lstm_l0_i2h_weight" in arg2 and \
        "lstm_parameters" not in arg2


def test_legacy_zoneout_and_encode():
    import mxnet_tpu.rnn as mrnn
    z = mrnn.ZoneoutCell(mrnn.LSTMCell(4, prefix="zl_"),
                         zoneout_states=0.1)
    outs, st = z.unroll(3, sym.Variable("data"))
    assert len(outs) == 3 and len(st) == 2
    coded, vocab = mrnn.encode_sentences([["a", "b"], ["b", "c"]],
                                         start_label=1)
    assert coded == [[1, 2], [2, 3]]
    # closed vocab raises on unknown without unknown_token
    with pytest.raises(ValueError):
        mrnn.encode_sentences([["zzz"]], vocab=dict(vocab))


def test_fused_unfused_bidirectional_interchange():
    """Bidirectional: fused kernel vs BidirectionalCell stack under
    exchanged weights (reference: unfuse wraps layers in
    BidirectionalCell)."""
    import mxnet_tpu.rnn as mrnn
    cell = mrnn.FusedRNNCell(3, num_layers=1, mode="lstm",
                             bidirectional=True, prefix="blstm_")
    out, _ = cell.unroll(4, sym.Variable("data"), layout="NTC")
    ex = out.simple_bind(mx.cpu(), data=(2, 4, 5))
    mx.init.FusedRNN(mx.init.Xavier(), 3, 1, "lstm", bidirectional=True)(
        "blstm_parameters", ex.arg_dict["blstm_parameters"])
    x = np.random.RandomState(1).rand(2, 4, 5).astype(np.float32)
    ex.forward(data=x)
    fused_out = ex.outputs[0].asnumpy()
    assert fused_out.shape == (2, 4, 6)   # 2*hidden concat

    unpacked = cell.unpack_weights(
        {"blstm_parameters": ex.arg_dict["blstm_parameters"]})
    assert "blstm_l0_r_i2h_weight" in unpacked
    stack = cell.unfuse()
    uout, _ = stack.unroll(4, sym.Variable("data"), layout="NTC",
                           merge_outputs=True)
    shapes = {}
    for name in uout.list_arguments():
        if "begin_state" in name:
            shapes[name] = (2, 3)
    ex2 = uout.simple_bind(mx.cpu(), data=(2, 4, 5), **shapes)
    for k, v in unpacked.items():
        if k in ex2.arg_dict:
            ex2.arg_dict[k][:] = v.asnumpy()
    ex2.forward(data=x)
    np.testing.assert_allclose(fused_out, ex2.outputs[0].asnumpy(),
                               rtol=2e-5, atol=2e-6)


def test_interval_sampler_and_test_utils():
    s = gluon.contrib.data.IntervalSampler(10, 3)
    order = list(s)
    assert sorted(order) == list(range(10)) and order[:4] == [0, 3, 6, 9]
    assert list(gluon.contrib.data.IntervalSampler(10, 3,
                                                   rollover=False)) == \
        [0, 3, 6, 9]
    arr, dense = mx.test_utils.rand_sparse_ndarray((6, 3), "row_sparse",
                                                   density=0.5)
    np.testing.assert_allclose(arr.tostype("default").asnumpy(), dense)
    a = sym.FullyConnected(sym.Variable("x"), sym.Variable("w"),
                           sym.Variable("b"), num_hidden=4)
    b = sym.FullyConnected(sym.Variable("q"), sym.Variable("r"),
                           sym.Variable("t"), num_hidden=4)
    assert mx.test_utils.same_symbol_structure(a, b)
    assert not mx.test_utils.same_symbol_structure(a, sym.softmax(a))
