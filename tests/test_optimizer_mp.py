"""Mixed-precision (fp32-master) optimizer path depth (round-5 matrix
follow-up — the shape/dtype matrix skipped optimizer update ops).

reference: tests/python/unittest/test_optimizer.py exercises every
optimizer at fp16 with multi_precision; the mp_* ops carry an fp32
master copy so tiny updates are not lost to fp16 rounding.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.ndarray import invoke


def test_mp_sgd_update_semantics():
    """w32' = w32 - lr*(g + wd*w32); w16' = cast(w32')."""
    rng = onp.random.RandomState(0)
    w32h = rng.randn(6).astype("float32")
    gh = rng.randn(6).astype("float32")
    w16 = nd.array(w32h).astype("float16")
    g16 = nd.array(gh).astype("float16")
    w32 = nd.array(w32h)
    out16, out32 = invoke("mp_sgd_update", w16, g16, w32, lr=0.1, wd=0.01)
    want32 = w32h - 0.1 * (onp.asarray(g16.asnumpy(), "float32")
                           + 0.01 * w32h)
    onp.testing.assert_allclose(out32.asnumpy(), want32, rtol=1e-6,
                                atol=1e-7)
    onp.testing.assert_allclose(out16.asnumpy(),
                                want32.astype("float16"), rtol=1e-3,
                                atol=1e-4)
    assert str(out16.dtype) == "float16" and str(out32.dtype) == "float32"


def test_mp_master_keeps_tiny_updates():
    """The classic motivation: lr*grad below fp16 resolution of w must
    still accumulate in the master copy (and eventually move w16)."""
    w0 = 1.0
    lr, g = 1e-4, 1.0        # step 1e-4: fp16(1.0 - 1e-4) == 1.0 exactly
    steps = 20
    w16 = nd.array(onp.array([w0], "float32")).astype("float16")
    w32 = nd.array(onp.array([w0], "float32"))
    g16 = nd.array(onp.array([g], "float32")).astype("float16")
    for _ in range(steps):
        w16, w32 = invoke("mp_sgd_update", w16, g16, w32, lr=lr)
    # master accumulated all 20 steps
    onp.testing.assert_allclose(w32.asnumpy(), [w0 - steps * lr * g],
                                rtol=1e-5, atol=1e-6)
    # pure fp16 loses every step
    w_pure = nd.array(onp.array([w0], "float32")).astype("float16")
    for _ in range(steps):
        w_pure = invoke("sgd_update", w_pure, g16, lr=lr)
    assert float(w_pure.asnumpy()[0]) == w0, "fp16 step unexpectedly moved"
    assert float(w32.asnumpy()[0]) < w0


@pytest.mark.parametrize("opt_name,opt_args", [
    ("sgd", {"momentum": 0.9}),
    ("nag", {"momentum": 0.9}),
    ("sgd", {}),
])
def test_optimizer_multi_precision_tracks_fp32(opt_name, opt_args):
    """Optimizer(multi_precision=True) on fp16 weights must track the
    fp32 optimizer trajectory to fp16-cast accuracy."""
    rng = onp.random.RandomState(1)
    wh = rng.randn(12).astype("float32")
    opt16 = mx.optimizer.create(opt_name, learning_rate=0.05,
                                multi_precision=True, **opt_args)
    opt32 = mx.optimizer.create(opt_name, learning_rate=0.05, **opt_args)
    w16 = nd.array(wh).astype("float16")
    w32 = nd.array(wh)
    s16 = opt16.create_state_multi_precision(0, w16)
    s32 = opt32.create_state(0, w32)
    for i in range(10):
        gh = rng.randn(12).astype("float32") * 0.5
        opt16.update_multi_precision(0, w16, nd.array(gh).astype("float16"),
                                     s16)
        opt32.update(0, w32, nd.array(gh), s32)
    onp.testing.assert_allclose(w16.asnumpy().astype("float32"),
                                w32.asnumpy(), rtol=2e-3, atol=2e-3)


def test_multi_mp_sgd_mom_matches_per_tensor():
    """The fused multi-tensor op == N per-tensor mp updates."""
    rng = onp.random.RandomState(3)
    n = 3
    ws, gs, moms, w32s = [], [], [], []
    for i in range(n):
        wh = rng.randn(4 + i).astype("float32")
        ws.append(nd.array(wh).astype("float16"))
        gs.append(nd.array(rng.randn(4 + i).astype("float32"))
                  .astype("float16"))
        moms.append(nd.array(onp.zeros(4 + i, "float32")))
        w32s.append(nd.array(wh))
    flat = []
    for i in range(n):
        flat += [ws[i], gs[i], moms[i], w32s[i]]
    outs = invoke("multi_mp_sgd_mom_update", *flat,
                  lrs=[0.1] * n, wds=[0.01] * n, momentum=0.9,
                  num_weights=n)
    for i in range(n):
        w16_i, mom_i, w32_i = invoke(
            "mp_sgd_mom_update", ws[i], gs[i],
            nd.array(onp.zeros(4 + i, "float32")), w32s[i],
            lr=0.1, wd=0.01, momentum=0.9)
        onp.testing.assert_allclose(outs[3 * i].asnumpy(),
                                    w16_i.asnumpy(), rtol=1e-3, atol=1e-4)
        onp.testing.assert_allclose(outs[3 * i + 2].asnumpy(),
                                    w32_i.asnumpy(), rtol=1e-6, atol=1e-7)


def test_adam_bf16_update_finite_and_close():
    """adam_update at bf16 weights stays finite and near the fp32 path."""
    rng = onp.random.RandomState(5)
    wh = rng.randn(16).astype("float32")
    gh = (rng.randn(16) * 0.1).astype("float32")
    m0 = onp.zeros(16, "float32")
    v0 = onp.zeros(16, "float32")
    w_bf, m_bf, v_bf = invoke(
        "adam_update", nd.array(wh).astype("bfloat16"),
        nd.array(gh).astype("bfloat16"), nd.array(m0), nd.array(v0),
        lr=0.01)
    w_f, m_f, v_f = invoke("adam_update", nd.array(wh), nd.array(gh),
                           nd.array(m0), nd.array(v0), lr=0.01)
    got = w_bf.asnumpy().astype("float32")
    assert onp.isfinite(got).all()
    onp.testing.assert_allclose(got, w_f.asnumpy(), rtol=2e-2, atol=2e-2)
