"""Telemetry v2 — live export, trace correlation, flight recorder, anomaly
detection, and the deadline-aware preemption/commit satellites.

Acceptance (ISSUE 6): a /metrics scrape matches `telemetry.snapshot()`
counter-for-counter; an injected-stall post-mortem embeds the
flight-recorder ring; everything is a no-op under MXNET_TPU_TELEMETRY=0
(no thread, no port). The 2-rank merged-trace test lives in test_dist.py
(slow marker).
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, resilience as rz, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faults, watchdog
from mxnet_tpu.resilience.commit import CommitCoordinator
from mxnet_tpu.resilience.errors import PreemptionError, StallError
from mxnet_tpu.resilience.preempt import PreemptionListener, PreemptionNotice
from mxnet_tpu.telemetry import anomaly, export, flight

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    was_enabled = telemetry.ENABLED
    telemetry.enable()
    telemetry.reset()
    yield
    export.stop_http_server()
    export.stop_stream()
    telemetry.reset()
    (telemetry.enable if was_enabled else telemetry.disable)()


def _counter(name):
    return telemetry.snapshot()["counters"].get(name, 0)


def _scrape(port, path="/metrics"):
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=10) as resp:
        return resp.read().decode("utf-8")


def _seed_metrics():
    telemetry.inc("t.calls", 5)
    telemetry.inc("comm.collectives", 3)
    telemetry.set_gauge("t.mem", 77)
    for v in (0.5, 2.0, 300.0):
        telemetry.observe("t.lat_ms", v)


# ===========================================================================
# prometheus text format
# ===========================================================================
def test_prometheus_text_roundtrip_counters():
    _seed_metrics()
    text = export.prometheus_text()
    parsed = export.parse_prometheus_text(text)
    assert parsed == telemetry.snapshot()["counters"]


def test_prometheus_text_gauges_and_histograms():
    _seed_metrics()
    telemetry.set_gauge("t.mem", 10)          # watermark stays 77
    text = export.prometheus_text(rank=0)
    assert 'mxnet_tpu_t_mem{rank="0"} 10' in text
    assert 'mxnet_tpu_t_mem_max{rank="0"} 77' in text
    # histogram buckets are CUMULATIVE and end at +Inf == count
    lines = [l for l in text.splitlines()
             if l.startswith("mxnet_tpu_t_lat_ms_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in lines]
    assert counts == sorted(counts)
    assert 'le="+Inf"} 3' in lines[-1]
    assert 'mxnet_tpu_t_lat_ms_count{rank="0"} 3' in text
    assert 'mxnet_tpu_t_lat_ms_sum{rank="0"} 302.5' in text


def test_histogram_quantiles_interpolation():
    """Telemetry-v2 follow-on: p50/p99 derived from the sparse cumulative
    buckets (prometheus histogram_quantile semantics + exact min/max
    clamp) for ALL histograms, not just the rolling step windows."""
    from mxnet_tpu.telemetry.metrics import Histogram
    h = Histogram("t", bounds=(1, 2, 4, 8, 16))
    for v in (0.5, 1.5, 1.7, 3, 3, 3, 5, 6, 7, 12):
        h.observe(v)
    q = export.histogram_quantiles(h.snapshot())
    # p50: rank 5 lands in (2,4] with 3 before it -> 2 + 2*(5-3)/3
    assert abs(q["p50"] - (2 + 2 * (5 - 3) / 3)) < 1e-9
    # p99: interpolation says 15.2 inside (8,16]; exact max clamps to 12
    assert q["p99"] == 12
    # overflow bucket answers with the observed max
    h2 = Histogram("o", bounds=(1,))
    for v in (5.0, 9.0):
        h2.observe(v)
    assert export.histogram_quantiles(h2.snapshot())["p99"] == 9.0
    assert export.histogram_quantiles(Histogram("e").snapshot()) is None
    # the rank-holding bucket's TRUE lower edge holds even when the
    # buckets below it are empty (omitted from the sparse snapshot):
    # 1 obs at 0.5 and 9 at 15.0 -> p50 lives in (8,16], never below 8
    h3 = Histogram("s", bounds=(1, 2, 4, 8, 16))
    h3.observe(0.5)
    for _ in range(9):
        h3.observe(15.0)
    q3 = export.histogram_quantiles(h3.snapshot())
    assert q3["p50"] == pytest.approx(8 + 8 * (5 - 1) / 9)
    assert q3["p50"] >= 8


def test_prometheus_text_emits_quantile_series():
    _seed_metrics()
    text = export.prometheus_text(rank=0)
    assert "# TYPE mxnet_tpu_t_lat_ms_p50 gauge" in text
    p50 = [l for l in text.splitlines()
           if l.startswith('mxnet_tpu_t_lat_ms_p50{rank="0"}')]
    p99 = [l for l in text.splitlines()
           if l.startswith('mxnet_tpu_t_lat_ms_p99{rank="0"}')]
    assert len(p50) == 1 and len(p99) == 1
    snap_h = telemetry.snapshot()["histograms"]["t.lat_ms"]
    q = export.histogram_quantiles(snap_h)
    assert float(p50[0].rsplit(" ", 1)[1]) == pytest.approx(q["p50"])
    assert float(p99[0].rsplit(" ", 1)[1]) == pytest.approx(q["p99"])
    # the quantile gauges must not confuse the counter round-trip
    assert export.parse_prometheus_text(text) == \
        telemetry.snapshot()["counters"]


def test_snapshot_payload_hist_quantiles():
    _seed_metrics()
    payload = export.snapshot_payload()
    assert "t.lat_ms" in payload["hist_quantiles"]
    assert set(payload["hist_quantiles"]["t.lat_ms"]) == {"p50", "p99"}


# ===========================================================================
# live endpoint
# ===========================================================================
@pytest.mark.obs
def test_metrics_endpoint_scrape_parity():
    """ISSUE acceptance: a live /metrics scrape matches telemetry.snapshot()
    counter-for-counter."""
    _seed_metrics()
    server = export.start_http_server(0)      # ephemeral port
    assert server is not None
    parsed = export.parse_prometheus_text(_scrape(server.port))
    assert parsed == telemetry.snapshot()["counters"]
    # scrapes are idempotent reads: a second one still matches
    telemetry.inc("t.calls", 2)
    parsed = export.parse_prometheus_text(_scrape(server.port))
    assert parsed["t.calls"] == 7


@pytest.mark.obs
def test_snapshot_endpoint_payload():
    _seed_metrics()
    telemetry.step_event("fused_step", 5.0)
    server = export.start_http_server(0)
    payload = json.loads(_scrape(server.port, "/snapshot"))
    assert payload["snapshot"] == telemetry.snapshot()
    assert payload["trace_id"] == telemetry.trace_id()
    assert payload["rank"] == 0
    assert payload["step_quantiles"]["fused_step"]["n"] == 1
    assert _scrape(server.port, "/healthz").strip() == "ok"


@pytest.mark.obs
def test_scrape_atomic_under_concurrent_writes():
    """Exporter reads racing inc/observe/set_gauge from step threads must
    see consistent metrics (the concurrency satellite): the gauge
    value/max pair can never be torn (max < value), and counter text
    always parses."""
    server = export.start_http_server(0)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            i += 1
            telemetry.inc("w.calls")
            telemetry.set_gauge("w.gauge", i)
            telemetry.observe("w.lat", i % 100)

    threads = [threading.Thread(target=writer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(20):
            parsed = export.parse_prometheus_text(_scrape(server.port))
            assert parsed.get("w.calls", 0) >= 0
            snap = telemetry.snapshot()
            g = snap["gauges"].get("w.gauge")
            if g is not None:
                assert g["max"] >= g["value"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_stream_writes_jsonl(tmp_path):
    _seed_metrics()
    path = str(tmp_path / "stream.jsonl")
    streamer = export.start_stream(path, interval_s=0.05)
    assert streamer is not None
    time.sleep(0.2)
    export.stop_stream()
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines, "streamer wrote nothing"
    assert lines[-1]["snapshot"]["counters"]["t.calls"] == 5
    assert lines[-1]["trace_id"] == telemetry.trace_id()


# ===========================================================================
# disabled mode: no thread, no port
# ===========================================================================
def test_disabled_mode_binds_no_port_starts_no_thread():
    """ISSUE acceptance: MXNET_TPU_TELEMETRY=0 + a configured port must
    bind nothing and start no exporter/streamer thread."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = r"""
import os, socket, threading, sys
import mxnet_tpu  # import-time maybe_start_from_env runs here
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import export
assert not telemetry.ENABLED
assert export.start_http_server() is None
assert export.start_stream() is None
names = [t.name for t in threading.enumerate()]
assert not any(n.startswith("mxnet_tpu_metrics") for n in names), names
s = socket.socket()
try:
    s.connect(("127.0.0.1", int(os.environ["MXNET_TPU_METRICS_PORT"])))
except (ConnectionRefusedError, OSError):
    print("PORT_FREE")
finally:
    s.close()
# the flight recorder and anomaly tracker are inert too
telemetry.step_event("fused_step", 5.0)
from mxnet_tpu.telemetry import flight
assert flight.records() == []
# ISSUE 12: the RequestTrace ring and the fleet federation are fully
# inert — NULL traces, empty ring, no network touched even with peers
# configured in the env
from mxnet_tpu.telemetry import federation, request_trace
os.environ["MXNET_TPU_FLEET_PEERS"] = "127.0.0.1:9"
assert federation.fleet_snapshot() is None
assert federation.fleet_metrics_text() is None
tr = request_trace.start("r1")
assert tr is request_trace.NULL_TRACE
tr.mark("queue")
tr.note_drain(RuntimeError("x"))
assert tr.finish("completed") is None
assert request_trace.records() == []
assert telemetry.snapshot() == {"counters": {}, "gauges": {},
                                "histograms": {}}
print("DISABLED_OK")
"""
    stream_path = "/tmp/_obs_disabled_stream.jsonl"
    if os.path.exists(stream_path):
        os.remove(stream_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_TELEMETRY="0",
               MXNET_TPU_METRICS_PORT=str(port),
               MXNET_TPU_METRICS_STREAM=stream_path)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PORT_FREE" in r.stdout and "DISABLED_OK" in r.stdout
    assert not os.path.exists(stream_path)


@pytest.mark.obs
def test_env_autostart_binds_configured_port(tmp_path):
    """The inverse: with telemetry ON the env knob starts a real scrapable
    endpoint at import time."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = r"""
import os, urllib.request
import mxnet_tpu
from mxnet_tpu import telemetry
telemetry.inc("autostart.probe", 3)
port = int(os.environ["MXNET_TPU_METRICS_PORT"])
body = urllib.request.urlopen(
    "http://127.0.0.1:%d/metrics" % port, timeout=10).read().decode()
assert "autostart_probe" in body, body
print("AUTOSTART_OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_METRICS_PORT=str(port))
    env.pop("MXNET_TPU_TELEMETRY", None)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "AUTOSTART_OK" in r.stdout


def test_stream_final_flush_on_short_run(tmp_path):
    """A run shorter than one stream interval still leaves a final line:
    the env-autostart path registers an atexit flush."""
    path = str(tmp_path / "short.jsonl")
    code = r"""
import os
import mxnet_tpu
from mxnet_tpu import telemetry
telemetry.inc("short.run", 3)
# exits immediately — well inside the 60 s stream interval
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_METRICS_STREAM=path,
               MXNET_TPU_METRICS_STREAM_S="60")
    env.pop("MXNET_TPU_TELEMETRY", None)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines and lines[-1]["snapshot"]["counters"]["short.run"] == 3


def test_enable_after_disabled_start_brings_up_endpoint():
    """A process started disabled with a configured port gets its endpoint
    when telemetry.enable() runs (the documented runtime switch)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = r"""
import os, urllib.request
import mxnet_tpu
from mxnet_tpu import telemetry
assert not telemetry.ENABLED
telemetry.enable()
telemetry.inc("late.enable", 1)
port = int(os.environ["MXNET_TPU_METRICS_PORT"])
body = urllib.request.urlopen(
    "http://127.0.0.1:%d/metrics" % port, timeout=10).read().decode()
assert "late_enable" in body, body
print("LATE_ENABLE_OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_TELEMETRY="0",
               MXNET_TPU_METRICS_PORT=str(port),
               MXNET_TPU_METRICS_HOST="127.0.0.1")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "LATE_ENABLE_OK" in r.stdout


# ===========================================================================
# trace correlation
# ===========================================================================
def test_trace_id_stable_and_settable():
    tid = telemetry.trace_id()
    assert tid == telemetry.trace_id()
    telemetry.set_trace_id("deadbeef")
    assert telemetry.trace_id() == "deadbeef"


def test_dump_trace_stamps_rank_and_trace_id(tmp_path):
    with telemetry.span("stamped", "test"):
        pass
    path = str(tmp_path / "trace.json")
    telemetry.dump_trace(path)
    obj = json.load(open(path))
    meta = obj["metadata"]
    assert meta["rank"] == 0
    assert meta["trace_id"] == telemetry.trace_id()
    spans = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert spans and all(e["pid"] == 0 for e in spans)


def test_merged_trace_single_process(tmp_path):
    with telemetry.span("local_span", "test"):
        pass
    path = str(tmp_path / "merged.json")
    telemetry.dump_trace(path, merged=True)
    obj = json.load(open(path))
    assert obj["metadata"]["merged"] is True
    assert obj["metadata"]["ranks"] == [0]
    names = {e["name"] for e in obj["traceEvents"] if e["ph"] == "X"}
    assert "local_span" in names


def test_merged_trace_shared_clock(tmp_path):
    """Two fake rank dumps with skewed epochs merge onto one clock: rank
    1's spans shift by the epoch delta, and both ranks get process rows."""
    from mxnet_tpu.telemetry.trace import write_merged_chrome_trace
    dumps = [
        {"rank": 0, "trace_id": "t0", "epoch_unix": 1000.0,
         "events": [["a", "test", 1.0, 0.5, 1]]},
        {"rank": 1, "trace_id": "t0", "epoch_unix": 1002.0,
         "events": [["b", "test", 1.0, 0.5, 1]]},
    ]
    path = str(tmp_path / "m.json")
    write_merged_chrome_trace(path, dumps)
    obj = json.load(open(path))
    spans = {e["name"]: e for e in obj["traceEvents"] if e["ph"] == "X"}
    assert spans["a"]["pid"] == 0 and spans["b"]["pid"] == 1
    # rank 1's epoch started 2 s later: same local ts lands 2e6 µs later
    assert spans["b"]["ts"] - spans["a"]["ts"] == pytest.approx(2e6)
    procs = [e for e in obj["traceEvents"] if e["ph"] == "M"]
    assert {p["pid"] for p in procs} == {0, 1}


def test_merged_trace_tolerates_missing_epoch(tmp_path):
    """An out-of-band dump without an epoch anchor merges unshifted; the
    anchored ranks keep their own base instead of being re-based by a
    unix-epoch-sized offset."""
    from mxnet_tpu.telemetry.trace import write_merged_chrome_trace
    dumps = [
        {"rank": 0, "epoch_unix": 1000.0,
         "events": [["a", "test", 1.0, 0.5, 1]]},
        {"rank": 1,   # pre-v2 dump: no epoch_unix
         "events": [["b", "test", 1.0, 0.5, 1]]},
    ]
    path = str(tmp_path / "m.json")
    write_merged_chrome_trace(path, dumps)
    spans = {e["name"]: e for e in
             json.load(open(path))["traceEvents"] if e["ph"] == "X"}
    assert spans["a"]["ts"] == pytest.approx(1e6)  # NOT shifted by ~1000 s
    assert spans["b"]["ts"] == pytest.approx(1e6)


def test_mxtop_stream_tail_read(tmp_path):
    """fetch_stream reads only the tail of a large stream file and skips a
    partially-appended last line."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import mxtop
    finally:
        sys.path.pop(0)
    path = str(tmp_path / "big.jsonl")
    with open(path, "w") as f:
        for i in range(5000):
            f.write(json.dumps({"ts": i, "snapshot": {}}) + "\n")
        f.write('{"ts": 9999, "snapsho')      # torn mid-append line
    assert mxtop.fetch_stream(path, block=256)["ts"] == 4999
    with open(str(tmp_path / "empty.jsonl"), "w"):
        pass
    with pytest.raises(ValueError):
        mxtop.fetch_stream(str(tmp_path / "empty.jsonl"))


def test_aggregate_trace_local():
    with telemetry.span("agg_span", "test"):
        pass
    dumps = telemetry.aggregate_trace()
    assert len(dumps) == 1
    assert dumps[0]["rank"] == 0
    assert any(e[0] == "agg_span" for e in dumps[0]["events"])


# ===========================================================================
# comm-overlap attribution (ISSUE 12 tentpole)
# ===========================================================================
def test_attribution_partition_sums_to_step():
    """The compute/collective/host/idle decomposition is a partition of
    the step window — it sums to step time exactly (the acceptance's 5%
    bound holds by construction), with overlapping comm spans unioned and
    host spans deduplicated against comm."""
    from mxnet_tpu.telemetry import attribution
    events = [
        ("fused_step", "step", 0.0, 1.0, 1),
        ("comm.bucket[a]", "comm", 0.1, 0.2, 1),     # [0.10, 0.30]
        ("comm.bucket[b]", "comm", 0.25, 0.1, 1),    # [0.25, 0.35] overlap
        ("checkpoint", "resilience", 0.5, 0.1, 1),   # host
        ("checkpoint", "resilience", 0.3, 0.1, 1),   # half under comm
    ]
    row = attribution.attribute_window(events, 0.0, 1.0)
    assert row["collective_ms"] == pytest.approx(250.0)   # union, not sum
    assert row["comm_busy_ms"] == pytest.approx(300.0)
    assert row["host_ms"] == pytest.approx(150.0)         # comm part cut
    assert row["idle_ms"] == 0.0
    assert row["compute_ms"] == pytest.approx(1000 - 250 - 150)
    total = (row["compute_ms"] + row["collective_ms"] + row["host_ms"]
             + row["idle_ms"])
    assert total == pytest.approx(row["step_ms"])
    assert row["comm_launches"] == 2
    # overlap: comm phase = [0.1, 1.0]; host was off the comm path for
    # 0.9 - 0.25 of it
    assert row["overlap_frac"] == pytest.approx((0.9 - 0.25) / 0.9,
                                                abs=1e-3)


def test_overlap_report_on_live_spans_and_gauges():
    """overlap_report() reads the live span buffer; step_event publishes
    the same decomposition as attrib.* gauges and a flight record."""
    # a step that JUST ended: step_event's live window is [now-dur, now],
    # exactly how the real step sites call it
    ts = telemetry.span_clock() - 0.02
    telemetry.record_span("comm.bucket[0..5]", "comm", ts + 0.001, 0.004)
    telemetry.record_span("train_step", "step", ts, 0.02)
    rep = telemetry.overlap_report(site="train_step")
    assert rep["summary"]["steps"] == 1
    row = rep["steps"][0]
    assert row["collective_ms"] == pytest.approx(4.0, rel=0.01)
    assert row["comm_launches"] == 1
    assert 0.0 < row["overlap_frac"] < 1.0
    # the live per-step pass: gauges + flight "attrib" record
    telemetry.step_event("train_step", 20.0)
    gauges = telemetry.snapshot()["gauges"]
    assert "attrib.train_step.collective_ms" in gauges
    rec = telemetry.flight_records()[-1]
    assert "attrib" in rec and rec["attrib"]["comm_launches"] >= 1


def test_overlap_report_no_comm_steps():
    ts = telemetry.span_clock()
    telemetry.record_span("fused_step", "step", ts, 0.01)
    rep = telemetry.overlap_report(site="fused_step")
    row = rep["steps"][0]
    assert row["overlap_frac"] is None
    assert row["compute_ms"] == pytest.approx(row["step_ms"])
    assert rep["summary"]["overlap_frac"] is None


# ===========================================================================
# /requests endpoint + fleet federation (ISSUE 12 tentpole)
# ===========================================================================
@pytest.mark.obs
def test_requests_endpoint_serves_trace_ring():
    from mxnet_tpu.telemetry import request_trace
    tr = request_trace.start("req-endpoint-1")
    tr.mark("queue").mark("prefill")
    tr.finish("completed", tokens=3)
    server = export.start_http_server(0)
    payload = json.loads(_scrape(server.port, "/requests"))
    assert payload["rank"] == 0
    assert payload["trace_id"] == telemetry.trace_id()
    reqs = {r["request_id"]: r for r in payload["requests"]}
    assert reqs["req-endpoint-1"]["outcome"] == "completed"
    assert reqs["req-endpoint-1"]["tokens"] == 3


@pytest.mark.obs
def test_fleet_endpoints_local_only():
    """With no peers configured the fleet view degrades to this rank —
    same payload shape, workers=1 — so dashboards need no special case."""
    _seed_metrics()
    server = export.start_http_server(0)
    fleet = json.loads(_scrape(server.port, "/fleet/snapshot"))
    assert fleet["workers"] == 1
    assert fleet["stale_ranks"] == [] and fleet["missing"] == []
    assert fleet["merged"]["counters"]["t.calls"] == 5
    assert set(fleet["ranks"]) == {"0"}
    text = _scrape(server.port, "/fleet/metrics")
    assert 'mxnet_tpu_t_calls{rank="0"} 5' in text
    assert "mxnet_tpu_fleet_workers 1" in text


@pytest.mark.obs
def test_fleet_snapshot_merges_peer_and_tolerates_death():
    """A (stub) peer's /snapshot merges into the fleet view rank-labeled;
    when the peer dies its last good payload is served stale-marked and
    telemetry.federation.stale_ranks counts it."""
    import http.server
    from mxnet_tpu.telemetry import federation
    peer_payload = {
        "rank": 1, "trace_id": "t", "hist_quantiles": {},
        "snapshot": {"counters": {"t.calls": 7, "peer.only": 2},
                     "gauges": {}, "histograms": {}},
    }

    class _Peer(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib contract
            body = json.dumps(peer_payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # noqa: A002
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Peer)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    _seed_metrics()
    federation.configure(["127.0.0.1:%d" % httpd.server_address[1]])
    try:
        fleet = federation.fleet_snapshot()
        assert fleet["workers"] == 2
        assert set(fleet["ranks"]) == {"0", "1"}
        assert fleet["merged"]["counters"]["t.calls"] == 12   # 5 + 7
        assert fleet["merged"]["counters"]["peer.only"] == 2
        assert fleet["stale_ranks"] == []
        text = federation.fleet_metrics_text()
        assert 'mxnet_tpu_t_calls{rank="0"} 5' in text
        assert 'mxnet_tpu_t_calls{rank="1"} 7' in text
        # one HELP/TYPE header despite two ranks: the blob stays parseable
        assert text.count("# TYPE mxnet_tpu_t_calls counter") == 1
        # kill the peer: stale cache serves, stale_ranks counts
        httpd.shutdown()
        httpd.server_close()
        fleet = federation.fleet_snapshot()
        assert len(fleet["stale_ranks"]) == 1
        assert fleet["ranks"]["1"]["stale"] is True
        assert fleet["workers"] == 2                          # still both
        assert fleet["merged"]["counters"][
            "telemetry.federation.stale_ranks"] == 1
    finally:
        federation.reset()
        try:
            httpd.server_close()
        except OSError:
            pass


def test_fleet_missing_peer_without_cache(monkeypatch):
    """A peer that NEVER answered is reported missing (not fabricated),
    and each failed scrape ticks the stale counter."""
    from mxnet_tpu.telemetry import federation
    monkeypatch.setenv("MXNET_TPU_FLEET_TIMEOUT_S", "0.2")
    federation.configure(["127.0.0.1:9"])       # nothing listens there
    try:
        fleet = federation.fleet_snapshot()
        assert fleet["missing"] == ["http://127.0.0.1:9"]
        assert fleet["workers"] == 1
        assert fleet["merged"]["counters"][
            "telemetry.federation.stale_ranks"] == 1
        fleet = federation.fleet_snapshot()
        assert fleet["merged"]["counters"][
            "telemetry.federation.stale_ranks"] == 2
    finally:
        federation.reset()


def test_fleet_peers_env_parsing(monkeypatch):
    from mxnet_tpu.telemetry import federation
    monkeypatch.setenv("MXNET_TPU_FLEET_PEERS",
                       "10.0.0.2:9100, http://10.0.0.3:9100/,")
    assert federation.peers() == ["http://10.0.0.2:9100",
                                  "http://10.0.0.3:9100"]
    federation.configure(["a:1"])
    assert federation.peers() == ["http://a:1"]
    federation.reset()
    assert federation.peers() == ["http://10.0.0.2:9100",
                                  "http://10.0.0.3:9100"]


# ===========================================================================
# flight recorder
# ===========================================================================
def test_flight_record_deltas_and_ring_bound():
    rec = flight.FlightRecorder(maxlen=4)
    telemetry.inc("comm.collectives", 2)
    rec.record_step("fused_step", 10.0)
    telemetry.inc("comm.collectives", 3)
    r = rec.record_step("fused_step", 11.0)
    assert r["deltas"]["comm.collectives"] == 3
    for i in range(10):
        rec.record_step("fused_step", float(i))
    recs = rec.records()
    assert len(recs) == 4                      # bounded ring
    assert recs[-1]["seq"] == 12


def test_flight_buffers_events_and_retrace_reasons():
    flight.note_event("checkpoint", "step=3")
    flight.note_retrace("FusedTrainStep", "arg0 shape (2,3)->(4,3)")
    telemetry.step_event("fused_step", 5.0)
    rec = telemetry.flight_records()[-1]
    assert rec["events"] == ["checkpoint step=3"]
    assert "arg0 shape" in rec["retrace_reasons"][0]
    # buffers drain into ONE record
    telemetry.step_event("fused_step", 5.0)
    rec2 = telemetry.flight_records()[-1]
    assert "events" not in rec2 and "retrace_reasons" not in rec2


def test_flight_dump_roundtrip(tmp_path):
    telemetry.step_event("trainer", 7.0)
    path = flight.dump(str(tmp_path / "flight.json"), reason="test")
    obj = json.load(open(path))
    assert obj["reason"] == "test"
    assert obj["trace_id"] == telemetry.trace_id()
    assert obj["records"][-1]["site"] == "trainer"
    assert flight.dump(str(tmp_path / "nope.json")) is not None
    flight.reset()
    assert flight.dump(str(tmp_path / "empty.json")) is None


def test_stall_post_mortem_embeds_flight_ring():
    """ISSUE acceptance: an injected hang's StallError carries the flight
    ring and format_report() renders it."""
    telemetry.step_event("fused_step", 12.0)
    telemetry.step_event("fused_step", 13.0)
    with pytest.raises(StallError) as ei:
        with faults.inject("obs.site:hang:1:30"):
            with watchdog.guard("obs.site", deadline_s=0.25):
                faults.check("obs.site")
    err = ei.value
    assert err.flight_dump, "StallError must embed the flight ring"
    assert err.flight_dump[-1]["site"] == "fused_step"
    report = err.format_report()
    assert "flight recorder" in report
    assert "fused_step" in report


def test_runner_stall_flight_ledger(tmp_path):
    """End-to-end: a fused-step run that hangs produces a StallError whose
    flight ring shows the steps that led up to it, and the recovered run's
    ledger carries the restore event."""
    mx.random.seed(42)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    rng = np.random.RandomState(0)
    X = rng.rand(4, 8, 6).astype(np.float32)
    Y = rng.randint(0, 3, (4, 8)).astype(np.float32)
    batch_fn = lambda i: (nd.array(X[i]), nd.array(Y[i]))  # noqa: E731
    with faults.inject("train.step:hang:3:30"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=str(tmp_path / "ck"), ckpt_every=1,
            max_restarts=2, step_deadline_s=0.5)
        report = runner.run(4)
    assert report.restarts == 1
    events = [e for r in telemetry.flight_records()
              for e in r.get("events", [])]
    assert any(e.startswith("restore") for e in events), events
    assert any(e.startswith("checkpoint") for e in events), events


def test_flight_crash_dump_on_unhandled_exception(tmp_path):
    """The excepthook chain dumps the ring when the process dies on an
    unhandled exception."""
    code = r"""
import mxnet_tpu
from mxnet_tpu import telemetry
telemetry.step_event("fused_step", 9.0)
raise RuntimeError("synthetic crash for the flight recorder")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_FLIGHT_DIR=str(tmp_path))
    env.pop("MXNET_TPU_TELEMETRY", None)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode != 0
    assert "flight recorder dumped to" in r.stderr, r.stderr
    dumps = [f for f in os.listdir(str(tmp_path))
             if f.startswith("flight_rank0_")]
    assert len(dumps) == 1
    obj = json.load(open(os.path.join(str(tmp_path), dumps[0])))
    assert "synthetic crash" in obj["reason"]
    assert obj["records"][-1]["site"] == "fused_step"


def test_runner_dumps_flight_on_fatal(tmp_path, monkeypatch):
    """A run dying on an exhausted restart budget leaves a flight dump."""
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    calls = {"n": 0}

    def step_fn(i):
        calls["n"] += 1
        telemetry.step_event("train_step", 1.0)
        raise PreemptionError("host keeps dying")

    runner = rz.ResilientRunner(
        step_fn, state_get=lambda: {"x": 1}, state_set=lambda t: None,
        ckpt_dir=str(tmp_path / "ck"), ckpt_every=1, max_restarts=1)
    with pytest.raises(PreemptionError):
        runner.run(3)
    dumps = [f for f in os.listdir(str(tmp_path))
             if f.startswith("flight_rank0_")]
    assert len(dumps) == 1
    obj = json.load(open(os.path.join(str(tmp_path), dumps[0])))
    assert "PreemptionError" in obj["reason"]


# ===========================================================================
# anomaly detection
# ===========================================================================
def test_anomaly_counter_fires_on_step_time_regression():
    """ISSUE satellite: a synthetic step-time regression trips the rolling-
    median detector — counter + per-site counter + marker span."""
    for _ in range(12):
        telemetry.step_event("fused_step", 10.0)
    assert _counter("telemetry.anomaly.step_time") == 0
    telemetry.step_event("fused_step", 500.0)   # 50× the median
    assert _counter("telemetry.anomaly.step_time") == 1
    assert _counter("telemetry.anomaly.step_time.fused_step") == 1
    names = [e[0] for e in telemetry.span_events()]
    assert "anomaly@fused_step" in names
    rec = telemetry.flight_records()[-1]
    assert rec["anomalies"] == ["step_time"]


def test_anomaly_quiet_on_steady_cadence_and_warmup():
    tracker = anomaly.StepTimeTracker(factor=4.0)
    # the first WARMUP steps never fire, even when wildly different
    assert tracker.observe("s", 1.0) == []
    assert tracker.observe("s", 1000.0) == []
    t2 = anomaly.StepTimeTracker(factor=4.0)
    for _ in range(20):
        assert t2.observe("s", 10.0) == []
    assert t2.observe("s", 20.0) == []          # 2× median: fine


def test_anomaly_slo_tracking(monkeypatch):
    tracker = anomaly.StepTimeTracker(slo_ms=50.0)
    assert [k for k, _ in tracker.observe("s", 60.0)] == ["slo"]
    assert tracker.observe("s", 10.0) == []
    monkeypatch.setenv("MXNET_TPU_STEP_SLO_MS", "25")
    anomaly.reset()
    telemetry.step_event("train_step", 30.0)
    assert _counter("telemetry.anomaly.slo") == 1
    assert _counter("telemetry.anomaly.slo.train_step") == 1


def test_step_quantiles():
    for ms in range(1, 101):
        telemetry.step_event("trainer", float(ms))
    q = telemetry.step_quantiles("trainer")
    # window 64: the last 64 observations are 37..100
    assert q["n"] == 64
    assert 60 <= q["p50"] <= 75
    assert q["p99"] >= 99
    assert telemetry.step_quantiles()["trainer"] == q
    assert telemetry.step_quantiles("unseen") is None


# ===========================================================================
# resilience satellites
# ===========================================================================
def test_ckpt_save_ms_histogram_recorded(tmp_path):
    runner = rz.ResilientRunner(
        lambda i: 0.0, state_get=lambda: {"x": 1},
        state_set=lambda t: None, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=1)
    runner.run(3)
    h = telemetry.snapshot()["histograms"]["ckpt.save_ms"]
    assert h["count"] == 3
    assert h["max"] > 0


def test_preempt_skips_save_when_window_too_short(tmp_path):
    """SIGTERM deadline awareness: with the rolling max save time bigger
    than the remaining grace window, the proactive save is skipped and
    recovery falls back to restore-and-replay."""
    # seed the save-cost ledger with a pathologically slow save
    telemetry.observe("ckpt.save_ms", 60000.0)
    listener = PreemptionListener(poll_fn=False, sigterm=False,
                                  grace_s=0.5)
    listener.notify("maintenance imminent", "poll")
    runner = rz.ResilientRunner(
        lambda i: 0.0, state_get=lambda: {"x": 1},
        state_set=lambda t: None, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=100, preempt_listener=listener)
    saves0 = _counter("resilience.proactive_checkpoints")
    with pytest.raises(PreemptionError) as ei:
        runner._check_preempt(5, rz.RunReport())
    assert "skipped" in str(ei.value)
    assert _counter("resilience.preempt.save_skipped") == 1
    assert _counter("resilience.proactive_checkpoints") == saves0


def test_preempt_saves_when_window_fits(tmp_path):
    telemetry.observe("ckpt.save_ms", 5.0)      # fast saves
    listener = PreemptionListener(poll_fn=False, sigterm=False,
                                  grace_s=30.0)
    listener.notify("maintenance imminent", "poll")
    runner = rz.ResilientRunner(
        lambda i: 0.0, state_get=lambda: {"x": 1},
        state_set=lambda t: None, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=100, preempt_listener=listener)
    report = rz.RunReport()
    with pytest.raises(PreemptionError) as ei:
        runner._check_preempt(5, report)
    assert "committed" in str(ei.value)
    assert report.proactive_ckpts == 1
    assert _counter("resilience.preempt.save_skipped") == 0


def test_notice_deadline_and_grace_env(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PREEMPT_GRACE_S", "7")
    n = PreemptionNotice("r", "sigterm")
    assert n.deadline - n.received_at == pytest.approx(7.0)
    assert 6.0 < n.remaining_s() <= 7.0
    n2 = PreemptionNotice("r", "poll", grace_s=0.0)
    assert n2.remaining_s() <= 0.0


class _FakeCoordClient:
    def __init__(self):
        self.kv = {}
        self.deleted = []

    def key_value_set(self, key, value):
        self.kv[key] = value

    def wait_at_barrier(self, key, timeout_ms):
        pass

    def blocking_key_value_get(self, key, timeout_ms):
        return self.kv[key]

    def key_value_delete(self, key):
        self.deleted.append(key)
        self.kv.pop(key, None)


def test_commit_cleanup_round_bounds_kv_growth():
    """ROADMAP carry-over: every KV election reclaims the previous round's
    key, so coordinator-KV growth is bounded over long runs."""
    from mxnet_tpu.resilience import commit as commit_mod
    coord = CommitCoordinator()
    client = _FakeCoordClient()
    rounds = [commit_mod._next_round() for _ in range(4)]
    steps = coord._exchange_kv(client, 10, "save", rounds[0])
    assert steps == [10]
    assert client.deleted == []                # nothing to reclaim yet
    coord._exchange_kv(client, 11, "save", rounds[1])
    coord._exchange_kv(client, 11, "restore", rounds[2])
    coord._exchange_kv(client, 12, "save", rounds[3])
    assert len(client.deleted) == 3
    # mixed kinds reclaim the right namespaces, in order
    assert "save/round_%d" % rounds[0] in client.deleted[0]
    assert "save/round_%d" % rounds[1] in client.deleted[1]
    assert "restore/round_%d" % rounds[2] in client.deleted[2]
    # steady state: exactly ONE live key per rank
    assert len(client.kv) == 1
    assert _counter("resilience.commit.cleanups") == 3


def test_commit_cleanup_reclaims_failed_rounds():
    """A round whose barrier dies still gets its key reclaimed by the next
    successful election (flaky coordinators must not leak a key per
    failure)."""
    from mxnet_tpu.resilience import commit as commit_mod

    class FlakyBarrier(_FakeCoordClient):
        def __init__(self):
            super().__init__()
            self.fail_next = False

        def wait_at_barrier(self, key, timeout_ms):
            if self.fail_next:
                self.fail_next = False
                raise TimeoutError("barrier timed out")

    coord = CommitCoordinator()
    client = FlakyBarrier()
    client.fail_next = True
    with pytest.raises(TimeoutError):
        coord._exchange_kv(client, 3, "save", commit_mod._next_round())
    assert len(client.kv) == 1                 # the failed round's key
    coord._exchange_kv(client, 4, "save", commit_mod._next_round())
    assert len(client.kv) == 1                 # failed round reclaimed
    assert len(client.deleted) == 1


def test_preempt_skips_save_when_grace_already_expired(tmp_path):
    """Even with NO save history, an expired grace window skips the save
    (starting a save with zero budget guarantees the torn write)."""
    listener = PreemptionListener(poll_fn=False, sigterm=False,
                                  grace_s=0.0)
    listener.notify("too late", "poll")
    runner = rz.ResilientRunner(
        lambda i: 0.0, state_get=lambda: {"x": 1},
        state_set=lambda t: None, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=100, preempt_listener=listener)
    report = rz.RunReport()
    with pytest.raises(PreemptionError):
        runner._check_preempt(0, report)
    assert _counter("resilience.preempt.save_skipped") == 1
    assert report.proactive_ckpts == 0


def test_worst_save_ms_is_rolling_not_lifetime(tmp_path):
    """One cold outlier save must age out of the budgeting window once
    later saves are fast (a lifetime max would disable proactive
    checkpoints forever)."""
    runner = rz.ResilientRunner(
        lambda i: 0.0, state_get=lambda: {"x": 1},
        state_set=lambda t: None, ckpt_dir=str(tmp_path / "ck"),
        ckpt_every=1)
    runner._save_ms_window.append(60000.0)     # the cold outlier
    assert runner._worst_save_ms() == 60000.0
    for _ in range(8):                          # window maxlen
        runner._save_ms_window.append(5.0)
    assert runner._worst_save_ms() == 5.0
    # before this runner's first save, the histogram max is the prior
    runner._save_ms_window.clear()
    telemetry.observe("ckpt.save_ms", 123.0)
    assert runner._worst_save_ms() == 123.0


def test_commit_cleanup_survives_missing_delete_support():
    from mxnet_tpu.resilience import commit as commit_mod

    class NoDelete(_FakeCoordClient):
        def key_value_delete(self, key):
            raise RuntimeError("UNIMPLEMENTED")

    coord = CommitCoordinator()
    client = NoDelete()
    coord._exchange_kv(client, 1, "save", commit_mod._next_round())
    steps = coord._exchange_kv(client, 2, "save", commit_mod._next_round())
    assert steps == [2]                        # election unharmed
    assert _counter("resilience.commit.cleanups") == 0


# ===========================================================================
# tooling: parse_log modes + mxtop
# ===========================================================================
def test_parse_log_flight_mode(tmp_path):
    telemetry.inc("comm.collectives", 4)
    telemetry.step_event("fused_step", 10.0)
    dump = flight.dump(str(tmp_path / "flight.json"), reason="test")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--flight", "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0] == "step,site,step_ms,anomalies,compiles,events,notes"
    assert any("fused_step" in l and "coll=4" in l for l in lines[1:])


def test_parse_log_anomalies_mode(tmp_path):
    # the real step paths observe the histogram AND fire step_event
    for ms in [10.0] * 12 + [999.0]:
        telemetry.observe("trainer.step_ms", ms)
        telemetry.step_event("trainer", ms)
    dump = str(tmp_path / "telemetry.json")
    telemetry.dump(dump)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--anomalies", "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "step_time,count,1" in r.stdout
    assert "step_time.trainer,count,1" in r.stdout
    assert "trainer.step_ms,max_ms,999" in r.stdout


def test_parse_log_serve_mode(tmp_path):
    """`parse_log.py --serve`: tokens/s, ttft/tpot quantiles, pressure
    gauges, and shed counts from a telemetry dump (ISSUE 8 CI satellite)."""
    telemetry.inc("serve.requests", 10)
    telemetry.inc("serve.completed", 8)
    telemetry.inc("serve.tokens", 64)
    telemetry.inc("serve.shed", 2)
    telemetry.inc("serve.shed.queue_full", 2)
    telemetry.set_gauge("serve.tokens_per_s", 123.4)
    telemetry.set_gauge("serve.queue_depth", 0)
    telemetry.set_gauge("serve.queue_depth", 3)
    telemetry.set_gauge("serve.queue_depth", 0)
    for ms in (5.0, 6.0, 50.0):
        telemetry.observe("serve.ttft_ms", ms)
        telemetry.observe("serve.tpot_ms", ms / 10)
    dump = str(tmp_path / "serve.json")
    telemetry.dump(dump)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--serve", "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0] == "metric,value"
    rows = dict(l.rsplit(",", 1) for l in lines[1:])
    assert rows["tokens_per_s"] == "123.4"
    assert rows["requests"] == "10"
    assert rows["shed"] == "2" and rows["shed.queue_full"] == "2"
    assert rows["queue_depth_peak"] == "3"
    assert float(rows["ttft_ms_p50"]) > 0
    assert float(rows["tpot_ms_p99"]) > 0


def test_mxtop_once_from_stream(tmp_path):
    telemetry.inc("comm.collectives", 9)
    telemetry.set_gauge("memory.cpu0.bytes_in_use", 4096)
    telemetry.step_event("fused_step", 3.0)
    path = str(tmp_path / "stream.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(export.snapshot_payload()) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mxtop.py"),
         "--stream", path, "--once"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "mxtop" in r.stdout
    assert "fused_step" in r.stdout
    assert "collectives" in r.stdout
    assert "cpu0" in r.stdout


@pytest.mark.obs
def test_mxtop_once_from_endpoint():
    telemetry.step_event("trainer", 2.0)
    server = export.start_http_server(0)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mxtop.py"),
         "--port", str(server.port), "--once"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "trainer" in r.stdout


def test_mxtop_serve_view_single_and_fleet(tmp_path):
    """`mxtop --serve` renders tokens/s, queue/batch pressure, shed
    counts and TTFT/TPOT quantiles from a single /snapshot payload AND
    from a /fleet/snapshot payload (one row per rank + fleet totals)."""
    from mxnet_tpu.telemetry import federation
    telemetry.inc("serve.requests", 10)
    telemetry.inc("serve.completed", 8)
    telemetry.inc("serve.shed", 2)
    telemetry.inc("serve.shed.queue_full", 2)
    telemetry.set_gauge("serve.tokens_per_s", 123.4)
    telemetry.set_gauge("serve.queue_depth", 3)
    telemetry.set_gauge("serve.batch_occupancy", 4)
    for ms in (5.0, 6.0, 50.0):
        telemetry.observe("serve.ttft_ms", ms)
        telemetry.observe("serve.tpot_ms", ms / 10)
    single = str(tmp_path / "single.jsonl")
    with open(single, "w") as f:
        f.write(json.dumps(export.snapshot_payload()) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mxtop.py"),
         "--stream", single, "--serve", "--once"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "123.40" in r.stdout          # tokens/s
    assert "queue_full=2" in r.stdout    # shed by reason
    assert "ttft p50/p99" in r.stdout
    fleet = str(tmp_path / "fleet.jsonl")
    with open(fleet, "w") as f:
        f.write(json.dumps(federation.fleet_snapshot()) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mxtop.py"),
         "--stream", fleet, "--serve", "--once"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "fleet: 1 rank(s)" in r.stdout
    assert any(line.startswith("  fleet ")      # fleet totals row present
               for line in r.stdout.splitlines())


def test_mxtop_once_fails_cleanly_without_target(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mxtop.py"),
         "--stream", str(tmp_path / "missing.jsonl"), "--once"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0
    assert "cannot read" in r.stderr


# ===========================================================================
# tracelint: the new threaded modules stay TPU006-clean, no suppressions
# ===========================================================================
@pytest.mark.lint
def test_new_observability_modules_tpu006_clean():
    from mxnet_tpu import analysis
    paths = [os.path.join(REPO, "mxnet_tpu", "telemetry", m)
             for m in ("export.py", "flight.py", "anomaly.py",
                       "federation.py", "request_trace.py",
                       "attribution.py")]
    findings = [f for p in paths
                for f in analysis.lint_file(p, rules=["TPU006"])]
    assert not findings, "\n".join(f.format() for f in findings)
    for p in paths:
        src = open(p).read()
        assert "tpu-lint: disable" not in src, \
            "%s must stay clean WITHOUT suppressions" % p
