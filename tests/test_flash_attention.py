"""Pallas flash-attention kernel correctness (interpreter mode).

reference contrast: src/operator/contrib/transformer.cc keeps the S^2
probability matrix in HBM for the backward; these kernels recompute each
tile from the saved logsumexp, so dq/dk/dv are O(S) HBM. The suite runs
the REAL kernels through the Pallas interpreter on the CPU mesh
(MXNET_FLASH_INTERPRET=1) and checks both directions against the plain-XLA
reference; the on-chip run (MXNET_TEST_DEVICE=tpu) compiles the same
kernels for the MXU.
"""
import os
import sys

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu  # noqa: F401 — ensures package import order
fa = sys.modules["mxnet_tpu.parallel.flash_attention"]


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    # Interpreter mode pins the kernel math on the host; the on-chip run
    # (MXNET_TEST_DEVICE=tpu) must NOT have it set — even inherited from
    # the caller's environment — so the kernels compile natively for the
    # MXU; native tiling/layout/VMEM failures are invisible to the
    # interpreter (round-4 VERDICT weak #2).
    from mxnet_tpu.test_utils import is_accel_test_device
    if is_accel_test_device():
        monkeypatch.delenv("MXNET_FLASH_INTERPRET", raising=False)
    else:
        monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
    yield


def _rand(shape, seed):
    return jnp.asarray(onp.random.RandomState(seed).randn(*shape)
                       .astype("float32"))


CASES = [
    # B, H, Hkv, Sq, Sk, D, causal
    (2, 4, 4, 128, 128, 64, False),
    (2, 4, 4, 128, 128, 64, True),
    (1, 8, 2, 256, 256, 64, True),     # GQA
    (1, 2, 2, 160, 160, 64, False),    # non-128-multiple seq
    (1, 2, 2, 160, 160, 64, True),
    (1, 2, 2, 96, 224, 64, True),      # Sq != Sk causal (decode window)
]


@pytest.mark.parametrize("B,H,Hkv,Sq,Sk,D,causal", CASES)
def test_forward_matches_reference(B, H, Hkv, Sq, Sk, D, causal):
    q = _rand((B, H, Sq, D), 0)
    k = _rand((B, Hkv, Sk, D), 1)
    v = _rand((B, Hkv, Sk, D), 2)
    sc = D ** -0.5
    out = fa._flash(q, k, v, causal, sc)
    ref = fa._ref_attention(q, k, v, causal, sc)
    onp.testing.assert_allclose(out, ref, atol=2e-4, rtol=1e-4)


@pytest.mark.parametrize("B,H,Hkv,Sq,Sk,D,causal", CASES)
def test_backward_matches_reference(B, H, Hkv, Sq, Sk, D, causal):
    q = _rand((B, H, Sq, D), 3)
    k = _rand((B, Hkv, Sk, D), 4)
    v = _rand((B, Hkv, Sk, D), 5)
    sc = D ** -0.5
    # weighted sum so cotangents vary per position
    w = _rand((B, H, Sq, D), 6)

    def loss_pl(q_, k_, v_):
        return jnp.sum(fa._flash(q_, k_, v_, causal, sc) * w)

    def loss_ref(q_, k_, v_):
        return jnp.sum(fa._ref_attention(q_, k_, v_, causal, sc) * w)

    g_pl = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_pl, g_ref, ["dq", "dk", "dv"]):
        onp.testing.assert_allclose(got, want, atol=5e-3, rtol=1e-3,
                                    err_msg=name)


def test_lse_is_logsumexp():
    q = _rand((1, 2, 128, 64), 7)
    k = _rand((1, 2, 128, 64), 8)
    v = _rand((1, 2, 128, 64), 9)
    sc = 64 ** -0.5
    _, lse = fa._pallas_forward(q, k, v, False, sc)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sc
    want = jax.scipy.special.logsumexp(logits, axis=-1)
    onp.testing.assert_allclose(lse, want, atol=2e-4, rtol=1e-4)


def test_grad_under_jit_and_bf16():
    q = _rand((1, 2, 128, 64), 10).astype(jnp.bfloat16)
    k = _rand((1, 2, 128, 64), 11).astype(jnp.bfloat16)
    v = _rand((1, 2, 128, 64), 12).astype(jnp.bfloat16)

    @jax.jit
    def step(q_, k_, v_):
        return jax.grad(
            lambda a, b, c: jnp.sum(
                fa.flash_attention(a, b, c, causal=True)
                .astype(jnp.float32)))(q_, k_, v_)

    dq = step(q, k, v)
    assert dq.dtype == jnp.bfloat16 and bool(jnp.isfinite(
        dq.astype(jnp.float32)).all())


# ---------------------------------------------------------------------------
# flash-kernel ring attention (sequence parallelism) — both directions run
# the Pallas kernels per ring block; backward's dk/dv ride the ring home.
# check_vma=False: the interpreter's block slicing can't mix vma'd operands
# with unvaried grid indices (TPU mosaic lowering has no such restriction).
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("H,Hkv,causal", [(2, 2, False), (2, 2, True),
                                          (4, 2, True)])
def test_ring_flash_matches_full_attention(H, Hkv, causal):
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    ra = sys.modules["mxnet_tpu.parallel.ring_attention"]

    devs = jax.devices()[:4]
    mesh = Mesh(onp.array(devs), ("seq",))
    B, S, D = 1, 512, 64
    q = _rand((B, H, S, D), 20)
    k = _rand((B, Hkv, S, D), 21)
    v = _rand((B, Hkv, S, D), 22)
    w = _rand((B, H, S, D), 23)
    sc = D ** -0.5

    body = lambda q_, k_, v_: ra.ring_attention(q_, k_, v_,  # noqa: E731
                                                axis_name="seq",
                                                causal=causal)
    kw = dict(mesh=mesh, in_specs=(P(None, None, "seq", None),) * 3,
              out_specs=P(None, None, "seq", None))
    try:
        f = shard_map(body, check_vma=False, **kw)
    except TypeError:   # the 0.4.x line names the flag check_rep
        f = shard_map(body, check_rep=False, **kw)
    o_ring = f(q, k, v)
    o_ref = fa._ref_attention(q, k, v, causal, sc)
    onp.testing.assert_allclose(o_ring, o_ref, atol=5e-4, rtol=1e-4)

    g_ring = jax.grad(lambda a, b, c: jnp.sum(f(a, b, c) * w),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(fa._ref_attention(a, b, c, causal, sc) * w),
        argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_ring, g_ref, ["dq", "dk", "dv"]):
        onp.testing.assert_allclose(got, want, atol=5e-3, rtol=1e-3,
                                    err_msg=name)
