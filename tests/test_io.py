"""IO: NDArrayIter + .params serde (reference: tests/python/unittest/test_io.py)."""
import os
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_ndarray_iter():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4
    # discard mode
    it2 = mx.io.NDArrayIter(data, label, batch_size=3, last_batch_handle="discard")
    assert len(list(it2)) == 3
    # shuffle determinism by seed
    a = [b.data[0].asnumpy() for b in mx.io.NDArrayIter(data, label, 5, shuffle=True, shuffle_seed=3)]
    b = [b.data[0].asnumpy() for b in mx.io.NDArrayIter(data, label, 5, shuffle=True, shuffle_seed=3)]
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


def test_params_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "test.params")
    d = {"arg:weight": nd.array(np.random.rand(3, 4)),
         "aux:mean": nd.ones((5,), dtype="int32"),
         "b16": nd.ones((2, 2), dtype="float16")}
    nd.save(fname, d)
    back = nd.load(fname)
    assert set(back) == set(d)
    for k in d:
        assert back[k].dtype == d[k].dtype
        assert np.array_equal(back[k].asnumpy(), d[k].asnumpy())


def test_params_save_load_list(tmp_path):
    fname = str(tmp_path / "list.params")
    nd.save(fname, [nd.ones((2,)), nd.zeros((3,))])
    back = nd.load(fname)
    assert isinstance(back, list) and len(back) == 2
    assert np.array_equal(back[0].asnumpy(), [1, 1])


def test_prefetching_iter():
    data = np.arange(24).reshape(12, 2).astype(np.float32)
    it = mx.io.PrefetchingIter(mx.io.NDArrayIter(data, None, batch_size=4))
    n = 0
    for batch in it:
        n += 1
        assert batch.data[0].shape == (4, 2)
    assert n == 3
