"""IO: NDArrayIter + .params serde (reference: tests/python/unittest/test_io.py)."""
import os
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_ndarray_iter():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4
    # discard mode
    it2 = mx.io.NDArrayIter(data, label, batch_size=3, last_batch_handle="discard")
    assert len(list(it2)) == 3
    # shuffle determinism by seed
    a = [b.data[0].asnumpy() for b in mx.io.NDArrayIter(data, label, 5, shuffle=True, shuffle_seed=3)]
    b = [b.data[0].asnumpy() for b in mx.io.NDArrayIter(data, label, 5, shuffle=True, shuffle_seed=3)]
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


def test_params_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "test.params")
    d = {"arg:weight": nd.array(np.random.rand(3, 4)),
         "aux:mean": nd.ones((5,), dtype="int32"),
         "b16": nd.ones((2, 2), dtype="float16")}
    nd.save(fname, d)
    back = nd.load(fname)
    assert set(back) == set(d)
    for k in d:
        assert back[k].dtype == d[k].dtype
        assert np.array_equal(back[k].asnumpy(), d[k].asnumpy())


def test_params_save_load_list(tmp_path):
    fname = str(tmp_path / "list.params")
    nd.save(fname, [nd.ones((2,)), nd.zeros((3,))])
    back = nd.load(fname)
    assert isinstance(back, list) and len(back) == 2
    assert np.array_equal(back[0].asnumpy(), [1, 1])


def test_prefetching_iter():
    data = np.arange(24).reshape(12, 2).astype(np.float32)
    it = mx.io.PrefetchingIter(mx.io.NDArrayIter(data, None, batch_size=4))
    n = 0
    for batch in it:
        n += 1
        assert batch.data[0].shape == (4, 2)
    assert n == 3


# ---------------------------------------------------------------------------
# C++-backed iterator classes (reference: src/io/iter_image_recordio_2.cc,
# iter_csv.cc, iter_mnist.cc)
# ---------------------------------------------------------------------------

def _make_rec(tmp_path, n=24, size=40, classes=4, with_idx=True):
    """Write a tiny .rec(+.idx) pack of random images via recordio.pack_img."""
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rng = np.random.RandomState(0)
    labels = rng.randint(0, classes, n)
    if with_idx:
        w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    else:
        w = recordio.MXRecordIO(rec_path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        hdr = recordio.IRHeader(0, float(labels[i]), i, 0)
        buf = recordio.pack_img(hdr, img, img_fmt=".jpg")
        if with_idx:
            w.write_idx(i, buf)
        else:
            w.write(buf)
    w.close()
    return rec_path, idx_path, labels


def test_image_record_iter(tmp_path):
    rec, idx, labels = _make_rec(tmp_path)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec, path_imgidx=idx, data_shape=(3, 32, 32),
        batch_size=8, shuffle=False, preprocess_threads=2)
    batches = list(it)
    assert len(batches) == 3
    b = batches[0]
    assert b.data[0].shape == (8, 3, 32, 32)
    assert b.label[0].shape == (8,)
    np.testing.assert_array_equal(b.label[0].asnumpy(), labels[:8])
    # reset reproduces the epoch
    it.reset()
    again = list(it)
    np.testing.assert_allclose(again[0].data[0].asnumpy(),
                               batches[0].data[0].asnumpy())


def test_image_record_iter_no_idx_round_batch(tmp_path):
    rec, _, labels = _make_rec(tmp_path, n=10, with_idx=False)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 32, 32), batch_size=8,
        round_batch=True, preprocess_threads=1)
    batches = list(it)
    assert len(batches) == 2
    assert batches[1].pad == 6  # 10 % 8 -> wraps 6 from the epoch head


def test_image_record_iter_augment_and_partition(tmp_path):
    rec, idx, _ = _make_rec(tmp_path, n=20)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec, path_imgidx=idx, data_shape=(3, 24, 24),
        batch_size=5, shuffle=True, seed=7, rand_crop=True, rand_mirror=True,
        mean_r=123.0, mean_g=117.0, mean_b=104.0, std_r=58.0, std_g=57.0,
        std_b=57.0, part_index=0, num_parts=2, preprocess_threads=2)
    batches = list(it)
    assert len(batches) == 2  # 10-record partition / 5
    x = batches[0].data[0].asnumpy()
    assert abs(x.mean()) < 2.0  # normalized scale, not raw pixels


def test_image_record_iter_trains_zoo_resnet(tmp_path):
    """The verdict's done-criterion: ImageRecordIter feeds a model_zoo
    resnet through a real fused training step."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    rec, idx, _ = _make_rec(tmp_path, n=16, size=36, classes=4)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec, path_imgidx=idx, data_shape=(3, 32, 32),
        batch_size=8, shuffle=True, seed=1, preprocess_threads=2,
        scale=1.0 / 255)
    mx.random.seed(11)
    net = vision.resnet18_v1(classes=4)
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    first = next(iter(it))
    net(first.data[0])
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.005, "momentum": 0.9})
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer)
    losses = []
    for _ in range(6):  # epochs over the tiny pack (memorization)
        it.reset()
        epoch = [float(fused(batch.data[0], batch.label[0]).asnumpy())
                 for batch in it]
        losses.append(sum(epoch) / len(epoch))
    assert losses[-1] < losses[0], losses


def test_csv_iter(tmp_path):
    data_csv = str(tmp_path / "d.csv")
    label_csv = str(tmp_path / "l.csv")
    rng = np.random.RandomState(3)
    d = rng.rand(11, 6).astype(np.float32)
    l = rng.randint(0, 3, (11, 1)).astype(np.float32)
    np.savetxt(data_csv, d, delimiter=",")
    np.savetxt(label_csv, l, delimiter=",")
    it = mx.io.CSVIter(data_csv=data_csv, data_shape=(2, 3),
                       label_csv=label_csv, batch_size=4)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 2, 3)
    assert batches[2].pad == 1
    np.testing.assert_allclose(batches[0].data[0].asnumpy(),
                               d[:4].reshape(4, 2, 3), rtol=1e-5)
    np.testing.assert_array_equal(batches[0].label[0].asnumpy(), l[:4, 0])
    it.reset()
    assert len(list(it)) == 3


def test_mnist_iter(tmp_path):
    import struct as _struct
    # synthesize idx-ubyte files (magic 2051 images / 2049 labels)
    n, h, w = 30, 28, 28
    rng = np.random.RandomState(5)
    imgs = rng.randint(0, 255, (n, h, w), dtype=np.uint8)
    labs = rng.randint(0, 10, n).astype(np.uint8)
    img_path = str(tmp_path / "images-idx3-ubyte")
    lab_path = str(tmp_path / "labels-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(_struct.pack(">IIII", 2051, n, h, w))
        f.write(imgs.tobytes())
    with open(lab_path, "wb") as f:
        f.write(_struct.pack(">II", 2049, n))
        f.write(labs.tobytes())
    it = mx.io.MNISTIter(image=img_path, label=lab_path, batch_size=8,
                         shuffle=False, silent=True)
    batches = list(it)
    assert len(batches) == 3  # tail dropped like the reference
    assert batches[0].data[0].shape == (8, 1, 28, 28)
    np.testing.assert_allclose(batches[0].data[0].asnumpy()[:, 0],
                               imgs[:8].astype(np.float32) / 255.0)
    np.testing.assert_array_equal(batches[0].label[0].asnumpy(), labs[:8])
    # flat mode
    it2 = mx.io.MNISTIter(image=img_path, label=lab_path, batch_size=8,
                          shuffle=True, flat=True, seed=2, silent=True)
    b = next(iter(it2))
    assert b.data[0].shape == (8, 784)


def test_image_record_iter_exhaustion_no_hang(tmp_path):
    """Iterating past the epoch without reset() must raise StopIteration
    immediately, not block on the prefetch queue."""
    rec, idx, _ = _make_rec(tmp_path, n=8)
    it = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                               data_shape=(3, 32, 32), batch_size=8,
                               preprocess_threads=1)
    assert len(list(it)) == 1
    assert len(list(it)) == 0  # immediate, no deadlock
    it.reset()
    assert len(list(it)) == 1


def test_image_record_iter_seeded_augment_reproducible(tmp_path):
    rec, idx, _ = _make_rec(tmp_path, n=12)
    def epoch():
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx, data_shape=(3, 24, 24),
            batch_size=4, shuffle=True, seed=5, rand_crop=True,
            rand_mirror=True, preprocess_threads=3)
        return [b.data[0].asnumpy() for b in it]
    a, b = epoch(), epoch()
    assert len(a) == len(b) == 3
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_csv_iter_no_round_batch_keeps_tail(tmp_path):
    data_csv = str(tmp_path / "d2.csv")
    np.savetxt(data_csv, np.arange(10, dtype=np.float32).reshape(10, 1),
               delimiter=",")
    it = mx.io.CSVIter(data_csv=data_csv, data_shape=(1,), batch_size=4,
                       round_batch=False)
    batches = list(it)
    assert len(batches) == 3
    assert batches[2].pad == 2
    # tail values served, pad filled with the last row (not wrapped)
    np.testing.assert_array_equal(batches[2].data[0].asnumpy()[:2, 0], [8, 9])


def test_image_record_iter_pad_exceeds_epoch(tmp_path):
    """batch_size > 2x records: fill tiles the tiny epoch, no garbage rows."""
    rec, idx, labels = _make_rec(tmp_path, n=3)
    it = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                               data_shape=(3, 32, 32), batch_size=8,
                               round_batch=True, preprocess_threads=1)
    b = next(iter(it))
    assert b.pad == 5
    got = b.label[0].asnumpy()
    exp = np.tile(labels, 3)[:8]
    np.testing.assert_array_equal(got, exp)


def test_image_record_iter_mean_img_computed(tmp_path):
    """Missing mean_img file is computed over the pack and persisted
    (reference: src/io/iter_normalize.h)."""
    rec, idx, _ = _make_rec(tmp_path, n=6, size=32)
    mean_path = str(tmp_path / "mean.bin")
    it = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                               data_shape=(3, 32, 32), batch_size=6,
                               mean_img=mean_path, preprocess_threads=1)
    assert os.path.exists(mean_path)
    b = next(iter(it))
    # mean-subtracted batch over the whole pack has ~zero mean
    assert abs(b.data[0].asnumpy().mean()) < 1.0
    # second iterator loads the saved file and agrees
    it2 = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                                data_shape=(3, 32, 32), batch_size=6,
                                mean_img=mean_path, preprocess_threads=1)
    b2 = next(iter(it2))
    np.testing.assert_allclose(b2.data[0].asnumpy(), b.data[0].asnumpy())


def test_csv_iter_wrapped_lines(tmp_path):
    """Rows may wrap file lines (np.loadtxt-reshape semantics): 4 logical
    rows of width 6 written 4 values per line must round-trip exactly."""
    path = str(tmp_path / "wrap.csv")
    vals = np.arange(24, dtype=np.float32)
    with open(path, "w") as f:
        for i in range(0, 24, 4):
            f.write(",".join(str(v) for v in vals[i:i + 4]) + "\n")
    it = mx.io.CSVIter(data_csv=path, data_shape=(6,), batch_size=1)
    rows = [b.data[0].asnumpy()[0] for b in it]
    assert len(rows) == 4
    np.testing.assert_array_equal(np.concatenate(rows), vals)
    # a single long line holding several rows also works
    path2 = str(tmp_path / "long.csv")
    with open(path2, "w") as f:
        f.write(",".join(str(v) for v in vals) + "\n")
    it2 = mx.io.CSVIter(data_csv=path2, data_shape=(6,), batch_size=3)
    b = next(iter(it2))
    assert b.data[0].shape == (3, 6) and b.pad == 0


def test_image_record_uint8_iter(tmp_path):
    """reference: ImageRecordUInt8Iter — raw uint8 pixels, no
    mean/scale normalization applied."""
    rec, idx, _ = _make_rec(tmp_path)
    it = mx.io.ImageRecordUInt8Iter(path_imgrec=rec, path_imgidx=idx,
                                    data_shape=(3, 32, 32), batch_size=8)
    batch = next(iter(it))
    d = batch.data[0]
    assert d.dtype == np.uint8, d.dtype
    arr = d.asnumpy()
    assert arr.max() > 1  # raw pixel range, not normalized
    assert arr.shape == (8, 3, 32, 32)
