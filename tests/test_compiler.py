"""Whole-graph symbolic compiler + persistent AOT executable cache.

Acceptance (ISSUE 11): a Module forward/fit on a resnet-scale symbol
graph produces identical outputs via the whole-graph program vs the
op-by-op executor, with exactly ONE compiled program (compile counters
prove no per-op dispatch after bind); a second process/instance with a
warm MXNET_TPU_AOT_CACHE reports cache hits and zero fresh compiles for
the cached programs (BENCH=startup is the process-level evidence; the
in-instance restores are asserted here). Cache robustness: corrupted/
truncated entries are counted misses followed by a recompile, version
skew misses, concurrent writers are atomic last-write-wins, keep=N
evicts oldest-first.
"""
import json
import os
import pickle
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import compiler, nd, telemetry
from mxnet_tpu import symbol as sym
from mxnet_tpu.compiler import cache as cache_mod
from mxnet_tpu.compiler import lower as lower_mod
from mxnet_tpu.compiler.cache import AOTCache, cache_key
from mxnet_tpu.io.io import DataBatch, NDArrayIter

pytestmark = pytest.mark.compiler


@pytest.fixture(autouse=True)
def _clean_compiler(monkeypatch):
    """Fresh telemetry + program memo per test; the AOT cache stays OFF
    unless a test points MXNET_TPU_AOT_CACHE somewhere itself."""
    monkeypatch.delenv("MXNET_TPU_AOT_CACHE", raising=False)
    monkeypatch.delenv("MXNET_TPU_WHOLE_GRAPH", raising=False)
    telemetry.enable()
    telemetry.reset()
    lower_mod._MEMO.clear()
    yield
    lower_mod._MEMO.clear()
    telemetry.reset()


def _counters():
    return telemetry.snapshot()["counters"]


# ---------------------------------------------------------------------------
# fixture graphs
# ---------------------------------------------------------------------------
def _mlp_symbol():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=16)
    act1 = sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = sym.FullyConnected(act1, name="fc2", num_hidden=3)
    return sym.SoftmaxOutput(fc2, name="softmax")


def _resnetish_symbol(n_blocks=2, channels=8):
    """A resnet-shaped graph: conv stem, residual conv+BN+relu blocks
    with identity adds, global pooling, FC head, softmax loss — the
    acceptance topology (convs, BN aux states, residual fan-out that
    exercises CSE-safe shared subgraphs, multi-consumer nodes)."""
    x = sym.var("data")
    h = sym.Convolution(x, name="stem", num_filter=channels, kernel=(3, 3),
                        pad=(1, 1), no_bias=True)
    h = sym.BatchNorm(h, name="stem_bn", fix_gamma=False)
    h = sym.Activation(h, name="stem_relu", act_type="relu")
    for i in range(n_blocks):
        s = sym.Convolution(h, name="b%d_c1" % i, num_filter=channels,
                            kernel=(3, 3), pad=(1, 1), no_bias=True)
        s = sym.BatchNorm(s, name="b%d_bn1" % i, fix_gamma=False)
        s = sym.Activation(s, name="b%d_relu1" % i, act_type="relu")
        s = sym.Convolution(s, name="b%d_c2" % i, num_filter=channels,
                            kernel=(3, 3), pad=(1, 1), no_bias=True)
        s = sym.BatchNorm(s, name="b%d_bn2" % i, fix_gamma=False)
        h = sym.Activation(h + s, name="b%d_out" % i, act_type="relu")
    h = sym.Pooling(h, name="gap", global_pool=True, pool_type="avg",
                    kernel=(1, 1))
    h = sym.Flatten(h, name="flat")
    h = sym.FullyConnected(h, name="head", num_hidden=4)
    return sym.SoftmaxOutput(h, name="softmax")


def _feed_values(net, data_shape, seed=0):
    rng = np.random.RandomState(seed)
    vals = {}
    for name, shape in zip(net.list_arguments(),
                           net.infer_shape(data=data_shape)[0]):
        if name == "data":
            vals[name] = rng.normal(size=shape).astype("float32")
        elif name == "softmax_label":
            vals[name] = rng.randint(0, 3, size=shape).astype("float32")
        elif name.endswith("gamma"):
            vals[name] = np.ones(shape, "float32")
        else:
            vals[name] = (rng.normal(size=shape) * 0.1).astype("float32")
    return vals


def _bind_and_run(net, vals, data_shape, label_shape, compile_graph,
                  steps=1, lr=0.0, grad_req="write"):
    """simple_bind + forward(is_train)/backward loop with an optional SGD
    update applied host-side — the same math on both executor paths."""
    kw = {"data": data_shape}
    if "softmax_label" in net.list_arguments():
        kw["softmax_label"] = label_shape
    ex = net.simple_bind(mx.cpu(), grad_req=grad_req,
                         compile_graph=compile_graph, **kw)
    for k, v in vals.items():
        ex.arg_dict[k][:] = v
    outs, grads = None, None
    for _ in range(steps):
        outs = ex.forward(is_train=True)[0].asnumpy()
        ex.backward()
        grads = {k: g.asnumpy() for k, g in ex.grad_dict.items()
                 if g is not None}
        if lr:
            for k, g in ex.grad_dict.items():
                if g is None or k in ("data", "softmax_label"):
                    continue
                ex.arg_dict[k][:] = ex.arg_dict[k].asnumpy() - \
                    lr * g.asnumpy()
    return outs, grads, ex


# ---------------------------------------------------------------------------
# graph passes
# ---------------------------------------------------------------------------
def test_pass_constant_folding_and_dce():
    """All-constant subgraphs evaluate at lower time (with the registry
    fns, so values match eager bit for bit) and their producers die."""
    z = sym.zeros((2, 3))
    one = sym.ones((2, 3))
    a = sym.var("a")
    net = a + (z + one * 2.0)
    ir = compiler.from_symbol(net)
    n_ops_before = ir.n_ops()
    ir, stats = compiler.run_pipeline(ir)
    assert stats["folded"] >= 2, stats
    assert stats["dce_removed"] >= 2, stats
    assert ir.n_ops() < n_ops_before
    # parity through the executor
    x = np.arange(6, dtype="float32").reshape(2, 3)
    ex = net.bind(mx.cpu(), {"a": nd.array(x)}, compile_graph=True)
    out = ex.forward()[0].asnumpy()
    np.testing.assert_array_equal(out, x + 2.0)


def test_pass_cse_merges_duplicate_subgraphs():
    a = sym.var("a")
    b = sym.var("b")
    p1 = a * b          # two structurally identical products built
    p2 = a * b          # independently — one must survive
    net = p1 + p2
    ir = compiler.from_symbol(net)
    ir, stats = compiler.run_pipeline(ir)
    assert stats["cse_merged"] == 1, stats
    x, y = np.full((2, 2), 3.0, "float32"), np.full((2, 2), 5.0, "float32")
    ex = net.bind(mx.cpu(), {"a": nd.array(x), "b": nd.array(y)},
                  compile_graph=True)
    np.testing.assert_array_equal(ex.forward()[0].asnumpy(), 2 * x * y)


def test_unsupported_random_op_reason():
    data = sym.var("data")
    net = sym.Dropout(data, name="drop", p=0.5)
    with pytest.raises(compiler.UnsupportedGraphError) as ei:
        compiler.from_symbol(net)
    assert ei.value.reason == "random_op:Dropout"


def test_graph_hash_value_exact_for_constants():
    """Two graphs differing ONLY in a folded constant's value must not
    collide — constants are baked into the emitted program, so a
    shape/dtype-only hash would hand the second graph the FIRST one's
    compiled program (wrong numerics) through the memo/AOT key."""
    a = sym.var("a")
    net2 = a * (sym.ones((4,)) * 2.0)
    net3 = a * (sym.ones((4,)) * 3.0)
    ir2, _ = compiler.run_pipeline(compiler.from_symbol(net2))
    ir3, _ = compiler.run_pipeline(compiler.from_symbol(net3))
    assert compiler.graph_hash(ir2) != compiler.graph_hash(ir3)
    outs = []
    for net in (net2, net3):
        ex = net.bind(mx.cpu(), {"a": nd.ones((4,))}, compile_graph=True)
        outs.append(ex.forward()[0].asnumpy())
    np.testing.assert_array_equal(outs[0], np.full(4, 2.0))
    np.testing.assert_array_equal(outs[1], np.full(4, 3.0))


def test_graph_hash_stable_and_distinct():
    ir1, _ = compiler.run_pipeline(compiler.from_symbol(_mlp_symbol()))
    ir2, _ = compiler.run_pipeline(compiler.from_symbol(_mlp_symbol()))
    ir3, _ = compiler.run_pipeline(compiler.from_symbol(
        _resnetish_symbol(1)))
    assert compiler.graph_hash(ir1) == compiler.graph_hash(ir2)
    assert compiler.graph_hash(ir1) != compiler.graph_hash(ir3)


# ---------------------------------------------------------------------------
# executor parity (the tentpole)
# ---------------------------------------------------------------------------
def test_mlp_forward_backward_bitexact_one_program():
    net = _mlp_symbol()
    vals = _feed_values(net, (4, 5))
    o_wg, g_wg, ex = _bind_and_run(net, vals, (4, 5), (4,), True)
    assert _counters().get("compiler.compile") == 1
    # post-bind steady state: NO per-op dispatch — the invoke counter
    # must not move across another forward+backward
    before = _counters().get("ndarray.invoke", 0)
    ex.forward(is_train=True)
    ex.backward()
    assert _counters().get("ndarray.invoke", 0) == before
    assert _counters().get("compiler.compile") == 1, \
        "second forward must reuse the ONE compiled program"
    o_ref, g_ref, _ = _bind_and_run(net, vals, (4, 5), (4,), False)
    np.testing.assert_array_equal(o_wg, o_ref)
    assert sorted(g_wg) == sorted(g_ref)
    for k in g_ref:
        np.testing.assert_array_equal(g_wg[k], g_ref[k],
                                      err_msg="grad %s" % k)


def test_resnet_scale_module_fit_parity():
    """The acceptance graph: conv/BN/residual topology through a short
    fit loop. Forward outputs are bit-identical; the whole-graph
    backward (one fused vjp program) may reassociate conv-backward
    low bits vs the chained per-op vjp, so grads and the fitted params
    assert at tight tolerance."""
    net = _resnetish_symbol()
    vals = _feed_values(net, (2, 3, 8, 8), seed=7)
    o_wg, g_wg, _ = _bind_and_run(net, vals, (2, 3, 8, 8), (2,), True,
                                  steps=3, lr=0.05)
    assert _counters().get("compiler.compile") == 1, \
        "resnet-scale fit must run as exactly ONE compiled program"
    o_ref, g_ref, _ = _bind_and_run(net, vals, (2, 3, 8, 8), (2,), False,
                                    steps=3, lr=0.05)
    np.testing.assert_allclose(o_wg, o_ref, rtol=2e-5, atol=1e-6)
    for k in g_ref:
        np.testing.assert_allclose(g_wg[k], g_ref[k], rtol=2e-4,
                                   atol=1e-6, err_msg="grad %s" % k)


def test_resnet_forward_outputs_identical():
    """Inference outputs of the conv graph match op-by-op dispatch to
    within one float32 ulp (XLA fuses the conv+BN+relu chain differently
    under whole-graph jit — the same deviation class `hybridize` accepts;
    the dense graph in test_mlp_forward_backward_bitexact_one_program
    IS bit-identical)."""
    net = _resnetish_symbol()
    vals = _feed_values(net, (2, 3, 8, 8), seed=11)
    kw = {"data": (2, 3, 8, 8), "softmax_label": (2,)}
    outs = {}
    for cg in (True, False):
        ex = net.simple_bind(mx.cpu(), grad_req="null", compile_graph=cg,
                             **kw)
        for k, v in vals.items():
            ex.arg_dict[k][:] = v
        outs[cg] = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(outs[True], outs[False], rtol=2e-7,
                               atol=1e-7)


def test_module_fit_one_program_and_score():
    """Module.fit rides the whole-graph program transparently (the
    Module-level wiring) and still learns."""
    mx.random.seed(0)
    np.random.seed(0)
    x = np.random.normal(size=(96, 8)).astype("float32")
    w = np.random.normal(size=(8, 3)).astype("float32")
    y = np.argmax(x @ w, axis=1).astype("float32")
    it = NDArrayIter(x, y, batch_size=16, shuffle=True,
                     label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu(),
                        label_names=("softmax_label",), compile_graph=True)
    mod.fit(it, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    assert mod.score(it, "acc")[0][1] > 0.6
    c = _counters()
    assert c.get("compiler.fallback", 0) == 0
    # fit compiles the fwd+bwd program; predict/score adds the pure
    # forward — 2 executables TOTAL, not 2 per batch
    assert c.get("compiler.compile") == 2, c.get("compiler.compile")


def test_module_multi_device_shares_one_program():
    """Two data-parallel executors with equal batch slices share ONE
    compiled program through the process memo."""
    n_dev = 2
    x = np.random.RandomState(0).normal(size=(32, 8)).astype("float32")
    y = np.zeros(32, "float32")
    it = NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(),
                        context=[mx.cpu(i) for i in range(n_dev)],
                        label_names=("softmax_label",), compile_graph=True)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    batch = next(iter(it))
    mod.forward(batch, is_train=True)
    mod.backward()
    assert _counters().get("compiler.compile") == 1


def test_grad_req_add_accumulates():
    net = _mlp_symbol()
    vals = _feed_values(net, (4, 5), seed=3)
    _, g1, ex = _bind_and_run(net, vals, (4, 5), (4,), True,
                              grad_req="add")
    ex.forward(is_train=True)
    ex.backward()
    g2 = ex.grad_dict["fc1_weight"].asnumpy()
    np.testing.assert_allclose(g2, 2 * g1["fc1_weight"], rtol=1e-6)


def test_backward_with_out_grads_parity():
    a = sym.var("a")
    net = a * 3.0 + 1.0
    cot = np.arange(6, dtype="float32").reshape(2, 3)

    def run(cg):
        ex = net.bind(mx.cpu(), {"a": nd.ones((2, 3))},
                      {"a": nd.zeros((2, 3))}, compile_graph=cg)
        ex.forward(is_train=True)
        ex.backward(out_grads=nd.array(cot))
        return ex.grad_dict["a"].asnumpy()
    np.testing.assert_array_equal(run(True), run(False))


def test_random_graph_falls_back_counted_never_errors():
    data = sym.var("data")
    net = sym.Dropout(data, name="drop", p=0.0)
    ex = net.bind(mx.cpu(), {"data": nd.ones((2, 2))}, compile_graph=True)
    out = ex.forward(is_train=False)[0]
    np.testing.assert_array_equal(out.asnumpy(), np.ones((2, 2)))
    c = _counters()
    assert c.get("compiler.fallback") == 1
    assert c.get("compiler.fallback.random_op:Dropout") == 1
    # pinned: the next forward goes straight op-by-op, no re-attempt
    ex.forward(is_train=False)
    assert _counters().get("compiler.fallback") == 1


def test_gate_off_keeps_op_by_op(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_WHOLE_GRAPH", "0")
    net = _mlp_symbol()
    vals = _feed_values(net, (4, 5))
    o, _, _ = _bind_and_run(net, vals, (4, 5), (4,), None)
    assert o.shape == (4, 3)
    assert _counters().get("compiler.lower", 0) == 0


# ---------------------------------------------------------------------------
# AOT cache robustness (satellite)
# ---------------------------------------------------------------------------
def _toy_compiled(mult=2.0):
    f = jax.jit(lambda x: x * mult + 1)
    return f.lower(jax.ShapeDtypeStruct((4,), jnp.float32)).compile()


def test_cache_roundtrip(tmp_path):
    cache = AOTCache(str(tmp_path), keep=8)
    key = cache_key(kind="test", prog="toy")
    assert cache.load(key) is None
    assert _counters().get("compiler.cache.misses") == 1
    assert cache.store(key, _toy_compiled())
    out = cache.load(key)(np.ones(4, np.float32))
    np.testing.assert_array_equal(np.asarray(out), np.full(4, 3.0))
    c = _counters()
    assert c.get("compiler.cache.hits") == 1
    assert c.get("compiler.cache.writes") == 1


@pytest.mark.parametrize("how", ["truncate", "garbage", "bad_magic",
                                 "flip_payload"])
def test_cache_corrupt_entry_is_counted_miss(tmp_path, how):
    cache = AOTCache(str(tmp_path), keep=8)
    key = cache_key(kind="test", prog="corrupt", how=how)
    assert cache.store(key, _toy_compiled())
    fname = os.path.join(str(tmp_path), key + ".aotx")
    blob = open(fname, "rb").read()
    if how == "truncate":
        blob = blob[:len(blob) // 2]
    elif how == "garbage":
        blob = b"not an executable at all"
    elif how == "bad_magic":
        blob = b"XXXXXX\n" + blob[7:]
    else:  # flip_payload: valid magic, digest now wrong
        blob = blob[:-8] + bytes(8)
    with open(fname, "wb") as f:
        f.write(blob)
    assert cache.load(key) is None
    c = _counters()
    assert c.get("compiler.cache.corrupt") == 1
    assert c.get("compiler.cache.misses") == 1
    # recompile + overwrite heals the entry
    assert cache.store(key, _toy_compiled())
    assert cache.load(key) is not None


def test_cache_version_mismatch_is_miss(tmp_path, monkeypatch):
    cache = AOTCache(str(tmp_path), keep=8)
    key = cache_key(kind="test", prog="versioned")
    assert cache.store(key, _toy_compiled())
    # a worker on a different compiler stack derives a DIFFERENT key for
    # the same program — never loads this entry
    monkeypatch.setattr(cache_mod, "_versions",
                        lambda: {"jax": "999.0", "jaxlib": "999.0",
                                 "mxnet_tpu": "x", "platform": "cpu",
                                 "device_count": 1})
    key2 = cache_key(kind="test", prog="versioned")
    assert key2 != key
    assert cache.load(key2) is None
    assert _counters().get("compiler.cache.misses") == 1


def test_cache_concurrent_writers_last_write_wins(tmp_path):
    cache = AOTCache(str(tmp_path), keep=8)
    key = cache_key(kind="test", prog="race")
    compiled = [_toy_compiled(m) for m in (2.0, 3.0, 4.0, 5.0)]
    errs = []

    def writer(c):
        try:
            for _ in range(5):
                cache.store(key, c)
        except Exception as e:  # noqa: BLE001 - the assertion target
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(c,))
               for c in compiled]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    files = [f for f in os.listdir(str(tmp_path)) if f.endswith(".aotx")]
    assert files == [key + ".aotx"], files  # no temp debris, ONE entry
    out = cache.load(key)(np.ones(4, np.float32))
    # whichever writer won, the entry is a complete valid executable
    assert float(np.asarray(out)[0]) in (3.0, 4.0, 5.0, 6.0)


def test_cache_keep_n_eviction_oldest_first(tmp_path):
    cache = AOTCache(str(tmp_path), keep=3)
    keys = [cache_key(kind="test", prog="evict", i=i) for i in range(5)]
    for i, key in enumerate(keys):
        assert cache.store(key, _toy_compiled())
        # force a strictly increasing mtime order
        os.utime(os.path.join(str(tmp_path), key + ".aotx"),
                 (1000 + i, 1000 + i))
        cache._evict()
    left = sorted(f for f in os.listdir(str(tmp_path))
                  if f.endswith(".aotx"))
    assert left == sorted(k + ".aotx" for k in keys[2:]), left
    assert _counters().get("compiler.cache.evictions") == 2


def test_executor_recompiles_after_truncated_entry(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", str(tmp_path))
    net = _mlp_symbol()
    vals = _feed_values(net, (4, 5))
    o1, _, _ = _bind_and_run(net, vals, (4, 5), (4,), True)
    entries = [f for f in os.listdir(str(tmp_path)) if f.endswith(".aotx")]
    assert entries, "executor program must land in the cache"
    for f in entries:
        full = os.path.join(str(tmp_path), f)
        open(full, "wb").write(open(full, "rb").read()[:100])
    lower_mod._MEMO.clear()
    telemetry.reset()
    o2, _, _ = _bind_and_run(net, vals, (4, 5), (4,), True)
    np.testing.assert_array_equal(o1, o2)
    c = _counters()
    assert c.get("compiler.cache.corrupt", 0) >= 1
    assert c.get("compiler.compile") == 1  # recompiled, did not crash


def test_executor_program_restores_across_instances(tmp_path, monkeypatch):
    """The in-process stand-in for the two-process BENCH=startup row: a
    second executor build (fresh memo = fresh 'process') restores the
    compiled program from the warm cache with zero fresh compiles."""
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", str(tmp_path))
    net = _mlp_symbol()
    vals = _feed_values(net, (4, 5))
    o1, g1, _ = _bind_and_run(net, vals, (4, 5), (4,), True)
    assert _counters().get("compiler.compile") == 1
    lower_mod._MEMO.clear()
    telemetry.reset()
    o2, g2, _ = _bind_and_run(net, vals, (4, 5), (4,), True)
    c = _counters()
    assert c.get("compiler.compile", 0) == 0, "warm start must not compile"
    assert c.get("compiler.cache.hits") == 1
    np.testing.assert_array_equal(o1, o2)
    for k in g1:
        np.testing.assert_array_equal(g1[k], g2[k])
    ring = [name for name, _ in telemetry.recent_compiles()]
    assert any("[cached]" in name for name in ring), ring


# ---------------------------------------------------------------------------
# serve + train-step programs ride the same cache
# ---------------------------------------------------------------------------
@pytest.mark.serve
def test_serve_warmup_restores_from_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", str(tmp_path))
    from mxnet_tpu.models.llama import LlamaConfig, llama_init
    from mxnet_tpu.serve.kv_cache import KVBlockPool
    from mxnet_tpu.serve.programs import ServePrograms
    cfg = LlamaConfig(vocab_size=128, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=64, rope_theta=10000.0,
                      max_seq_len=32, dtype=jnp.float32)
    params = llama_init(jax.random.PRNGKey(0), cfg)

    def build():
        pool = KVBlockPool(cfg, num_blocks=16, block_size=8)
        sp = ServePrograms(params, cfg, pool, max_batch=2, max_context=16,
                           chunk_size=8, prefill_rows=2)
        sp.warmup()
        return sp

    def chunk_once(sp):
        import numpy as np
        prompt = [5, 6, 7]
        tokens = np.zeros((2, 8), np.int32)
        positions = np.full((2, 8), -1, np.int32)
        tokens[0, :3] = prompt
        positions[0, :3] = [0, 1, 2]
        tables = np.full((2, sp.blocks_per_stream), sp.pool.num_blocks,
                         np.int32)
        tables[0, 0] = 0
        return int(sp.chunk_prefill(
            tokens, positions, tables, np.zeros(2, np.uint32),
            np.asarray([3, 0], np.int32), np.zeros(2, np.float32),
            np.zeros(2, np.int32), np.ones(2, np.float32))[0])

    sp1 = build()
    n_exec = len(sp1.program_names)
    assert _counters().get("serve.compile") == n_exec
    tok1 = chunk_once(sp1)
    telemetry.reset()
    sp2 = build()
    c = _counters()
    assert c.get("serve.compile", 0) == 0, \
        "warm warmup must restore every executable"
    assert c.get("compiler.cache.hits") == n_exec
    assert tok1 == chunk_once(sp2)
    ring = [name for name, _ in telemetry.recent_compiles()]
    assert all("[cached]" in name for name in ring), ring


def test_sharded_train_step_cache_restore(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", str(tmp_path))
    from jax.sharding import Mesh

    from mxnet_tpu.parallel.sharding import ShardingRules
    from mxnet_tpu.parallel.train_step import ShardedTrainStep
    mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(2), ("data",))

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    batch = {"x": jnp.ones((8, 4)), "y": jnp.zeros((8, 2))}

    def round_():
        step = ShardedTrainStep(loss_fn,
                                {"w": jnp.ones((4, 2), jnp.float32)},
                                mesh, rules=ShardingRules([]), lr=0.1)
        p, s = step.init()
        losses = []
        for i in range(3):
            p, s, l = step(p, s, batch, i)
            losses.append(float(l))
        return losses

    l1 = round_()
    assert _counters().get("compiler.cache.writes") == 1
    telemetry.reset()
    l2 = round_()
    c = _counters()
    assert c.get("train_step.aot_restored") == 1
    assert c.get("compiler.cache.hits") == 1
    assert l1 == l2  # restored executable is bit-identical


def test_fused_step_cache_donation_policy(tmp_path, monkeypatch):
    """donate=False rides the cache (restore is bit-identical);
    donate=True (default) skips it with a counted reason — a deserialized
    donating fused-step program corrupts XLA:CPU (2026-08-04)."""
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", str(tmp_path))
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    def round_(donate):
        mx.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
        net.initialize()
        rng = np.random.RandomState(0)
        x = nd.array(rng.normal(size=(8, 5)).astype("float32"))
        y = nd.array(rng.randint(0, 3, (8,)).astype("float32"))
        net(x)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        fused = gluon.FusedTrainStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), tr, donate=donate)
        return [float(fused(x, y).asnumpy()) for _ in range(3)]

    l1 = round_(False)
    assert _counters().get("compiler.cache.writes") == 1
    telemetry.reset()
    l2 = round_(False)
    c = _counters()
    assert c.get("fused_step.aot_restored") == 1
    assert l1 == l2
    telemetry.reset()
    round_(True)
    c = _counters()
    assert c.get("compiler.cache.skipped_donated") == 1
    assert c.get("fused_step.aot_restored", 0) == 0


# ---------------------------------------------------------------------------
# tooling satellites
# ---------------------------------------------------------------------------
def test_parse_log_compile_table(tmp_path):
    net = _mlp_symbol()
    vals = _feed_values(net, (4, 5))
    _bind_and_run(net, vals, (4, 5), (4,), True)
    report = telemetry.compile_report()
    path = str(tmp_path / "compile.json")
    with open(path, "w") as f:
        json.dump(report, f)
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    out = subprocess.run(
        [sys.executable, os.path.join(tools, "parse_log.py"), path,
         "--compile"], capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "| compiler | compile | 1 |" in out.stdout, out.stdout
    assert "lower_ms" in out.stdout
    assert "compiler:" in out.stdout  # the recent-compiles ring rows


def test_large_tensor_scope_shim():
    """The x64 probe/shim (satellite): int64 survives inside the scope on
    every jax that ships either spelling of enable_x64."""
    with mx.util.large_tensor_scope():
        a = jnp.asarray([2 ** 40], dtype="int64")
        assert str(a.dtype) == "int64"
        assert int(a[0]) == 2 ** 40


@pytest.mark.lint
def test_compiler_package_lint_clean_zero_suppressions():
    """mxnet_tpu/compiler/ must be tracelint-clean with ZERO suppression
    comments (ISSUE 11 CI satellite)."""
    import mxnet_tpu.analysis as analysis
    comp_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "compiler")
    findings = analysis.check(comp_dir)
    assert findings == [], "\n".join(str(f) for f in findings)
    for name in os.listdir(comp_dir):
        if name.endswith(".py"):
            with open(os.path.join(comp_dir, name)) as f:
                assert "tpu-lint" not in f.read(), (
                    "suppression found in %s" % name)


@pytest.mark.slow
def test_bench_startup_cold_vs_warm_subprocess(tmp_path):
    """The process-level acceptance: BENCH=startup's second process
    reports cache hits >= 1 and ZERO fresh compiles."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH="startup", JAX_PLATFORMS="cpu",
               MXNET_TPU_AOT_CACHE=str(tmp_path))
    out = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                         env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads([ln for ln in out.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert row["compile_count_cold"] > 0
    assert row["compile_count_warm"] == 0
    assert row["cache_hits_warm"] >= 1
