"""Per-op gradient sweep: autograd vs finite differences through the
Symbol executor, the reference's core op-testing idiom
(tests/python/unittest/test_operator.py + test_utils.check_numeric_gradient,
SURVEY.md §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.test_utils import check_numeric_gradient

RNG = np.random.RandomState(7)


def _x(shape=(3, 4), lo=-2.0, hi=2.0):
    return RNG.uniform(lo, hi, size=shape)


# (name, symbol builder, {input: value}) — positive-domain ops get shifted
# inputs; ops non-differentiable at ties/kinks get inputs away from them.
UNARY = [
    ("tanh", lambda x: sym.tanh(x), _x()),
    ("sigmoid", lambda x: sym.sigmoid(x), _x()),
    ("softsign", lambda x: sym.softsign(x), _x()),
    ("exp", lambda x: sym.exp(x), _x(hi=1.5)),
    ("log", lambda x: sym.log(x), _x(lo=0.5, hi=3.0)),
    ("log1p", lambda x: sym.log1p(x), _x(lo=-0.5, hi=2.0)),
    ("expm1", lambda x: sym.expm1(x), _x(hi=1.5)),
    ("sqrt", lambda x: sym.sqrt(x), _x(lo=0.5, hi=3.0)),
    ("rsqrt", lambda x: sym.rsqrt(x), _x(lo=0.5, hi=3.0)),
    ("cbrt", lambda x: sym.cbrt(x), _x(lo=0.5, hi=3.0)),
    ("square", lambda x: sym.square(x), _x()),
    ("sin", lambda x: sym.sin(x), _x()),
    ("cos", lambda x: sym.cos(x), _x()),
    ("tan", lambda x: sym.tan(x), _x(lo=-1.0, hi=1.0)),
    ("arcsin", lambda x: sym.arcsin(x), _x(lo=-0.8, hi=0.8)),
    ("arccos", lambda x: sym.arccos(x), _x(lo=-0.8, hi=0.8)),
    ("arctan", lambda x: sym.arctan(x), _x()),
    ("sinh", lambda x: sym.sinh(x), _x(lo=-1.5, hi=1.5)),
    ("cosh", lambda x: sym.cosh(x), _x(lo=-1.5, hi=1.5)),
    ("arcsinh", lambda x: sym.arcsinh(x), _x()),
    ("arccosh", lambda x: sym.arccosh(x), _x(lo=1.5, hi=3.0)),
    ("arctanh", lambda x: sym.arctanh(x), _x(lo=-0.8, hi=0.8)),
    ("erf", lambda x: sym.erf(x), _x(lo=-1.2, hi=1.2)),
    ("abs", lambda x: sym.abs(x), _x(lo=0.3, hi=2.0)),
    ("negative", lambda x: sym.negative(x), _x()),
    ("reciprocal", lambda x: sym.reciprocal(x), _x(lo=0.5, hi=3.0)),
    ("relu", lambda x: sym.relu(x), _x(lo=0.2, hi=2.0)),
    ("softmax", lambda x: sym.square(sym.softmax(x, axis=-1)), _x()),
    ("log_softmax", lambda x: sym.log_softmax(x, axis=-1), _x()),
    ("sum", lambda x: sym.sum(x), _x()),
    ("mean", lambda x: sym.mean(x), _x()),
    ("prod", lambda x: sym.prod(x), _x(lo=0.5, hi=1.5)),
    ("nansum", lambda x: sym.nansum(x), _x()),
    ("norm", lambda x: sym.norm(x), _x(lo=0.3, hi=2.0)),
    ("transpose", lambda x: sym.transpose(x), _x()),
    ("reshape", lambda x: sym.reshape(x, shape=(4, 3)), _x()),
    ("flip", lambda x: sym.flip(x, axis=1), _x()),
    ("LayerNorm_data",
     lambda x: sym.square(sym.LayerNorm(x, sym.Variable("g"),
                                        sym.Variable("b"), axis=-1)),
     _x()),
]


@pytest.mark.parametrize("name,build,xval",
                         UNARY, ids=[u[0] for u in UNARY])
def test_unary_grad(name, build, xval):
    x = sym.Variable("x")
    s = build(x)
    loc = {"x": xval}
    if name == "LayerNorm_data":
        loc["g"] = RNG.uniform(0.5, 1.5, size=(xval.shape[-1],))
        loc["b"] = RNG.uniform(-0.5, 0.5, size=(xval.shape[-1],))
    eps = 1e-2 if name in ("softmax", "LayerNorm_data") else 1e-4
    check_numeric_gradient(s, loc, rtol=2e-2, atol=1e-3,
                           numeric_eps=eps)


BINARY = [
    ("broadcast_add", lambda a, b: sym.broadcast_add(a, b),
     (3, 4), (1, 4)),
    ("broadcast_sub", lambda a, b: sym.broadcast_sub(a, b),
     (3, 4), (3, 1)),
    ("broadcast_mul", lambda a, b: sym.broadcast_mul(a, b),
     (3, 4), (1, 4)),
    ("broadcast_div", lambda a, b: sym.broadcast_div(a, b),
     (3, 4), (1, 4)),
    ("broadcast_power", lambda a, b: sym.broadcast_power(a, b),
     (3, 4), (1, 4)),
    ("dot", lambda a, b: sym.dot(a, b), (3, 4), (4, 2)),
    ("batch_dot", lambda a, b: sym.batch_dot(a, b), (2, 3, 4), (2, 4, 2)),
    ("elemwise_add", lambda a, b: sym.elemwise_add(a, b), (3, 4), (3, 4)),
    ("elemwise_mul", lambda a, b: sym.elemwise_mul(a, b), (3, 4), (3, 4)),
    ("hypot", lambda a, b: sym.broadcast_hypot(a, b), (3, 4), (1, 4)),
]


@pytest.mark.parametrize("name,build,sa,sb",
                         BINARY, ids=[b[0] for b in BINARY])
def test_binary_grad(name, build, sa, sb):
    a = sym.Variable("a")
    b = sym.Variable("b")
    s = build(a, b)
    lo = 0.5 if name in ("broadcast_div", "broadcast_power", "hypot") else \
        -2.0
    loc = {"a": RNG.uniform(max(lo, 0.5) if lo > 0 else lo, 2.0, size=sa),
           "b": RNG.uniform(max(lo, 0.5) if lo > 0 else lo, 2.0, size=sb)}
    check_numeric_gradient(s, loc, rtol=2e-2, atol=1e-4)


def test_fully_connected_grad():
    x = sym.Variable("x")
    w = sym.Variable("w")
    b = sym.Variable("b")
    s = sym.FullyConnected(x, w, b, num_hidden=5)
    check_numeric_gradient(s, {"x": _x((2, 3)), "w": _x((5, 3)),
                               "b": _x((5,))}, rtol=2e-2, atol=1e-4)


def test_convolution_grad():
    x = sym.Variable("x")
    w = sym.Variable("w")
    b = sym.Variable("b")
    s = sym.Convolution(x, w, b, kernel=(3, 3), num_filter=2, pad=(1, 1))
    # f32 executor: FD noise scales ~1/eps, conv sums amplify it — use
    # the coarser eps the reference's f32 op tests use
    check_numeric_gradient(
        s, {"x": _x((1, 2, 5, 5)), "w": _x((2, 2, 3, 3)), "b": _x((2,))},
        rtol=5e-2, atol=5e-3, numeric_eps=1e-2)


def test_pooling_grad():
    x = sym.Variable("x")
    s = sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    check_numeric_gradient(s, {"x": _x((1, 2, 4, 4))}, rtol=2e-2,
                           atol=1e-4)


def test_take_pick_grad():
    x = sym.Variable("x")
    s = sym.pick(x, sym.Variable("idx"), axis=-1)
    idx = RNG.randint(0, 4, size=(3,)).astype(np.float64)
    check_numeric_gradient(s, {"x": _x((3, 4)), "idx": idx},
                           grad_nodes=["x"], rtol=2e-2, atol=1e-4)


# ---------------------------------------------------------------------------
# training-output regression heads: backward is the hand-coded loss gradient
# (out - label) * grad_scale / num_output, NOT the forward vjp (reference:
# src/operator/regression_output.cc). The silent-ones bug (identity forward,
# pass-through vjp => gradient independent of the parameters) was caught by
# the SVRG convergence tests.
# ---------------------------------------------------------------------------
def test_regression_output_training_gradients():
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.ndarray.ndarray import invoke
    rng = np.random.RandomState(3)
    d = rng.randn(4, 3).astype("float32")
    lab = rng.randn(4, 3).astype("float32")
    for name, want in (
        ("LinearRegressionOutput", (d - lab) / 3.0),
        ("MAERegressionOutput", np.sign(d - lab) / 3.0),
        ("LogisticRegressionOutput",
         (1 / (1 + np.exp(-d)) - lab) / 3.0),
    ):
        x = nd.array(d)
        y = nd.array(lab)
        x.attach_grad()
        with autograd.record():
            out = invoke(name, x, y)
        out.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5,
                                   atol=1e-6, err_msg=name)


def test_regression_output_gradient_tracks_weights():
    """The gradient MUST respond to a weight change (the regression bug)."""
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.ndarray.ndarray import invoke
    rng = np.random.RandomState(5)
    X = nd.array(rng.rand(8, 4).astype("float32"))
    w = nd.array(rng.rand(1, 4).astype("float32"))
    b = nd.array(np.zeros(1, "float32"))
    y = nd.array(rng.rand(8,).astype("float32"))
    w.attach_grad()
    grads = []
    for _ in range(2):
        with autograd.record():
            pred = invoke("FullyConnected", X, w, b, num_hidden=1)
            out = invoke("LinearRegressionOutput", pred, y)
        out.backward()
        grads.append(w.grad.asnumpy().copy())
        w[:] = w + 1.0
    assert np.abs(grads[1] - grads[0]).max() > 0.1, (
        "LinearRegressionOutput gradient did not track the weights")


def test_identity_attach_kl_sparse_reg_gradient():
    """Backward = cotangent + penalty * dKL/drho_hat / batch (reference:
    identity_attach_KL_sparse_reg.cc), NOT the identity vjp."""
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.ndarray.ndarray import invoke
    rng = np.random.RandomState(9)
    d = rng.uniform(0.1, 0.9, (4, 5)).astype("float32")
    target, penalty = 0.2, 0.05
    x = nd.array(d)
    x.attach_grad()
    with autograd.record():
        out = invoke("IdentityAttachKLSparseReg", x,
                     sparseness_target=target, penalty=penalty)
        loss = out.sum()
    loss.backward()
    rho = np.clip(d.mean(axis=0), 1e-6, 1 - 1e-6)
    dkl = -target / rho + (1 - target) / (1 - rho)
    want = 1.0 + penalty * dkl[None] / d.shape[0]
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.broadcast_to(want, d.shape),
                               rtol=1e-5, atol=1e-6)


def test_make_loss_grad_scale_and_normalization():
    """MakeLoss backward = grad_scale (per normalization mode), not the
    plain identity vjp (reference: make_loss.cc)."""
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.ndarray.ndarray import invoke
    d = np.array([[0.5, 0.0], [2.0, 0.0]], "float32")
    for name in ("MakeLoss", "make_loss"):
        x = nd.array(d)
        x.attach_grad()
        with autograd.record():
            out = invoke(name, x, grad_scale=3.0)
            out.sum().backward()
        np.testing.assert_allclose(x.grad.asnumpy(),
                                   np.full_like(d, 3.0), rtol=1e-6)
        # batch normalization divides by N
        x2 = nd.array(d)
        x2.attach_grad()
        with autograd.record():
            invoke(name, x2, grad_scale=3.0,
                   normalization="batch").sum().backward()
        np.testing.assert_allclose(x2.grad.asnumpy(),
                                   np.full_like(d, 1.5), rtol=1e-6)
        # valid: 2 elements above thresh 0.1
        x3 = nd.array(d)
        x3.attach_grad()
        with autograd.record():
            invoke(name, x3, grad_scale=4.0, valid_thresh=0.1,
                   normalization="valid").sum().backward()
        np.testing.assert_allclose(x3.grad.asnumpy(),
                                   np.full_like(d, 2.0), rtol=1e-6)
