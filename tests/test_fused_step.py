"""FusedTrainStep: one-jit Gluon training must match the imperative
`loss.backward(); trainer.step()` path exactly (same ops, same scalars).
reference behavior: SURVEY.md §3.2 call stack."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def _mlp(seed, bn=False, dropout=0.0):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        if bn:
            net.add(nn.BatchNorm())
        if dropout:
            net.add(nn.Dropout(dropout))
        net.add(nn.Dense(8))
    net.initialize(mx.init.Xavier(rnd_type="gaussian"), ctx=mx.cpu())
    return net


def _data(n=16, d=12, classes=8, seed=0):
    rng = np.random.RandomState(seed)
    x = nd.array(rng.randn(n, d).astype(np.float32))
    y = nd.array(rng.randint(0, classes, (n,)).astype(np.float32))
    return x, y


@pytest.mark.parametrize("optimizer,opt_args", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("signum", {"learning_rate": 0.01, "momentum": 0.9, "wd_lh": 1e-4}),
    ("signsgd", {"learning_rate": 0.005}),
    ("ftml", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.05}),
    ("adadelta", {"rho": 0.9, "epsilon": 1e-5}),
    ("adamax", {"learning_rate": 0.002}),
    ("nadam", {"learning_rate": 0.005}),
    ("rmsprop", {"learning_rate": 0.005}),
    ("rmsprop", {"learning_rate": 0.005, "centered": True, "gamma2": 0.85}),
    ("ftrl", {"learning_rate": 0.05, "lamda1": 0.001}),
    ("lamb", {"learning_rate": 0.01}),
    ("lars", {"learning_rate": 0.05, "momentum": 0.9, "eta": 0.001}),
    ("dcasgd", {"learning_rate": 0.05, "momentum": 0.9}),
])
def test_fused_matches_imperative(optimizer, opt_args):
    mx.random.seed(7)
    net_a = _mlp(0)
    x, y = _data()
    net_a(x)  # init shapes
    # clone params into a second net
    net_b = _mlp(1)
    net_b(x)
    for (ka, pa), (kb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        pb.set_data(pa.data().copy())

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr_a = gluon.Trainer(net_a.collect_params(), optimizer, dict(opt_args))
    tr_b = gluon.Trainer(net_b.collect_params(), optimizer, dict(opt_args))
    fused = gluon.FusedTrainStep(net_b, loss_fn, tr_b)

    for step in range(4):
        with autograd.record():
            la = loss_fn(net_a(x), y)
        la.backward()
        tr_a.step(x.shape[0])
        lb = fused(x, y)
        np.testing.assert_allclose(float(la.mean().asnumpy()),
                                   float(lb.asnumpy()), rtol=1e-5, atol=1e-6)
    for (ka, pa), (kb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=2e-5, atol=2e-6,
                                   err_msg="param %s diverged" % ka)


def test_fused_bn_dropout_trains():
    """BatchNorm aux stats update + dropout RNG inside the fused program."""
    mx.random.seed(11)
    net = _mlp(2, bn=True, dropout=0.3)
    x, y = _data(n=32)
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    fused = gluon.FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    bn = [p for name, p in net.collect_params().items()
          if "running_mean" in name][0]
    before = bn.data().asnumpy().copy()
    losses = [float(fused(x, y).asnumpy()) for _ in range(15)]
    assert losses[-1] < losses[0], losses
    assert not np.allclose(bn.data().asnumpy(), before), \
        "BatchNorm running stats did not update through the fused step"


def test_fused_lr_scheduler_advances():
    """Scheduler state (num_update) must advance per fused step — the lr is
    host-computed and fed as a device scalar each call."""
    mx.random.seed(13)
    net = _mlp(3)
    x, y = _data()
    net(x)
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.4, "lr_scheduler": sched})
    fused = gluon.FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    for _ in range(5):
        fused(x, y)
    assert tr._optimizer.num_update == 5
    assert tr.learning_rate < 0.4


def test_fused_hybridized_net():
    """A hybridized net inlines into the fused trace (no nested CachedOp)."""
    mx.random.seed(17)
    net = _mlp(4)
    net.hybridize()
    x, y = _data()
    net(x)
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    fused = gluon.FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    losses = [float(fused(x, y).asnumpy()) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_fused_input_nesting_retrace():
    """A call with identical shapes but different input NESTING must not
    reuse a stale trace (round-2 verdict Weak #10): programs are keyed by
    the flattened input format."""

    class TwoIn(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(8)

        def hybrid_forward(self, F, a, b=None):
            return self.d(a if b is None else a + b)

    mx.random.seed(23)
    net = TwoIn()
    net.initialize(mx.init.Xavier(rnd_type="gaussian"), ctx=mx.cpu())
    x, y = _data()
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    fused = gluon.FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr)
    l_single = fused(x, y)                 # data = one array
    # snapshot params BEFORE the pair step: its loss is computed on these
    net_ref = TwoIn()
    net_ref.initialize(ctx=mx.cpu())
    net_ref(x, x)  # trigger deferred init so set_data has shapes
    for (name, p_ref), (_, p) in zip(
            sorted(net_ref.collect_params().items()),
            sorted(net.collect_params().items())):
        p_ref.set_data(p.data())
    l_pair = fused([x, x], y)              # data = list of two, same shapes
    assert len(fused._programs) == 2
    # the pair trace must actually consume both inputs: f(x,x) == f(2x-ish)
    out_pair = net_ref(x, x)
    loss_ref = gluon.loss.SoftmaxCrossEntropyLoss()(out_pair, y)
    np.testing.assert_allclose(float(l_pair.asnumpy()),
                               float(loss_ref.mean().asnumpy()), rtol=2e-2)


# ---------------------------------------------------------------------------
# mesh mode: fused multi-device Gluon (reference: multi-device Trainer +
# KVStore 'device' — SURVEY.md §2.3 row 1; here one GSPMD program)
# ---------------------------------------------------------------------------

def _bn_mlp(seed):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.BatchNorm(),
                nn.Dense(8))
    net.initialize(mx.init.Xavier(rnd_type="gaussian"), ctx=mx.cpu())
    return net


def test_fused_mesh_data_parallel_matches_single_device():
    """The same fused step over an 8-device DP mesh must match the
    single-device run numerically (global batch semantics)."""
    from mxnet_tpu.parallel import create_mesh
    x, y = _data(n=32, d=12)

    def run(mesh):
        mx.random.seed(3)
        net = _bn_mlp(0)
        net(x)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        fused = gluon.FusedTrainStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), tr, mesh=mesh)
        losses = [float(fused(x, y).asnumpy()) for _ in range(8)]
        params = [v.data().asnumpy()
                  for _, v in sorted(net.collect_params().items())]
        return losses, params

    l_single, p_single = run(None)
    mesh = create_mesh(data=8)
    l_mesh, p_mesh = run(mesh)
    np.testing.assert_allclose(l_mesh, l_single, rtol=1e-4, atol=1e-5)
    for a, b in zip(p_mesh, p_single):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    assert l_single[-1] < l_single[0]


def test_fused_mesh_resnet_trains():
    """Gluon zoo resnet + Trainer trains on the 8-device virtual mesh
    (round-2 verdict task #7 done-criterion)."""
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import create_mesh
    mx.random.seed(5)
    mesh = create_mesh(data=8)
    net = vision.resnet18_v1(classes=4)
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(16, 3, 32, 32).astype(np.float32))
    y = nd.array(rng.randint(0, 4, (16,)).astype(np.float32))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01, "momentum": 0.9})
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr, mesh=mesh)
    losses = [float(fused(x, y).asnumpy()) for _ in range(10)]
    assert losses[-1] < losses[0], losses
    # params live sharded/replicated on the mesh
    w = net.collect_params()
    any_param = next(iter(w.values())).data()
    assert len(any_param._read().sharding.device_set) == 8


def test_eager_tape_matches_fused_step_end_to_end():
    """The eager tape (FGradient rules + jitted backward cache) and the
    FusedTrainStep jit program must produce numerically matching training
    trajectories from identical inits — cross-validates the round-5
    autograd layer against the compiled path over several steps (crossing
    the backward-cache warm-up threshold)."""
    import numpy as np
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    def build():
        mx.random.seed(42)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        return net, tr

    rng = np.random.RandomState(0)
    X = rng.rand(6, 32, 8).astype(np.float32)
    Y = rng.randint(0, 3, (6, 32)).astype(np.float32)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # eager tape path (un-hybridized: every op recorded)
    net_e, tr_e = build()
    eager_losses = []
    for i in range(6):
        x, y = nd.array(X[i]), nd.array(Y[i])
        with autograd.record():
            loss = loss_fn(net_e(x), y)
        loss.backward()
        tr_e.step(32)
        eager_losses.append(float(loss.mean().asnumpy()))

    # fused jit path
    net_f, tr_f = build()
    step = gluon.FusedTrainStep(net_f, loss_fn, tr_f)
    fused_losses = []
    for i in range(6):
        l = step(nd.array(X[i]), nd.array(Y[i]))
        fused_losses.append(float(l.mean().asnumpy()))

    np.testing.assert_allclose(eager_losses, fused_losses, rtol=2e-5,
                               atol=1e-6)
    # final parameters match too
    for (kn, pe), (_, pf) in zip(sorted(net_e.collect_params().items()),
                                 sorted(net_f.collect_params().items())):
        np.testing.assert_allclose(pe.data().asnumpy(),
                                   pf.data().asnumpy(), rtol=2e-4,
                                   atol=2e-6, err_msg=kn)
