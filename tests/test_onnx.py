"""mx.contrib.onnx export/import roundtrip.

reference: python/mxnet/contrib/onnx/ + tests/python-pytest/onnx/ — a
model exported to ONNX and re-imported must produce identical outputs.
The serializer is this build's own wire-format codec (no `onnx` pip
package in the image), so the roundtrip exercises encoder AND decoder.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib.onnx import export_model, import_model


def _random_params(sym, input_shapes, seed=0):
    shapes, _, _ = sym.infer_shape(**input_shapes)
    rng = onp.random.RandomState(seed)
    args = {}
    for name, shp in zip(sym.list_arguments(), shapes):
        if name in input_shapes:
            continue
        args[name] = nd.array((rng.randn(*shp) * 0.1).astype("float32"))
    return args


def _forward(sym, args, aux, data):
    ex = sym.bind(mx.cpu(), dict(args, data=data), aux_states=aux or None)
    return ex.forward(is_train=False)[0].asnumpy()


def _roundtrip(sym, input_shape, tmp_path, aux=None, args=None, atol=1e-5):
    args = args or _random_params(sym, {"data": input_shape})
    aux = aux or {}
    path = str(tmp_path / "model.onnx")
    export_model(sym, dict(args, **aux), {"data": input_shape},
                 onnx_file_path=path)
    sym2, args2, aux2 = import_model(path)
    data = nd.array(onp.random.RandomState(1)
                    .randn(*input_shape).astype("float32"))
    out1 = _forward(sym, args, aux, data)
    out2 = _forward(sym2, args2, aux2, data)
    onp.testing.assert_allclose(out1, out2, atol=atol, rtol=1e-4)
    return path


def test_cnn_roundtrip(tmp_path):
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                            name="conv1")
    b1 = mx.sym.BatchNorm(c1, name="bn1")
    a1 = mx.sym.Activation(b1, act_type="relu", name="act1")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                        name="pool1")
    f1 = mx.sym.Flatten(p1, name="flat")
    fc = mx.sym.FullyConnected(f1, num_hidden=10, name="fc1")
    out = mx.sym.softmax(fc, name="sm")

    args = _random_params(out, {"data": (2, 3, 8, 8)})
    aux = {"bn1_moving_mean": nd.zeros((8,)),
           "bn1_moving_var": nd.ones((8,))}
    _roundtrip(out, (2, 3, 8, 8), tmp_path, aux=aux, args=args)


def test_mlp_elemwise_roundtrip(tmp_path):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    t = mx.sym.Activation(fc1, act_type="tanh", name="t1")
    s = mx.sym.sigmoid(fc1, name="s1")
    mixed = mx.sym.broadcast_add(t, s, name="mix")
    fc2 = mx.sym.FullyConnected(mixed, num_hidden=4, no_bias=True,
                                name="fc2")
    out = mx.sym.log_softmax(fc2, name="out")
    _roundtrip(out, (3, 6), tmp_path)


def test_structural_ops_roundtrip(tmp_path):
    data = mx.sym.Variable("data")
    r = mx.sym.reshape(data, shape=(2, 12), name="rsh")
    tr = mx.sym.transpose(r, axes=(1, 0), name="tr")
    e = mx.sym.expand_dims(tr, axis=0, name="ex")
    sq = mx.sym.squeeze(e, axis=0, name="sq")
    cat = mx.sym.concat(sq, sq, dim=1, name="cat")
    _roundtrip(cat, (4, 6), tmp_path, args={})


def test_global_pool_and_leaky(tmp_path):
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4, name="c")
    l = mx.sym.LeakyReLU(c, act_type="leaky", slope=0.1, name="lk")
    g = mx.sym.Pooling(l, kernel=(1, 1), pool_type="avg", global_pool=True,
                       name="gp")
    _roundtrip(g, (2, 3, 5, 5), tmp_path)


def test_proto_encode_decode_fidelity():
    """The wire codec roundtrips every field kind it claims to support."""
    from mxnet_tpu.contrib.onnx import proto as P
    t = P.TensorProto(name="w", dims=[2, 3], data_type=P.DT.FLOAT,
                      raw_data=onp.arange(6, dtype="float32").tobytes())
    att = P.AttributeProto(name="ints", type=P.AT.INTS, ints=[1, -2, 300])
    node = P.NodeProto(op_type="Conv", name="n", input=["a", "b"],
                       output=["y"], attribute=[att])
    g = P.GraphProto(name="g", node=[node], initializer=[t])
    m = P.ModelProto(ir_version=8, producer_name="mxnet-tpu", graph=g,
                     opset_import=[P.OperatorSetIdProto(domain="",
                                                        version=13)])
    m2 = P.ModelProto.decode(m.encode())
    assert m2.ir_version == 8 and m2.producer_name == "mxnet-tpu"
    assert m2.opset_import[0].version == 13
    n2 = m2.graph.node[0]
    assert n2.op_type == "Conv" and n2.input == ["a", "b"]
    assert n2.attribute[0].ints == [1, -2, 300]
    t2 = m2.graph.initializer[0]
    assert t2.dims == [2, 3]
    onp.testing.assert_array_equal(
        onp.frombuffer(t2.raw_data, dtype="float32"),
        onp.arange(6, dtype="float32"))


def test_unsupported_op_raises(tmp_path):
    data = mx.sym.Variable("data")
    out = mx.sym.Correlation(data, data, name="corr") if hasattr(
        mx.sym, "Correlation") else None
    if out is None:
        pytest.skip("no unsupported op available")
    with pytest.raises(NotImplementedError):
        export_model(out, {}, {"data": (1, 2, 4, 4)},
                     onnx_file_path=str(tmp_path / "x.onnx"))


def test_export_input_shape_forms(tmp_path):
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    args = _random_params(out, {"data": (2, 5)})
    # reference API form: list of shape tuples, zipped with data inputs
    p = export_model(out, args, [(2, 5)],
                     onnx_file_path=str(tmp_path / "a.onnx"))
    sym2, a2, x2 = import_model(p)
    d = nd.array(onp.random.RandomState(2).randn(2, 5).astype("float32"))
    onp.testing.assert_allclose(_forward(out, args, {}, d),
                                _forward(sym2, a2, x2, d), atol=1e-5)


def test_gemm_general_form_imports(tmp_path):
    """A stock-exporter-style Gemm (transB=0, alpha/beta != 1) must
    compute alpha*A@B + beta*C, not the FullyConnected layout."""
    from mxnet_tpu.contrib.onnx import proto as P
    rng = onp.random.RandomState(3)
    A = rng.randn(2, 4).astype("float32")
    B = rng.randn(4, 3).astype("float32")
    C = rng.randn(3).astype("float32")
    g = P.GraphProto(
        name="g",
        node=[P.NodeProto(op_type="Gemm", name="gm", input=["A", "B", "C"],
                          output=["Y"],
                          attribute=[
                              P.AttributeProto(name="alpha", type=P.AT.FLOAT,
                                               f=2.0),
                              P.AttributeProto(name="beta", type=P.AT.FLOAT,
                                               f=0.5),
                              P.AttributeProto(name="transB", type=P.AT.INT,
                                               i=0)])],
        initializer=[],
        input=[P.ValueInfoProto(name=n) for n in ("A", "B", "C")],
        output=[P.ValueInfoProto(name="Y")])
    m = P.ModelProto(ir_version=8, graph=g,
                     opset_import=[P.OperatorSetIdProto(domain="",
                                                        version=13)])
    path = str(tmp_path / "gemm.onnx")
    open(path, "wb").write(m.encode())
    sym, _, _ = import_model(path)
    ex = sym.bind(mx.cpu(), {"A": nd.array(A), "B": nd.array(B),
                             "C": nd.array(C)})
    got = ex.forward()[0].asnumpy()
    onp.testing.assert_allclose(got, 2.0 * (A @ B) + 0.5 * C, rtol=1e-5,
                                atol=1e-6)


def test_transpose_dot_export_refused(tmp_path):
    a = mx.sym.Variable("a")
    bsym = mx.sym.Variable("b")
    out = mx.sym.dot(a, bsym, transpose_b=True, name="d")
    with pytest.raises(NotImplementedError):
        export_model(out, {}, {"a": (2, 3), "b": (4, 3)},
                     onnx_file_path=str(tmp_path / "x.onnx"))


def test_bf16_initializer_roundtrip(tmp_path):
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, no_bias=True,
                                name="fcb")
    w = nd.array(onp.random.RandomState(5).randn(3, 4).astype("float32"))
    wb = w.astype("bfloat16")
    p = export_model(out, {"fcb_weight": wb}, {"data": (2, 4)},
                     onnx_file_path=str(tmp_path / "b.onnx"))
    sym2, a2, _ = import_model(p)
    assert str(a2["fcb_weight"].dtype) == "bfloat16"
    onp.testing.assert_allclose(
        a2["fcb_weight"].astype("float32").asnumpy(),
        wb.astype("float32").asnumpy())


def test_unsupported_activation_refused(tmp_path):
    data = mx.sym.Variable("data")
    out = mx.sym.LeakyReLU(data, act_type="selu", name="s")
    with pytest.raises(NotImplementedError):
        export_model(out, {}, {"data": (2, 4)},
                     onnx_file_path=str(tmp_path / "x.onnx"))


def test_gelu_exports_as_erf_decomposition(tmp_path):
    data = mx.sym.Variable("data")
    out = mx.sym.Activation(data, act_type="gelu", name="g")
    p = export_model(out, {}, {"data": (2, 4)},
                     onnx_file_path=str(tmp_path / "g.onnx"))
    sym2, a2, x2 = import_model(p)
    d = nd.array(onp.random.RandomState(6).randn(2, 4).astype("float32"))
    o1 = out.bind(mx.cpu(), {"data": d}).forward()[0].asnumpy()
    o2 = sym2.bind(mx.cpu(), dict(a2, data=d)).forward()[0].asnumpy()
    onp.testing.assert_allclose(o1, o2, atol=1e-5, rtol=1e-4)
