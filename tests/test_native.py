"""Native host-kernel tests (RecordIO framing scanner + image normalize).

reference analog: tests/cpp/ covered the C++ IO layer with gtest; here the
C++ is exercised through its ctypes surface against the python
implementations as ground truth.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native, recordio


def test_native_builds():
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain; pure-python fallbacks apply")
    assert native.available()
    assert native.lib().mxtpu_version() == 1


def test_recordio_index_matches_python(tmp_path):
    rec_path = str(tmp_path / "a.rec")
    idx_path = str(tmp_path / "a.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    payloads = [b"x" * n for n in (1, 3, 4, 1000, 7)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()

    with open(rec_path, "rb") as f:
        buf = f.read()
    starts, sizes = native.index_recordio_buffer(buf)
    assert list(sizes) == [len(p) for p in payloads]

    # python .idx agrees with the native scan
    with open(idx_path) as f:
        py_starts = [int(line.split("\t")[1]) for line in f]
    assert list(starts) == py_starts


def test_recordio_missing_idx_recovery(tmp_path):
    rec_path = str(tmp_path / "b.rec")
    w = recordio.MXRecordIO(rec_path, "w")
    for i in range(5):
        w.write(("rec%d" % i).encode())
    w.close()
    # no .idx on disk: the reader rebuilds it by scanning framing
    r = recordio.MXIndexedRecordIO(str(tmp_path / "b.idx"), rec_path, "r")
    assert len(r.keys) == 5
    assert r.read_idx(3) == b"rec3"
    assert r.read_idx(0) == b"rec0"
    r.rebuild_index(write=True)
    assert (tmp_path / "b.idx").exists()
    r.close()


def test_recordio_index_corrupt_magic():
    with pytest.raises(IOError):
        native.index_recordio_buffer(b"\x00" * 16)


def test_img_to_chw_norm_matches_numpy():
    img = np.random.randint(0, 256, (17, 23, 3), np.uint8)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    got = native.img_to_chw_norm(img, mean, std)
    want = ((img.astype(np.float32) / 255.0 - mean) / std).transpose(2, 0, 1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # no mean/std: plain scale + transpose
    got2 = native.img_to_chw_norm(img)
    np.testing.assert_allclose(
        got2, (img.astype(np.float32) / 255.0).transpose(2, 0, 1),
        rtol=1e-6)


def test_batch_to_chw_norm():
    batch = np.random.randint(0, 256, (4, 8, 9, 3), np.uint8)
    mean = np.array([0.5, 0.5, 0.5], np.float32)
    std = np.array([0.25, 0.25, 0.25], np.float32)
    got = native.batch_to_chw_norm(batch, mean, std)
    want = ((batch.astype(np.float32) / 255.0 - mean) / std
            ).transpose(0, 3, 1, 2)
    assert got.shape == (4, 3, 8, 9)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
