"""INT8 quantization (reference suite: tests/python/quantization/
test_quantization.py — quantize_v2 roundtrip, quantized FC/conv vs fp32,
calibrated quantize_net accuracy)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(64, 32).astype(np.float32) * 3)
    q, mn, mxr = nd.invoke("_contrib_quantize_v2", x)
    assert q.dtype == np.int8
    back = nd.invoke("_contrib_dequantize", q, mn, mxr)
    err = np.abs(back.asnumpy() - x.asnumpy()).max()
    assert err <= float(mxr.asnumpy()) / 127.0 + 1e-6


def test_quantize_v2_calibrated_range_clips():
    x = nd.array(np.array([-10.0, -1.0, 0.5, 20.0], np.float32))
    q, mn, mxr = nd.invoke("_contrib_quantize_v2", x,
                           min_calib_range=-2.0, max_calib_range=2.0)
    back = nd.invoke("_contrib_dequantize", q, mn, mxr).asnumpy()
    assert back[3] <= 2.0 + 1e-6  # clipped at the calibrated threshold
    np.testing.assert_allclose(back[2], 0.5, atol=2.0 / 127)


def test_quantized_fully_connected_vs_fp32():
    rng = np.random.RandomState(1)
    x = rng.randn(8, 16).astype(np.float32)
    w = rng.randn(4, 16).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    xq, xmn, xmx = nd.invoke("_contrib_quantize_v2", nd.array(x))
    wq, wmn, wmx = nd.invoke("_contrib_quantize_v2", nd.array(w))
    bq, bmn, bmx = nd.invoke("_contrib_quantize_v2", nd.array(b))
    out32, omn, omx = nd.invoke(
        "_contrib_quantized_fully_connected", xq, wq, bq, xmn, xmx,
        wmn, wmx, bmn, bmx, num_hidden=4)
    assert out32.dtype == np.int32
    deq = nd.invoke("_contrib_dequantize", out32, omn, omx).asnumpy()
    ref = x @ w.T + b
    # int8 matmul: relative tolerance scales with the value magnitudes
    assert np.abs(deq - ref).max() / max(np.abs(ref).max(), 1) < 0.05


def test_quantized_conv_vs_fp32():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(5, 3, 3, 3).astype(np.float32)
    xq, xmn, xmx = nd.invoke("_contrib_quantize_v2", nd.array(x))
    wq, wmn, wmx = nd.invoke("_contrib_quantize_v2", nd.array(w))
    out32, omn, omx = nd.invoke(
        "_contrib_quantized_conv", xq, wq, None, xmn, xmx, wmn, wmx,
        None, None, kernel=(3, 3), pad=(1, 1), num_filter=5, no_bias=True)
    deq = nd.invoke("_contrib_dequantize", out32, omn, omx).asnumpy()
    ref = np.asarray(mx.nd.invoke(
        "Convolution", nd.array(x), nd.array(w), kernel=(3, 3), pad=(1, 1),
        num_filter=5, no_bias=True).asnumpy())
    assert np.abs(deq - ref).max() / max(np.abs(ref).max(), 1) < 0.05


def test_entropy_threshold_ignores_outliers():
    """KL calibration should pick a threshold well below a lone outlier."""
    rng = np.random.RandomState(3)
    c = qz._Collector()
    bulk = rng.randn(20000).astype(np.float32)
    data = np.concatenate([bulk, [500.0]])
    c.update("layer", data)
    t_naive = qz.calib_thresholds(c, "naive")["layer"]
    t_entropy = qz.calib_thresholds(c, "entropy")["layer"]
    assert t_naive >= 499.0
    assert t_entropy < 100.0  # far below the 500 outlier


def _calib_batches(rng, n, shape):
    return [nd.array(rng.randn(*shape).astype(np.float32)) for _ in range(n)]


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_net_mlp(calib_mode):
    mx.random.seed(4)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(32,
                activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(32, 20).astype(np.float32))
    ref = net(x).asnumpy()
    qnet = qz.quantize_net(
        net, calib_mode=calib_mode,
        calib_data=_calib_batches(rng, 4, (32, 20))
        if calib_mode != "none" else None)
    out = qnet(x).asnumpy()
    cos = (ref * out).sum() / (np.linalg.norm(ref) * np.linalg.norm(out))
    # entropy mode deliberately trades tail range for resolution (KL-optimal
    # clipping) — a random tiny MLP has no classification margin to absorb
    # it, so its bound is looser than the minmax modes'
    bound = 0.95 if calib_mode == "entropy" else 0.999
    assert cos > bound, "cosine %.5f under calib_mode=%s" % (cos, calib_mode)


def test_quantize_net_excludes_layers():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16), nn.Dense(8))
    net.initialize(ctx=mx.cpu())
    x = nd.array(np.random.randn(4, 10).astype(np.float32))
    net(x)
    first_name = list(net._children.values())[0].name
    qnet = qz.quantize_net(net, calib_mode="none",
                           exclude_layers=[first_name])
    kids = list(qnet._children.values())
    assert isinstance(kids[0], nn.Dense)
    assert isinstance(kids[1], qz.QuantizedDense)


def test_quantize_net_zoo_model():
    """Verdict done-criterion: quantized zoo model within tolerance of
    fp32."""
    from mxnet_tpu.gluon.model_zoo import vision
    mx.random.seed(6)
    net = vision.resnet18_v1(classes=10)
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(4, 3, 32, 32).astype(np.float32))
    ref = net(x).asnumpy()
    qnet = qz.quantize_net(net, calib_mode="naive",
                           calib_data=_calib_batches(rng, 2, (4, 3, 32, 32)))
    out = qnet(x).asnumpy()
    cos = (ref * out).sum() / (np.linalg.norm(ref) * np.linalg.norm(out))
    assert cos > 0.99, "cosine similarity %.4f" % cos
    # top-1 agreement on the tiny batch
    assert (ref.argmax(1) == out.argmax(1)).mean() >= 0.75


def test_quantize_net_hybridized_calibrates():
    """Calibration must see real activations even when the net was
    hybridized (the cached jit program would bypass python probes)."""
    mx.random.seed(8)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    rng = np.random.RandomState(2)
    x = nd.array(rng.randn(8, 6).astype(np.float32))
    net(x)
    qnet = qz.quantize_net(net, calib_mode="naive",
                           calib_data=_calib_batches(rng, 2, (8, 6)))
    kids = list(qnet._children.values())
    assert all(isinstance(k, qz.QuantizedDense) for k in kids)
    assert all(k._act_max is not None for k in kids), \
        "hybridized calibration produced no thresholds"
    out = qnet(x)  # runs through a fresh trace
    assert np.isfinite(out.asnumpy()).all()


# ---------------------------------------------------------------------------
# symbolic quantize_model (reference: quantization.py quantize_model)
# ---------------------------------------------------------------------------
def _sym_model():
    from mxnet_tpu import sym
    data = sym.Variable("data")
    c1 = sym.Convolution(data, sym.Variable("c1_w"), sym.Variable("c1_b"),
                         kernel=(3, 3), num_filter=8, pad=(1, 1), name="c1")
    a1 = sym.Activation(c1, act_type="relu")
    f1 = sym.FullyConnected(a1, sym.Variable("f1_w"), sym.Variable("f1_b"),
                            num_hidden=10, name="f1")
    return sym.softmax(f1, name="sm")


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_model_symbolic(calib_mode):
    out = _sym_model()
    rng = np.random.RandomState(0)
    ex = out.simple_bind(mx.cpu(), data=(4, 3, 8, 8))
    arg_params = {}
    for k, v in ex.arg_dict.items():
        if k != "data":
            v[:] = rng.randn(*v.shape).astype(np.float32) * 0.1
            arg_params[k] = v.copy()
    x = rng.rand(4, 3, 8, 8).astype(np.float32)
    ex.forward(data=x)
    ref = ex.outputs[0].asnumpy()

    qsym, qarg, _ = qz.quantize_model(
        out, arg_params, {}, calib_mode=calib_mode,
        calib_data=nd.array(x) if calib_mode != "none" else None)
    # fp32 weights replaced by int8 + range params
    assert "c1_w" not in qarg and "c1_w_quantize" in qarg
    assert qarg["c1_w_quantize"].dtype == np.int8
    # the rewritten graph serializes and reloads (JSON roundtrip)
    import mxnet_tpu.symbol as msym
    qsym = msym.load_json(qsym.tojson())
    qex = qsym.simple_bind(mx.cpu(), data=(4, 3, 8, 8))
    # public path: simple_bind honors __dtype__ (int8 buffers), copyto
    # preserves the payload
    assert qex.arg_dict["c1_w_quantize"].dtype == np.int8
    for k, v in qarg.items():
        if k in qex.arg_dict:
            v.copyto(qex.arg_dict[k])
    qex.forward(data=x)
    got = qex.outputs[0].asnumpy()
    cos = (ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got))
    assert cos > 0.999, cos
    assert (got.argmax(-1) == ref.argmax(-1)).all()


def test_quantize_model_excludes():
    out = _sym_model()
    rng = np.random.RandomState(0)
    ex = out.simple_bind(mx.cpu(), data=(2, 3, 8, 8))
    arg_params = {k: v.copy() for k, v in ex.arg_dict.items()
                  if k != "data"}
    qsym, qarg, _ = qz.quantize_model(out, arg_params, {},
                                      excluded_sym_names=["c1"],
                                      calib_mode="none")
    assert "c1_w" in qarg and "c1_w_quantize" not in qarg
    assert "f1_w_quantize" in qarg


def test_load_json_multi_output_slot0():
    """Slot 0 of a multi-output node must be sliced on reload (was: the
    whole output group leaked into the consumer)."""
    from mxnet_tpu import sym
    import mxnet_tpu.symbol as msym
    q = sym.contrib.quantize_v2(sym.Variable("x"))
    out = q[0].astype("float32") * 2.0
    loaded = msym.load_json(out.tojson())
    ex = loaded.simple_bind(mx.cpu(), x=(2, 3))
    ex.forward(x=np.ones((2, 3), np.float32))
    assert ex.outputs[0].shape == (2, 3)
