"""mx.np vs real numpy behavior sweep.

reference idiom: tests/python/unittest/test_numpy_op.py — every op checked
against numpy's own result on random inputs. Sweep families: elementwise,
reductions, manipulation, indexing edge semantics (boolean masks, fancy
indexing, zero-dim), operator protocols, and the documented mx.np
deviations (float32 default dtype for python lists).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx

np = mx.np


def _cmp(mx_out, onp_out, rtol=1e-5, atol=1e-6):
    if isinstance(onp_out, (tuple, list)):
        assert len(mx_out) == len(onp_out)
        for m, o in zip(mx_out, onp_out):
            _cmp(m, o, rtol, atol)
        return
    got = mx_out.asnumpy() if hasattr(mx_out, "asnumpy") else onp.asarray(
        mx_out)
    onp.testing.assert_allclose(got, onp_out, rtol=rtol, atol=atol)


A = onp.random.RandomState(7).randn(3, 4).astype("float32")
B = onp.random.RandomState(8).randn(3, 4).astype("float32")
P = onp.abs(A) + 0.5          # strictly positive
I4 = onp.array([[3, 1, 2, 0], [1, 0, 3, 2], [0, 2, 1, 3]], dtype="int32")

UNARY = [
    ("exp", A), ("expm1", A), ("log", P), ("log2", P), ("log10", P),
    ("log1p", P), ("sqrt", P), ("cbrt", A), ("square", A), ("abs", A),
    ("fabs", A), ("sign", A), ("sin", A), ("cos", A), ("tan", A),
    ("arcsin", A / 10), ("arccos", A / 10), ("arctan", A), ("sinh", A),
    ("cosh", A), ("tanh", A), ("arcsinh", A), ("arccosh", P + 1),
    ("arctanh", A / 10), ("floor", A), ("ceil", A), ("trunc", A),
    ("rint", A), ("fix", A), ("degrees", A), ("radians", A),
    ("deg2rad", A), ("rad2deg", A), ("negative", A), ("positive", A),
    ("reciprocal", P), ("exp2", A), ("sinc", A), ("i0", A),
    ("nan_to_num", A), ("isnan", A), ("isinf", A), ("isfinite", A),
    ("signbit", A), ("real", A), ("imag", A), ("conj", A),
]

BINARY = [
    ("add", A, B), ("subtract", A, B), ("multiply", A, B),
    ("divide", A, P), ("true_divide", A, P), ("power", P, B),
    ("float_power", P, B), ("maximum", A, B), ("minimum", A, B),
    ("fmax", A, B), ("fmin", A, B), ("hypot", A, B),
    ("arctan2", A, B), ("atan2", A, B), ("copysign", A, B),
    ("logaddexp", A, B), ("logaddexp2", A, B), ("mod", A, P),
    ("remainder", A, P), ("fmod", A, P), ("heaviside", A, B),
    ("nextafter", A, B), ("floor_divide", A, P),
    ("equal", A, B), ("not_equal", A, B), ("greater", A, B),
    ("less", A, B), ("greater_equal", A, B), ("less_equal", A, B),
    ("logical_and", A, B), ("logical_or", A, B), ("logical_xor", A, B),
]

REDUCTIONS = [
    ("sum", {}), ("sum", {"axis": 0}), ("sum", {"axis": 1, "keepdims": True}),
    ("prod", {}), ("mean", {"axis": 0}), ("std", {}), ("var", {"axis": 1}),
    ("max", {}), ("min", {"axis": 0}), ("amax", {"axis": 1}),
    ("amin", {}), ("ptp", {}), ("median", {}), ("cumsum", {"axis": 1}),
    ("cumprod", {"axis": 0}), ("nansum", {}), ("nanmean", {"axis": 0}),
    ("nanmax", {}), ("nanmin", {}), ("argmax", {}), ("argmin", {"axis": 1}),
    ("count_nonzero", {}), ("all", {}), ("any", {}),
]

MANIP = [
    ("reshape", (A, (4, 3))), ("ravel", (A,)), ("transpose", (A,)),
    ("swapaxes", (A, 0, 1)), ("moveaxis", (A, 0, 1)),
    ("rollaxis", (A, 1)), ("expand_dims", (A, 0)),
    ("squeeze", (A[None],)), ("broadcast_to", (A[0], (3, 4))),
    ("tile", (A, 2)), ("repeat", (A, 2)), ("roll", (A, 1)),
    ("flip", (A,)), ("fliplr", (A,)), ("flipud", (A,)), ("rot90", (A,)),
    ("atleast_1d", (A[0, 0],)), ("atleast_2d", (A[0],)),
    ("atleast_3d", (A,)), ("diag", (A[0],)), ("diagflat", (A[0],)),
    ("tril", (A,)), ("triu", (A,)), ("vander", (A[0],)),
    ("sort", (A,)), ("argsort", (A,)), ("diff", (A,)),
    ("ediff1d", (A,)), ("gradient", (A[0],)), ("trim_zeros",
        (onp.array([0., 1., 2., 0.]),)),
    ("append", (A, B)), ("delete", (A, 1, 0)), ("insert", (A, 1, 5.0, 0)),
    ("interp", (onp.array([1.5, 2.5]), onp.array([1., 2., 3.]),
                onp.array([3., 4., 5.]))),
    ("convolve", (A[0], B[0])), ("correlate", (A[0], B[0])),
    ("unwrap", (A[0],)), ("take", (A, I4[0], 1)),
    ("compress", (onp.array([True, False, True]), A, 0)),
    ("polyval", (onp.array([1., -2., 3.]), A[0],)),
]


@pytest.mark.parametrize("name,arg", UNARY, ids=[u[0] for u in UNARY])
def test_unary_matches_numpy(name, arg):
    _cmp(getattr(np, name)(np.array(arg)), getattr(onp, name)(arg),
         rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name,a,b", BINARY, ids=[b[0] for b in BINARY])
def test_binary_matches_numpy(name, a, b):
    _cmp(getattr(np, name)(np.array(a), np.array(b)),
         getattr(onp, name)(a, b), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name,kw", REDUCTIONS,
                         ids=["%s-%s" % (r[0], r[1]) for r in REDUCTIONS])
def test_reduction_matches_numpy(name, kw):
    _cmp(getattr(np, name)(np.array(A), **kw), getattr(onp, name)(A, **kw),
         rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name,args", MANIP, ids=[m[0] for m in MANIP])
def test_manipulation_matches_numpy(name, args):
    mx_args = [np.array(a) if isinstance(a, onp.ndarray) else a
               for a in args]
    _cmp(getattr(np, name)(*mx_args), getattr(onp, name)(*args),
         rtol=1e-4, atol=1e-5)


def test_multioutput_matches_numpy():
    _cmp(np.divmod(np.array(P), 2.0), onp.divmod(P, 2.0))
    _cmp(np.modf(np.array(A)), onp.modf(A))
    _cmp(np.frexp(np.array(P)), onp.frexp(P), rtol=1e-6)
    h, e = np.histogram(np.array(A.ravel()), bins=5)
    h2, e2 = onp.histogram(A.ravel(), bins=5)
    _cmp(h, h2); _cmp(e, e2, rtol=1e-5)
    _cmp(np.unravel_index(np.array([7], dtype="int32"), (3, 4)),
         onp.unravel_index(onp.array([7]), (3, 4)))
    u, inv = np.unique(np.array(I4), return_inverse=True)
    u2, inv2 = onp.unique(I4, return_inverse=True)
    _cmp(u, u2); _cmp(inv.reshape(inv2.shape), inv2)
    _cmp(np.meshgrid(np.array(A[0]), np.array(B[0])),
         onp.meshgrid(A[0], B[0]))


def test_sets_match_numpy():
    x = onp.array([1, 2, 3, 4], dtype="int32")
    y = onp.array([3, 4, 5], dtype="int32")
    for name in ["union1d", "intersect1d", "setdiff1d", "setxor1d"]:
        _cmp(getattr(np, name)(np.array(x), np.array(y)),
             getattr(onp, name)(x, y))
    _cmp(np.isin(np.array(x), np.array(y)), onp.isin(x, y))


def test_integer_and_bit_ops_match_numpy():
    x = onp.array([12, 7, 255], dtype="int32")
    y = onp.array([10, 3, 15], dtype="int32")
    for name in ["bitwise_and", "bitwise_or", "bitwise_xor", "gcd",
                 "lcm", "left_shift"]:
        _cmp(getattr(np, name)(np.array(x), np.array(y % 4)),
             getattr(onp, name)(x, y % 4))
    _cmp(np.invert(np.array(x)), onp.invert(x))
    _cmp(np.bincount(np.array(y)), onp.bincount(y))
    _cmp(np.packbits(np.array([1, 0, 1], dtype="uint8")),
         onp.packbits(onp.array([1, 0, 1], dtype="uint8")))


# ---------------------------------------------------------------------------
# indexing edge semantics (reference: test_numpy_ndarray.py indexing sweeps)
# ---------------------------------------------------------------------------
def test_basic_indexing_matches_numpy():
    x = onp.arange(24.0, dtype="float32").reshape(2, 3, 4)
    m = np.array(x)
    for key in [0, -1, (0, 1), (slice(None), 1), (slice(1, None),),
                (0, slice(None, None, 2)), (Ellipsis, 0),
                (None, 0), (0, None, 1), (slice(None), slice(None, None, -1)),
                (1, slice(2, 0, -1), slice(None))]:
        got = m[key]
        _cmp(got, x[key])


def test_boolean_mask_get_set():
    x = onp.arange(12.0, dtype="float32").reshape(3, 4)
    m = np.array(x)
    mask = m > 5
    _cmp(m[mask], x[x > 5])
    m[mask] = -1.0
    x[x > 5] = -1.0
    _cmp(m, x)
    # row-mask
    rm = np.array(onp.array([True, False, True]))
    _cmp(m[rm], x[onp.array([True, False, True])])


def test_fancy_indexing_get_set():
    x = onp.arange(12.0, dtype="float32").reshape(3, 4)
    m = np.array(x)
    idx = onp.array([2, 0], dtype="int32")
    _cmp(m[np.array(idx)], x[idx])
    _cmp(m[np.array(idx), 1], x[idx, 1])
    _cmp(m[np.array(idx), np.array(idx)], x[idx, idx])
    # list index
    _cmp(m[[0, 2]], x[[0, 2]])
    # set through fancy index
    m[np.array(idx)] = 9.0
    x[idx] = 9.0
    _cmp(m, x)
    m[np.array([0], dtype="int32"), np.array([3], dtype="int32")] = 7.0
    x[onp.array([0]), onp.array([3])] = 7.0
    _cmp(m, x)


def test_zero_dim_semantics():
    s = np.array(3.5)
    assert s.shape == () and s.ndim == 0
    assert abs(float(s) - 3.5) < 1e-6
    assert abs(s.item() - 3.5) < 1e-6
    with pytest.raises(TypeError):
        iter(s)
    r = np.array([1.0, 2.0]).sum()
    assert r.shape == ()
    # zero-dim participates in arithmetic
    _cmp(s + np.array([1.0]), onp.float32(3.5) + onp.array([1.0]))


def test_operator_protocols_match_numpy():
    a = onp.array([[1.0, 2.0], [3.0, 4.0]], dtype="float32")
    b = onp.array([[5.0, 6.0], [7.0, 8.0]], dtype="float32")
    ma, mb = np.array(a), np.array(b)
    _cmp(ma @ mb, a @ b)
    _cmp(ma // mb, a // b)
    _cmp(ma % mb, a % b)
    q, r = divmod(ma, mb)
    q2, r2 = divmod(a, b)
    _cmp(q, q2); _cmp(r, r2)
    ia = onp.array([6, 12], dtype="int32")
    ib = onp.array([3, 10], dtype="int32")
    mi, mj = np.array(ia), np.array(ib)
    _cmp(mi & mj, ia & ib)
    _cmp(mi | mj, ia | ib)
    _cmp(mi ^ mj, ia ^ ib)
    _cmp(~mi, ~ia)
    _cmp(mi << 1, ia << 1)
    _cmp(mi >> 1, ia >> 1)
    # comparisons produce bool arrays usable as masks
    assert (ma > 2).dtype == onp.bool_
    # in-place
    c = np.array(a.copy()); c += 1; _cmp(c, a + 1)
    c = np.array(a.copy()); c //= 2; _cmp(c, a // 2)
    c = np.array(a.copy()); c **= 2; _cmp(c, a ** 2)
    # containment
    assert 3.0 in ma
    assert 99.0 not in ma


def test_numpy_deviation_defaults():
    """Documented mx.np deviations: python lists default to float32."""
    assert np.array([1, 2]).dtype == onp.float32
    # dtype-carrying sources keep their dtype up to TPU-native width:
    # 64-bit ints/floats narrow to 32 (XLA default; x64 opt-in), the
    # TPU-first analog of the reference's os-dependent int64 default
    assert np.array(onp.array([1, 2], dtype="int64")).dtype == onp.int32
    assert np.array(onp.ones(3, dtype="float64")).dtype == onp.float32
    assert np.array(onp.array([1, 2], dtype="int16")).dtype == onp.int16
    assert np.array(onp.ones(3, dtype="float16")).dtype == onp.float16


def test_method_surface_matches_numpy():
    x = onp.random.RandomState(3).rand(4, 5).astype("float32")
    m = np.array(x)
    _cmp(m.std(), x.std(), rtol=1e-5)
    _cmp(m.var(axis=1), x.var(axis=1), rtol=1e-4)
    _cmp(m.cumsum(axis=0), x.cumsum(axis=0), rtol=1e-5)
    _cmp(m.argsort(axis=1), x.argsort(axis=1))
    _cmp(m.diagonal(), x.diagonal())
    _cmp(m.trace(), x.trace(), rtol=1e-5)
    _cmp(m.dot(m.T), x.dot(x.T), rtol=1e-4)
    _cmp(m.ptp(), onp.ptp(x))   # numpy 2.0 removed ndarray.ptp; mx keeps it
    _cmp(m.round(2), x.round(2), atol=1e-6)
    _cmp(m.take(np.array([0, 2], dtype="int32"), axis=1),
         x.take([0, 2], axis=1))
    nz = m.nonzero(); nz2 = x.nonzero()
    _cmp(list(nz), list(nz2))
    assert m.flatten().shape == (20,)       # numpy semantics: full 1-D
    assert m.ravel().shape == (20,)
    assert m.itemsize == 4 and m.nbytes == 80
    assert m.tobytes() == x.tobytes()
    y = np.array([3.0, 1.0, 2.0])
    y.sort()                                 # in place, like numpy
    _cmp(y, onp.array([1.0, 2.0, 3.0]))
    z = np.zeros((2, 2))
    z.fill(7.0)
    _cmp(z, onp.full((2, 2), 7.0))
    # flat iterates in row-major order
    assert [round(v.item(), 3) for v in np.array([[1., 2.], [3., 4.]]).flat] \
        == [1.0, 2.0, 3.0, 4.0]


def test_mutating_functions_match_numpy():
    x = onp.eye(3, dtype="float32")
    m = np.array(x)
    np.fill_diagonal(m, 5.0); onp.fill_diagonal(x, 5.0)
    _cmp(m, x)
    np.place(m, m == 0.0, 9.0); onp.place(x, x == 0.0, 9.0)
    _cmp(m, x)
    np.put(m, onp.array([0]), -1.0); onp.put(x, [0], -1.0)
    _cmp(m, x)
    dst = np.zeros((3, 3)); np.copyto(dst, m)
    _cmp(dst, x)


def test_window_and_creation_functions():
    for name in ["bartlett", "blackman", "hamming", "hanning"]:
        _cmp(getattr(np, name)(8), getattr(onp, name)(8), rtol=1e-5,
             atol=1e-6)
    _cmp(np.kaiser(8, 3.0), onp.kaiser(8, 3.0), rtol=1e-5, atol=1e-5)
    _cmp(np.geomspace(1.0, 64.0, 7), onp.geomspace(1.0, 64.0, 7),
         rtol=1e-5)
    _cmp(np.tri(3), onp.tri(3))
    _cmp(np.indices((2, 3)), onp.indices((2, 3)))
    _cmp(np.tril_indices(3), onp.tril_indices(3))
    _cmp(np.frombuffer(b"\x00\x00\x80?", dtype="float32"),
         onp.frombuffer(b"\x00\x00\x80?", dtype="float32"))
    _cmp(np.fromiter(range(4), "float32"), onp.fromiter(range(4),
                                                        "float32"))
    _cmp(np.block([[np.ones((2, 2)), np.zeros((2, 2))]]),
         onp.block([[onp.ones((2, 2)), onp.zeros((2, 2))]]))
    _cmp(np.c_[np.array([1., 2.]), np.array([3., 4.])],
         onp.c_[onp.array([1., 2.]), onp.array([3., 4.])])
    _cmp(np.r_[np.array([1., 2.]), np.array([3., 4.])],
         onp.r_[onp.array([1., 2.]), onp.array([3., 4.])])


def test_logic_and_type_inspection():
    a = np.array([1.0, 2.0])
    assert bool(np.allclose(a, a))
    assert bool(np.array_equal(a, a))
    assert not bool(np.array_equal(a, a + 1))
    assert np.isscalar(3.0) and not np.isscalar(a)
    assert np.result_type(a, onp.float64) == onp.float64
    assert np.iscomplexobj(np.array(onp.array([1 + 1j]))) is True
    assert np.isrealobj(a) is True
    assert np.shape(a) == (2,) and np.ndim(a) == 1 and np.size(a) == 2
    assert np.shares_memory(a, a[0:1])
    assert not np.shares_memory(a, a.copy())


def test_save_load_roundtrip(tmp_path):
    x = np.array(onp.random.rand(3, 2).astype("float32"))
    p = str(tmp_path / "arr.npy")
    np.save(p, x)
    y = np.load(p)
    _cmp(y, x.asnumpy())


def test_random_distributions_statistics():
    np.random.seed(11)
    n = 20000
    assert abs(np.random.lognormal(0.0, 0.25, size=(n,)).asnumpy().mean()
               - onp.exp(0.25 ** 2 / 2)) < 0.05
    assert abs(np.random.laplace(1.0, 2.0, size=(n,)).asnumpy().mean()
               - 1.0) < 0.1
    assert abs(np.random.rayleigh(2.0, size=(n,)).asnumpy().mean()
               - 2.0 * onp.sqrt(onp.pi / 2)) < 0.1
    assert abs(np.random.chisquare(4.0, size=(n,)).asnumpy().mean()
               - 4.0) < 0.15
    w = np.random.weibull(2.0, size=(n,)).asnumpy()
    assert abs(w.mean() - 0.8862) < 0.05
    g = np.random.gumbel(0.0, 1.0, size=(n,)).asnumpy()
    assert abs(g.mean() - 0.5772) < 0.1
    b = np.random.bernoulli(0.3, size=(n,)).asnumpy()
    assert abs(b.mean() - 0.3) < 0.05
    bi = np.random.binomial(10, 0.5, size=(n // 10,)).asnumpy()
    assert abs(bi.mean() - 5.0) < 0.3
    mvn = np.random.multivariate_normal(
        np.array([1.0, -1.0]),
        np.array([[1.0, 0.3], [0.3, 1.0]]), size=n // 4)
    mu = mvn.asnumpy().mean(axis=0)
    assert abs(mu[0] - 1.0) < 0.1 and abs(mu[1] + 1.0) < 0.1
    pm = np.random.permutation(10).asnumpy()
    assert sorted(pm.tolist()) == list(range(10))


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------
def test_asarray_does_not_mutate_legacy_array():
    import mxnet_tpu as mx
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    d = {a: "cached"}                       # legacy arrays are id-hashable
    view = np.asarray(a)
    assert type(a) is mx.nd.NDArray         # caller's object untouched
    assert d[a] == "cached"
    assert type(view) is np.ndarray
    # aliasing goes both ways through the view
    a[0, 0] = 9.0
    assert abs(view[0, 0].item() - 9.0) < 1e-6
    view[1, 1] = -1.0
    assert abs(a[1, 1].asnumpy() - (-1.0)) < 1e-6
    # legacy flatten semantics survive (batch-preserving 2-D)
    assert a.flatten().shape == (2, 2)
    assert view.flatten().shape == (4,)


def test_concatenate_positional_axis():
    a = np.ones((2, 3))
    _cmp(np.concatenate((a, a), 1), onp.concatenate(
        (onp.ones((2, 3)),) * 2, 1))
    _cmp(np.stack((a, a), 1), onp.stack((onp.ones((2, 3)),) * 2, 1))


def test_r_c_slice_keys():
    _cmp(np.r_[0:5], onp.r_[0:5])
    _cmp(np.r_[0:1:5j], onp.r_[0:1:5j], rtol=1e-6)
    _cmp(np.r_[np.array([9.0]), 0:3], onp.r_[onp.array([9.0]), 0:3])


def test_npx_softmax_respects_length():
    import mxnet_tpu as mx
    x = np.array([[1.0, 2.0, 3.0, 4.0]])
    s = mx.npx.softmax(x, axis=-1, length=np.array([2], dtype="int32"))
    out = s.asnumpy()[0]
    assert out[2] == 0.0 and out[3] == 0.0        # masked tail
    onp.testing.assert_allclose(out[:2].sum(), 1.0, rtol=1e-5)


def test_shuffle_choice_reproducible_under_seed():
    import mxnet_tpu as mx
    mx.random.seed(42)
    p1 = np.random.permutation(16).asnumpy()
    c1 = np.random.choice(10, size=(6,), replace=False).asnumpy()
    w1 = np.random.choice(5, size=(8,),
                          p=[0.1, 0.2, 0.3, 0.2, 0.2]).asnumpy()
    m1 = np.random.multinomial(20, [0.25, 0.25, 0.5]).asnumpy()
    mx.random.seed(42)
    onp.testing.assert_array_equal(np.random.permutation(16).asnumpy(), p1)
    onp.testing.assert_array_equal(
        np.random.choice(10, size=(6,), replace=False).asnumpy(), c1)
    onp.testing.assert_array_equal(
        np.random.choice(5, size=(8,),
                         p=[0.1, 0.2, 0.3, 0.2, 0.2]).asnumpy(), w1)
    onp.testing.assert_array_equal(
        np.random.multinomial(20, [0.25, 0.25, 0.5]).asnumpy(), m1)
    # statistics: weighted choice follows p; permutation is a permutation
    draws = np.random.choice(3, size=(6000,), p=[0.6, 0.3, 0.1]).asnumpy()
    freq = onp.bincount(draws.astype("int64"), minlength=3) / 6000.0
    onp.testing.assert_allclose(freq, [0.6, 0.3, 0.1], atol=0.04)
    assert sorted(p1.tolist()) == list(range(16))
    assert len(set(c1.tolist())) == 6
    m = np.random.multinomial(50, [0.5, 0.5], size=2).asnumpy()
    assert m.shape == (2, 2) and (m.sum(axis=1) == 50).all()


def test_review_round2_regressions():
    import mxnet_tpu as mx
    # masked_softmax: fully-masked fp16 row -> 0, not NaN
    d = np.array(onp.random.rand(2, 4).astype("float16"))
    m = np.array(onp.array([[1, 1, 0, 0], [0, 0, 0, 0]], dtype="float16"))
    out = mx.npx.masked_softmax(d, m).asnumpy()
    assert onp.isfinite(out).all()
    onp.testing.assert_allclose(out[1], 0.0)
    onp.testing.assert_allclose(out[0, :2].sum(), 1.0, rtol=1e-3)
    # take(mode="clip") clamps out-of-bounds indices
    a = np.array([1.0, 2.0, 3.0])
    got = a.take(np.array([5], dtype="int32")).asnumpy()
    onp.testing.assert_allclose(got, [3.0])
    # sum/mean dtype is honored (fp16 overflow avoided)
    big = np.full((70000,), 1.0, dtype="float16")
    assert onp.isinf(big.sum().asnumpy())            # fp16 accum overflows
    assert float(big.sum(dtype="float32").asnumpy()) == 70000.0
    # vectorized binomial/multinomial still correct + seeded
    mx.random.seed(9)
    b = np.random.binomial(100, 0.5, size=(500,)).asnumpy()
    assert abs(b.mean() - 50.0) < 1.5
    m2 = np.random.multinomial(30, [0.2, 0.8], size=3).asnumpy()
    assert m2.shape == (3, 2) and (m2.sum(axis=1) == 30).all()
