"""Runtime telemetry subsystem tests.

Covers the registry semantics (counter/gauge/histogram, thread safety,
type conflicts), the instrumented hot paths (CachedOp JIT-cache metrics,
kvstore comm bytes, train-step histograms, sync counters), disabled-mode
no-op behavior, chrome-trace export structure, the profiler integration,
and the pause/resume + Scope + dumps-format profiler satellites.
"""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts from an empty, enabled registry and leaves the
    global state the way the rest of the suite expects it."""
    was_enabled = telemetry.ENABLED
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    (telemetry.enable if was_enabled else telemetry.disable)()


# ---------------------------------------------------------------- registry
def test_counter_semantics():
    c = telemetry.counter("t.calls")
    assert c.value == 0
    c.inc()
    c.inc(41)
    assert c.value == 42
    assert telemetry.snapshot()["counters"]["t.calls"] == 42
    # module-level convenience targets the same metric
    telemetry.inc("t.calls", 8)
    assert c.value == 50


def test_gauge_watermark():
    telemetry.set_gauge("t.mem", 100)
    telemetry.set_gauge("t.mem", 40)
    g = telemetry.snapshot()["gauges"]["t.mem"]
    assert g["value"] == 40
    assert g["max"] == 100


def test_histogram_semantics():
    for v in (0.5, 1.5, 1000.0):
        telemetry.observe("t.lat_ms", v)
    h = telemetry.snapshot()["histograms"]["t.lat_ms"]
    assert h["count"] == 3
    assert h["sum"] == pytest.approx(1002.0)
    assert h["min"] == 0.5 and h["max"] == 1000.0
    assert h["avg"] == pytest.approx(334.0)
    assert sum(h["buckets"].values()) == 3


def test_registry_type_conflict():
    telemetry.counter("t.dual")
    with pytest.raises(TypeError):
        telemetry.gauge("t.dual")


def test_counter_thread_safety():
    c = telemetry.counter("t.mt")

    def work():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


def test_dumps_formats():
    telemetry.inc("t.one", 3)
    telemetry.observe("t.h", 2.0)
    table = telemetry.dumps()
    assert "t.one" in table and "t.h" in table
    js = json.loads(telemetry.dumps(format="json"))
    assert js["counters"]["t.one"] == 3
    with pytest.raises(ValueError):
        telemetry.dumps(format="xml")


# ---------------------------------------------------------------- disabled
def test_disabled_mode_is_noop():
    telemetry.disable()
    # every instrumented path: dispatch, sync, hybridized forward,
    # kvstore push/pull, trainer step
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.rand(2, 3).astype(np.float32))
    y = nd.array(np.random.rand(2, 4).astype(np.float32))
    net(x).asnumpy()
    kv = mx.kv.create("local")
    kv.init(0, nd.zeros((4,)))
    kv.push(0, nd.ones((4,)))
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with autograd.record():
        loss = nd.mean(nd.square(net(x) - y))
    loss.backward()
    trainer.step(1)
    with telemetry.span("user.range"):
        pass
    snap = telemetry.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "histograms": {}}
    telemetry.dump_trace("/tmp/_telem_disabled_trace.json")
    events = json.load(open("/tmp/_telem_disabled_trace.json"))["traceEvents"]
    assert all(e["ph"] != "X" for e in events)  # no spans recorded


# ---------------------------------------------------------------- CachedOp
def test_cachedop_cache_metrics():
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.rand(2, 3).astype(np.float32))
    net(x)   # first call: miss + compile
    c = telemetry.snapshot()["counters"]
    assert c["cachedop.cache_miss"] == 1
    assert c["cachedop.compile"] == 1
    assert "cachedop.cache_hit" not in c
    net(x)   # same signature: hit
    net(x)
    c = telemetry.snapshot()["counters"]
    assert c["cachedop.compile"] == 1
    assert c["cachedop.cache_hit"] == 2
    h = telemetry.snapshot()["histograms"]["cachedop.compile_ms"]
    assert h["count"] == 1 and h["sum"] > 0

    # a new input shape is a retrace — the silent recompile made visible
    x2 = nd.array(np.random.rand(5, 3).astype(np.float32))
    net(x2)
    c = telemetry.snapshot()["counters"]
    assert c["cachedop.compile"] == 2
    assert c["cachedop.retrace"] == 1


# ---------------------------------------------------------------- kvstore
def test_kvstore_push_pull_byte_counters():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((8, 4)))
    kv.push("w", nd.ones((8, 4)))                 # 128 f32 bytes
    out = nd.zeros((8, 4))
    kv.pull("w", out=out)
    c = telemetry.snapshot()["counters"]
    assert c["kvstore.push_calls"] == 1
    assert c["kvstore.pull_calls"] == 1
    assert c["kvstore.push_bytes"] == 8 * 4 * 4
    assert c["kvstore.pull_bytes"] == 8 * 4 * 4
    # multi-replica push counts the full wire payload
    kv.push("w", [nd.ones((8, 4)), nd.ones((8, 4))])
    c = telemetry.snapshot()["counters"]
    assert c["kvstore.push_bytes"] == 3 * 8 * 4 * 4


# ---------------------------------------------------------------- trace
def test_chrome_trace_structure(tmp_path):
    with telemetry.span("outer", "user"):
        with telemetry.span("inner", "user"):
            pass
    telemetry.inc("t.count", 7)
    path = str(tmp_path / "trace.json")
    assert telemetry.dump_trace(path) == path
    trace = json.load(open(path))
    events = trace["traceEvents"]
    assert isinstance(events, list)
    spans = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in spans} >= {"outer", "inner"}
    for e in spans:
        assert e["ts"] >= 0 and e["dur"] >= 0 and "tid" in e
    counters = [e for e in events if e["ph"] == "C"]
    assert any(e["name"] == "t.count" and e["args"]["value"] == 7
               for e in counters)


# ------------------------------------------------------------ acceptance
def test_three_step_hybridized_training_loop(tmp_path):
    """ISSUE acceptance: 3-step hybridized Gluon loop → exactly 1 CachedOp
    compile + ≥2 hits per signature, nonzero step-time histogram, loadable
    chrome trace, telemetry inside profiler.dumps()."""
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.array(np.random.rand(8, 3).astype(np.float32))
    y = nd.array(np.random.rand(8, 4).astype(np.float32))
    for _ in range(3):
        with autograd.record():
            loss = nd.mean(nd.square(net(x) - y))
        loss.backward()
        trainer.step(8)
    snap = telemetry.snapshot()
    c = snap["counters"]
    assert c["cachedop.compile"] == 1         # one train-mode signature
    assert c["cachedop.cache_hit"] >= 2
    assert snap["histograms"]["trainer.step_ms"]["count"] == 3
    assert c["ndarray.invoke"] > 0

    path = str(tmp_path / "trace.json")
    telemetry.dump_trace(path)
    events = json.load(open(path))["traceEvents"]
    step_spans = [e for e in events
                  if e["ph"] == "X" and e["name"] == "trainer.step"]
    assert len(step_spans) == 3
    assert all(e["dur"] > 0 for e in step_spans)

    js = json.loads(mx.profiler.dumps(format="json"))
    assert js["telemetry"]["counters"]["cachedop.compile"] == 1


def test_fused_train_step_metrics():
    net = gluon.nn.Dense(2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    fused = gluon.FusedTrainStep(
        net, gluon.loss.L2Loss(), trainer)
    x = nd.array(np.random.rand(4, 3).astype(np.float32))
    y = nd.array(np.random.rand(4, 2).astype(np.float32))
    for _ in range(2):
        fused(x, y)
    snap = telemetry.snapshot()
    assert snap["counters"]["fused_step.compile"] == 1
    assert snap["histograms"]["fused_step.step_ms"]["count"] == 2


def test_sync_counters():
    a = nd.ones((4, 4))
    a.asnumpy()
    a.wait_to_read()
    c = telemetry.snapshot()["counters"]
    assert c["ndarray.sync.asnumpy"] >= 1
    assert c["ndarray.sync.wait_to_read"] >= 1


def test_memory_sampling_best_effort():
    # CPU backend usually reports no allocator stats; the call must still
    # be safe and return a count
    n = telemetry.sample_memory()
    assert isinstance(n, int) and n >= 0


# ------------------------------------------------------- profiler satellites
def test_profiler_dumps_format_validation(tmp_path):
    with pytest.raises(ValueError):
        mx.profiler.dumps(format="csv")
    mx.profiler.set_config(filename=str(tmp_path / "prof.out"))
    try:
        mx.profiler.dump(format="table")
        text = open(str(tmp_path / "prof.out")).read()
        assert text.startswith("Name")          # the human table, not JSON
        mx.profiler.dump(format="json")
        json.load(open(str(tmp_path / "prof.out")))
        with pytest.raises(ValueError):
            mx.profiler.dump(format="yaml")
    finally:
        mx.profiler.set_config(filename="profile.json")


def test_profiler_pause_resume_aggregation():
    prof = mx.profiler
    prof.reset()
    prof.set_config(profile_all=False)
    prof.set_state("run")
    try:
        nd.dot(nd.ones((4, 4)), nd.ones((4, 4))).asnumpy()
        assert "dot" in prof.dumps()
        prof.reset()
        prof.pause()
        assert prof.state() == "run"            # paused, NOT stopped
        assert prof.is_paused()
        # pause must not tear down an active device trace
        assert not prof._trace_active           # none started here...
        prof._trace_active = True
        prof.pause()
        assert prof._trace_active               # ...and pause left it alone
        prof._trace_active = False
        nd.dot(nd.ones((4, 4)), nd.ones((4, 4))).asnumpy()
        assert "dot" not in prof.dumps()        # aggregation suspended
        prof.resume()
        assert not prof.is_paused()
        nd.dot(nd.ones((4, 4)), nd.ones((4, 4))).asnumpy()
        assert "dot" in prof.dumps()
    finally:
        prof.set_state("stop")
        prof.reset()


def test_profiler_scope_reentrant_and_decorator():
    prof = mx.profiler
    prof.reset()
    s = prof.Scope("nested")
    with s:
        with s:                                  # same instance, nested
            pass
    table = prof.dumps()
    line = [ln for ln in table.splitlines() if "scope:nested" in ln][0]
    assert int(line.split()[1]) == 2             # two ranges recorded

    @prof.scope("decorated")
    def f(a, b):
        return a + b

    assert f(2, 3) == 5
    assert "scope:decorated" in prof.dumps()
    prof.reset()


# ---------------------------------------------------------------- tooling
def test_parse_log_telemetry_mode(tmp_path):
    telemetry.inc("cachedop.compile", 2)
    telemetry.set_gauge("memory.cpu0.bytes_in_use", 1024)
    telemetry.observe("trainer.step_ms", 3.5)
    dump = str(tmp_path / "telemetry.json")
    telemetry.dump(dump)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         dump, "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0] == "metric,kind,count,value,max"
    body = "\n".join(lines[1:])
    assert "cachedop.compile,counter,,2," in body
    assert "memory.cpu0.bytes_in_use,gauge,,1024,1024" in body
    assert "trainer.step_ms,histogram,1," in body

    # a profiler dump embedding telemetry parses the same way
    prof_dump = str(tmp_path / "profile.json")
    with open(prof_dump, "w") as f:
        f.write(mx.profiler.dumps(format="json"))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         prof_dump, "--telemetry"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "cachedop.compile" in r.stdout
