"""contrib.svrg_optimization (round-4 VERDICT missing #3).

reference: tests/python/unittest/test_contrib_svrg_module.py /
test_contrib_svrg_optimizer.py — snapshot/full-grad bookkeeping, the
variance-reduction identity at w == w0, and an end-to-end fit run.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.contrib.svrg_optimization import SVRGModule, SVRGOptimizer
from mxnet_tpu.io.io import NDArrayIter


def _lin_reg_symbol():
    data = sym.var("data")
    fc = sym.FullyConnected(data, name="fc", num_hidden=1)
    return sym.LinearRegressionOutput(fc, name="lro")


def _toy_iter(n=32, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 4).astype("float32")
    y = (X @ np.array([1.5, -2.0, 0.5, 3.0], "float32")
         + 0.8).astype("float32")
    return NDArrayIter(X, y, batch_size=batch, label_name="lro_label")


def _make_module(update_freq=2):
    m = SVRGModule(_lin_reg_symbol(), data_names=("data",),
                   label_names=("lro_label",), update_freq=update_freq)
    it = _toy_iter()
    m.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    m.init_params(initializer=mx.init.Uniform(0.05))
    m.init_optimizer(optimizer="sgd",
                     optimizer_params=(("learning_rate", 0.05),))
    return m, it


def test_update_full_grads_snapshots_mu():
    m, it = _make_module()
    m.update_full_grads(it)
    assert m._full_grads is not None
    names = set(m._exec_group.param_names)
    assert set(m._full_grads) == names
    # mu must equal the mean of per-batch grads computed independently
    it.reset()
    ref = {n: None for n in names}
    nb = 0
    for batch in it:
        m._mod_aux.forward(batch, is_train=True)
        m._mod_aux.backward()
        for n, grads in zip(m._mod_aux._exec_group.param_names,
                            m._mod_aux._exec_group.grad_arrays):
            g = grads[0].asnumpy()
            ref[n] = g if ref[n] is None else ref[n] + g
        nb += 1
    for n in names:
        np.testing.assert_allclose(m._full_grads[n].asnumpy(),
                                   ref[n] / nb, rtol=1e-5, atol=1e-6)


def test_variance_reduced_grad_equals_mu_at_snapshot():
    """At w == w0 the batch terms cancel exactly: g_vr == mu."""
    m, it = _make_module()
    m.update_full_grads(it)
    it.reset()
    batch = next(iter(it))
    m.forward_backward(batch)
    for n, grads in zip(m._exec_group.param_names,
                        m._exec_group.grad_arrays):
        np.testing.assert_allclose(grads[0].asnumpy(),
                                   m._full_grads[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_svrg_fit_converges():
    m = SVRGModule(_lin_reg_symbol(), data_names=("data",),
                   label_names=("lro_label",), update_freq=3)
    it = _toy_iter()
    m.fit(it, num_epoch=40, eval_metric="mse", optimizer="sgd",
          optimizer_params=(("learning_rate", 0.2),),
          initializer=mx.init.Uniform(0.05))
    it.reset()
    met = mx.metric.create("mse")
    score = m.score(it, met)
    mse = dict(score)["mse"]
    assert mse < 0.08, "SVRG fit did not converge: mse=%f" % mse


def test_svrg_matches_plain_sgd_direction_off_snapshot():
    """One step after a parameter change, g_vr != plain grad (the control
    variate is active) but both drive the loss down."""
    m, it = _make_module()
    m.update_full_grads(it)
    # move w off the snapshot
    it.reset()
    b = next(iter(it))
    m.forward_backward(b)
    m.update()
    it.reset()
    b = next(iter(it))
    m.forward_backward(b)          # now w != w0: correction is non-zero
    for n, grads, grads0 in zip(m._exec_group.param_names,
                                m._exec_group.grad_arrays,
                                m._mod_aux._exec_group.grad_arrays):
        gv = grads[0].asnumpy()
        want = (gv * 0 + m._full_grads[n].asnumpy())
        if not np.allclose(gv, want, atol=1e-7):
            break
    else:
        raise AssertionError("variance-reduced grads identical to mu "
                             "after w moved off the snapshot")


def test_svrg_optimizer_dispatch():
    opt = SVRGOptimizer(default_optimizer="sgd", learning_rate=0.5,
                        full_idx_offset=10)
    from mxnet_tpu import nd
    w = nd.array(np.ones((3,), "float32"))
    g = nd.array(np.full((3,), 2.0, "float32"))
    s = opt.create_state(0, w)
    opt.update(0, w, g, s)                 # sgd: w -= 0.5*2
    np.testing.assert_allclose(w.asnumpy(), np.zeros(3), atol=1e-6)
    mu_slot = nd.array(np.zeros((3,), "float32"))
    opt.update(11, mu_slot, g, opt.create_state(11, mu_slot))
    np.testing.assert_allclose(mu_slot.asnumpy(), g.asnumpy())


def test_svrg_optimizer_registered():
    o = mx.optimizer.create("svrgoptimizer", default_optimizer="sgd",
                            learning_rate=0.1)
    assert isinstance(o, SVRGOptimizer)
