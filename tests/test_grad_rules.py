"""Every FGradient-style rule must agree with the generic jax.vjp tape.

reference: the reference trusts its hand-written FGradient attrs to the
numeric-gradient sweep; here each rule is additionally pinned against
the generic path on broadcast/edge shapes (swap the rule out, rerun,
compare)."""
import contextlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ops import registry as _reg

RNG = np.random.RandomState(11)


@contextlib.contextmanager
def _rules_disabled():
    saved = [(op, op.vjp_rule) for op in set(_reg._REGISTRY.values())]
    for op, _ in saved:
        op.vjp_rule = None
    try:
        yield
    finally:
        for op, rule in saved:
            op.vjp_rule = rule


def _grads(build, arrs):
    xs = [nd.array(a) for a in arrs]
    for x in xs:
        x.attach_grad()
    with autograd.record():
        loss = build(*xs).sum()
    loss.backward()
    return [x.grad.asnumpy() for x in xs]


def _check(build, *arrs):
    got = _grads(build, arrs)
    with _rules_disabled():
        want = _grads(build, arrs)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=2e-5, atol=1e-6)


def _r(*shape):
    return np.asarray(RNG.rand(*shape), dtype=np.float32) + 0.5


BINARY_SHAPES = [((3, 4), (3, 4)), ((3, 4), (1, 4)), ((3, 4), (3, 1)),
                 ((2, 3, 4), (4,)), ((3, 4), ())]


@pytest.mark.parametrize("sa,sb", BINARY_SHAPES)
@pytest.mark.parametrize("opname", ["broadcast_add", "broadcast_sub",
                                    "broadcast_mul", "broadcast_div",
                                    "broadcast_maximum",
                                    "broadcast_minimum",
                                    "broadcast_power"])
def test_binary_rules(opname, sa, sb):
    _check(lambda a, b: nd.invoke(opname, a, b), _r(*sa), _r(*sb))


def test_binary_scalar_operand():
    _check(lambda a: a * 3.0 + 1.0 - a / 2.0, _r(3, 4))


@pytest.mark.parametrize("opname", ["negative", "exp", "log", "sqrt",
                                    "square", "tanh", "sigmoid", "relu",
                                    "abs", "rsqrt"])
def test_unary_rules(opname):
    _check(lambda a: nd.invoke(opname, a), _r(3, 4))


@pytest.mark.parametrize("act", ["relu", "sigmoid", "tanh", "softrelu",
                                 "softsign", "gelu"])
def test_activation_rule(act):
    # gelu exercises the backward-time jax.vjp fallback inside the rule
    _check(lambda a: nd.Activation(a, act_type=act),
           _r(3, 4) - 1.0)


@pytest.mark.parametrize("ta", [False, True])
@pytest.mark.parametrize("tb", [False, True])
def test_dot_rule(ta, tb):
    a = _r(4, 3) if ta else _r(3, 4)
    b = _r(5, 4) if tb else _r(4, 5)
    _check(lambda x, y: nd.dot(x, y, transpose_a=ta, transpose_b=tb), a, b)


def test_dot_nd_fallback():
    _check(lambda x, y: nd.dot(x, y), _r(2, 3, 4), _r(4, 5))


@pytest.mark.parametrize("flatten,bias", [(True, True), (True, False),
                                          (False, True)])
def test_fully_connected_rule(flatten, bias):
    x = _r(2, 3, 4) if flatten else _r(2, 4)

    def build(*xs):
        if bias:
            return nd.FullyConnected(xs[0], xs[1], xs[2], num_hidden=5,
                                     flatten=flatten)
        return nd.FullyConnected(xs[0], xs[1], None, num_hidden=5,
                                 no_bias=True, flatten=flatten)
    arrs = [x, _r(5, 12 if flatten else 4)] + ([_r(5)] if bias else [])
    _check(build, *arrs)


def test_shape_op_rules():
    _check(lambda a: a.reshape((4, 3)) * 2.0, _r(3, 4))
    _check(lambda a: a.T * 2.0, _r(3, 4))
    _check(lambda a: a.transpose((2, 0, 1)) * 2.0, _r(2, 3, 4))
    _check(lambda a: a.flatten() * 2.0, _r(2, 3, 4))
    _check(lambda a: a.expand_dims(1) * 2.0, _r(3, 4))


@pytest.mark.parametrize("kw", [{}, {"axis": 1}, {"axis": (0, 2)},
                                {"axis": -1, "keepdims": True},
                                {"axis": 0, "keepdims": True}])
@pytest.mark.parametrize("opname", ["sum", "mean"])
def test_reduce_rules(opname, kw):
    _check(lambda a: nd.invoke(opname, a, **kw), _r(2, 3, 4))


@pytest.mark.parametrize("opname", ["softmax", "log_softmax"])
@pytest.mark.parametrize("axis", [-1, 1])
def test_softmax_rules(opname, axis):
    _check(lambda a, m: nd.invoke(opname, a, axis=axis) * m,
           _r(2, 3, 4), _r(2, 3, 4))


def test_getitem_rule():
    _check(lambda a: a[:, 1:3] * 2.0, _r(3, 4))
    _check(lambda a: a[1] * 2.0, _r(3, 4))


def test_copy_rule():
    _check(lambda a: a.copy() * 3.0, _r(3, 4))


def test_chain_through_rules_and_generic():
    """A chain mixing rule-backed and generic-path ops."""
    def build(a, b):
        h = nd.dot(a, b).tanh()
        h = h / (h.square().sum(axis=1, keepdims=True).sqrt() + 1.0)
        return nd.log_softmax(h, axis=-1) * nd.softmax(h)
    _check(build, _r(3, 4), _r(4, 5))


def test_higher_order_still_works_through_rules():
    """create_graph replays primal fns; rules must not break it."""
    xv = _r(3)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
        gx = autograd.grad([y], [x], create_graph=True)[0]
        z = gx.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6.0 * xv, rtol=1e-5)


def test_softmax_temperature_and_sum_exclude():
    """kwargs the closed forms do not model fall back to backward-time
    jax.vjp (temperature, use_length, exclude)."""
    _check(lambda a, m: nd.softmax(a, axis=-1, temperature=2.0) * m,
           _r(2, 3, 4), _r(2, 3, 4))
    _check(lambda a: nd.sum(a, axis=1, exclude=True) * 2.0, _r(2, 3, 4))
    _check(lambda a: nd.mean(a, axis=(0,), exclude=True) * 2.0, _r(2, 3, 4))


def test_maximum_tie_splits_like_generic():
    a = np.ones((3, 4), np.float32)
    b = np.ones((3, 4), np.float32)
    _check(lambda x, y: nd.maximum(x, y), a, b)
    _check(lambda x, y: nd.minimum(x, y), a, b)


def test_mixed_dtype_chain_through_rules():
    """Rules must return input-dtype cotangents so upstream generic
    pullbacks accept them."""
    xv = _r(3, 4).astype(np.float16)
    x = nd.array(xv, dtype="float16")
    w = nd.array(_r(4,), dtype="float32")
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        h = x.astype("float16") * 1.0        # generic-ish chain start
        loss = (h.astype("float32") * w).sum()
    loss.backward()
    assert x.grad.dtype == np.float16
    assert np.isfinite(x.grad.asnumpy()).all()
