"""WaitToRead hard-barrier contract (round-3 VERDICT weak #7).

reference: NDArray::WaitToRead blocks until the dependency engine has
finished every pending write to the variable — MXNet timing and error
semantics key off it. The axon-tunnel discovery showed transports can ack
`block_until_ready` early, so `wait_to_read` adds a 1-element D2H there
(`_needs_hard_barrier`). This test pins the contract in a way that FAILS
if wait_to_read ever returns before execution completes: after the wait,
realizing the value must be near-instant relative to the compute.
"""
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _slow_chain(x, iters=60):
    """A deliberately slow dependency chain (hundreds of ms on the CPU
    test machine): iterated matmul keeps the async queue busy."""
    y = x
    for _ in range(iters):
        y = nd.dot(y, x) * (1.0 / 8.0) + x
    return y


def test_wait_to_read_blocks_until_execution_done():
    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(400, 400).astype("float32") * 0.01)
    # Adapt the chain length until measured execution sits comfortably
    # above timer noise — a machine fast enough to finish 60 iters in
    # <200ms gets a longer chain instead of a flaky ratio assert.
    iters = 60
    for _ in range(5):
        # warm the compile cache so the timed run measures execution
        _slow_chain(x, iters).wait_to_read()
        t0 = time.perf_counter()
        y = _slow_chain(x, iters)
        t_dispatch = time.perf_counter() - t0
        t1 = time.perf_counter()
        y.wait_to_read()
        t_wait = time.perf_counter() - t1
        if t_dispatch + t_wait >= 0.2:
            break
        iters *= 2

    t2 = time.perf_counter()
    _ = y.asnumpy()
    t_read = time.perf_counter() - t2

    # the wait must have absorbed the execution: reading afterwards is
    # near-instant. If wait_to_read returned early, t_read would carry
    # the compute instead and exceed t_wait.
    assert t_wait > 0.0
    assert t_read < max(0.05, 0.5 * (t_dispatch + t_wait)), (
        "wait_to_read returned before execution completed: "
        "dispatch=%.4fs wait=%.4fs read-after-wait=%.4fs"
        % (t_dispatch, t_wait, t_read))


def test_wait_to_read_surfaces_deferred_errors():
    """The barrier is where async execution errors surface (reference:
    ThreadedVar exception_ptr)."""
    a = nd.array(onp.ones((4, 4), "float32"))
    b = nd.array(onp.ones((5, 5), "float32"))
    bad = nd.dot(a, b)          # shape mismatch poisons the output var
    with pytest.raises(Exception):
        bad.wait_to_read()


def test_hard_barrier_gate_detection():
    """The axon-tunnel gate must be off for ordinary backends and its
    detection must not throw on them (BASELINE.md documents the gate)."""
    import jax
    from mxnet_tpu.ndarray.ndarray import _needs_hard_barrier
    x = nd.array(onp.ones((2,), "float32"))
    x.wait_to_read()
    client = next(iter(x.data_jax.devices())).client
    gate = _needs_hard_barrier(client)
    assert gate == ("axon" in (getattr(client, "platform_version", "")
                               or "").lower())
