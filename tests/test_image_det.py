"""ImageDetIter + detection augmenters (reference:
python/mxnet/image/detection.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image


def _make_dataset(tmp_path, n=6, size=32):
    """PNG files + packed det labels [A=2, B=5, id,x1,y1,x2,y2]."""
    from PIL import Image
    rng = np.random.RandomState(0)
    entries = []
    for i in range(n):
        arr = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        fname = "img%d.png" % i
        Image.fromarray(arr).save(str(tmp_path / fname))
        n_obj = 1 + i % 2
        label = [2, 5]
        for j in range(n_obj):
            label += [float(j), 0.2, 0.2, 0.7, 0.7]
        entries.append((np.array(label, np.float32), fname))
    return entries


def test_image_det_iter(tmp_path):
    entries = _make_dataset(tmp_path)
    it = image.ImageDetIter(batch_size=4, data_shape=(3, 16, 16),
                            imglist=entries, path_root=str(tmp_path))
    assert it.max_objects == 2 and it.obj_width == 5
    batch = next(it)
    data = batch.data[0]
    label = batch.label[0]
    assert data.shape == (4, 3, 16, 16)
    assert label.shape == (4, 2, 5)
    lab = label.asnumpy()
    # first image has one object, padded row is -1
    assert lab[0, 0, 0] == 0.0
    assert (lab[0, 1] == -1.0).all()
    np.testing.assert_allclose(lab[0, 0, 1:], [0.2, 0.2, 0.7, 0.7],
                               atol=1e-6)
    # provide_label matches emitted shape
    assert tuple(it.provide_label[0].shape) == (4, 2, 5)


def test_det_horizontal_flip_flips_boxes():
    aug = image.DetHorizontalFlipAug(p=1.0)
    src = mx.nd.array(np.zeros((8, 8, 3), np.float32))
    label = np.array([[0, 0.1, 0.2, 0.4, 0.6],
                      [-1, -1, -1, -1, -1]], np.float32)
    _, out = aug(src, label.copy())
    np.testing.assert_allclose(out[0], [0, 0.6, 0.2, 0.9, 0.6], atol=1e-6)
    assert (out[1] == -1).all()


def test_det_random_pad_keeps_boxes_inside():
    aug = image.DetRandomPadAug(area_range=(2.0, 2.0),
                                aspect_ratio_range=(1.0, 1.0))
    src = mx.nd.array(np.full((8, 8, 3), 255.0, np.float32))
    label = np.array([[1, 0.0, 0.0, 1.0, 1.0]], np.float32)
    out_img, out = aug(src, label.copy())
    # padded to ~sqrt(2)*8 per side; the box shrinks proportionally
    w = out[0, 3] - out[0, 1]
    assert 0.5 < w < 1.0
    assert out_img.shape[0] >= 8 and out_img.shape[1] >= 8


def test_det_random_crop_updates_labels():
    np.random.seed(3)
    import random as _r
    _r.seed(3)
    aug = image.DetRandomCropAug(min_object_covered=0.5,
                                 area_range=(0.5, 0.9),
                                 aspect_ratio_range=(1.0, 1.0))
    src = mx.nd.array(np.zeros((32, 32, 3), np.float32))
    label = np.array([[2, 0.25, 0.25, 0.75, 0.75]], np.float32)
    _, out = aug(src, label.copy())
    # object survives with normalized coords inside [0, 1]
    assert out[0, 0] == 2
    assert (out[0, 1:] >= -1e-6).all() and (out[0, 1:] <= 1 + 1e-6).all()


def test_create_det_augmenter_chain():
    augs = image.CreateDetAugmenter((3, 16, 16), rand_mirror=True,
                                    rand_crop=0.5, rand_pad=0.5,
                                    mean=True, std=True)
    src = mx.nd.array(np.random.RandomState(0).randint(
        0, 255, (24, 24, 3)).astype(np.uint8), dtype="uint8")
    label = np.array([[0, 0.1, 0.1, 0.8, 0.8]], np.float32)
    out, lab = src, label
    for a in augs:
        out, lab = a(out, lab)
    assert out.shape == (16, 16, 3)
    assert lab.shape == label.shape
