"""Small API namespaces (reference: python/mxnet/{rnn,visualization,
monitor,util,attribute,engine,libinfo,log}.py + gluon/contrib/)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd


def test_legacy_rnn_lstm_unroll_executes():
    cell = mx.rnn.LSTMCell(8, prefix="l0_")
    outputs, states = cell.unroll(3, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    ex = outputs.simple_bind(mx.cpu(), data=(2, 3, 4),
                             l0_begin_state_0=(2, 8),
                             l0_begin_state_1=(2, 8))
    out = ex.forward(is_train=False,
                     data=np.random.randn(2, 3, 4).astype(np.float32))
    assert out[0].shape == (2, 3, 8)


def test_legacy_rnn_stack_and_modifiers():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.GRUCell(8, prefix="g0_"))
    stack.add(mx.rnn.DropoutCell(0.2))
    stack.add(mx.rnn.ResidualCell(mx.rnn.RNNCell(8, prefix="r0_")))
    outs, states = stack.unroll(2, inputs=mx.sym.Variable("data"))
    assert len(outs) == 2
    assert len(states) == len(stack.state_info)


def test_legacy_fused_rnn_unfuse():
    fused = mx.rnn.FusedRNNCell(16, num_layers=2, mode="lstm")
    stack = fused.unfuse()
    assert len(stack._cells) == 2
    assert isinstance(stack._cells[0], mx.rnn.LSTMCell)


def test_bucket_sentence_iter():
    rng = np.random.RandomState(0)
    sentences = [list(rng.randint(1, 50, rng.randint(3, 12)))
                 for _ in range(100)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[5, 10, 15])
    batch = next(iter(it))
    assert batch.bucket_key in (5, 10, 15)
    assert batch.data[0].shape == (8, batch.bucket_key)
    assert batch.label[0].shape == (8, batch.bucket_key)
    # label is data shifted by one
    d = batch.data[0].asnumpy()
    l = batch.label[0].asnumpy()
    np.testing.assert_array_equal(l[:, :-1], d[:, 1:])


def test_viz_print_summary(capsys):
    d = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(d, mx.sym.Variable("fc_weight"),
                               mx.sym.Variable("fc_bias"), num_hidden=4)
    total = mx.viz.print_summary(mx.sym.softmax(fc),
                                 shape={"data": (2, 8)})
    assert total == 4 * 8 + 4  # weight + bias counted from inferred shapes
    out = capsys.readouterr().out
    assert "FullyConnected" in out
    with pytest.raises(ImportError):
        mx.viz.plot_network(fc)


def test_monitor_collects_stats():
    mon = mx.mon.Monitor(interval=1, pattern=".*")

    class FakeExe:
        arg_names = ["w"]
        arg_arrays = [nd.array(np.array([1.0, -3.0], np.float32))]
        grad_arrays = [nd.array(np.array([0.5, 0.5], np.float32))]
        outputs = [nd.array(np.array([2.0], np.float32))]

    mon.install(FakeExe())
    mon.tic()
    res = mon.toc()
    names = {n for _, n, _ in res}
    assert names == {"w", "w_grad", "output0"}
    stats = {n: v for _, n, v in res}
    assert abs(stats["w"] - 2.0) < 1e-6  # mean |[1,-3]|


def test_attr_scope_nests():
    with mx.AttrScope(__ctx_group__="a", lr_mult="2"):
        assert mx.attribute.current()["__ctx_group__"] == "a"
        with mx.AttrScope(__ctx_group__="b"):
            cur = mx.attribute.current()
            assert cur["__ctx_group__"] == "b"
            assert cur["lr_mult"] == "2"
        assert mx.attribute.current()["__ctx_group__"] == "a"
    assert "__ctx_group__" not in mx.attribute.current()


def test_engine_bulk_scope():
    prev = mx.engine.set_bulk_size(10)
    assert mx.engine.set_bulk_size(prev) == 10
    with mx.engine.bulk(5):
        pass


def test_util_np_scopes_and_libinfo():
    assert not mx.util.is_np_array()
    with mx.util.np_array():
        assert mx.util.is_np_array()
        arr = mx.util.default_array([1.0, 2.0])
        assert type(arr) is mx.np.ndarray
    legacy = mx.util.default_array([1.0])
    assert type(legacy) is mx.nd.NDArray
    assert mx.libinfo.__version__
    feats = mx.libinfo.features()
    assert isinstance(feats, dict)
    import os
    assert os.path.isdir(mx.libinfo.find_include_path())


def test_gluon_contrib_layers():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(2, 6).astype(np.float32))
    net = gluon.contrib.nn.HybridConcurrent(axis=1)
    net.add(gluon.nn.Dense(4), gluon.nn.Dense(3))
    net.initialize(ctx=mx.cpu())
    assert net(x).shape == (2, 7)
    assert gluon.contrib.nn.Identity()(x).shape == (2, 6)
    sbn = gluon.contrib.nn.SyncBatchNorm(in_channels=3)
    sbn.initialize(ctx=mx.cpu())
    y = sbn(nd.array(rng.randn(2, 3, 4, 4).astype(np.float32)))
    assert y.shape == (2, 3, 4, 4)


def test_gluon_contrib_variational_dropout_trains():
    from mxnet_tpu import autograd
    mx.random.seed(3)
    cell = gluon.contrib.rnn.VariationalDropoutCell(
        gluon.rnn.GRUCell(8), drop_inputs=0.3, drop_outputs=0.3)
    cell.initialize(ctx=mx.cpu())
    x = nd.array(np.random.randn(4, 5, 6).astype(np.float32))
    with autograd.record():
        outputs, _ = cell.unroll(5, x, layout="NTC", merge_outputs=True)
        loss = (outputs * outputs).sum()
    loss.backward()
    assert outputs.shape == (4, 5, 8)


def test_sdml_loss():
    """reference: gluon/loss.py (SDMLLoss) — per-sample smoothed in-batch
    softmax metric loss; matched pairs score lower than random ones."""
    from mxnet_tpu import autograd
    rng = np.random.RandomState(0)
    x1 = nd.array(rng.randn(6, 8).astype(np.float32))
    x2 = nd.array(rng.randn(6, 8).astype(np.float32))
    x1.attach_grad()
    loss_fn = gluon.loss.SDMLLoss()
    with autograd.record():
        l = loss_fn(x1, x2)
        total = l.mean()
    total.backward()
    assert l.shape == (6,)
    assert np.abs(x1.grad.asnumpy()).sum() > 0
    matched = float(loss_fn(x2, x2).mean().asnumpy())
    rand = float(total.asnumpy())
    assert matched < rand


def test_contrib_text():
    """mx.contrib.text (reference: python/mxnet/contrib/text/): Vocabulary
    indexing, CustomEmbedding loading, composite lookup."""
    import collections
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.contrib import text

    counter = text.utils.count_tokens_from_str(
        "the quick brown fox the lazy dog the fox")
    assert counter["the"] == 3 and counter["fox"] == 2

    vocab = text.Vocabulary(counter, most_freq_count=4, min_freq=1,
                            reserved_tokens=["<pad>"])
    # 0=<unk>, 1=<pad>, then freq-desc/alpha: the, fox, then 2 more
    assert vocab.to_indices("the") == 2
    assert vocab.to_indices("fox") == 3
    assert vocab.to_indices("zebra") == 0
    assert vocab.to_tokens(1) == "<pad>"
    assert len(vocab) == 6
    if "dog" in vocab.token_to_idx:
        assert vocab.to_indices(["the", "dog"]) == \
            [2, vocab.token_to_idx["dog"]]

    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "vec.txt")
        with open(path, "w") as f:
            f.write("the 1.0 2.0\nfox 3.0 4.0\n")
        emb = text.embedding.CustomEmbedding(path)
        assert emb.vec_len == 2
        v = emb.get_vecs_by_tokens(["the", "missing"]).asnumpy()
        np.testing.assert_allclose(v[0], [1.0, 2.0])
        np.testing.assert_allclose(v[1], [0.0, 0.0])   # unknown -> zeros
        emb.update_token_vectors("fox", mx.nd.array([[9.0, 9.0]]))
        np.testing.assert_allclose(
            emb.get_vecs_by_tokens("fox").asnumpy(), [9.0, 9.0])

        comp = text.embedding.CompositeEmbedding(vocab, [emb, emb])
        assert comp.vec_len == 4
        np.testing.assert_allclose(
            comp.get_vecs_by_tokens("the").asnumpy(), [1, 2, 1, 2])

    # registry mechanism
    assert "glove" in text.embedding.get_pretrained_file_names()
    names = text.embedding.get_pretrained_file_names("glove")
    assert "glove.6B.50d.txt" in names
    import pytest
    with pytest.raises(FileNotFoundError):
        text.embedding.create("glove",
                              pretrained_file_name="glove.6B.50d.txt",
                              embedding_root=str(d))
