"""Registry-wide numeric-gradient sweep (round-4 VERDICT task #5).

reference: tests/python/unittest/test_operator.py sweeps every operator
with check_numeric_gradient. Here the sweep is AUTOMATED over the op
registry: every differentiable, non-creation, non-random op object is
checked — autograd's directional derivative against a central finite
difference through the imperative `invoke` path (so the tape, not just
the jax fn, is exercised). Ops whose inputs can't be auto-generated get
a spec; ops that are legitimately unswee pable get a skip-list entry
with a reason. A final accounting test enforces >=80% checked coverage
so the sweep can't silently rot.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.ndarray.ndarray import invoke
from mxnet_tpu.ops import registry

RNG = onp.random.RandomState(11)
SHAPE = (3, 4)


def _pos(shape=SHAPE, lo=0.6, hi=1.4):
    return RNG.uniform(lo, hi, size=shape).astype("float32")


def _sym_pd(shape=(4, 4)):
    a = RNG.randn(*shape).astype("float32")
    return (a @ a.T + shape[0] * onp.eye(shape[0])).astype("float32")


def _tri(shape=(4, 4)):
    return (onp.tril(RNG.rand(*shape)) + 2 * onp.eye(shape[0])).astype(
        "float32")


# ---------------------------------------------------------------------------
# specs: name -> dict(inputs=[np arrays], kwargs={}, tol=, eps=)
# only for ops the auto-generator can't feed (required kwargs, structured
# inputs, integer operands)
# ---------------------------------------------------------------------------
SPECS = {
    "Activation": dict(inputs=[_pos()], kwargs={"act_type": "tanh"}),
    "BatchNorm": dict(
        inputs=[_pos((2, 3, 4, 4)), _pos((3,)), _pos((3,)),
                onp.zeros(3, "float32"), onp.ones(3, "float32")],
        kwargs={}, n_diff=3),
    "Cast": dict(inputs=[_pos()], kwargs={"dtype": "float32"}),
    "Concat": dict(inputs=[_pos(), _pos()], kwargs={"dim": 1}),
    "Convolution": dict(
        inputs=[_pos((1, 2, 5, 5)), _pos((3, 2, 3, 3)), _pos((3,))],
        kwargs={"kernel": (3, 3), "num_filter": 3}),
    "Deconvolution": dict(
        inputs=[_pos((1, 3, 4, 4)), _pos((3, 2, 3, 3)), _pos((2,))],
        kwargs={"kernel": (3, 3), "num_filter": 2}),
    "Correlation": dict(
        inputs=[_pos((1, 2, 6, 6)), _pos((1, 2, 6, 6))],
        kwargs={"kernel_size": 1, "max_displacement": 2, "stride1": 1,
                "stride2": 1}),
    "Crop": dict(inputs=[_pos((1, 2, 6, 6))],
                 kwargs={"h_w": (4, 4), "center_crop": True},
                 skip_fd_kwargs=True),
    "Embedding": dict(
        inputs=[onp.array([[0., 2.], [1., 3.]], "float32"),
                _pos((4, 5))],
        kwargs={"input_dim": 4, "output_dim": 5}, n_diff=(1,)),
    "FullyConnected": dict(
        inputs=[_pos((2, 5)), _pos((3, 5)), _pos((3,))],
        kwargs={"num_hidden": 3}),
    "GridGenerator": dict(
        inputs=[_pos((1, 6))], kwargs={"transform_type": "affine",
                                       "target_shape": (4, 4)}),
    "BilinearSampler": dict(
        inputs=[_pos((1, 2, 5, 5)),
                RNG.uniform(-0.7, 0.7, (1, 2, 4, 4)).astype("float32")],
        kwargs={}),
    "SpatialTransformer": dict(
        inputs=[_pos((1, 2, 5, 5)), _pos((1, 6), lo=-0.2, hi=0.2)],
        kwargs={"transform_type": "affine", "sampler_type": "bilinear",
                "target_shape": (4, 4)}),
    "GroupNorm": dict(inputs=[_pos((2, 4, 3, 3)), _pos((4,)), _pos((4,))],
                      kwargs={"num_groups": 2}),
    "InstanceNorm": dict(inputs=[_pos((2, 3, 4)), _pos((3,)), _pos((3,))],
                         kwargs={}),
    "LayerNorm": dict(inputs=[_pos((3, 6)), _pos((6,)), _pos((6,))],
                      kwargs={}),
    "RMSNorm": dict(inputs=[_pos((3, 6)), _pos((6,))], kwargs={}),
    "L2Normalization": dict(inputs=[_pos()], kwargs={}),
    "LRN": dict(inputs=[_pos((1, 4, 3, 3))], kwargs={"nsize": 3}),
    "LeakyReLU": dict(inputs=[_pos()], kwargs={"act_type": "leaky"}),

    "Pad": dict(inputs=[_pos((1, 2, 3, 3))],
                kwargs={"mode": "constant",
                        "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    "Pooling": dict(inputs=[_pos((1, 2, 4, 4))],
                    kwargs={"kernel": (2, 2), "pool_type": "avg",
                            "stride": (2, 2)}),
    "ROIPooling": dict(
        inputs=[_pos((1, 2, 8, 8)),
                onp.array([[0, 0, 0, 6, 6]], "float32")],
        kwargs={"pooled_size": (2, 2), "spatial_scale": 1.0}, n_diff=(0,)),
    "_contrib_ROIAlign": dict(
        inputs=[_pos((1, 2, 8, 8)),
                onp.array([[0, 0.5, 0.5, 6.0, 6.0]], "float32")],
        kwargs={"pooled_size": (2, 2), "spatial_scale": 1.0}, n_diff=(0,)),
    "_contrib_PSROIPooling": dict(
        inputs=[_pos((1, 8, 6, 6)),
                onp.array([[0, 0, 0, 5, 5]], "float32")],
        kwargs={"output_dim": 2, "pooled_size": 2, "spatial_scale": 1.0},
        n_diff=(0,)),
    "_contrib_DeformableConvolution": dict(
        inputs=[_pos((1, 2, 5, 5)), _pos((1, 18, 5, 5), lo=0.25, hi=0.4),
                _pos((3, 2, 3, 3)), _pos((3,))],
        kwargs={"kernel": (3, 3), "num_filter": 3, "pad": (1, 1)},
        tol=0.08),
    "_contrib_AdaptiveAvgPooling2D": dict(
        inputs=[_pos((1, 2, 6, 6))], kwargs={"output_size": 3}),
    "_contrib_BilinearResize2D": dict(
        inputs=[_pos((1, 2, 4, 4))], kwargs={"height": 6, "width": 6}),
    "SequenceLast": dict(inputs=[_pos((4, 2, 3))], kwargs={}),
    "SequenceMask": dict(inputs=[_pos((4, 2, 3))], kwargs={}),
    "SequenceReverse": dict(inputs=[_pos((4, 2, 3))], kwargs={}),
    "SliceChannel": dict(inputs=[_pos((2, 4))],
                         kwargs={"num_outputs": 2, "axis": 1}),
    "_split_v2": dict(inputs=[_pos((2, 4))],
                      kwargs={"indices_or_sections": 2, "axis": 1}),

    "SoftmaxActivation": dict(inputs=[_pos()], kwargs={}),
    "softmax_cross_entropy": dict(
        inputs=[_pos((3, 5)), onp.array([0., 2., 4.], "float32")],
        kwargs={}, n_diff=(0,)),
    "SwapAxis": dict(inputs=[_pos()], kwargs={"dim1": 0, "dim2": 1}),
    "UpSampling": dict(inputs=[_pos((1, 2, 3, 3))],
                       kwargs={"scale": 2, "sample_type": "nearest"}),
    "Reshape": dict(inputs=[_pos()], kwargs={"shape": (4, 3)}),
    "broadcast_axes": dict(inputs=[_pos((1, 4))],
                           kwargs={"axis": 0, "size": 3}),
    "broadcast_to": dict(inputs=[_pos((1, 4))], kwargs={"shape": (3, 4)}),
    "broadcast_like": dict(inputs=[_pos((1, 4)), _pos((3, 4))],
                           kwargs={}, n_diff=(0,)),
    "batch_take": dict(inputs=[_pos((3, 4)),
                               onp.array([0., 2., 1.], "float32")],
                       kwargs={}, n_diff=(0,)),
    "take": dict(inputs=[_pos((4, 3)), onp.array([0., 2.], "float32")],
                 kwargs={}, n_diff=(0,)),
    "take_along_axis": dict(
        inputs=[_pos((3, 4)), onp.zeros((3, 1), "float32")],
        kwargs={"axis": 1}, n_diff=(0,)),
    "pick": dict(inputs=[_pos((3, 4)), onp.array([0., 1., 3.], "float32")],
                 kwargs={}, n_diff=(0,)),
    "gather_nd": dict(
        inputs=[_pos((4, 3)), onp.array([[0, 2], [1, 0]], "float32").T],
        kwargs={}, n_diff=(0,)),
    "scatter_nd": dict(
        inputs=[_pos((2,)), onp.array([[0, 2]], "float32")],
        kwargs={"shape": (4,)}, n_diff=(0,)),
    "one_hot": dict(inputs=[onp.array([0., 2.], "float32")],
                    kwargs={"depth": 4}, n_diff=()),
    "where": dict(
        inputs=[onp.array([[1., 0., 1., 0.]] * 3, "float32"),
                _pos(), _pos()],
        kwargs={}, n_diff=(1, 2)),
    "slice": dict(inputs=[_pos()], kwargs={"begin": (0, 1), "end": (2, 3)}),
    "slice_axis": dict(inputs=[_pos()],
                       kwargs={"axis": 1, "begin": 0, "end": 2}),
    "slice_like": dict(inputs=[_pos((3, 4)), _pos((2, 2))], kwargs={},
                       n_diff=(0,)),
    "diag": dict(inputs=[_pos((4, 4))], kwargs={}),
    "repeat": dict(inputs=[_pos()], kwargs={"repeats": 2}),
    "tile": dict(inputs=[_pos()], kwargs={"reps": (2, 1)}),
    "flip": dict(inputs=[_pos()], kwargs={"axis": 1}),
    "expand_dims": dict(inputs=[_pos()], kwargs={"axis": 0}),
    "stack": dict(inputs=[_pos(), _pos()], kwargs={"axis": 0}),
    "clip": dict(inputs=[_pos()], kwargs={"a_min": 0.0, "a_max": 10.0}),
    "moveaxis": dict(inputs=[_pos()], kwargs={"source": 0,
                                              "destination": 1}),
    "depth_to_space": dict(inputs=[_pos((1, 4, 2, 2))],
                           kwargs={"block_size": 2}),
    "space_to_depth": dict(inputs=[_pos((1, 1, 4, 4))],
                           kwargs={"block_size": 2}),
    "reshape_like": dict(inputs=[_pos((3, 4)), _pos((4, 3))], kwargs={},
                         n_diff=(0,)),
    "smooth_l1": dict(inputs=[_pos()], kwargs={"scalar": 1.0}),
    "sort": dict(inputs=[_pos()], kwargs={}),
    "max": dict(inputs=[_pos()], kwargs={"axis": 1}),
    "min": dict(inputs=[_pos()], kwargs={"axis": 1}),
    "norm": dict(inputs=[_pos()], kwargs={"ord": 2}),
    "logsumexp": dict(inputs=[_pos()], kwargs={}),
    "moments": dict(inputs=[_pos()], kwargs={"axes": (0,)}),
    "khatri_rao": dict(inputs=[_pos((2, 3)), _pos((4, 3))], kwargs={}),
    "dot_scaled": dict(inputs=[_pos((3, 4)), _pos((4, 2))],
                       kwargs={"scale": 0.5}),
    "_contrib_div_sqrt_dim": dict(inputs=[_pos()], kwargs={}),
    "_contrib_interleaved_matmul_selfatt_qk": dict(
        inputs=[_pos((4, 2, 3 * 8))], kwargs={"heads": 2}),
    "_contrib_interleaved_matmul_selfatt_valatt": dict(
        inputs=[_pos((4, 2, 3 * 8)), _pos((4, 4, 4))],
        kwargs={"heads": 2}),
    "linalg_potrf": dict(inputs=[_sym_pd()], kwargs={}),
    "linalg_potri": dict(inputs=[_tri()], kwargs={}),
    "linalg_trmm": dict(inputs=[_tri(), _pos((4, 4))], kwargs={}),
    "linalg_trsm": dict(inputs=[_tri(), _pos((4, 4))], kwargs={},
                        tol=0.08),
    "linalg_gemm": dict(
        inputs=[_pos((3, 4)), _pos((4, 2)), _pos((3, 2))], kwargs={}),
    "linalg_gemm2": dict(inputs=[_pos((3, 4)), _pos((4, 2))], kwargs={}),
    "linalg_det": dict(inputs=[_sym_pd()], kwargs={}),
    "linalg_slogdet": dict(inputs=[_sym_pd()], kwargs={}),
    "linalg_sumlogdiag": dict(inputs=[_sym_pd()], kwargs={}),
    "linalg_extractdiag": dict(inputs=[_pos((4, 4))], kwargs={}),
    "linalg_extracttrian": dict(inputs=[_pos((4, 4))], kwargs={}),
    "linalg_makediag": dict(inputs=[_pos((4,))], kwargs={}),
    "linalg_maketrian": dict(inputs=[_pos((10,))], kwargs={}),
    "linalg_inverse": dict(inputs=[_sym_pd()], kwargs={}),
    "linalg_syrk": dict(inputs=[_pos((3, 4))], kwargs={}),
    "linalg_gelqf": dict(inputs=[_pos((2, 4))], kwargs={}, tol=0.1),
    "_sparse_dot_csr_dense": None,   # handled by test_sparse.py (stype)
    "MakeLoss": dict(inputs=[_pos()], kwargs={}),
    "make_loss": dict(inputs=[_pos()], kwargs={}),
    "Flatten": dict(inputs=[_pos((2, 3, 2))], kwargs={}),
    "ElementWiseSum": dict(inputs=[_pos(), _pos(), _pos()], kwargs={}),
    "dot": dict(inputs=[_pos((3, 4)), _pos((4, 2))], kwargs={}),
    "batch_dot": dict(inputs=[_pos((2, 3, 4)), _pos((2, 4, 2))],
                      kwargs={}),
    "cumsum": dict(inputs=[_pos()], kwargs={"axis": 1}),
    "_power": dict(inputs=[_pos(), _pos()], kwargs={}),
    "arccosh": dict(inputs=[_pos(lo=1.5, hi=3.0)], kwargs={}),
    "arctanh": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "arccos": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "arcsin": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "erfinv": dict(inputs=[_pos(lo=0.1, hi=0.7)], kwargs={}),
    "gamma": dict(inputs=[_pos(lo=1.5, hi=3.0)], kwargs={}),
    "gammaln": dict(inputs=[_pos(lo=1.5, hi=3.0)], kwargs={}),
    "rcbrt": dict(inputs=[_pos(lo=0.5, hi=2.0)], kwargs={}),
    "BlockGrad": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "BlockGrad_inner": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "zeros_like": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "ones_like": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "logical_not": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "sign": dict(inputs=[_pos()], kwargs={}, n_diff=()),
}

# ops that cannot be swept here, with the reason (reference: the skip
# decorators scattered through test_operator.py)
SKIP = {
    "_sparse_dot_csr_dense": "needs CSR-stype inputs; covered by "
                             "tests/test_sparse.py end-to-end",
    "SVMOutput": "identity forward, hinge-loss TRAINING backward "
                 "(reference: svm_output.cc) — numeric FD of the forward "
                 "is identity by design; covered by tests/test_models.py",
    "Softmax": "SoftmaxOutput semantics: backward is the TRAINING "
               "gradient (p - onehot), deliberately not the forward vjp "
               "(reference: softmax_output.cc); covered by "
               "tests/test_symbol_module.py",
    "LinearRegressionOutput": "training-output op: backward is the "
               "hand-coded loss gradient (out - label), not the forward "
               "vjp (reference: regression_output.cc); semantics pinned "
               "by tests/test_svrg.py + test_operator_grads.py",
    "LogisticRegressionOutput": "training-output op (sigmoid fwd, "
               "out - label bwd); see LinearRegressionOutput",
    "MAERegressionOutput": "training-output op (identity fwd, "
               "sign(out - label) bwd); see LinearRegressionOutput",
    "IdentityAttachKLSparseReg": "identity fwd; backward ADDS the KL "
               "sparsity-penalty gradient (not the forward vjp); "
               "closed-form checked in test_operator_grads.py",
    "_np_linalg_qr": "jax QR derivative unimplemented for wide "
                     "matrices; square case covered in "
                     "tests/test_numpy_ns.py::test_np_linalg_multioutput",
}

# np-namespace ops that need structured inputs or are only piecewise
# differentiable at auto-generated points
_WELL_SEP = onp.arange(1.0, 13.0, dtype="float32").reshape(3, 4)

NP_SPECS = {
    "_np_arccos": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "_np_arccosh": dict(inputs=[_pos(lo=1.5, hi=3.0)], kwargs={}),
    "_np_arcsin": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "_np_arctanh": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "_np_acos": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "_np_acosh": dict(inputs=[_pos(lo=1.5, hi=3.0)], kwargs={}),
    "_np_asin": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "_np_atanh": dict(inputs=[_pos(lo=-0.8, hi=-0.2)], kwargs={}),
    "_np_broadcast_to": dict(inputs=[_pos((1, 4))],
                             kwargs={"shape": (3, 4)}),
    "_np_clip": dict(inputs=[_pos(), 0.0, 10.0], kwargs={},
                     n_diff=(0,)),
    "_np_compress": dict(
        inputs=[onp.array([True, False, True]), _pos((3, 4))],
        kwargs={"axis": 0}, n_diff=(1,)),
    "_np_choose": dict(
        inputs=[onp.array([0, 1], "int32"), _pos((2, 2))], kwargs={},
        n_diff=(1,)),
    "_np_cross": dict(inputs=[_pos((3,)), _pos((3,))], kwargs={}),
    "_np_diag": dict(inputs=[_pos((4,))], kwargs={}),
    "_np_diagflat": dict(inputs=[_pos((4,))], kwargs={}),
    "_np_diagonal": dict(inputs=[_pos((4, 4))], kwargs={}),
    "_np_dot": dict(inputs=[_pos((3, 4)), _pos((4, 2))], kwargs={}),
    "_np_matmul": dict(inputs=[_pos((3, 4)), _pos((4, 2))], kwargs={}),
    "_np_vdot": dict(inputs=[_pos((4,)), _pos((4,))], kwargs={}),
    "_np_vecdot": dict(inputs=[_pos((4,)), _pos((4,))], kwargs={}),
    "_np_inner": dict(inputs=[_pos((4,)), _pos((4,))], kwargs={}),
    "_np_outer": dict(inputs=[_pos((3,)), _pos((4,))], kwargs={}),
    "_np_kron": dict(inputs=[_pos((2, 2)), _pos((2, 2))], kwargs={}),
    "_np_trace": dict(inputs=[_pos((4, 4))], kwargs={}),
    "_np_tensordot": dict(inputs=[_pos((3, 4)), _pos((4, 2))],
                          kwargs={"axes": 1}),
    "_np_einsum": dict(inputs=["ij,jk->ik", _pos((3, 4)), _pos((4, 2))],
                       kwargs={}, n_diff=(1, 2)),
    "_np_expand_dims": dict(inputs=[_pos()], kwargs={"axis": 0}),
    "_np_flip": dict(inputs=[_pos()], kwargs={"axis": 1}),
    "_np_take": dict(inputs=[_pos((4, 3)), onp.array([0, 2], "int32")],
                     kwargs={"axis": 0}, n_diff=(0,)),
    "_np_take_along_axis": dict(
        inputs=[_pos((3, 4)), onp.zeros((3, 1), "int64")],
        kwargs={"axis": 1}, n_diff=(0,)),
    "_np_where": dict(
        inputs=[onp.array([[True, False, True, False]] * 3), _pos(),
                _pos()], kwargs={}, n_diff=(1, 2)),
    "_np_interp": dict(
        inputs=[onp.array([1.3, 2.1], "float32"),
                onp.array([1., 2., 3.], "float32"),
                onp.array([2., 4., 8.], "float32")],
        kwargs={}, n_diff=(2,)),
    "_np_pad": dict(inputs=[_pos()], kwargs={"pad_width": 1}),
    "_np_repeat": dict(inputs=[_pos()], kwargs={"repeats": 2}),
    "_np_reshape": dict(inputs=[_pos(), (4, 3)], kwargs={},
                        n_diff=(0,)),
    "_np_resize": dict(inputs=[_pos(), (4, 3)], kwargs={}, n_diff=(0,)),
    "_np_roll": dict(inputs=[_pos()], kwargs={"shift": 1}),
    "_np_rot90": dict(inputs=[_pos()], kwargs={}),
    "_np_squeeze": dict(inputs=[_pos((1, 3, 4))], kwargs={}),
    "_np_swapaxes": dict(inputs=[_pos(), 0, 1], kwargs={}, n_diff=(0,)),
    "_np_moveaxis": dict(inputs=[_pos(), 0, 1], kwargs={}, n_diff=(0,)),
    "_np_rollaxis": dict(inputs=[_pos(), 1], kwargs={}, n_diff=(0,)),
    "_np_permute_dims": dict(inputs=[_pos(), (1, 0)], kwargs={},
                             n_diff=(0,)),
    "_np_matrix_transpose": dict(inputs=[_pos()], kwargs={}),
    "_np_split": dict(inputs=[_pos((4, 4)), 2], kwargs={}, n_diff=(0,)),
    "_np_array_split": dict(inputs=[_pos((4, 4)), 2], kwargs={},
                            n_diff=(0,)),
    "_np_vsplit": dict(inputs=[_pos((4, 4)), 2], kwargs={}, n_diff=(0,)),
    "_np_hsplit": dict(inputs=[_pos((4, 4)), 2], kwargs={}, n_diff=(0,)),
    "_np_dsplit": dict(inputs=[_pos((2, 2, 4)), 2], kwargs={},
                       n_diff=(0,)),
    "_np_tile": dict(inputs=[_pos(), (2, 1)], kwargs={}, n_diff=(0,)),
    "_np_tril": dict(inputs=[_pos((4, 4))], kwargs={}),
    "_np_triu": dict(inputs=[_pos((4, 4))], kwargs={}),
    "_np_vander": dict(inputs=[_pos((4,))], kwargs={}),
    "_np_trim_zeros": dict(inputs=[_pos((4,))], kwargs={}),
    "_np_unwrap": dict(inputs=[_pos((4,))], kwargs={}),
    "_np_delete": dict(inputs=[_pos((4,)), 1], kwargs={}, n_diff=(0,)),
    "_np_insert": dict(inputs=[_pos((4,)), 1, 5.0], kwargs={},
                       n_diff=(0,)),
    "_np_append": dict(inputs=[_pos((4,)), _pos((4,))], kwargs={}),
    "_np_atleast_1d": dict(inputs=[_pos()], kwargs={}),
    "_np_atleast_2d": dict(inputs=[_pos()], kwargs={}),
    "_np_atleast_3d": dict(inputs=[_pos()], kwargs={}),
    "_np_astype": dict(inputs=[_pos(), "float32"], kwargs={},
                       n_diff=(0,)),
    "_np_average": dict(inputs=[_pos()], kwargs={}),
    "_np_convolve": dict(inputs=[_pos((4,)), _pos((3,))], kwargs={}),
    "_np_correlate": dict(inputs=[_pos((4,)), _pos((3,))], kwargs={}),
    "_np_gradient": dict(inputs=[_pos((5,))], kwargs={}),
    "_np_heaviside": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_polyval": dict(inputs=[_pos((3,)), _pos((4,))], kwargs={}),
    "_np_polyadd": dict(inputs=[_pos((3,)), _pos((3,))], kwargs={}),
    "_np_polysub": dict(inputs=[_pos((3,)), _pos((3,))], kwargs={}),
    "_np_polymul": dict(inputs=[_pos((3,)), _pos((3,))], kwargs={}),
    "_np_polyder": dict(inputs=[_pos((4,))], kwargs={}),
    "_np_polyint": dict(inputs=[_pos((4,))], kwargs={}),
    "_np_polydiv": dict(inputs=[_pos((4,)), _pos((2,), lo=1.0, hi=2.0)],
                        kwargs={}),
    "_np_polyfit": dict(
        inputs=[onp.arange(5.0, dtype="float32"), _pos((5,)), 2],
        kwargs={}, n_diff=(1,)),
    "_np_ptp": dict(inputs=[_pos()], kwargs={}),
    "_np_quantile": dict(inputs=[_WELL_SEP, 0.4], kwargs={},
                         n_diff=(0,), eps=2e-4),
    "_np_percentile": dict(inputs=[_WELL_SEP, 40.0], kwargs={},
                           n_diff=(0,), eps=2e-4),
    "_np_nanquantile": dict(inputs=[_WELL_SEP, 0.4], kwargs={},
                            n_diff=(0,), eps=2e-4),
    "_np_nanpercentile": dict(inputs=[_WELL_SEP, 40.0], kwargs={},
                              n_diff=(0,), eps=2e-4),
    "_np_median": dict(inputs=[_WELL_SEP], kwargs={}, eps=2e-4),
    "_np_nanmedian": dict(inputs=[_WELL_SEP], kwargs={}, eps=2e-4),
    "_np_partition": dict(inputs=[_pos((6,)), 3], kwargs={}, n_diff=(0,)),
    "_np_sort": dict(inputs=[_pos((6,))], kwargs={}),
    "_np_sinc": dict(inputs=[_pos(lo=0.2, hi=0.8)], kwargs={}),
    "_np_copysign": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_ldexp": dict(
        inputs=[_pos(), onp.ones(SHAPE, "int32")], kwargs={},
        n_diff=(0,)),
    "_np_float_power": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_power": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_pow": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_fmod": dict(inputs=[_pos(lo=2.0, hi=3.0), _pos(lo=0.6, hi=0.9)],
                     kwargs={}),
    "_np_mod": dict(inputs=[_pos(lo=2.0, hi=3.0), _pos(lo=0.6, hi=0.9)],
                    kwargs={}),
    "_np_remainder": dict(inputs=[_pos(lo=2.0, hi=3.0),
                                  _pos(lo=0.6, hi=0.9)], kwargs={}),
    "_np_divmod": dict(inputs=[_pos(lo=2.0, hi=3.0),
                               _pos(lo=0.6, hi=0.9)], kwargs={}),
    "_np_modf": dict(inputs=[_pos(lo=0.1, hi=0.9)], kwargs={}),
    "_np_select": dict(
        inputs=[[onp.array([True, False]), onp.array([False, True])],
                [_pos((2,)), _pos((2,))]], kwargs={}, n_diff=()),
    "_np_piecewise": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_apply_along_axis": None,
    "_np_apply_over_axes": None,
    "_np_meshgrid": dict(inputs=[_pos((3,)), _pos((4,))], kwargs={}),
    "_np_broadcast_arrays": dict(inputs=[_pos((1, 4)), _pos((3, 1))],
                                 kwargs={}),
    "_np_ix_": dict(inputs=[_pos((3,))], kwargs={}, n_diff=()),
    "_np_trapezoid": dict(inputs=[_pos((5,))], kwargs={}),
    "_np_corrcoef": dict(inputs=[_pos((3, 5))], kwargs={}),
    "_np_cov": dict(inputs=[_pos((3, 5))], kwargs={}),
    "_np_i0": dict(inputs=[_pos()], kwargs={}),
    "_np_angle": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_real": dict(inputs=[_pos()], kwargs={}),
    "_np_imag": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_conj": dict(inputs=[_pos()], kwargs={}),
    "_np_conjugate": dict(inputs=[_pos()], kwargs={}),
    "_np_nan_to_num": dict(inputs=[_pos()], kwargs={}),
    "_np_concatenate": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_concat": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_stack": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_vstack": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_hstack": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_dstack": dict(inputs=[_pos(), _pos()], kwargs={}),
    "_np_column_stack": dict(inputs=[_pos((3,)), _pos((3,))], kwargs={}),
    "_np_cumsum": dict(inputs=[_pos()], kwargs={}),
    "_np_cumprod": dict(inputs=[_pos()], kwargs={}),
    "_np_nancumsum": dict(inputs=[_pos()], kwargs={}),
    "_np_nancumprod": dict(inputs=[_pos()], kwargs={}),
    "_np_diff": dict(inputs=[_pos()], kwargs={}),
    "_np_ediff1d": dict(inputs=[_pos()], kwargs={}),
    "_np_fix": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_rint": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_round": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_around": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_floor": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_ceil": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_trunc": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_sign": dict(inputs=[_pos()], kwargs={}, n_diff=()),
    "_np_copy": dict(inputs=[_pos()], kwargs={}),
    "_np_ravel": dict(inputs=[_pos()], kwargs={}),
    "_np_transpose": dict(inputs=[_pos()], kwargs={}),
    "_np_max": dict(inputs=[_pos()], kwargs={}),
    "_np_min": dict(inputs=[_pos()], kwargs={}),
    "_np_amax": dict(inputs=[_pos()], kwargs={}),
    "_np_amin": dict(inputs=[_pos()], kwargs={}),
    "_np_fmax": dict(inputs=[_pos(), _pos(lo=2.0, hi=3.0)], kwargs={}),
    "_np_fmin": dict(inputs=[_pos(), _pos(lo=2.0, hi=3.0)], kwargs={}),
    "_np_maximum": dict(inputs=[_pos(), _pos(lo=2.0, hi=3.0)], kwargs={}),
    "_np_minimum": dict(inputs=[_pos(), _pos(lo=2.0, hi=3.0)], kwargs={}),
    "_np_nanmax": dict(inputs=[_pos()], kwargs={}),
    "_np_nanmin": dict(inputs=[_pos()], kwargs={}),
    "_np_frexp": dict(inputs=[_pos()], kwargs={}, n_diff=()),
}
NP_SPECS["_np_linalg_pinv"] = dict(inputs=[_sym_pd()], kwargs={},
                                   tol=0.1)
SPECS["broadcast_mod"] = dict(
    inputs=[_pos(), _pos(lo=3.0, hi=4.0)], kwargs={})
SPECS.update(NP_SPECS)

NP_SKIP = {
    "_np_apply_along_axis": "callable first argument, not a tensor op",
    "_np_apply_over_axes": "callable first argument, not a tensor op",
}
SKIP.update(NP_SKIP)


def _unique_diff_ops():
    uniq = {}
    for n in sorted(registry.list_ops()):
        op = registry.get(n)
        if op.differentiable and not op.creation and not op.random:
            uniq.setdefault(id(op), n)
    return sorted(uniq.values())


ALL_OPS = _unique_diff_ops()


def _auto_inputs(name):
    """Default inputs: 1-3 positive (3,4) float arrays, first that runs."""
    for k in (1, 2, 3):
        ins = [_pos() for _ in range(k)]
        try:
            out = invoke(name, *[nd.array(a) for a in ins])
            first = out[0] if isinstance(out, (list, tuple)) else out
            arr = first.asnumpy()
            if not onp.issubdtype(arr.dtype, onp.floating):
                return None, None
            if not onp.isfinite(arr).all():
                continue
            return ins, {}
        except Exception:
            continue
    return None, None


_RESULTS = {"checked": set(), "skipped": set(), "no_auto": set()}


def _run_check(name, inputs, kwargs, n_diff=None, tol=0.06, eps=3e-3):
    xs = []
    for a in inputs:
        if isinstance(a, onp.ndarray):
            xs.append(nd.array(a, dtype=str(a.dtype)))
        else:
            xs.append(a)                      # non-tensor positional arg
    tensor_idx = [i for i, a in enumerate(inputs)
                  if isinstance(a, onp.ndarray)
                  and onp.issubdtype(onp.asarray(a).dtype, onp.floating)]
    if n_diff is None:
        n_diff = tuple(tensor_idx)
    elif isinstance(n_diff, int):
        n_diff = tuple(range(n_diff))

    def fwd(arrs):
        out = invoke(name, *arrs, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        total = 0.0
        for k, o in enumerate(outs):
            w = _W(k, tuple(o.shape))
            total = total + float((o * nd.array(w)).sum().asnumpy())
        return total

    _w_cache = {}

    def _W(k, shape):
        key = (k, shape)
        if key not in _w_cache:
            _w_cache[key] = onp.random.RandomState(100 + k).uniform(
                0.5, 1.5, size=shape).astype("float32")
        return _w_cache[key]

    for x in xs:
        if isinstance(x, nd.NDArray):
            x.attach_grad()
    with autograd.record():
        out = invoke(name, *xs, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        loss = None
        for k, o in enumerate(outs):
            term = (o * nd.array(_W(k, tuple(o.shape)))).sum()
            loss = term if loss is None else loss + term
    loss.backward()

    for i in n_diff:
        x = xs[i]
        g = x.grad
        assert g is not None, "%s: no grad for input %d" % (name, i)
        host = inputs[i].astype("float64")
        v = onp.random.RandomState(50 + i).randn(*host.shape)
        if v.size:                      # 0-size: direction is empty, the
            v /= max(1e-12, onp.abs(v).max())   # FD still pins 0 == 0
        plus = [a for a in inputs]
        minus = [a for a in inputs]
        plus[i] = (host + eps * v).astype("float32")
        minus[i] = (host - eps * v).astype("float32")

        def realize(lst):
            return [nd.array(a, dtype=str(a.dtype))
                    if isinstance(a, onp.ndarray) else a for a in lst]

        num = (fwd(realize(plus)) - fwd(realize(minus))) / (2 * eps)
        ana = float((g.asnumpy().astype("float64") *
                     v.astype(g.dtype and "float64")).sum())
        denom = max(abs(num), abs(ana), 1.0)
        assert abs(num - ana) / denom < tol, (
            "%s input %d: analytic %.6f vs numeric %.6f" % (name, i, ana,
                                                            num))


@pytest.mark.parametrize("name", ALL_OPS)
def test_registry_gradient(name):
    if name in SKIP:
        _RESULTS["skipped"].add(name)
        pytest.skip(SKIP[name])
    spec = SPECS.get(name)
    if spec is None and name in SPECS:
        _RESULTS["skipped"].add(name)
        pytest.skip("spec marked as covered elsewhere")
    if spec is None:
        inputs, kwargs = _auto_inputs(name)
        if inputs is None:
            _RESULTS["no_auto"].add(name)
            pytest.skip("no auto-generated inputs run this op")
        spec = dict(inputs=inputs, kwargs=kwargs)
    _run_check(name, spec["inputs"], spec.get("kwargs", {}),
               n_diff=spec.get("n_diff"), tol=spec.get("tol", 0.06),
               eps=spec.get("eps", 3e-3))
    _RESULTS["checked"].add(name)


def test_zz_sweep_coverage():
    """Accounting: >=80% of unique differentiable ops actually checked.
    (zz prefix: runs after the parametrized sweep.)"""
    total = len(ALL_OPS)
    checked = len(_RESULTS["checked"])
    if not checked and not _RESULTS["skipped"]:
        pytest.skip("sweep tests did not run in this session "
                    "(selected standalone)")
    unreached = _RESULTS["no_auto"]
    assert checked / total >= 0.8, (
        "gradient sweep coverage %d/%d = %.0f%%; unreachable ops: %s"
        % (checked, total, 100.0 * checked / total, sorted(unreached)))
