"""Op-parity audit (round-2 verdict Missing #4): the reference's
user-facing operator catalog resolves against this build's registry.

The catalog below is the curated user-facing surface of the reference's
src/operator/ registry (tests/python/unittest/test_operator.py exercises
exactly these names). The reference mount is empty (SURVEY.md §0), so the
list is reconstructed from the stable 1.x API; every name here must exist
either in the op registry or as an `mx.nd` callable.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops import registry as _reg

CATALOG = """
Activation BatchNorm BatchNorm_v1 BilinearSampler BlockGrad Cast Concat
Convolution Correlation Crop Custom Deconvolution Dropout Embedding Flatten
FullyConnected GridGenerator GroupNorm IdentityAttachKLSparseReg
InstanceNorm L2Normalization LRN LayerNorm LeakyReLU LinearRegressionOutput
LogisticRegressionOutput MAERegressionOutput MakeLoss Pad Pooling RNN
ROIPooling Reshape SVMOutput SequenceLast SequenceMask SequenceReverse
SliceChannel Softmax SoftmaxActivation SoftmaxOutput SpatialTransformer
SwapAxis UpSampling abs adam_update add_n arccos arccosh arcsin arcsinh
arctan arctanh argmax argmax_channel argmin argsort batch_dot batch_take
broadcast_add broadcast_axes broadcast_axis broadcast_div broadcast_equal
broadcast_greater broadcast_greater_equal broadcast_hypot broadcast_lesser
broadcast_lesser_equal broadcast_like broadcast_logical_and
broadcast_logical_or broadcast_logical_xor broadcast_maximum
broadcast_minimum broadcast_mod broadcast_mul broadcast_not_equal
broadcast_power broadcast_sub broadcast_to cast cast_storage cbrt ceil clip
concat cos cosh cumsum degrees depth_to_space diag dot elemwise_add
elemwise_div elemwise_mul elemwise_sub erf erfinv exp expand_dims expm1
fill_element_0index fix flatten flip floor ftrl_update gamma gammaln
gather_nd hard_sigmoid identity khatri_rao lamb_update_phase1
lamb_update_phase2 linalg_det linalg_extractdiag linalg_extracttrian
linalg_gelqf linalg_gemm linalg_gemm2 linalg_inverse linalg_makediag
linalg_maketrian linalg_potrf linalg_potri linalg_slogdet
linalg_sumlogdiag linalg_syrk linalg_trmm linalg_trsm log log10 log1p log2
log_softmax logical_not make_loss max mean min moments mp_lamb_update_phase1
mp_lamb_update_phase2 mp_nag_mom_update mp_sgd_mom_update mp_sgd_update
multi_all_finite multi_lars multi_mp_sgd_mom_update multi_mp_sgd_update
multi_sgd_mom_update multi_sgd_update nag_mom_update nanprod nansum negative
norm normal one_hot ones_like pad pick preloaded_multi_mp_sgd_mom_update
prod radians rcbrt reciprocal relu repeat reshape reshape_like reverse rint
rmsprop_update rmspropalex_update round rsqrt scatter_nd sgd_mom_update
sgd_update shape_array shuffle sigmoid sign signsgd_update signum_update sin
sinh size_array slice slice_axis slice_like smooth_l1 softmax
softmax_cross_entropy softmin softsign sort space_to_depth split sqrt square
squeeze stack stop_gradient sum swapaxes take tan tanh tile topk transpose
trunc uniform unravel_index where zeros_like
""".split()

CONTRIB = """
quantize_v2 dequantize requantize quantized_fully_connected quantized_conv
interleaved_matmul_selfatt_qk interleaved_matmul_selfatt_valatt
div_sqrt_dim adamw_update
box_nms box_iou box_encode box_decode ROIAlign BilinearResize2D
AdaptiveAvgPooling2D arange_like
MultiBoxPrior MultiBoxTarget MultiBoxDetection
DeformableConvolution PSROIPooling
""".split()


def test_user_facing_op_catalog_resolves():
    ops = set(_reg.list_ops())
    missing = [n for n in CATALOG
               if n not in ops and not hasattr(nd, n)]
    assert not missing, "reference ops absent: %s" % missing


def test_contrib_op_catalog_resolves():
    ops = set(_reg.list_ops())
    missing = [n for n in CONTRIB if "_contrib_" + n not in ops]
    assert not missing, "contrib ops absent: %s" % missing
    for n in CONTRIB:
        assert hasattr(nd.contrib, n)


# -- functional spot-checks of the newly closed gaps -----------------------

def test_linalg_ops_numeric():
    rng = np.random.RandomState(0)
    a_np = rng.randn(3, 3).astype(np.float32)
    spd = a_np @ a_np.T + 3 * np.eye(3, dtype=np.float32)
    a = nd.array(spd)
    np.testing.assert_allclose(nd.invoke("linalg_det", a).asnumpy(),
                               np.linalg.det(spd), rtol=1e-4)
    np.testing.assert_allclose(nd.invoke("linalg_inverse", a).asnumpy(),
                               np.linalg.inv(spd), rtol=1e-3, atol=1e-4)
    sign, logdet = nd.invoke("linalg_slogdet", a)
    np.testing.assert_allclose(logdet.asnumpy(),
                               np.linalg.slogdet(spd)[1], rtol=1e-4)
    # potrf -> potri == inverse
    l = nd.invoke("linalg_potrf", a)
    inv = nd.invoke("linalg_potri", l)
    np.testing.assert_allclose(inv.asnumpy(), np.linalg.inv(spd),
                               rtol=1e-3, atol=1e-4)
    # gelqf: A = L Q with orthonormal Q rows
    m = nd.array(rng.randn(2, 4).astype(np.float32))
    lmat, q = nd.invoke("linalg_gelqf", m)
    np.testing.assert_allclose((lmat.asnumpy() @ q.asnumpy()), m.asnumpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(q.asnumpy() @ q.asnumpy().T, np.eye(2),
                               rtol=1e-4, atol=1e-5)
    # trsm solves
    b = rng.randn(3, 2).astype(np.float32)
    x = nd.invoke("linalg_trsm", l, nd.array(b)).asnumpy()
    np.testing.assert_allclose(np.tril(l.asnumpy()) @ x, b, rtol=1e-3,
                               atol=1e-4)


def test_multi_sgd_update_matches_single():
    rng = np.random.RandomState(1)
    ws = [rng.randn(4).astype(np.float32) for _ in range(3)]
    gs = [rng.randn(4).astype(np.float32) for _ in range(3)]
    args = []
    for w, g in zip(ws, gs):
        args.extend([nd.array(w), nd.array(g)])
    outs = nd.invoke("multi_sgd_update", *args, lrs=[0.1, 0.2, 0.3],
                     wds=[0.0, 0.01, 0.0], num_weights=3)
    for i, (w, g) in enumerate(zip(ws, gs)):
        lr, wd = [0.1, 0.2, 0.3][i], [0.0, 0.01, 0.0][i]
        expect = w - lr * (g + wd * w)
        np.testing.assert_allclose(outs[i].asnumpy(), expect, rtol=1e-5)


def test_multi_all_finite_and_lars():
    good = nd.array(np.ones(3, np.float32))
    bad = nd.array(np.array([1.0, np.inf, 0.0], np.float32))
    assert float(nd.invoke("multi_all_finite", good, good).asnumpy()[0]) == 1
    assert float(nd.invoke("multi_all_finite", good, bad).asnumpy()[0]) == 0
    lrs = nd.array(np.array([0.1, 0.1], np.float32))
    wsq = nd.array(np.array([4.0, 0.0], np.float32))
    gsq = nd.array(np.array([1.0, 1.0], np.float32))
    wds = nd.array(np.array([0.0, 0.0], np.float32))
    out = nd.invoke("multi_lars", lrs, wsq, gsq, wds, eta=0.1).asnumpy()
    np.testing.assert_allclose(out[0], 0.1 * (0.1 * 2 / 1), rtol=1e-4)
    np.testing.assert_allclose(out[1], 0.1, rtol=1e-5)  # trust=1 fallback


def test_lrn_and_svm_output():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 6, 4, 4).astype(np.float32)
    out = nd.invoke("LRN", nd.array(x), nsize=5, alpha=1e-3).asnumpy()
    # direct formula at one position
    c = 2
    lo, hi = max(0, c - 2), min(6, c + 3)
    win = (x[0, lo:hi, 0, 0] ** 2).sum()
    expect = x[0, c, 0, 0] / (2.0 + (1e-3 / 5) * win) ** 0.75
    np.testing.assert_allclose(out[0, c, 0, 0], expect, rtol=1e-4)

    from mxnet_tpu import autograd
    scores = nd.array(rng.randn(4, 3).astype(np.float32))
    label = nd.array(np.array([0, 1, 2, 0], np.float32))
    scores.attach_grad()
    with autograd.record():
        y = nd.invoke("SVMOutput", scores, label, margin=1.0)
    y.backward()
    g = scores.grad.asnumpy()
    assert g.shape == (4, 3)
    assert np.abs(g).sum() > 0
    np.testing.assert_allclose(g.sum(axis=1), 0, atol=1e-5)  # zero-sum rows


def test_batch_take_reshape_like_moments():
    rng = np.random.RandomState(3)
    a = nd.array(rng.randn(3, 5).astype(np.float32))
    idx = nd.array(np.array([0, 4, 2], np.float32))
    np.testing.assert_allclose(
        nd.invoke("batch_take", a, idx).asnumpy(),
        a.asnumpy()[np.arange(3), [0, 4, 2]])
    b = nd.array(rng.randn(2, 6).astype(np.float32))
    like = nd.array(np.zeros((3, 4), np.float32))
    assert nd.invoke("reshape_like", b, like).shape == (3, 4)
    m, v = nd.invoke("moments", a, axes=(1,))
    np.testing.assert_allclose(m.asnumpy(), a.asnumpy().mean(1), rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), a.asnumpy().var(1), rtol=1e-4)


def test_linspace_digamma_ravel():
    np.testing.assert_allclose(nd.linspace(0, 1, 5).asnumpy(),
                               [0, 0.25, 0.5, 0.75, 1.0])
    np.testing.assert_allclose(
        nd.digamma(nd.array(np.array([1.0]))).asnumpy(), [-0.57721566],
        rtol=1e-5)
    r = nd.ravel_multi_index(
        nd.array(np.array([[0, 1], [2, 3]]), dtype="int64"), shape=(3, 4))
    np.testing.assert_array_equal(r.asnumpy(), [2, 7])
    # inverse of unravel_index
    u = nd.unravel_index(r, shape=(3, 4))
    np.testing.assert_array_equal(u.asnumpy(), [[0, 1], [2, 3]])


def test_im2col_col2im():
    """reference: im2col.h ops — col2im is the exact transpose; with
    non-overlapping windows it is the exact inverse."""
    from mxnet_tpu import autograd
    x = nd.array(np.arange(2 * 3 * 4 * 4, dtype=np.float32)
                 .reshape(2, 3, 4, 4))
    cols = nd.im2col(x, kernel=(2, 2), stride=(2, 2))
    assert cols.shape == (2, 12, 4)
    back = nd.col2im(cols, output_size=(4, 4), kernel=(2, 2), stride=(2, 2))
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy())
    # overlapping windows: gradient counts patch membership
    x.attach_grad()
    with autograd.record():
        loss = nd.im2col(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1)).sum()
    loss.backward()
    g = x.grad.asnumpy()
    assert g[0, 0, 0, 0] == 4.0 and g[0, 0, 2, 2] == 9.0
