"""SCALE.md validation: the Llama-3-8B sharded program at reduced depth.

reference: BASELINE.json configs[4] (8B pretraining on v5e-64). The dry
config keeps every LAYER dimension of the 8B (d_model 4096, 32/8 GQA
heads, hidden 14336, SwiGLU, RoPE theta, remat, one-hot vocab-sharded
embedding) and shrinks only depth/vocab/context; the mesh is the same
three-axis (data, fsdp, model) GSPMD layout as the 64-chip plan, 8 ways.
"""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import jax
jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp
from mxnet_tpu.models.llama import CONFIGS, llama_init, llama_loss
from mxnet_tpu.parallel.mesh import create_mesh
from mxnet_tpu.parallel.sharding import LLAMA_RULES
from mxnet_tpu.parallel.train_step import ShardedTrainStep

cfg = CONFIGS['llama3_8b_dry']
assert cfg.dim == 4096 and cfg.hidden_dim == 14336
assert cfg.n_heads == 32 and cfg.n_kv_heads == 8
mesh = create_mesh(data=2, fsdp=2, model=2)
params = llama_init(jax.random.PRNGKey(0), cfg)
step = ShardedTrainStep(lambda p, b: llama_loss(p, b, cfg), params,
                        mesh, rules=LLAMA_RULES, optimizer='adamw',
                        lr=1e-4)
p, s = step.init()
tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 129), 0,
                            cfg.vocab_size)
p, s, loss = step(p, s, {'tokens': tokens})
assert bool(jnp.isfinite(loss)), float(loss)
assert 6.0 < float(loss) < 12.0, float(loss)
leaf = jax.tree_util.tree_leaves(p)[0]
assert len(leaf.sharding.device_set) == 8

# HBM-ledger budget check (SCALE.md: 16 GB/chip on v5e-64, ~12.9 GB/chip
# planned): the dryrun must fit the declared budget AND the per-scope
# breakdown must explain the device bytes. CPU live_arrays counts host
# copies (llama_init's unsharded tree is still live), so the residual
# tolerance here is looser than the accelerator default.
from mxnet_tpu.telemetry import ledger
rep = ledger.check_budget(16 * 2**30, residual_tolerance=0.75)
assert rep['ok'], rep['failures']
assert rep['scopes'].get('params', 0) > 0, rep['scopes']
assert rep['scopes'].get('optimizer', 0) > 0, rep['scopes']
print('SCALE8B OK loss=%.4f params=%dB budget_ok=%s'
      % (float(loss), rep['scopes']['params'], rep['ok']))
"""


@pytest.mark.slow
def test_8b_layer_shapes_train_step_on_3axis_mesh():
    """Runs in a fresh subprocess: the 8B layer shapes peak ~10 GB of
    host RAM, and sharing an interpreter with the rest of the suite's
    live arrays has produced allocator aborts."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # keep the flags conftest already probed (the cpu collective-watchdog
    # flags only exist in newer jaxlibs — re-adding them unconditionally
    # CHECK-aborts the child on jaxlib 0.4.36); only pin the virtual
    # device count the 3-axis mesh needs
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (flags
                        + " --xla_force_host_platform_device_count=8").strip()
    res = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                         capture_output=True, text=True, timeout=1500)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "SCALE8B OK" in res.stdout
