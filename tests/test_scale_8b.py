"""SCALE.md validation: the Llama-3-8B sharded program at reduced depth.

reference: BASELINE.json configs[4] (8B pretraining on v5e-64). The dry
config keeps every LAYER dimension of the 8B (d_model 4096, 32/8 GQA
heads, hidden 14336, SwiGLU, RoPE theta, remat, one-hot vocab-sharded
embedding) and shrinks only depth/vocab/context; the mesh is the same
three-axis (data, fsdp, model) GSPMD layout as the 64-chip plan, 8 ways.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp


@pytest.mark.slow
def test_8b_layer_shapes_train_step_on_3axis_mesh():
    from mxnet_tpu.models.llama import CONFIGS, llama_init, llama_loss
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.parallel.sharding import LLAMA_RULES
    from mxnet_tpu.parallel.train_step import ShardedTrainStep

    cfg = CONFIGS["llama3_8b_dry"]
    assert cfg.dim == 4096 and cfg.hidden_dim == 14336
    assert cfg.n_heads == 32 and cfg.n_kv_heads == 8

    mesh = create_mesh(data=2, fsdp=2, model=2)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    step = ShardedTrainStep(lambda p, b: llama_loss(p, b, cfg), params,
                            mesh, rules=LLAMA_RULES, optimizer="adamw",
                            lr=1e-4)
    p, s = step.init()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 257), 0,
                                cfg.vocab_size)
    p, s, loss = step(p, s, {"tokens": tokens})
    assert jnp.isfinite(loss), float(loss)
    # roughly ln(vocab) at init — the program computes a real LM loss
    assert 6.0 < float(loss) < 12.0, float(loss)
    # parameters actually live sharded across all 8 devices
    leaf = jax.tree_util.tree_leaves(p)[0]
    assert len(leaf.sharding.device_set) == 8
