"""Tape-connectivity sweep: every user-facing NDArray transformation must
flow gradients under autograd.record().

Round-5 found two silent-detach bugs (`x[key]` views and `copy()/copyto()`
raw buffer copies gave zero gradients with no error — the worst failure
mode a tape can have). This sweep pins the class: for each method/op, run
loss = f(x).sum(), backward, and require a nonzero gradient. The
reference's equivalent guarantee is that everything routes through
imperative ops with FGradient (reference: imperative.cc RecordOp).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

RNG = np.random.RandomState(3)


def _x(shape=(3, 4)):
    x = nd.array(RNG.rand(*shape).astype(np.float32) + 0.5)
    x.attach_grad()
    return x


CASES = [
    ("getitem_slice", lambda x: x[:, 1:3]),
    ("getitem_int", lambda x: x[1]),
    ("getitem_ellipsis", lambda x: x[..., 0]),
    ("getitem_step", lambda x: x[::2]),
    ("getitem_fancy", lambda x: x[nd.array(np.array([0, 2]), dtype="int32")]),
    ("copy", lambda x: x.copy()),
    ("copyto", lambda x: x.copyto(nd.zeros((3, 4)))),
    ("as_in_context_same", lambda x: x.as_in_context(x.context)),
    ("T", lambda x: x.T),
    ("transpose", lambda x: x.transpose()),
    ("reshape", lambda x: x.reshape((4, 3))),
    ("reshape_like", lambda x: x.reshape_like(nd.zeros((2, 6)))),
    ("swapaxes", lambda x: x.swapaxes(0, 1)),
    ("flatten", lambda x: x.flatten()),
    ("expand_dims", lambda x: x.expand_dims(0)),
    ("squeeze", lambda x: x.expand_dims(0).squeeze()),
    ("astype", lambda x: x.astype("float64")),
    ("astype_same", lambda x: x.astype("float32")),
    ("slice_method", lambda x: x.slice(begin=(0, 1), end=(2, 3))),
    ("slice_axis", lambda x: x.slice_axis(1, 1, 3)),
    ("take", lambda x: x.take(nd.array(np.array([0, 2]), dtype="int32"))),
    ("clip", lambda x: x.clip(0.6, 1.2)),
    ("sum_axis", lambda x: x.sum(axis=1)),
    ("mean", lambda x: x.mean(axis=0)),
    ("max", lambda x: x.max(axis=1)),
    ("abs", lambda x: x.abs()),
    ("exp", lambda x: x.exp()),
    ("log", lambda x: x.log()),
    ("sqrt", lambda x: x.sqrt()),
    ("square", lambda x: x.square()),
    ("tile", lambda x: x.tile(reps=(2, 1))),
    ("repeat", lambda x: x.repeat(repeats=2, axis=0)),
    ("flip", lambda x: nd.flip(x, axis=1)),
    ("concat_self", lambda x: nd.concat(x, x, dim=0)),
    ("stack_self", lambda x: nd.stack(x, x, axis=0)),
    ("split_first", lambda x: nd.split(x, num_outputs=2, axis=1)[0]),
    ("where", lambda x: nd.where(x > 1.0, x, 2.0 * x)),
    ("dot", lambda x: nd.dot(x, nd.array(RNG.rand(4, 2)
                                         .astype(np.float32)))),
    ("broadcast_to", lambda x: x.reshape((3, 4, 1))
                                .broadcast_to((3, 4, 2))),
    ("pad_like", lambda x: nd.concat(x, nd.zeros((3, 1)), dim=1)),
    ("maximum", lambda x: nd.maximum(x, 0.9)),
    ("neg", lambda x: -x),
    ("add_scalar", lambda x: x + 1.0),
    ("radd", lambda x: 1.0 + x),
    ("mul", lambda x: x * x),
    ("div", lambda x: x / 2.0),
    ("pow", lambda x: x ** 2),
    ("linalg_gemm2", lambda x: nd.linalg.gemm2(x, x.T)),
    ("image_normalize", lambda x: nd.image.normalize(
        x.reshape((1, 3, 2, 2)), mean=(0.5,), std=(2.0,))),
]


@pytest.mark.parametrize("name,fn", CASES, ids=[c[0] for c in CASES])
def test_grad_flows(name, fn):
    x = _x()
    with autograd.record():
        out = fn(x)
        loss = out.sum() if not isinstance(out, (list, tuple)) else \
            sum(o.sum() for o in out)
    loss.backward()
    g = x.grad.asnumpy()
    assert np.abs(g).max() > 0, (
        "%s: zero gradient — op is detached from the tape" % name)
    assert np.isfinite(g).all(), name


def test_chained_transform_grad_values():
    """A chain of the risky transforms with a hand-checkable gradient."""
    x = _x((2, 4))
    with autograd.record():
        y = x.copy().T[1:3]            # (2,2): rows 1..2 of the transpose
        loss = (y * 2.0).sum()
    loss.backward()
    expect = np.zeros((2, 4), np.float32)
    expect[:, 1:3] = 2.0
    np.testing.assert_allclose(x.grad.asnumpy(), expect)


def test_np_surface_indexing_grads():
    """The mx.np array surface delegates indexing to the same tape paths."""
    import numpy as onp
    from mxnet_tpu import np as mnp
    x = mnp.array(onp.ones((3, 4), onp.float32))
    x.attach_grad()
    with autograd.record():
        loss = (x[:, 1:3] * 2.0).sum() + (x.copy() * 1.0).sum()
    loss.backward()
    g = onp.asarray(x.grad.asnumpy())
    onp.testing.assert_allclose(g[0], [1, 3, 3, 1])

    y = mnp.array(onp.arange(12, dtype=onp.float32).reshape(4, 3))
    y.attach_grad()
    with autograd.record():
        l2 = y[mnp.array([0, 2, 2], dtype="int32")].sum()
    l2.backward()
    onp.testing.assert_allclose(onp.asarray(y.grad.asnumpy())[:, 0],
                                [1, 0, 2, 0])
