"""Tests for test_utils, AMP, profiler, runtime features.

reference idioms: tests/python/unittest/test_operator.py uses
check_numeric_gradient/check_consistency; tests/python/unittest/
test_profiler.py; tests/python/gpu/test_amp.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_consistency, rand_ndarray,
                                  default_context)


def test_assert_almost_equal_tolerances():
    a = np.array([1.0, 2.0], np.float32)
    assert_almost_equal(a, a + 1e-7)
    with pytest.raises(AssertionError):
        assert_almost_equal(a, a + 1.0)


def test_check_numeric_gradient_dense():
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    out = mx.sym.FullyConnected(data, weight=w, no_bias=True, num_hidden=3)
    check_numeric_gradient(out, {"data": np.random.rand(2, 4),
                                 "w": np.random.rand(3, 4)})


def test_check_numeric_gradient_catches_wrong_grad():
    # register an op whose custom vjp is deliberately wrong (3x instead of
    # 2x) and assert the harness flags it.
    import jax
    from mxnet_tpu.ops import registry as reg

    if "_test_bad_grad_sq" not in reg.list_ops():
        @jax.custom_vjp
        def bad_sq(x):
            return x * x

        bad_sq.defvjp(lambda x: (x * x, x),
                      lambda x, g: (3.0 * x * g,))
        reg.register("_test_bad_grad_sq")(bad_sq)
        mx.sym.populate(vars(mx.sym), ["_test_bad_grad_sq"])
    out = mx.sym._test_bad_grad_sq(mx.sym.var("x"))
    with pytest.raises(AssertionError):
        check_numeric_gradient(out, {"x": np.random.rand(3) + 0.5})
    # and the correct gradient passes
    sq = mx.sym.square(mx.sym.var("x"))
    check_numeric_gradient(sq, {"x": np.random.rand(3) + 0.5})


def test_check_consistency_dtypes():
    data = mx.sym.var("data")
    out = mx.sym.dot(data, mx.sym.var("w"))
    ctx = default_context()
    check_consistency(out, [
        {"ctx": ctx, "data": (4, 5), "w": (5, 3),
         "type_dict": {"data": np.float32, "w": np.float32}},
        {"ctx": ctx, "data": (4, 5), "w": (5, 3),
         "type_dict": {"data": np.float64, "w": np.float64}},
    ], rtol=1e-3, atol=1e-4)


def test_rand_ndarray_sparse():
    rsp = rand_ndarray((10, 4), stype="row_sparse", density=0.5)
    assert rsp.stype == "row_sparse"
    dense = rsp.tostype("default")
    assert dense.shape == (10, 4)


def test_runtime_features():
    feats = mx.runtime.feature_list()
    names = {f.name for f in feats}
    assert "XLA" in names and "TPU" in names
    assert mx.runtime.is_enabled("XLA")
    assert mx.runtime.Features().is_enabled("CPU")
    with pytest.raises(RuntimeError):
        mx.runtime.Features().is_enabled("NOT_A_FEATURE")


def test_profiler_aggregate():
    mx.profiler.reset()
    mx.profiler.set_config(profile_all=False, aggregate_stats=True)
    mx.profiler.set_state("run")
    a = nd.ones((8, 8))
    b = nd.dot(a, a)
    (b * 2).asnumpy()
    mx.profiler.set_state("stop")
    table = mx.profiler.dumps()
    assert "dot" in table
    js = mx.profiler.dumps(format="json", reset_stats=True)
    assert "dot" in js
    with mx.profiler.Scope("custom_range"):
        pass
    assert "custom_range" in mx.profiler.dumps()
    mx.profiler.reset()


def test_amp_init_and_training():
    from mxnet_tpu.contrib import amp
    import mxnet_tpu.ndarray.ndarray as nd_mod
    amp.init()  # bf16 policy
    try:
        assert nd_mod._AMP_WRAP is not None
        # matmul-class op now computes in bf16
        a = nd.ones((4, 4))
        out = nd.dot(a, a)
        assert out.dtype.name == "bfloat16"
        # fp32-pinned op stays fp32 even on bf16 input
        s = nd.softmax(out)
        assert s.dtype.name == "float32"

        # end-to-end training still converges under AMP
        net = gluon.nn.Dense(1)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        x = nd.array(np.random.rand(32, 3).astype(np.float32))
        y = nd.sum(x, axis=1, keepdims=True)
        for _ in range(50):
            with autograd.record():
                with amp.scale_loss(
                        nd.mean(nd.square(net(x) - y)), trainer) as l:
                    l.backward()
            trainer.step(1)  # loss is already a mean
        final = float(nd.mean(nd.square(net(x) - y)).asnumpy())
        assert final < 0.05, final
    finally:
        nd_mod._AMP_WRAP = None
        amp.amp._initialized = False


def test_amp_loss_scaler_dynamics():
    from mxnet_tpu.contrib.amp import LossScaler
    s = LossScaler(init_scale=16.0, scale_factor=2.0, scale_window=2)
    s.update_scale(True)
    assert s.loss_scale == 8.0
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 16.0


def test_metric_pcc_matches_binary_mcc():
    """PCC (multiclass R_k) degenerates to MCC for binary problems
    (reference: metric.py PCC)."""
    m = mx.metric.create("pcc")
    labels = nd.array(np.array([0, 0, 1, 1]))
    preds = nd.array(np.array([[0.9, 0.1], [0.6, 0.4],
                               [0.7, 0.3], [0.2, 0.8]]))
    m.update([labels], [preds])
    # confusion: pred [0,0,0,1] vs truth [0,0,1,1] -> MCC = 1/sqrt(3)
    np.testing.assert_allclose(m.get()[1], 1.0 / np.sqrt(3), rtol=1e-6)
    # perfect multiclass = 1.0, and the matrix grows with new classes
    m.reset()
    labels2 = nd.array(np.array([0, 1, 2, 3]))
    preds2 = nd.array(np.eye(4, dtype=np.float32))
    m.update([labels2], [preds2])
    np.testing.assert_allclose(m.get()[1], 1.0)
