"""Control-flow contrib ops (reference: src/operator/control_flow.cc,
python/mxnet/ndarray/contrib.py foreach/while_loop/cond) — lax.scan/
while_loop/cond under the hood, so they stay compiled control flow
inside hybridized/jitted programs."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_foreach_cumsum():
    data = nd.array(np.arange(6, dtype=np.float32).reshape(6, 1))
    init = nd.zeros((1,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = nd.contrib.foreach(body, data, init)
    np.testing.assert_allclose(outs.asnumpy().ravel(),
                               np.cumsum(np.arange(6)))
    np.testing.assert_allclose(final.asnumpy(), [15.0])


def test_foreach_multiple_states_and_grad():
    data = nd.array(np.ones((4, 2), np.float32))
    w = nd.array(np.full((2,), 2.0, np.float32))
    w.attach_grad()

    def body(x, state):
        s = state + x * w
        return s, s

    with autograd.record():
        outs, final = nd.contrib.foreach(body, data, nd.zeros((2,)))
        loss = final.sum()
    loss.backward()
    # final = sum_i x_i * w = 4 * w elementwise -> d(loss)/dw = 4 per elem
    np.testing.assert_allclose(w.grad.asnumpy(), [4.0, 4.0])


def test_while_loop():
    def cond_fn(i, s):
        return i < 5

    def func(i, s):
        return s + i, (i + 1, s + i)   # output, new loop vars

    outs, (i_fin, s_fin) = nd.contrib.while_loop(
        cond_fn, func, (nd.zeros((1,)), nd.zeros((1,))),
        max_iterations=8)
    # steps: s accumulates 0+0,+1,+2,+3,+4 = 10
    np.testing.assert_allclose(s_fin.asnumpy().item(), 10.0)
    np.testing.assert_allclose(i_fin.asnumpy().item(), 5.0)
    assert outs.shape == (8, 1)          # max_iterations buffer
    np.testing.assert_allclose(outs.asnumpy().ravel()[:5],
                               [0, 1, 3, 6, 10])
    np.testing.assert_allclose(outs.asnumpy().ravel()[5:], 0.0)  # padding


def test_cond():
    x = nd.array(np.array([3.0], np.float32))

    out = nd.contrib.cond(nd.array([1.0]),
                          lambda a: a * 2,
                          lambda a: a - 1, (x,))
    np.testing.assert_allclose(out.asnumpy(), [6.0])
    out = nd.contrib.cond(nd.array([0.0]),
                          lambda a: a * 2,
                          lambda a: a - 1, (x,))
    np.testing.assert_allclose(out.asnumpy(), [2.0])


def test_foreach_inside_hybridized_block():
    """foreach stays ONE lax.scan inside a hybridized forward."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Scanner(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.proj = nn.Dense(4, flatten=False)

        def hybrid_forward(self, F, x):
            h = self.proj(x)                      # (N, T, 4)
            seq = F.transpose(h, axes=(1, 0, 2))  # (T, N, 4)

            def body(xt, state):
                s = F.tanh(state + xt)
                return s, s

            outs, final = F.contrib.foreach(body, seq,
                                            F.zeros_like(seq[0]))
            return final

    net = Scanner()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(0).randn(2, 5, 3).astype(np.float32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    np.testing.assert_allclose(y_eager, y_hyb, rtol=1e-5, atol=1e-6)


def test_while_loop_single_var_and_zero_trip():
    """Single (non-tuple) loop var works traced and eager; zero-trip loops
    return the zero buffer in both regimes."""
    # single loop var, hybridized
    from mxnet_tpu import gluon

    class Loop(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            outs, (final,) = F.contrib.while_loop(
                lambda i: nd.sum(i) < 10.0,
                lambda i: (i * 2, i + 3),
                (x,), max_iterations=6)
            return final

    net = Loop()
    net.initialize()
    x = nd.array(np.array([1.0], np.float32))
    eager = float(net(x).asnumpy().item())
    net.hybridize()
    hyb = float(net(x).asnumpy().item())
    assert eager == hyb == 10.0    # 1 -> 4 -> 7 -> 10, stop

    # zero-trip
    outs, (v,) = nd.contrib.while_loop(
        lambda i: nd.sum(i) < 0.0, lambda i: (i * 2, i + 1),
        (nd.array(np.array([5.0], np.float32)),), max_iterations=4)
    assert outs.shape == (4, 1)
    np.testing.assert_allclose(outs.asnumpy(), 0.0)
    np.testing.assert_allclose(v.asnumpy(), [5.0])

    # sym.contrib exposes the trio
    from mxnet_tpu import sym
    assert hasattr(sym.contrib, "foreach")
    assert hasattr(sym.contrib, "while_loop")
    assert hasattr(sym.contrib, "cond")
