"""Memory-observability plane tests (ISSUE 16).

Covers the HBM ledger (scope accounting, overlay exclusion, reconcile
residual math, per-chip budget checks, per-program static footprints on
both cold compile and warm AOT-cache restore), the on-demand profiling
endpoint (capture + rate limiting + full inertness under
``MXNET_TPU_TELEMETRY=0``), the serve KV byte gauges and the ledger
breakdown carried by `Overloaded(kv_exhausted)` / `StallError`, the
bench-history store (`tools/benchdb.py`) and the perf-regression gate
(`tools/check_bench.py --ci`), and the tracelint cleanliness of every
new module.
"""
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import export, ledger, profiling

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
sys.path.insert(0, TOOLS)
import benchdb  # noqa: E402
import check_bench  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_telemetry():
    was_enabled = telemetry.ENABLED
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    (telemetry.enable if was_enabled else telemetry.disable)()


# ------------------------------------------------------------------ ledger
def test_account_and_scopes():
    ledger.account("params", 1000)
    ledger.account("kv_pool", 500)
    assert ledger.scopes() == {"params": 1000, "kv_pool": 500}
    # absolute set semantics: a re-account replaces, never accumulates
    ledger.account("params", 800)
    assert ledger.scopes()["params"] == 800
    # adjust() is the increment form
    assert ledger.adjust("params", 200) == 1000
    # every scope exports a gauge
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["memory.scope.params.bytes"]["value"] == 1000
    assert gauges["memory.scope.kv_pool.bytes"]["value"] == 500


def test_reconcile_residual_math(monkeypatch):
    """residual = device bytes - Σ(non-overlay scopes); prefix_cache is an
    overlay (its bytes live inside kv_pool storage) and must not be
    double-counted."""
    ledger.account("kv_pool", 600)
    ledger.account("prefix_cache", 400)   # overlay: inside kv_pool's 600
    ledger.account("params", 100)
    monkeypatch.setattr(ledger, "_device_bytes",
                        lambda: (1000, "memory_stats", 2))
    rep = ledger.reconcile()
    assert rep["scoped_bytes"] == 700     # 600 + 100, NOT + 400
    assert rep["residual_bytes"] == 300
    assert rep["source"] == "memory_stats"
    assert ledger.scopes()["unattributed"] == 300
    assert telemetry.snapshot()["gauges"][
        "memory.scope.unattributed.bytes"]["value"] == 300
    assert ledger.last_reconcile()["residual_bytes"] == 300


def test_check_budget_pass_and_fail(monkeypatch):
    ledger.account("params", 900)
    monkeypatch.setattr(ledger, "_device_bytes",
                        lambda: (1000, "memory_stats", 2))
    # 500 B/chip under a 1 KiB budget, residual 10% under 25% tolerance
    rep = ledger.check_budget(1024)
    assert rep["ok"], rep["failures"]
    assert rep["per_chip_bytes"] == 500
    assert rep["scopes"]["params"] == 900
    # budget violation
    rep = ledger.check_budget(400)
    assert not rep["ok"] and any("budget" in f for f in rep["failures"])
    # residual violation: the ledger explains only half the device bytes
    ledger.reset()
    ledger.account("params", 500)
    rep = ledger.check_budget(1024, residual_tolerance=0.25)
    assert not rep["ok"]
    assert any("residual" in f for f in rep["failures"])


def test_ledger_lever_disables_quietly(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_LEDGER", "0")
    assert not ledger.enabled()
    ledger.account("params", 123)
    assert ledger.scopes() == {}
    assert ledger.reconcile() is None
    assert "memory.scope.params.bytes" not in \
        telemetry.snapshot()["gauges"]
    rep = ledger.check_budget(1 << 30)
    assert not rep["ok"]                  # an unaccountable run can't pass


def test_breakdown_and_format_scopes():
    ledger.account("kv_pool", 3 << 30)
    ledger.account("params", 1 << 20)
    line = ledger.breakdown()
    assert "kv_pool=3.0GiB" in line and "scoped" in line
    table = ledger.format_scopes()
    assert "kv_pool" in table and "memory ledger" in table


def test_reset_clears_everything():
    ledger.account("params", 10)
    ledger.note_program("x", {"temp_bytes": 5, "bytes": 5})
    ledger.reset()
    assert ledger.scopes() == {}
    assert ledger.programs() == []
    assert ledger.last_reconcile() is None


# ---------------------------------------------------------- program ledger
def test_note_program_and_programs_scope():
    ledger.note_program("serve.decode", {"temp_bytes": 100, "code_bytes": 20,
                                         "bytes": 120})
    ledger.note_program("serve.chunk", {"temp_bytes": 50, "code_bytes": 10,
                                        "bytes": 60}, cached=True)
    progs = {p["label"]: p for p in ledger.programs()}
    assert progs["serve.decode"]["cached"] is False
    assert progs["serve.chunk"]["cached"] is True
    assert ledger.scopes()["programs"] == 180
    counters = telemetry.snapshot()["counters"]
    assert counters["ledger.programs.fresh"] == 1
    assert counters["ledger.programs.cached"] == 1
    # newest wins per label — no duplicate rows, scope total follows
    ledger.note_program("serve.decode", {"temp_bytes": 10, "bytes": 10})
    assert ledger.scopes()["programs"] == 70


def test_harvest_real_compiled():
    compiled = jax.jit(lambda x: x @ x).lower(
        jnp.ones((8, 8), jnp.float32)).compile()
    fp = ledger.harvest(compiled)
    assert fp is not None
    assert fp["argument_bytes"] >= 8 * 8 * 4
    assert fp["bytes"] == fp.get("temp_bytes", 0) + fp.get("code_bytes", 0)


def test_footprint_cold_compile_and_warm_restore(tmp_path, monkeypatch):
    """Acceptance: an AOT-cached program reports its memory_analysis
    footprint on the cold compile AND on a warm cache restore — without
    recompiling (the footprint rides the cache entry's meta)."""
    from mxnet_tpu.compiler import cache as aotc
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", str(tmp_path))

    def lower():
        return jax.jit(lambda x: x * 2 + 1).lower(
            jnp.ones((16, 16), jnp.float32))

    key = aotc.cache_key(test="memobs_warm")
    _, was_cached = aotc.load_or_compile(key, lower, "memobs.prog")
    assert not was_cached
    cold = {p["label"]: p for p in ledger.programs()}["memobs.prog"]
    assert cold["cached"] is False

    # a fresh process would start with an empty ledger: simulate it
    ledger.reset()
    telemetry.reset()
    _, was_cached = aotc.load_or_compile(key, lower, "memobs.prog")
    assert was_cached
    warm = {p["label"]: p for p in ledger.programs()}["memobs.prog"]
    assert warm["cached"] is True
    # the warm restore replays the numbers recorded at compile time
    assert {k: v for k, v in warm.items() if k not in ("cached",)} == \
        {k: v for k, v in cold.items() if k not in ("cached",)}
    assert telemetry.snapshot()["counters"]["ledger.programs.cached"] == 1


def test_snapshot_payload_carries_memory_block():
    ledger.account("params", 2048)
    ledger.note_program("p1", {"temp_bytes": 7, "bytes": 7})
    ledger.reconcile()
    payload = export.snapshot_payload()
    mem = payload["memory"]
    assert mem["scopes"]["params"] == 2048
    assert mem["programs"][0]["label"] == "p1"
    assert mem["reconcile"]["scoped_bytes"] >= 2048
    assert "profiles" in payload


def test_step_event_reconciles_rate_limited(monkeypatch):
    monkeypatch.setattr(ledger, "_device_bytes",
                        lambda: (100, "memory_stats", 1))
    ledger.account("params", 60)
    telemetry.step_event("train", 1.0)
    assert ledger.last_reconcile() is not None
    assert ledger.scopes()["unattributed"] == 40
    # a second step inside MIN_RECONCILE_S must not probe again
    monkeypatch.setattr(ledger, "_device_bytes",
                        lambda: (999, "memory_stats", 1))
    telemetry.step_event("train", 1.0)
    assert ledger.scopes()["unattributed"] == 40


# ------------------------------------------------------------- serve wiring
def test_kv_pool_byte_gauges_and_overloaded_breakdown():
    from mxnet_tpu.models.llama import LlamaConfig
    from mxnet_tpu.serve.errors import Overloaded
    from mxnet_tpu.serve.kv_cache import KVBlockPool
    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                      n_kv_heads=2, hidden_dim=64, rope_theta=10000.0,
                      max_seq_len=64, dtype=jnp.float32)
    pool = KVBlockPool(cfg, num_blocks=4, block_size=4)
    assert pool.storage_bytes > 0
    assert ledger.scopes()["kv_pool"] == pool.storage_bytes
    pool.alloc("a", 12)                   # 3 of 4 blocks
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["serve.kv.bytes"]["value"] == 3 * pool.bytes_per_block
    with pytest.raises(Overloaded) as ei:
        pool.alloc("b", 8)
    err = ei.value
    assert err.reason == "kv_exhausted"
    assert err.ledger_breakdown["kv_pool"] == pool.storage_bytes
    assert "HBM ledger" in str(err)


def test_prefix_bytes_are_overlay():
    from mxnet_tpu.models.llama import LlamaConfig
    from mxnet_tpu.serve.kv_cache import KVBlockPool
    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                      n_kv_heads=2, hidden_dim=64, rope_theta=10000.0,
                      max_seq_len=64, dtype=jnp.float32)
    pool = KVBlockPool(cfg, num_blocks=8, block_size=4)
    pool.alloc("a", 8)                    # 2 full blocks
    pool.register_prefix("a", list(range(8)))
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["serve.prefix.bytes"]["value"] == 2 * pool.bytes_per_block
    scopes = ledger.scopes()
    assert scopes["prefix_cache"] == 2 * pool.bytes_per_block
    assert "prefix_cache" in ledger.OVERLAY_SCOPES


def test_draft_pool_uses_own_scope_and_gauge():
    from mxnet_tpu.models.llama import LlamaConfig
    from mxnet_tpu.serve.kv_cache import KVBlockPool
    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                      n_kv_heads=2, hidden_dim=64, rope_theta=10000.0,
                      max_seq_len=64, dtype=jnp.float32)
    pool = KVBlockPool(cfg, num_blocks=4, block_size=4, scope="kv_draft")
    pool.alloc("a", 4)
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["serve.kv.draft_bytes"]["value"] == pool.bytes_per_block
    assert ledger.scopes()["kv_draft"] == pool.storage_bytes


def test_stall_error_report_names_scopes():
    from mxnet_tpu.resilience.errors import StallError
    err = StallError("stalled", site="train.step", deadline_s=5.0,
                     ledger_dump={"kv_pool": 1 << 30, "params": 1 << 20})
    report = err.format_report()
    assert "memory ledger" in report
    assert "kv_pool" in report


# -------------------------------------------------------------- profiling
def test_capture_profile_cpu_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PROFILE_MIN_S", "0")
    profiling.reset()
    path = telemetry.capture_profile(ms=10, dir=str(tmp_path))
    assert path is not None and os.path.exists(path)
    recs = profiling.records()
    assert recs and recs[-1]["kind"] == "cpu_spans"
    assert telemetry.snapshot()["counters"]["profile.captures"] == 1
    # the capture is announced in the flight ring for the next step record
    telemetry.step_event("train", 1.0)
    events = telemetry.flight_records(limit=1)[0].get("events", [])
    assert any(e.startswith("profile ") for e in events)


def test_capture_profile_rate_limited(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PROFILE_MIN_S", "3600")
    profiling.reset()
    first = telemetry.capture_profile(ms=10, dir=str(tmp_path))
    assert first is not None
    second = telemetry.capture_profile(ms=10, dir=str(tmp_path))
    assert second is None
    assert telemetry.snapshot()["counters"]["profile.rate_limited"] == 1


def test_profile_endpoint_and_429(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PROFILE_MIN_S", "3600")
    monkeypatch.setenv("MXNET_TPU_PROFILE_DIR", str(tmp_path))
    profiling.reset()
    server = export.start_http_server(0)
    base = "http://127.0.0.1:%d" % server.port
    try:
        body = json.loads(urllib.request.urlopen(
            base + "/profile?ms=10", timeout=10).read())
        assert body["ok"] and os.path.exists(body["path"])
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/profile?ms=10", timeout=10)
        assert ei.value.code == 429
        retry = json.loads(ei.value.read())
        assert retry["error"] == "rate_limited"
    finally:
        export.stop_http_server()


def test_disabled_telemetry_is_fully_inert(tmp_path):
    """Under MXNET_TPU_TELEMETRY=0 the whole plane is inert: no gauges,
    no ledger state, no profile capture, no file, no profile directory.
    Subprocess-tested so the gate is evaluated at import like production."""
    code = """
import os, sys, json
import jax, jax.numpy as jnp
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import ledger, profiling
assert not telemetry.ENABLED
ledger.account("params", 4096)
assert ledger.scopes() == {}
assert ledger.reconcile() is None
compiled = jax.jit(lambda x: x + 1).lower(jnp.ones((4,))).compile()
ledger.note_program("p", ledger.harvest(compiled))
assert ledger.programs() == []
out = telemetry.capture_profile(ms=10, dir=sys.argv[1])
assert out is None
assert not os.path.exists(sys.argv[1])
assert profiling.records() == []
snap = telemetry.snapshot()
assert not snap.get("gauges") and not snap.get("counters")
print("INERT_OK")
"""
    env = dict(os.environ, MXNET_TPU_TELEMETRY="0", JAX_PLATFORMS="cpu",
               MXNET_TPU_PROFILE_MIN_S="0")
    env.pop("MXNET_TPU_METRICS_PORT", None)
    r = subprocess.run(
        [sys.executable, "-c", code, str(tmp_path / "profdir")],
        capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "INERT_OK" in r.stdout


# ----------------------------------------------------------------- benchdb
def test_fingerprint_stable_and_distinct():
    fp1 = benchdb.fingerprint(backend="cpu", device_count=1)
    fp2 = benchdb.fingerprint(backend="cpu", device_count=1)
    assert benchdb.fingerprint_id(fp1) == benchdb.fingerprint_id(fp2)
    fp3 = benchdb.fingerprint(backend="tpu", device_count=64)
    assert benchdb.fingerprint_id(fp1) != benchdb.fingerprint_id(fp3)
    # a silent cpu fallback is a DIFFERENT environment, not a regression
    fp4 = benchdb.fingerprint(backend="cpu", device_count=1,
                              cpu_fallback=True)
    assert benchdb.fingerprint_id(fp1) != benchdb.fingerprint_id(fp4)


def test_append_load_roundtrip_and_bad_lines(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    benchdb.append({"metric": "m", "value": 1.0}, path)
    benchdb.append({"metric": "m", "value": 2.0}, path)
    with open(path, "a") as f:
        f.write("{truncated garbage\n")
    rows = benchdb.load(path)
    assert [r["value"] for r in rows] == [1.0, 2.0]


def test_history_path_env_override(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_BENCH_HISTORY", str(tmp_path / "h.jsonl"))
    assert benchdb.history_path() == str(tmp_path / "h.jsonl")


# ------------------------------------------------------------- check_bench
def test_direction_heuristics():
    assert check_bench.direction_for("resnet50_img_per_sec") == "up"
    assert check_bench.direction_for("serve_tok_per_sec") == "up"
    assert check_bench.direction_for("obs_scrape_p50_us") == "down"
    assert check_bench.direction_for("startup_warm_s") == "down"


def _hist_rows(metric, values, fpid):
    return [{"metric": metric, "value": v, "fingerprint_id": fpid}
            for v in values]


def test_check_passes_healthy_fails_regressed():
    rows = _hist_rows("x_tok_per_sec", [100, 101, 99, 100], "fp1")
    rep = check_bench.check(rows)
    assert rep["ok"] and not rep["regressions"]
    rows.append({"metric": "x_tok_per_sec", "value": 80,
                 "fingerprint_id": "fp1"})   # -20% vs median 100
    rep = check_bench.check(rows)
    assert not rep["ok"]
    assert rep["regressions"][0]["delta_pct"] == -20.0
    # latency direction: +20% on a _us metric is also a regression
    rows2 = _hist_rows("y_p50_us", [10, 10, 10, 12.5], "fp1")
    rep2 = check_bench.check(rows2)
    assert not rep2["ok"]


def test_check_skips_cross_fingerprint_and_short_series():
    rows = (_hist_rows("m_tok_per_sec", [100, 100, 100], "fast-chip")
            + _hist_rows("m_tok_per_sec", [5], "laptop"))
    rep = check_bench.check(rows)
    # the laptop row is never compared against the fast-chip baseline
    assert rep["ok"]
    assert rep["skipped"]["fingerprint_mismatch"] == 1
    assert rep["skipped"]["insufficient_history"] == 1


def test_per_metric_tolerance_override():
    rows = _hist_rows("noisy_tok_per_sec", [100, 100, 100, 85], "fp1")
    assert not check_bench.check(rows)["ok"]
    assert check_bench.check(rows, tolerances={"noisy": 0.25})["ok"]


def test_check_bench_ci_subprocess(tmp_path):
    """The gate as CI runs it: exit 0 on healthy history, exit 1 after an
    injected 20% regression, exit 2 on an empty history."""
    script = os.path.join(TOOLS, "check_bench.py")
    hist = tmp_path / "hist.jsonl"
    fp = benchdb.fingerprint(backend="cpu", device_count=1)
    fpid = benchdb.fingerprint_id(fp)
    for v in (100, 102, 99, 101):
        benchdb.append({"metric": "gate_tok_per_sec", "value": v,
                        "fingerprint_id": fpid}, str(hist))
    run = lambda *a: subprocess.run(  # noqa: E731
        [sys.executable, script, "--ci", *a], capture_output=True,
        text=True, timeout=120)
    r = run(str(hist))
    assert r.returncode == 0, r.stdout + r.stderr
    benchdb.append({"metric": "gate_tok_per_sec", "value": 80,
                    "fingerprint_id": fpid}, str(hist))
    r = run(str(hist))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout
    r = run(str(tmp_path / "missing.jsonl"))
    assert r.returncode == 2


def test_check_bench_ci_passes_on_committed_history():
    """Acceptance: the gate exits 0 against the repo's real committed
    bench history (run alongside run_tracelint.sh --ci)."""
    hist = os.path.join(REPO, "bench_history.jsonl")
    assert os.path.exists(hist), "committed bench_history.jsonl missing"
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_bench.py"), "--ci",
         hist], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_serve_warmup_programs_have_footprints(tmp_path, monkeypatch):
    """Acceptance: every serve executable (chunked prefill, decode, CoW)
    records a memory_analysis footprint at warmup — on the cold compile
    AND when a second server restores the same programs from the AOT
    cache without recompiling."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.llama import LlamaConfig, llama_init
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", str(tmp_path))
    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                      n_kv_heads=2, hidden_dim=64, rope_theta=10000.0,
                      max_seq_len=64, dtype=jnp.float32)
    params = llama_init(jax.random.PRNGKey(0), cfg)

    def warm():
        server = mx.serve.InferenceServer(
            params, cfg, max_batch=2, kv_blocks=16, block_size=8,
            max_context=32, queue_cap=8)
        server.warmup()

    warm()
    cold = {p["label"]: p for p in ledger.programs()
            if p["label"].startswith("serve.")}
    assert cold, "no serve.* footprints after cold warmup"
    assert not any(p["cached"] for p in cold.values())

    ledger.reset()
    telemetry.reset()
    warm()
    restored = {p["label"]: p for p in ledger.programs()
                if p["label"].startswith("serve.")}
    assert set(restored) == set(cold)
    assert all(p["cached"] for p in restored.values()), restored
    # visible in the /snapshot payload both times
    assert any(p["label"].startswith("serve.")
               for p in export.snapshot_payload()["memory"]["programs"])


# -------------------------------------------------------------- parse_log
def test_parse_log_mem_mode(tmp_path):
    ledger.account("kv_pool", 4096)
    ledger.note_program("serve.decode", {"temp_bytes": 64, "code_bytes": 0,
                                         "argument_bytes": 128,
                                         "output_bytes": 128, "bytes": 64})
    ledger.reconcile()
    payload = export.snapshot_payload()
    dump = tmp_path / "snap.json"
    dump.write_text(json.dumps(payload))
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "parse_log.py"), str(dump),
         "--mem"], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "kv_pool" in r.stdout
    assert "serve.decode" in r.stdout
    assert "reconcile:" in r.stdout


# -------------------------------------------------------------------- lint
@pytest.mark.lint
def test_new_modules_tracelint_clean_zero_suppressions():
    """ledger/profiling/benchdb/check_bench are tracelint-clean with ZERO
    suppression markers — observability code meets the bar it enforces."""
    from mxnet_tpu import analysis
    paths = [
        os.path.join(REPO, "mxnet_tpu", "telemetry", "ledger.py"),
        os.path.join(REPO, "mxnet_tpu", "telemetry", "profiling.py"),
        os.path.join(TOOLS, "benchdb.py"),
        os.path.join(TOOLS, "check_bench.py"),
    ]
    findings = analysis.lint_paths(paths)
    assert not findings, "\n".join(f.format() for f in findings)
    for p in paths:
        with open(p) as f:
            assert "tpu-lint:" not in f.read(), \
                "suppression marker in %s" % p
