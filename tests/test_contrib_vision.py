"""Contrib vision/detection ops (reference: src/operator/contrib/
bounding_box.cc, roi_align.cc, bilinear_resize-inl.h,
adaptive_avg_pooling-inl.h) — numeric checks against numpy references."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_arange_like():
    x = nd.zeros((2, 3))
    out = nd.contrib.arange_like(x, start=1.0, step=2.0)
    np.testing.assert_allclose(out.asnumpy(),
                               (1 + 2 * np.arange(6)).reshape(2, 3))
    out = nd.contrib.arange_like(x, axis=1)
    np.testing.assert_allclose(out.asnumpy(), [0, 1, 2])


def test_bilinear_resize2d():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 4, 6).astype(np.float32)
    out = nd.contrib.BilinearResize2D(nd.array(x), height=8, width=12)
    assert out.shape == (2, 3, 8, 12)
    # align-corners: the four corners are preserved exactly
    np.testing.assert_allclose(out.asnumpy()[:, :, 0, 0], x[:, :, 0, 0],
                               rtol=1e-5)
    np.testing.assert_allclose(out.asnumpy()[:, :, -1, -1], x[:, :, -1, -1],
                               rtol=1e-5)
    # upscale by identity size is identity
    same = nd.contrib.BilinearResize2D(nd.array(x), height=4, width=6)
    np.testing.assert_allclose(same.asnumpy(), x, rtol=1e-5)


def test_adaptive_avg_pooling2d():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 6, 8).astype(np.float32)
    # divisible case equals mean pooling
    out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x), output_size=(3, 4))
    ref = x.reshape(2, 3, 3, 2, 4, 2).mean(axis=(3, 5))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)
    # non-divisible: windows follow floor/ceil boundaries
    out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x), output_size=(4, 3))
    ref = np.zeros((2, 3, 4, 3), np.float32)
    for i in range(4):
        for j in range(3):
            h0, h1 = (i * 6) // 4, -((-(i + 1) * 6) // 4)
            w0, w1 = (j * 8) // 3, -((-(j + 1) * 8) // 3)
            ref[:, :, i, j] = x[:, :, h0:h1, w0:w1].mean(axis=(2, 3))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)
    # global pooling
    out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x))
    np.testing.assert_allclose(out.asnumpy()[..., 0, 0],
                               x.mean(axis=(2, 3)), rtol=1e-5)


def test_roi_align():
    # constant feature map: every aligned roi pools to the constant
    x = np.full((1, 2, 8, 8), 5.0, np.float32)
    rois = np.array([[0, 0, 0, 7, 7], [0, 2, 2, 6, 6]], np.float32)
    out = mx.nd.contrib.ROIAlign(nd.array(x), nd.array(rois),
                                 pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (2, 2, 2, 2)
    np.testing.assert_allclose(out.asnumpy(), 5.0, rtol=1e-5)
    # linear ramp in x: pooled values increase along width
    ramp = np.tile(np.arange(8, dtype=np.float32), (8, 1))[None, None]
    out = mx.nd.contrib.ROIAlign(nd.array(ramp),
                                 nd.array(np.array([[0, 0, 0, 7, 7]], np.float32)),
                                 pooled_size=(1, 4), spatial_scale=1.0)
    v = out.asnumpy()[0, 0, 0]
    assert (np.diff(v) > 0).all(), v


def test_box_iou():
    a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    b = np.array([[0, 0, 2, 2], [10, 10, 11, 11]], np.float32)
    iou = nd.contrib.box_iou(nd.array(a), nd.array(b)).asnumpy()
    np.testing.assert_allclose(iou[0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(iou[1, 0], 1.0 / 7.0, rtol=1e-5)
    assert iou[0, 1] == 0.0
    # center format
    ac = np.array([[1, 1, 2, 2]], np.float32)   # == corner [0,0,2,2]
    iou_c = nd.contrib.box_iou(nd.array(ac), nd.array(ac),
                               format="center").asnumpy()
    np.testing.assert_allclose(iou_c[0, 0], 1.0, rtol=1e-6)


def test_box_nms():
    # [id, score, x1, y1, x2, y2]
    data = np.array([
        [0, 0.9, 0, 0, 10, 10],
        [0, 0.8, 1, 1, 11, 11],     # heavy overlap with #0 -> suppressed
        [0, 0.7, 20, 20, 30, 30],   # far away -> kept
        [1, 0.6, 0, 0, 10, 10],     # other class -> kept (no force)
        [0, 0.0, 0, 0, 1, 1],       # below valid_thresh -> dropped
    ], np.float32)
    out = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5,
                             valid_thresh=0.01, coord_start=2,
                             score_index=1, id_index=0).asnumpy()
    kept = out[out[:, 1] > 0]
    np.testing.assert_allclose(sorted(kept[:, 1].tolist()),
                               [0.6, 0.7, 0.9])
    # force_suppress ignores class ids
    out = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5,
                             valid_thresh=0.01, coord_start=2,
                             score_index=1, id_index=0,
                             force_suppress=True).asnumpy()
    kept = out[out[:, 1] > 0]
    np.testing.assert_allclose(sorted(kept[:, 1].tolist()), [0.7, 0.9])
    # batch dim passthrough
    out = nd.contrib.box_nms(nd.array(data[None]), overlap_thresh=0.5,
                             valid_thresh=0.01).asnumpy()
    assert out.shape == (1, 5, 6)


def test_box_decode_roundtrip():
    anchors = np.array([[[0, 0, 4, 4], [2, 2, 10, 8]]], np.float32)
    # zero offsets decode to the anchors themselves
    zeros = np.zeros((1, 2, 4), np.float32)
    out = nd.contrib.box_decode(nd.array(zeros), nd.array(anchors)).asnumpy()
    np.testing.assert_allclose(out, anchors, rtol=1e-5, atol=1e-5)


def test_roi_align_outside_image_zeroed():
    """Samples outside [-1, size] contribute zero (reference roi_align.cc
    skips them), so a fully-outside roi pools to ~0."""
    x = np.full((1, 1, 8, 8), 1.0, np.float32)
    rois = np.array([[0, -40, -40, -20, -20]], np.float32)
    out = mx.nd.contrib.ROIAlign(nd.array(x), nd.array(rois),
                                 pooled_size=(2, 2), spatial_scale=1.0)
    np.testing.assert_allclose(out.asnumpy(), 0.0, atol=1e-6)


def test_arange_like_repeat_and_resize_defaults():
    x = nd.zeros((2, 4))
    out = nd.contrib.arange_like(x, axis=1, repeat=2)
    np.testing.assert_allclose(out.asnumpy(), [0, 0, 1, 1])
    # height-only call is valid (reference defaults the other dim to 1)
    img = nd.array(np.random.RandomState(0).randn(1, 1, 4, 4)
                   .astype(np.float32))
    out = nd.contrib.BilinearResize2D(img, height=8)
    assert out.shape == (1, 1, 8, 1)
    import pytest
    with pytest.raises(NotImplementedError):
        # async exception semantics: the dispatch error surfaces at the
        # sync point (reference: test_exc_handling.py)
        nd.contrib.BilinearResize2D(img, height=8, width=8,
                                    mode="like").asnumpy()


def test_multibox_prior():
    x = nd.zeros((1, 8, 2, 2))   # 2x2 feature map
    out = nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1.0, 2.0))
    # A = len(sizes) + len(ratios) - 1 = 3 anchors per cell
    assert out.shape == (1, 2 * 2 * 3, 4)
    a = out.asnumpy()[0]
    # first cell center is ((0+0.5)/2, (0+0.5)/2) = (0.25, 0.25)
    first = a[0]
    np.testing.assert_allclose((first[0] + first[2]) / 2, 0.25, atol=1e-6)
    np.testing.assert_allclose((first[1] + first[3]) / 2, 0.25, atol=1e-6)
    # anchor 0: size 0.5 ratio 1 -> width == height == 0.5
    np.testing.assert_allclose(first[2] - first[0], 0.5, atol=1e-6)
    np.testing.assert_allclose(first[3] - first[1], 0.5, atol=1e-6)
    # reference ordering: sizes first (anchor 1 = size 0.25 ratio 1),
    # then ratios[1:] at sizes[0] (anchor 2 = size 0.5 ratio 2)
    second = a[1]
    np.testing.assert_allclose(second[2] - second[0], 0.25, atol=1e-6)
    np.testing.assert_allclose(second[3] - second[1], 0.25, atol=1e-6)
    third = a[2]
    np.testing.assert_allclose(third[2] - third[0], 0.5 * np.sqrt(2),
                               atol=1e-6)
    np.testing.assert_allclose(third[3] - third[1], 0.5 / np.sqrt(2),
                               atol=1e-6)


def test_multibox_target_and_detection():
    # 2 anchors; 1 GT box exactly equal to anchor 0
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    label = np.array([[[2, 0.1, 0.1, 0.4, 0.4],
                       [-1, 0, 0, 0, 0]]], np.float32)   # one GT, cls 2
    cls_pred = np.zeros((1, 4, 2), np.float32)
    bt, bm, ct = nd.contrib.MultiBoxTarget(nd.array(anchors),
                                           nd.array(label),
                                           nd.array(cls_pred))
    bt, bm, ct = bt.asnumpy(), bm.asnumpy(), ct.asnumpy()
    assert bt.shape == (1, 8) and bm.shape == (1, 8) and ct.shape == (1, 2)
    # anchor 0 is positive with zero offsets (perfect match), cls target 3
    np.testing.assert_allclose(bt[0, :4], 0.0, atol=1e-5)
    np.testing.assert_allclose(bm[0, :4], 1.0)
    np.testing.assert_allclose(bm[0, 4:], 0.0)
    assert ct[0, 0] == 3.0 and ct[0, 1] == 0.0

    # detection: feed probabilities putting cls 0 on anchor 0
    cls_prob = np.zeros((1, 3, 2), np.float32)
    cls_prob[0, 0] = [0.05, 0.9]     # background
    cls_prob[0, 1] = [0.9, 0.05]     # class 0 confident on anchor 0
    cls_prob[0, 2] = [0.05, 0.05]
    loc_pred = np.zeros((1, 8), np.float32)  # zero offsets -> anchors
    out = nd.contrib.MultiBoxDetection(nd.array(cls_prob),
                                       nd.array(loc_pred),
                                       nd.array(anchors),
                                       threshold=0.1).asnumpy()
    assert out.shape == (1, 2, 6)
    kept = out[0][out[0, :, 0] >= 0]
    assert len(kept) == 1
    np.testing.assert_allclose(kept[0, 0], 0.0)          # class id
    np.testing.assert_allclose(kept[0, 1], 0.9, atol=1e-6)
    np.testing.assert_allclose(kept[0, 2:], [0.1, 0.1, 0.4, 0.4], atol=1e-5)


def test_multibox_target_negative_mining():
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4],
                         [0.5, 0.5, 0.7, 0.7],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    label = np.array([[[0, 0.1, 0.1, 0.4, 0.4]]], np.float32)
    cls_pred = np.zeros((1, 2, 3), np.float32)
    cls_pred[0, 0] = [0.9, 0.1, 0.8]   # background confidence per anchor
    bt, bm, ct = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred),
        negative_mining_ratio=1.0, ignore_label=-1.0)
    ct = ct.asnumpy()
    # 1 positive -> quota 1 negative: the hardest (lowest bg prob, anchor 1)
    assert ct[0, 0] == 1.0           # positive, cls 0 -> target 1
    assert ct[0, 1] == 0.0           # kept negative
    assert ct[0, 2] == -1.0          # ignored


def test_deformable_convolution_zero_offset_equals_conv():
    """With zero offsets, DeformableConvolution reduces to a plain conv
    (reference: deformable_convolution.cc semantics)."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 9, 8, 8), np.float32)
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=4, pad=(1, 1), no_bias=True).asnumpy()
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        num_filter=4, pad=(1, 1), no_bias=True).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_deformable_convolution_shifts():
    """A constant +1-pixel x-offset equals shifting the input."""
    rng = np.random.RandomState(1)
    x = rng.randn(1, 1, 6, 6).astype(np.float32)
    w = np.ones((1, 1, 1, 1), np.float32)
    off = np.zeros((1, 2, 6, 6), np.float32)
    off[:, 1] = 1.0   # x-offset +1
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(1, 1),
        num_filter=1, no_bias=True).asnumpy()
    # out[..., :, j] == x[..., :, j+1]; last column samples x=6 -> zero
    np.testing.assert_allclose(out[0, 0, :, :-1], x[0, 0, :, 1:],
                               rtol=1e-5)
    np.testing.assert_allclose(out[0, 0, :, -1], 0.0, atol=1e-6)


def test_deformable_convolution_backward():
    """Gradients flow to data, offsets, and weights."""
    from mxnet_tpu import autograd
    rng = np.random.RandomState(2)
    x = nd.array(rng.randn(1, 2, 6, 6).astype(np.float32))
    off = nd.array(0.1 * rng.randn(1, 2 * 9, 6, 6).astype(np.float32))
    w = nd.array(rng.randn(3, 2, 3, 3).astype(np.float32))
    for a in (x, off, w):
        a.attach_grad()
    with autograd.record():
        y = nd.contrib.DeformableConvolution(
            x, off, w, kernel=(3, 3), num_filter=3, pad=(1, 1),
            no_bias=True).sum()
    y.backward()
    for name, a in (("data", x), ("offset", off), ("weight", w)):
        g = a.grad.asnumpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0, name


def test_psroi_pooling():
    """Each output bin pools from its own channel group: feeding channel
    value = its (d, i, j) code recovers the code per bin."""
    od, ps, h, w = 2, 3, 9, 9
    data = np.zeros((1, od * ps * ps, h, w), np.float32)
    for d in range(od):
        for i in range(ps):
            for j in range(ps):
                data[0, d * ps * ps + i * ps + j] = d * 100 + i * 10 + j
    rois = np.array([[0, 0, 0, 9, 9]], np.float32)
    out = mx.nd.contrib.PSROIPooling(nd.array(data), nd.array(rois),
                                     spatial_scale=1.0, output_dim=od,
                                     pooled_size=ps).asnumpy()
    assert out.shape == (1, od, ps, ps)
    for d in range(od):
        for i in range(ps):
            for j in range(ps):
                np.testing.assert_allclose(out[0, d, i, j],
                                           d * 100 + i * 10 + j, atol=1e-5)


def test_deformable_edge_decay_and_psroi_grad():
    """Edge samples decay bilinearly to zero (reference
    dmcn_im2col_bilinear); PSROIPooling is differentiable."""
    from mxnet_tpu import autograd
    # 1x1 kernel, offset placing the sample at y = -0.5: value must be
    # half the first row, not the full clamped row
    x = np.full((1, 1, 4, 4), 2.0, np.float32)
    off = np.zeros((1, 2, 4, 4), np.float32)
    off[:, 0, 0, :] = -0.5    # y-offset -0.5 on the first output row
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(np.ones((1, 1, 1, 1),
                                                     np.float32)),
        kernel=(1, 1), num_filter=1, no_bias=True).asnumpy()
    np.testing.assert_allclose(out[0, 0, 0], 1.0, atol=1e-6)   # 0.5 * 2.0
    np.testing.assert_allclose(out[0, 0, 1], 2.0, atol=1e-6)

    data = nd.array(np.random.RandomState(0).randn(1, 2 * 2 * 2, 6, 6)
                    .astype(np.float32))
    rois = nd.array(np.array([[0, 1, 1, 4, 4]], np.float32))
    data.attach_grad()
    with autograd.record():
        y = mx.nd.contrib.PSROIPooling(data, rois, spatial_scale=1.0,
                                       output_dim=2, pooled_size=2).sum()
    y.backward()
    g = data.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_gluon_deformable_convolution_block():
    """gluon.contrib.cnn.DeformableConvolution (reference:
    gluon/contrib/cnn/conv_layers.py): zero-initialized offsets start as a
    plain conv; the block hybridizes and backprops into the offset conv."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.contrib.cnn import DeformableConvolution

    mx.random.seed(0)
    net = DeformableConvolution(8, kernel_size=3, padding=1)
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(0).randn(2, 4, 8, 8)
                 .astype(np.float32))
    y = net(x)
    ref = nd.Convolution(x, net.weight.data(), net.bias.data(),
                         kernel=(3, 3), num_filter=8, pad=(1, 1))
    np.testing.assert_allclose(y.asnumpy(), ref.asnumpy(), rtol=1e-4,
                               atol=1e-4)
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    assert np.isfinite(net.offset.weight.grad().asnumpy()).all()
    net.hybridize()
    np.testing.assert_allclose(net(x).asnumpy(), y.asnumpy(), rtol=1e-4,
                               atol=1e-4)
